"""Trajectory-accelerated grouped scheduling: the batched fast path.

`schedule_batch_grouped` (ops/grouped.py) already hoists static filter/score
work, but still pays one FULL filter/score sweep per pod — 100k sequential
heavy scan steps leave the TPU mostly idle (SURVEY §7 hard part 1; the
reference's own loop is serial per scheduleOne, generic_scheduler.go:131-175,
so this is where a TPU-native design wins an order of magnitude, not 5%).

The key structural fact: while a group of IDENTICAL pods schedules, a node's
local state (free resources, per-device GPU memory, VG/device storage, host
port counts) changes ONLY when that node is chosen — every commit touches just
the chosen node's row/column. So for one group:

  1. Trajectory precompute (J steps, J = max commits any node can take,
     bounded by the implicit pods-slot request → typically ~110): virtually
     commit the pod to EVERY node at once per step, recording per-step
     node-local masks (resources / ports / storage / GPU), raw scores, and
     allocation takes. Row n after j steps is bit-identical to the real
     carry's row n after j commits to n, because the arithmetic per row is
     exactly the scan's commit arithmetic.
  2. Light selection scan (one step per pod): the carry is just x i32[N] —
     commits per node so far. Node-local quantities are O(N) gathers from the
     trajectory at x; the carry-coupled PodTopologySpread / InterPodAffinity
     counts are reconstructed EXACTLY as `base + match * x` (pure integer
     arithmetic in f32, exact below 2^24) and fed through the original
     `_domain_counts`, so every count, min, max and normalize is bit-identical
     to the naive kernel. The step is ~20 small ops instead of the full
     ~dozen-plugin sweep.

Placements, failure reasons, allocation takes and the exit carry are all
bit-identical to `schedule_batch` (tests/test_fast.py proves it); groups too
small to amortize the trajectory fall back to the grouped path.
"""

from __future__ import annotations

import functools
import os
import sys
import time
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .encode import PodBatch, round_up
from .grouped import (
    DEFAULT_GROUP_CHUNK,
    _bucket,
    _group_call,
    _static_parts,
    group_runs,
)
from .kernels import (
    Carry,
    F_GPU,
    F_NODE_PORTS,
    F_POD_AFFINITY,
    F_RESOURCES,
    F_SPREAD,
    F_STORAGE,
    NUM_FILTERS,
    NodeStatic,
    PodRow,
    WEIGHT_ORDER,
    _EPS,
    _domain_counts,
    _minmax_normalize,
    combine_scores,
    commit_choice,
    gpu_allocate_rowwise,
    gpu_mask,
    gpu_share_raw,
    local_storage_eval,
    port_adds,
    ports_mask,
    resource_fail,
    schedule_step,
)
from .sanitize import sanitizable
from . import delta as _delta
from . import wave as _wave
from .state import pod_rows_from_batch
from ..utils import metrics as _metrics

# Trajectories longer than this fall back to the per-pod grouped path (a node
# that can absorb >512 copies of one pod implies an unrealistically small
# request; the [J,N,R] trajectory would not be worth its HBM footprint).
J_CAP = 512

# Path counters (tests/diagnostics): groups scheduled per strategy since
# import. The sort path must actually fire for plain groups — parity alone
# can't tell which path produced the result.
PATH_COUNTS = {
    "sort": 0, "micro": 0, "scan": 0, "grouped": 0, "sort_fallback": 0,
    "domain": 0, "domain_fallback": 0, "domain_pallas": 0,
}


def _count_path(path: str, n: int = 1) -> None:
    """Tally a strategy selection in PATH_COUNTS and mirror it into
    osim_fast_path_total{path=...}."""
    if n <= 0:
        return
    PATH_COUNTS[path] += n
    _metrics.FAST_PATH.inc(n, path=path)

# Max combined (domain-tuple, eligibility) classes for the domain-merge path;
# groups whose nodes span more classes take the micro scan instead. Tests may
# set this to 0 to force the micro body.
DM_CAP = 64

# Wedge forensics (OSIM_PROGRESS=1): one stderr line immediately BEFORE each
# device dispatch, so when a tunnel deadline kills the process the last line
# names the exact group/path/shape that hung — the axon relay's failure mode
# is a silent indefinite block inside one device call (BASELINE.md round-5).
_PROGRESS = os.environ.get("OSIM_PROGRESS", "") not in ("", "0")


def _progress(msg: str) -> None:
    if _PROGRESS:
        print(f"[osim {time.strftime('%H:%M:%S')}] {msg}",
              file=sys.stderr, flush=True)


# Channel layout of Trajectory.packed — everything the selection step needs,
# in one array so the whole per-step state fits a small [N,CH] matrix.
CH_CPU, CH_MEM, CH_RES_FAIL, CH_PORT_OK, CH_STO_OK, CH_GPU_OK = range(6)
CH_STO_RAW, CH_GPU_RAW = 6, 7
N_CH = 8


class Trajectory(NamedTuple):
    """Per-node state/score evolution for one pod spec: index j = value after
    j commits of this pod onto that node. Layout is [N, J, ...] — lane-local
    per-node selection (TPU lowers general gathers poorly).

    `packed` f32[N,J,CH] carries the selection-step channels (cpu/mem free,
    the four local feasibility bits as 0.0/1.0, and the two raw scores); the
    full-width arrays are only touched once per group (exit carry, takes)."""
    free: jnp.ndarray         # f32[N,J,R]
    gpu_free: jnp.ndarray     # f32[N,J,G]
    vg_free: jnp.ndarray      # f32[N,J,V]
    dev_free: jnp.ndarray     # f32[N,J,DV]
    gpu_take: jnp.ndarray     # f32[N,J,G]
    vg_take: jnp.ndarray      # f32[N,J,V]
    dev_take: jnp.ndarray     # f32[N,J,DV]
    packed: jnp.ndarray       # f32[N,J,N_CH]


@sanitizable("ops.fast:build_trajectory", static_argnames=("j_steps",))
@functools.partial(jax.jit, static_argnames=("j_steps",))
def build_trajectory(
    ns: NodeStatic,
    carry: Carry,
    pod: PodRow,
    weights: jnp.ndarray,
    j_steps: int,
    filter_on=None,
):
    """Virtual-commit the pod to every node j_steps times, recording the
    node-local evolution, plus the group's static masks/scores.

    Returns (Trajectory, static_ok, static_ff, static_scores, na_ok).

    Exactness: each recorded row equals the real scan carry's row after the
    same number of commits to that node, because (a) the scan's commit only
    mutates the chosen node's row/column, and (b) the arithmetic applied here
    per row is the scan's own commit expression with onehot ≡ 1 (1.0 * v == v
    exactly in f32). Rows past a node's local feasibility limit are never
    gathered: the local masks are monotone in j (free/gpu/storage only
    shrink, a host-port self-conflict is permanent), so x stops there.
    """
    add_any, add_wild, add_ipc = port_adds(
        carry.port_any.shape[0], carry.port_ipc.shape[0], pod
    )

    def step(vc: Carry, _):
        res_fail = resource_fail(ns, vc, pod)
        port_ok = ports_mask(vc, pod)
        storage_ok, vg_take_all, dev_take_all, storage_raw = local_storage_eval(
            ns, vc, pod
        )
        g_ok = gpu_mask(ns, vc, pod)
        g_raw = gpu_share_raw(ns, vc, pod)
        g_take = gpu_allocate_rowwise(ns, vc.gpu_free, pod)
        packed = jnp.stack(
            [
                vc.free[:, 0], vc.free[:, 1],
                res_fail.astype(jnp.float32), port_ok.astype(jnp.float32),
                storage_ok.astype(jnp.float32), g_ok.astype(jnp.float32),
                storage_raw, g_raw,
            ],
            axis=1,
        )                                                   # [N,CH]
        out = (
            vc.free, vc.gpu_free, vc.vg_free, vc.dev_free,
            g_take, vg_take_all, dev_take_all, packed,
        )
        vc2 = vc._replace(
            free=vc.free - pod.req[None, :],
            gpu_free=vc.gpu_free - g_take * pod.gpu_mem,
            vg_free=vc.vg_free - vg_take_all,
            dev_free=vc.dev_free - dev_take_all,
            port_any=vc.port_any + add_any[:, None],
            port_wild=vc.port_wild + add_wild[:, None],
            port_ipc=vc.port_ipc + add_ipc[:, None],
        )
        return vc2, out

    _, outs = jax.lax.scan(step, carry, None, length=j_steps)
    # scan stacks along axis 0 ([J,N,...]); move J next to the node axis so
    # per-step selection is a lane-local reduction.
    traj = Trajectory(*(jnp.moveaxis(o, 0, 1) for o in outs))
    static_ok, static_ff, static_scores, na_ok = _static_parts(
        ns, pod, weights, filter_on
    )
    return traj, static_ok, static_ff, static_scores, na_ok


def _x_onehot(x: jnp.ndarray, j_steps: int) -> jnp.ndarray:
    """bool[N,J] selector of each node's current commit count."""
    return jnp.arange(j_steps)[None, :] == x[:, None]


def _sel_j(traj_arr: jnp.ndarray, oh: jnp.ndarray) -> jnp.ndarray:
    """Select traj_arr[n, x_n] for every node via one-hot reduce.

    Exactness: exactly one J-lane is selected, the rest contribute literal
    zeros — adding zeros never changes an f32 value (the only bit change is
    -0.0 → +0.0, which nothing downstream distinguishes)."""
    if traj_arr.dtype == jnp.bool_:
        return jnp.any(traj_arr & oh, axis=1)
    if traj_arr.ndim == 2:
        return jnp.sum(traj_arr * oh.astype(traj_arr.dtype), axis=1)
    return jnp.sum(traj_arr * oh.astype(traj_arr.dtype)[:, :, None], axis=1)


class GroupFlags(NamedTuple):
    """Host-known facts about a group's pod spec, passed as STATIC jit args
    so _light_eval prunes provably-dead subgraphs at trace time. Every prune
    replaces a subcomputation with the constant the full graph would produce
    for this spec (ports_mask of a portless pod is all-true, the open-local
    score of a volume-less pod is all-zero, ...), so placements stay
    bit-identical — only tracing work and per-step kernels disappear."""
    dyn_ports: bool      # pod requests host ports (port state evolves)
    dyn_storage: bool    # pod has open-local volumes
    dyn_gpu: bool        # pod requests GPU share (gpu_free evolves)
    any_hard_spread: bool
    any_soft_spread: bool
    any_req_aff: bool    # required (anti)affinity terms
    any_pref_aff: bool   # preferred (anti)affinity terms
    any_anti_sym: bool   # existing anti-affinity terms repel this pod
    # topology spread (soft and/or hard) is the ONLY carry-coupled term and
    # uses non-hostname keys: the selection step reduces to partial9 +
    # w*spread with a small domain-count carry (the micro body)
    micro_spread: bool = False
    # EVERY carry-coupled term (spread, required/preferred inter-pod
    # affinity, anti-affinity symmetry) is domain-keyed over non-hostname
    # keys and there are no gpu/storage dynamics: the whole selection
    # reduces to the per-class domain path (domain_select)
    domain_aff: bool = False


ALL_DYNAMIC = GroupFlags(*([True] * 8))


def group_flags(row_np: dict, anti_topo_np: np.ndarray) -> GroupFlags:
    """Derive GroupFlags from one pod's numpy feature row."""
    spread_active = row_np["spread_topo"] >= 0
    soft = spread_active & ~row_np["spread_hard"]
    aff_active = row_np["aff_topo"] >= 0
    anti_match = (anti_topo_np >= 0) & row_np["match_anti"]
    f = GroupFlags(
        dyn_ports=bool((row_np["hp_pid"] > 0).any()),
        dyn_storage=bool(row_np["has_local"]),
        dyn_gpu=bool(row_np["gpu_mem"] > 0),
        any_hard_spread=bool((spread_active & row_np["spread_hard"]).any()),
        any_soft_spread=bool(soft.any()),
        any_req_aff=bool((aff_active & row_np["aff_required"]).any()),
        any_pref_aff=bool((aff_active & ~row_np["aff_required"]).any()),
        any_anti_sym=bool(anti_match.any()),
    )
    # hostname-keyed constraints count per node, not per domain — they keep
    # the general body
    keys_domainable = (
        bool((row_np["spread_topo"][spread_active] > 0).all())
        and bool((row_np["aff_topo"][aff_active] > 0).all())
        and bool((anti_topo_np[anti_match] > 0).all())
    )
    any_coupled = (
        f.any_soft_spread or f.any_hard_spread or f.any_req_aff
        or f.any_pref_aff or f.any_anti_sym
    )
    micro = (
        (f.any_soft_spread or f.any_hard_spread)
        and not f.any_req_aff
        and not f.any_pref_aff
        and not f.any_anti_sym
        and not f.dyn_gpu
        and not f.dyn_storage
        and keys_domainable
    )
    domain_aff = (
        any_coupled
        and not f.dyn_gpu
        and not f.dyn_storage
        and keys_domainable
    )
    return f._replace(micro_spread=micro, domain_aff=domain_aff)


def _light_eval(
    ns: NodeStatic,
    carry0: Carry,
    pod: PodRow,
    static_ok: jnp.ndarray,
    static_scores: dict,
    na_ok: jnp.ndarray,
    weights: jnp.ndarray,
    fo: jnp.ndarray,
    x: jnp.ndarray,
    cur: jnp.ndarray,
    flags: GroupFlags,
    hoisted: dict,
):
    """Evaluate feasibility + scores at commit state (x, cur) — shared by the
    selection scan's step and the once-per-group reason attribution. Returns
    (score f32[N] with -inf on infeasible, parts dict of effective per-filter
    bools for first-fail attribution). `hoisted` carries group-static values
    (computed once per chunk, loop-invariant): gpu_share score and the
    port/storage/gpu masks when their state cannot evolve."""
    N = ns.valid.shape[0]
    ones = jnp.ones(N, bool)
    xf = x.astype(jnp.float32)
    free2 = cur[:, CH_CPU:CH_MEM + 1]                 # [N,2]
    res_fail_x = (cur[:, CH_RES_FAIL] > 0.5) & fo[F_RESOURCES]
    if flags.dyn_ports:
        port_ok = (cur[:, CH_PORT_OK] > 0.5) | ~fo[F_NODE_PORTS]
    else:
        port_ok = ones  # a portless pod conflicts nowhere (ports_mask)
    if flags.dyn_storage:
        storage_ok = cur[:, CH_STO_OK] > 0.5
        storage_raw = cur[:, CH_STO_RAW]
    else:
        storage_ok = ones  # local_storage_eval: ok ≡ True when !has_local
    if flags.dyn_gpu:
        gpu_ok = cur[:, CH_GPU_OK] > 0.5
        gpu_raw = cur[:, CH_GPU_RAW]
    else:
        gpu_ok = ones  # gpu_mask admits non-GPU pods everywhere

    def srow(sel_idx):
        # sel_counts[sel_idx] after x commits: base + match * x — pure
        # integer f32 arithmetic, bit-equal to the scan's iterative +1s.
        return carry0.sel_counts[sel_idx] + pod.match_sel[sel_idx].astype(
            jnp.float32
        ) * xf

    # PodTopologySpread hard constraints (mirror kernels.spread_mask)
    if flags.any_hard_spread:
        def one_spread(topo_idx, sel_idx, max_skew, hard):
            active_c = (topo_idx >= 0) & hard
            k = jnp.maximum(topo_idx, 0)
            has_key = ns.topo[:, k] >= 0
            _, cnt_n, min_count, _ = _domain_counts(ns, srow(sel_idx), k, na_ok)
            ok_c = (cnt_n + 1.0 - min_count) <= max_skew + _EPS
            ok_c = ok_c & has_key
            return jnp.where(active_c, ok_c, jnp.ones_like(ok_c))

        spread_ok = jnp.all(
            jax.vmap(one_spread, in_axes=(0, 0, 0, 0), out_axes=1)(
                pod.spread_topo, pod.spread_sel, pod.spread_skew,
                pod.spread_hard,
            ),
            axis=1,
        ) | ~fo[F_SPREAD]
    else:
        spread_ok = ones  # every constraint row is inactive => all-true

    # InterPodAffinity required terms + anti-affinity symmetry
    # (mirror kernels.pod_affinity_mask)
    if flags.any_req_aff:
        def one_aff(topo_idx, sel_idx, anti, required):
            active_t = (topo_idx >= 0) & required
            k = jnp.maximum(topo_idx, 0)
            has_key = ns.topo[:, k] >= 0
            _, cnt, _, total = _domain_counts(ns, srow(sel_idx), k)
            self_match = pod.match_sel[sel_idx]
            aff_feasible = (cnt > 0) | (self_match & (total == 0))
            aff_feasible = aff_feasible & has_key
            ok_t = jnp.where(anti, cnt == 0, aff_feasible)
            return jnp.where(active_t, ok_t, jnp.ones(N, bool))

        req_ok = jnp.all(
            jax.vmap(one_aff, in_axes=(0, 0, 0, 0), out_axes=1)(
                pod.aff_topo, pod.aff_sel, pod.aff_anti, pod.aff_required
            ),
            axis=1,
        )
    else:
        req_ok = ones

    if flags.any_anti_sym:
        def one_sym(topo_idx, base_row, own, match):
            active_t = (topo_idx >= 0) & match
            k = jnp.maximum(topo_idx, 0)
            has_key = ns.topo[:, k] >= 0
            _, cnt, _, _ = _domain_counts(ns, base_row + own * xf, k)
            ok_t = (cnt == 0) | ~has_key
            return jnp.where(active_t, ok_t, jnp.ones(N, bool))

        sym_ok = jnp.all(
            jax.vmap(one_sym, in_axes=(0, 0, 0, 0), out_axes=1)(
                ns.anti_topo, carry0.anti_counts, pod.own_anti, pod.match_anti
            ),
            axis=1,
        )
    else:
        sym_ok = ones
    aff_ok = (req_ok & sym_ok) | ~fo[F_POD_AFFINITY]

    mask = (
        static_ok & port_ok & ~res_fail_x & spread_ok & aff_ok & storage_ok
        & gpu_ok & ns.valid
    )

    # Dynamic scores (mirror kernels.score_* on the reconstructed state)
    la, ba = _la_ba(ns, pod, free2)

    if flags.any_soft_spread:
        def one_ssc(topo_idx, sel_idx, hard):
            active_c = (topo_idx >= 0) & ~hard
            k = jnp.maximum(topo_idx, 0)
            _, cnt, _, _ = _domain_counts(ns, srow(sel_idx), k, na_ok)
            return jnp.where(active_c, cnt, 0.0)

        raw_sp = jnp.sum(
            jax.vmap(one_ssc, in_axes=(0, 0, 0), out_axes=1)(
                pod.spread_topo, pod.spread_sel, pod.spread_hard
            ),
            axis=1,
        )
        mx_sp = jnp.max(jnp.where(ns.valid, raw_sp, 0.0))
        sp_score = jnp.clip(
            jnp.where(
                mx_sp > 0, (mx_sp - raw_sp) * 100.0 / jnp.maximum(mx_sp, 1e-9),
                100.0,
            ),
            0.0,
            100.0,
        )
    else:
        sp_score = jnp.full(N, 100.0)  # raw ≡ 0 => mx 0 => the 100.0 branch

    if flags.any_pref_aff:
        def one_asc(topo_idx, sel_idx, anti, required, weight):
            active_t = (topo_idx >= 0) & ~required
            k = jnp.maximum(topo_idx, 0)
            _, cnt, _, _ = _domain_counts(ns, srow(sel_idx), k)
            signed = jnp.where(anti, -weight, weight) * cnt
            return jnp.where(active_t, signed, 0.0)

        raw_a = jnp.sum(
            jax.vmap(one_asc, in_axes=(0, 0, 0, 0, 0), out_axes=1)(
                pod.aff_topo, pod.aff_sel, pod.aff_anti, pod.aff_required,
                pod.aff_weight,
            ),
            axis=1,
        )
        any_active = jnp.any((pod.aff_topo >= 0) & ~pod.aff_required)
        ipa = jnp.where(any_active, _minmax_normalize(raw_a, ns.valid), 0.0)
    else:
        ipa = jnp.zeros(N)  # the where(any_active, ..., 0.0) branch

    by_name = {
        "balanced_allocation": ba,
        "least_allocated": la,
        "topology_spread": sp_score,
        "inter_pod_affinity": ipa,
        "gpu_share": (
            _minmax_normalize(gpu_raw, ns.valid)
            if flags.dyn_gpu
            else hoisted["gpu_score"]
        ),
        "open_local": (
            jnp.where(
                pod.has_local, _minmax_normalize(storage_raw, ns.valid), 0.0
            )
            if flags.dyn_storage
            else jnp.zeros(N)  # has_local False => the 0.0 branch
        ),
        **static_scores,
    }
    score = combine_scores(by_name, weights)
    score = jnp.where(mask, score, -jnp.inf)
    parts = {
        "port_ok": port_ok, "res_fail": res_fail_x, "spread_ok": spread_ok,
        "aff_ok": aff_ok, "storage_ok": storage_ok, "gpu_ok": gpu_ok,
    }
    return score, parts


def _la_ba(ns: NodeStatic, pod: PodRow, free2: jnp.ndarray):
    """LeastAllocated + BalancedAllocation from cpu/mem free values — the one
    definition all fast paths share (free2 is [N,2] or [N,J,2]; the ops are
    elementwise, so every lane is bit-identical to the per-step kernel)."""
    alloc2 = ns.alloc[:, :2]
    req2 = pod.req[:2]
    if free2.ndim == 3:
        alloc2 = alloc2[:, None, :]
        req2 = req2[None, None, :]
    else:
        req2 = req2[None, :]
    free_after = free2 - req2
    frac = jnp.where(alloc2 > 0, free_after / jnp.maximum(alloc2, 1e-9), 0.0)
    la = jnp.clip(jnp.mean(frac, axis=-1), 0.0, 1.0) * 100.0
    used_after = alloc2 - free2 + req2
    frac_b = jnp.where(alloc2 > 0, used_after / jnp.maximum(alloc2, 1e-9), 0.0)
    frac_b = jnp.clip(frac_b, 0.0, 1.0)
    ba = (1.0 - jnp.abs(frac_b[..., 0] - frac_b[..., 1])) * 100.0
    return la, ba


def _lane_rows(
    ns: NodeStatic, traj: Trajectory, pod: PodRow, static_scores: dict
) -> dict:
    """The nine node-local score rows per (node, lane) — shared by the sort
    path and the micro body so the arithmetic can never drift between them.
    Assumes gpu_free is frozen for the group (callers gate on !dyn_gpu) and
    no storage volumes / preferred affinity terms."""
    N, J, _ = traj.packed.shape
    free2 = traj.packed[:, :, CH_CPU:CH_MEM + 1]
    la, ba = _la_ba(ns, pod, free2)
    gpu_score = _minmax_normalize(traj.packed[:, 0, CH_GPU_RAW], ns.valid)

    def bcast(v):
        return jnp.broadcast_to(v[:, None], (N, J))

    return {
        "balanced_allocation": ba,
        "least_allocated": la,
        "inter_pod_affinity": jnp.zeros((N, J)),
        "gpu_share": bcast(gpu_score),
        "open_local": jnp.zeros((N, J)),
        **{k: bcast(v) for k, v in static_scores.items()},
    }


def _sortable(flags: GroupFlags) -> bool:
    """A group is sort-path eligible when every score/mask is a function of
    the node's OWN commit count alone: no spread/affinity terms (they couple
    through domain counts) and no GPU/storage volumes (their scores are
    min-max normalized over the batch's CURRENT raw values, which change as
    other nodes commit). Host ports are fine — purely node-local."""
    return not (
        flags.any_hard_spread
        or flags.any_soft_spread
        or flags.any_req_aff
        or flags.any_pref_aff
        or flags.any_anti_sym
        or flags.dyn_gpu
        or flags.dyn_storage
    )


@sanitizable("ops.fast:sort_select", static_argnames=("out_size",))
@functools.partial(jax.jit, static_argnames=("out_size",))
def sort_select(
    ns: NodeStatic,
    traj: Trajectory,
    pod: PodRow,
    static_ok: jnp.ndarray,
    static_scores: dict,
    weights: jnp.ndarray,
    valid_count: jnp.ndarray,
    out_size: int,
    filter_on=None,
):
    """Whole-group selection in ONE pass for sort-eligible groups.

    With purely node-local scores, the sequential argmax is a k-way merge of
    each node's (non-increasing) score sequence — i.e. the globally sorted
    order of all [N,J] trajectory entries. A STABLE descending sort on the
    row-major flattening reproduces the scan's tie-breaks exactly: equal
    scores resolve to the lowest flat index = lowest node index first (the
    scan's first-max argmax), and within a node to increasing commit count
    (forced by sequence order anyway).

    Returns (mono_ok, nodes i32[out_size], jidx i32[out_size], x i32[N]).
    mono_ok is False when some node's score sequence INCREASES at a step
    (balanced-allocation can rise while least-allocated falls); the caller
    must then discard this result and take the scan path — the merge
    argument needs non-increasing rows."""
    N, J, _ = traj.packed.shape
    fo = jnp.ones(NUM_FILTERS, bool) if filter_on is None else filter_on

    res_fail = traj.packed[:, :, CH_RES_FAIL] > 0.5
    port_ok = (traj.packed[:, :, CH_PORT_OK] > 0.5) | ~fo[F_NODE_PORTS]
    storage_ok = traj.packed[:, :, CH_STO_OK] > 0.5
    gpu_ok = traj.packed[:, :, CH_GPU_OK] > 0.5
    mask = (
        static_ok[:, None] & port_ok & ~res_fail & storage_ok & gpu_ok
        & ns.valid[:, None]
    )                                                      # [N,J]

    by_name = dict(_lane_rows(ns, traj, pod, static_scores))
    by_name["topology_spread"] = jnp.full((N, J), 100.0)  # no soft constraints
    score = combine_scores(by_name, weights)
    score = jnp.where(mask, score, -jnp.inf)

    mono_ok = jnp.all(score[:, 1:] <= score[:, :-1])

    flat = score.reshape(-1)
    order = jnp.argsort(-flat, stable=True)[:out_size]
    sel_score = flat[order]
    feasible = jnp.isfinite(sel_score) & (jnp.arange(out_size) < valid_count)
    sel_n = (order // J).astype(jnp.int32)
    sel_j = (order % J).astype(jnp.int32)
    nodes = jnp.where(feasible, sel_n, -1)
    jidx = jnp.where(feasible, sel_j, 0)
    x = jnp.zeros(N, jnp.int32).at[sel_n].add(feasible.astype(jnp.int32))
    return mono_ok, nodes, jidx, x


@sanitizable("ops.fast:cur_at")
@jax.jit
def cur_at(traj: Trajectory, x: jnp.ndarray) -> jnp.ndarray:
    """packed[n, x_n] for every node (reason attribution after a sort-path
    group needs the final-state channels)."""
    return _sel_j(traj.packed, _x_onehot(x, traj.packed.shape[1]))


def _hoisted_values(ns: NodeStatic, cur: jnp.ndarray, flags: GroupFlags) -> dict:
    """Group-invariant values _light_eval needs, computed once per jit call
    (outside the scan body). For a non-GPU group gpu_free never changes, so
    the gpu-share score is frozen at its entry value — cur's CH_GPU_RAW
    channel is constant across lanes for such groups."""
    out = {}
    if not flags.dyn_gpu:
        out["gpu_score"] = _minmax_normalize(cur[:, CH_GPU_RAW], ns.valid)
    return out


SP_IDX = WEIGHT_ORDER.index("topology_spread")
IPA_IDX = WEIGHT_ORDER.index("inter_pod_affinity")
assert SP_IDX == len(WEIGHT_ORDER) - 1 and IPA_IDX == SP_IDX - 1, (
    "the fast paths' partial-sum splits need the carry-coupled terms LAST "
    "in combine_scores' fold order: ..., inter_pod_affinity, topology_spread"
)


@sanitizable("ops.fast:light_scan", static_argnames=("group_size", "flags"))
@functools.partial(jax.jit, static_argnames=("group_size", "flags"))
def light_scan(
    ns: NodeStatic,
    traj: Trajectory,
    carry0: Carry,
    pod: PodRow,
    static_ok: jnp.ndarray,
    static_scores: dict,
    na_ok: jnp.ndarray,
    weights: jnp.ndarray,
    x0: jnp.ndarray,
    offset: jnp.ndarray,
    group_size: int,
    valid_count: jnp.ndarray,
    filter_on=None,
    flags: GroupFlags = ALL_DYNAMIC,
):
    """Select nodes for `group_size` pods of the group, starting from commit
    state x0 (chunks of one group thread x through; everything else is
    reconstructed from x at chunk start). Only steps with offset + i <
    valid_count commit. Returns (x, nodes i32[G], jidx i32[G]).

    The scan carry keeps `cur` = packed[n, x_n] for every node (invariant:
    a commit only advances the chosen node's lane, so one dynamic row update
    per step maintains it) — the step never re-reads the [N,J,*] trajectory.
    Failure reasons are NOT computed per step: an infeasible step commits
    nothing, so the state freezes and every later step of the group fails
    identically — light_reasons attributes the whole failure suffix once.

    flags.micro_spread selects the MICRO body: when topology spread (soft
    and/or hard, non-hostname keys) is the only carry-coupled term, the 9
    other score rows are hoisted into a per-lane partial sum and the step is
    `partial9 + w_sp * spread` (+ the DoNotSchedule skew mask from the same
    reconstructed domain counts) — an exact split of combine_scores'
    explicit left fold because topology_spread is the LAST summand
    (asserted at import)."""
    N = ns.valid.shape[0]
    j_steps = traj.packed.shape[1]
    fo = jnp.ones(NUM_FILTERS, bool) if filter_on is None else filter_on

    if flags.micro_spread:
        return _light_scan_micro(
            ns, traj, carry0, pod, static_ok, static_scores, na_ok, weights,
            x0, offset, group_size, valid_count, fo, flags,
        )

    cur0 = _sel_j(traj.packed, _x_onehot(x0, j_steps))
    hoisted = _hoisted_values(ns, cur0, flags)

    def step(carry_xc, i):
        x, cur = carry_xc
        active = (offset + i) < valid_count
        score, _ = _light_eval(
            ns, carry0, pod, static_ok, static_scores, na_ok, weights, fo,
            x, cur, flags, hoisted,
        )
        node = jnp.argmax(score)
        # any(mask) == the winning score is finite (infeasible rows are -inf)
        ok = (score[node] > -jnp.inf) & active
        node_out = jnp.where(ok, node, -1)
        jidx = jnp.where(ok, x[node], 0)

        onehot = (jnp.arange(N) == node) & ok
        x2 = x + onehot.astype(jnp.int32)
        # Maintain cur = packed[n, x_n]: refresh only the chosen node's row.
        j_next = jnp.clip(x[node] + 1, 0, j_steps - 1)
        new_row = jax.lax.dynamic_slice(
            traj.packed, (node, j_next, 0), (1, 1, N_CH)
        )[0]
        row = jnp.where(ok, new_row, cur[node][None, :])
        cur2 = jax.lax.dynamic_update_slice(cur, row, (node, 0))

        return (x2, cur2), (node_out.astype(jnp.int32), jidx.astype(jnp.int32))

    (x_final, _), (nodes, jidxs) = jax.lax.scan(
        step, (x0, cur0), jnp.arange(group_size)
    )
    return x_final, nodes, jidxs


class SpreadTables(NamedTuple):
    """Loop-invariant spread-reconstruction tables shared VERBATIM by the
    micro body and the domain-merge path — one construction site keeps their
    f32 arithmetic structurally bit-identical (the domain path's exactness
    argument depends on it). in_key_cd is None unless flags.any_hard_spread."""
    k_c: jnp.ndarray       # i32[C] topo key per constraint row
    to_c: jnp.ndarray      # f32[C,D,N] domain membership per constraint
    elig_f: jnp.ndarray    # f32[N] spread eligibility (na_ok & valid)
    match_c: jnp.ndarray   # f32[C] pod matches the constraint's selector
    base_dom: jnp.ndarray  # f32[C,D] eligible-node counts at group entry
    active_c: jnp.ndarray  # bool[C] soft rows (feed the score)
    hard_c: jnp.ndarray    # bool[C] DoNotSchedule rows (feed the mask)
    in_key_cd: jnp.ndarray | None  # bool[C,D] eligible domains of the row's key


def _spread_tables(
    ns: NodeStatic, carry0: Carry, pod: PodRow, na_ok, flags: GroupFlags
) -> SpreadTables:
    """Spread tables (non-hostname keys; soft rows feed the score, hard rows
    the mask — both share the per-row domain-count reconstruction)."""
    active_c = (pod.spread_topo >= 0) & ~pod.spread_hard          # [C]
    hard_c = (pod.spread_topo >= 0) & pod.spread_hard             # [C]
    k_c = jnp.maximum(pod.spread_topo, 0)                         # [C]
    to_c = ns.topo_onehot[k_c]                                    # [C,D,N]
    elig_f = (na_ok & ns.valid).astype(jnp.float32)               # [N]
    base_rows = carry0.sel_counts[pod.spread_sel]                 # [C,N]
    match_c = pod.match_sel[pod.spread_sel].astype(jnp.float32)   # [C]
    counts0 = jnp.where(elig_f > 0, base_rows, 0.0)               # [C,N]
    base_dom = jnp.einsum(
        "cdn,cn->cd", to_c, counts0, precision=jax.lax.Precision.HIGHEST
    )                                                             # [C,D]
    in_key_cd = None
    if flags.any_hard_spread:
        dom_elig = jnp.einsum(
            "cdn,n->cd", to_c, elig_f, precision=jax.lax.Precision.HIGHEST
        ) > 0.0                                                   # [C,D]
        in_key_cd = (ns.domain_key[None, :] == k_c[:, None]) & dom_elig
    return SpreadTables(
        k_c, to_c, elig_f, match_c, base_dom, active_c, hard_c, in_key_cd
    )


def _lane_partials(
    ns, traj, pod, static_scores, static_ok, weights, fo, prefix_end=SP_IDX
):
    """(partial, feas) per lane — the partial is the left-fold prefix of
    combine_scores through WEIGHT_ORDER[:prefix_end] (SP_IDX for the micro
    body's partial9, IPA_IDX for the domain path's partial8; `partial +
    w_ipa*ipa + w_sp*sp` then equals the full fold by construction because
    the coupled terms are last). Feasibility covers the only dynamics a
    micro/domain-eligible group has: ports and resources."""
    p9 = combine_scores(
        _lane_rows(ns, traj, pod, static_scores), weights,
        order=WEIGHT_ORDER[:prefix_end],
    )                                                             # [N,J]
    feas = (
        static_ok[:, None]
        & ((traj.packed[:, :, CH_PORT_OK] > 0.5) | ~fo[F_NODE_PORTS])
        & ~((traj.packed[:, :, CH_RES_FAIL] > 0.5) & fo[F_RESOURCES])
        & ns.valid[:, None]
    )                                                             # [N,J]
    return p9, feas


def _spread_norm(raw: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """The topology-spread score normalization (mirror of
    kernels.score_topology_spread on reconstructed counts); `valid` masks
    which entries may define the max."""
    mx = jnp.max(jnp.where(valid, raw, 0.0))
    return jnp.clip(
        jnp.where(mx > 0, (mx - raw) * 100.0 / jnp.maximum(mx, 1e-9), 100.0),
        0.0,
        100.0,
    )


def _hard_spread_ok(dom, cnt, in_key_cd, hard_c, skew, has_key, f_spread_on):
    """DoNotSchedule skew verdict (mirror kernels.spread_mask via the
    reconstructed dom — integer-exact, so bit-identical). `cnt`/`has_key`
    are per-(constraint, node) in the micro body and per-(constraint, class)
    in the domain path; the arithmetic is identical. Mask args are bool —
    the ONE definition shared by the micro body, the XLA domain scan and
    the Pallas kernel (the exactness contract depends on it)."""
    min_dom = jnp.min(jnp.where(in_key_cd, dom, jnp.inf), axis=1)
    min_c = jnp.where(jnp.isfinite(min_dom), min_dom, 0.0)
    ok = ((cnt + 1.0 - min_c[:, None]) <= skew[:, None] + _EPS) & has_key
    return jnp.all(jnp.where(hard_c[:, None], ok, True), axis=0) | ~f_spread_on


def _light_scan_micro(
    ns, traj, carry0, pod, static_ok, static_scores, na_ok, weights,
    x0, offset, group_size, valid_count, fo, flags,
):
    """The topology-spread micro body (see light_scan docstring). Traced inside
    light_scan's jit; everything here but the scan body is loop-invariant."""
    N = ns.valid.shape[0]
    j_steps = traj.packed.shape[1]
    D = ns.topo_onehot.shape[1]

    p9, feas = _lane_partials(
        ns, traj, pod, static_scores, static_ok, weights, fo
    )
    w_sp = weights[SP_IDX]
    score_lane = jnp.where(feas, p9, -jnp.inf)                    # [N,J]

    st = _spread_tables(ns, carry0, pod, na_ok, flags)
    active_c, hard_c = st.active_c, st.hard_c
    k_c, to_c, elig_f = st.k_c, st.to_c, st.elig_f
    match_c, base_dom = st.match_c, st.base_dom
    if flags.any_hard_spread:
        has_key_cn = (ns.topo[:, k_c] >= 0).T                     # [C,N]
    xf0 = x0.astype(jnp.float32)
    y0 = jnp.einsum(
        "cdn,n->cd", to_c, elig_f * xf0,
        precision=jax.lax.Precision.HIGHEST,
    )                                                             # [C,D]
    # select p9 and feasibility SEPARATELY: _sel_j's one-hot multiply would
    # turn score_lane's -inf entries into NaN (-inf * 0.0) on unselected lanes
    oh0 = _x_onehot(x0, j_steps)
    cur_s0 = jnp.where(
        _sel_j(feas, oh0), _sel_j(p9, oh0), -jnp.inf
    )                                                             # [N]

    def step(carry_xy, i):
        x, cur_s, y = carry_xy
        active = (offset + i) < valid_count
        dom = base_dom + match_c[:, None] * y                     # [C,D]
        cnt = jnp.einsum(
            "cd,cdn->cn", dom, to_c, precision=jax.lax.Precision.HIGHEST
        )                                                         # [C,N]
        raw = jnp.sum(jnp.where(active_c[:, None], cnt, 0.0), axis=0)
        sp = _spread_norm(raw, ns.valid)
        score = cur_s + w_sp * sp                                 # -inf stays
        if flags.any_hard_spread:
            spread_ok = _hard_spread_ok(
                dom, cnt, st.in_key_cd, st.hard_c, pod.spread_skew,
                has_key_cn, fo[F_SPREAD],
            )
            score = jnp.where(spread_ok, score, -jnp.inf)
        node = jnp.argmax(score)
        ok = (score[node] > -jnp.inf) & active
        node_out = jnp.where(ok, node, -1)
        jidx = jnp.where(ok, x[node], 0)

        onehot = (jnp.arange(N) == node) & ok
        x2 = x + onehot.astype(jnp.int32)
        j_next = jnp.clip(x[node] + 1, 0, j_steps - 1)
        new_s = jax.lax.dynamic_slice(score_lane, (node, j_next), (1, 1))
        new_s = jnp.where(ok, new_s, cur_s[node][None, None])
        cur_s2 = jax.lax.dynamic_update_slice(cur_s[:, None], new_s, (node, 0))[
            :, 0
        ]
        to_col = jax.lax.dynamic_slice(to_c, (0, 0, node), (to_c.shape[0], D, 1))
        y2 = y + to_col[:, :, 0] * (
            elig_f[node] * ok.astype(jnp.float32)
        )
        return (x2, cur_s2, y2), (
            node_out.astype(jnp.int32), jidx.astype(jnp.int32)
        )

    (x_final, _, _), (nodes, jidxs) = jax.lax.scan(
        step, (x0, cur_s0, y0), jnp.arange(group_size)
    )
    return x_final, nodes, jidxs


class DomainPlan(NamedTuple):
    """Host-built static structure for the domain-merge path: the partition
    of nodes into combined (spread-domain-tuple, eligibility) classes. All
    nodes in one class are interchangeable w.r.t. every carry-coupled term of
    a micro-eligible group (topology spread is domain-keyed, and these nodes
    share every constraint's domain), so the scan state shrinks from [N] to
    [Dc] — see domain_select.

    Dc is PADDED to max(4, next_pow2(real classes)) for jit-shape reuse; the
    synthetic tail classes hold counts=0 / elig=0 / combo_valid=False, so
    they are permanently exhausted and excluded from the spread max. Callers
    wanting the real class count must use combo_of_node.max()+1, not
    counts.shape[0]."""
    combo_of_node: np.ndarray  # i32[N] class id per node
    counts: np.ndarray         # i32[Dc] trajectory lanes per class (nodes * J)
    offsets: np.ndarray        # i32[Dc] class start in the combo-sorted order
    elig_combo: np.ndarray     # f32[Dc] 1.0 = class counts for spread
    combo_valid: np.ndarray    # bool[Dc] class holds >= 1 valid node
    t_onehot: np.ndarray       # f32[C,D,Dc] spread-row domain membership
    has_key: np.ndarray        # bool[C,Dc] class has spread row c's topo key
    t_aff: np.ndarray          # f32[CA,D,Dc] affinity-row domain membership
    has_key_aff: np.ndarray    # bool[CA,Dc]
    t_anti: np.ndarray         # f32[AT,D,Dc] anti-sym-term domain membership
    has_key_anti: np.ndarray   # bool[AT,Dc]


def _map_onehot(keys_np, act, uniq_cols, col_of, dc, dc_pad, n_domains):
    """(map, onehot, has_key) for one constraint family: map[r, m] = class
    m's domain under row r's topo key (-1 when inactive / key missing)."""
    R = keys_np.shape[0]
    m = np.full((R, dc_pad), -1, np.int32)
    act_rows = np.nonzero(act)[0]
    if act_rows.size:
        m[act_rows[:, None], np.arange(dc)[None, :]] = uniq_cols[
            :, [col_of[int(keys_np[r])] for r in act_rows]
        ].T
    onehot = (
        m[:, None, :] == np.arange(n_domains)[None, :, None]
    ).astype(np.float32)
    return m, onehot


def _domain_plan(
    spread_topo_np: np.ndarray,
    aff_topo_np: np.ndarray,
    anti_topo_np: np.ndarray,
    match_anti_np: np.ndarray,
    topo_np: np.ndarray,
    valid_np: np.ndarray,
    elig_np: np.ndarray,
    j_steps: int,
    n_domains: int,
):
    """Partition nodes into combined domain classes over EVERY coupled
    term's topology key (spread rows, affinity rows, matching registered
    anti-affinity terms) plus the spread-eligibility bit; None when the
    group is too fragmented (Dc > DM_CAP) to beat the scan paths."""
    s_act = spread_topo_np >= 0
    a_act = aff_topo_np >= 0
    t_act = (anti_topo_np >= 0) & match_anti_np
    keys = np.unique(np.concatenate([
        spread_topo_np[s_act], aff_topo_np[a_act], anti_topo_np[t_act],
    ]))
    col_of = {int(k): i for i, k in enumerate(keys)}
    cols = topo_np[:, keys]                                     # [N,K']
    # spread eligibility splits classes ONLY when a spread row consumes it —
    # for pure-affinity groups it would just fragment Dc for nothing
    if s_act.any():
        keymat = np.concatenate(
            [cols, elig_np[:, None].astype(np.int32)], axis=1
        )
    else:
        keymat = cols
    uniq, inv = np.unique(keymat, axis=0, return_inverse=True)
    dc = uniq.shape[0]
    if dc > DM_CAP:
        return None
    dc_pad = max(4, 1 << (dc - 1).bit_length())
    node_counts = np.bincount(inv, minlength=dc_pad)
    counts = (node_counts * j_steps).astype(np.int32)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
    elig_combo = np.zeros(dc_pad, np.float32)
    if s_act.any():
        elig_combo[:dc] = uniq[:, -1]
        uniq_cols = uniq[:, :-1]                                # [dc,K']
    else:
        uniq_cols = uniq  # elig column absent (unused without spread rows)
    combo_valid = np.zeros(dc_pad, bool)
    np.logical_or.at(combo_valid, inv, valid_np)
    map_s, t_onehot = _map_onehot(
        spread_topo_np, s_act, uniq_cols, col_of, dc, dc_pad, n_domains
    )
    map_a, t_aff = _map_onehot(
        aff_topo_np, a_act, uniq_cols, col_of, dc, dc_pad, n_domains
    )
    map_t, t_anti = _map_onehot(
        anti_topo_np, t_act, uniq_cols, col_of, dc, dc_pad, n_domains
    )
    return DomainPlan(
        inv.astype(np.int32), counts, offsets, elig_combo, combo_valid,
        t_onehot, map_s >= 0, t_aff, map_a >= 0, t_anti, map_t >= 0,
    )


@sanitizable(
    "ops.fast:domain_select",
    static_argnames=("group_size", "l_cap", "flags", "use_pallas"),
    skip_kwargs=("use_pallas",),
)
@functools.partial(
    jax.jit, static_argnames=("group_size", "l_cap", "flags", "use_pallas")
)
def domain_select(
    ns: NodeStatic,
    traj: Trajectory,
    carry0: Carry,
    pod: PodRow,
    static_ok: jnp.ndarray,
    static_scores: dict,
    na_ok: jnp.ndarray,
    weights: jnp.ndarray,
    combo_of_node: jnp.ndarray,
    counts: jnp.ndarray,
    offsets: jnp.ndarray,
    elig_combo: jnp.ndarray,
    combo_valid: jnp.ndarray,
    t_onehot: jnp.ndarray,
    has_key_cm: jnp.ndarray,
    t_aff: jnp.ndarray,
    has_key_aff: jnp.ndarray,
    t_anti: jnp.ndarray,
    has_key_anti: jnp.ndarray,
    group_size: int,
    l_cap: int,
    valid_count: jnp.ndarray,
    filter_on=None,
    flags: GroupFlags = ALL_DYNAMIC,
    use_pallas: bool = False,
):
    """Whole-group selection with an O(Dc) scan state for domain-eligible
    groups: every carry-coupled term — topology spread, required/preferred
    inter-pod affinity, anti-affinity symmetry — keyed by non-hostname
    topology keys (flags.domain_aff), with no gpu/storage dynamics.

    Two structural facts shrink the scan from O(N) to O(Dc) per step:
      1. The spread term is DOMAIN-keyed: every node of a combined class
         (same domain under each constraint, same eligibility) shares the
         same spread score and DoNotSchedule verdict at every step.
      2. Within a class, relative order is by the node-local partial score
         alone (the spread addend is class-constant), so the scan's pick
         sequence inside a class is the sort-path merge: one stable sort of
         all [N,J] lanes keyed (class, score desc) — ties resolve to the
         lowest flat index = the scan's first-max argmax.

    The scan then walks per-class HEAD pointers: each step scores only the
    Dc class heads (head partial + w_sp * spread(class)), pops the winner,
    and updates the [Dc] domain-count state. Cross-class ties pick the
    lowest head node index, which equals the global argmax tie-break because
    each class head is its class's lowest-index maximum.

    Exactness: head partials are the same f32 lane values; domain counts
    for spread, affinity and anti-symmetry are reconstructed with the
    shared helpers' einsum arithmetic (exact integer f32); the spread and
    min-max normalizations apply the identical expressions; and the fold
    `(partial8 + w_ipa*ipa) + w_sp*sp` is combine_scores' own left
    association — so every per-step total is bit-identical to the scan
    bodies'. mono_ok False (a lane sequence rose) voids fact 2; the caller
    falls back to the micro scan (spread-only groups) or the light scan.

    Returns (mono_ok, nodes i32[group_size], jidx i32[group_size], x i32[N]).
    """
    N, J, _ = traj.packed.shape
    Dc = counts.shape[0]
    fo = jnp.ones(NUM_FILTERS, bool) if filter_on is None else filter_on

    # partial8 lanes: the fold prefix BEFORE both coupled terms; the step
    # adds w_ipa*ipa(class) then w_sp*sp(class), reproducing the full fold.
    p8, feas = _lane_partials(
        ns, traj, pod, static_scores, static_ok, weights, fo,
        prefix_end=IPA_IDX,
    )
    score_lane = jnp.where(feas, p8, -jnp.inf)
    mono_ok = jnp.all(score_lane[:, 1:] <= score_lane[:, :-1])

    # Stable sort keyed (class asc, score desc): within a class, lanes land
    # in exactly the order the scan would pop them (ties keep flat order =
    # lowest node first, then increasing j within a node).
    flat_combo = jnp.broadcast_to(combo_of_node[:, None], (N, J)).reshape(-1)
    neg = (-score_lane).reshape(-1)
    flat_idx = jnp.arange(N * J, dtype=jnp.int32)
    _, sneg, sidx = jax.lax.sort(
        (flat_combo, neg, flat_idx), num_keys=2, is_stable=True
    )
    gidx = jnp.clip(offsets[:, None] + jnp.arange(l_cap)[None, :], 0, N * J - 1)
    in_range = jnp.arange(l_cap)[None, :] < counts[:, None]
    hscore = jnp.where(in_range, -sneg[gidx], -jnp.inf)           # [Dc,L]
    hflat = sidx[gidx]
    hnode = (hflat // J).astype(jnp.int32)
    hj = (hflat % J).astype(jnp.int32)
    cap_eff = jnp.minimum(counts, l_cap)

    # spread tables — the micro body's own construction (shared helper, so
    # the arithmetic cannot drift between the two bodies)
    st = _spread_tables(ns, carry0, pod, na_ok, flags)
    w_sp = weights[SP_IDX]
    w_ipa = weights[IPA_IDX]
    any_aff = flags.any_req_aff or flags.any_pref_aff or flags.any_anti_sym
    valid_f = ns.valid.astype(jnp.float32)

    if flags.any_req_aff or flags.any_pref_aff:
        # inter-pod affinity tables (mirror _light_eval's one_aff/one_asc:
        # _domain_counts with elig=None counts over ALL valid nodes)
        k_a = jnp.maximum(pod.aff_topo, 0)
        to_a = ns.topo_onehot[k_a]                                # [CA,D,N]
        base_rows_a = carry0.sel_counts[pod.aff_sel]              # [CA,N]
        match_a = pod.match_sel[pod.aff_sel].astype(jnp.float32)  # [CA]
        counts0_a = jnp.where(valid_f > 0, base_rows_a, 0.0)
        base_dom_a = jnp.einsum(
            "cdn,cn->cd", to_a, counts0_a,
            precision=jax.lax.Precision.HIGHEST,
        )                                                         # [CA,D]
        in_key_a = (ns.domain_key[None, :] == k_a[:, None]) & (
            jnp.einsum(
                "cdn,n->cd", to_a, valid_f,
                precision=jax.lax.Precision.HIGHEST,
            ) > 0.0
        )                                                         # [CA,D]
        req_t = (pod.aff_topo >= 0) & pod.aff_required
        pref_t = (pod.aff_topo >= 0) & ~pod.aff_required
        self_match_a = pod.match_sel[pod.aff_sel]                 # [CA] bool
        any_pref_active = jnp.any(pref_t)
    if flags.any_anti_sym:
        # anti-affinity symmetry tables (mirror _light_eval's one_sym)
        k_t = jnp.maximum(ns.anti_topo, 0)
        to_t = ns.topo_onehot[k_t]                                # [AT,D,N]
        counts0_t = jnp.where(valid_f > 0, carry0.anti_counts, 0.0)
        base_dom_t = jnp.einsum(
            "tdn,tn->td", to_t, counts0_t,
            precision=jax.lax.Precision.HIGHEST,
        )                                                         # [AT,D]
        active_sym = (ns.anti_topo >= 0) & pod.match_anti         # [AT]

    any_spread = flags.any_soft_spread or flags.any_hard_spread

    def step(carry_hy, i):
        h, y = carry_hy
        if any_spread:
            y_elig = y * elig_combo
            dom = st.base_dom + st.match_c[:, None] * jnp.einsum(
                "cdm,m->cd", t_onehot, y_elig,
                precision=jax.lax.Precision.HIGHEST,
            )                                                     # [C,D]
            cnt_cm = jnp.einsum(
                "cd,cdm->cm", dom, t_onehot,
                precision=jax.lax.Precision.HIGHEST,
            )                                                     # [C,Dc]
            raw = jnp.sum(
                jnp.where(st.active_c[:, None], cnt_cm, 0.0), axis=0
            )
            sp = _spread_norm(raw, combo_valid)                   # [Dc]
        else:
            # no active spread row: raw ≡ 0 => the 100.0 branch (the same
            # prune _light_eval applies)
            sp = jnp.full(Dc, 100.0)
        hc = jnp.clip(h, 0, l_cap - 1)[:, None]
        hs = jnp.where(
            h < cap_eff,
            jnp.take_along_axis(hscore, hc, axis=1)[:, 0],
            -jnp.inf,
        )

        ipa = jnp.zeros(Dc)
        aff_ok = jnp.ones(Dc, bool)
        if flags.any_req_aff or flags.any_pref_aff:
            # every pod of the group is identical, so its commits add
            # match_a per class commit to the row's selector counts
            dom_a = base_dom_a + match_a[:, None] * jnp.einsum(
                "cdm,m->cd", t_aff, y, precision=jax.lax.Precision.HIGHEST
            )                                                     # [CA,D]
            cnt_a = jnp.einsum(
                "cd,cdm->cm", dom_a, t_aff,
                precision=jax.lax.Precision.HIGHEST,
            )                                                     # [CA,Dc]
            if flags.any_req_aff:
                total_a = jnp.sum(
                    jnp.where(in_key_a, dom_a, 0.0), axis=1
                )                                                 # [CA]
                feasible = (cnt_a > 0) | (
                    self_match_a[:, None] & (total_a[:, None] == 0)
                )
                feasible = feasible & has_key_aff
                ok_t = jnp.where(
                    pod.aff_anti[:, None], cnt_a == 0, feasible
                )
                aff_ok = aff_ok & jnp.all(
                    jnp.where(req_t[:, None], ok_t, True), axis=0
                )
            if flags.any_pref_aff:
                signed = jnp.where(
                    pod.aff_anti, -pod.aff_weight, pod.aff_weight
                )[:, None] * cnt_a
                raw_a = jnp.sum(
                    jnp.where(pref_t[:, None], signed, 0.0), axis=0
                )                                                 # [Dc]
                # the oracle's own normalization over valid classes
                ipa = jnp.where(
                    any_pref_active,
                    _minmax_normalize(raw_a, combo_valid),
                    0.0,
                )
        if flags.any_anti_sym:
            dom_t = base_dom_t + pod.own_anti[:, None] * jnp.einsum(
                "tdm,m->td", t_anti, y, precision=jax.lax.Precision.HIGHEST
            )                                                     # [AT,D]
            cnt_t = jnp.einsum(
                "td,tdm->tm", dom_t, t_anti,
                precision=jax.lax.Precision.HIGHEST,
            )                                                     # [AT,Dc]
            ok_t = (cnt_t == 0) | ~has_key_anti
            aff_ok = aff_ok & jnp.all(
                jnp.where(active_sym[:, None], ok_t, True), axis=0
            )

        # the full fold: ((partial8 + w_ipa*ipa) + w_sp*sp)
        total = (hs + w_ipa * ipa) + w_sp * sp
        if flags.any_hard_spread:
            spread_ok = _hard_spread_ok(
                dom, cnt_cm, st.in_key_cd, st.hard_c, pod.spread_skew,
                has_key_cm, fo[F_SPREAD],
            )
            total = jnp.where(spread_ok, total, -jnp.inf)
        if any_aff:
            total = jnp.where(
                aff_ok | ~fo[F_POD_AFFINITY], total, -jnp.inf
            )
        node_h = jnp.take_along_axis(hnode, hc, axis=1)[:, 0]
        j_h = jnp.take_along_axis(hj, hc, axis=1)[:, 0]
        mx_t = jnp.max(total)
        m = jnp.argmin(jnp.where(total == mx_t, node_h, N))
        ok = (mx_t > -jnp.inf) & (i < valid_count)
        node_out = jnp.where(ok, node_h[m], -1)
        j_out = jnp.where(ok, j_h[m], 0)
        oh = (jnp.arange(Dc) == m) & ok
        return (
            h + oh.astype(jnp.int32),
            y + oh.astype(jnp.float32),
        ), (node_out.astype(jnp.int32), j_out.astype(jnp.int32))

    if use_pallas:
        # The whole pop loop as one fused on-core kernel (VMEM head tables,
        # scratch state) — no per-iteration dispatch at all.
        nodes, jidxs = _domain_pop_pallas(
            hscore, hnode, hj, cap_eff, elig_combo, combo_valid, st,
            t_onehot, has_key_cm, pod.spread_skew, w_sp, fo[F_SPREAD],
            valid_count, group_size, flags.any_hard_spread, N,
        )
    else:
        # The step body is tiny ([Dc]-sized ops), so per-iteration dispatch
        # overhead dominates — unrolling amortizes it without changing the
        # op sequence (group_size is a multiple of 16: _bucket_light floors
        # at 32).
        _, (nodes, jidxs) = jax.lax.scan(
            step,
            (jnp.zeros(Dc, jnp.int32), jnp.zeros(Dc, jnp.float32)),
            jnp.arange(group_size),
            unroll=16,
        )
    sel_n = jnp.clip(nodes, 0, N - 1)
    x = jnp.zeros(N, jnp.int32).at[sel_n].add((nodes >= 0).astype(jnp.int32))
    return mono_ok, nodes, jidxs, x


def _pallas_requested() -> bool:
    """OSIM_PALLAS=1 routes the domain-select pop loop through the fused
    Pallas kernel (_domain_pop_pallas); 0/unset keeps the XLA scan. Off by
    default until the kernel is validated on the real TPU — the interpret
    path is exercised by tests on CPU either way."""
    return os.environ.get("OSIM_PALLAS", "0") == "1"


def _domain_pop_pallas(
    hscore, hnode, hj, cap_eff, elig_combo, combo_valid, st: SpreadTables,
    t_onehot, has_key_cm, skew, w_sp, fo_spread, valid_count, group_size,
    any_hard: bool, big_n: int,
):
    """The domain-merge pop loop as ONE Pallas kernel: head tables live in
    VMEM, the [Dc] state (head pointers, commit counts, current head
    score/node/lane) lives in scratch, and the whole sequential selection
    runs on-core — no per-iteration XLA dispatch at all. Arithmetic is the
    XLA scan body's, expression for expression (same f32 ops on the same
    values → bit-identical totals; the oracle-parity suite runs this kernel
    in interpret mode). Returns (nodes i32[G], jidx i32[G])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Dc, L = hscore.shape
    C, D, _ = t_onehot.shape
    inf = jnp.inf

    def kernel(
        iparams_ref, fparams_ref,
        hscore_ref, hnode_ref, hj_ref, cap_ref, elig_ref, cvalid_ref,
        base_dom_ref, t_ref, match_ref, soft_ref, hard_ref, skew_ref,
        haskey_ref, inkey_ref,
        nodes_ref, jidx_ref,
        h_ref, y_ref, hs_ref, nd_ref, jv_ref,
    ):
        cap0 = cap_ref[0, :]
        h_ref[0, :] = jnp.zeros((Dc,), jnp.int32)
        y_ref[0, :] = jnp.zeros((Dc,), jnp.float32)
        hs_ref[0, :] = jnp.where(cap0 > 0, hscore_ref[:, 0], -inf)
        nd_ref[0, :] = hnode_ref[:, 0]
        jv_ref[0, :] = hj_ref[:, 0]
        w_sp_s = fparams_ref[0, 0]
        valid_count_s = iparams_ref[0, 0]
        fo_spread_on = iparams_ref[0, 1] > 0
        bign = iparams_ref[0, 2]

        def body(i, _):
            y = y_ref[0, :]
            dom = base_dom_ref[:, :] + match_ref[0, :][:, None] * jnp.sum(
                t_ref[:, :, :] * y[None, None, :], axis=2
            )                                                     # [C,D]
            cnt = jnp.sum(dom[:, :, None] * t_ref[:, :, :], axis=1)  # [C,Dc]
            raw = jnp.sum(
                jnp.where(soft_ref[0, :][:, None] > 0, cnt, 0.0), axis=0
            )                                                     # [Dc]
            sp = _spread_norm(raw, cvalid_ref[0, :] > 0)
            total = hs_ref[0, :] + w_sp_s * sp
            if any_hard:
                spread_ok = _hard_spread_ok(
                    dom, cnt, inkey_ref[:, :] > 0, hard_ref[0, :] > 0,
                    skew_ref[0, :], haskey_ref[:, :] > 0, fo_spread_on,
                )
                total = jnp.where(spread_ok, total, -inf)
            mx_t = jnp.max(total)
            key = jnp.where(total == mx_t, nd_ref[0, :], bign)[None, :]
            m = jnp.argmin(key, axis=1)[0]
            ok = (mx_t > -inf) & (i < valid_count_s)
            nodes_ref[0, i] = jnp.where(ok, nd_ref[0, m], -1)
            jidx_ref[0, i] = jnp.where(ok, jv_ref[0, m], 0)

            @pl.when(ok)
            def _():
                nh = h_ref[0, m] + 1
                h_ref[0, m] = nh
                y_ref[0, m] = y_ref[0, m] + elig_ref[0, m]
                nhc = jnp.minimum(nh, L - 1)
                alive = nh < cap_ref[0, m]
                hs_ref[0, m] = jnp.where(alive, hscore_ref[m, nhc], -inf)
                nd_ref[0, m] = hnode_ref[m, nhc]
                jv_ref[0, m] = hj_ref[m, nhc]

            return 0

        jax.lax.fori_loop(0, group_size, body, 0)

    iparams = jnp.stack(
        [valid_count.astype(jnp.int32), fo_spread.astype(jnp.int32),
         jnp.int32(big_n)]
    )[None, :]
    fparams = jnp.stack([w_sp.astype(jnp.float32)])[None, :]
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    nodes, jidxs = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((1, group_size), jnp.int32),
            jax.ShapeDtypeStruct((1, group_size), jnp.int32),
        ),
        in_specs=[smem, smem] + [vmem] * 14,
        out_specs=(vmem, vmem),
        scratch_shapes=[
            pltpu.VMEM((1, Dc), jnp.int32),
            pltpu.VMEM((1, Dc), jnp.float32),
            pltpu.VMEM((1, Dc), jnp.float32),
            pltpu.VMEM((1, Dc), jnp.int32),
            pltpu.VMEM((1, Dc), jnp.int32),
        ],
        # Mosaic lowering exists only on TPU; everywhere else (CPU tests,
        # GPU installs) the interpreter runs the same kernel logic instead
        # of crashing at trace time.
        interpret=jax.default_backend() != "tpu",
    )(
        iparams, fparams, hscore, hnode, hj,
        cap_eff[None, :].astype(jnp.int32),
        elig_combo[None, :].astype(jnp.float32),
        combo_valid[None, :].astype(jnp.float32),
        st.base_dom, t_onehot.astype(jnp.float32),
        st.match_c[None, :], st.active_c[None, :].astype(jnp.float32),
        st.hard_c[None, :].astype(jnp.float32), skew[None, :],
        has_key_cm.astype(jnp.float32),
        (st.in_key_cd.astype(jnp.float32) if any_hard
         else jnp.zeros((C, D), jnp.float32)),
    )
    return nodes[0], jidxs[0]


@sanitizable("ops.fast:light_reasons", static_argnames=("flags",))
@functools.partial(jax.jit, static_argnames=("flags",))
def light_reasons(
    ns: NodeStatic,
    carry0: Carry,
    pod: PodRow,
    static_ok: jnp.ndarray,
    static_ff: jnp.ndarray,
    static_scores: dict,
    na_ok: jnp.ndarray,
    weights: jnp.ndarray,
    x: jnp.ndarray,
    cur: jnp.ndarray,
    filter_on=None,
    flags: GroupFlags = ALL_DYNAMIC,
) -> jnp.ndarray:
    """Failure-reason histogram i32[F] at state (x, cur) — evaluated once per
    group for its failure suffix (identical for every failed pod, because a
    failed step commits nothing). Matches the grouped path's per-step nested
    first-fail attribution exactly."""
    fo = jnp.ones(NUM_FILTERS, bool) if filter_on is None else filter_on
    _, p = _light_eval(
        ns, carry0, pod, static_ok, static_scores, na_ok, weights, fo, x, cur,
        flags, _hoisted_values(ns, cur, flags),
    )
    first_fail = jnp.where(
        static_ff < NUM_FILTERS,
        static_ff,
        jnp.where(
            ~p["port_ok"],
            F_NODE_PORTS,
            jnp.where(
                p["res_fail"],
                F_RESOURCES,
                jnp.where(
                    ~p["spread_ok"],
                    F_SPREAD,
                    jnp.where(
                        ~p["aff_ok"],
                        F_POD_AFFINITY,
                        jnp.where(
                            ~p["storage_ok"],
                            F_STORAGE,
                            jnp.where(~p["gpu_ok"], F_GPU, NUM_FILTERS),
                        ),
                    ),
                ),
            ),
        ),
    )
    return jnp.zeros(NUM_FILTERS, jnp.int32).at[
        jnp.clip(first_fail, 0, NUM_FILTERS - 1)
    ].add(jnp.where((first_fail < NUM_FILTERS) & ns.valid, 1, 0))


@sanitizable("ops.fast:gather_takes")
@jax.jit
def gather_takes(traj: Trajectory, nodes: jnp.ndarray, jidxs: jnp.ndarray):
    """Per-pod allocation takes from (chosen node, commit index) — one gather
    per group after all chunks finish."""
    N = traj.packed.shape[0]
    node_c = jnp.clip(nodes, 0, N - 1)
    placed = (nodes >= 0)[:, None]
    gpu_take = jnp.where(placed, traj.gpu_take[node_c, jidxs], 0.0)
    vg_take = jnp.where(placed, traj.vg_take[node_c, jidxs], 0.0)
    dev_take = jnp.where(placed, traj.dev_take[node_c, jidxs], 0.0)
    return gpu_take, vg_take, dev_take


@sanitizable("ops.fast:exit_carry")
@jax.jit
def exit_carry(
    ns: NodeStatic, carry0: Carry, pod: PodRow, traj: Trajectory, x: jnp.ndarray
) -> Carry:
    """Fold the group's commits (x per node) back into a Carry, bit-identical
    to the scan's iterative commits: node-local rows are gathered from the
    trajectory (capturing the scan's exact f32 subtraction sequence); the
    integer count tables are reconstructed as base + per-commit-add * x."""
    xf = x.astype(jnp.float32)
    oh = _x_onehot(x, traj.packed.shape[1])
    add_any, add_wild, add_ipc = port_adds(
        carry0.port_any.shape[0], carry0.port_ipc.shape[0], pod
    )
    return Carry(
        free=_sel_j(traj.free, oh),
        sel_counts=carry0.sel_counts
        + pod.match_sel.astype(jnp.float32)[:, None] * xf[None, :],
        gpu_free=_sel_j(traj.gpu_free, oh),
        vg_free=_sel_j(traj.vg_free, oh),
        dev_free=_sel_j(traj.dev_free, oh),
        port_any=carry0.port_any + add_any[:, None] * xf[None, :],
        port_wild=carry0.port_wild + add_wild[:, None] * xf[None, :],
        port_ipc=carry0.port_ipc + add_ipc[:, None] * xf[None, :],
        anti_counts=carry0.anti_counts + pod.own_anti[:, None] * xf[None, :],
    )


def _traj_len(
    free_np: np.ndarray, valid_np: np.ndarray, req_np: np.ndarray, length: int
):
    """Trajectory length needed for this group: the most commits any node can
    locally absorb (resource bound; every pod carries an implicit pods-slot
    request, so this is finite) + slack for f32 drift, capped by group size."""
    pos = req_np > 1e-9
    if not pos.any():
        return None
    caps = np.floor((free_np[:, pos] + _EPS) / req_np[pos]).min(axis=1)
    caps = np.clip(caps, 0.0, None)
    caps = caps[valid_np[: caps.shape[0]]]
    c_max = float(caps.max()) if caps.size else 0.0
    if not np.isfinite(c_max):
        return None
    return int(min(c_max + 2, length + 1))


def _bucket_j(j: int) -> int:
    return 1 << max(int(j) - 1, 7).bit_length()


def _bucket_light(n: int) -> int:
    """Chunk bucket for the light scan: light steps are cheap but not free,
    so pow2 buckets (up to 2x padding waste) hurt more than the few extra
    compiles of 2048-granular buckets."""
    if n <= 2048:
        return _bucket(n)
    return (n + 2047) // 2048 * 2048


def schedule_batch_fast(
    ns: NodeStatic,
    carry: Carry,
    batch: PodBatch,
    weights,
    max_group_chunk: int = DEFAULT_GROUP_CHUNK,
    force_fast: bool = False,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
) -> Tuple[Carry, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """schedule_batch semantics (bit-identical placements/reasons/takes/carry)
    with per-group trajectory acceleration; same returns as
    schedule_batch_grouped. Groups too small to amortize a trajectory (or with
    absurdly deep ones, J > J_CAP) take the grouped per-pod scan instead.
    `force_fast` disables the amortization heuristic (tests). Out-of-tree
    plugins (extra_filters/extra_scores) may read the carry arbitrarily,
    which voids the trajectory's node-local-evolution premise — any plugin
    routes the whole batch through the grouped path."""
    P = batch.p
    G = ns.gpu_total.shape[1]
    V = ns.vg_cap.shape[1]
    DV = ns.dev_cap.shape[1]
    nodes_out = np.full(P, -1, np.int32)
    reasons_out = np.zeros((P, NUM_FILTERS), np.int32)
    take_out = np.zeros((P, G), np.int32)
    vg_out = np.zeros((P, V), np.float32)
    dev_out = np.zeros((P, DV), np.float32)
    rows_all = pod_rows_from_batch(batch)
    N = ns.valid.shape[0]
    valid_np = np.asarray(ns.valid)

    # A disabled NodeResourcesFit filter voids the trajectory-length bound
    # (the resource filter is what stops a node's commit count at c_max, see
    # _traj_len) — those profiles take the per-pod grouped path.
    res_filter_on = (
        filter_on is None or bool(np.asarray(filter_on)[F_RESOURCES])
    ) and not extra_filters and not extra_scores
    # One device->host sync for ALL groups' trajectory lengths: free only
    # shrinks while a batch schedules (no evictions mid-batch), so per-node
    # commit caps computed from the batch-entry free are safe upper bounds
    # for every later group.
    free_entry = np.asarray(carry.free) if res_filter_on else None
    anti_topo_np = np.asarray(ns.anti_topo)
    topo_np = np.asarray(ns.topo)
    n_domains = int(ns.topo_onehot.shape[1])

    for start, length in group_runs(batch):
        row = jax.tree.map(lambda a: a[start], rows_all)
        flags = group_flags(
            {
                "hp_pid": batch.hp_pid[start],
                "has_local": batch.has_local[start],
                "gpu_mem": batch.gpu_mem[start],
                "spread_topo": batch.spread_topo[start],
                "spread_hard": batch.spread_hard[start],
                "aff_topo": batch.aff_topo[start],
                "aff_required": batch.aff_required[start],
                "match_anti": batch.match_anti[start],
            },
            anti_topo_np,
        )
        j_need = (
            _traj_len(free_entry, valid_np, batch.req[start], length)
            if free_entry is not None and (force_fast or length >= 64)
            else None
        )
        # Break-even: fast cost ≈ j_need heavy trajectory steps + length
        # cheap selection steps (sort ≈ free, scan ≈ heavy/8), vs length
        # heavy steps on the grouped path — fast wins from ~1.2x j_need;
        # 1.5x keeps margin for the fixed exit/gather overhead.
        use_fast = (
            j_need is not None
            and _bucket_j(j_need) <= J_CAP
            and (force_fast or length >= max(3 * j_need // 2, 64))
        )
        if not use_fast:
            _count_path("grouped")
            done = 0
            while done < length:
                n = min(length - done, max_group_chunk)
                g = _bucket(n)
                _progress(f"group@{start} len={length} grouped chunk g={g}")
                carry, (nodes, reasons, take, vg_take, dev_take) = _group_call(
                    ns, carry, row, g, jnp.int32(n), weights, filter_on,
                    extra_filters, extra_scores,
                )
                sl = slice(start + done, start + done + n)
                nodes_np, reasons_np, take_np, vg_np, dev_np = jax.device_get(
                    (nodes, reasons, take, vg_take, dev_take)
                )
                nodes_out[sl] = nodes_np[:n]
                reasons_out[sl] = reasons_np[:n]
                take_out[sl] = take_np[:n]
                vg_out[sl] = vg_np[:n]
                dev_out[sl] = dev_np[:n]
                done += n
            continue

        j_steps = _bucket_j(j_need)
        _progress(f"group@{start} len={length} traj j={j_steps} N={N}")
        traj, static_ok, static_ff, static_scores, na_ok = build_trajectory(
            ns, carry, row, weights, j_steps, filter_on
        )
        sl = slice(start, start + length)

        def finish(nodes_dev, jidx_dev, x_dev, mono_dev=None):
            """Dispatch the group's whole tail (takes, failure-suffix reason
            row, exit carry) and fetch every host-needed value in ONE
            device_get — each host sync pays a full tunnel round trip, so
            the reason row is computed speculatively (one cheap kernel
            instead of a second sync when failures exist) and the sort/
            domain mono verdict rides the same fetch (on False the caller
            discards everything fetched and replays with a scan)."""
            take_dev, vg_dev, dev_dev = gather_takes(traj, nodes_dev, jidx_dev)
            reason_dev = light_reasons(
                ns, carry, row, static_ok, static_ff, static_scores,
                na_ok, weights, x_dev, cur_at(traj, x_dev), filter_on, flags,
            )
            carry_dev = exit_carry(ns, carry, row, traj, x_dev)
            _progress(f"group@{start} finish sync")
            mono_np, *got = jax.device_get(
                (jnp.bool_(True) if mono_dev is None else mono_dev,
                 nodes_dev, take_dev, vg_dev, dev_dev, reason_dev)
            )
            return bool(mono_np), tuple(got), carry_dev

        def commit(got, carry_dev):
            nonlocal carry
            nodes_np, take_np, vg_np, dev_np, reason_np = got
            nodes_out[sl] = nodes_np
            take_out[sl] = take_np.astype(np.int32)
            vg_out[sl] = vg_np
            dev_out[sl] = dev_np
            if (nodes_np < 0).any():
                # A failed step commits nothing, so the whole failure suffix
                # of the group shares one state — one reason row covers it.
                reasons_out[sl][nodes_np < 0] = reason_np
            carry = carry_dev

        committed = False

        # Sort path: whole group in one device call when scores are purely
        # node-local and per-node non-increasing (checked on device; the
        # check's verdict is fetched together with the speculated tail).
        out_size = _bucket_light(length)
        if _sortable(flags) and out_size <= N * j_steps:
            _progress(f"group@{start} sort out={out_size}")
            mono, nodes_d, jidx_d, x = sort_select(
                ns, traj, row, static_ok, static_scores, weights,
                jnp.int32(length), out_size, filter_on,
            )
            mono_ok, got, carry_dev = finish(
                nodes_d[:length], jidx_d[:length], x, mono
            )
            if mono_ok:
                _count_path("sort")
                commit(got, carry_dev)
                committed = True
            else:
                # a balanced-allocation rise broke monotonicity — the merge
                # argument doesn't hold, replay with the scan below
                _count_path("sort_fallback")

        if not committed and flags.domain_aff:
            # Domain-merge path: O(Dc) scan state instead of O(N). The class
            # partition needs the pod's spread eligibility on host (one small
            # bool[N] transfer per group).
            # deliberate bool[N] fetch: the domain partition is planned on host
            elig_np = np.asarray(na_ok) & valid_np  # osim: lint-ok[device-sync-in-loop]
            plan = _domain_plan(
                batch.spread_topo[start], batch.aff_topo[start],
                anti_topo_np, batch.match_anti[start], topo_np, valid_np,
                elig_np, j_steps, n_domains,
            )
            if plan is not None:
                g = _bucket_light(length)
                l_cap = _bucket_light(min(int(plan.counts.max()), length))
                _progress(f"group@{start} domain g={g} l_cap={l_cap}")
                # the Pallas kernel implements the spread-only step body
                use_pallas = _pallas_requested() and not (
                    flags.any_req_aff or flags.any_pref_aff
                    or flags.any_anti_sym
                )
                mono, nodes_w, jidx_w, x_w = domain_select(
                    ns, traj, carry, row, static_ok, static_scores, na_ok,
                    weights, plan.combo_of_node, plan.counts, plan.offsets,
                    plan.elig_combo, plan.combo_valid, plan.t_onehot,
                    plan.has_key, plan.t_aff, plan.has_key_aff, plan.t_anti,
                    plan.has_key_anti, g, l_cap, jnp.int32(length),
                    filter_on, flags, use_pallas,
                )
                mono_ok, got, carry_dev = finish(
                    nodes_w[:length], jidx_w[:length], x_w, mono
                )
                if mono_ok:
                    _count_path("domain")
                    _count_path("domain_pallas", int(use_pallas))
                    commit(got, carry_dev)
                    committed = True
                else:
                    # a rising lane sequence voids the within-class merge
                    # argument — replay with the micro scan
                    _count_path("domain_fallback")

        if not committed:
            _count_path("micro" if flags.micro_spread else "scan")
            x = jnp.zeros(N, jnp.int32)
            chunks = []
            done = 0
            while done < length:
                n = min(length - done, max_group_chunk)
                g = _bucket_light(n)
                _progress(f"group@{start} light-scan chunk g={g} done={done}")
                x, nodes, jidxs = light_scan(
                    ns, traj, carry, row, static_ok, static_scores,
                    na_ok, weights, x, jnp.int32(done), g,
                    jnp.int32(length), filter_on, flags,
                )
                chunks.append((n, nodes, jidxs))
                done += n
            # One transfer per group (per-chunk np.asarray syncs dominated
            # the host-side cost at TPU-tunnel latencies).
            nodes_d = jnp.concatenate([c[1][: c[0]] for c in chunks])
            jidx_d = jnp.concatenate([c[2][: c[0]] for c in chunks])
            _, got, carry_dev = finish(nodes_d, jidx_d, x)
            commit(got, carry_dev)

    return carry, nodes_out, reasons_out, take_out, vg_out, dev_out


# ---------------------------------------------------------------------------
# Scenario axis: vmap the whole scan (ROADMAP item 1)
# ---------------------------------------------------------------------------

# Scenario-count bucket: the leading axis of every batched call is padded to a
# multiple of this (pad scenarios are copies of scenario 0, results discarded)
# so a sweep whose scenario count wobbles between calls still reuses one
# compiled program per (node, pod) shape.
SCENARIO_BUCKET = 8


def scenario_bucket(s: int, floor: int = 0) -> int:
    """Padded scenario count for `s` real lanes. `floor` (itself a padded
    count) keeps a warm shape warm across consecutive serving packs: a
    3-lane pack following an 8-lane pack pads back to 8 and reuses the
    compiled program instead of tracing a 8-vs-smaller shape pair (the
    continuous-batching loop passes the previous pack's pad here)."""
    return round_up(max(int(s), 1, int(floor)), SCENARIO_BUCKET)


# (N, P) shape key -> set of padded scenario counts seen: each distinct entry
# in a value set is one compiled program for that bucket. The recompile guard
# (analysis/jaxpr_audit.py) asserts every bucket stays at <= 2 programs across
# a whole capacity sweep.
_SCENARIO_PROGRAMS: dict = {}


def scenario_programs() -> dict:
    """Snapshot of {(n_nodes, n_pods): {padded scenario counts}} traced so far
    through schedule_scenarios_host."""
    return {k: set(v) for k, v in _SCENARIO_PROGRAMS.items()}


def reset_scenario_programs() -> None:
    # reachable from a watchdog-guarded driver callable, but guarded_call's
    # supervising thread parks in done.wait() until the worker finishes —
    # the callable has the drivers' shared state to itself (benches and
    # warmup call this between runs, never concurrently with a sweep)
    _SCENARIO_PROGRAMS.clear()  # osim: audit-ok[race]


@sanitizable("ops.fast:schedule_scenarios", donate_argnums=(1,))
@functools.partial(jax.jit, donate_argnums=(1,))
def schedule_scenarios(
    ns: NodeStatic,
    carry_s: Carry,
    pods: PodRow,
    weights_s: jnp.ndarray,
    valid_s: jnp.ndarray,
    filter_on=None,
):
    """The naive commit scan under jax.vmap over a leading scenario axis.

    Scenarios share one padded node tensor (`ns`) and one pod sequence
    (`pods`, broadcast); what varies per scenario is the node-valid mask
    `valid_s` bool[S,N], the carry (every Carry leaf stacked on axis 0) and
    the score-weight vector `weights_s` f32[S,W].

    Exactness: every filter ANDs with ns.valid, reason counts gate on
    ns.valid, score normalization masks by it, and _domain_counts
    eligibility-masks its counts — so a row that is encoded-real but
    valid=False for a scenario is fully inert, and lane s is bit-identical
    to a serial schedule_batch over a table whose valid mask is valid_s[s].
    Vmapping the NAIVE scan (not the host-driven fast paths) keeps the whole
    sweep a single device dispatch; the fast paths prove bit-identity to
    this same scan, so per-scenario results match serial simulate() output.

    Returns (carry_s, nodes i32[S,P], reasons i32[S,P,F], gpu_take i32[S,P,G],
    vg_take f32[S,P,V], dev_take f32[S,P,DV]).
    """

    def one(valid, carry, weights):
        ns_s = ns._replace(valid=valid)

        def step(c, pod):
            return schedule_step(ns_s, weights, c, pod, filter_on)

        final, (nodes, reasons, gpu_take, vg_take, dev_take) = jax.lax.scan(
            step, carry, pods
        )
        return final, nodes, reasons, gpu_take, vg_take, dev_take

    return jax.vmap(one)(valid_s, carry_s, weights_s)


# ---------------------------------------------------------------------------
# Chunked commit driver: preemption-safe execution (docs/durability.md)
# ---------------------------------------------------------------------------

def commit_chunk_size() -> int:
    """Pods per chunk for the chunked commit driver (`OSIM_COMMIT_CHUNK`).
    0 (the default) keeps the monolithic single-scan dispatch. Any positive
    value splits the per-pod commit scan into an outer host loop of
    fixed-size chunks so a long plan can checkpoint between chunks — the
    chunk size is the rung: every chunk call compiles ONE program per
    (node-bucket, chunk) pair regardless of total pod count."""
    try:
        return max(0, int(os.environ.get("OSIM_COMMIT_CHUNK", "0") or 0))
    except ValueError:
        return 0


def scenario_carry_digest(carry_s: Carry) -> int:
    """Digest of a (stacked) carry: per-leaf device `digest_fold` reductions
    chained in Carry._fields order. Only S 4-byte scalars transfer; the
    result is bit-identical to `scenario_carry_digest_host` over the
    device_get of the same carry (delta.digest_fold_host is the numpy twin),
    which is what lets a resumed process verify a snapshot without a
    device round-trip."""
    parts = [_delta.digest_fold(getattr(carry_s, f)) for f in Carry._fields]
    return _delta.combine_digests(int(jax.device_get(p)) for p in parts)


def scenario_carry_digest_host(leaves: dict) -> int:
    """Host twin of scenario_carry_digest over {field: np.ndarray} leaves."""
    return _delta.combine_digests(
        _delta.digest_fold_host(np.asarray(leaves[f])) for f in Carry._fields
    )


def carry_to_host(carry_s: Carry) -> dict:
    """device_get every Carry leaf -> {field: np.ndarray} (snapshot form)."""
    got = jax.device_get(carry_s)
    return {f: np.asarray(getattr(got, f)) for f in Carry._fields}


def carry_from_host(carry_s: Carry, leaves: dict) -> Carry:
    """Re-pin host snapshot leaves onto the CURRENT carry's shardings.

    `carry_s` is whatever the resumed (or recovering) process built for the
    mesh it has NOW — its values are discarded; only its per-leaf
    NamedShardings are kept. This is the elastic-resume step: a snapshot
    taken on a 4-device mesh lands on 2 devices or plain CPU by being
    device_put against the new layout, and the commit arithmetic is
    sharding-independent (PR 14's digest-identical lanes), so the resumed
    plan stays byte-identical."""
    for f in Carry._fields:
        cur = getattr(carry_s, f)
        want = tuple(cur.shape)
        have = tuple(np.asarray(leaves[f]).shape)
        if want != have:
            raise ValueError(
                f"carry snapshot leaf {f!r} has shape {have}, current plan "
                f"expects {want} — snapshot is from a different plan shape"
            )
    return Carry(*(
        jax.device_put(
            np.asarray(leaves[f]), getattr(carry_s, f).sharding
        )
        for f in Carry._fields
    ))


@sanitizable("ops.fast:schedule_scenarios_chunked", donate_argnums=(1,))
@functools.partial(jax.jit, donate_argnums=(1,))
def schedule_scenarios_chunked(
    ns: NodeStatic,
    carry_s: Carry,
    pods: PodRow,
    weights_s: jnp.ndarray,
    valid_s: jnp.ndarray,
    count: jnp.ndarray,
    filter_on=None,
):
    """One fixed-size chunk of the scenario commit scan, count-gated.

    Per-step arithmetic is exactly schedule_scenarios' (the same
    schedule_step under the same vmap); the only addition is the `count`
    gate: step i with i >= count is a pad step whose carry writes are
    masked out leaf-by-leaf (`jnp.where` on every Carry leaf — for live
    steps the where selects the new value bitwise, so real steps are
    untouched). Chaining ceil(P/C) chunk calls over a pod sequence padded
    to a multiple of C therefore yields a final carry and (host-trimmed)
    outputs byte-identical to the single monolithic scan — the property
    tests/test_checkpoint.py asserts by digest across seeds.

    Pad-step OUTPUTS are garbage by design: pads only ever trail the last
    chunk, and the host driver trims them before concatenating. `count` is
    a traced i32 scalar so the partial last chunk reuses the full chunk's
    compiled program (one program per (N, C) shape, rung-disciplined).
    `carry_s` is donated, exactly like schedule_scenarios."""
    p_chunk = jax.tree_util.tree_leaves(pods)[0].shape[0]
    idx = jnp.arange(p_chunk, dtype=jnp.int32)

    def one(valid, carry, weights):
        ns_s = ns._replace(valid=valid)

        def step(c, xs):
            i, pod = xs
            c2, out = schedule_step(ns_s, weights, c, pod, filter_on)
            live = i < count
            c2 = jax.tree_util.tree_map(
                lambda new, old: jnp.where(live, new, old), c2, c
            )
            return c2, out

        final, (nodes, reasons, gpu_take, vg_take, dev_take) = jax.lax.scan(
            step, carry, (idx, pods)
        )
        return final, nodes, reasons, gpu_take, vg_take, dev_take

    return jax.vmap(one)(valid_s, carry_s, weights_s)


@sanitizable("ops.fast:schedule_universes", donate_argnums=(1,))
@functools.partial(jax.jit, donate_argnums=(1,))
def schedule_universes(
    ns_s: NodeStatic,
    carry_s: Carry,
    pods_s: PodRow,
    weights_s: jnp.ndarray,
    filter_on=None,
):
    """Exhaustive-checking axis: vmap the naive commit scan over universes
    where EVERYTHING varies per lane — node tensors, carry, pod sequence and
    weights (every NodeStatic/Carry/PodRow leaf stacked on axis 0, scalars
    widened to [S]).

    schedule_scenarios varies only (valid, carry, weights) around one shared
    cluster; `simon prove` (analysis/semantics.py) needs whole distinct
    *universes* per lane — different node capacities, labels, taints, pod
    requests, selectors — packed from a small catalog by stamped gather. The
    body is the same naive scan that every fast path proves bit-identity to,
    so lane u reproduces exactly what a serial schedule_batch over universe
    u's table would commit.

    Returns (carry_s, nodes i32[S,P], reasons i32[S,P,F], gpu_take i32[S,P,G],
    vg_take f32[S,P,V], dev_take f32[S,P,DV]).
    """

    def one(ns, carry, pods, weights):
        def step(c, pod):
            return schedule_step(ns, weights, c, pod, filter_on)

        final, (nodes, reasons, gpu_take, vg_take, dev_take) = jax.lax.scan(
            step, carry, pods
        )
        return final, nodes, reasons, gpu_take, vg_take, dev_take

    return jax.vmap(one)(ns_s, carry_s, pods_s, weights_s)


# ---------------------------------------------------------------------------
# Conflict-parallel wave commit (ops/wave.py; ROADMAP item 1)
# ---------------------------------------------------------------------------

@sanitizable("ops.fast:schedule_wave")
@jax.jit
def schedule_wave(
    ns: NodeStatic,
    carry_s: Carry,
    pods: PodRow,
    weights_s: jnp.ndarray,
    valid_s: jnp.ndarray,
    choices_s: jnp.ndarray,
    count: jnp.ndarray,
    filter_on=None,
):
    """One conflict-parallel commit round over a wave of W pods, vmapped
    over scenario lanes (schedule_scenarios' axis discipline: shared `ns`
    and pod wave, per-lane valid/carry/weights — plus the per-lane round
    state `choices_s` i32[S,W]).

    The round body is ops/wave.py's Jacobi step: replay the previous
    round's choices through the exact commit arithmetic (cheap scan,
    count-gated like schedule_scenarios_chunked), then re-decide all W
    pods at their own prefix carries in one data-parallel probe — the
    heavy ~dozen-plugin sweep runs W-wide instead of once per scan step.
    On the converged round (returned choices == `choices_s`) every output
    is byte-identical to the serial scan over the same wave.

    `carry_s` is NOT donated: the wave-input carry is re-read by every
    round until the host driver observes the fixpoint and adopts the exit
    carry. Returns (carry_s, choices i32[S,W], reasons i32[S,W,F],
    gpu_take i32[S,W,G], vg_take f32[S,W,V], dev_take f32[S,W,DV]).
    """

    def one(valid, carry, weights, choices):
        return _wave.wave_round(
            ns._replace(valid=valid), weights, carry, pods, choices,
            count, filter_on,
        )

    return jax.vmap(one)(valid_s, carry_s, weights_s, choices_s)


@sanitizable("ops.fast:schedule_universes_wave")
@jax.jit
def schedule_universes_wave(
    ns_s: NodeStatic,
    carry_s: Carry,
    pods_s: PodRow,
    weights_s: jnp.ndarray,
    choices_s: jnp.ndarray,
    filter_on=None,
):
    """schedule_universes' axis (EVERY leaf stacked per lane) under the
    wave round body: one Jacobi round for S whole universes at once, the
    whole pod sequence as a single wave. `simon prove --engine wave`
    drives this to a fixpoint per chunk and must reproduce the banked
    placement digest bit-for-bit — the reordered engine's admission
    proof. No count gate (every presented pod row is live) and no carry
    donation (rounds re-read the chunk-input carry)."""

    def one(ns, carry, pods, weights, choices):
        return _wave.wave_round(
            ns, weights, carry, pods, choices, None, filter_on
        )

    return jax.vmap(one)(ns_s, carry_s, pods_s, weights_s, choices_s)


@sanitizable("ops.fast:commit_choices")
@jax.jit
def commit_choices(
    ns: NodeStatic,
    carry_s: Carry,
    pods: PodRow,
    valid_s: jnp.ndarray,
    choices_s: jnp.ndarray,
    count: jnp.ndarray,
):
    """The wave engine's COMMIT PHASE in isolation: replay decided
    choices (i32[S,W], -1 = no commit) through `kernels.commit_choice` —
    the row-wise O(row) commit — with no probe and no prefix-carry
    stacking. This is the only part of the wave engine that is
    inherently sequential (each commit reads the previous commit's
    carry), so its wall time is the engine's sequential depth; the
    `wave_commit_10k` bench gates it at ≥10× faster than the serial
    decide+commit scan. Byte-identical to replaying the same choices
    through the serial scan (see commit_choice's bit-identity note).

    Returns (carry_s, gpu_take i32[S,W,G], vg_take f32[S,W,V],
    dev_take f32[S,W,DV]). `carry_s` is not donated (callers may retry
    a wave after a fault injection)."""
    w = int(jax.tree_util.tree_leaves(pods)[0].shape[0])
    idx = jnp.arange(w, dtype=jnp.int32)

    def one(valid, carry, choices):
        ns_l = ns._replace(valid=valid)
        gated = jnp.where(idx < count, choices, jnp.int32(-1))

        def step(c, xs):
            pod, choice = xs
            c2, gpu_take, vg_take, dev_take = commit_choice(
                ns_l, c, pod, choice
            )
            return c2, (gpu_take.astype(jnp.int32), vg_take, dev_take)

        final, takes = jax.lax.scan(step, carry, (pods, gated))
        return (final,) + takes

    return jax.vmap(one)(valid_s, carry_s, choices_s)


def schedule_universes_wave_host(
    ns_s: NodeStatic,
    carry_s: Carry,
    pods_s: PodRow,
    weights_s: jnp.ndarray,
    filter_on=None,
):
    """Drive schedule_universes_wave to its fixpoint: same signature and
    return tuple as schedule_universes (which donates its carry; this
    driver instead keeps the input carry alive across rounds and returns
    the converged round's exit carry). Guaranteed to converge within W+1
    rounds (ops/wave.py); the impossible-overrun guard falls back to the
    serial oracle rather than looping."""
    s_pad = int(jax.tree_util.tree_leaves(carry_s)[0].shape[0])
    p_pad = int(jax.tree_util.tree_leaves(pods_s)[0].shape[1])
    choices = jnp.full((s_pad, p_pad), -1, jnp.int32)
    prev = np.full((s_pad, p_pad), -1, np.int32)
    rounds = 0
    while True:
        rounds += 1
        _progress(f"universes-wave S={s_pad} P={p_pad} round {rounds}")
        carry_w, choices_new, reasons, gpu_take, vg_take, dev_take = (
            schedule_universes_wave(
                ns_s, carry_s, pods_s, weights_s, choices, filter_on
            )
        )
        ch = np.asarray(jax.device_get(choices_new))
        if np.array_equal(ch, prev):
            break
        if rounds > 1:
            _metrics.WAVE_CONFLICTS.inc(int((ch != prev).sum()))
        if rounds > p_pad + 1:
            _metrics.WAVE_FALLBACKS.inc(reason="universes_max_rounds")
            return schedule_universes(
                ns_s, carry_s, pods_s, weights_s, filter_on
            )
        choices, prev = choices_new, ch
    _metrics.COMMIT_ROUNDS.observe(rounds)
    return carry_w, choices_new, reasons, gpu_take, vg_take, dev_take


def schedule_scenarios_host(
    ns: NodeStatic,
    carry_s: Carry,
    batch: PodBatch,
    weights_s: jnp.ndarray,
    valid_s: jnp.ndarray,
    s_real: int,
    filter_on=None,
):
    """Host driver for one batched call: dispatches schedule_scenarios and
    returns (carry_s, nodes, reasons, gpu_take, vg_take, dev_take) with the
    numpy outputs trimmed to the `s_real` live scenarios. `carry_s` /
    `weights_s` / `valid_s` must already be padded to scenario_bucket(s_real)
    (pad lanes = copies of scenario 0); the returned carry keeps the padded
    axis so it threads straight into the next call.

    The input `carry_s` is CONSUMED: schedule_scenarios donates it (the
    stacked carry is the big resident tensor of a sweep, and XLA reuses its
    buffers for the output carry). Callers must rebind — the stacked carry
    from ops.state.stack_carry is freshly materialized per sweep, so the
    simulator's own serial carry is never at risk.

    With OSIM_COMMIT_CHUNK > 0 (and more pods than one chunk) the dispatch
    is the chunked commit driver instead: ceil(P/C) count-gated
    schedule_scenarios_chunked calls whose chained result is byte-identical
    to the single scan, with a checkpoint hook between chunks
    (durable/checkpoint.py) and device-fault recovery — see
    docs/durability.md.

    With the wave engine enabled (OSIM_WAVE_COMMIT / auto above
    ops.wave.WAVE_AUTO_MIN_PODS pods) the dispatch is the
    conflict-parallel wave driver instead — byte-identical to the serial
    scan by fixpoint construction (docs/performance.md), checkpointing
    one wave per `plan_chunk` record with the same digest chain a serial
    chunked run of chunk = wave size would journal."""
    rows = pod_rows_from_batch(batch)
    s_pad = int(valid_s.shape[0])
    key = (int(ns.valid.shape[0]), int(batch.p))
    _SCENARIO_PROGRAMS.setdefault(key, set()).add(s_pad)
    _metrics.SCENARIOS_PER_CALL.observe(s_real)
    if _wave.wave_enabled(int(batch.p)):
        return _schedule_scenarios_wave_host(
            ns, carry_s, rows, weights_s, valid_s, s_real, s_pad,
            int(batch.p), _wave.wave_size(), filter_on,
        )
    chunk = commit_chunk_size()
    if chunk and int(batch.p) > chunk:
        return _schedule_scenarios_chunked_host(
            ns, carry_s, rows, weights_s, valid_s, s_real, s_pad,
            int(batch.p), chunk, filter_on,
        )
    _progress(
        f"scenarios S={s_real}/{s_pad} P={batch.p} N={ns.valid.shape[0]}"
    )
    carry_s, nodes, reasons, gpu_take, vg_take, dev_take = schedule_scenarios(
        ns, carry_s, rows, weights_s, valid_s, filter_on
    )
    got = jax.device_get((nodes, reasons, gpu_take, vg_take, dev_take))
    return (carry_s,) + tuple(np.asarray(a)[:s_real] for a in got)


def _schedule_scenarios_chunked_host(
    ns: NodeStatic,
    carry_s: Carry,
    rows: PodRow,
    weights_s: jnp.ndarray,
    valid_s: jnp.ndarray,
    s_real: int,
    s_pad: int,
    p_real: int,
    chunk: int,
    filter_on=None,
):
    """The outer host loop of the chunked commit driver.

    Per chunk: optional device-fault injection, one
    schedule_scenarios_chunked dispatch, host transfer of the chunk's
    outputs, then the checkpoint hook (journal `plan_chunk` + periodic
    atomic carry snapshot, durable/checkpoint.py). On resume the active
    checkpointer hands back a verified snapshot: the loop re-pins its carry
    onto the current mesh (carry_from_host), counts the covered chunks as
    skipped, and re-executes only the journal tail — cross-checking every
    re-executed chunk's digest against the journaled one. A DeviceLostError
    from the fault plane rolls back to the last good in-memory snapshot and
    replays (degraded, not failed) until the strike budget runs out."""
    from ..durable import checkpoint as _checkpoint
    from ..resilience import faults as _faults
    from ..utils import flightrec as _flightrec

    N = int(ns.valid.shape[0])
    n_chunks = -(-p_real // chunk)
    p_pad = n_chunks * chunk
    if p_pad != p_real:
        rows = jax.tree_util.tree_map(
            lambda a: jnp.concatenate(
                [a, jnp.broadcast_to(a[:1], (p_pad - p_real,) + a.shape[1:])]
            ),
            rows,
        )
    _SCENARIO_PROGRAMS.setdefault((N, chunk), set()).add(s_pad)

    cp = _checkpoint.active_checkpointer()
    plan = None
    start_chunk = 0
    outs: list = []  # host (nodes, reasons, gpu, vg, dev) tuples, in order
    if cp is not None:
        plan = cp.begin_plan(
            n_nodes=N, p_real=p_real, s_pad=s_pad, chunk=chunk,
            n_chunks=n_chunks,
        )
        restore = plan.restore
        if restore is not None:
            start_chunk = restore.chunks_done
            carry_s = carry_from_host(carry_s, restore.carry)
            outs.append(restore.outputs)
            _metrics.RESUME_CHUNKS_SKIPPED.inc(start_chunk)
            _flightrec.note(
                "plan-restore", plan=plan.key, chunk=start_chunk - 1,
                digest=f"{restore.digest:08x}",
            )
            _flightrec.dump("chunk-restore", run_dir=cp.run_dir)

    # Device-loss recovery needs a host-resident rollback point; pay for it
    # only when a checkpointer is active or a device fault can actually fire.
    track = cp is not None or _faults.has_rules("device")
    last_good = None  # (chunk_idx, host carry leaves, len(outs), digest)
    if track:
        host0 = carry_to_host(carry_s)
        last_good = (
            start_chunk, host0, len(outs), scenario_carry_digest_host(host0),
        )
    strikes = 0

    i = start_chunk
    while i < n_chunks:
        rule = _faults.maybe_inject("device", f"commit-chunk:{i}")
        if rule is not None:
            try:
                _faults.apply_device_fault(rule)
            except _faults.DeviceLostError:
                strikes += 1
                if last_good is None or strikes >= 3:
                    _metrics.DEVICE_LOST.inc(handled="no")
                    raise
                _metrics.DEVICE_LOST.inc(handled="yes")
                g_chunk, g_carry, g_outs, g_digest = last_good
                _flightrec.note(
                    "device-lost", chunk=i, restored_to=g_chunk,
                    digest=f"{g_digest:08x}",
                )
                _flightrec.dump(
                    "device-lost",
                    run_dir=cp.run_dir if cp is not None else None,
                )
                carry_s = carry_from_host(carry_s, g_carry)
                del outs[g_outs:]
                i = g_chunk
                continue
        lo = i * chunk
        count = min(chunk, p_real - lo)
        _progress(
            f"scenarios S={s_real}/{s_pad} N={N} "
            f"chunk {i + 1}/{n_chunks} (C={chunk}, live={count})"
        )
        rows_c = jax.tree_util.tree_map(lambda a: a[lo:lo + chunk], rows)
        carry_s, nodes, reasons, gpu_take, vg_take, dev_take = (
            schedule_scenarios_chunked(
                ns, carry_s, rows_c, weights_s, valid_s,
                jnp.int32(count), filter_on,
            )
        )
        got = jax.device_get((nodes, reasons, gpu_take, vg_take, dev_take))
        outs.append(tuple(np.asarray(a)[:, :count] for a in got))
        _metrics.PLAN_CHUNKS.inc()
        if cp is not None:
            digest = scenario_carry_digest(carry_s)
            hostc = cp.on_chunk(plan, i, lo + count, digest, carry_s, outs)
            if hostc is not None:
                last_good = (i + 1, hostc, len(outs), digest)
        i += 1

    if cp is not None:
        cp.finish_plan(plan, scenario_carry_digest(carry_s))
    cat = tuple(
        np.concatenate([o[k] for o in outs], axis=1) for k in range(5)
    )
    return (carry_s,) + tuple(a[:s_real] for a in cat)


def _schedule_scenarios_wave_host(
    ns: NodeStatic,
    carry_s: Carry,
    rows: PodRow,
    weights_s: jnp.ndarray,
    valid_s: jnp.ndarray,
    s_real: int,
    s_pad: int,
    p_real: int,
    wave: int,
    filter_on=None,
):
    """The outer host loop of the conflict-parallel wave commit driver.

    Structure is _schedule_scenarios_chunked_host's with one wave per
    chunk slot: per wave, iterate schedule_wave rounds until the probe
    reproduces its own input choices (the fixpoint — byte-identical to
    the serial scan, ops/wave.py), then adopt that round's exit carry
    and outputs. A wave that exhausts OSIM_WAVE_ROUNDS is re-run through
    the serial chunked kernel (the oracle path; counted in
    osim_wave_fallbacks_total) so the driver is never slower than
    serial + the round budget, and never wrong.

    Durability and fault handling are inherited wholesale: one
    `plan_chunk` journal record per committed wave with the same
    scenario-carry digest chain a serial chunked run (C = wave) would
    write — so a wave plan resumes from a serial run's snapshot and vice
    versa — and device-loss rolls back to the last good committed wave
    (in-flight rounds are discarded; rounds mutate nothing until the
    fixpoint is adopted)."""
    from ..durable import checkpoint as _checkpoint
    from ..resilience import faults as _faults
    from ..utils import flightrec as _flightrec

    N = int(ns.valid.shape[0])
    n_waves = -(-p_real // wave)
    p_pad = n_waves * wave
    if p_pad != p_real:
        rows = jax.tree_util.tree_map(
            lambda a: jnp.concatenate(
                [a, jnp.broadcast_to(a[:1], (p_pad - p_real,) + a.shape[1:])]
            ),
            rows,
        )
    _SCENARIO_PROGRAMS.setdefault((N, wave), set()).add(s_pad)
    max_rounds = _wave.wave_max_rounds()

    cp = _checkpoint.active_checkpointer()
    plan = None
    start_wave = 0
    outs: list = []  # host (nodes, reasons, gpu, vg, dev) tuples, in order
    if cp is not None:
        plan = cp.begin_plan(
            n_nodes=N, p_real=p_real, s_pad=s_pad, chunk=wave,
            n_chunks=n_waves,
        )
        restore = plan.restore
        if restore is not None:
            start_wave = restore.chunks_done
            carry_s = carry_from_host(carry_s, restore.carry)
            outs.append(restore.outputs)
            _metrics.RESUME_CHUNKS_SKIPPED.inc(start_wave)
            _flightrec.note(
                "plan-restore", plan=plan.key, chunk=start_wave - 1,
                digest=f"{restore.digest:08x}",
            )
            _flightrec.dump("chunk-restore", run_dir=cp.run_dir)

    track = cp is not None or _faults.has_rules("device")
    last_good = None  # (wave_idx, host carry leaves, len(outs), digest)
    if track:
        host0 = carry_to_host(carry_s)
        last_good = (
            start_wave, host0, len(outs), scenario_carry_digest_host(host0),
        )
    strikes = 0

    i = start_wave
    while i < n_waves:
        rule = _faults.maybe_inject("device", f"commit-chunk:{i}")
        if rule is not None:
            try:
                _faults.apply_device_fault(rule)
            except _faults.DeviceLostError:
                strikes += 1
                if last_good is None or strikes >= 3:
                    _metrics.DEVICE_LOST.inc(handled="no")
                    raise
                _metrics.DEVICE_LOST.inc(handled="yes")
                g_wave, g_carry, g_outs, g_digest = last_good
                _flightrec.note(
                    "device-lost", chunk=i, restored_to=g_wave,
                    digest=f"{g_digest:08x}",
                )
                _flightrec.dump(
                    "device-lost",
                    run_dir=cp.run_dir if cp is not None else None,
                )
                carry_s = carry_from_host(carry_s, g_carry)
                del outs[g_outs:]
                i = g_wave
                continue
        lo = i * wave
        count = min(wave, p_real - lo)
        rows_w = jax.tree_util.tree_map(lambda a: a[lo:lo + wave], rows)
        choices = jnp.full((s_pad, wave), -1, jnp.int32)
        prev = np.full((s_pad, wave), -1, np.int32)
        rounds = 0
        converged = False
        while True:
            rounds += 1
            _progress(
                f"scenarios S={s_real}/{s_pad} N={N} "
                f"wave {i + 1}/{n_waves} round {rounds} "
                f"(W={wave}, live={count})"
            )
            carry_w, choices_new, reasons, gpu_take, vg_take, dev_take = (
                schedule_wave(
                    ns, carry_s, rows_w, weights_s, valid_s, choices,
                    jnp.int32(count), filter_on,
                )
            )
            ch = np.asarray(jax.device_get(choices_new))
            if np.array_equal(ch, prev):
                converged = True
                break
            if rounds > 1:
                _metrics.WAVE_CONFLICTS.inc(
                    int((ch[:s_real, :count] != prev[:s_real, :count]).sum())
                )
            if max_rounds and rounds >= max_rounds:
                break
            choices, prev = choices_new, ch
        _metrics.COMMIT_ROUNDS.observe(rounds)
        if converged:
            carry_s = carry_w
            got = jax.device_get((reasons, gpu_take, vg_take, dev_take))
            outs.append(
                (np.ascontiguousarray(ch[:, :count]),)
                + tuple(np.asarray(a)[:, :count] for a in got)
            )
        else:
            _metrics.WAVE_FALLBACKS.inc(reason="max_rounds")
            _progress(
                f"wave {i + 1}/{n_waves}: no fixpoint in {rounds} rounds; "
                "replaying through the serial chunk kernel"
            )
            carry_s, nodes, reasons, gpu_take, vg_take, dev_take = (
                schedule_scenarios_chunked(
                    ns, carry_s, rows_w, weights_s, valid_s,
                    jnp.int32(count), filter_on,
                )
            )
            got = jax.device_get(
                (nodes, reasons, gpu_take, vg_take, dev_take)
            )
            outs.append(tuple(np.asarray(a)[:, :count] for a in got))
        _metrics.PLAN_CHUNKS.inc()
        if cp is not None:
            digest = scenario_carry_digest(carry_s)
            hostc = cp.on_chunk(plan, i, lo + count, digest, carry_s, outs)
            if hostc is not None:
                last_good = (i + 1, hostc, len(outs), digest)
        i += 1

    if cp is not None:
        cp.finish_plan(plan, scenario_carry_digest(carry_s))
    cat = tuple(
        np.concatenate([o[k] for o in outs], axis=1) for k in range(5)
    )
    return (carry_s,) + tuple(a[:s_real] for a in cat)
