"""Delta kernels for the resident cluster state (engine/resident.py).

Two tiny jit families keep the device copy of the node planes in sync without
a full ops/encode re-encode:

  * apply_rows / apply_flags — scatter freshly re-encoded rows into the
    resident planes. Row *contents* are always recomputed on the host by the
    exact encode_node_into code path (never incrementally adjusted on device:
    f32 accumulation is non-associative, and byte-identity with a fresh encode
    is the resident path's correctness contract), so the device work is pure
    data movement. Index vectors are bucket-padded; pad slots carry an
    out-of-range index and are dropped by XLA's scatter `mode="drop"`.

  * digest_fold — an order-independent-combining u32 digest of one tensor,
    used by the drift detector. Float planes are bitcast to their raw u32
    pattern (NaN payloads and signed zeros included — the digest must see
    exactly the bytes a fresh encode would produce), ints/bools are widened.
    Each element is weighted by an odd constant (2i+1) so permutations and
    zero-fills still change the sum, then summed mod 2^32 (uint32 wraparound).
    digest_fold_host is the numpy twin that produces bit-identical values for
    host-side arrays; combine_digests chains per-plane digests (FNV-1a style)
    into one cluster digest.

All jit entries here are registered with analysis/jaxpr_audit.py and the
invariant prover — they run inside the serving loop, so they get the same
static guarantees as the scheduling kernels.
"""

from __future__ import annotations

import functools
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from .encode import round_up
from .sanitize import sanitizable

__all__ = [
    "apply_rows",
    "apply_flags",
    "digest_fold",
    "digest_fold_host",
    "combine_digests",
    "pad_indices",
]


def pad_indices(idx: Iterable[int], n: int) -> np.ndarray:
    """Bucket-pad a host index list to i32[round_up(U, 8)]; pad slots hold n
    (one past the last row), which scatter `mode="drop"` discards. Bucketing
    keeps the jit cache warm across delta batches of similar size."""
    raw = np.asarray(list(idx), np.int32)
    u = round_up(max(len(raw), 1), 8)
    out = np.full(u, n, np.int32)
    out[: len(raw)] = raw
    return out


@sanitizable("ops.delta:apply_rows", donate_argnums=(0,))
@functools.partial(jax.jit, donate_argnums=(0,))
def apply_rows(arr: jnp.ndarray, idx: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """Scatter whole re-encoded rows into a 2-D plane: arr[idx[u]] = rows[u].
    Out-of-range idx entries (the pad slots) are dropped, not clamped —
    clamping would silently overwrite the last real row.

    `arr` is donated: the scatter lands in place instead of copying the
    whole plane per delta. Callers must treat the passed plane as consumed
    — ResidentCluster hands in a fresh copy whenever a table_view() loan of
    the old plane may still be live (see engine/resident._apply_rows)."""
    return arr.at[idx].set(rows, mode="drop")


@sanitizable("ops.delta:apply_flags", donate_argnums=(0,))
@functools.partial(jax.jit, donate_argnums=(0,))
def apply_flags(arr: jnp.ndarray, idx: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """apply_rows for 1-D per-node vectors (unsched/valid flags, name ids).
    Same donation contract as apply_rows: `arr` is consumed."""
    return arr.at[idx].set(vals, mode="drop")


def _bits_u32(x: jnp.ndarray) -> jnp.ndarray:
    if x.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    return x.astype(jnp.uint32)


@sanitizable("ops.delta:digest_fold")
@jax.jit
def digest_fold(x: jnp.ndarray) -> jnp.ndarray:
    """u32[] position-weighted checksum of one tensor (see module docstring).
    Returns a scalar uint32; the only host transfer the drift detector pays is
    this 4-byte scalar per plane."""
    u = _bits_u32(x).ravel()
    w = jnp.arange(u.shape[0], dtype=jnp.uint32) * jnp.uint32(2) + jnp.uint32(1)
    return jnp.sum(u * w, dtype=jnp.uint32)


def digest_fold_host(x: np.ndarray) -> int:
    """Bit-identical numpy twin of digest_fold for host-resident arrays."""
    x = np.ascontiguousarray(x)
    if x.dtype == np.float32:
        u = x.view(np.uint32).ravel()
    else:
        u = x.astype(np.uint32).ravel()
    # All-uint32 arithmetic: numpy array multiply and sum both wrap mod 2^32,
    # matching the device's uint32 wraparound bit for bit.
    w = np.arange(u.size, dtype=np.uint32) * np.uint32(2) + np.uint32(1)
    return int(np.sum(u * w, dtype=np.uint32))


def combine_digests(parts: Iterable[int]) -> int:
    """Chain per-plane digests into one cluster digest (FNV-1a over u32
    words). Order matters — callers fold planes in a fixed field order."""
    h = 2166136261
    for p in parts:
        h = ((h ^ (int(p) & 0xFFFFFFFF)) * 16777619) & 0xFFFFFFFF
    return h
