"""Host↔device state plumbing: numpy tables → jnp pytrees and back.

The reference's equivalent is the informer/watch machinery that keeps the
scheduler cache in sync with the fake apiserver
(`/root/reference/pkg/simulator/simulator.go:127-187`). Here the whole cluster
ships to the device once, and the only thing that ever comes back per batch is
the placement vector and failure-reason counts.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .encode import (
    Encoder,
    NodeTable,
    PodBatch,
    anti_table_size,
    port_table_sizes,
    round_up,
    selector_table_size,
)
from .kernels import Carry, NodeStatic, PodRow


def node_static_from_table(enc: Encoder, table: NodeTable) -> NodeStatic:
    D = round_up(len(enc.domains) + 1, 4)
    domain_key = np.full(D, -1, np.int32)
    for did_minus1, k_idx in enumerate(enc.domain_topo):
        domain_key[did_minus1 + 1] = k_idx
    # one-hot domain membership per topology key: [K,D,N]; segment sums become
    # matvecs on device (TPU scatters serialize — see kernels._domain_counts).
    # Key 0 (hostname) is handled natively by the kernels and stays zero here.
    K = table.topo.shape[1]
    N = table.n
    topo_onehot = np.zeros((K, D, N), np.float32)
    for k in range(1, K):
        d = table.topo[:, k]
        rows = np.nonzero((d >= 0) & table.valid)[0]
        topo_onehot[k, d[rows], rows] = 1.0
    return NodeStatic(
        alloc=jnp.asarray(table.alloc),
        label_pair=jnp.asarray(table.label_pair),
        label_key=jnp.asarray(table.label_key),
        label_num=jnp.asarray(table.label_num),
        taint_key=jnp.asarray(table.taint_key),
        taint_val=jnp.asarray(table.taint_val),
        taint_effect=jnp.asarray(table.taint_effect),
        name_id=jnp.asarray(table.name_id),
        unsched=jnp.asarray(table.unsched),
        avoid_pods=jnp.asarray(table.avoid_pods),
        topo=jnp.asarray(table.topo),
        valid=jnp.asarray(table.valid),
        gpu_total=jnp.asarray(table.gpu_total),
        vg_cap=jnp.asarray(table.vg_cap),
        vg_name=jnp.asarray(table.vg_name),
        dev_cap=jnp.asarray(table.dev_cap),
        dev_ssd=jnp.asarray(table.dev_ssd),
        has_storage=jnp.asarray(table.has_storage),
        domain_key=jnp.asarray(domain_key),
        topo_onehot=jnp.asarray(topo_onehot),
        unsched_key_id=jnp.int32(enc.unsched_key_id),
        empty_val_id=jnp.int32(enc.empty_val_id),
        anti_topo=jnp.asarray(anti_topo_array(enc)),
    )


def anti_topo_array(enc: Encoder) -> np.ndarray:
    """i32[AT] topo-key index per registered required-anti-affinity term."""
    AT = anti_table_size(enc)
    arr = np.full(AT, -1, np.int32)
    for t, (k_idx, _sel) in enumerate(enc.anti_terms):
        arr[t] = k_idx
    return arr


def carry_from_table(
    table: NodeTable,
    sel_counts: Optional[np.ndarray] = None,
    num_selectors: int = 1,
    port_counts: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    anti_counts: Optional[np.ndarray] = None,
) -> Carry:
    if sel_counts is None:
        # same bucketing as encode.selector_table_size so direct callers
        # (bench, entry) line up with encode_pods' match_sel axis
        sel_counts = np.zeros(
            (round_up(max(num_selectors, 1), 8), table.n), np.float32
        )
    if port_counts is None:
        z = np.zeros((2, table.n), np.float32)
        port_counts = (z, z, z)
    if anti_counts is None:
        # encode.anti_table_size bucketing (min 2)
        anti_counts = np.zeros((2, table.n), np.float32)
    return Carry(
        free=jnp.asarray(table.free),
        sel_counts=jnp.asarray(sel_counts),
        gpu_free=jnp.asarray(table.gpu_free),
        vg_free=jnp.asarray(table.vg_free),
        dev_free=jnp.asarray(table.dev_free),
        port_any=jnp.asarray(port_counts[0]),
        port_wild=jnp.asarray(port_counts[1]),
        port_ipc=jnp.asarray(port_counts[2]),
        anti_counts=jnp.asarray(anti_counts),
    )


def pod_rows_from_batch_host(batch: PodBatch) -> PodRow:
    """Stacked PodRow pytree with HOST numpy leaves — for per-pod drivers
    (extender path, preemption probe rows) that slice one row at a time:
    slicing device arrays costs an un-jitted device get per field per pod,
    and round-tripping jnp→np pays ~40 transfers each way for data that
    starts and ends as numpy. The field set mirrors pod_rows_from_batch."""
    import numpy as _np

    # PodRow fields map 1:1 onto PodBatch attributes of the same name
    return PodRow(
        **{f: _np.asarray(getattr(batch, f)) for f in PodRow._fields}
    )


def pod_rows_from_batch(batch: PodBatch) -> PodRow:
    """Stacked PodRow pytree ([P, ...] device leaves) for lax.scan."""
    return PodRow(
        **{f: jnp.asarray(getattr(batch, f)) for f in PodRow._fields}
    )


def _grow_rows(arr: jnp.ndarray, rows: int) -> jnp.ndarray:
    old, N = arr.shape
    if rows <= old:
        return arr
    return jnp.zeros((rows, N), arr.dtype).at[:old].set(arr)


def stack_carry(carry: Carry, count: int) -> Carry:
    """Scenario-stacked Carry: every leaf gains a leading [S] axis holding
    `count` identical copies — the starting state of a multi-scenario sweep
    (all scenarios begin from the same cluster; their carries diverge as the
    vmapped scan commits per-scenario placements).

    Donation-safe by construction: each eager broadcast_to materializes a
    fresh dense [S, ...] buffer (XLA arrays have no stride-0 views), so the
    stacked carry shares no buffer with `carry` and schedule_scenarios may
    donate it while the source carry — possibly the simulator's live serial
    carry or a resident device plane — stays untouched. tests/test_warmup.py
    pins this contract."""
    import jax

    return jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (count,) + a.shape), carry
    )


def _grow_rows_stacked(arr: jnp.ndarray, rows: int) -> jnp.ndarray:
    S, old, N = arr.shape
    if rows <= old:
        return arr
    return jnp.zeros((S, rows, N), arr.dtype).at[:, :old].set(arr)


def align_carry_scenarios(
    carry_s: Carry, enc: Encoder, ns: Optional[NodeStatic] = None
) -> Carry | Tuple[Carry, NodeStatic]:
    """align_carry for a scenario-stacked carry ([S, rows, N] leaves): grows
    the selector/port/anti row axes (axis 1) in lockstep across all scenarios.
    Pass `ns` to also refresh NodeStatic.anti_topo, exactly as align_carry
    does; returns (carry_s, ns) in that case.

    Donation note: when nothing grew the SAME carry object returns (identity
    preserved for the caller's re-pin check); on growth the result still
    shares its ungrown leaves with the input. Either way, handing the result
    to the donating schedule_scenarios consumes the input carry_s too —
    callers must rebind both names (run_scenarios threads one name through,
    which does exactly that)."""
    PID, PIP = port_table_sizes(enc)
    new = {
        "sel_counts": _grow_rows_stacked(
            carry_s.sel_counts, selector_table_size(enc)
        ),
        "port_any": _grow_rows_stacked(carry_s.port_any, PID),
        "port_wild": _grow_rows_stacked(carry_s.port_wild, PID),
        "port_ipc": _grow_rows_stacked(carry_s.port_ipc, PIP),
        "anti_counts": _grow_rows_stacked(
            carry_s.anti_counts, anti_table_size(enc)
        ),
    }
    if all(v is getattr(carry_s, k) for k, v in new.items()):
        grown = carry_s
    else:
        grown = carry_s._replace(**new)
    if ns is None:
        return grown
    want = anti_topo_array(enc)
    have = np.asarray(ns.anti_topo)
    if have.shape != want.shape or not np.array_equal(have, want):
        ns = ns._replace(anti_topo=jnp.asarray(want))
    return grown, ns


def align_carry(
    carry: Carry, enc: Encoder, ns: Optional[NodeStatic] = None
) -> Carry | Tuple[Carry, NodeStatic]:
    """Grow the selector/port/anti axes when a later batch registers new
    entries; counts accumulated so far are preserved in place (ids are
    append-only). Pass `ns` to also regrow NodeStatic.anti_topo in lockstep
    (its AT axis must match carry.anti_counts / pod.match_anti for the vmap in
    pod_affinity_mask); returns (carry, ns) in that case."""
    PID, PIP = port_table_sizes(enc)
    new = {
        "sel_counts": _grow_rows(carry.sel_counts, selector_table_size(enc)),
        "port_any": _grow_rows(carry.port_any, PID),
        "port_wild": _grow_rows(carry.port_wild, PID),
        "port_ipc": _grow_rows(carry.port_ipc, PIP),
        "anti_counts": _grow_rows(carry.anti_counts, anti_table_size(enc)),
    }
    # preserve identity when nothing grew, so callers can use an `is` check
    # to decide whether sharded state needs re-pinning
    if all(v is getattr(carry, k) for k, v in new.items()):
        grown = carry
    else:
        grown = carry._replace(**new)
    if ns is None:
        return grown
    # Refresh anti_topo whenever its content is stale, not just on shape
    # growth: the 0-term pad state and the 1-term state share shape (1,), so a
    # first term registered after NodeStatic was built changes content only.
    want = anti_topo_array(enc)
    have = np.asarray(ns.anti_topo)
    if have.shape != want.shape or not np.array_equal(have, want):
        ns = ns._replace(anti_topo=jnp.asarray(want))
    return grown, ns
