"""Tensorization: object model → structure-of-arrays device tensors.

This is the L1 replacement: where the reference keeps cluster state in the fake
clientset's ObjectTracker (`vendor/k8s.io/client-go/testing/fixture.go`), the
TPU build keeps it as HBM-resident tensors. Strings (labels, taints, namespaces)
are interned into integer vocabularies on the host; all per-node and per-pod
scheduling state becomes fixed-shape arrays so the whole Filter/Score/Select
loop stays inside one XLA computation.

Shapes (N nodes, P pods, R resources, padded caps L/T/TERM/EXPR/VAL/TOL/S/K):
  NodeTable: alloc f32[N,R], free f32[N,R], label_pair i32[N,L], label_key
  i32[N,L], label_num f32[N,L], taint_{key,val,effect} i32[N,T], name_id i32[N],
  unsched bool[N], avoid_pods bool[N], topo i32[N,K], valid bool[N]
  PodBatch: req f32[P,R], selector term tensors, tolerations, preferred terms,
  spread/affinity constraint tables, match_sel bool[P,S].

Bucketed padding (`round_up`) keeps jit cache hits across add-node iterations.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..utils import metrics as _metrics

from ..core.matcher import match_label_selector
from ..core.objects import (
    ANNO_GPU_COUNT_POD,
    ANNO_GPU_MEM_POD,
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_POD_LOCAL_STORAGE,
    RESOURCE_GPU_COUNT,
    LabelSelector,
    Node,
    NodeLocalStorage,
    Pod,
)

# Resource scaling: canonical int units -> f32-safe units.
# cpu is already milli; byte-like resources go to MiB so f32's 24-bit mantissa
# stays exact up to 16 TiB per node.
_BYTE_LIKE = (
    "memory", "ephemeral-storage", "storage", "hugepages-",
    "alibabacloud.com/gpu-mem",
)

# Fixed resource-axis index of the whole-GPU count extended resource
# (alibabacloud.com/gpu-count). Its node allocatable is DYNAMIC in the
# reference — the gpu-share plugin's Reserve rewrites it to the number of
# fully-idle devices (open-gpu-share.go:183-190) — so the kernels recompute
# effective availability from the per-device state instead of trusting the
# static row (kernels.run_filters).
GPU_COUNT_IDX = 3
_EFFECTS = {"NoSchedule": 1, "PreferNoSchedule": 2, "NoExecute": 3}

OP_PAD, OP_IN, OP_NOT_IN, OP_EXISTS, OP_NOT_EXISTS, OP_GT, OP_LT = range(7)
_OPS = {
    "In": OP_IN,
    "NotIn": OP_NOT_IN,
    "Exists": OP_EXISTS,
    "DoesNotExist": OP_NOT_EXISTS,
    "Gt": OP_GT,
    "Lt": OP_LT,
}


def resource_scale(name: str) -> float:
    if any(name.startswith(b) or name == b for b in _BYTE_LIKE):
        return float(1 << 20)
    return 1.0


def round_up(n: int, floor: int = 8, step: int = 4096) -> int:
    """Bucket a dynamic size so jit caches hit across add-node iterations and
    varying app sizes: next power of two up to `step`, then multiples of
    `step` (bounds padding waste to <1/16 for big batches where scan steps
    are paid per padded row).

    `floor` is the smallest bucket ever returned; `step` is the linear
    granularity past the power-of-two region. They are distinct knobs: the
    old `minimum` name suggested granularity but only ever set the floor."""
    size = max(n, floor, 1)
    if size <= step:
        return 1 << (size - 1).bit_length()
    return (size + step - 1) // step * step


# The node-axis shape ladder (ROADMAP 5(b), docs/performance.md): every node
# table pads to a rung, so the jit family compiles a finite program set no
# matter how node counts grow — powers of two from the floor up to the step,
# then multiples of the step: 64, 128, ..., 4096, 8192, 12288, ...
NODE_BUCKET_FLOOR = 64
NODE_BUCKET_STEP = 4096


def node_bucket(n: int) -> int:
    """The ladder rung (padded node-axis length) covering `n` real nodes.
    Tiny clusters pay a few inert padded rows; in exchange the engine keeps
    one compiled program per rung instead of one per node count."""
    return round_up(n, floor=NODE_BUCKET_FLOOR, step=NODE_BUCKET_STEP)


def ladder_rungs(n_max: int) -> List[int]:
    """Every ladder rung up to and including the one covering `n_max` — the
    complete program family a capacity sweep over [1, n_max] can touch."""
    rungs = [NODE_BUCKET_FLOOR]
    while rungs[-1] < n_max:
        rungs.append(node_bucket(rungs[-1] + 1))
    return rungs


class Vocab:
    """Host-side string interner. Id 0 is reserved for 'absent'."""

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}

    def id(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._ids) + 1
            self._ids[s] = i
        return i

    def get(self, s: str) -> int:
        return self._ids.get(s, 0)

    def __len__(self) -> int:
        return len(self._ids)


def _pod_row_sig(pod: Pod) -> Tuple:
    """Encoding signature: pods with equal signatures produce identical
    PodBatch rows (the name itself is never encoded). Mutable per-clone dicts
    (labels, annotations-derived features, requests) are keyed by content;
    spec-derived immutable structures that _clone_pod shares between replicas
    (affinity, tolerations, spread constraints, host ports, nodeSelector) are
    keyed by identity — distinct parses never share ids, so identity keying is
    conservative (never merges pods that differ)."""
    return (
        pod.meta.namespace,
        tuple(sorted(pod.meta.labels.items())),
        tuple(sorted(pod.requests.items())),
        pod.node_name,
        pod.meta.owner_kind,
        pod.meta.annotations.get(ANNO_GPU_MEM_POD),
        pod.meta.annotations.get(ANNO_GPU_COUNT_POD),
        pod.meta.annotations.get(ANNO_POD_LOCAL_STORAGE),
        id(pod.affinity),
        id(pod.tolerations),
        id(pod.spread_constraints),
        id(pod.host_ports),
        id(pod.node_selector),
    )


@dataclass
class SelectorEntry:
    """A deduped (namespaces, LabelSelector) pair used by spread/affinity terms."""
    namespaces: Tuple[str, ...]
    selector: Optional[LabelSelector]

    def matches(self, pod: Pod) -> bool:
        if self.namespaces and pod.meta.namespace not in self.namespaces:
            return False
        return match_label_selector(self.selector, pod.meta.labels)


class Encoder:
    """Shared vocabularies + caps for one simulation. Nodes and pods must be
    encoded by the same Encoder so ids line up."""

    UNSCHED_TAINT_KEY = "node.kubernetes.io/unschedulable"

    def __init__(
        self,
        topology_keys: Sequence[str] = (),
        ignored_resources: Sequence[str] = (),
    ) -> None:
        # Extender-managed resources with ignoredByScheduler: the reference
        # adds these to NodeResourcesFit's IgnoredResources for every profile
        # (vendor/.../scheduler/factory.go:105-130). Skipping them here keeps
        # them out of the req/alloc tensors entirely, so the device resource
        # filter never sees them — the extender (which matches interest on
        # the raw pod.requests dict) remains the sole authority.
        self.ignored_resources = frozenset(r for r in ignored_resources if r)
        self.keys = Vocab()        # label keys
        self.vals = Vocab()        # label values
        # Pre-intern ids the kernels reference as scalars, so they are stable
        # regardless of node/pod encode order.
        self.unsched_key_id = self.keys.id(self.UNSCHED_TAINT_KEY)
        self.empty_val_id = self.vals.id("")
        self.pairs = Vocab()       # "key=value"
        self.names = Vocab()       # node names
        self.vgs = Vocab()         # LVM volume-group names (open-local)
        self.resources: List[str] = ["cpu", "memory", "pods", RESOURCE_GPU_COUNT]
        assert self.resources[GPU_COUNT_IDX] == RESOURCE_GPU_COUNT
        # kubernetes.io/hostname is pinned at index 0: its domains are the
        # nodes themselves, handled natively by the kernels (a dense one-hot
        # for it would be O(N^2) memory — kernels.HOSTNAME_KEY_IDX).
        self.topology_keys: List[str] = ["kubernetes.io/hostname"] + [
            k for k in dict.fromkeys(list(topology_keys))
            if k != "kubernetes.io/hostname"
        ]
        self.selectors: List[SelectorEntry] = []
        self._selector_ids: Dict[Tuple, int] = {}
        self.domains = Vocab()     # "topokey=value" domain ids
        self.domain_topo: List[int] = []  # topo-key index per domain id (1-based)
        # NodePorts: (protocol, port) ids and specific (protocol, port, ip) ids.
        # Id 0 is the pad row of the count tables (never incremented).
        self.ports = Vocab()
        self.port_ips = Vocab()
        # InterPodAffinity symmetry: registry of distinct required
        # anti-affinity (topo key idx, selector id) terms across ALL pods, so
        # existing pods' anti-affinity can repel matching incomers (the
        # vendored plugin's existingAntiAffinityCounts).
        self.anti_terms: List[Tuple[int, int]] = []
        self._anti_ids: Dict[Tuple[int, int], int] = {}
        # (namespace, sorted labels) -> bool[S] selector match vector; see
        # match_vector. Append-only selector ids keep stale entries safe to
        # detect by length.
        self._match_cache: Dict[Tuple, np.ndarray] = {}

    def domain_id(self, key_idx: int, key: str, value: str) -> int:
        before = len(self.domains)
        did = self.domains.id(f"{key}={value}")
        if len(self.domains) > before:
            self.domain_topo.append(key_idx)
        return did

    # -- registration -------------------------------------------------------
    def resource_index(self, name: str) -> int:
        if name not in self.resources:
            self.resources.append(name)
        return self.resources.index(name)

    def topo_index(self, key: str) -> int:
        if key not in self.topology_keys:
            self.topology_keys.append(key)
        return self.topology_keys.index(key)

    def selector_id(self, namespaces: Sequence[str], selector: Optional[LabelSelector]) -> int:
        key = (
            tuple(sorted(namespaces)),
            selector.key() if selector is not None else None,
        )
        sid = self._selector_ids.get(key)
        if sid is None:
            sid = len(self.selectors)
            self._selector_ids[key] = sid
            self.selectors.append(SelectorEntry(tuple(sorted(namespaces)), selector))
        return sid

    def pair_id(self, key: str, value: str) -> int:
        self.keys.id(key)
        self.vals.id(value)
        return self.pairs.id(f"{key}={value}")

    def anti_term_id(self, topo_idx: int, sel_id: int) -> int:
        key = (topo_idx, sel_id)
        aid = self._anti_ids.get(key)
        if aid is None:
            aid = len(self.anti_terms)
            self._anti_ids[key] = aid
            self.anti_terms.append(key)
        return aid

    def port_ids(self, pod: Pod) -> List[Tuple[int, bool, int]]:
        """(pid, is_wildcard_ip, ipid) per host port; registers vocab entries."""
        from ..core.matcher import _WILDCARD_IPS

        out = []
        for proto, port, ip in pod.host_ports:
            pid = self.ports.id(f"{proto}:{port}")
            wild = ip in _WILDCARD_IPS
            ipid = 0 if wild else self.port_ips.id(f"{proto}:{port}:{ip}")
            out.append((pid, wild, ipid))
        return out

    def anti_ids(self, pod: Pod) -> List[int]:
        """Required anti-affinity term ids this pod carries; registers them."""
        out = []
        for t in pod.affinity.anti_required:
            if not t.topology_key:
                continue
            k = self.topo_index(t.topology_key)
            s = self.selector_id(t.namespaces or (pod.meta.namespace,), t.selector)
            out.append(self.anti_term_id(k, s))
        return out

    def register_pods(self, pods: Sequence[Pod]) -> None:
        """Pre-register every resource name, topology key and selector used by
        a pod batch, so caps and ids are stable before arrays are built.

        Deduped by row signature: workload replicas are prototype clones
        (core/workloads._clone_pod) whose registrations are identical, so one
        representative per signature registers for the whole group."""
        seen: Set[Tuple] = set()
        for pod in pods:
            sig = _pod_row_sig(pod)
            if sig in seen:
                continue
            seen.add(sig)
            for r in pod.requests:
                if r not in self.ignored_resources:
                    self.resource_index(r)
            for c in pod.spread_constraints:
                if c.topology_key:
                    self.topo_index(c.topology_key)
                self.selector_id((pod.meta.namespace,), c.selector)
            aff = pod.affinity
            for terms in (aff.pod_required, aff.anti_required):
                for t in terms:
                    if t.topology_key:
                        self.topo_index(t.topology_key)
                    self.selector_id(t.namespaces or (pod.meta.namespace,), t.selector)
            for wt in list(aff.pod_preferred) + list(aff.anti_preferred):
                t = wt.term
                if t.topology_key:
                    self.topo_index(t.topology_key)
                self.selector_id(t.namespaces or (pod.meta.namespace,), t.selector)
            self.anti_ids(pod)
            self.port_ids(pod)


@dataclass
class NodeTable:
    """SoA encoding of all nodes. All arrays are numpy; the engine ships them
    to the device once per simulation."""
    alloc: np.ndarray       # f32[N,R] allocatable, scaled units
    free: np.ndarray        # f32[N,R] allocatable - requested(existing pods)
    label_pair: np.ndarray  # i32[N,L]
    label_key: np.ndarray   # i32[N,L]
    label_num: np.ndarray   # f32[N,L] numeric label value (nan if non-numeric)
    taint_key: np.ndarray   # i32[N,T]
    taint_val: np.ndarray   # i32[N,T]
    taint_effect: np.ndarray  # i32[N,T] 0=pad
    name_id: np.ndarray     # i32[N]
    unsched: np.ndarray     # bool[N]
    avoid_pods: np.ndarray  # bool[N] NodePreferAvoidPods annotation present
    topo: np.ndarray        # i32[N,K] domain id or -1
    valid: np.ndarray       # bool[N]
    gpu_total: np.ndarray   # f32[N,G] per-device total GPU mem, MiB (0 = none)
    gpu_free: np.ndarray    # f32[N,G] per-device free after existing pods
    # open-local storage (parity: the simon/node-local-storage annotation,
    # utils.GetNodeStorage — VGs are shared bin-packed pools, devices are
    # exclusively allocated whole disks)
    vg_cap: np.ndarray      # f32[N,V] VG capacity, MiB (0 = pad)
    vg_free: np.ndarray     # f32[N,V] capacity - requested
    vg_name: np.ndarray     # i32[N,V] VG name vocab id (0 = pad)
    dev_cap: np.ndarray     # f32[N,DV] device capacity, MiB (0 = pad)
    dev_ssd: np.ndarray     # bool[N,DV] media type is SSD
    dev_free: np.ndarray    # f32[N,DV] 1.0 = free, 0.0 = allocated/pad
    has_storage: np.ndarray  # bool[N] node carries the storage annotation
    names: List[str] = field(default_factory=list)

    @property
    def n(self) -> int:
        return self.alloc.shape[0]


@dataclass
class PodBatch:
    """SoA encoding of a pod batch to schedule sequentially."""
    req: np.ndarray            # f32[P,R]
    has_req: np.ndarray        # bool[P] (simon score: empty requests => max)
    node_name_id: np.ndarray   # i32[P] 0 = unpinned
    gpu_mem: np.ndarray        # f32[P] per-GPU shared-memory request, MiB
    gpu_num: np.ndarray        # f32[P] number of GPU shares requested
    # required node affinity: OR over TERM terms, AND over EXPR exprs each
    sel_op: np.ndarray         # i32[P,TERM,EXPR]
    sel_key: np.ndarray        # i32[P,TERM,EXPR]
    sel_val: np.ndarray        # i32[P,TERM,EXPR,VAL] pair ids for In/NotIn
    sel_num: np.ndarray        # f32[P,TERM,EXPR] numeric rhs for Gt/Lt
    has_terms: np.ndarray      # bool[P] any required term present
    # plain nodeSelector: all pairs must be present
    ns_pair: np.ndarray        # i32[P,NS]
    # preferred node affinity terms (flattened single-expression groups)
    pref_weight: np.ndarray    # f32[P,PREF]
    pref_op: np.ndarray        # i32[P,PREF,EXPR]
    pref_key: np.ndarray       # i32[P,PREF,EXPR]
    pref_val: np.ndarray       # i32[P,PREF,EXPR,VAL]
    pref_num: np.ndarray       # f32[P,PREF,EXPR]
    # tolerations
    tol_key: np.ndarray        # i32[P,TOL] 0 = all keys
    tol_val: np.ndarray        # i32[P,TOL]
    tol_exists: np.ndarray     # bool[P,TOL]
    tol_effect: np.ndarray     # i32[P,TOL] 0 = all effects
    tol_valid: np.ndarray      # bool[P,TOL]
    # topology spread constraints
    spread_topo: np.ndarray    # i32[P,C] topo key index or -1
    spread_sel: np.ndarray     # i32[P,C] selector id
    spread_skew: np.ndarray    # f32[P,C]
    spread_hard: np.ndarray    # bool[P,C]
    # inter-pod (anti)affinity terms
    aff_topo: np.ndarray       # i32[P,A] topo key index or -1
    aff_sel: np.ndarray        # i32[P,A]
    aff_anti: np.ndarray       # bool[P,A]
    aff_required: np.ndarray   # bool[P,A]
    aff_weight: np.ndarray     # f32[P,A] (preferred terms; 0 for required)
    # open-local storage volumes (parity: simon/pod-local-storage VolumeRequest)
    lvm_req: np.ndarray        # f32[P,SV] LVM request MiB per slot (0 = pad)
    lvm_vg: np.ndarray         # i32[P,SV] explicit VG id, 0 = binpack over VGs
    dev_req: np.ndarray        # f32[P,SV] exclusive-device request MiB (0 = pad)
    dev_media_ssd: np.ndarray  # bool[P,SV] device request wants SSD media
    has_local: np.ndarray      # bool[P] pod carries any local-storage volume
    # membership of this pod in each deduped selector
    match_sel: np.ndarray      # bool[P,S]
    owned_by_rs: np.ndarray    # bool[P] controller is ReplicaSet/RC (NodePreferAvoidPods)
    # NodePorts: requested host ports (pid indexes the port_any/port_wild count
    # tables; ipid indexes port_ipc; 0 = pad)
    hp_pid: np.ndarray         # i32[P,HP]
    hp_wild: np.ndarray        # bool[P,HP] hostIP is wildcard
    hp_ipid: np.ndarray        # i32[P,HP]
    # InterPodAffinity symmetry: per registered required-anti-affinity term
    match_anti: np.ndarray     # bool[P,AT] pod matches term's selector+namespaces
    own_anti: np.ndarray       # f32[P,AT] times this pod carries the term
    valid: np.ndarray          # bool[P]
    keys: List[str] = field(default_factory=list)  # namespace/name per row

    @property
    def p(self) -> int:
        return self.req.shape[0]


def _num_or_nan(s: str) -> float:
    # Fast reject before the try: raising costs ~1.5us per call and nearly
    # every label value / node name is non-numeric (k8s label values cannot
    # start with whitespace, so the leading-char test loses nothing).
    if not s or not (s[0].isdigit() or s[0] in "+-"):
        return float("nan")
    try:
        return float(int(s))
    except ValueError:
        return float("nan")


def node_axes(
    enc: Encoder,
    nodes: Sequence[Node],
    storages: Optional[Sequence[Optional["NodeLocalStorage"]]] = None,
) -> Tuple[int, int, int, int, int]:
    """Bucketed per-node axis caps (L, T, G, V, DV) for this node list — the
    shape-defining maxima of encode_nodes, factored out so the resident delta
    path can detect when an incoming node no longer fits the resident buckets
    (and must trigger a structural re-encode instead of a row scatter)."""
    if storages is None:
        storages = [nd.local_storage() for nd in nodes]
    L = round_up(max((len(nd.meta.labels) for nd in nodes), default=1), 4)
    T = round_up(max((len(nd.taints) for nd in nodes), default=1), 2)
    G = round_up(max((nd.gpu_count() for nd in nodes), default=1), 2)
    V = round_up(max((len(s.vgs) for s in storages if s), default=1), 2)
    DV = round_up(max((len(s.devices) for s in storages if s), default=1), 2)
    return L, T, G, V, DV


# Sentinel distinguishing "caller already decoded local storage (maybe None)"
# from "not provided — decode it here"; None is a legal storage value.
_STORAGE_UNSET: Optional[NodeLocalStorage] = NodeLocalStorage()


def clear_node_row(table: NodeTable, i: int) -> None:
    """Reset row i of every per-node array to the pad value encode_nodes
    allocates (zeros, NaN label_num, -1 topo, False flags) so a subsequent
    encode_node_into writes bytes identical to a from-scratch encode."""
    table.alloc[i] = 0.0
    table.free[i] = 0.0
    table.label_pair[i] = 0
    table.label_key[i] = 0
    table.label_num[i] = np.nan
    table.taint_key[i] = 0
    table.taint_val[i] = 0
    table.taint_effect[i] = 0
    table.name_id[i] = 0
    table.unsched[i] = False
    table.avoid_pods[i] = False
    table.topo[i] = -1
    table.valid[i] = False
    table.gpu_total[i] = 0.0
    table.gpu_free[i] = 0.0
    table.vg_cap[i] = 0.0
    table.vg_free[i] = 0.0
    table.vg_name[i] = 0
    table.dev_cap[i] = 0.0
    table.dev_ssd[i] = False
    table.dev_free[i] = 0.0
    table.has_storage[i] = False


def encode_node_into(
    enc: Encoder,
    table: NodeTable,
    i: int,
    nd: Node,
    usage: Dict[str, Dict[str, int]],
    gpu_usage: Dict[str, np.ndarray],
    st: Optional["NodeLocalStorage"] = _STORAGE_UNSET,
) -> None:
    """Encode one node into row i of a zeroed/cleared table. This is THE
    per-node encode — encode_nodes loops over it and the resident delta path
    replays it for changed rows, so both produce identical bytes by
    construction. Assumes row i holds pad values (see clear_node_row)."""
    L = table.label_pair.shape[1]
    T = table.taint_key.shape[1]
    V = table.vg_cap.shape[1]
    DV = table.dev_cap.shape[1]
    table.valid[i] = True
    table.name_id[i] = enc.names.id(nd.name)
    table.unsched[i] = nd.unschedulable
    table.avoid_pods[i] = (
        "scheduler.alpha.kubernetes.io/preferAvoidPods" in nd.meta.annotations
    )
    for r, res in enumerate(enc.resources):
        a = nd.allocatable.get(res, 0) / resource_scale(res)
        table.alloc[i, r] = a
        used = usage.get(nd.name, {}).get(res, 0) / resource_scale(res)
        table.free[i, r] = a - used
    for j, (k, v) in enumerate(sorted(nd.meta.labels.items())):
        if j >= L:
            break
        table.label_key[i, j] = enc.keys.id(k)
        table.label_pair[i, j] = enc.pair_id(k, v)
        table.label_num[i, j] = _num_or_nan(v)
    for j, t in enumerate(nd.taints):
        if j >= T:
            break
        table.taint_key[i, j] = enc.keys.id(t.key)
        table.taint_val[i, j] = enc.vals.id(t.value)
        table.taint_effect[i, j] = _EFFECTS.get(t.effect, 0)
    table.topo[i, 0] = i  # hostname: every node is its own domain
    for k_idx, key in enumerate(enc.topology_keys[1:], start=1):
        v = nd.meta.labels.get(key)
        if v is not None:
            table.topo[i, k_idx] = enc.domain_id(k_idx, key, v)
    g_cnt = nd.gpu_count()
    if g_cnt > 0:
        per_dev = np.float32(nd.gpu_mem_per_device() / float(1 << 20))
        table.gpu_total[i, :g_cnt] = per_dev
        table.gpu_free[i, :g_cnt] = per_dev
        used = gpu_usage.get(nd.name)
        if used is not None:
            table.gpu_free[i, : len(used)] -= used.astype(np.float32)
    if st is _STORAGE_UNSET:
        st = nd.local_storage()
    if st is not None:
        table.has_storage[i] = True
        for j, vg in enumerate(st.vgs[:V]):
            table.vg_name[i, j] = enc.vgs.id(vg.name)
            table.vg_cap[i, j] = np.float32(vg.capacity / float(1 << 20))
            table.vg_free[i, j] = np.float32(
                max(vg.capacity - vg.requested, 0) / float(1 << 20)
            )
        for j, dev in enumerate(st.devices[:DV]):
            table.dev_cap[i, j] = np.float32(dev.capacity / float(1 << 20))
            table.dev_ssd[i, j] = dev.media_type == "ssd"
            table.dev_free[i, j] = 0.0 if dev.is_allocated else 1.0


# Per-row NodeTable array fields the template-stamping pass broadcasts from
# a template row to its clone rows (every array in the dataclass; `names` is
# the only non-array field and is built separately).
_STAMP_FIELDS = (
    "alloc", "free", "label_pair", "label_key", "label_num",
    "taint_key", "taint_val", "taint_effect", "name_id", "unsched",
    "avoid_pods", "topo", "valid", "gpu_total", "gpu_free",
    "vg_cap", "vg_free", "vg_name", "dev_cap", "dev_ssd", "dev_free",
    "has_storage",
)

# Placeholder for "this label holds the node's own name" in template
# signatures — a control character no real label value can contain.
_OWN_NAME_SENTINEL = "\x00own-name\x00"


def _node_stamp_sig(
    enc: Encoder,
    nd: Node,
    usage: Dict[str, Dict[str, int]],
    gpu_usage: Dict[str, np.ndarray],
    st: Optional[NodeLocalStorage],
    host_key: str,
) -> Tuple:
    """Template signature: nodes with equal signatures encode to identical
    table rows except the name-derived cells (name_id, topo[:, 0], and — when
    the hostname label carries the node's own name — that label slot's pair
    id and numeric view), which the stamping pass fixes up per clone row.
    Covers exactly the inputs encode_node_into reads. The hostname label
    value is replaced by a sentinel only when it equals the node's own name;
    a literal hostname value stays in the signature, so nodes are never
    merged across a real content difference."""
    g_cnt = nd.gpu_count()
    g_used = gpu_usage.get(nd.name)
    labels = []
    for k in sorted(nd.meta.labels):
        v = nd.meta.labels[k]
        if k == host_key and v == nd.name:
            v = _OWN_NAME_SENTINEL
        labels.append((k, v))
    return (
        tuple(sorted(nd.allocatable.items())),
        tuple(labels),
        tuple((t.key, t.value, t.effect) for t in nd.taints),
        nd.unschedulable,
        "scheduler.alpha.kubernetes.io/preferAvoidPods" in nd.meta.annotations,
        g_cnt,
        nd.gpu_mem_per_device() if g_cnt > 0 else 0,
        tuple(sorted(usage.get(nd.name, {}).items())),
        None if g_used is None else tuple(np.asarray(g_used).tolist()),
        None if st is None else (
            tuple((vg.name, vg.capacity, vg.requested) for vg in st.vgs),
            tuple(
                (d.capacity, d.media_type, d.is_allocated)
                for d in st.devices
            ),
        ),
    )


def encode_nodes(
    enc: Encoder,
    nodes: Sequence[Node],
    existing_usage: Optional[Dict[str, Dict[str, int]]] = None,
    existing_gpu: Optional[Dict[str, np.ndarray]] = None,
    n_pad: Optional[int] = None,
    min_axes: Optional[Tuple[int, int, int, int, int]] = None,
    stamp: Optional[bool] = None,
) -> NodeTable:
    """Build the node table. existing_usage maps node name -> canonical request
    totals of already-bound pods (subtracted into `free`); existing_gpu maps
    node name -> used MiB per device (from aggregate_gpu_usage). min_axes is an
    optional (L, T, G, V, DV) floor — the resident path pins it to its resident
    bucket sizes so a verification re-encode lands in identical shapes.

    `stamp` controls the template-stamping fast path (None reads
    OSIM_STAMP_ENCODE, default on): each distinct node spec is encoded once
    with encode_node_into, then its clones are stamped by a vectorized row
    broadcast plus per-row name fixups. Capacity planning adds copies of one
    node type, so at 100k nodes this turns an O(minutes) Python loop into a
    handful of row encodes plus numpy broadcasts. Byte-identical to the loop
    encode by construction: the signature covers every input the row encode
    reads, and clones intern their name-derived vocab entries at their loop
    position, so vocab ids match the loop encode exactly."""
    n = len(nodes)
    # Node-axis ladder floor of 64 (node_bucket): tiny clusters pay a few
    # inert padded rows, and in exchange the whole jit family
    # (scan/traj/light/sort) keeps ONE shape across interactive runs and
    # most capacity-search probes — tracing the big scheduling graphs
    # dominates small-cluster wall time otherwise.
    N = n_pad if n_pad is not None else node_bucket(n)
    R = len(enc.resources)
    K = max(len(enc.topology_keys), 1)
    usage = existing_usage or {}
    gpu_usage = existing_gpu or {}
    if stamp is None:
        stamp = os.environ.get("OSIM_STAMP_ENCODE", "1") != "0"
    stamp = bool(stamp) and n >= 2

    storages: List[Optional[NodeLocalStorage]] = []
    storages_by_row: Dict[int, Optional[NodeLocalStorage]] = {}
    sigs: List[Tuple] = []
    if stamp:
        # Signature pre-pass. Capacity clones carry a `_stamp_token` (minted
        # by engine.capacity.new_fake_nodes): identity keying like
        # _pod_row_sig's, which makes their signature a handful of dict
        # lookups instead of a full content tuple — the difference between
        # O(rows) Python and O(templates) Python at 100k nodes. Everything a
        # materializing run may mutate (unschedulable, the storage
        # annotation, usage maps) stays in the token signature, so a drifted
        # clone falls out of the group instead of merging wrongly. Axis caps
        # (node_axes) are computed over one representative per distinct
        # signature — group members are content-equal, so the max is the max.
        host_key = enc.topology_keys[0]
        ax_nodes: List[Node] = []
        ax_st: List[Optional[NodeLocalStorage]] = []
        seen_tok: Dict[object, Tuple] = {}
        names_list: List[str] = []
        no_usage = not usage and not gpu_usage
        for i, nd in enumerate(nodes):
            meta = nd.meta
            name = meta.name
            names_list.append(name)
            tok = nd.__dict__.get("_stamp_token")
            if tok is not None:
                if no_usage:
                    sig = (
                        tok,
                        nd.unschedulable,
                        meta.annotations.get(ANNO_NODE_LOCAL_STORAGE),
                    )
                else:
                    sig = (
                        tok,
                        nd.unschedulable,
                        meta.annotations.get(ANNO_NODE_LOCAL_STORAGE),
                        tuple(sorted(usage[name].items()))
                        if name in usage else None,
                        tuple(np.asarray(gpu_usage[name]).tolist())
                        if name in gpu_usage else None,
                    )
                prev = seen_tok.get(tok)
                if prev is None:
                    seen_tok[tok] = sig
                if prev is None or prev != sig:
                    ax_nodes.append(nd)
                    ax_st.append(nd.local_storage())
            else:
                st = nd.local_storage()
                storages_by_row[i] = st
                sig = _node_stamp_sig(enc, nd, usage, gpu_usage, st, host_key)
                ax_nodes.append(nd)
                ax_st.append(st)
            sigs.append(sig)
        L, T, G, V, DV = node_axes(enc, ax_nodes, ax_st)
    else:
        storages = [nd.local_storage() for nd in nodes]
        L, T, G, V, DV = node_axes(enc, nodes, storages)
    if min_axes is not None:
        L = max(L, min_axes[0])
        T = max(T, min_axes[1])
        G = max(G, min_axes[2])
        V = max(V, min_axes[3])
        DV = max(DV, min_axes[4])

    alloc = np.zeros((N, R), np.float32)
    free = np.zeros((N, R), np.float32)
    label_pair = np.zeros((N, L), np.int32)
    label_key = np.zeros((N, L), np.int32)
    label_num = np.full((N, L), np.nan, np.float32)
    taint_key = np.zeros((N, T), np.int32)
    taint_val = np.zeros((N, T), np.int32)
    taint_effect = np.zeros((N, T), np.int32)
    name_id = np.zeros(N, np.int32)
    unsched = np.zeros(N, bool)
    avoid = np.zeros(N, bool)
    topo = np.full((N, K), -1, np.int32)
    valid = np.zeros(N, bool)
    gpu_total = np.zeros((N, G), np.float32)
    gpu_free = np.zeros((N, G), np.float32)
    vg_cap = np.zeros((N, V), np.float32)
    vg_free = np.zeros((N, V), np.float32)
    vg_name = np.zeros((N, V), np.int32)
    dev_cap = np.zeros((N, DV), np.float32)
    dev_ssd = np.zeros((N, DV), bool)
    dev_free = np.zeros((N, DV), np.float32)
    has_storage = np.zeros(N, bool)

    table = NodeTable(
        alloc=alloc, free=free, label_pair=label_pair, label_key=label_key,
        label_num=label_num, taint_key=taint_key, taint_val=taint_val,
        taint_effect=taint_effect, name_id=name_id, unsched=unsched,
        avoid_pods=avoid, topo=topo, valid=valid,
        gpu_total=gpu_total, gpu_free=gpu_free,
        vg_cap=vg_cap, vg_free=vg_free, vg_name=vg_name,
        dev_cap=dev_cap, dev_ssd=dev_ssd, dev_free=dev_free,
        has_storage=has_storage,
        names=names_list if stamp else [nd.meta.name for nd in nodes],
    )
    if not stamp:
        for i, nd in enumerate(nodes):
            encode_node_into(
                enc, table, i, nd, usage, gpu_usage, st=storages[i]
            )
        return table

    # Template-stamping pass. Sequential over nodes so every vocab intern
    # happens at the same global position the per-node loop would do it.
    first_row: Dict[Tuple, int] = {}
    # template row -> [(clone row, name_id, hostname pair_id, num(name))]
    clones: Dict[int, List[Tuple[int, int, int, float]]] = {}
    host_bound: Dict[int, bool] = {}
    # Interning inlined against the raw vocab dicts: three method calls per
    # clone add up to most of the pass at 100k rows (Vocab.id semantics,
    # verbatim).
    names_d = enc.names._ids
    vals_d = enc.vals._ids
    pairs_d = enc.pairs._ids
    _nan = float("nan")
    for i, sig in enumerate(sigs):
        tmpl = first_row.get(sig)
        if tmpl is None:
            nd = nodes[i]
            first_row[sig] = i
            host_bound[i] = nd.meta.labels.get(host_key) == names_list[i]
            encode_node_into(
                enc, table, i, nd, usage, gpu_usage,
                st=storages_by_row.get(i, _STORAGE_UNSET),
            )
            continue
        # The clone's only new vocab entries vs its template are its name and
        # (when hostname-bound) its hostname label pair; intern them NOW, at
        # this node's loop position, so ids match the loop encode exactly.
        # (pair_id(host_key, name) minus its keys.id call, which is a pure
        # hit — the template row already interned host_key.)
        name = names_list[i]
        nid = names_d.get(name)
        if nid is None:
            nid = len(names_d) + 1
            names_d[name] = nid
        if host_bound[tmpl]:
            if name not in vals_d:
                vals_d[name] = len(vals_d) + 1
            pair = host_key + "=" + name
            pid = pairs_d.get(pair)
            if pid is None:
                pid = len(pairs_d) + 1
                pairs_d[pair] = pid
            num = _num_or_nan(name)
        else:
            pid, num = 0, _nan
        clones.setdefault(tmpl, []).append((i, nid, pid, num))
    stamped = 0
    for tmpl, rows in clones.items():
        idx = np.fromiter((r[0] for r in rows), np.int32, len(rows))
        for f in _STAMP_FIELDS:
            arr = getattr(table, f)
            arr[idx] = arr[tmpl]
        table.name_id[idx] = np.fromiter(
            (r[1] for r in rows), np.int32, len(rows)
        )
        table.topo[idx, 0] = idx  # hostname: every node is its own domain
        if host_bound[tmpl]:
            # the hostname label sits at the same sorted-label slot on every
            # clone (labels sort by key; only its value differs)
            key_id = enc.keys.get(host_key)
            j = int(np.nonzero(table.label_key[tmpl] == key_id)[0][0])
            table.label_pair[idx, j] = np.fromiter(
                (r[2] for r in rows), np.int32, len(rows)
            )
            table.label_num[idx, j] = np.fromiter(
                (r[3] for r in rows), np.float32, len(rows)
            )
        stamped += len(rows)
    if stamped:
        _metrics.ENCODE_STAMPED_ROWS.inc(stamped)
    return table


def _encode_term_exprs(enc: Encoder, exprs, EXPR: int, VAL: int):
    """Encode one node-selector term's expressions into fixed arrays."""
    op = np.zeros(EXPR, np.int32)
    key = np.zeros(EXPR, np.int32)
    val = np.zeros((EXPR, VAL), np.int32)
    num = np.zeros(EXPR, np.float32)
    for e, ex in enumerate(exprs[:EXPR]):
        op[e] = _OPS.get(ex.operator, OP_PAD)
        key[e] = enc.keys.id(ex.key)
        for v, value in enumerate(ex.values[:VAL]):
            val[e, v] = enc.pair_id(ex.key, value)
        if ex.operator in ("Gt", "Lt") and ex.values:
            try:
                num[e] = float(int(ex.values[0]))
            except ValueError:
                num[e] = float("nan")
    return op, key, val, num


def encode_pods(
    enc: Encoder,
    pods: Sequence[Pod],
    p_pad: Optional[int] = None,
) -> PodBatch:
    """Encode a pod batch.

    Row-level dedup: workload replicas are prototype clones whose encoded rows
    are identical (name excluded — it never becomes a feature), so only one
    representative per `_pod_row_sig` runs the per-row Python encode (incl.
    the O(S) selector matching); clones expand by a numpy gather. This is what
    keeps 100k-pod × hundreds-of-workloads encodes in seconds."""
    enc.register_pods(pods)
    p = len(pods)
    P = p_pad if p_pad is not None else round_up(p)
    R = len(enc.resources)
    S = selector_table_size(enc)

    reps: List[Pod] = []
    rep_of: Dict[Tuple, int] = {}
    inverse = np.empty(p, np.int32)
    for i, pod in enumerate(pods):
        sig = _pod_row_sig(pod)
        j = rep_of.get(sig)
        if j is None:
            j = len(reps)
            rep_of[sig] = j
            reps.append(pod)
        inverse[i] = j
    D = len(reps)

    def cap(f, minimum=1):
        return max((f(pod) for pod in reps), default=minimum) or minimum

    # Feature-axis floors cover typical specs so batches from different apps
    # (and capacity-search probes) share ONE jit shape family — distinct
    # (TERM, EXPR, ...) combos each trace their own multi-second graphs
    # otherwise. The axes are tiny relative to the [N]-wide work, so padding
    # costs ~nothing; round_up still grows past the floor for outliers.
    TERM = round_up(cap(lambda pd: len(pd.affinity.node_required)), 2)
    EXPR = round_up(
        cap(
            lambda pd: max(
                [len(t.match_expressions) for t in pd.affinity.node_required]
                + [
                    len(t.preference.match_expressions)
                    for t in pd.affinity.node_preferred
                ]
                + [0]
            )
        ),
        4,
    )
    VAL = round_up(
        cap(
            lambda pd: max(
                [
                    len(e.values)
                    for t in pd.affinity.node_required
                    for e in t.match_expressions
                ]
                + [
                    len(e.values)
                    for t in pd.affinity.node_preferred
                    for e in t.preference.match_expressions
                ]
                + [0]
            )
        ),
        4,
    )
    NS = round_up(cap(lambda pd: len(pd.node_selector)), 4)
    PREF = round_up(cap(lambda pd: len(pd.affinity.node_preferred)), 2)
    TOL = round_up(cap(lambda pd: len(pd.tolerations)), 4)
    C = round_up(cap(lambda pd: len(pd.spread_constraints)), 2)
    A = round_up(
        cap(
            lambda pd: len(pd.affinity.pod_required)
            + len(pd.affinity.anti_required)
            + len(pd.affinity.pod_preferred)
            + len(pd.affinity.anti_preferred)
        ),
        2,
    )
    vols = [pd.local_volumes() for pd in reps]
    SV = round_up(max((max(len(l), len(d)) for l, d in vols), default=1), 2)
    HP = round_up(cap(lambda pd: len(pd.host_ports)), 2)
    AT = anti_table_size(enc)

    b = PodBatch(
        req=np.zeros((D, R), np.float32),
        has_req=np.zeros(D, bool),
        node_name_id=np.zeros(D, np.int32),
        gpu_mem=np.zeros(D, np.float32),
        gpu_num=np.zeros(D, np.float32),
        sel_op=np.zeros((D, TERM, EXPR), np.int32),
        sel_key=np.zeros((D, TERM, EXPR), np.int32),
        sel_val=np.zeros((D, TERM, EXPR, VAL), np.int32),
        sel_num=np.zeros((D, TERM, EXPR), np.float32),
        has_terms=np.zeros(D, bool),
        ns_pair=np.zeros((D, NS), np.int32),
        pref_weight=np.zeros((D, PREF), np.float32),
        pref_op=np.zeros((D, PREF, EXPR), np.int32),
        pref_key=np.zeros((D, PREF, EXPR), np.int32),
        pref_val=np.zeros((D, PREF, EXPR, VAL), np.int32),
        pref_num=np.zeros((D, PREF, EXPR), np.float32),
        tol_key=np.zeros((D, TOL), np.int32),
        tol_val=np.zeros((D, TOL), np.int32),
        tol_exists=np.zeros((D, TOL), bool),
        tol_effect=np.zeros((D, TOL), np.int32),
        tol_valid=np.zeros((D, TOL), bool),
        spread_topo=np.full((D, C), -1, np.int32),
        spread_sel=np.zeros((D, C), np.int32),
        spread_skew=np.zeros((D, C), np.float32),
        spread_hard=np.zeros((D, C), bool),
        aff_topo=np.full((D, A), -1, np.int32),
        aff_sel=np.zeros((D, A), np.int32),
        aff_anti=np.zeros((D, A), bool),
        aff_required=np.zeros((D, A), bool),
        aff_weight=np.zeros((D, A), np.float32),
        lvm_req=np.zeros((D, SV), np.float32),
        lvm_vg=np.zeros((D, SV), np.int32),
        dev_req=np.zeros((D, SV), np.float32),
        dev_media_ssd=np.zeros((D, SV), bool),
        has_local=np.zeros(D, bool),
        match_sel=np.zeros((D, S), bool),
        owned_by_rs=np.zeros(D, bool),
        hp_pid=np.zeros((D, HP), np.int32),
        hp_wild=np.zeros((D, HP), bool),
        hp_ipid=np.zeros((D, HP), np.int32),
        match_anti=np.zeros((D, AT), bool),
        own_anti=np.zeros((D, AT), np.float32),
        valid=np.zeros(D, bool),
        keys=[pd.key for pd in pods],
    )

    for i, pod in enumerate(reps):
        b.valid[i] = True
        b.has_req[i] = bool(pod.requests)
        b.owned_by_rs[i] = pod.meta.owner_kind in ("ReplicaSet", "ReplicationController")
        for res, q in pod.requests.items():
            if res in enc.ignored_resources:
                continue  # extender-owned (factory.go:105-130), not fit-checked
            b.req[i, enc.resource_index(res)] = q / resource_scale(res)
        b.req[i, enc.resources.index("pods")] += 1.0  # each pod occupies a slot
        b.gpu_mem[i] = np.float32(pod.gpu_mem_request() / float(1 << 20))
        b.gpu_num[i] = float(pod.gpu_count_request())
        if pod.node_name:
            b.node_name_id[i] = enc.names.id(pod.node_name)
        for j, t in enumerate(pod.affinity.node_required[:TERM]):
            op, key, val, num = _encode_term_exprs(enc, t.match_expressions, EXPR, VAL)
            b.sel_op[i, j], b.sel_key[i, j], b.sel_val[i, j], b.sel_num[i, j] = op, key, val, num
        b.has_terms[i] = bool(pod.affinity.node_required)
        for j, (k, v) in enumerate(sorted(pod.node_selector.items())[:NS]):
            b.ns_pair[i, j] = enc.pair_id(k, v)
        for j, pref in enumerate(pod.affinity.node_preferred[:PREF]):
            b.pref_weight[i, j] = float(pref.weight)
            op, key, val, num = _encode_term_exprs(
                enc, pref.preference.match_expressions, EXPR, VAL
            )
            b.pref_op[i, j], b.pref_key[i, j], b.pref_val[i, j], b.pref_num[i, j] = (
                op, key, val, num,
            )
        for j, t in enumerate(pod.tolerations[:TOL]):
            b.tol_valid[i, j] = True
            b.tol_key[i, j] = enc.keys.id(t.key) if t.key else 0
            b.tol_val[i, j] = enc.vals.id(t.value) if t.value else enc.vals.id("")
            b.tol_exists[i, j] = t.operator == "Exists"
            b.tol_effect[i, j] = _EFFECTS.get(t.effect, 0)
        for j, c in enumerate(pod.spread_constraints[:C]):
            b.spread_topo[i, j] = enc.topo_index(c.topology_key) if c.topology_key else -1
            b.spread_sel[i, j] = enc.selector_id((pod.meta.namespace,), c.selector)
            b.spread_skew[i, j] = float(c.max_skew)
            b.spread_hard[i, j] = c.when_unsatisfiable == "DoNotSchedule"
        terms = (
            [(t, False, True, 0.0) for t in pod.affinity.pod_required]
            + [(t, True, True, 0.0) for t in pod.affinity.anti_required]
            + [(wt.term, False, False, float(wt.weight)) for wt in pod.affinity.pod_preferred]
            + [(wt.term, True, False, float(wt.weight)) for wt in pod.affinity.anti_preferred]
        )
        for j, (t, anti, required, weight) in enumerate(terms[:A]):
            b.aff_topo[i, j] = enc.topo_index(t.topology_key) if t.topology_key else -1
            b.aff_sel[i, j] = enc.selector_id(t.namespaces or (pod.meta.namespace,), t.selector)
            b.aff_anti[i, j] = anti
            b.aff_required[i, j] = required
            b.aff_weight[i, j] = weight
        b.match_sel[i] = match_vector(enc, pod)
        for j, (pid, wild, ipid) in enumerate(enc.port_ids(pod)[:HP]):
            b.hp_pid[i, j] = pid
            b.hp_wild[i, j] = wild
            b.hp_ipid[i, j] = ipid
        for t, (_k_idx, sel_id) in enumerate(enc.anti_terms):
            b.match_anti[i, t] = b.match_sel[i, sel_id]  # same SelectorEntry
        for aid in enc.anti_ids(pod):
            b.own_anti[i, aid] += 1.0
        lvm_vols, dev_vols = vols[i]
        b.has_local[i] = bool(lvm_vols or dev_vols)
        # Explicit-VG volumes are allocated before binpack volumes, each class
        # in annotation order (ProcessLVMPVCPredicate handles pvcsWithVG first,
        # algo/common.go:59-75); device volumes are sorted ascending by size —
        # the reference sorts each media class ascending before the greedy
        # match (CheckExclusiveResourceMeetsPVCSize, algo/common.go:291-294),
        # and a stable ascending sort of the union preserves per-media order.
        lvm_vols = sorted(lvm_vols, key=lambda x: not x.vg_name)
        for j, v in enumerate(lvm_vols[:SV]):
            b.lvm_req[i, j] = np.float32(v.size / float(1 << 20))
            b.lvm_vg[i, j] = enc.vgs.id(v.vg_name) if v.vg_name else 0
        for j, v in enumerate(sorted(dev_vols, key=lambda x: x.size)[:SV]):
            b.dev_req[i, j] = np.float32(v.size / float(1 << 20))
            b.dev_media_ssd[i, j] = v.media_type == "ssd"

    # Expand representative rows to the full padded batch by gather.
    expanded = {}
    for f in b.__dataclass_fields__:
        if f == "keys":
            continue
        arr = getattr(b, f)
        out = np.zeros((P,) + arr.shape[1:], arr.dtype)
        if f in ("spread_topo", "aff_topo"):
            out[:] = -1  # pad rows keep the inactive sentinel
        if p:
            out[:p] = arr[inverse]
        expanded[f] = out
    return PodBatch(keys=b.keys, **expanded)


def host_allocate_gpu(free: np.ndarray, mem: float, num: int) -> Optional[List[int]]:
    """Host mirror of GpuNodeInfo.AllocateGpuId (gpunodeinfo.go:232-290):
    single-GPU pods take the tightest-fitting device (min free >= mem, ties to
    the lowest id); multi-GPU pods run the two-pointer greedy that may pack
    several shares onto one device. Returns the device-id list or None.
    `free` is mutated on success (used MiB subtracted)."""
    if mem <= 0 or num <= 0:
        return None
    if num == 1:
        best = -1
        best_free = np.float32(0)
        for d in range(len(free)):
            if free[d] >= mem and (best < 0 or free[d] < best_free):
                best, best_free = d, free[d]
        if best < 0:
            return None
        free[best] -= np.float32(mem)
        return [best]
    ids: List[int] = []
    d = 0
    while d < len(free) and len(ids) < num:
        if free[d] >= mem:
            ids.append(d)
            free[d] -= np.float32(mem)
        else:
            d += 1
    if len(ids) < num:
        return None
    return ids


def aggregate_gpu_usage(
    nodes: Sequence[Node], placed: Sequence[Tuple[Pod, str]]
) -> Dict[str, np.ndarray]:
    """Per-node used-MiB-per-device arrays for already-bound GPU pods.

    Only pods carrying a gpu-index annotation contribute, and only to devices
    that exist (parity: addOrUpdatePod skips pods whose annotation is missing
    or unparseable, gpunodeinfo.go:122-140). The scheduler cache skips
    Succeeded/Failed pods (deviceinfo.go:45-67)."""
    by_name = {nd.name: nd for nd in nodes}
    used: Dict[str, np.ndarray] = {}
    for pod, node_name in placed:
        mem_bytes = pod.gpu_mem_request()
        if mem_bytes <= 0 or pod.phase in ("Succeeded", "Failed"):
            continue
        nd = by_name.get(node_name)
        if nd is None or nd.gpu_count() <= 0:
            continue
        ids = pod.gpu_index_ids()
        if not ids:
            continue
        mem = np.float32(mem_bytes / float(1 << 20))
        arr = used.setdefault(node_name, np.zeros(nd.gpu_count(), np.float32))
        for d in ids:
            if 0 <= d < len(arr):
                arr[d] += mem
    return used


def aggregate_usage(placed: Sequence[Tuple[Pod, str]]) -> Dict[str, Dict[str, int]]:
    """Canonical per-node request totals of already-bound pods, including the
    implicit 'pods' slot each pod occupies — feed this to encode_nodes so
    NodeResourcesFit sees both resource and pod-count pressure."""
    usage: Dict[str, Dict[str, int]] = {}
    for pod, node_name in placed:
        tot = usage.setdefault(node_name, {})
        for res, q in pod.requests.items():
            tot[res] = tot.get(res, 0) + q
        tot["pods"] = tot.get("pods", 0) + 1
    return usage


def selector_table_size(enc: Encoder) -> int:
    """Bucketed S axis (sel_counts rows / match_sel columns): registering one
    more selector must not change every kernel's shape — pad rows hold zero
    counts and False matches, which every consumer treats as inert."""
    return round_up(max(len(enc.selectors), 1), 8)


def anti_table_size(enc: Encoder) -> int:
    """Bucketed AT axis (anti_counts rows / match_anti columns / anti_topo);
    pad rows carry topo -1, which deactivates them in pod_affinity_mask."""
    return round_up(max(len(enc.anti_terms), 1), 2)


def port_table_sizes(enc: Encoder) -> Tuple[int, int]:
    """(PID, PIP) axis sizes for the port count tables. Row 0 is the pad row
    (vocab ids are 1-based), so sizes are len+1 rounded for bucket stability."""
    return round_up(len(enc.ports) + 1, 2), round_up(len(enc.port_ips) + 1, 2)


def initial_port_counts(
    enc: Encoder,
    table: NodeTable,
    placed: Sequence[Tuple[Pod, str]],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(port_any f32[PID,N], port_wild f32[PID,N], port_ipc f32[PIP,N]):
    host-port usage counts of already-bound pods, per node. port_any counts
    every use of a (protocol, port) pair; port_wild only wildcard-hostIP uses;
    port_ipc counts per specific (protocol, port, hostIP) triple."""
    PID, PIP = port_table_sizes(enc)
    port_any = np.zeros((PID, table.n), np.float32)
    port_wild = np.zeros((PID, table.n), np.float32)
    port_ipc = np.zeros((PIP, table.n), np.float32)
    node_index = {name: i for i, name in enumerate(table.names)}
    for pod, node_name in placed:
        ni = node_index.get(node_name)
        if ni is None or not pod.host_ports:
            continue
        for pid, wild, ipid in enc.port_ids(pod):
            if pid < PID:
                port_any[pid, ni] += 1.0
                if wild:
                    port_wild[pid, ni] += 1.0
            if not wild and ipid < PIP:
                port_ipc[ipid, ni] += 1.0
    return port_any, port_wild, port_ipc


def initial_anti_counts(
    enc: Encoder,
    table: NodeTable,
    placed: Sequence[Tuple[Pod, str]],
) -> np.ndarray:
    """anti_counts f32[AT,N]: per (required-anti-affinity term, node) count of
    already-placed pods carrying the term. Bound pods' terms must have been
    registered (register_pods) before this is called."""
    AT = anti_table_size(enc)
    counts = np.zeros((AT, table.n), np.float32)
    node_index = {name: i for i, name in enumerate(table.names)}
    for pod, node_name in placed:
        ni = node_index.get(node_name)
        if ni is None:
            continue
        for aid in enc.anti_ids(pod):
            counts[aid, ni] += 1.0
    return counts


def match_vector(enc: Encoder, pod: Pod) -> np.ndarray:
    """bool[S] — which registered selectors match this pod. Memoized by the
    pod's (namespace, labels) signature: workload replicas are label-identical
    clones, so a 100k-pod cluster hits the Python matcher only once per
    distinct workload instead of pods x selectors times (the reference's
    per-pod listers pay the full product; SURVEY §5.7 scale strategy)."""
    S = selector_table_size(enc)
    sig = (pod.meta.namespace, tuple(sorted(pod.meta.labels.items())))
    cached = enc._match_cache.get(sig)
    if cached is not None and cached.shape[0] == S:
        return cached
    vec = np.zeros(S, bool)
    for s, entry in enumerate(enc.selectors):
        vec[s] = entry.matches(pod)
    enc._match_cache[sig] = vec
    return vec


def initial_selector_counts(
    enc: Encoder,
    table: NodeTable,
    placed: Sequence[Tuple[Pod, str]],
) -> np.ndarray:
    """sel_counts f32[S,N]: per (selector, node) count of already-placed pods
    matching the selector. Seeded from existing cluster pods; maintained on
    device as the scan carry afterwards."""
    S = selector_table_size(enc)
    counts = np.zeros((S, table.n), np.float32)
    node_index = {name: i for i, name in enumerate(table.names)}
    for pod, node_name in placed:
        ni = node_index.get(node_name)
        if ni is None:
            continue
        counts[:, ni] += match_vector(enc, pod)
    return counts
