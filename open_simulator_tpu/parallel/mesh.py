"""Multi-chip scaling: shard the node axis over a device mesh.

The reference's entire "distributed backend" is a 16-goroutine pool with √n
chunking (`vendor/.../scheduler/internal/parallelize/parallelism.go:26-57`).
The TPU equivalent shards the node table across devices along the node axis:
filter masks and score kernels run on local node shards, and the argmax/
reductions (host selection, domain counts, min-max normalization) become XLA
collectives over ICI inserted automatically by GSPMD — we only annotate
shardings, per the scaling-book recipe (mesh → shardings → let XLA insert
collectives).

Pods are replicated (each step's pod features are tiny); the carry's free
matrix is sharded with the nodes, and sel_counts shards along its node axis.

The multi-scenario sweep shards the OTHER way: lanes of the vmapped commit
engine (ops.fast.schedule_scenarios) are independent, so the scenario axis
is embarrassingly parallel — `scenario_mesh` / `shard_scenarios` split the
stacked carry, per-lane valid masks and weight rows across devices along
axis 0 with the node tensors replicated, and each device runs its lanes
with zero cross-device traffic until the host gathers results.

Both directions compose: `product_mesh_2d` builds an explicit 2-D
(scenarios, nodes) mesh and `shard_scenarios_2d` lays the sweep out over
it — lanes split over the scenario axis AND every node-axis tensor (the
shared NodeStatic and the per-lane carry planes) splits over the node
axis, so a 100k-node table occupies 1/n_devices of each device's HBM
instead of being replicated per device. The per-node filter/score kernels
run on local (lane, node) shards; argmax/min-max/domain reductions lower
to collectives over the node axis only, inserted by GSPMD.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.kernels import Carry, NodeStatic, PodRow, schedule_step

NODE_AXIS = "nodes"
SCENARIO_AXIS = "scenarios"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(devices, (NODE_AXIS,))


def product_mesh(n_devices: int = 0) -> Optional[Mesh]:
    """Mesh for the product engine: first n_devices (or all when 0) of
    jax.devices(). Returns None for n_devices==1 — single-device runs skip
    sharding entirely."""
    devices = jax.devices()
    if n_devices < 0:
        raise ValueError(f"--devices must be >= 0, got {n_devices}")
    if n_devices == 1 or len(devices) == 1:
        return None
    if n_devices > 0:
        if n_devices > len(devices):
            raise ValueError(
                f"--devices {n_devices} requested but only "
                f"{len(devices)} JAX devices are visible"
            )
        devices = devices[:n_devices]
    return make_mesh(devices)


def node_sharding(mesh: Mesh) -> NodeStatic:
    """PartitionSpecs for each NodeStatic leaf (node axis sharded)."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))
    return NodeStatic(
        alloc=s(NODE_AXIS, None),
        label_pair=s(NODE_AXIS, None),
        label_key=s(NODE_AXIS, None),
        label_num=s(NODE_AXIS, None),
        taint_key=s(NODE_AXIS, None),
        taint_val=s(NODE_AXIS, None),
        taint_effect=s(NODE_AXIS, None),
        name_id=s(NODE_AXIS),
        unsched=s(NODE_AXIS),
        avoid_pods=s(NODE_AXIS),
        topo=s(NODE_AXIS, None),
        valid=s(NODE_AXIS),
        gpu_total=s(NODE_AXIS, None),
        vg_cap=s(NODE_AXIS, None),
        vg_name=s(NODE_AXIS, None),
        dev_cap=s(NODE_AXIS, None),
        dev_ssd=s(NODE_AXIS, None),
        has_storage=s(NODE_AXIS),
        domain_key=s(None),      # small, replicated
        topo_onehot=s(None, None, NODE_AXIS),
        unsched_key_id=s(),
        empty_val_id=s(),
        anti_topo=s(None),       # small, replicated
    )


def carry_sharding(mesh: Mesh) -> Carry:
    s = lambda *spec: NamedSharding(mesh, P(*spec))
    return Carry(
        free=s(NODE_AXIS, None),
        sel_counts=s(None, NODE_AXIS),
        gpu_free=s(NODE_AXIS, None),
        vg_free=s(NODE_AXIS, None),
        dev_free=s(NODE_AXIS, None),
        port_any=s(None, NODE_AXIS),
        port_wild=s(None, NODE_AXIS),
        port_ipc=s(None, NODE_AXIS),
        anti_counts=s(None, NODE_AXIS),
    )


def replicated(mesh: Mesh, tree):
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda _: sh, tree)


def shard_state(mesh: Mesh, ns: NodeStatic, carry: Carry):
    """device_put the cluster state onto the mesh with node-axis sharding."""
    ns_sh = jax.device_put(ns, node_sharding(mesh))
    carry_sh = jax.device_put(carry, carry_sharding(mesh))
    return ns_sh, carry_sh


def scenario_mesh(mesh: Mesh) -> Mesh:
    """The same devices as `mesh`, re-axed for the multi-scenario sweep:
    one 1-D axis named SCENARIO_AXIS. A separate Mesh object is required —
    a jit call must see every committed input on ONE mesh, and the sweep's
    lanes shard where the serial engine's nodes do."""
    return Mesh(list(mesh.devices.flat), (SCENARIO_AXIS,))


def shard_scenarios(
    mesh: Mesh,
    ns: NodeStatic,
    carry_s: Carry,
    valid_s: jnp.ndarray,
    weights_s: jnp.ndarray,
):
    """device_put the stacked sweep state onto `mesh` (a scenario_mesh):
    every [S, ...] tensor splits on its lane axis, the shared node tensors
    replicate. Committed shardings make GSPMD compile schedule_scenarios
    with the lane split for real (and the donated carry keeps it: donated
    buffers alias outputs shard for shard). Callers must ensure S divides
    the device count evenly — scenario_bucket pads S to a multiple of 8,
    so 2/4/8-device meshes always divide; check before calling for other
    shapes."""
    lane = NamedSharding(mesh, P(SCENARIO_AXIS))
    ns_sh = jax.device_put(ns, replicated(mesh, ns))
    carry_sh = jax.device_put(carry_s, jax.tree.map(lambda _: lane, carry_s))
    valid_sh = jax.device_put(valid_s, lane)
    weights_sh = jax.device_put(weights_s, lane)
    return ns_sh, carry_sh, valid_sh, weights_sh


def product_mesh_2d(
    scenario_devices: int, node_devices: int
) -> Optional[Mesh]:
    """An explicit 2-D (SCENARIO_AXIS, NODE_AXIS) mesh over the first
    scenario_devices x node_devices of jax.devices(). The multi-scenario
    sweep shards lanes over the first axis and the node tables over the
    second (shard_scenarios_2d); the serial engine's node_sharding /
    carry_sharding specs name only NODE_AXIS, so they compose with this
    mesh unchanged (unnamed axes replicate). Returns None for the 1x1
    degenerate mesh — single-device runs skip sharding entirely."""
    import numpy as np

    if scenario_devices < 1 or node_devices < 1:
        raise ValueError(
            f"mesh axes must be >= 1, got {scenario_devices}x{node_devices}"
        )
    want = scenario_devices * node_devices
    if want == 1:
        return None
    devices = jax.devices()
    if want > len(devices):
        raise ValueError(
            f"{scenario_devices}x{node_devices} mesh needs {want} devices "
            f"but only {len(devices)} JAX devices are visible"
        )
    grid = np.array(devices[:want]).reshape(scenario_devices, node_devices)
    return Mesh(grid, (SCENARIO_AXIS, NODE_AXIS))


def shard_scenarios_2d(
    mesh: Mesh,
    ns: NodeStatic,
    carry_s: Carry,
    valid_s: jnp.ndarray,
    weights_s: jnp.ndarray,
):
    """device_put the stacked sweep state onto a 2-D (scenarios, nodes)
    mesh: [S, ...] tensors split on the lane axis AND their node axis, the
    shared NodeStatic splits on its node axis only (node_sharding's specs
    name NODE_AXIS; the unnamed SCENARIO_AXIS replicates it across lane
    rows). Callers must ensure S divides the scenario-axis size and the
    padded node axis divides the node-axis size — node_bucket keeps N a
    multiple of 64, so 2/4/8-way node splits always divide."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))
    carry_sh = jax.device_put(
        carry_s,
        Carry(
            free=s(SCENARIO_AXIS, NODE_AXIS, None),
            sel_counts=s(SCENARIO_AXIS, None, NODE_AXIS),
            gpu_free=s(SCENARIO_AXIS, NODE_AXIS, None),
            vg_free=s(SCENARIO_AXIS, NODE_AXIS, None),
            dev_free=s(SCENARIO_AXIS, NODE_AXIS, None),
            port_any=s(SCENARIO_AXIS, None, NODE_AXIS),
            port_wild=s(SCENARIO_AXIS, None, NODE_AXIS),
            port_ipc=s(SCENARIO_AXIS, None, NODE_AXIS),
            anti_counts=s(SCENARIO_AXIS, None, NODE_AXIS),
        ),
    )
    ns_sh = jax.device_put(ns, node_sharding(mesh))
    valid_sh = jax.device_put(valid_s, s(SCENARIO_AXIS, NODE_AXIS))
    weights_sh = jax.device_put(weights_s, s(SCENARIO_AXIS))
    return ns_sh, carry_sh, valid_sh, weights_sh


def hbm_bytes_per_device(*trees) -> dict:
    """Bytes resident per device for the given pytrees — real or planned.

    Materialized jax.Arrays are summed over each leaf's addressable
    shards, so a sharded layout reports its true per-device footprint
    while a replicated layout reports the full tensor on every device.
    Leaves that are not materialized yet — ``jax.ShapeDtypeStruct`` avals
    (with or without a sharding), numpy arrays — fall back to the static
    shape-arithmetic estimator from ``analysis.budget``, which the
    preflight auditor continuously cross-checks against
    ``compiled.memory_analysis()``; the same call therefore answers both
    "what is resident now" and "what will this tree cost once placed".
    Snapshots into the osim_hbm_bytes_per_device gauge and returns
    {device: bytes}."""
    from ..analysis.budget import leaf_bytes_by_device
    from ..utils import metrics

    default_dev = str(jax.devices()[0])
    out: dict = {}
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            if hasattr(leaf, "addressable_shards"):
                for shard in leaf.addressable_shards:
                    key = str(shard.device)
                    out[key] = out.get(key, 0) + int(shard.data.nbytes)
            else:
                for key, n in leaf_bytes_by_device(
                    leaf, default_device=default_dev
                ).items():
                    out[key] = out.get(key, 0) + n
    for dev, nbytes in sorted(out.items()):
        metrics.HBM_BYTES_PER_DEVICE.set(nbytes, device=dev)
    return out


def sharded_schedule_batch(mesh: Mesh):
    """jit-compiled sharded batch scheduler bound to a mesh.

    Sharding propagation: each scan step's masks/scores compute on node shards;
    the global argmax, min/max normalizations and domain-count scatters lower
    to ICI collectives chosen by GSPMD.
    """

    def fn(ns: NodeStatic, carry: Carry, pods: PodRow, weights: jnp.ndarray):
        def step(c, pod):
            return schedule_step(ns, weights, c, pod)

        final_carry, (nodes, reasons, gpu_take, vg_take, dev_take) = jax.lax.scan(
            step, carry, pods
        )
        return final_carry, nodes, reasons, gpu_take, vg_take, dev_take

    rep = NamedSharding(mesh, P())
    return jax.jit(
        fn,
        in_shardings=(
            node_sharding(mesh),
            carry_sharding(mesh),
            None,     # pods: let XLA replicate
            rep,      # weights
        ),
        out_shardings=(carry_sharding(mesh), rep, rep, rep, rep, rep),
    )
