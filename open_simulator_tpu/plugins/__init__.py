"""Out-of-tree scheduler plugins: the extensible-algorithm hook.

Parity: the reference accepts an `extraRegistry` of user plugin factories and
hands it to scheduler.New (WithFrameworkOutOfTreeRegistry,
`/root/reference/pkg/simulator/simulator.go:190-203`; the README's
"extensible scheduling algorithm" feature). The TPU-native equivalent is a
registry of jax-traceable device kernels over the cluster-state tensors:

  - a Filter plugin is `fn(ns: NodeStatic, carry: Carry, pod: PodRow) ->
    bool[N]` (True = node feasible); failures report as "rejected by an
    out-of-tree filter plugin" (kernels.F_EXTRA).
  - a Score plugin is `fn(ns, carry, pod) -> f32[N]`, added to the weighted
    in-tree sum at its configured weight (normalize inside your kernel if you
    want 0..100 semantics).

Plugins see exactly the state the in-tree kernels see: NodeStatic (immutable
node features), Carry (free resources, selector/anti-affinity counts, GPU and
storage state, host-port tables) and the encoded PodRow. They run inside the
scheduling jit, so they must be pure and shape-static — standard jax rules.

Example:

    from open_simulator_tpu.plugins import DevicePlugin
    from open_simulator_tpu.engine.simulator import simulate

    def spare_cpu_filter(ns, carry, pod):
        return carry.free[:, 0] >= 2 * pod.req[0]   # keep 2x headroom

    plug = DevicePlugin(name="headroom", filter_fn=spare_cpu_filter)
    simulate(cluster, apps, plugins=[plug])

Because an out-of-tree plugin may read the carry arbitrarily, batches that
carry plugins schedule through the per-pod grouped path (the trajectory fast
path assumes node-local state evolution; ops/fast.py docstring).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Sequence, Tuple


class DevicePlugin(NamedTuple):
    """One out-of-tree plugin: a Filter kernel, a Score kernel, or both."""
    name: str
    filter_fn: Optional[Callable] = None   # (ns, carry, pod) -> bool[N]
    score_fn: Optional[Callable] = None    # (ns, carry, pod) -> f32[N]
    weight: float = 1.0


def split_registry(
    plugins: Sequence[DevicePlugin],
) -> Tuple[tuple, tuple]:
    """(extra_filters, extra_scores) tuples for the kernel entry points.
    Tuples (hashable, order-stable) because they ride as static jit args."""
    filters = tuple(p.filter_fn for p in plugins if p.filter_fn is not None)
    scores = tuple(
        (p.score_fn, float(p.weight))
        for p in plugins
        if p.score_fn is not None
    )
    return filters, scores
