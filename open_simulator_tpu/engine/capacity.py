"""Capacity planning: the add-node search.

Parity: the reference's interactive loop re-simulates from scratch after each
manually-added node (`pkg/apply/apply.go:197-259`) and gates success on average
utilization limits from env MaxCPU/MaxMemory/MaxVG
(`satisfyResourceSetting`, `apply.go:689-775`).

TPU-native upgrade: simulation is cheap enough to *search* — exponential probe
then bisection on the clone count — so `plan_capacity` finds the minimum number
of new nodes automatically instead of asking a human after every step. The
interactive mode is kept for CLI parity.
"""

from __future__ import annotations

import copy
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..core.objects import LABEL_NEW_NODE, Node
from .simulator import AppResource, ClusterResource, SimulateResult, simulate

def new_fake_nodes(template: Node, count: int) -> List[Node]:
    """Clone the candidate node `count` times as simon-NNNNN with the new-node
    label (parity: utils.NewFakeNodes, utils.go:885-915 — the reference uses
    random 5-char suffixes; we use ordinals so names are guaranteed unique at
    any count and identical across capacity-search probes)."""
    out = []
    for i in range(count):
        node = copy.deepcopy(template)
        node.meta.name = f"simon-{i:05d}"
        node.meta.labels["kubernetes.io/hostname"] = node.meta.name
        node.meta.labels[LABEL_NEW_NODE] = "true"
        out.append(node)
    return out


def max_resource_limits() -> Tuple[float, float]:
    """Env knobs MaxCPU / MaxMemory / MaxVG as percentages
    (pkg/type/const.go:29-31); 100 means no limit."""

    def read(name: str) -> float:
        try:
            v = float(os.environ.get(name, "100"))
        except ValueError:
            return 100.0
        return v if 0 < v <= 100 else 100.0

    return read("MaxCPU"), read("MaxMemory"), read("MaxVG")


def satisfy_resource_setting(result: SimulateResult) -> bool:
    """Cluster-average requested/allocatable must stay under MaxCPU/MaxMemory,
    and cluster-total VG requested/capacity under MaxVG (apply.go:689-775 —
    occupancy rates truncate to whole percents and fail only when strictly
    above the limit, matching the reference's int() + '>' comparison)."""
    max_cpu, max_mem, max_vg = max_resource_limits()
    if max_cpu >= 100 and max_mem >= 100 and max_vg >= 100:
        return True
    total_cpu = total_cpu_req = total_mem = total_mem_req = 0
    for st in result.node_status:
        total_cpu += st.node.allocatable.get("cpu", 0)
        total_mem += st.node.allocatable.get("memory", 0)
        for pod in st.pods:
            total_cpu_req += pod.requests.get("cpu", 0)
            total_mem_req += pod.requests.get("memory", 0)
    cpu_ok = total_cpu == 0 or int(100.0 * total_cpu_req / total_cpu) <= max_cpu
    mem_ok = total_mem == 0 or int(100.0 * total_mem_req / total_mem) <= max_mem
    # VG occupancy from the post-simulation storage state (the reference reads
    # the bind-updated node annotations; result.storage is that decode)
    vg_cap = vg_req = 0
    for st_name, storage in result.storage.items():
        for vg in storage.vgs:
            vg_cap += vg.capacity
            vg_req += vg.requested
    vg_ok = vg_cap == 0 or int(100.0 * vg_req / vg_cap) <= max_vg
    return cpu_ok and mem_ok and vg_ok


@dataclass
class CapacityPlan:
    nodes_added: int
    result: SimulateResult
    attempts: int


def _probe(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    template: Node,
    k: int,
    weights: Optional[dict],
    use_greed: bool = False,
    mesh=None,
) -> SimulateResult:
    trial = ClusterResource(
        nodes=list(cluster.nodes) + new_fake_nodes(template, k),
        pods=list(cluster.pods),
        daemonsets=list(cluster.daemonsets),
        others=dict(cluster.others),
    )
    return simulate(trial, apps, weights=weights, use_greed=use_greed, mesh=mesh)


def plan_capacity(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    new_node: Node,
    max_new_nodes: int = 1 << 14,
    weights: Optional[dict] = None,
    use_greed: bool = False,
    mesh=None,
) -> Optional[CapacityPlan]:
    """Minimum clones of `new_node` so every pod schedules and utilization
    gates pass. Returns None if even max_new_nodes doesn't suffice."""

    attempts = 0

    def good(res: SimulateResult) -> bool:
        return not res.unscheduled and satisfy_resource_setting(res)

    base = _probe(cluster, apps, new_node, 0, weights, use_greed, mesh)
    attempts += 1
    if good(base):
        return CapacityPlan(0, base, attempts)

    # exponential growth to bracket, then bisect
    lo, hi = 0, 1
    hi_result = None
    while hi <= max_new_nodes:
        hi_result = _probe(cluster, apps, new_node, hi, weights, use_greed, mesh)
        attempts += 1
        if good(hi_result):
            break
        lo = hi
        hi *= 2
    else:
        return None
    best, best_result = hi, hi_result
    while lo + 1 < hi:
        mid = (lo + hi) // 2
        res = _probe(cluster, apps, new_node, mid, weights, use_greed, mesh)
        attempts += 1
        if good(res):
            hi, best, best_result = mid, mid, res
        else:
            lo = mid
    return CapacityPlan(best, best_result, attempts)
