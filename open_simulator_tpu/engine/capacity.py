"""Capacity planning: the add-node search.

Parity: the reference's interactive loop re-simulates from scratch after each
manually-added node (`pkg/apply/apply.go:197-259`) and gates success on average
utilization limits from env MaxCPU/MaxMemory/MaxVG
(`satisfyResourceSetting`, `apply.go:689-775`).

TPU-native upgrade: simulation is cheap enough to *search* — exponential probe
then bisection on the clone count — so `plan_capacity` finds the minimum number
of new nodes automatically instead of asking a human after every step. The
interactive mode is kept for CLI parity.
"""

from __future__ import annotations

import copy
import os
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..core.objects import LABEL_NEW_NODE, Node
from ..utils import metrics
from ..utils.tracing import span
from .simulator import (
    AppResource,
    ClusterResource,
    Scenario,
    ScenarioOutcome,
    SimulateResult,
    Simulator,
    batch_ineligible_reason,
    simulate,
)

# Batched-sweep lane shaping: the exponential ladder probes LADDER_LANES
# doubling counts per device call; bracket refinement evaluates up to
# SWEEP_LANES interior candidates per call. Both match the scenario bucket
# (ops.fast.SCENARIO_BUCKET) exactly so every phase pads to S=8 — together
# with the refine phase reusing the ladder's node bucket, the entire
# batched search runs one compiled program. A bracket of width ≤ 8 closes
# exactly in one refine call; wider brackets narrow ~9x per call.
LADDER_LANES = 8
SWEEP_LANES = 8

def new_fake_nodes(template: Node, count: int, start: int = 0) -> List[Node]:
    """Clone the candidate node `count - start` times as simon-NNNNN (ordinals
    start..count-1) with the new-node label (parity: utils.NewFakeNodes,
    utils.go:885-915 — the reference uses random 5-char suffixes; we use
    ordinals so names are guaranteed unique at any count and identical across
    capacity-search probes). `start` lets the batched sweep's trial cache
    extend an existing clone list without re-deepcopying the prefix.

    Every clone carries a shared `_stamp_token`: identity keying for the
    template-stamping encode (ops.encode), in the spirit of _pod_row_sig's
    id() keys — clones of one template are content-equal except name and
    hostname BY CONSTRUCTION (this deepcopy), so the encoder can group them
    without recomputing content signatures. The token is minted once per
    template object and must only ever be set on fresh deepcopies; code
    that mutates a clone's labels or taints afterwards must delete the
    attribute (unschedulable/storage/usage drift is already covered by the
    encoder's token signature)."""
    token = template.__dict__.get("_clone_token")
    if token is None:
        token = object()
        template.__dict__["_clone_token"] = token
    out = []
    for i in range(start, count):
        node = copy.deepcopy(template)
        node.meta.name = f"simon-{i:05d}"
        node.meta.labels["kubernetes.io/hostname"] = node.meta.name
        node.meta.labels[LABEL_NEW_NODE] = "true"
        node.__dict__.pop("_clone_token", None)  # don't inherit minting state
        node.__dict__["_stamp_token"] = token
        out.append(node)
    return out


class _TrialReuse:
    """Per-plan reuse of the batched sweep's trial state across device calls.

    Two layers. (1) Fake-node clones are deepcopied once and grown
    incrementally (`fakes`) instead of re-cloned per sweep. (2) Within a
    ladder rung (same n_pad bucket, see ops.encode.node_bucket), the previous
    sweep's encoder and node table are reused: a sweep needing fewer clones
    clears the surplus rows back to pad values, one needing more encodes only
    the new rows — clear_node_row/encode_node_into deltas, never a full
    re-encode. Crossing a rung drops the cache (the table's node axis must be
    reallocated), and the fresh encode is template-stamped, so even that is
    cheap. Rows are byte-identical to a from-scratch encode by construction
    (clear_node_row resets to exactly the pad values encode_nodes allocates);
    the shared encoder keeps ids consistent for every lane."""

    def __init__(self, template: Node, n_base: int) -> None:
        self._template = template
        self.n_base = n_base
        self._fakes: List[Node] = []
        self.enc = None
        self.table = None
        self.encoded = 0  # real rows currently encoded (n_base + clones)
        self.n_pad = 0
        self.rungs_touched: set = set()

    def fakes(self, count: int) -> List[Node]:
        if count > len(self._fakes):
            self._fakes.extend(
                new_fake_nodes(self._template, count, start=len(self._fakes))
            )
        return self._fakes[:count]

    def preencoded(self, max_count: int, n_pad: int):
        """(enc, table) delta-updated for a trial of n_base + max_count
        nodes at this rung, or None when the rung changed (full re-encode)."""
        from ..ops.encode import clear_node_row, encode_node_into

        if self.table is None or n_pad != self.n_pad:
            return None
        want = self.n_base + max_count
        if want > n_pad:
            return None
        table = self.table
        if want < self.encoded:
            for i in range(want, self.encoded):
                clear_node_row(table, i)
            del table.names[want:]
        elif want > self.encoded:
            grown = self.fakes(max_count)[self.encoded - self.n_base:]
            for i, nd in enumerate(grown, start=self.encoded):
                clear_node_row(table, i)
                encode_node_into(self.enc, table, i, nd, {}, {})
                table.names.append(nd.name)
        self.encoded = want
        return self.enc, table

    def capture(self, sim, n_real: int, n_pad: int) -> None:
        if sim._table is None:
            return
        self.enc = sim.enc
        self.table = sim._table
        self.encoded = n_real
        self.n_pad = n_pad


def max_resource_limits() -> Tuple[float, float]:
    """Env knobs MaxCPU / MaxMemory / MaxVG as percentages
    (pkg/type/const.go:29-31); 100 means no limit."""

    def read(name: str) -> float:
        try:
            v = float(os.environ.get(name, "100"))
        except ValueError:
            return 100.0
        return v if 0 < v <= 100 else 100.0

    return read("MaxCPU"), read("MaxMemory"), read("MaxVG")


def satisfy_resource_setting(result: SimulateResult) -> bool:
    """Cluster-average requested/allocatable must stay under MaxCPU/MaxMemory,
    and cluster-total VG requested/capacity under MaxVG (apply.go:689-775 —
    occupancy rates truncate to whole percents and fail only when strictly
    above the limit, matching the reference's int() + '>' comparison)."""
    max_cpu, max_mem, max_vg = max_resource_limits()
    if max_cpu >= 100 and max_mem >= 100 and max_vg >= 100:
        return True
    total_cpu = total_cpu_req = total_mem = total_mem_req = 0
    for st in result.node_status:
        total_cpu += st.node.allocatable.get("cpu", 0)
        total_mem += st.node.allocatable.get("memory", 0)
        for pod in st.pods:
            total_cpu_req += pod.requests.get("cpu", 0)
            total_mem_req += pod.requests.get("memory", 0)
    cpu_ok = total_cpu == 0 or int(100.0 * total_cpu_req / total_cpu) <= max_cpu
    mem_ok = total_mem == 0 or int(100.0 * total_mem_req / total_mem) <= max_mem
    # VG occupancy from the post-simulation storage state (the reference reads
    # the bind-updated node annotations; result.storage is that decode)
    vg_cap = vg_req = 0
    for st_name, storage in result.storage.items():
        for vg in storage.vgs:
            vg_cap += vg.capacity
            vg_req += vg.requested
    vg_ok = vg_cap == 0 or int(100.0 * vg_req / vg_cap) <= max_vg
    return cpu_ok and mem_ok and vg_ok


def satisfy_outcome(out: ScenarioOutcome) -> bool:
    """satisfy_resource_setting over a verdict-mode lane's totals — the same
    int() truncation and strict '>' comparison, fed by ScenarioOutcome sums
    that Simulator._scenario_outcomes builds to mirror exactly what
    satisfy_resource_setting would read off the materialized result."""
    max_cpu, max_mem, max_vg = max_resource_limits()
    if max_cpu >= 100 and max_mem >= 100 and max_vg >= 100:
        return True
    cpu_ok = (
        out.cpu_alloc == 0
        or int(100.0 * out.cpu_req / out.cpu_alloc) <= max_cpu
    )
    mem_ok = (
        out.mem_alloc == 0
        or int(100.0 * out.mem_req / out.mem_alloc) <= max_mem
    )
    vg_ok = out.vg_cap == 0 or int(100.0 * out.vg_req / out.vg_cap) <= max_vg
    return cpu_ok and mem_ok and vg_ok


def _good_outcome(out: ScenarioOutcome) -> bool:
    """The batched analog of plan_capacity's good(): everything scheduled and
    the utilization gates pass."""
    return out.unscheduled == 0 and satisfy_outcome(out)


@dataclass
class CapacityPlan:
    nodes_added: int
    result: SimulateResult
    attempts: int
    # probes re-run because a transient extender failure (not a scheduling
    # verdict) left pods unscheduled — nonzero means the search ran degraded
    retries: int = 0
    # batched (vmapped multi-scenario) device calls the search issued; 0 on
    # the serial bisection path
    batched_calls: int = 0


class _TransientTrialError(Exception):
    """A capacity probe left pods unscheduled because of a transient extender
    failure (UnscheduledPod.transient), not a scheduling verdict. Carries the
    result so an exhausted retry can still return it honestly."""

    def __init__(self, result: SimulateResult, reason: str) -> None:
        super().__init__(reason)
        self.result = result


def _probe(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    template: Node,
    k: int,
    weights: Optional[dict],
    use_greed: bool = False,
    mesh=None,
    n_pad: Optional[int] = None,
    profiles=None,
    expand_cache: Optional[dict] = None,
    extenders=None,
) -> SimulateResult:
    from ..durable.watchdog import call_deadline_s, guarded_call

    trial = ClusterResource(
        nodes=list(cluster.nodes) + new_fake_nodes(template, k),
        pods=list(cluster.pods),
        daemonsets=list(cluster.daemonsets),
        others=dict(cluster.others),
    )
    metrics.CAPACITY_PROBES.inc()
    with span("capacity-probe", nodes_added=k):
        # OSIM_CALL_DEADLINE_S>0 puts a host-side watchdog around the
        # blocking compile/execute (a wedged device call raises
        # DeadlineExceeded instead of hanging the sweep); 0 runs inline.
        return guarded_call(
            "capacity-probe",
            lambda: simulate(
                trial, apps, weights=weights, use_greed=use_greed, mesh=mesh,
                n_pad=n_pad, profiles=profiles, expand_cache=expand_cache,
                extenders=extenders,
            ),
            call_deadline_s(),
        )


def lower_bound_nodes(result: SimulateResult, template: Node) -> int:
    """Heuristic node-count estimate from aggregate demand: k clones supply
    k × the template's allocatable per resource, so ⌈unmet demand /
    allocatable⌉ is usually close to the answer. NOT a true lower bound —
    re-simulation can migrate already-placed pods onto clones and unlock
    existing capacity for the unmet pods — so it only seeds the exponential
    phase's first probe; the bisection still verifies the full [0, hi]
    bracket (plan_capacity)."""
    demand: dict = {"pods": 0}
    for u in result.unscheduled:
        demand["pods"] += 1
        for res, q in u.pod.requests.items():
            demand[res] = demand.get(res, 0) + q
    k = 1
    for res, q in demand.items():
        alloc = template.allocatable.get(res, 0)
        if q > 0 and alloc > 0:
            k = max(k, -(-q // alloc))
    return k


def plan_capacity(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    new_node: Node,
    max_new_nodes: int = 1 << 14,
    weights: Optional[dict] = None,
    use_greed: bool = False,
    mesh=None,
    profiles=None,
    extenders=None,
    journal=None,
    resume: bool = False,
    sweep_mode: str = "auto",
) -> Optional[CapacityPlan]:
    """Public entry: _plan_capacity_impl with mid-plan checkpointing armed.

    A journaled call installs a durable.checkpoint.PlanCheckpointer for its
    duration, so when the chunked commit driver is on (OSIM_COMMIT_CHUNK)
    every batched-sweep device call journals `plan_chunk` records and
    periodically snapshots its carry — a SIGKILL *inside* one sweep then
    resumes mid-scan instead of re-running the whole call (`resume=True`
    replays the journal tail; see docs/durability.md). Unjournaled calls
    pay nothing. See _plan_capacity_impl for the full search contract."""
    if journal is None:
        return _plan_capacity_impl(
            cluster, apps, new_node, max_new_nodes, weights, use_greed,
            mesh, profiles, extenders, journal, resume, sweep_mode,
        )
    from ..durable.checkpoint import PlanCheckpointer, installed

    cp = PlanCheckpointer(journal, resume=resume)
    with installed(cp):
        return _plan_capacity_impl(
            cluster, apps, new_node, max_new_nodes, weights, use_greed,
            mesh, profiles, extenders, journal, resume, sweep_mode,
        )


def _plan_capacity_impl(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    new_node: Node,
    max_new_nodes: int = 1 << 14,
    weights: Optional[dict] = None,
    use_greed: bool = False,
    mesh=None,
    profiles=None,
    extenders=None,
    journal=None,
    resume: bool = False,
    sweep_mode: str = "auto",
) -> Optional[CapacityPlan]:
    """Minimum clones of `new_node` so every pod schedules and utilization
    gates pass. Returns None if even max_new_nodes doesn't suffice.

    `sweep_mode`: "batched" evaluates whole ladders of node counts per
    device call through the vmapped scenario engine
    (Simulator.run_scenarios) — log₂-few batched calls instead of ~11
    serial probes; "serial" is the probe-at-a-time bisection; "auto"
    (default) picks batched whenever the workload is batch-eligible
    (see simulator.batch_ineligible_reason — extenders, profiles, mesh,
    plugins, greed ordering, DaemonSets, and preemption-eligible pods all
    force serial, whose per-scenario control flow a vmapped lane cannot
    reproduce). Both modes return identical plans: the batched verdict
    lanes run the same commit engine the serial path proves bit-identity
    against, and the winning count is re-materialized serially either way.

    Durability: with a `journal` (durable.RunJournal), every trial's verdict
    is committed as a `trial` record *after* it completes, and with
    `resume=True` previously-journaled verdicts are consumed (FIFO per node
    count — the search order is deterministic given the same verdicts, so
    records replay in the order they were produced) instead of re-running
    the probe. A resumed run therefore re-simulates only trials the crashed
    run never finished, plus one `final` materializing replay — which is
    journaled as `final`, not `trial`, and never counted in
    `CapacityPlan.attempts`, so attempts/retries are identical between an
    interrupted+resumed sweep and an uninterrupted one. The batched path
    journals one `sweep` record per device call carrying ALL lane verdicts,
    consumed FIFO on resume — a SIGKILL'd batched search resumes with zero
    re-run scenarios. A resumed run always replays the crashed run's search
    shape: journaled `sweep` records force batched mode, journaled non-base
    `trial` records force serial, regardless of `sweep_mode`."""

    from ..durable.watchdog import call_deadline_s, guarded_call
    from ..ops.encode import node_bucket
    from ..resilience.policy import RetryExhaustedError, RetryPolicy
    from ..utils.tracing import log

    if sweep_mode not in ("auto", "serial", "batched"):
        raise ValueError(
            f"sweep_mode must be auto|serial|batched, got {sweep_mode!r}"
        )

    attempts = 0
    retries = 0
    batched_calls = 0
    n_base = len(cluster.nodes)
    # Workload expansion/validation is node-independent for everything but
    # DaemonSets — one shared cache expands the 100k-pod workload once for
    # the whole search instead of once per probe.
    expand_cache: dict = {}
    # A trial whose pods failed on a transient extender error (a blip, not a
    # verdict) is re-run once before its node count is trusted: buying nodes
    # for a transport timeout would mis-size the cluster.
    trial_policy = RetryPolicy.from_env(max_attempts=2)

    # node_count -> FIFO of journaled trial records from the crashed run(s);
    # sweep_cache: FIFO of journaled batched-sweep records
    resume_cache: dict = {}
    sweep_cache: list = []
    if resume and journal is not None:
        for e in journal.events("trial"):
            resume_cache.setdefault(int(e["node_count"]), []).append(e)
        sweep_cache = list(journal.events("sweep"))

    # Resolve the search shape. A resume MUST replay the crashed run's shape
    # (the journal's verdicts only line up with the search that produced
    # them); otherwise "auto" takes the batched path whenever the workload
    # is batch-eligible.
    mode = sweep_mode
    if resume and journal is not None:
        if sweep_cache:
            mode = "batched"
        elif any(
            int(e.get("node_count", 0)) > 0 for e in journal.events("trial")
        ):
            mode = "serial"
    if mode != "serial":
        reason = batch_ineligible_reason(
            cluster, apps, [Scenario(node_count=0)], use_greed=use_greed,
            mesh=mesh, profiles=profiles, extenders=extenders,
        )
        if reason is not None:
            if mode == "batched":
                log.warning(
                    "plan_capacity: batched sweep unavailable (%s); "
                    "using serial bisection", reason,
                )
            mode = "serial"
        else:
            mode = "batched"

    # seed for the exponential phase's first hi (demand/supply estimate);
    # journaled with the base trial so a resume never needs the base result
    seed_hi: Optional[int] = None
    # result of the most recent LIVE simulate — the only result whose pod
    # bindings are current (probes share cached pod objects; see finalize)
    last_live: Optional[SimulateResult] = None

    def good(res: SimulateResult) -> bool:
        return not res.unscheduled and satisfy_resource_setting(res)

    def run_trial(k: int, n_pad: Optional[int]):
        """One live probe with transient-blip retry. Returns
        (result, attempts_this_trial, retries_this_trial)."""
        t_attempts = 0
        t_retries = 0

        def once(_timeout: Optional[float]) -> SimulateResult:
            nonlocal t_attempts
            t_attempts += 1
            res = _probe(
                cluster, apps, new_node, k, weights, use_greed, mesh,
                n_pad=n_pad, profiles=profiles, expand_cache=expand_cache,
                extenders=extenders,
            )
            blips = [u for u in res.unscheduled if u.transient]
            if blips:
                raise _TransientTrialError(res, blips[0].reason)
            return res

        def note(_attempt: int, exc: BaseException, _delay: float) -> None:
            nonlocal t_retries
            t_retries += 1
            log.warning(
                "capacity probe (%d nodes) hit a transient extender failure "
                "(%s); retrying trial", k, exc,
            )

        try:
            res = trial_policy.execute(
                once, retryable=(_TransientTrialError,),
                target="capacity-probe", on_retry=note,
            )
        except RetryExhaustedError as e:
            # the retry blipped too — return the degraded result honestly
            # (its unscheduled list carries the extender error as the reason)
            res = e.last_exc.result  # type: ignore[union-attr]
        return res, t_attempts, t_retries

    def probe(k: int, n_pad: Optional[int] = None):
        """One committed trial: journaled verdict, or a cache hit on resume.
        Returns (good, result-or-None) — None when the verdict came from the
        journal (no live simulation ran, so there is no result object)."""
        nonlocal attempts, retries, seed_hi, last_live
        pending = resume_cache.get(k)
        if pending:
            e = pending.pop(0)
            if not pending:
                resume_cache.pop(k, None)
            attempts += int(e.get("attempt", 1))
            retries += int(e.get("retries", 0))
            if k == 0 and e.get("seed_hi") is not None:
                seed_hi = int(e["seed_hi"])
            return bool(e.get("good")), None
        res, t_attempts, t_retries = run_trial(k, n_pad)
        attempts += t_attempts
        retries += t_retries
        last_live = res
        g = good(res)
        payload = dict(node_count=k, good=g, attempt=t_attempts,
                       retries=t_retries)
        if k == 0 and not g:
            seed_hi = max(min(lower_bound_nodes(res, new_node),
                              max_new_nodes), 1)
            payload["seed_hi"] = seed_hi
        if journal is not None:
            journal.append("trial", **payload)
        return g, res

    def finalize(k: int, n_pad: Optional[int]) -> SimulateResult:
        """Materializing replay of the winning count. Probes share cached
        pod objects and every probe rebinds them, so only the LAST live
        simulate's result carries true bindings — when the winner isn't it
        (or the winner's verdict came from the journal), replay once. Same
        executables, so this is one cheap run; journaled as `final`, not
        `trial`, and excluded from attempts/retries so plans are
        byte-identical across interrupted/uninterrupted runs.

        The replay's correctness rests on run-to-run determinism of
        simulate (e.g. DaemonSet pods re-expand with fresh RNG-suffixed
        names, which must never influence placement) — the same property
        journal-based resume rests on. One cheap re-check turns any future
        nondeterminism into a loud error instead of a silently-wrong
        CapacityPlan. HTTP extenders are legitimately non-reproducible
        (stateful endpoints, transient timeouts on ignorable extenders), so
        with extenders configured a mismatch is attributed and tolerated —
        the returned result honestly shows any unscheduled pods."""
        res, _t_attempts, _t_retries = run_trial(k, n_pad)
        g = good(res)
        if journal is not None:
            journal.append("final", node_count=k, good=g)
        if not g:
            if extenders:
                log.warning(
                    "capacity replay of the winning probe (%d nodes) no "
                    "longer satisfies the plan — an extender answered "
                    "differently between probes; returning the replayed "
                    "result as-is", k,
                )
            else:
                raise RuntimeError(
                    "capacity replay of the winning probe no longer "
                    f"satisfies the plan ({k} nodes): simulate() is "
                    "nondeterministic"
                )
        return res

    # Trial-state reuse for the batched sweeps: fake clones deepcopied once,
    # and within a ladder rung the previous sweep's encoder + node table are
    # delta-updated instead of re-encoded (verdict mode never mutates node
    # objects, so the rows stay truthful across device calls).
    reuse = _TrialReuse(new_node, n_base)

    def sweep(counts: List[int], n_pad_sweep: int, phase: str):
        """One batched device call — verdicts for a whole ladder of node
        counts at once — or its journal replay on resume. Each lane k is the
        base cluster plus the first k clones of the max-count trial cluster
        (Scenario.node_count masks the rest; masked rows are inert in every
        kernel). Returns [good?] aligned with counts, or None when the
        post-expansion gate in run_scenarios refused (preemption-eligible
        pods) — the caller falls back to serial before anything was
        journaled, so resume shape stays consistent."""
        nonlocal attempts, batched_calls
        counts = list(counts)
        if sweep_cache:
            e = sweep_cache.pop(0)
            if list(map(int, e.get("counts", []))) == counts:
                attempts += len(counts)
                batched_calls += 1
                return [bool(g) for g in e.get("good", [])]
            # The journaled search diverged from the planned one (e.g. env
            # utilization limits changed between runs): the remaining
            # records can't line up either — go fully live from here.
            log.warning(
                "plan_capacity resume: journaled sweep counts %s do not "
                "match planned %s; discarding remaining sweep records and "
                "re-running live", e.get("counts"), counts,
            )
            sweep_cache.clear()
        trial = ClusterResource(
            nodes=list(cluster.nodes) + reuse.fakes(max(counts)),
            pods=list(cluster.pods),
            daemonsets=list(cluster.daemonsets),
            others=dict(cluster.others),
        )
        scenarios = [
            Scenario(name=f"+{k}", node_count=n_base + k) for k in counts
        ]
        metrics.CAPACITY_PROBES.inc(len(counts))
        metrics.NODE_BUCKET.set(n_pad_sweep)
        reuse.rungs_touched.add(n_pad_sweep)
        pre = reuse.preencoded(max(counts), n_pad_sweep)
        t0 = time.monotonic()
        holder = {}

        def run():
            sim = Simulator(
                trial, weights=weights, use_greed=use_greed,
                n_pad=n_pad_sweep, expand_cache=expand_cache,
                preencoded=pre,
            )
            holder["sim"] = sim
            return sim.run_scenarios(apps, scenarios, materialize=False)

        with span("capacity-sweep", lanes=len(counts), phase=phase):
            outs = guarded_call("capacity-sweep", run, call_deadline_s())
        if outs is None:
            return None
        reuse.capture(holder["sim"], n_base + max(counts), n_pad_sweep)
        metrics.BATCH_SWEEP_DURATION.observe(time.monotonic() - t0)
        verdicts = [_good_outcome(o) for o in outs]
        attempts += len(counts)
        batched_calls += 1
        if journal is not None:
            journal.append(
                "sweep", phase=phase, counts=counts, good=verdicts,
                n_pad=n_pad_sweep,
            )
        return verdicts

    g0, base = probe(0)
    if g0:
        if base is None:
            base = finalize(0, None)
        metrics.CAPACITY_NODES_ADDED.set(0)
        return CapacityPlan(0, base, attempts, retries)

    if mode == "batched":
        # --- batched ladder: geometric counts, LADDER_LANES per call -------
        # Same bracket the serial exponential phase walks probe-by-probe,
        # evaluated as whole device calls; the demand/supply seed skips most
        # low counts exactly as it does serially.
        ladder = []
        k = seed_hi or 1
        while k <= max_new_nodes:
            ladder.append(k)
            k *= 2
        hi: Optional[int] = None
        lo = 0
        fell_back = False
        n_pad_ladder = 0
        for start in range(0, len(ladder), LADDER_LANES):
            chunk = ladder[start:start + LADDER_LANES]
            n_pad_ladder = node_bucket(n_base + chunk[-1])
            verdicts = sweep(chunk, n_pad_ladder, "ladder")
            if verdicts is None:
                fell_back = True
                break
            goods = [c for c, g in zip(chunk, verdicts) if g]
            if goods:
                hi = min(goods)
                lo = max(
                    [lo] + [c for c, g in zip(chunk, verdicts)
                            if not g and c < hi]
                )
                break
            lo = max([lo] + chunk)
        if fell_back:
            log.warning(
                "plan_capacity: workload has preemption-eligible pods; "
                "batched sweep cannot reproduce per-scenario preemption — "
                "using serial bisection"
            )
            mode = "serial"
        elif hi is None:
            return None  # the whole ladder failed: workload does not fit
        else:
            # --- batched refinement: close (lo, hi] ------------------------
            # Up to SWEEP_LANES interior candidates per call, every call
            # pinned to the LADDER's node bucket: the refine counts all sit
            # below the ladder chunk that bracketed them, so its bucket
            # covers every trial cluster and the whole batched search —
            # ladder and refinement — reuses one compiled program (the
            # recompile guard asserts ≤ 2 per bucket).
            n_pad_refine = n_pad_ladder
            while hi - lo > 1 and not fell_back:
                width = hi - lo - 1
                if width <= SWEEP_LANES:
                    cands = list(range(lo + 1, hi))
                else:
                    step = (hi - lo) / (SWEEP_LANES + 1)
                    cands = sorted({
                        min(hi - 1, max(lo + 1, lo + int(round(step * (i + 1)))))
                        for i in range(SWEEP_LANES)
                    })
                verdicts = sweep(cands, n_pad_refine, "refine")
                if verdicts is None:  # unreachable after a live ladder call,
                    fell_back = True  # but kept defensive
                    break
                goods = [c for c, g in zip(cands, verdicts) if g]
                if goods:
                    hi = min(goods)
                bads = [c for c, g in zip(cands, verdicts)
                        if not g and c < hi]
                if bads:
                    lo = max(bads)
            if not fell_back:
                best_result = finalize(hi, node_bucket(n_base + hi))
                metrics.CAPACITY_NODES_ADDED.set(hi)
                return CapacityPlan(
                    hi, best_result, attempts, retries, batched_calls
                )
            mode = "serial"

    # Exponential growth to bracket, seeded by the demand/supply estimate
    # (skips most low probes), then bisect over the FULL [0, hi] range —
    # the estimate is only a starting guess, so minimality never depends on
    # it. Every probe of a phase is padded to the phase's bracket bucket so
    # the node-axis shapes — and therefore the XLA executables — are
    # identical across probes: the whole search compiles once per bucket
    # instead of once per probe.
    lo, hi = 0, (seed_hi or 1)
    best_result: Optional[SimulateResult] = None
    while hi <= max_new_nodes:
        # (exponential probes rely on encode_nodes' default node_bucket(n)
        # padding; only the bisection below needs an explicit pin, so every
        # mid-probe shares the bracket's bucket)
        g, hi_result = probe(hi)
        if g:
            best_result = hi_result
            break
        lo = hi  # a failed probe IS a verified lower bound
        hi *= 2
    else:
        return None
    best = hi
    n_pad = node_bucket(n_base + hi)
    while lo + 1 < hi:
        mid = (lo + hi) // 2
        g, res = probe(mid, n_pad=n_pad)
        if g:
            hi, best, best_result = mid, mid, res
        else:
            lo = mid
    if best_result is None or best_result is not last_live:
        best_result = finalize(best, n_pad)
    metrics.CAPACITY_NODES_ADDED.set(best)
    return CapacityPlan(best, best_result, attempts, retries)
