"""Resident cluster state: delta updates under a robustness envelope.

Every `/api/deploy-apps` request used to re-encode the whole cluster
(ops/encode.encode_nodes — ~450 ms at 10k nodes, BENCH_r03). The reference
never pays this because its informer cache applies watch deltas in place
(SURVEY §0); the TPU-native analog is a `ResidentCluster`: the encoded node
planes stay device-resident and each snapshot refresh lands as a handful of
jitted row scatters (ops/delta.py) instead of a full host re-encode.

The dangerous failure mode of long-lived derived state is *silent drift* — a
delta stream that diverges from the source of truth corrupts every subsequent
answer. The resident path is therefore built as a robustness subsystem first:

  generation fencing   every mutation bumps a globally monotonic epoch (never
                       reused across instances or re-serves); requests record
                       the epoch they were admitted under and the admission
                       queue re-keys any ticket whose epoch moved before
                       dequeue, so a coalesced batch can never mix requests
                       that saw different cluster states. Mutation happens
                       under the resident lock by building NEW arrays (numpy
                       planes are copied before row writes; jnp arrays are
                       immutable by construction), so a reader that grabbed
                       the previous view keeps a consistent snapshot — a
                       mid-batch delta cannot produce a torn read.

  drift detection      a cheap u32 digest of every resident plane (device
                       planes digested on device — the only transfer is one
                       scalar per plane) is periodically cross-checked against
                       the digest of a full re-encode of the mirror
                       (OSIM_RESIDENT_VERIFY_EVERY deltas, default 64;
                       0 disables the periodic check, `verify_now()` is
                       always available).

  anti-entropy repair  on digest mismatch, torn delta, delta-budget
                       exhaustion (OSIM_RESIDENT_DELTA_BUDGET) or a mid-run
                       OSIM_RESIDENT=0 flip, the state machine degrades to a
                       full re-encode, journals the repair through durable/
                       and increments osim_resident_drift_repairs_total. The
                       resident path can only ever be a performance upgrade:
                       structural changes it cannot express as row deltas
                       (node removal/reorder, bucket overflow, resource/
                       topology axis growth) take the same full re-encode,
                       counted separately in osim_resident_fallbacks_total.

Correctness contract: after every sync the resident planes are byte-identical
to `encode_nodes(self.enc, nodes, usage, gpu_usage, n_pad=<resident N>,
min_axes=<resident axes>)` — the SAME encoder (vocab ids are append-only and
idempotent), the same bucketed shapes. Row contents are always recomputed on
the host by the exact encode_node_into code path and scattered whole; nothing
is ever incrementally adjusted in f32 (non-associativity would break
byte-identity). tests/test_resident.py drives 200+ random delta sequences
against this contract.

Known self-healing gap: the encoder vocabs are shared with in-flight
simulations (admission serializes simulates, but a snapshot sync in a request
thread may intern new vocab entries concurrently). A lost-update interleaving
there leaves rows encoded under a stale id — exactly the drift class the
digest cross-check exists to catch and repair.

Chaos hooks (`simon chaos`, target "resident"): op "apply" kind torn_delta
applies a genuine partial device update then repairs; op "verify" kind
digest_mismatch perturbs the resident digest so the detector fires; op
"fence" kind stale_generation returns a sentinel epoch so the admission fence
re-keys the ticket (see resilience/faults.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.objects import Node, Pod
from ..ops import delta as delta_ops
from ..ops.encode import (
    Encoder,
    NodeTable,
    aggregate_gpu_usage,
    aggregate_usage,
    clear_node_row,
    encode_node_into,
    encode_nodes,
    node_axes,
    resource_scale,
)
from ..ops.kernels import NodeStatic
from ..ops.state import node_static_from_table
from ..resilience import faults
from ..utils import metrics
from ..utils.tracing import log

# Planes that live device-resident and are updated by jitted scatters. They
# are exactly the NodeTable fields consumed by state.carry_from_table — the
# per-request hot path reads them with a no-op jnp.asarray.
DEVICE_PLANES = ("free", "gpu_free", "vg_free", "dev_free")

# Fixed digest field order: every NodeTable array field (host mirror), then
# the device planes. Appending the device copies means the digest witnesses
# both "mirror == truth" and "device == mirror" in one number.
_DIGEST_FIELDS = tuple(
    f.name for f in dataclasses.fields(NodeTable) if f.name != "names"
)


class TornDelta(RuntimeError):
    """A delta apply stopped part-way (injected or real) — the device planes
    may be inconsistent with the mirror and must be repaired."""


# The epoch is module-globally monotonic so fence values can never collide
# across ResidentCluster instances or server re-serves (the satellite-1 bug
# class: serve() resetting state while coalesce keys survive).
_EPOCH_LOCK = threading.Lock()
_EPOCH_COUNTER = itertools.count(1)


def _next_epoch() -> int:
    with _EPOCH_LOCK:
        return next(_EPOCH_COUNTER)


def resident_enabled() -> bool:
    """OSIM_RESIDENT env knob; default on. 0/false/no/off disable."""
    return os.environ.get("OSIM_RESIDENT", "1").strip().lower() not in (
        "0", "false", "no", "off",
    )


def _verify_every() -> int:
    try:
        return int(os.environ.get("OSIM_RESIDENT_VERIFY_EVERY", "64"))
    except ValueError:
        return 64


def _delta_budget() -> int:
    try:
        return int(os.environ.get("OSIM_RESIDENT_DELTA_BUDGET", "4096"))
    except ValueError:
        return 4096


def digest_table(
    table: NodeTable, device: Optional[Dict[str, jnp.ndarray]] = None
) -> int:
    """One u32 digest over every array field of `table` (host), then over the
    device planes (or the table's own planes again when `device` is None, so
    a fresh encode digests shape-compatibly with a resident digest)."""
    parts: List[int] = []
    for name in _DIGEST_FIELDS:
        parts.append(delta_ops.digest_fold_host(getattr(table, name)))
    for name in DEVICE_PLANES:
        if device is not None:
            parts.append(int(delta_ops.digest_fold(device[name])))
        else:
            parts.append(delta_ops.digest_fold_host(getattr(table, name)))
    return delta_ops.combine_digests(parts)


class ResidentCluster:
    """Device-resident encoded cluster state with fencing, drift detection
    and anti-entropy repair. One instance per server snapshot source; all
    mutation happens in `sync` / `repair` under the internal lock."""

    def __init__(self, journal=None, journal_dir: Optional[str] = None) -> None:
        self.enc = Encoder(topology_keys=("kubernetes.io/hostname",))
        self.epoch = 0
        self._lock = threading.RLock()
        self._nodes: List[Node] = []
        self._bound: List[Tuple[Pod, str]] = []
        self._usage: Dict[str, Dict[str, int]] = {}
        self._gpu_usage: Dict[str, np.ndarray] = {}
        self._host: Optional[NodeTable] = None
        self._axes: Tuple[int, int, int, int, int] = (0, 0, 0, 0, 0)
        self._dev: Dict[str, jnp.ndarray] = {}
        self._ns: Optional[NodeStatic] = None
        self._ns_key: Optional[tuple] = None
        self._static_epoch = 0
        self._deltas_since_encode = 0
        self._since_verify = 0
        self._loaned = False
        self._disabled = False
        self._journal = journal
        self._journal_dir = journal_dir
        self.repairs = 0  # lifetime count, for cheap test/debug introspection

    # -- public surface ----------------------------------------------------

    def fence_epoch(self) -> int:
        """The epoch a request must record at admission. The continuous-
        batching scheduler loop (server/loop.py) consults this ONCE PER PACK
        at pack-take time and re-keys every ticket whose admission-time epoch
        moved — so all lanes of one batched device call see the same resident
        state. The stale_generation chaos kind returns a sentinel that can
        never match a live epoch, forcing the re-key (the degraded outcome is
        a private coalesce key — never a cross-generation merge)."""
        rule = faults.maybe_inject("resident", "fence")
        if rule is not None and rule.kind == "stale_generation":
            return -1
        return self.epoch

    def sync(self, nodes: Sequence[Node], pods: Sequence[Pod]) -> int:
        """Bring the resident state up to date with a fresh snapshot; returns
        the new epoch. Structural changes and faults degrade to a full
        re-encode — this call never raises on drift, it heals."""
        with self._lock:
            if not resident_enabled():
                had_live_state = self._host is not None and not self._disabled
                self._adopt(nodes, pods)
                self._disabled = True
                if had_live_state:
                    # mid-run degrade: journal the forced repair once, then
                    # serve full re-encodes until the knob flips back
                    self._repair("disabled")
                else:
                    self._encode_full()
                    self._bump()
                return self.epoch
            self._disabled = False
            if self._host is None:
                self._adopt(nodes, pods)
                self._reencode("cold_start", count=False)
                return self.epoch
            return self._sync_delta(nodes, pods)

    def verify_now(self) -> bool:
        """Force one drift-detector pass; True = digests matched (a mismatch
        repairs and still returns False for observability)."""
        with self._lock:
            if self._host is None:
                return True
            return self._verify()

    def covers_reason(
        self, nodes: Sequence[Node], bound: Sequence[Tuple[Pod, str]]
    ) -> Optional[str]:
        """None when the resident planes are exactly the encode of (nodes,
        bound); otherwise a fallback-reason label. Node identity is the fast
        path (the server hands the same snapshot objects that were synced);
        content equality is the correctness backstop for arbitrary callers."""
        with self._lock:
            if self._disabled or self._host is None:
                return "disabled"
            if len(nodes) != len(self._nodes):
                return "not_covering"
            for nd, mine in zip(nodes, self._nodes):
                if nd is mine:
                    continue
                if nd.name != mine.name or nd.raw != mine.raw:
                    return "not_covering"
            if aggregate_usage(bound) != self._usage:
                return "not_covering"
            gpu = aggregate_gpu_usage(nodes, bound)
            if set(gpu) != set(self._gpu_usage):
                return "not_covering"
            for name, arr in gpu.items():
                if not np.array_equal(arr, self._gpu_usage[name]):
                    return "not_covering"
            return None

    def device_state(
        self, all_pods: Sequence[Pod], bound: Sequence[Tuple[Pod, str]]
    ) -> Tuple[NodeTable, NodeStatic]:
        """The Simulator fast path (after covers_reason returned None):
        register the request's pods into the shared encoder, re-encode if the
        registration grew a shape-defining axis, and hand back the resident
        table view (device planes substituted) plus the cached NodeStatic."""
        with self._lock:
            assert self._host is not None
            self.enc.register_pods(list(all_pods))
            for pod, _ in bound:
                self.enc.register_pods([pod])
            if (
                len(self.enc.resources) != self._host.alloc.shape[1]
                or max(len(self.enc.topology_keys), 1) != self._host.topo.shape[1]
            ):
                self._reencode("shape_growth")
            return self.table_view(), self._node_static()

    def table_view(self) -> NodeTable:
        """The resident NodeTable with device planes substituted: numpy
        fields stay host (NodeStatic construction, names lookups), the four
        carry planes are jnp — carry_from_table's jnp.asarray is a no-op, so
        a request pays zero node-plane transfers.

        Handing out a view LOANS the current device planes to the caller
        (its Simulator carry aliases them zero-copy). ops/delta.apply_rows
        donates its input plane, so the next sync must not scatter into a
        loaned buffer in place — _apply_rows checks the loan flag and feeds
        the donating kernel a fresh copy instead, leaving every outstanding
        view intact."""
        with self._lock:
            assert self._host is not None
            self._loaned = True
            return dataclasses.replace(self._host, **dict(self._dev))

    # -- internals (call with self._lock held) -----------------------------

    def _adopt(self, nodes: Sequence[Node], pods: Sequence[Pod]) -> None:
        self._nodes = list(nodes)
        self._bound = [(p, p.node_name) for p in pods if p.node_name]
        self._usage = aggregate_usage(self._bound)
        self._gpu_usage = aggregate_gpu_usage(self._nodes, self._bound)

    def _bump(self) -> None:
        self.epoch = _next_epoch()
        metrics.RESIDENT_EPOCH.set(self.epoch)

    def _reencode(self, reason: str, count: bool = True) -> None:
        """Structural full re-encode (still resident afterwards). Not a drift
        repair — the state was correct, it just could not absorb the change
        as row deltas."""
        if count:
            metrics.RESIDENT_FALLBACKS.inc(reason=reason)
        self._encode_full()
        self._bump()

    def _encode_full(self) -> None:
        self._host = encode_nodes(
            self.enc,
            self._nodes,
            existing_usage=self._usage,
            existing_gpu=self._gpu_usage,
        )
        self._axes = (
            self._host.label_pair.shape[1],
            self._host.taint_key.shape[1],
            self._host.gpu_total.shape[1],
            self._host.vg_cap.shape[1],
            self._host.dev_cap.shape[1],
        )
        self._dev = {
            name: jnp.asarray(getattr(self._host, name))
            for name in DEVICE_PLANES
        }
        self._static_epoch += 1
        self._deltas_since_encode = 0
        self._since_verify = 0
        self._loaned = False

    def _repair(self, reason: str) -> None:
        """Anti-entropy: re-encode from the mirror of record, journal, count.
        Every drift/torn/stale path funnels here — the request that triggered
        it is answered from the repaired state, never from the drifted one."""
        self._encode_full()
        self._bump()
        self.repairs += 1
        metrics.RESIDENT_DRIFT_REPAIRS.inc(reason=reason)
        try:
            journal = self._ensure_journal()
            if journal is not None:
                journal.append(
                    "resident_repair", reason=reason, epoch=self.epoch
                )
        except Exception as e:  # journal loss must not take down serving
            log.warning("resident repair journal write failed: %s", e)
        log.warning(
            "resident state repaired (reason=%s) at epoch %d", reason, self.epoch
        )

    def _ensure_journal(self):
        if self._journal is not None:
            return self._journal
        from ..durable.journal import RunJournal, default_runs_root

        run_dir = self._journal_dir or os.path.join(
            default_runs_root(), f"resident-{os.getpid()}"
        )
        self._journal = RunJournal.open(run_dir)
        return self._journal

    def _node_static(self) -> NodeStatic:
        key = (
            self._static_epoch,
            len(self.enc.domains),
            len(self.enc.anti_terms),
        )
        if self._ns is None or self._ns_key != key:
            assert self._host is not None
            self._ns = node_static_from_table(self.enc, self._host)
            self._ns_key = key
        return self._ns

    # -- delta machinery ---------------------------------------------------

    def _sync_delta(self, nodes: Sequence[Node], pods: Sequence[Pod]) -> int:
        assert self._host is not None
        host = self._host
        old_nodes = self._nodes
        old_usage, old_gpu = self._usage, self._gpu_usage
        self._adopt(nodes, pods)

        # structural gates: anything the fixed-shape planes cannot absorb
        old_names = [nd.name for nd in old_nodes]
        new_names = [nd.name for nd in self._nodes]
        if new_names[: len(old_names)] != old_names:
            reason = (
                "node_removed"
                if set(old_names) - set(new_names)
                else "node_order"
            )
            self._reencode(reason)
            return self.epoch
        if len(new_names) > host.n:
            self._reencode("bucket_overflow")
            return self.epoch

        changed_rows: List[int] = []   # node object changed -> full row
        for i in range(len(old_nodes)):
            nd = self._nodes[i]
            old = old_nodes[i]
            if nd is old or nd.raw == old.raw:
                continue
            changed_rows.append(i)
        added_rows = list(range(len(old_nodes), len(self._nodes)))
        if changed_rows or added_rows:
            fit = [self._nodes[i] for i in changed_rows + added_rows]
            axes = node_axes(self.enc, fit)
            if any(a > b for a, b in zip(axes, self._axes)):
                self._reencode("bucket_overflow")
                return self.epoch

        usage_rows: List[int] = []     # only the bound-pod load changed
        touched = set(changed_rows) | set(added_rows)
        for i, nd in enumerate(self._nodes):
            if i in touched:
                continue
            if old_usage.get(nd.name) != self._usage.get(nd.name):
                usage_rows.append(i)
                continue
            a, b = old_gpu.get(nd.name), self._gpu_usage.get(nd.name)
            if (a is None) != (b is None) or (
                a is not None and not np.array_equal(a, b)
            ):
                usage_rows.append(i)

        if not changed_rows and not added_rows and not usage_rows:
            return self.epoch  # no-op sync: nothing moved, epoch holds

        try:
            self._apply_rows(changed_rows, added_rows, usage_rows)
        except TornDelta:
            self._repair("torn_delta")
            return self.epoch

        if changed_rows:
            metrics.RESIDENT_DELTAS.inc(len(changed_rows), kind="node_row")
        if added_rows:
            metrics.RESIDENT_DELTAS.inc(len(added_rows), kind="node_added")
        if usage_rows:
            metrics.RESIDENT_DELTAS.inc(len(usage_rows), kind="pod_usage")
        self._bump()
        self._deltas_since_encode += 1
        self._since_verify += 1
        budget = _delta_budget()
        if budget and self._deltas_since_encode >= budget:
            self._repair("delta_budget")
            return self.epoch
        every = _verify_every()
        if every and self._since_verify >= every:
            self._verify()
        return self.epoch

    def _apply_rows(
        self, changed: List[int], added: List[int], usage_rows: List[int]
    ) -> None:
        """Copy-on-write the touched planes, replay the exact encode for the
        touched rows on the host, scatter the rows to the device planes. The
        swapped-in table is fresh arrays throughout — readers holding the
        previous view keep a consistent snapshot."""
        assert self._host is not None
        host = self._host
        full_rows = sorted(changed) + added
        if full_rows:
            # a node-object change can move any field: copy every plane
            table = dataclasses.replace(
                host,
                **{
                    f.name: getattr(host, f.name).copy()
                    for f in dataclasses.fields(NodeTable)
                    if f.name != "names"
                },
                names=list(host.names),
            )
            for i in added:
                table.names.append(self._nodes[i].name)
            for i in full_rows:
                clear_node_row(table, i)
                encode_node_into(
                    self.enc, table, i, self._nodes[i],
                    self._usage, self._gpu_usage,
                )
        else:
            table = dataclasses.replace(
                host,
                free=host.free.copy(),
                gpu_free=host.gpu_free.copy(),
            )
        for i in usage_rows:
            self._recompute_usage_row(table, i)

        rule = faults.maybe_inject("resident", "apply")
        torn = rule is not None and rule.kind == "torn_delta"

        rows = sorted(set(full_rows) | set(usage_rows))
        idx = jnp.asarray(delta_ops.pad_indices(rows, host.n))
        U = int(idx.shape[0])
        dev = dict(self._dev)
        planes = DEVICE_PLANES if full_rows else ("free", "gpu_free")
        # apply_rows donates its plane argument. When no table_view() loan
        # is outstanding the planes are uniquely ours and the scatter lands
        # in place (zero-copy delta — the donation win); when a view has
        # been handed out since the last sync, its borrower's carry aliases
        # these exact buffers, so donate a fresh copy and leave the loaned
        # generation intact for its holder.
        loaned = self._loaned
        for k, name in enumerate(planes):
            src = getattr(table, name)
            stack = np.zeros((U,) + src.shape[1:], src.dtype)
            stack[: len(rows)] = src[rows]
            plane = dev[name].copy() if loaned else dev[name]
            dev[name] = delta_ops.apply_rows(plane, idx, jnp.asarray(stack))
            if torn and k == 0:
                # genuine partial apply: the first plane landed, the rest
                # did not — exactly the inconsistency repair must heal
                self._dev = dev
                self._host = table
                raise TornDelta("injected by fault plan: torn delta apply")
        self._dev = dev
        self._host = table
        # the planes just installed are fresh (donated-in-place from our own
        # generation, or copies when loaned) — no outstanding view holds them
        self._loaned = False
        if full_rows:
            self._static_epoch += 1

    def _recompute_usage_row(self, table: NodeTable, i: int) -> None:
        """Exact encode arithmetic for the two load-bearing planes of an
        otherwise-unchanged node (f64 intermediate, f32 on assignment — byte
        parity with encode_node_into)."""
        nd = self._nodes[i]
        for r, res in enumerate(self.enc.resources):
            a = nd.allocatable.get(res, 0) / resource_scale(res)
            used = self._usage.get(nd.name, {}).get(res, 0) / resource_scale(res)
            table.free[i, r] = a - used
        table.gpu_free[i] = 0.0
        g_cnt = nd.gpu_count()
        if g_cnt > 0:
            per_dev = np.float32(nd.gpu_mem_per_device() / float(1 << 20))
            table.gpu_free[i, :g_cnt] = per_dev
            used_g = self._gpu_usage.get(nd.name)
            if used_g is not None:
                table.gpu_free[i, : len(used_g)] -= used_g.astype(np.float32)

    # -- drift detection ---------------------------------------------------

    def _verify(self) -> bool:
        assert self._host is not None
        self._since_verify = 0
        got = digest_table(self._host, self._dev)
        rule = faults.maybe_inject("resident", "verify")
        if rule is not None and rule.kind == "digest_mismatch":
            got ^= 0xDEADBEEF
        fresh = encode_nodes(
            self.enc,
            self._nodes,
            existing_usage=self._usage,
            existing_gpu=self._gpu_usage,
            n_pad=self._host.n,
            min_axes=self._axes,
        )
        want = digest_table(fresh)
        if got == want:
            metrics.RESIDENT_VERIFICATIONS.inc(outcome="ok")
            return True
        metrics.RESIDENT_VERIFICATIONS.inc(outcome="mismatch")
        self._repair("digest_mismatch")
        return False
