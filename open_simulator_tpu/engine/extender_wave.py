"""Wave-pipelined extender scheduling: overlap host HTTP with device compute.

The serial extender path (Simulator._schedule_run_extenders' legacy loop)
pays, per pod: one probe_step device call, a *serial* chain of HTTP
filter/prioritize round trips on a fresh connection, then one commit_step —
so extender-enabled clusters ran ~100x slower than the pure-JAX path. This
engine restructures that loop into waves of W pods
(`OSIM_EXTENDER_WAVE`, 0 = legacy serial escape hatch):

  1. **probe_many** (ops/kernels.py) filters + scores the whole wave against
     the wave-start carry in ONE device call (the wave axis is padded with
     the scenario-bucket discipline so the jit cache stays small);
  2. the per-pod extender chains — order-preserving within a pod — fan out
     across a bounded thread pool over keep-alive pooled connections
     (utils/httppool.py, `OSIM_EXTENDER_POOL`); while those HTTP calls are
     in flight the NEXT wave is already probed AND its chains queued on the
     pool (speculatively, against the pre-commit carry — the verbs are
     idempotent and faults.begin_key replays fault coins, so discarding a
     speculative chain and re-issuing it later is invisible);
  3. **commit_wave** applies the wave's placements in pod order through a
     scan that re-runs the filters against the live carry and compares with
     the mask each pod's HTTP chain actually saw. A match proves the serial
     path would have issued byte-identical requests, so the commit IS the
     serial placement; the first mismatch makes that pod and every later pod
     in the wave respill to the front of the queue (their serial outcome
     depends on commits that must land first).

Byte-identity with the serial path holds by construction (deterministic
extenders — the same assumption the serial path's own retries make), and is
pinned by tests/test_extender_wave.py digest equivalence. Progress is
guaranteed: a freshly probed wave's first pod is rechecked against the exact
carry it was probed at, so every wave commits at least one pod.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.fast import scenario_bucket
from ..ops.kernels import commit_wave, probe_many
from ..resilience import faults
from ..utils import metrics
from ..utils.httppool import configured_pool_size
from ..utils.tracing import activate, current_context, log, span
from .extenders import (
    EXTENDER_SCORE_SCALE,
    ExtenderError,
    HTTPExtender,
    TransientExtenderError,
    _pod_uid,
)

DEFAULT_WAVE = 64


def wave_size() -> int:
    """OSIM_EXTENDER_WAVE: pods probed + dispatched per wave. 0 disables the
    wave engine entirely (documented escape hatch: the simulator falls back
    to the legacy serial per-pod loop, byte-identical by construction)."""
    try:
        w = int(os.environ.get("OSIM_EXTENDER_WAVE", "") or DEFAULT_WAVE)
    except ValueError:
        w = DEFAULT_WAVE
    return max(0, w)


class _ChainResult:
    """Host-side outcome of one pod's extender filter+prioritize chain."""

    __slots__ = (
        "feasible_names", "combined", "ext_msgs", "error", "error_transient",
        "n_device_feasible",
    )

    def __init__(self, feasible_names, combined, ext_msgs, error,
                 error_transient, n_device_feasible):
        self.feasible_names = feasible_names
        self.combined = combined
        self.ext_msgs = ext_msgs
        self.error = error
        self.error_transient = error_transient
        self.n_device_feasible = n_device_feasible


def _run_chain(
    pod, feasible, interested: Sequence[HTTPExtender]
) -> _ChainResult:
    """One pod's extender chain — the exact host logic of the legacy serial
    loop (chain order, ignorable skip, first-wins failedNodes attribution,
    prioritize errors dropped), run on a pool worker thread. Everything it
    touches is pod-local except the extenders themselves, whose shared state
    (breaker, retry rng, connection pool) is lock-guarded."""
    n_device_feasible = len(feasible)
    ext_msgs: Dict[str, str] = {}
    error: Optional[str] = None
    error_transient = False
    for ext in interested:
        if not feasible:
            break
        try:
            feasible, failed_map = ext.filter(pod, feasible)
        except ExtenderError as e:
            if ext.is_ignorable:
                # degraded mode: an erroring (or circuit-open) ignorable
                # extender is skipped, not fatal
                metrics.EXTENDER_SKIPPED.inc(endpoint=ext.base)
                log.warning("skipping ignorable extender: %s", e)
                continue
            error = str(e)
            error_transient = isinstance(e, TransientExtenderError)
            break
        for name, msg in failed_map.items():
            ext_msgs.setdefault(name, msg)
    combined = {n.name: 0.0 for n in feasible}
    if error is None and feasible:
        for ext in interested:
            if not ext.cfg.prioritize_verb:
                continue
            try:
                for host, s in ext.prioritize(pod, feasible).items():
                    if host in combined:
                        combined[host] += s
            except ExtenderError as e:
                # prioritize errors are ignored (generic_scheduler.go
                # :529-536 logs and drops them)
                metrics.EXTENDER_SKIPPED.inc(endpoint=ext.base)
                log.warning("extender prioritize failed: %s", e)
    return _ChainResult(
        [n.name for n in feasible], combined, ext_msgs, error,
        error_transient, n_device_feasible,
    )


def _chain_task(pod, feasible, interested, trace_ctx=None) -> _ChainResult:
    """Pool-thread wrapper of one chain: re-activates the trace context
    captured on the dispatching thread, so the chain's span (and every
    extender-http child under it) stays a child-by-ID of the simulate call
    that launched the wave."""
    metrics.EXTENDER_INFLIGHT.inc()
    try:
        with activate(trace_ctx):
            with span("extender-chain", pod=_pod_uid(pod)):
                return _run_chain(pod, feasible, interested)
    finally:
        metrics.EXTENDER_INFLIGHT.dec()


def _stack_rows(rows, idx: np.ndarray):
    """Wave-stacked host PodRow: numpy fancy-index of the run's row table."""
    return jax.tree.map(lambda a: a[idx], rows)


class _Wave:
    """One dispatched wave: pod indices, the probe it chained against (device
    refs + host copies), and the in-flight chain futures."""

    __slots__ = (
        "idx", "rows", "mask", "ff", "mask_np", "ff_np", "futures",
        "chains", "glue",
    )

    def __init__(self, idx, rows, mask, ff, mask_np, ff_np, futures):
        self.idx = idx
        self.rows = rows      # stacked PodRow, shared by probe and commit
        self.mask = mask
        self.ff = ff
        self.mask_np = mask_np
        self.ff_np = ff_np
        self.futures = futures
        self.chains: Optional[List[_ChainResult]] = None
        self.glue = None      # (ext_allowed, ext_score, want) host arrays


def run_waves(
    sim,
    pods,
    rows,
    weights,
    filter_on,
    interest: Sequence[Tuple[bool, ...]],
    wave: int,
) -> Tuple[list, int]:
    """Drive the wave pipeline over one extender-interested pod run.

    `sim` is the Simulator (carry/ns/extenders/cluster live there; commits
    mutate sim._carry), `rows` the host PodRow table for `pods`, `interest`
    the per-pod per-extender interest vector computed once by the routing
    split. Returns (failed UnscheduledPods in pod order, scheduled count).
    """
    from .simulator import UnscheduledPod

    n_nodes = len(sim.cluster.nodes)
    name_index = sim._name_index_map()
    n_pad = int(sim._ns.valid.shape[0])
    fo = filter_on
    interested_by_pod = [
        [e for e, hit in zip(sim._extenders, iv) if hit] for iv in interest
    ]

    nodes_host = sim.cluster.nodes
    pending: List[int] = list(range(len(pods)))
    failures: Dict[int, UnscheduledPod] = {}
    scheduled = 0
    workers = max(1, min(wave, configured_pool_size()))
    # pod index -> fault-counter snapshot taken at its FIRST chain dispatch;
    # restored before any re-dispatch (respill, discarded speculation) so
    # re-issued chains replay their first run's fault decisions exactly
    fault_snaps: Dict[int, object] = {}

    def padded(idx: List[int]) -> np.ndarray:
        w_pad = scenario_bucket(len(idx))
        return np.asarray(idx + [idx[0]] * (w_pad - len(idx)), np.int64)

    # Captured ONCE on the simulate thread: every chain queued on the pool
    # re-activates this context so its spans (and outbound traceparent
    # headers) stay in the dispatching request's trace.
    trace_ctx = current_context()

    with ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="osim-extender"
    ) as pool:

        def launch(idx: List[int]) -> _Wave:
            """Probe `idx` against the CURRENT carry and queue its chains on
            the pool. Speculative when the previous wave has not committed
            yet — a stale mask is caught by commit_wave's recheck."""
            wave_rows = _stack_rows(rows, padded(idx))
            mask, _score, ff = probe_many(
                sim._ns, sim._carry, wave_rows, weights, fo,
                sim._extra_filters, sim._extra_scores,
            )
            mask_np, ff_np = jax.device_get((mask, ff))
            metrics.EXTENDER_WAVE_SIZE.observe(len(idx))
            futures = []
            for w, i in enumerate(idx):
                uid = _pod_uid(pods[i])
                if i in fault_snaps:
                    faults.restore_key(uid, fault_snaps[i])
                else:
                    fault_snaps[i] = faults.snapshot_key(uid)
                js = np.flatnonzero(mask_np[w, :n_nodes])
                feasible = (
                    list(nodes_host)
                    if js.size == n_nodes
                    else [nodes_host[j] for j in js]
                )
                futures.append(
                    pool.submit(
                        _chain_task, pods[i], feasible,
                        interested_by_pod[i], trace_ctx,
                    )
                )
            return _Wave(idx, wave_rows, mask, ff, mask_np, ff_np, futures)

        def prepare(wv: _Wave) -> None:
            """Gather the wave's chain results and build its commit-glue
            arrays. Idempotent; called for the NEXT wave while the current
            wave's commit is still computing on device, so this HTTP wait
            and glue Python overlap device time."""
            if wv.chains is not None:
                return
            wv.chains = [f.result() for f in wv.futures]
            w_pad = int(wv.mask_np.shape[0])
            ext_allowed = np.zeros((w_pad, n_pad), bool)
            ext_score = np.zeros((w_pad, n_pad), np.float32)
            want = np.zeros(w_pad, bool)
            for w, res in enumerate(wv.chains):
                if res.error is not None or not res.feasible_names:
                    continue
                want[w] = True
                js = np.fromiter(
                    (name_index[nm] for nm in res.feasible_names),
                    np.int64, len(res.feasible_names),
                )
                ext_allowed[w, js] = True
                ext_score[w, js] = np.fromiter(
                    (
                        res.combined[nm] * EXTENDER_SCORE_SCALE
                        for nm in res.feasible_names
                    ),
                    np.float32, len(res.feasible_names),
                )
            wv.glue = (ext_allowed, ext_score, want)

        cur: Optional[_Wave] = None
        while pending or cur is not None:
            if cur is None:
                cur, pending = launch(pending[:wave]), pending[wave:]
            # speculative overlap: probe the NEXT wave and queue its chains
            # behind cur's on the pool, so its HTTP flies while cur commits
            nxt: Optional[_Wave] = None
            if pending:
                nxt, pending = launch(pending[:wave]), pending[wave:]
            prepare(cur)

            wave_idx = cur.idx
            w_real = len(wave_idx)
            mask_np, ff_np = cur.mask_np, cur.ff_np
            chains = cur.chains
            ext_allowed, ext_score, want = cur.glue
            (
                sim._carry, nodes, respill, gpu_take, vg_take, dev_take,
            ) = commit_wave(
                sim._ns, sim._carry, cur.rows, weights,
                cur.mask, cur.ff,
                jnp.asarray(ext_allowed), jnp.asarray(ext_score),
                jnp.asarray(want), fo,
                sim._extra_filters, sim._extra_scores,
            )
            if nxt is not None:
                # cur's commit is in flight on device: drain nxt's HTTP and
                # build its glue NOW, where both hide behind device time
                prepare(nxt)
            nodes_np, respill_np, take_np, vg_np, dev_np = jax.device_get(
                (nodes, respill, gpu_take, vg_take, dev_take)
            )

            nz = np.flatnonzero(respill_np[:w_real])
            first_respill = int(nz[0]) if nz.size else w_real
            for w in range(first_respill):
                i = wave_idx[w]
                res = chains[w]
                ni = int(nodes_np[w])
                if ni >= 0:
                    sim._bind_placed(
                        pods[i], ni, take_np[w], vg_np[w], dev_np[w]
                    )
                    scheduled += 1
                elif res.error is not None:
                    failures[i] = UnscheduledPod(
                        pods[i], res.error, transient=res.error_transient
                    )
                else:
                    failures[i] = UnscheduledPod(
                        pods[i],
                        sim._extender_reason(
                            n_nodes, mask_np[w], ff_np[w], res.ext_msgs,
                            res.n_device_feasible,
                        ),
                    )
            if first_respill < w_real:
                spilled = wave_idx[first_respill:]
                metrics.EXTENDER_WAVE_RESPILL.inc(len(spilled))
                if nxt is not None:
                    # the speculative wave chained against a carry that just
                    # changed under it: discard it. Its chains are already
                    # drained (prepare(nxt) ran before the results came
                    # back), so no stale chain can still be drawing fault
                    # coins when the re-dispatch replays them
                    pending = list(nxt.idx) + pending
                    nxt = None
                # back to the FRONT: serial commit order is the contract
                pending = spilled + pending
            cur = nxt

    failed = [failures[i] for i in sorted(failures)]
    return failed, scheduled
