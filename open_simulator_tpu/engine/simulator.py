"""Simulation engine: the TPU-native equivalent of pkg/simulator.

Parity map (reference → here):
  - `Simulate(cluster, apps, opts...)` (`pkg/simulator/core.go:67`) → `simulate()`
  - `Simulator.RunCluster` / `ScheduleApp` (`pkg/simulator/simulator.go:219-275`)
    → `_schedule_batch_host` over the cluster's pending pods, then each app's
    pods in order.
  - the per-pod create→watch→bind handshake (`simulator.go:309-348,449-468`)
    → a single `lax.scan` on device; placements come back as one vector.
  - `Close()` teardown dance (`simulator.go:350-363`) → nothing: the engine is
    a plain object with no background goroutines to defuse (SURVEY §3.4's
    leak-by-design is structurally impossible here).

Pod ordering parity: core/ordering.py (AffinityQueue then TolerationQueue,
plus a working GreedQueue behind use_greed — `simulator.go:238-241`,
`pkg/algo/`).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.objects import (
    ANNO_GPU_INDEX,
    ANNO_NODE_LOCAL_STORAGE,
    DEFAULT_SCHEDULER,
    LocalDevice,
    LocalVG,
    Node,
    NodeLocalStorage,
    Pod,
)
from ..core.ordering import order_pods
from ..core import workloads
from ..core.workloads import WORKLOAD_KINDS, pods_from_workload
from ..ops.encode import (
    Encoder,
    aggregate_gpu_usage,
    aggregate_usage,
    encode_nodes,
    encode_pods,
    initial_anti_counts,
    initial_port_counts,
    initial_selector_counts,
)
from ..ops.fast import (
    schedule_batch_fast,
    schedule_scenarios_host,
    scenario_bucket,
)
from ..ops.kernels import (
    FILTER_MESSAGES,
    NUM_FILTERS,
    DEFAULT_WEIGHTS,
    weights_array,
)
from ..ops.state import (
    align_carry,
    align_carry_scenarios,
    carry_from_table,
    node_static_from_table,
    stack_carry,
)
from ..utils import metrics
from ..utils.tracing import progress, span


@dataclass
class ClusterResource:
    """Initial cluster state (parity: simulator.ResourceTypes, core.go:33-45)."""
    nodes: List[Node] = field(default_factory=list)
    pods: List[Pod] = field(default_factory=list)
    daemonsets: List[dict] = field(default_factory=list)
    others: Dict[str, List[dict]] = field(default_factory=dict)

    @staticmethod
    def from_objects(objs: Sequence[dict]) -> "ClusterResource":
        cluster = ClusterResource()
        for o in objs:
            kind = o.get("kind", "")
            if kind == "Node":
                cluster.nodes.append(Node.from_dict(o))
            elif kind == "Pod":
                cluster.pods.append(Pod.from_dict(o))
            elif kind == "DaemonSet":
                cluster.daemonsets.append(o)
            else:
                cluster.others.setdefault(kind, []).append(o)
        return cluster

    def attach_local_storage(self, storage_by_name: Dict[str, str]) -> None:
        """Match node-local-storage JSON specs to nodes by file stem
        (parity: MatchAndSetLocalStorageAnnotationOnNode, utils.go:385-401)."""
        for node in self.nodes:
            info = storage_by_name.get(node.name)
            if info is not None:
                node.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = info


@dataclass
class AppResource:
    """One app: ordered list of decoded workload objects (core.go:47-51)."""
    name: str
    objects: List[dict] = field(default_factory=list)


@dataclass
class Scenario:
    """One lane of a multi-scenario sweep (simulate_batch). Scenarios share
    the cluster and app list; each lane varies the score weights and/or the
    set of usable nodes. `node_count` keeps only the first N cluster nodes
    (the capacity ladder's shape); `node_valid` is an explicit keep-mask over
    cluster.nodes in order. At most one of the two may be set."""

    name: str = ""
    weights: Optional[dict] = None       # None = the sweep's default weights
    node_count: Optional[int] = None
    node_valid: Optional[Sequence[bool]] = None

    def keep_mask(self, n_nodes: int) -> Optional[np.ndarray]:
        """bool[n_nodes] keep-mask, or None when every node is usable."""
        if self.node_count is not None and self.node_valid is not None:
            raise ValueError(
                "Scenario sets both node_count and node_valid"
            )
        if self.node_count is not None:
            if not 0 <= self.node_count <= n_nodes:
                raise ValueError(
                    f"Scenario node_count {self.node_count} outside "
                    f"[0, {n_nodes}]"
                )
            if self.node_count == n_nodes:
                return None
            mask = np.zeros(n_nodes, bool)
            mask[: self.node_count] = True
            return mask
        if self.node_valid is not None:
            mask = np.asarray(list(self.node_valid), bool)
            if mask.shape != (n_nodes,):
                raise ValueError(
                    f"Scenario node_valid has {mask.shape[0]} entries for "
                    f"{n_nodes} nodes"
                )
            return None if mask.all() else mask
        return None


@dataclass
class ScenarioOutcome:
    """Lightweight per-scenario verdict data from a non-materializing sweep
    (run_scenarios(materialize=False)) — everything the capacity planner's
    good() gate reads, without building S full SimulateResults."""

    name: str
    unscheduled: int
    # totals mirroring satisfy_resource_setting's sums: allocatable over the
    # scenario's nodes, requests over every bound pod (pre-bound + placed)
    cpu_alloc: float = 0.0
    cpu_req: float = 0.0
    mem_alloc: float = 0.0
    mem_req: float = 0.0
    vg_cap: int = 0
    vg_req: int = 0


@dataclass
class UnscheduledPod:
    pod: Pod
    reason: str
    # True when the failure is a transient external-I/O error (exhausted
    # extender retries) rather than a scheduling verdict — the capacity
    # planner retries such trials instead of buying nodes for a blip
    transient: bool = False


@dataclass
class PreemptedPod:
    """A victim evicted by DefaultPreemption (vendored default_preemption.go
    PrepareCandidate deletes victims from the cluster; the simulation records
    them here instead of silently dropping them)."""
    pod: Pod
    node: str
    by: str  # preemptor pod key


@dataclass
class NodeStatus:
    node: Node
    pods: List[Pod] = field(default_factory=list)


@dataclass
class SimulateResult:
    unscheduled: List[UnscheduledPod] = field(default_factory=list)
    node_status: List[NodeStatus] = field(default_factory=list)
    preempted: List[PreemptedPod] = field(default_factory=list)
    # Post-simulation open-local state per node (the reference mutates the
    # node annotation on every storage Bind; here the device carry holds the
    # truth and is decoded once at the end): node name -> NodeLocalStorage
    # with updated vg.requested / device.is_allocated.
    storage: Dict[str, NodeLocalStorage] = field(default_factory=dict)

    def pods_on(self, node_name: str) -> List[Pod]:
        for st in self.node_status:
            if st.node.name == node_name:
                return st.pods
        return []


def _reason_string(n_nodes: int, counts: np.ndarray) -> str:
    """Rebuild the reference's unschedulable diagnostics, e.g.
    '0/4 nodes are available: 3 node(s) had taint..., 1 Insufficient resources.'
    """
    parts = [
        f"{int(counts[f])} {FILTER_MESSAGES[f]}"
        for f in range(NUM_FILTERS)
        if counts[f] > 0
    ]
    detail = ", ".join(parts) if parts else "no nodes in cluster"
    return f"0/{n_nodes} nodes are available: {detail}."


def _count_filter_failures(counts: np.ndarray) -> None:
    """Surface a per-filter rejection histogram (counts are per-(pod,node),
    the same rows _reason_string prints) as
    osim_filter_failure_total{reason=...}."""
    for f in range(min(len(counts), NUM_FILTERS)):
        c = int(counts[f])
        if c > 0:
            metrics.FILTER_FAILURE.inc(c, reason=FILTER_MESSAGES[f])


# jitted preemption-probe programs keyed by (out-of-tree filter tuple,
# packed-layout offsets) — shared across ALL Simulator instances so repeated
# simulate() calls reuse compiled executables instead of retracing per
# instance (see _device_fits_many). Bounded FIFO: a long-lived server sees
# varying table layouts (and identity-keyed plugin closures) per request,
# and an unbounded cache would pin every stale jit + its executables forever.
_PROBE_JIT_CACHE: Dict[tuple, object] = {}
_PROBE_JIT_CACHE_MAX = 32


class Simulator:
    """Owns the device-resident cluster state for one simulation run."""

    def __init__(
        self,
        cluster: ClusterResource,
        weights: Optional[dict] = None,
        use_greed: bool = False,
        mesh=None,
        n_pad: Optional[int] = None,
        profiles=None,
        plugins=None,
        patch_pods=None,
        expand_cache=None,
        extenders=None,
        resident=None,
        preencoded=None,
    ) -> None:
        """`mesh` (jax.sharding.Mesh or None): when set, the node axis of the
        cluster state is sharded across the mesh devices and the same grouped
        scheduling program runs under GSPMD — per-node filter/score work on
        local shards, argmax/min-max/domain reductions as ICI collectives
        (the production analog of the reference's 16-goroutine node fan-out,
        parallelize/parallelism.go:26-57).

        `resident` (engine/resident.ResidentCluster or None): opt-in fast
        path for the serving loop — when the resident state covers this
        exact cluster + bound-pod set, _build_device_state adopts its
        encoder and device planes instead of a full encode_nodes pass.
        Gated off under mesh/extenders/n_pad (those change the encoding);
        any non-covering condition falls back to the full encode, counted
        in osim_resident_fallbacks_total. Never a correctness downgrade:
        coverage is checked by content, not by trust.

        `expand_cache` (dict or None): capacity-search optimization — a dict
        shared across repeated simulations of the SAME apps against varying
        node sets (engine/capacity.plan_capacity). Non-DaemonSet workload
        pods are expanded, patched and validated once, then rebound fresh on
        every reuse; DaemonSet pods stay per-run (their synthesis is
        per-node). Do not share a cache between different app lists.

        `expand_cache` and `patch_pods` compose only for DaemonSets (patched
        every run, like the reference patches on every Simulate): non-DS
        hooks would run once per cache lifetime, silently diverging from
        WithPatchPodsFuncMap semantics — that combination raises.

        `preencoded` ((Encoder, NodeTable) or None): capacity-sweep reuse —
        adopt an already-built encoder and node table (delta-updated by the
        caller to match `cluster.nodes` exactly; see
        capacity._TrialReuse) instead of running encode_nodes. The table's
        node axis must equal `n_pad`. Pods are still registered on the
        shared encoder — registration is content-keyed and idempotent, so
        re-registering the same workload is free and never shifts ids."""
        self.cluster = cluster
        self.use_greed = use_greed
        self.mesh = mesh
        # Node-axis padding override: the capacity search pads every probe of
        # a bisection bracket to the SAME bucket so XLA compiles once for the
        # whole search (padded rows are valid=False and inert).
        self.n_pad = n_pad
        # Out-of-tree device plugins (plugins.DevicePlugin; the extraRegistry
        # analog, simulator.go:190-203).
        from ..plugins import split_registry

        self._extra_filters, self._extra_scores = split_registry(plugins or ())
        # Scheduler extenders (WithExtenders parity, simulator.go:211-216):
        # config-global HTTP filter/prioritize callbacks. Non-empty extenders
        # switch scheduling to the per-pod probe→extend→commit path.
        from .extenders import build_extenders

        self._extenders = build_extenders(extenders)
        # Per-workload-kind pod mutation hooks (WithPatchPodsFuncMap parity,
        # simulator.go:243-249,471-500): kind -> fn(List[Pod]) applied to
        # every pod list generated from that workload kind.
        self._patch_pods = dict(patch_pods or {})
        self._expand_cache = expand_cache
        self._resident = (
            resident
            if resident is not None
            and mesh is None
            and not self._extenders
            and n_pad is None
            else None
        )
        non_ds_hooks = [k for k in self._patch_pods if k != "DaemonSet"]
        if expand_cache is not None and non_ds_hooks:
            # see the docstring: cached expansion would apply these hooks
            # once per cache lifetime instead of once per Simulate
            raise ValueError(
                "expand_cache cannot be combined with patch_pods hooks for "
                f"{non_ds_hooks}: cached pods are patched once per cache "
                "lifetime, not once per run (DaemonSet hooks are fine — "
                "DS pods re-expand every run)"
            )
        # Apiserver-grade validation before anything schedules: the reference
        # validates every imported node and synthesized pod and fails the
        # whole Simulate on the first invalid object (utils.go:495-508).
        from ..core.validation import check_nodes, check_pods

        check_nodes(cluster.nodes)
        check_pods(cluster.pods, where="cluster")
        self.weights = weights_array(weights or DEFAULT_WEIGHTS)
        # Per-schedulerName profile map (parity: scheduler.WithProfiles,
        # simulator.go:209 — each profile is its own framework; pods select
        # one by spec.schedulerName). (weights f32[W], filter_on bool[F]|None).
        if profiles:
            self._profiles = {
                p.scheduler_name: (weights_array(p.weights), p.filter_on_array())
                for p in profiles
            }
            self.weights = self._profiles[profiles[0].scheduler_name][0]
            # Safety net: a config whose only profile renames the scheduler
            # would leave every default-named pod unschedulable (the reference
            # would sit waiting for bind events forever in that misconfig) —
            # apply the first profile to default-named pods instead.
            self._profiles.setdefault(
                DEFAULT_SCHEDULER, self._profiles[profiles[0].scheduler_name]
            )
        else:
            self._profiles = {DEFAULT_SCHEDULER: (self.weights, None)}
        # Extender-managed ignoredByScheduler resources never enter the fit
        # tensors (factory.go:105-130 adds them to NodeResourcesFit's
        # IgnoredResources for every profile).
        ignored_res = [
            r for e in self._extenders for r in e.cfg.ignored_resources
        ]
        self._preencoded = preencoded
        if preencoded is not None:
            if n_pad is None or preencoded[1].alloc.shape[0] != n_pad:
                raise ValueError(
                    "preencoded table node axis "
                    f"{preencoded[1].alloc.shape[0]} must equal n_pad={n_pad}"
                )
            self.enc = preencoded[0]
        else:
            self.enc = Encoder(
                topology_keys=("kubernetes.io/hostname",),
                ignored_resources=ignored_res,
            )
        self._bound: List[Tuple[Pod, str]] = []   # (pod, node name)
        self._pending_cluster: List[Pod] = []
        for pod in cluster.pods:
            if pod.node_name:
                # Copy: preemption may evict pre-bound pods (clearing
                # node_name/phase/annotations), and the caller's cluster must
                # stay pristine for re-simulation by the capacity search.
                self._bound.append((copy.deepcopy(pod), pod.node_name))
            elif pod.scheduler_name in self._profiles:
                # Copy: scheduling mutates node_name/phase, and the caller's
                # cluster must stay pristine for re-simulation (the capacity
                # search probes the same ClusterResource many times).
                self._pending_cluster.append(copy.deepcopy(pod))
            else:
                # Parity: the reference's scheduler never sees pending pods
                # of other schedulers (no framework for the name) and the
                # simulation proceeds without them — but say so, since they
                # reduce the simulated demand (app pods with unknown names
                # DO fail loudly in _schedule_batch_host: they are part of
                # the requested deployment, not pre-existing state).
                from ..utils.tracing import log

                log.warning(
                    "ignoring pending cluster pod %s: no scheduler profile "
                    "named %r", pod.key, pod.scheduler_name,
                )
        # Cluster daemonsets expand against the final node list (core.go:85-96).
        for ds in cluster.daemonsets:
            ds_pods = pods_from_workload(ds, nodes=cluster.nodes)
            self._apply_patch_hooks("DaemonSet", ds_pods)
            self._pending_cluster.extend(ds_pods)
        self._table = None
        self._ns = None
        self._carry = None
        self._storage_takes: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        self._preempted: List[PreemptedPod] = []
        # PDBs ride along for DefaultPreemption's victim classification
        # (parity: the reference syncs PDBs into the fake cluster,
        # simulator.go:388-394, and the preemption plugin lists them).
        from .preemption import PodDisruptionBudget

        self._pdbs = [
            PodDisruptionBudget.from_dict(d)
            for d in cluster.others.get("PodDisruptionBudget", [])
        ]

    # -- device state ------------------------------------------------------
    def _build_device_state(self, all_pods: Sequence[Pod]) -> None:
        """Register every pod that will ever be scheduled, then ship the node
        table once. Registering everything up front keeps the resource axis
        and selector ids stable across app batches.

        With a covering ResidentCluster the node table and NodeStatic come
        from the resident device planes (no encode_nodes pass, no node-plane
        transfer); the per-request selector/port/anti counts are still built
        here — they depend on this request's registered selectors."""
        res = self._resident
        if res is not None:
            reason = res.covers_reason(self.cluster.nodes, self._bound)
            if reason is None:
                self.enc = res.enc
                self._table, self._ns = res.device_state(
                    list(all_pods), self._bound
                )
                sel = initial_selector_counts(self.enc, self._table, self._bound)
                ports = initial_port_counts(self.enc, self._table, self._bound)
                anti = initial_anti_counts(self.enc, self._table, self._bound)
                self._carry = carry_from_table(
                    self._table, sel, port_counts=ports, anti_counts=anti
                )
                self._reshard()
                return
            metrics.RESIDENT_FALLBACKS.inc(reason=reason)
        self.enc.register_pods(list(all_pods))
        for pod, _ in self._bound:
            self.enc.register_pods([pod])
        if self._preencoded is not None:
            # Capacity-sweep reuse: the caller delta-updated this table to
            # match cluster.nodes (asserted cheap: row count and axis width).
            self._table = self._preencoded[1]
            if len(self._table.names) != len(self.cluster.nodes):
                raise ValueError(
                    f"preencoded table holds {len(self._table.names)} rows "
                    f"but the cluster has {len(self.cluster.nodes)} nodes"
                )
        else:
            self._table = encode_nodes(
                self.enc,
                self.cluster.nodes,
                existing_usage=aggregate_usage(self._bound),
                existing_gpu=aggregate_gpu_usage(
                    self.cluster.nodes, self._bound
                ),
                n_pad=(
                    self.n_pad
                    if self.n_pad and self.n_pad >= len(self.cluster.nodes)
                    else None
                ),
            )
        self._ns = node_static_from_table(self.enc, self._table)
        sel = initial_selector_counts(self.enc, self._table, self._bound)
        ports = initial_port_counts(self.enc, self._table, self._bound)
        anti = initial_anti_counts(self.enc, self._table, self._bound)
        self._carry = carry_from_table(
            self._table, sel, port_counts=ports, anti_counts=anti
        )
        self._reshard()

    def _reshard(self) -> None:
        """(Re-)pin the cluster state to the mesh shardings. Called whenever
        ns/carry are rebuilt from host arrays (initial build, axis growth,
        eviction reversal), so every grouped-scheduler jit call sees committed
        sharded inputs and compiles the GSPMD program."""
        if self.mesh is None:
            return
        from ..parallel.mesh import shard_state

        self._ns, self._carry = shard_state(self.mesh, self._ns, self._carry)

    def _schedule_batch_host(self, pods: List[Pod]) -> List[UnscheduledPod]:
        """Dispatch a batch to its scheduler profiles: consecutive runs of one
        schedulerName schedule together (sequential-commit order across the
        whole batch is preserved exactly); pods naming an unconfigured
        scheduler are unschedulable — the reference's scheduler simply never
        sees them (no framework for that name), so the simulation would wait
        forever; failing them with an explicit reason surfaces the mistake."""
        failed: List[UnscheduledPod] = []
        i = 0
        while i < len(pods):
            j = i
            name = pods[i].scheduler_name
            while j < len(pods) and pods[j].scheduler_name == name:
                j += 1
            run_pods = pods[i:j]
            prof = self._profiles.get(name)
            if prof is None:
                failed.extend(
                    UnscheduledPod(
                        p, f"no scheduler profile named {name!r} is configured"
                    )
                    for p in run_pods
                )
            else:
                failed.extend(self._schedule_run(run_pods, prof[0], prof[1]))
            i = j
        return failed

    def _schedule_run(
        self, pods: List[Pod], weights, filter_on
    ) -> List[UnscheduledPod]:
        """Encode one profile run, scan it on device, decode placements."""
        if not pods:
            return []
        if self._extenders:
            # Only pods some extender is interested in pay the per-pod HTTP
            # path; consecutive uninterested runs keep the fused batch scan.
            # Splitting by CONSECUTIVE runs preserves the exact sequential-
            # commit order across the whole batch. The per-pod per-extender
            # interest vector is computed ONCE here and reused by the wave
            # engine / serial loop (it used to be recomputed per extender
            # per pod in the hot loop and again in this split).
            interest = [
                tuple(e.is_interested(p) for e in self._extenders)
                for p in pods
            ]
            failed: List[UnscheduledPod] = []
            i = 0
            while i < len(pods):
                j = i
                interested = any(interest[i])
                while j < len(pods) and interested == any(interest[j]):
                    j += 1
                if interested:
                    failed.extend(
                        self._schedule_run_extenders(
                            pods[i:j], weights, filter_on, interest[i:j]
                        )
                    )
                else:
                    failed.extend(
                        self._schedule_run_batch(pods[i:j], weights, filter_on)
                    )
                i = j
            return failed
        return self._schedule_run_batch(pods, weights, filter_on)

    def _schedule_run_batch(
        self, pods: List[Pod], weights, filter_on
    ) -> List[UnscheduledPod]:
        with span("encode", pods=len(pods)):
            batch = encode_pods(self.enc, pods)
        carry0, ns0 = self._carry, self._ns
        self._carry, self._ns = align_carry(self._carry, self.enc, self._ns)
        if self._carry is not carry0 or self._ns is not ns0:
            self._reshard()
        # Fast path: identical results to the naive scan — static work hoisted
        # per run of identical pods, big runs via per-node trajectories + the
        # light selection scan (ops/fast.py).
        import jax.numpy as jnp

        with span("schedule", pods=len(pods)) as sp:
            (
                self._carry,
                placed_np,
                reasons_np,
                take_np,
                vg_np,
                dev_np,
            ) = schedule_batch_fast(
                self._ns, self._carry, batch, weights,
                filter_on=None if filter_on is None else jnp.asarray(filter_on),
                extra_filters=self._extra_filters,
                extra_scores=self._extra_scores,
            )
            scheduled = int((placed_np >= 0).sum())
            sp.meta["scheduled"] = scheduled
        progress(
            "scheduled batch: %d/%d pods placed in %.2fs",
            scheduled, len(pods), sp.duration,
        )
        metrics.SCHEDULING_ATTEMPTS.inc(len(pods))
        failed: List[UnscheduledPod] = []
        n_nodes = len(self.cluster.nodes)
        fail_counts = np.zeros(reasons_np.shape[1], np.int64)
        for i, pod in enumerate(pods):
            ni = int(placed_np[i])
            if ni >= 0:
                self._bind_placed(pod, ni, take_np[i], vg_np[i], dev_np[i])
            else:
                fail_counts += reasons_np[i]
                failed.append(
                    UnscheduledPod(pod, _reason_string(n_nodes, reasons_np[i]))
                )
        _count_filter_failures(fail_counts)
        return failed

    def _bind_placed(self, pod: Pod, ni: int, take_row, vg_row, dev_row) -> None:
        """Record one placement on the host side (pod fields, bound list,
        storage reversal info) — shared by the batch decode and the extender
        per-pod path."""
        pod.node_name = self._table.names[ni]
        pod.phase = "Running"
        if pod.gpu_mem_request() > 0:
            # Device ids in allocation order, duplicates = multiple
            # shares packed onto one device (parity: the gpu-index
            # annotation codec, utils/pod.go:102-116).
            ids = [
                str(d)
                for d in range(take_row.shape[0])
                for _ in range(int(take_row[d]))
            ]
            if ids:
                pod.meta.annotations[ANNO_GPU_INDEX] = "-".join(ids)
        if vg_row.any() or dev_row.any():
            # Remember which VG slots / devices this pod took so an
            # eviction can reverse the allocation exactly.
            self._storage_takes[pod.key] = (
                np.asarray(vg_row).copy(),
                np.asarray(dev_row).copy(),
            )
        self._bound.append((pod, pod.node_name))
        # the single commit point for successful placements (failed
        # preemption retries roll back before ever reaching here)
        metrics.SCHEDULE_RESULT.inc(result="scheduled")

    def _schedule_run_extenders(
        self, pods: List[Pod], weights, filter_on, interest=None
    ) -> List[UnscheduledPod]:
        """Scheduling with extenders folded in (the split point
        generic_scheduler.go sits at: device filters → extender Filter chain
        (findNodesThatPassExtenders, :345-374) → device scores + extender
        Prioritize × weight × MaxNodeScore/MaxExtenderPriority (:521-555) →
        argmax → device commit). Default path: the wave pipeline
        (engine/extender_wave.py) — probe a whole wave in one device call,
        fan the HTTP chains across pooled connections, commit through a
        conflict-rechecking scan. OSIM_EXTENDER_WAVE=0 falls back to the
        legacy per-pod loop below; both produce byte-identical placements
        (docs/performance.md)."""
        import jax
        import jax.numpy as jnp

        from ..ops.kernels import commit_step, probe_step
        from ..ops.state import pod_rows_from_batch_host
        from ..utils.tracing import log
        from . import extender_wave
        from .extenders import (
            EXTENDER_SCORE_SCALE,
            ExtenderError,
            TransientExtenderError,
        )

        if interest is None:
            interest = [
                tuple(e.is_interested(p) for e in self._extenders)
                for p in pods
            ]
        with span("encode", pods=len(pods)):
            batch = encode_pods(self.enc, pods)
            # host-side row table: per-pod slicing below is numpy (free);
            # sliced straight off device arrays it was ~40 un-jitted device
            # gets PER POD, which dominated the whole extender path
            rows = pod_rows_from_batch_host(batch)
        fo = None if filter_on is None else jnp.asarray(filter_on)
        failed: List[UnscheduledPod] = []
        n_nodes = len(self.cluster.nodes)
        scheduled = 0
        wave = extender_wave.wave_size()
        with span("schedule-extenders", pods=len(pods)) as sp:
            if wave > 0:
                failed, scheduled = extender_wave.run_waves(
                    self, pods, rows, weights, fo, interest, wave
                )
                sp.meta["scheduled"] = scheduled
                pods_iter: List[Pod] = []
            else:
                pods_iter = pods
            for i, pod in enumerate(pods_iter):
                interested = [
                    e for e, hit in zip(self._extenders, interest[i]) if hit
                ]
                row = jax.tree.map(lambda a: a[i], rows)
                mask, score, first_fail = probe_step(
                    self._ns, self._carry, row, weights, fo,
                    self._extra_filters, self._extra_scores,
                )
                mask_np, score_np, ff_np = jax.device_get(
                    (mask, score, first_fail)
                )
                feasible = [
                    self.cluster.nodes[j] for j in range(n_nodes) if mask_np[j]
                ]
                n_device_feasible = len(feasible)
                ext_msgs: Dict[str, str] = {}   # node -> extender failure msg
                error: Optional[str] = None
                error_transient = False
                for ext in interested:
                    if not feasible:
                        break
                    try:
                        feasible, failed_map = ext.filter(pod, feasible)
                    except ExtenderError as e:
                        if ext.is_ignorable:
                            # degraded mode: an erroring (or circuit-open)
                            # ignorable extender is skipped, not fatal
                            metrics.EXTENDER_SKIPPED.inc(endpoint=ext.base)
                            log.warning(
                                "skipping ignorable extender: %s", e
                            )
                            continue
                        error = str(e)
                        error_transient = isinstance(e, TransientExtenderError)
                        break
                    for name, msg in failed_map.items():
                        ext_msgs.setdefault(name, msg)
                if error is not None:
                    failed.append(
                        UnscheduledPod(pod, error, transient=error_transient)
                    )
                    continue
                if not feasible:
                    failed.append(
                        UnscheduledPod(
                            pod,
                            self._extender_reason(
                                n_nodes, mask_np, ff_np, ext_msgs,
                                n_device_feasible,
                            ),
                        )
                    )
                    continue
                combined = {n.name: 0.0 for n in feasible}
                for ext in interested:
                    if not ext.cfg.prioritize_verb:
                        continue
                    try:
                        for host, s in ext.prioritize(pod, feasible).items():
                            if host in combined:
                                combined[host] += s
                    except ExtenderError as e:
                        # prioritize errors are ignored (generic_scheduler.go
                        # :529-536 logs and drops them)
                        metrics.EXTENDER_SKIPPED.inc(endpoint=ext.base)
                        log.warning("extender prioritize failed: %s", e)
                # lowest-node-index tie-break, matching the scan's argmax.
                # The combine is f32, mirroring commit_wave's on-device
                # `score + ext_score` exactly so both paths argmax the same
                # totals bit-for-bit.
                name_index = self._name_index_map()
                best_ni, best_total = -1, -np.inf
                for j in sorted(name_index[n.name] for n in feasible):
                    total = score_np[j] + np.float32(
                        combined[self.cluster.nodes[j].name]
                        * EXTENDER_SCORE_SCALE
                    )
                    if total > best_total:
                        best_ni, best_total = j, total
                self._carry, take, vg_take, dev_take = commit_step(
                    self._ns, self._carry, row, jnp.int32(best_ni)
                )
                take_np, vg_np, dev_np = jax.device_get(
                    (take, vg_take, dev_take)
                )
                self._bind_placed(pod, best_ni, take_np, vg_np, dev_np)
                scheduled += 1
            sp.meta["scheduled"] = scheduled
        progress(
            "scheduled batch (extenders): %d/%d pods placed in %.2fs",
            scheduled, len(pods), sp.duration,
        )
        metrics.SCHEDULING_ATTEMPTS.inc(len(pods))
        return failed

    def _name_index_map(self) -> Dict[str, int]:
        if not hasattr(self, "_name_index"):
            self._name_index = {
                name: i for i, name in enumerate(self._table.names)
            }
        return self._name_index

    @staticmethod
    def _extender_reason(
        n_nodes: int,
        mask_np: np.ndarray,
        ff_np: np.ndarray,
        ext_msgs: Dict[str, str],
        n_device_feasible: int,
    ) -> str:
        """Reason string when the extender chain empties the feasible set:
        device per-filter counts for device-failed nodes + extender failedMap
        messages; nodes an extender dropped without a message get the generic
        'didn't pass extender filter' count (the reference leaves those out of
        the FitError entirely — strictly less informative, so we deviate)."""
        counts = np.zeros(NUM_FILTERS, np.int64)
        for j in range(min(n_nodes, mask_np.shape[0])):
            if not mask_np[j] and ff_np[j] < NUM_FILTERS:
                counts[ff_np[j]] += 1
        _count_filter_failures(counts)
        if n_device_feasible > 0:
            # all device-feasible nodes were dropped by the extender chain;
            # one bounded reason label (extender messages are free-form)
            metrics.FILTER_FAILURE.inc(
                n_device_feasible, reason="node(s) didn't pass extender filter"
            )
        parts = [
            f"{int(counts[f])} {FILTER_MESSAGES[f]}"
            for f in range(NUM_FILTERS)
            if counts[f] > 0
        ]
        by_msg: Dict[str, int] = {}
        for msg in ext_msgs.values():
            by_msg[msg] = by_msg.get(msg, 0) + 1
        for msg in sorted(by_msg):
            parts.append(f"{by_msg[msg]} node(s) {msg}")
        unexplained = n_device_feasible - len(ext_msgs)
        if unexplained > 0:
            parts.append(
                f"{unexplained} node(s) didn't pass extender filter"
            )
        detail = ", ".join(parts) if parts else "no nodes in cluster"
        return f"0/{n_nodes} nodes are available: {detail}."

    # -- preemption (PostFilter) -------------------------------------------
    # lanes per batched probe call: bounds vmap memory on huge clusters
    # (each lane's run_filters is O(N) work) while keeping the jit cache to
    # a handful of bucketed shapes
    _PROBE_CHUNK = 256

    def _pod_eviction_delta(self, v: Pod) -> np.ndarray:
        """Additive packed-column delta of hypothetically evicting pod `v`
        (reverse of its bind contributions; layout per _probe_offsets).
        Computed once per pod per preemption pass (the encoder lookups —
        match_vector/port_ids/anti_ids — are the expensive part)."""
        from ..ops.encode import match_vector, resource_scale

        offs = self._probe_offsets()
        d = np.zeros(offs["__total__"][1], np.float32)

        def plane(key):
            s, e = offs[key]
            return d[s:e]

        free, sel = plane("free"), plane("sel")
        for res, q in v.requests.items():
            if res in self.enc.resources:
                free[self.enc.resources.index(res)] += q / resource_scale(res)
        free[self.enc.resources.index("pods")] += 1.0
        vec = match_vector(self.enc, v)
        m = min(vec.shape[0], sel.shape[0])
        sel[:m] -= vec[:m]  # evicted pod no longer counts
        mem = v.gpu_mem_request()
        if mem > 0:
            gpu = plane("gpu")
            for g in v.gpu_index_ids():
                if 0 <= g < gpu.shape[0]:
                    gpu[g] += np.float32(mem / float(1 << 20))
        takes = self._storage_takes.get(v.key)
        if takes is not None:
            plane("vg")[: takes[0].shape[0]] += takes[0]
            plane("dev")[: takes[1].shape[0]] += takes[1]
        port_any, port_wild, port_ipc = (
            plane("port_any"), plane("port_wild"), plane("port_ipc")
        )
        for pid, wild, ipid in self.enc.port_ids(v):
            if pid < port_any.shape[0]:
                port_any[pid] -= 1.0
                if wild:
                    port_wild[pid] -= 1.0
            if not wild and ipid < port_ipc.shape[0]:
                port_ipc[ipid] -= 1.0
        anti = plane("anti")
        for aid in self.enc.anti_ids(v):
            if aid < anti.shape[0]:
                anti[aid] -= 1.0
        return d

    # Packed probe-column layout: the nine carry planes a hypothetical
    # eviction touches, flattened into ONE f32 vector per node column. One
    # numpy slice builds a lane, one vector add applies a victim delta, one
    # device_put ships a whole chunk — versus nine of each before
    # (the 80k-dispatch hot spot that held preempt_tiered at ~12 pods/s).
    _PROBE_PLANES = (
        ("free", "free", True),        # (packed key, carry field, node-major)
        ("sel", "sel_counts", False),
        ("gpu", "gpu_free", True),
        ("vg", "vg_free", True),
        ("dev", "dev_free", True),
        ("port_any", "port_any", False),
        ("port_wild", "port_wild", False),
        ("port_ipc", "port_ipc", False),
        ("anti", "anti_counts", False),
    )

    def _probe_offsets(self) -> Dict[str, Tuple[int, int]]:
        """(start, end) of each plane inside the packed probe vector, from
        the live carry's shapes (static at trace time)."""
        offs: Dict[str, Tuple[int, int]] = {}
        pos = 0
        for key, field_name, node_major in self._PROBE_PLANES:
            arr = getattr(self._carry, field_name)
            n = arr.shape[1] if node_major else arr.shape[0]
            offs[key] = (pos, pos + n)
            pos += n
        offs["__total__"] = (0, pos)
        return offs

    def _carry_host_packed(self) -> np.ndarray:
        """f32[T, N] — every node's packed probe column, cached by carry
        identity (any carry swap — bind, evict, reshard — builds a new
        pytree and invalidates it). Host-side so lane construction is a
        numpy slice, not an un-jitted device get."""
        cached = getattr(self, "_carry_np", None)
        if cached is None or cached[0] is not self._carry:
            planes = []
            for key, field_name, node_major in self._PROBE_PLANES:
                a = np.asarray(getattr(self._carry, field_name), np.float32)
                planes.append(a.T if node_major else a)
            self._carry_np = (self._carry, np.concatenate(planes, axis=0))
        return self._carry_np[1]

    def _eviction_cols(
        self, ni: int, on_node, keep_ids, delta_cache: Optional[dict] = None
    ) -> np.ndarray:
        """Packed node column state with ONLY the kept pods: the current
        carry column plus the cached eviction delta of every pod not kept.
        With the shared `delta_cache`, repeated reprieve rounds cost one
        vector add per evicted pod instead of re-encoding it (linear, not
        quadratic, in queue length)."""
        cols = self._carry_host_packed()[:, ni].copy()
        for v in on_node:
            if id(v) in keep_ids:
                continue
            if delta_cache is not None:
                d = delta_cache.get(id(v))
                if d is None:
                    d = delta_cache[id(v)] = self._pod_eviction_delta(v)
            else:
                d = self._pod_eviction_delta(v)
            cols += d
        return cols

    def _device_fits_many(self, bound_by_node):
        """fits_many_fn for lane-parallel victim selection: evaluates ALL
        candidate (node, remaining-set) states of one reprieve round in a
        single vmapped device call (chunked at _PROBE_CHUNK lanes), running
        the REAL filter kernel on each post-eviction column (parity:
        selectVictimsOnNode's dry run of the filter plugins,
        default_preemption.go:598-626, fanned out like its parallel
        checkNode goroutines :560-576). Replaces one device round trip per
        (node, victim-set) probe with one per round."""
        import jax
        import jax.numpy as jnp

        from ..ops.encode import encode_pods
        from ..ops.kernels import run_filters
        from ..ops.state import pod_rows_from_batch_host

        # One jitted probe per (out-of-tree filter set, packed layout),
        # cached at module level: a per-Simulator closure would retrace +
        # recompile the whole vmapped filter family on EVERY simulate() call
        # (each capacity probe, each server request, each bench repeat) — the
        # compile tax that made preempt_tiered run at 11 pods/s warm. Lanes
        # arrive as packed f32[lanes, T] vectors (see _PROBE_PLANES) and are
        # unpacked with static offsets inside the jit.
        offs = self._probe_offsets()
        key = (
            self._extra_filters,
            tuple(sorted(offs.items())),
        )
        probe = _PROBE_JIT_CACHE.get(key)
        metrics.COMPILE_CACHE.inc(
            event="hit" if probe is not None else "miss"
        )
        if probe is None:
            extra_filters = self._extra_filters
            o = dict(offs)

            def pl(col, k):
                s, e = o[k]
                return col[s:e]

            @jax.jit
            def probe_many(ns, carry, row, nis, cols, filter_on):
                def one(ni, col):
                    carry2 = carry._replace(
                        free=carry.free.at[ni].set(pl(col, "free")),
                        sel_counts=carry.sel_counts.at[:, ni].set(pl(col, "sel")),
                        gpu_free=carry.gpu_free.at[ni].set(pl(col, "gpu")),
                        vg_free=carry.vg_free.at[ni].set(pl(col, "vg")),
                        dev_free=carry.dev_free.at[ni].set(pl(col, "dev")),
                        port_any=carry.port_any.at[:, ni].set(pl(col, "port_any")),
                        port_wild=carry.port_wild.at[:, ni].set(pl(col, "port_wild")),
                        port_ipc=carry.port_ipc.at[:, ni].set(pl(col, "port_ipc")),
                        anti_counts=carry.anti_counts.at[:, ni].set(pl(col, "anti")),
                    )
                    # same filter set the pod's profile schedules with (mask
                    # + out-of-tree plugins) — a disabled filter must not
                    # veto a node here either
                    mask, _ = run_filters(
                        ns, carry2, row, filter_on, extra_filters
                    )
                    return mask[ni]

                return jax.vmap(one)(nis, cols)

            while len(_PROBE_JIT_CACHE) >= _PROBE_JIT_CACHE_MAX:
                _PROBE_JIT_CACHE.pop(next(iter(_PROBE_JIT_CACHE)))
            probe = _PROBE_JIT_CACHE[key] = probe_many

        row_cache: Dict[str, object] = {}
        delta_cache: dict = {}
        name_index = self._name_index_map()

        def fits_many(pod: Pod, items) -> List[bool]:
            if not items:
                return []
            prof = self._profiles.get(pod.scheduler_name)
            fo = prof[1] if prof is not None else None
            fo = (
                jnp.ones(len(FILTER_MESSAGES), bool)
                if fo is None
                else jnp.asarray(fo)
            )
            row = row_cache.get(pod.key)
            if row is None:
                batch = encode_pods(self.enc, [pod])
                # host rows: slicing device arrays is ~40 un-jitted
                # gets per preemptor
                row = jax.tree.map(
                    lambda a: a[0], pod_rows_from_batch_host(batch)
                )
                row_cache[pod.key] = row
            out: List[bool] = []
            for start in range(0, len(items), self._PROBE_CHUNK):
                chunk = items[start : start + self._PROBE_CHUNK]
                nis = np.array(
                    [name_index[node.name] for node, _ in chunk], np.int32
                )
                col_list = [
                    self._eviction_cols(
                        name_index[node.name],
                        bound_by_node.get(node.name, []),
                        {id(p) for p in remaining},
                        delta_cache,
                    )
                    for node, remaining in chunk
                ]
                stacked = np.stack(col_list)   # f32[lanes, T] packed columns
                # pad the lane axis to a power-of-FOUR bucket (4/16/64/256):
                # each distinct lane count would otherwise compile its own
                # vmapped run_filters executable, and the compiles dominate
                # preemption wall time on cold caches (bench preempt_tiered)
                c = len(chunk)
                c_pad = 4
                while c_pad < c:
                    c_pad *= 4
                if c_pad != c:
                    nis = np.concatenate([nis, np.repeat(nis[:1], c_pad - c)])
                    stacked = np.concatenate(
                        [stacked, np.repeat(stacked[:1], c_pad - c, axis=0)]
                    )
                # dispatch through the locally-resolved probe: a fits_many
                # closure must keep the jit whose offsets match the columns
                # IT builds, even if a later rebuild resolved a newer one
                res = probe(
                    self._ns, self._carry, row, jnp.asarray(nis),
                    jnp.asarray(stacked), fo,
                )
                out.extend(bool(b) for b in np.asarray(res)[:c])
            return out

        return fits_many

    def _try_preemptions(
        self, failed: List[UnscheduledPod]
    ) -> List[UnscheduledPod]:
        """DefaultPreemption pass over this batch's failures: pods with
        priority > 0 may evict lower-priority pods (engine/preemption.py).
        Successful preemptors are rescheduled immediately; victims are
        removed from the cluster (the reference deletes them,
        default_preemption.go PrepareCandidate)."""
        from .extenders import ExtenderError
        from .preemption import try_preempt

        still_failed: List[UnscheduledPod] = []
        bound_by_node: Optional[Dict[str, List[Pod]]] = None
        fits_many_fn = None
        for u in failed:
            pod = u.pod
            if pod.priority <= 0:
                still_failed.append(u)
                continue
            if bound_by_node is None:
                bound_by_node = {}
                for p, node_name in self._bound:
                    bound_by_node.setdefault(node_name, []).append(p)
                fits_many_fn = self._device_fits_many(bound_by_node)
            try:
                res = try_preempt(
                    pod, self.cluster.nodes, bound_by_node, self._pdbs,
                    fits_many_fn=fits_many_fn, extenders=self._extenders,
                )
            except ExtenderError as e:
                # a non-ignorable extender failed ProcessPreemption: the
                # reference aborts this pod's preemption with the error
                # (default_preemption.go:373-374) — the pod stays failed
                # with the extender's message appended
                metrics.PREEMPTION_ATTEMPTS.inc(outcome="extender_error")
                still_failed.append(
                    UnscheduledPod(pod=pod, reason=f"{u.reason}; {e}")
                )
                continue
            if res is None or not res.victims:
                metrics.PREEMPTION_ATTEMPTS.inc(outcome="no_candidates")
                still_failed.append(u)
                continue
            # The host-side victim model covers resources only; the device
            # retry additionally enforces spread/affinity/storage/GPU. Snapshot
            # everything eviction touches so a failed retry rolls back instead
            # of leaving pods evicted for nothing.
            snapshot = (
                self._carry,
                list(self._bound),
                dict(self._storage_takes),
                len(self._preempted),
                self._snapshot_bindings(res.victims),
            )
            self._evict(res.victims, res.node, by=pod.key)
            # Reschedule the preemptor now that room exists. The reference
            # nominates the node and requeues; the retried pod normally lands
            # there but isn't pinned — same here (scores decide).
            retry_failed = self._schedule_batch_host([pod])
            if retry_failed:
                carry, bound_list, takes, n_pre, fields = snapshot
                self._carry = carry
                self._bound = bound_list
                self._storage_takes = takes
                del self._preempted[n_pre:]
                self._restore_bindings(fields)
                metrics.PREEMPTION_ATTEMPTS.inc(outcome="retry_failed")
                still_failed.extend(retry_failed)
            else:
                # preemption committed: victims stay evicted — count them
                # here, NOT in _evict (the rollback path above un-evicts)
                metrics.PREEMPTION_ATTEMPTS.inc(outcome="succeeded")
                metrics.SCHEDULE_RESULT.inc(
                    len(res.victims), result="preempted"
                )
                bound_by_node = None  # placements changed; rebuild lazily
        return still_failed

    def _evict(self, victims: List[Pod], node_name: str, by: str) -> None:
        """Remove victims from a node and reverse their carry contributions."""
        victim_keys = {id(v) for v in victims}
        self._bound = [
            (p, n) for p, n in self._bound if id(p) not in victim_keys
        ]
        ni = self._table.names.index(node_name)
        free = np.asarray(self._carry.free).copy()
        sel = np.asarray(self._carry.sel_counts).copy()
        gpu = np.asarray(self._carry.gpu_free).copy()
        vg = np.asarray(self._carry.vg_free).copy()
        dev = np.asarray(self._carry.dev_free).copy()
        port_any = np.asarray(self._carry.port_any).copy()
        port_wild = np.asarray(self._carry.port_wild).copy()
        port_ipc = np.asarray(self._carry.port_ipc).copy()
        anti = np.asarray(self._carry.anti_counts).copy()
        from ..ops.encode import resource_scale

        from ..ops.encode import match_vector

        for v in victims:
            for res, q in v.requests.items():
                r = self.enc.resources.index(res) if res in self.enc.resources else -1
                if r >= 0:
                    free[ni, r] += q / resource_scale(res)
            free[ni, self.enc.resources.index("pods")] += 1.0
            vec = match_vector(self.enc, v)
            m = min(vec.shape[0], sel.shape[0])
            sel[:m, ni] -= vec[:m]
            mem = v.gpu_mem_request()
            if mem > 0:
                for d in v.gpu_index_ids():
                    if 0 <= d < gpu.shape[1]:
                        gpu[ni, d] += np.float32(mem / float(1 << 20))
            takes = self._storage_takes.pop(v.key, None)
            if takes is not None:
                vg[ni, : takes[0].shape[0]] += takes[0]
                dev[ni, : takes[1].shape[0]] += takes[1]
            for pid, wild, ipid in self.enc.port_ids(v):
                if pid < port_any.shape[0]:
                    port_any[pid, ni] -= 1.0
                    if wild:
                        port_wild[pid, ni] -= 1.0
                if not wild and ipid < port_ipc.shape[0]:
                    port_ipc[ipid, ni] -= 1.0
            for aid in self.enc.anti_ids(v):
                if aid < anti.shape[0]:
                    anti[aid, ni] -= 1.0
            self._reset_bindings([v])
            self._preempted.append(PreemptedPod(pod=v, node=node_name, by=by))
        self._carry = self._carry._replace(
            free=free, sel_counts=sel, gpu_free=gpu, vg_free=vg, dev_free=dev,
            port_any=port_any, port_wild=port_wild, port_ipc=port_ipc,
            anti_counts=anti,
        )
        self._reshard()

    # The engine's FULL mutation surface on a pod is node_name / phase / the
    # gpu-index annotation (placement at _schedule_run, eviction at _evict) —
    # everything else is tracked outside the object. The three helpers below
    # are the only places that field set appears; extend all of them together.

    @staticmethod
    def _reset_bindings(pods: List[Pod]) -> None:
        """Return pods to their pre-scheduling state (expand-cache reuse and
        preemption eviction)."""
        for p in pods:
            p.node_name = ""
            p.phase = "Pending"
            p.meta.annotations.pop(ANNO_GPU_INDEX, None)

    @staticmethod
    def _snapshot_bindings(pods: List[Pod]) -> list:
        return [
            (p, p.node_name, p.phase, p.meta.annotations.get(ANNO_GPU_INDEX))
            for p in pods
        ]

    @staticmethod
    def _restore_bindings(fields: list) -> None:
        for p, node_name, phase, gpu_anno in fields:
            p.node_name, p.phase = node_name, phase
            if gpu_anno is not None:
                p.meta.annotations[ANNO_GPU_INDEX] = gpu_anno
            else:
                p.meta.annotations.pop(ANNO_GPU_INDEX, None)

    @staticmethod
    def _finalize_unscheduled(
        failed: List[UnscheduledPod],
    ) -> List[UnscheduledPod]:
        """Unscheduled commit point: pods that survived the preemption pass
        are final for this batch."""
        if failed:
            metrics.SCHEDULE_RESULT.inc(len(failed), result="unscheduled")
        return failed

    def _apply_patch_hooks(self, kind: str, pods: List[Pod]) -> None:
        """WithPatchPodsFuncMap parity (simulator.go:243-249,471-500): let the
        caller mutate the pods generated from each workload kind before they
        are validated/ordered/scheduled."""
        hook = self._patch_pods.get(kind)
        if hook is not None and pods:
            hook(pods)

    def _order(self, pods: List[Pod]) -> List[Pod]:
        return order_pods(pods, self.cluster.nodes, use_greed=self.use_greed)

    def _expand_apps(self, apps: Sequence[AppResource]) -> List[List[Pod]]:
        """Expand every app's workloads into ordered pod lists (cache-aware;
        shared by run() and run_scenarios())."""
        from ..core.validation import check_pods

        app_pods: List[List[Pod]] = []
        with span("expand-workloads"):
            for app in apps:
                pods: List[Pod] = []
                # keyed by POSITION in the app list, not name — the Simon
                # CR does not forbid duplicate app names, and the cache
                # contract already fixes the app list across reuses
                cache_key = len(app_pods)
                cached = (
                    self._expand_cache.get(cache_key)
                    if self._expand_cache is not None
                    else None
                )
                if self._expand_cache is not None:
                    metrics.EXPAND_CACHE.inc(
                        event="hit" if cached is not None else "miss"
                    )
                fresh_entry: Dict[int, List[Pod]] = {}
                fresh_validate: List[Pod] = []
                for idx, obj in enumerate(app.objects):
                    kind = obj.get("kind", "")
                    if kind not in WORKLOAD_KINDS:
                        continue
                    if kind != "DaemonSet" and cached is not None:
                        wl_pods = cached[idx]
                        self._reset_bindings(wl_pods)
                    else:
                        wl_pods = pods_from_workload(
                            obj, nodes=self.cluster.nodes
                        )
                        self._apply_patch_hooks(kind, wl_pods)
                        fresh_validate.extend(wl_pods)
                        if kind != "DaemonSet":
                            fresh_entry[idx] = wl_pods
                    pods.extend(wl_pods)
                # Cached pods were validated when first expanded; only
                # newly generated ones (first run, or DaemonSet pods,
                # whose synthesis is per-node) need checking.
                check_pods(fresh_validate, where=f"app {app.name}")
                if self._expand_cache is not None and cached is None:
                    self._expand_cache[cache_key] = fresh_entry
                app_pods.append(self._order(pods))
        return app_pods

    # -- public ------------------------------------------------------------
    def run(self, apps: Sequence[AppResource]) -> SimulateResult:
        with span("simulate", nodes=len(self.cluster.nodes), apps=len(apps)):
            app_pods = self._expand_apps(apps)

            with span("encode-cluster"):
                self._build_device_state(
                    self._pending_cluster + [p for pods in app_pods for p in pods]
                )

            result = SimulateResult()
            # RunCluster: the cluster's own pending pods schedule first.
            result.unscheduled.extend(
                self._finalize_unscheduled(
                    self._try_preemptions(
                        self._schedule_batch_host(
                            self._order(self._pending_cluster)
                        )
                    )
                )
            )
            # ScheduleApp: each app in configured order.
            for pods in app_pods:
                result.unscheduled.extend(
                    self._finalize_unscheduled(
                        self._try_preemptions(self._schedule_batch_host(pods))
                    )
                )

            with span("decode-result"):
                by_node: Dict[str, NodeStatus] = {
                    n.name: NodeStatus(node=n) for n in self.cluster.nodes
                }
                for pod, node_name in self._bound:
                    if node_name in by_node:
                        by_node[node_name].pods.append(pod)
                result.node_status = list(by_node.values())
                result.storage = self._storage_status()
                result.preempted = list(self._preempted)
            return result

    def run_scenarios(
        self,
        apps: Sequence[AppResource],
        scenarios: Sequence[Scenario],
        materialize: bool = True,
        *,
        reuse_state: bool = False,
        s_floor: int = 0,
    ):
        """One batched device sweep over S scenarios sharing this cluster and
        app list: expand/encode once, stack the scan carry with a leading
        scenario axis, and run the vmapped commit engine
        (ops.fast.schedule_scenarios) — per-scenario placements are
        bit-identical to S serial runs because invalid rows are inert in
        every filter/score/commit (see ops/kernels.py) and the scan itself is
        the naive engine every fast path proves equivalence against.

        Returns a list of per-scenario SimulateResults (materialize=True) or
        lightweight ScenarioOutcomes (materialize=False; the capacity
        planner's verdict mode — no binding, no per-pod SCHEDULE_RESULT
        metrics). Returns None when the workload needs per-scenario serial
        control flow this path cannot batch: any pod with priority > 0
        (preemption evicts different victims per lane) or a pre-bound pod on
        a scenario-masked node. Callers (simulate_batch) fall back to serial
        simulate() per scenario."""
        scenarios = list(scenarios)
        n_nodes = len(self.cluster.nodes)
        keeps = [sc.keep_mask(n_nodes) for sc in scenarios]
        with span(
            "simulate-scenarios",
            nodes=n_nodes, scenarios=len(scenarios), apps=len(apps),
        ):
            app_pods = self._expand_apps(apps)
            all_pods = self._pending_cluster + [
                p for pods in app_pods for p in pods
            ]
            if any(p.priority > 0 for p in all_pods):
                return None
            for keep in keeps:
                if keep is None:
                    continue
                dropped = {
                    n.name
                    for n, k in zip(self.cluster.nodes, keep)
                    if not k
                }
                if any(name in dropped for _, name in self._bound):
                    return None
            # reuse_state (ScenarioSession): the table/carry from the prior
            # pack are still valid for this cluster — skip the encode pass.
            # Safe because encode_pods registers each batch's pods itself
            # (content-keyed, idempotent) and align_carry_scenarios below
            # absorbs any encoder growth into the stacked carry.
            if not (reuse_state and self._table is not None):
                with span("encode-cluster"):
                    self._build_device_state(all_pods)
            # Per-scenario valid masks over the shared padded node axis: pad
            # rows stay False; masked real rows flip False per lane (inert in
            # every kernel, so lanes see exactly their own node set).
            table_valid = np.asarray(self._table.valid)
            valid_rows = []
            n_nodes_s = []
            for keep in keeps:
                v = table_valid.copy()
                if keep is not None:
                    v[:n_nodes] &= keep
                    n_nodes_s.append(int(keep.sum()))
                else:
                    n_nodes_s.append(n_nodes)
                valid_rows.append(v)
            weight_rows = [
                np.asarray(
                    weights_array(sc.weights)
                    if sc.weights is not None
                    else self.weights
                )
                for sc in scenarios
            ]
            # Scenario-axis bucketing: pad to SCENARIO_BUCKET with copies of
            # lane 0 (results discarded) so one compile serves nearby sweep
            # sizes, mirroring the node-axis round_up(n, 64) in encode.
            s_real = len(scenarios)
            # s_floor (ScenarioSession): pad at least to the previous call's
            # padded width so consecutive serving packs of nearby sizes hit
            # the same compiled program instead of bouncing between buckets.
            s_pad = scenario_bucket(s_real, floor=s_floor)
            metrics.LANE_OCCUPANCY.observe(s_real / s_pad)
            valid_rows += [valid_rows[0]] * (s_pad - s_real)
            weight_rows += [weight_rows[0]] * (s_pad - s_real)
            import jax.numpy as jnp

            valid_s = jnp.asarray(np.stack(valid_rows))
            weights_s = jnp.asarray(
                np.stack(weight_rows).astype(np.float32)
            )
            carry_s = stack_carry(self._carry, s_pad)
            # Under a 1-D mesh the sweep shards its LANE axis across the same
            # devices (scenario lanes are independent — no collectives), with
            # the node tensors replicated per device. Under an explicit 2-D
            # (scenarios, nodes) mesh (parallel.mesh.product_mesh_2d) the
            # node axis is sharded too — node tables are no longer
            # replicated, and the per-node kernels run on local shards with
            # GSPMD lowering the reductions to collectives. A dedicated
            # local (ns_sweep, smesh) pair keeps the sweep placement out of
            # self._ns, whose node-mesh sharding the serial path owns.
            smesh = None
            ns_sweep = self._ns
            shard_fn = None
            if self.mesh is not None:
                from ..parallel.mesh import (
                    NODE_AXIS,
                    SCENARIO_AXIS,
                    scenario_mesh,
                    shard_scenarios,
                    shard_scenarios_2d,
                )

                axes = self.mesh.axis_names
                if SCENARIO_AXIS in axes and NODE_AXIS in axes:
                    s_devs = int(self.mesh.shape[SCENARIO_AXIS])
                    n_devs = int(self.mesh.shape[NODE_AXIS])
                    n_axis = int(self._table.alloc.shape[0])
                    if s_pad % s_devs == 0 and n_axis % n_devs == 0:
                        smesh = self.mesh
                        shard_fn = shard_scenarios_2d
                    else:
                        progress(
                            "scenario sweep unsharded: %d lanes x %d node "
                            "rows not divisible by the %dx%d mesh",
                            s_pad, n_axis, s_devs, n_devs,
                        )
                else:
                    ndev = int(self.mesh.devices.size)
                    if s_pad % ndev == 0:
                        smesh = scenario_mesh(self.mesh)
                        shard_fn = shard_scenarios
                    else:
                        progress(
                            "scenario sweep unsharded: %d lanes not "
                            "divisible by %d devices", s_pad, ndev,
                        )
                if smesh is not None:
                    ns_sweep, carry_s, valid_s, weights_s = shard_fn(
                        smesh, self._ns, carry_s, valid_s, weights_s
                    )
            lanes = [
                {"placed": [], "failed": [], "fail_counts": None}
                for _ in range(s_real)
            ]
            # Same batch structure as run(): cluster-pending first, then each
            # app in configured order, split into consecutive schedulerName
            # runs exactly like _schedule_batch_host.
            batches = [self._order(self._pending_cluster)] + app_pods
            for pods in batches:
                i = 0
                while i < len(pods):
                    j = i
                    name = pods[i].scheduler_name
                    while j < len(pods) and pods[j].scheduler_name == name:
                        j += 1
                    run_pods = pods[i:j]
                    i = j
                    if name not in self._profiles:
                        reason = (
                            f"no scheduler profile named {name!r} is "
                            "configured"
                        )
                        for lane in lanes:
                            lane["failed"].extend(
                                UnscheduledPod(p, reason) for p in run_pods
                            )
                        continue
                    with span("encode", pods=len(run_pods)):
                        batch = encode_pods(self.enc, run_pods)
                    ns_prev, carry_prev = self._ns, carry_s
                    carry_s, self._ns = align_carry_scenarios(
                        carry_s, self.enc, self._ns
                    )
                    if smesh is not None and (
                        carry_s is not carry_prev
                        or self._ns is not ns_prev
                    ):
                        # growth rebuilt leaves off-mesh; re-pin before the
                        # next sharded call (identity check above keeps the
                        # steady state free of redundant device_puts)
                        ns_sweep, carry_s, valid_s, weights_s = shard_fn(
                            smesh, self._ns, carry_s, valid_s, weights_s,
                        )
                    elif smesh is None:
                        ns_sweep = self._ns
                    with span(
                        "schedule-scenarios",
                        pods=len(run_pods), scenarios=s_real,
                    ) as sp:
                        (
                            carry_s,
                            nodes_np,
                            reasons_np,
                            take_np,
                            vg_np,
                            dev_np,
                        ) = schedule_scenarios_host(
                            ns_sweep, carry_s, batch,
                            weights_s, valid_s, s_real,
                        )
                        sp.meta["scheduled"] = int((nodes_np >= 0).sum())
                    progress(
                        "scheduled scenario batch: %d/%d (pod,lane) placed "
                        "in %.2fs",
                        int((nodes_np >= 0).sum()),
                        len(run_pods) * s_real,
                        sp.duration,
                    )
                    metrics.SCHEDULING_ATTEMPTS.inc(len(run_pods) * s_real)
                    for s, lane in enumerate(lanes):
                        for p_idx, pod in enumerate(run_pods):
                            ni = int(nodes_np[s, p_idx])
                            if ni >= 0:
                                lane["placed"].append((
                                    pod, ni,
                                    take_np[s, p_idx],
                                    vg_np[s, p_idx],
                                    dev_np[s, p_idx],
                                ))
                            else:
                                if lane["fail_counts"] is None:
                                    lane["fail_counts"] = np.zeros(
                                        reasons_np.shape[2], np.int64
                                    )
                                lane["fail_counts"] += reasons_np[s, p_idx]
                                lane["failed"].append(UnscheduledPod(
                                    pod,
                                    _reason_string(
                                        n_nodes_s[s], reasons_np[s, p_idx]
                                    ),
                                ))
            import jax

            vg_free_s, dev_free_s = jax.device_get(
                (carry_s.vg_free, carry_s.dev_free)
            )
            vg_free_s = np.asarray(vg_free_s)
            dev_free_s = np.asarray(dev_free_s)
            if not materialize:
                return self._scenario_outcomes(
                    scenarios, keeps, lanes, vg_free_s, dev_free_s
                )
            # Materialize lane by lane against the shared pod objects: bind,
            # snapshot a deep copy, reset — each SimulateResult owns its pods
            # so lanes cannot alias each other's mutations.
            base_bound = list(self._bound)
            results = []
            with span("decode-scenarios"):
                for s, lane in enumerate(lanes):
                    keep = keeps[s]
                    self._bound = list(base_bound)
                    self._storage_takes = {}
                    for pod, ni, take, vg, dev in lane["placed"]:
                        self._bind_placed(pod, ni, take, vg, dev)
                    if lane["fail_counts"] is not None:
                        _count_filter_failures(lane["fail_counts"])
                    self._finalize_unscheduled(lane["failed"])
                    result = SimulateResult()
                    result.unscheduled = list(lane["failed"])
                    by_node: Dict[str, NodeStatus] = {
                        n.name: NodeStatus(node=n)
                        for idx, n in enumerate(self.cluster.nodes)
                        if keep is None or keep[idx]
                    }
                    for pod, node_name in self._bound:
                        if node_name in by_node:
                            by_node[node_name].pods.append(pod)
                    result.node_status = list(by_node.values())
                    result.storage = self._storage_status(
                        vg_free_s[s], dev_free_s[s], keep=keep
                    )
                    results.append(copy.deepcopy(result))
                    self._reset_bindings([t[0] for t in lane["placed"]])
            self._bound = base_bound
            self._storage_takes = {}
            return results

    def _scenario_outcomes(
        self, scenarios, keeps, lanes, vg_free_s, dev_free_s
    ) -> List[ScenarioOutcome]:
        """Aggregate each lane into the totals satisfy_resource_setting reads,
        without materializing node_status (verdict mode)."""
        outcomes = []
        for s, lane in enumerate(lanes):
            keep = keeps[s]
            out = ScenarioOutcome(
                name=scenarios[s].name or f"scenario-{s}",
                unscheduled=len(lane["failed"]),
            )
            for idx, node in enumerate(self.cluster.nodes):
                if keep is not None and not keep[idx]:
                    continue
                out.cpu_alloc += node.allocatable.get("cpu", 0)
                out.mem_alloc += node.allocatable.get("memory", 0)
            # requests over every bound pod: pre-bound (all on kept nodes —
            # gated in run_scenarios) plus this lane's placements
            for pod, _ in self._bound:
                out.cpu_req += pod.requests.get("cpu", 0)
                out.mem_req += pod.requests.get("memory", 0)
            for pod, *_rest in lane["placed"]:
                out.cpu_req += pod.requests.get("cpu", 0)
                out.mem_req += pod.requests.get("memory", 0)
            storage = self._storage_status(
                vg_free_s[s], dev_free_s[s], keep=keep
            )
            for st in storage.values():
                for vg in st.vgs:
                    out.vg_cap += vg.capacity
                    out.vg_req += vg.requested
            outcomes.append(out)
        return outcomes

    def _storage_status(
        self,
        vg_free: Optional[np.ndarray] = None,
        dev_free: Optional[np.ndarray] = None,
        keep: Optional[np.ndarray] = None,
    ) -> Dict[str, NodeLocalStorage]:
        """Decode the final vg_free/dev_free carry back into per-node storage
        state (parity: the bind-updated simon/node-local-storage annotations,
        plugin/open-local.go:221-247). A scenario fan-out passes its own
        carry slices plus its node keep-mask; the default decodes the live
        carry over every cluster node."""
        out: Dict[str, NodeLocalStorage] = {}
        if vg_free is None or dev_free is None:
            if self._carry is None:
                return out
            vg_free = np.asarray(self._carry.vg_free)
            dev_free = np.asarray(self._carry.dev_free)
        for i, node in enumerate(self.cluster.nodes):
            if keep is not None and not keep[i]:
                continue
            st = node.local_storage()
            if st is None:
                continue
            vgs = [
                LocalVG(
                    name=vg.name,
                    capacity=vg.capacity,
                    requested=max(
                        0,
                        min(
                            vg.capacity,
                            vg.capacity
                            - int(round(float(vg_free[i, j]))) * (1 << 20),
                        ),
                    ),
                )
                for j, vg in enumerate(st.vgs[: vg_free.shape[1]])
            ]
            devs = [
                LocalDevice(
                    name=d.name,
                    capacity=d.capacity,
                    media_type=d.media_type,
                    is_allocated=dev_free[i, j] < 0.5,
                )
                for j, d in enumerate(st.devices[: dev_free.shape[1]])
            ]
            out[node.name] = NodeLocalStorage(vgs=vgs, devices=devs)
        return out


def simulate(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    weights: Optional[dict] = None,
    use_greed: bool = False,
    mesh=None,
    n_pad: Optional[int] = None,
    profiles=None,
    plugins=None,
    patch_pods=None,
    expand_cache=None,
    extenders=None,
    resident=None,
) -> SimulateResult:
    """One-shot simulation (parity: simulator.Simulate, core.go:67-119).

    `plugins`: out-of-tree DevicePlugin registry (plugins/__init__.py).
    `patch_pods`: {workload kind: fn(List[Pod])} mutation hooks applied to
    generated pods (WithPatchPodsFuncMap parity).
    `expand_cache`: see Simulator — share one dict across re-simulations of
    the same apps (capacity search) to expand/validate workloads once.
    `extenders`: ExtenderConfig list (models/profiles.py) — HTTP
    filter/prioritize callbacks (WithExtenders parity).
    `resident`: optional engine/resident.ResidentCluster serving fast path
    (see Simulator)."""
    return Simulator(
        cluster, weights=weights, use_greed=use_greed, mesh=mesh, n_pad=n_pad,
        profiles=profiles, plugins=plugins, patch_pods=patch_pods,
        expand_cache=expand_cache, extenders=extenders, resident=resident,
    ).run(apps)


def batch_ineligible_reason(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    scenarios: Sequence[Scenario],
    use_greed: bool = False,
    mesh=None,
    profiles=None,
    plugins=None,
    extenders=None,
) -> Optional[str]:
    """Why this sweep cannot take the batched (vmapped) path, or None when it
    can. Every gate names a feature whose control flow is per-scenario serial
    (host round-trips per pod, node-set-dependent expansion/ordering) —
    simulate_batch falls back to serial simulate() per scenario for these.

    A mesh no longer gates: run_scenarios shards the scenario axis across
    the mesh devices (parallel.mesh.scenario_mesh) — `mesh` stays in the
    signature so callers probing eligibility need not special-case it."""
    if extenders:
        return "scheduler extenders"
    if profiles:
        return "scheduler profiles"
    if plugins:
        return "out-of-tree device plugins"
    masked = any(
        sc.node_count is not None or sc.node_valid is not None
        for sc in scenarios
    )
    if not masked:
        return None
    if use_greed:
        # greed_sort keys on cluster_totals(nodes): per-scenario node sets
        # would need per-scenario pod orderings
        return "greed ordering with per-scenario node sets"
    if cluster.daemonsets or any(
        obj.get("kind") == "DaemonSet"
        for app in apps
        for obj in app.objects
    ):
        # DaemonSet expansion is per-node: lanes with different node sets
        # would need different pod lists
        return "DaemonSets with per-scenario node sets"
    return None


def _scenario_cluster(
    cluster: ClusterResource, sc: Scenario
) -> ClusterResource:
    """The serial-fallback view of one scenario: the cluster restricted to the
    lane's kept nodes (shares pod/daemonset/other objects — Simulator copies
    what it mutates)."""
    keep = sc.keep_mask(len(cluster.nodes))
    if keep is None:
        return cluster
    return ClusterResource(
        nodes=[n for n, k in zip(cluster.nodes, keep) if k],
        pods=list(cluster.pods),
        daemonsets=list(cluster.daemonsets),
        others=dict(cluster.others),
    )


def simulate_batch(
    cluster: ClusterResource,
    apps: Sequence[AppResource],
    scenarios: Sequence[Scenario],
    *,
    weights: Optional[dict] = None,
    use_greed: bool = False,
    mesh=None,
    n_pad: Optional[int] = None,
    profiles=None,
    plugins=None,
    patch_pods=None,
    expand_cache=None,
    extenders=None,
    resident=None,
) -> List[SimulateResult]:
    """Simulate S scenarios against one cluster/app list, preferring a single
    batched device sweep (Simulator.run_scenarios — the vmapped commit
    engine) and falling back to per-scenario serial simulate() when a gated
    feature forces it (see batch_ineligible_reason). Either way the return
    is one ordinary SimulateResult per scenario, in scenario order, with
    per-scenario placements identical between the two paths.

    `weights` is the sweep default; Scenario.weights overrides per lane.
    The serial fallback never shares `expand_cache` across lanes — results
    must own their pods, and cached expansion would alias them."""
    from ..utils.tracing import log

    scenarios = list(scenarios)
    if not scenarios:
        return []
    # Captured BEFORE the batched attempt: run_scenarios may expand the
    # workloads (advancing the shared name RNG) and only then discover a
    # post-expansion gate; the serial fallback below must still see the
    # entry-time RNG state.
    rng_state = workloads._rng.getstate()
    reason = batch_ineligible_reason(
        cluster, apps, scenarios, use_greed=use_greed, mesh=mesh,
        profiles=profiles, plugins=plugins, extenders=extenders,
    )
    if reason is None:
        results = Simulator(
            cluster, weights=weights, use_greed=use_greed, mesh=mesh,
            n_pad=n_pad, patch_pods=patch_pods, expand_cache=expand_cache,
            resident=resident,
        ).run_scenarios(apps, scenarios)
        if results is not None:
            return results
        reason = (
            "preemption-eligible pods (priority > 0) or pre-bound pods on "
            "scenario-masked nodes"
        )
    log.info(
        "simulate_batch: serial fallback for %d scenario(s): %s",
        len(scenarios), reason,
    )
    # Every lane must be byte-identical to a standalone simulate() of its
    # scenario — including the random pod-name suffixes, which draw from the
    # process-global seeded RNG. Rewind it to the entry state per lane so an
    # earlier lane's expansion cannot perturb a later lane's names (the
    # batched path gets this for free: all lanes share one expansion).
    out = []
    for sc in scenarios:
        workloads._rng.setstate(rng_state)
        out.append(
            simulate(
                _scenario_cluster(cluster, sc), apps,
                weights=sc.weights if sc.weights is not None else weights,
                use_greed=use_greed, mesh=mesh, n_pad=n_pad,
                profiles=profiles, plugins=plugins, patch_pods=patch_pods,
                expand_cache=None, extenders=extenders, resident=resident,
            )
        )
    return out


class ScenarioSession:
    """A warm Simulator pinned to one (cluster, apps, weights) tuple so the
    continuous-batching scheduler loop can issue back-to-back batched device
    calls without re-paying per-call setup: Simulator construction
    (deep-copying bound/pending pods, validation) and the encode pass
    (_build_device_state) happen once, at session creation; each subsequent
    run() reuses the resident table/carry via run_scenarios(reuse_state=True).

    Determinism: workload expansion draws random pod-name suffixes from the
    process-global seeded RNG. The session captures the RNG state at creation
    and rewinds before EVERY run, so run([sc]) on the Nth pack is
    byte-identical to a cold simulate() of the same scenario — the pack-of-1
    equality test in tests/test_scheduler_loop.py holds call after call.

    Shape stability: `pad_floor` is a running max of the padded lane count
    this session has served, fed into the next call's scenario_bucket
    floor — once a pack has compiled the N-lane program, every later pack
    (however small) runs that same hot shape. Padding a lone request to
    the session's widest shape costs milliseconds of extra lane compute;
    re-compiling a narrower shape mid-serving costs *seconds* and stalls
    the scheduler loop, which is the wrong trade everywhere we serve. A
    session is bounded (server LRU, _SESSION_CAP) so a burst's wide shape
    dies with the session, not with the process.

    run() returns None when run_scenarios refuses the workload (priority
    pods, pre-bound-on-masked) — the caller falls back to simulate_batch,
    exactly like the cold path. A session is single-threaded by contract;
    the server's checkout/checkin wrapper enforces one user at a time."""

    def __init__(
        self,
        cluster: ClusterResource,
        apps: Sequence[AppResource],
        *,
        weights: Optional[dict] = None,
        resident=None,
    ) -> None:
        self._rng_state = workloads._rng.getstate()
        self.sim = Simulator(
            cluster, weights=weights, expand_cache={}, resident=resident,
        )
        self.apps = list(apps)
        self.calls = 0
        self.pad_floor = 0

    def run(self, scenarios: Sequence[Scenario]):
        """One batched device call over this session's cluster/apps. Returns
        per-scenario SimulateResults, or None when the batched path refuses
        (caller falls back cold)."""
        scenarios = list(scenarios)
        if not scenarios:
            return []
        if batch_ineligible_reason(
            self.sim.cluster, self.apps, scenarios,
        ) is not None:
            return None
        workloads._rng.setstate(self._rng_state)
        results = self.sim.run_scenarios(
            self.apps, scenarios,
            reuse_state=self.calls > 0, s_floor=self.pad_floor,
        )
        if results is None:
            return None
        self.calls += 1
        self.pad_floor = max(self.pad_floor, scenario_bucket(len(scenarios)))
        return results
