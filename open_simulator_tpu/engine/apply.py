"""The Applier: config → cluster/apps → simulate → (capacity plan) → report.

Parity: `/root/reference/pkg/apply/apply.go` (NewApplier/Run): builds cluster
from the custom config dir (or a real cluster via kubeconfig — not available in
this environment, cleanly rejected), renders each app (chart or manifest dir),
runs the simulation, and on unschedulable pods enters the add-node flow. The
reference's flow is interactive-only; ours defaults to the automatic bisection
search (engine/capacity.py) with interactive kept as an option.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from dataclasses import dataclass, field as dataclass_field
from typing import List, Optional, TextIO

from ..api.config import SimonConfig
from ..core.objects import Node
from ..utils import metrics
from ..utils.yamlio import (
    json_files_by_stem,
    load_yaml_documents,
    objects_from_directory,
)
from .capacity import CapacityPlan, new_fake_nodes, plan_capacity
from .report import full_report
from .simulator import AppResource, ClusterResource, SimulateResult, simulate


class ApplyError(Exception):
    pass


def build_cluster(cfg: SimonConfig) -> ClusterResource:
    if cfg.kube_config:
        # Real-cluster snapshot (CreateClusterResourceFromClient,
        # simulator.go:503-601) via the built-in REST client.
        from ..utils.kubeclient import (
            KubeClientError,
            create_cluster_resource_from_kubeconfig,
        )

        try:
            cluster = create_cluster_resource_from_kubeconfig(cfg.kube_config)
        except KubeClientError as e:
            raise ApplyError(f"spec.cluster.kubeConfig: {e}")
        if not cluster.nodes:
            raise ApplyError("cluster snapshot returned no nodes")
        return cluster
    objs = objects_from_directory(cfg.custom_config)
    cluster = ClusterResource.from_objects(objs)
    if not cluster.nodes:
        raise ApplyError(f"no Node objects found under {cfg.custom_config}")
    cluster.attach_local_storage(json_files_by_stem(cfg.custom_config))
    return cluster


def render_chart(path: str, name: str) -> List[dict]:
    """Helm chart rendering (parity: chart.ProcessChart, pkg/chart/chart.go).

    The built-in renderer (utils/chart.py) handles the Go-template subset
    application charts use; charts beyond that subset fall back to a real
    `helm template` binary when one is installed."""
    from ..utils.chart import ChartError, process_chart

    try:
        return process_chart(path, release_name=name)
    except ChartError as e:
        if "injected by fault plan" in str(e):
            # chaos testing: a helm binary on the host must not quietly heal
            # an injected rendering fault — the whole point is to exercise
            # the degraded per-app failure path
            raise ApplyError(f"app {name}: built-in chart renderer: {e}")
        helm = shutil.which("helm")
        if helm is None:
            raise ApplyError(
                f"app {name}: built-in chart renderer: {e} (and no helm "
                "binary is installed to fall back to; pre-render with "
                "`helm template` and point the app path at the output)"
            )
        proc = subprocess.run(
            [helm, "template", name, path],
            capture_output=True,
            text=True,
            check=False,
        )
        if proc.returncode != 0:
            raise ApplyError(
                f"helm template failed for {name}: {proc.stderr.strip()}"
            )
        return load_yaml_documents(proc.stdout)


@dataclass
class FailedApp:
    """An app whose chart/manifests could not be rendered. Rendering failures
    degrade to a per-app failure (the remaining apps still simulate) instead
    of aborting the whole run."""

    name: str
    error: str


def build_apps(
    cfg: SimonConfig, failures: Optional[List[FailedApp]] = None
) -> List[AppResource]:
    """Render every app in the config. With `failures` supplied, an app whose
    chart fails to render is recorded there and skipped; without it the first
    render error raises (backward-compatible library behavior)."""
    import yaml as _yaml

    apps = []
    for app in cfg.app_list:
        try:
            if app.chart:
                objects = render_chart(app.path, app.name)
            else:
                objects = objects_from_directory(app.path)
        except (ApplyError, _yaml.YAMLError, OSError, UnicodeDecodeError,
                ValueError) as e:
            if failures is None:
                if isinstance(e, ApplyError):
                    raise
                raise ApplyError(f"app {app.name}: {e}")
            failures.append(FailedApp(name=app.name, error=str(e)))
            continue
        apps.append(AppResource(name=app.name, objects=objects))
    return apps


def load_new_node(cfg: SimonConfig) -> Optional[Node]:
    if not cfg.new_node:
        return None
    objs = objects_from_directory(cfg.new_node)
    nodes = [o for o in objs if o.get("kind") == "Node"]
    if not nodes:
        return None
    # the reference supports exactly one candidate node (simon-config.yaml note)
    node = Node.from_dict(nodes[0])
    storage = json_files_by_stem(cfg.new_node)
    info = storage.get(node.name)
    if info is not None:
        from ..core.objects import ANNO_NODE_LOCAL_STORAGE

        node.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = info
    return node


@dataclass
class ApplyOutcome:
    result: SimulateResult
    plan: Optional[CapacityPlan] = None
    report: str = ""
    failed_apps: List[FailedApp] = dataclass_field(default_factory=list)
    # Honest device provenance (durable/watchdog.py ladder): which backend
    # actually ran the simulation, and — when the run degraded — why. These
    # are stamped as TOP-LEVEL fields of every serialized outcome so a
    # CPU-fallback run can never masquerade as a TPU capture.
    device: str = ""
    fallback: str = ""
    fallback_reason: str = ""


def placement_digest(result: SimulateResult) -> str:
    """Stable digest of the workload→node assignment. Two runs produced the
    same plan iff their digests match — the byte-identity check the
    crash-resume smoke uses (timestamps and attempt counts live elsewhere).

    Keyed by (workload kind/ns/name, node, replica count), NOT pod name:
    expanded pod names draw suffixes from the process-global seeded RNG
    (core/workloads.py), whose draw sequence depends on how many expansions
    ran — a resumed run skips most of them, so names differ while the plan
    (interchangeable replicas per workload per node) is identical."""
    import hashlib

    from ..core.objects import (
        ANNO_WORKLOAD_KIND,
        ANNO_WORKLOAD_NAME,
        ANNO_WORKLOAD_NAMESPACE,
    )

    counts: dict = {}
    for st in result.node_status:
        for p in st.pods:
            ann = p.meta.annotations
            wl = (
                ann.get(ANNO_WORKLOAD_KIND, ""),
                ann.get(ANNO_WORKLOAD_NAMESPACE, p.meta.namespace),
                # standalone pods carry no workload annotation; their
                # manifest name is already deterministic
                ann.get(ANNO_WORKLOAD_NAME) or p.meta.name,
                st.node.name,
            )
            counts[wl] = counts.get(wl, 0) + 1
    blob = "\n".join(
        "\t".join(k) + f"\t{n}" for k, n in sorted(counts.items())
    )
    return hashlib.blake2b(blob.encode(), digest_size=8).hexdigest()


def select_apps(
    apps: List[AppResource], out: TextIO, input_fn
) -> List[AppResource]:
    """Interactive multi-select of which apps to deploy (parity: the survey
    MultiSelect prompt, apply.go:173-195). Accepts comma-separated indices or
    names; empty input deploys everything."""
    if not apps:
        return apps
    print("applications:", file=out)
    for i, app in enumerate(apps):
        print(f"  [{i}] {app.name}", file=out)
    raw = input_fn("deploy which apps? (comma list of numbers/names, empty = all) ")
    raw = (raw or "").strip()
    if not raw:
        return apps
    chosen: List[AppResource] = []
    by_name = {a.name: a for a in apps}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.isdigit() and int(tok) < len(apps):
            app = apps[int(tok)]
        elif tok in by_name:
            app = by_name[tok]
        else:
            print(f"  ignoring unknown app {tok!r}", file=out)
            continue
        if app not in chosen:
            chosen.append(app)
    return chosen or apps


def run_apply(
    cfg: SimonConfig,
    interactive: bool = False,
    auto_plan: bool = True,
    out: Optional[TextIO] = None,
    input_fn=input,
    scheduler_config: str = "",
    use_greed: bool = False,
    devices: int = 1,
    extended_resources: Optional[List[str]] = None,
    run_dir: Optional[str] = None,
    resume: bool = False,
    config_path: str = "",
) -> ApplyOutcome:
    """With `run_dir`, the run is journaled (durable/journal.py): backend
    acquisition, every capacity trial, and the final outcome are committed
    as they happen, and `resume=True` replays the journal so a crashed run
    re-simulates only what it never finished. Without `run_dir` the run is
    un-journaled but still acquires its backend through the watchdog ladder
    and stamps honest device provenance on the outcome."""
    import sys

    from ..durable import (
        DeadlineExceeded,
        RunJournal,
        acquire_backend,
        atomic_write,
        call_deadline_s,
        guarded_call,
    )
    from ..models.profiles import load_scheduler_config

    from ..utils.tracing import span

    report_to_file = out is not None and out is not sys.stdout
    out = out or sys.stdout
    # Interactive prompts must stay visible on the terminal even when the
    # report is routed to --output-file.
    ui_out = sys.stderr if report_to_file else out

    journal: Optional[RunJournal] = None
    if run_dir:
        journal = RunJournal.open(run_dir)
        if not journal.has("run_start"):
            journal.append(
                "run_start", kind="apply", name=cfg.name,
                simon_config=config_path,
            )
        if resume:
            metrics.RUN_RESUMED.inc()
            journal.append("run_resume")

    with span("backend-acquire"):
        backend = acquire_backend(journal=journal)
    with span("build-cluster"):
        cluster = build_cluster(cfg)
    failed_apps: List[FailedApp] = []
    with span("render-apps"):
        apps = build_apps(cfg, failures=failed_apps)
    if report_to_file:
        # the report (with its FAILED APP lines) goes to --output-file, so
        # surface render failures on the terminal too
        for fa in failed_apps:
            print(f"app {fa.name}: failed to render: {fa.error}", file=ui_out)
    if interactive:
        apps = select_apps(apps, ui_out, input_fn)
    new_node = load_new_node(cfg)
    sched_cfg = load_scheduler_config(scheduler_config)
    profiles = sched_cfg.profiles
    extenders = sched_cfg.extenders
    mesh = None
    if devices != 1:
        from ..parallel.mesh import product_mesh

        mesh = product_mesh(devices)

    def _simulate_and_plan(resume_now: bool):
        result = guarded_call(
            "apply-simulate",
            lambda: simulate(
                cluster, apps, profiles=profiles, use_greed=use_greed,
                mesh=mesh, extenders=extenders,
            ),
            call_deadline_s(),
        )
        plan: Optional[CapacityPlan] = None

        if result.unscheduled and new_node is not None:
            if interactive:
                result = _interactive_loop(
                    cluster, apps, new_node, result, ui_out, input_fn,
                    profiles=profiles, use_greed=use_greed, mesh=mesh,
                    extenders=extenders,
                )
            elif auto_plan:
                print(
                    f"{len(result.unscheduled)} pod(s) unschedulable; "
                    f"searching for minimum copies of node "
                    f"{new_node.name}...",
                    file=out,
                )
                with span("capacity-search"):
                    plan = plan_capacity(
                        cluster, apps, new_node, profiles=profiles,
                        use_greed=use_greed, mesh=mesh, extenders=extenders,
                        journal=journal, resume=resume_now,
                    )
                if plan is None:
                    print(
                        "capacity search failed: workload does not fit",
                        file=out,
                    )
                else:
                    degraded = (
                        f", {plan.retries} retried on transient extender "
                        "errors"
                        if plan.retries
                        else ""
                    )
                    print(
                        f"capacity plan: add {plan.nodes_added} x "
                        f"{new_node.name} "
                        f"({plan.attempts} simulations{degraded})",
                        file=out,
                    )
                    result = plan.result
        return result, plan

    try:
        result, plan = _simulate_and_plan(resume)
    except DeadlineExceeded as e:
        # A guarded device call wedged mid-run (the r03–r05 failure mode,
        # post-acquisition flavor). Degrade to CPU explicitly, stamp the
        # provenance, and retry once — resuming from the journal so trials
        # the wedged attempt already committed are not re-simulated.
        reason = f"guarded call wedged mid-run: {e}"
        print(f"watchdog: {e}; degrading to CPU and retrying", file=ui_out)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        backend.update(
            device=str(jax.devices()[0]), fallback="cpu",
            fallback_reason=reason,
        )
        if journal is not None:
            journal.append(
                "backend_fallback", device=backend["device"], fallback="cpu",
                fallback_reason=reason,
            )
        result, plan = _simulate_and_plan(journal is not None)

    with span("render-report"):
        report = full_report(result, extended_resources=extended_resources)
    if failed_apps:
        report += "\n" + "\n".join(
            f"FAILED APP {fa.name}: {fa.error}" for fa in failed_apps
        )
    outcome = "ok"
    if result.unscheduled:
        outcome = "unschedulable"
    elif failed_apps:
        outcome = "render_failed"
    metrics.APPLY_RUNS.inc(outcome=outcome)
    # color only live terminal output (pterm/DisablePTerm parity): the
    # returned ApplyOutcome.report and --output-file stay plain text
    display = report
    if not report_to_file and getattr(out, "isatty", lambda: False)():
        from ..utils.tables import colorize_report

        display = colorize_report(report)
    print(display, file=out)
    device_line = f"device: {backend.get('device', '')}"
    if backend.get("fallback"):
        device_line += (
            f" (fallback={backend['fallback']}: {backend['fallback_reason']})"
        )
    print(device_line, file=out)

    digest = placement_digest(result)
    if journal is not None:
        import json as _json

        journal.append(
            "run_end", outcome=outcome,
            nodes_added=(plan.nodes_added if plan else 0), digest=digest,
        )
        # whole-file snapshot for `simon runs show` / the crash-resume smoke:
        # deliberately timestamp-free so interrupted+resumed and
        # uninterrupted runs produce byte-identical files
        atomic_write(
            os.path.join(journal.run_dir, "outcome.json"),
            _json.dumps(
                {
                    "outcome": outcome,
                    "device": backend.get("device", ""),
                    "fallback": backend.get("fallback", ""),
                    "fallback_reason": backend.get("fallback_reason", ""),
                    "nodes_added": plan.nodes_added if plan else 0,
                    "attempts": plan.attempts if plan else 0,
                    "retries": plan.retries if plan else 0,
                    "unscheduled": len(result.unscheduled),
                    "failed_apps": [fa.name for fa in failed_apps],
                    "placement_digest": digest,
                },
                indent=2,
                sort_keys=True,
            )
            + "\n",
        )
        journal.close()
    return ApplyOutcome(
        result=result, plan=plan, report=report, failed_apps=failed_apps,
        device=backend.get("device", ""),
        fallback=backend.get("fallback", ""),
        fallback_reason=backend.get("fallback_reason", ""),
    )


def _interactive_loop(
    cluster: ClusterResource,
    apps,
    new_node: Node,
    result: SimulateResult,
    out: TextIO,
    input_fn,
    weights=None,
    use_greed: bool = False,
    mesh=None,
    profiles=None,
    extenders=None,
) -> SimulateResult:
    """The reference's manual loop (apply.go:203-259): add one node / show
    reasons / exit, re-simulating from scratch each iteration."""
    added = 0
    while result.unscheduled:
        print(f"{len(result.unscheduled)} pod(s) failed to schedule.", file=out)
        choice = input_fn(
            "[a]dd a new node, show [r]easons, or [q]uit? "
        ).strip().lower()
        if choice.startswith("r"):
            for u in result.unscheduled:
                print(f"  {u.pod.key}: {u.reason}", file=out)
            continue
        if not choice.startswith("a"):
            break
        added += 1
        trial = ClusterResource(
            nodes=list(cluster.nodes) + new_fake_nodes(new_node, added),
            pods=list(cluster.pods),
            daemonsets=list(cluster.daemonsets),
            others=dict(cluster.others),
        )
        result = simulate(
            trial, apps, weights=weights, use_greed=use_greed, mesh=mesh,
            profiles=profiles, extenders=extenders,
        )
    return result
