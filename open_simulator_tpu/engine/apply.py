"""The Applier: config → cluster/apps → simulate → (capacity plan) → report.

Parity: `/root/reference/pkg/apply/apply.go` (NewApplier/Run): builds cluster
from the custom config dir (or a real cluster via kubeconfig — not available in
this environment, cleanly rejected), renders each app (chart or manifest dir),
runs the simulation, and on unschedulable pods enters the add-node flow. The
reference's flow is interactive-only; ours defaults to the automatic bisection
search (engine/capacity.py) with interactive kept as an option.
"""

from __future__ import annotations

import shutil
import subprocess
from dataclasses import dataclass, field as dataclass_field
from typing import List, Optional, TextIO

from ..api.config import SimonConfig
from ..core.objects import Node
from ..utils import metrics
from ..utils.yamlio import (
    json_files_by_stem,
    load_yaml_documents,
    objects_from_directory,
)
from .capacity import CapacityPlan, new_fake_nodes, plan_capacity
from .report import full_report
from .simulator import AppResource, ClusterResource, SimulateResult, simulate


class ApplyError(Exception):
    pass


def build_cluster(cfg: SimonConfig) -> ClusterResource:
    if cfg.kube_config:
        # Real-cluster snapshot (CreateClusterResourceFromClient,
        # simulator.go:503-601) via the built-in REST client.
        from ..utils.kubeclient import (
            KubeClientError,
            create_cluster_resource_from_kubeconfig,
        )

        try:
            cluster = create_cluster_resource_from_kubeconfig(cfg.kube_config)
        except KubeClientError as e:
            raise ApplyError(f"spec.cluster.kubeConfig: {e}")
        if not cluster.nodes:
            raise ApplyError("cluster snapshot returned no nodes")
        return cluster
    objs = objects_from_directory(cfg.custom_config)
    cluster = ClusterResource.from_objects(objs)
    if not cluster.nodes:
        raise ApplyError(f"no Node objects found under {cfg.custom_config}")
    cluster.attach_local_storage(json_files_by_stem(cfg.custom_config))
    return cluster


def render_chart(path: str, name: str) -> List[dict]:
    """Helm chart rendering (parity: chart.ProcessChart, pkg/chart/chart.go).

    The built-in renderer (utils/chart.py) handles the Go-template subset
    application charts use; charts beyond that subset fall back to a real
    `helm template` binary when one is installed."""
    from ..utils.chart import ChartError, process_chart

    try:
        return process_chart(path, release_name=name)
    except ChartError as e:
        if "injected by fault plan" in str(e):
            # chaos testing: a helm binary on the host must not quietly heal
            # an injected rendering fault — the whole point is to exercise
            # the degraded per-app failure path
            raise ApplyError(f"app {name}: built-in chart renderer: {e}")
        helm = shutil.which("helm")
        if helm is None:
            raise ApplyError(
                f"app {name}: built-in chart renderer: {e} (and no helm "
                "binary is installed to fall back to; pre-render with "
                "`helm template` and point the app path at the output)"
            )
        proc = subprocess.run(
            [helm, "template", name, path],
            capture_output=True,
            text=True,
            check=False,
        )
        if proc.returncode != 0:
            raise ApplyError(
                f"helm template failed for {name}: {proc.stderr.strip()}"
            )
        return load_yaml_documents(proc.stdout)


@dataclass
class FailedApp:
    """An app whose chart/manifests could not be rendered. Rendering failures
    degrade to a per-app failure (the remaining apps still simulate) instead
    of aborting the whole run."""

    name: str
    error: str


def build_apps(
    cfg: SimonConfig, failures: Optional[List[FailedApp]] = None
) -> List[AppResource]:
    """Render every app in the config. With `failures` supplied, an app whose
    chart fails to render is recorded there and skipped; without it the first
    render error raises (backward-compatible library behavior)."""
    import yaml as _yaml

    apps = []
    for app in cfg.app_list:
        try:
            if app.chart:
                objects = render_chart(app.path, app.name)
            else:
                objects = objects_from_directory(app.path)
        except (ApplyError, _yaml.YAMLError, OSError, UnicodeDecodeError,
                ValueError) as e:
            if failures is None:
                if isinstance(e, ApplyError):
                    raise
                raise ApplyError(f"app {app.name}: {e}")
            failures.append(FailedApp(name=app.name, error=str(e)))
            continue
        apps.append(AppResource(name=app.name, objects=objects))
    return apps


def load_new_node(cfg: SimonConfig) -> Optional[Node]:
    if not cfg.new_node:
        return None
    objs = objects_from_directory(cfg.new_node)
    nodes = [o for o in objs if o.get("kind") == "Node"]
    if not nodes:
        return None
    # the reference supports exactly one candidate node (simon-config.yaml note)
    node = Node.from_dict(nodes[0])
    storage = json_files_by_stem(cfg.new_node)
    info = storage.get(node.name)
    if info is not None:
        from ..core.objects import ANNO_NODE_LOCAL_STORAGE

        node.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = info
    return node


@dataclass
class ApplyOutcome:
    result: SimulateResult
    plan: Optional[CapacityPlan] = None
    report: str = ""
    failed_apps: List[FailedApp] = dataclass_field(default_factory=list)


def select_apps(
    apps: List[AppResource], out: TextIO, input_fn
) -> List[AppResource]:
    """Interactive multi-select of which apps to deploy (parity: the survey
    MultiSelect prompt, apply.go:173-195). Accepts comma-separated indices or
    names; empty input deploys everything."""
    if not apps:
        return apps
    print("applications:", file=out)
    for i, app in enumerate(apps):
        print(f"  [{i}] {app.name}", file=out)
    raw = input_fn("deploy which apps? (comma list of numbers/names, empty = all) ")
    raw = (raw or "").strip()
    if not raw:
        return apps
    chosen: List[AppResource] = []
    by_name = {a.name: a for a in apps}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.isdigit() and int(tok) < len(apps):
            app = apps[int(tok)]
        elif tok in by_name:
            app = by_name[tok]
        else:
            print(f"  ignoring unknown app {tok!r}", file=out)
            continue
        if app not in chosen:
            chosen.append(app)
    return chosen or apps


def run_apply(
    cfg: SimonConfig,
    interactive: bool = False,
    auto_plan: bool = True,
    out: Optional[TextIO] = None,
    input_fn=input,
    scheduler_config: str = "",
    use_greed: bool = False,
    devices: int = 1,
    extended_resources: Optional[List[str]] = None,
) -> ApplyOutcome:
    import sys

    from ..models.profiles import load_scheduler_config

    from ..utils.tracing import span

    report_to_file = out is not None and out is not sys.stdout
    out = out or sys.stdout
    # Interactive prompts must stay visible on the terminal even when the
    # report is routed to --output-file.
    ui_out = sys.stderr if report_to_file else out
    with span("build-cluster"):
        cluster = build_cluster(cfg)
    failed_apps: List[FailedApp] = []
    with span("render-apps"):
        apps = build_apps(cfg, failures=failed_apps)
    if report_to_file:
        # the report (with its FAILED APP lines) goes to --output-file, so
        # surface render failures on the terminal too
        for fa in failed_apps:
            print(f"app {fa.name}: failed to render: {fa.error}", file=ui_out)
    if interactive:
        apps = select_apps(apps, ui_out, input_fn)
    new_node = load_new_node(cfg)
    sched_cfg = load_scheduler_config(scheduler_config)
    profiles = sched_cfg.profiles
    extenders = sched_cfg.extenders
    mesh = None
    if devices != 1:
        from ..parallel.mesh import product_mesh

        mesh = product_mesh(devices)

    result = simulate(
        cluster, apps, profiles=profiles, use_greed=use_greed, mesh=mesh,
        extenders=extenders,
    )
    plan: Optional[CapacityPlan] = None

    if result.unscheduled and new_node is not None:
        if interactive:
            result = _interactive_loop(
                cluster, apps, new_node, result, ui_out, input_fn,
                profiles=profiles, use_greed=use_greed, mesh=mesh,
                extenders=extenders,
            )
        elif auto_plan:
            print(
                f"{len(result.unscheduled)} pod(s) unschedulable; searching for "
                f"minimum copies of node {new_node.name}...",
                file=out,
            )
            with span("capacity-search"):
                plan = plan_capacity(
                    cluster, apps, new_node, profiles=profiles,
                    use_greed=use_greed, mesh=mesh, extenders=extenders,
                )
            if plan is None:
                print("capacity search failed: workload does not fit", file=out)
            else:
                degraded = (
                    f", {plan.retries} retried on transient extender errors"
                    if plan.retries
                    else ""
                )
                print(
                    f"capacity plan: add {plan.nodes_added} x {new_node.name} "
                    f"({plan.attempts} simulations{degraded})",
                    file=out,
                )
                result = plan.result

    with span("render-report"):
        report = full_report(result, extended_resources=extended_resources)
    if failed_apps:
        report += "\n" + "\n".join(
            f"FAILED APP {fa.name}: {fa.error}" for fa in failed_apps
        )
    outcome = "ok"
    if result.unscheduled:
        outcome = "unschedulable"
    elif failed_apps:
        outcome = "render_failed"
    metrics.APPLY_RUNS.inc(outcome=outcome)
    # color only live terminal output (pterm/DisablePTerm parity): the
    # returned ApplyOutcome.report and --output-file stay plain text
    display = report
    if not report_to_file and getattr(out, "isatty", lambda: False)():
        from ..utils.tables import colorize_report

        display = colorize_report(report)
    print(display, file=out)
    return ApplyOutcome(
        result=result, plan=plan, report=report, failed_apps=failed_apps
    )


def _interactive_loop(
    cluster: ClusterResource,
    apps,
    new_node: Node,
    result: SimulateResult,
    out: TextIO,
    input_fn,
    weights=None,
    use_greed: bool = False,
    mesh=None,
    profiles=None,
    extenders=None,
) -> SimulateResult:
    """The reference's manual loop (apply.go:203-259): add one node / show
    reasons / exit, re-simulating from scratch each iteration."""
    added = 0
    while result.unscheduled:
        print(f"{len(result.unscheduled)} pod(s) failed to schedule.", file=out)
        choice = input_fn(
            "[a]dd a new node, show [r]easons, or [q]uit? "
        ).strip().lower()
        if choice.startswith("r"):
            for u in result.unscheduled:
                print(f"  {u.pod.key}: {u.reason}", file=out)
            continue
        if not choice.startswith("a"):
            break
        added += 1
        trial = ClusterResource(
            nodes=list(cluster.nodes) + new_fake_nodes(new_node, added),
            pods=list(cluster.pods),
            daemonsets=list(cluster.daemonsets),
            others=dict(cluster.others),
        )
        result = simulate(
            trial, apps, weights=weights, use_greed=use_greed, mesh=mesh,
            profiles=profiles, extenders=extenders,
        )
    return result
