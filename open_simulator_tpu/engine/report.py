"""Simulation reports: cluster / node / app views.

Parity: the pterm report tables in `/root/reference/pkg/apply/apply.go:308-687`
(reportClusterInfo, reportNodeInfo, reportApp*): per-node requested vs
allocatable cpu/mem with percentages, pod counts, new-node marking, pod→node
placements grouped by workload, and unscheduled pods with reasons.
"""

from __future__ import annotations

from ..core.objects import (
    ANNO_WORKLOAD_KIND,
    ANNO_WORKLOAD_NAME,
    LABEL_NEW_NODE,
    Node,
    Pod,
)
from ..utils.quantity import format_bytes, format_milli
from ..utils.tables import render_table
from .simulator import SimulateResult


def _pct(used: float, total: float) -> str:
    if total <= 0:
        return "-"
    return f"{100.0 * used / total:.1f}%"


def cluster_report(result: SimulateResult) -> str:
    headers = [
        "Node", "CPU Alloc", "CPU Req", "CPU%", "Mem Alloc", "Mem Req", "Mem%",
        "Pods", "PodCap", "New",
    ]
    rows = []
    total_cpu = total_cpu_req = 0
    total_mem = total_mem_req = 0
    for st in result.node_status:
        node = st.node
        cpu_alloc = node.allocatable.get("cpu", 0)
        mem_alloc = node.allocatable.get("memory", 0)
        cpu_req = sum(p.requests.get("cpu", 0) for p in st.pods)
        mem_req = sum(p.requests.get("memory", 0) for p in st.pods)
        total_cpu += cpu_alloc
        total_cpu_req += cpu_req
        total_mem += mem_alloc
        total_mem_req += mem_req
        rows.append(
            [
                node.name,
                format_milli(cpu_alloc),
                format_milli(cpu_req),
                _pct(cpu_req, cpu_alloc),
                format_bytes(mem_alloc),
                format_bytes(mem_req),
                _pct(mem_req, mem_alloc),
                len(st.pods),
                node.allocatable.get("pods", 0),
                "yes" if LABEL_NEW_NODE in node.meta.labels else "",
            ]
        )
    rows.append(
        [
            "(total)",
            format_milli(total_cpu),
            format_milli(total_cpu_req),
            _pct(total_cpu_req, total_cpu),
            format_bytes(total_mem),
            format_bytes(total_mem_req),
            _pct(total_mem_req, total_mem),
            sum(len(st.pods) for st in result.node_status),
            "",
            "",
        ]
    )
    return render_table(headers, rows)


def placement_report(result: SimulateResult) -> str:
    headers = ["Node", "Pod", "Workload", "CPU Req", "Mem Req"]
    rows = []
    for st in sorted(result.node_status, key=lambda s: s.node.name):
        for pod in sorted(st.pods, key=lambda p: p.key):
            kind = pod.meta.annotations.get(ANNO_WORKLOAD_KIND, "Pod")
            name = pod.meta.annotations.get(ANNO_WORKLOAD_NAME, "")
            rows.append(
                [
                    st.node.name,
                    pod.key,
                    f"{kind}/{name}" if name else kind,
                    format_milli(pod.requests.get("cpu", 0)),
                    format_bytes(pod.requests.get("memory", 0)),
                ]
            )
    return render_table(headers, rows)


def storage_report(result: SimulateResult) -> str:
    """Open-local view: per-node VG utilization and device allocation
    (parity: the local-storage tables of reportExtendedResource,
    apply.go:526-614)."""
    if not result.storage:
        return ""
    headers = ["Node", "Resource", "Capacity", "Requested", "Util/Alloc"]
    rows = []
    for name in sorted(result.storage):
        st = result.storage[name]
        for vg in st.vgs:
            rows.append(
                [
                    name,
                    f"VG {vg.name}",
                    format_bytes(vg.capacity),
                    format_bytes(vg.requested),
                    _pct(vg.requested, vg.capacity),
                ]
            )
        for dev in st.devices:
            rows.append(
                [
                    name,
                    f"Device {dev.name} ({dev.media_type})",
                    format_bytes(dev.capacity),
                    "-",
                    "allocated" if dev.is_allocated else "free",
                ]
            )
    return render_table(headers, rows)


def gpu_report(result: SimulateResult) -> str:
    """GPU-share view: per-node per-device memory utilization from the bound
    pods' gpu-index annotations (parity: the gpu tables of
    reportExtendedResource, apply.go:616-687)."""
    from ..core.objects import ANNO_GPU_INDEX  # noqa: F401 (doc pointer)

    headers = ["Node", "GPU", "Mem Total", "Mem Used", "Util", "Pods"]
    rows = []
    for st in sorted(result.node_status, key=lambda s: s.node.name):
        node = st.node
        count = node.gpu_count()
        if count <= 0:
            continue
        per_dev = node.gpu_mem_per_device()
        used = [0] * count
        pods_on = [0] * count
        for pod in st.pods:
            mem = pod.gpu_mem_request()
            if mem <= 0:
                continue
            for d in pod.gpu_index_ids():
                if 0 <= d < count:
                    used[d] += mem
                    pods_on[d] += 1
        for d in range(count):
            rows.append(
                [
                    node.name,
                    f"gpu-{d}",
                    format_bytes(per_dev),
                    format_bytes(used[d]),
                    _pct(used[d], per_dev),
                    pods_on[d],
                ]
            )
    if not rows:
        return ""
    return render_table(headers, rows)


def preempted_report(result: SimulateResult) -> str:
    """Victims evicted by DefaultPreemption (the reference emits 'Preempted'
    events via the event recorder; here they surface as a table)."""
    if not result.preempted:
        return ""
    headers = ["Victim", "Node", "Preempted By", "Priority"]
    rows = [
        [p.pod.key, p.node, p.by, p.pod.priority] for p in result.preempted
    ]
    return render_table(headers, rows)


def unscheduled_report(result: SimulateResult) -> str:
    if not result.unscheduled:
        return "All pods scheduled."
    headers = ["Pod", "Reason"]
    rows = [[u.pod.key, u.reason] for u in result.unscheduled]
    return render_table(headers, rows)


def full_report(
    result: SimulateResult,
    extended: bool = True,
    extended_resources=None,
) -> str:
    """Assembled report. `extended_resources` mirrors the reference's
    --extended-resources flag (cmd/apply/apply.go:32; containLocalStorage /
    containGpu gate the tables, apply.go:777-789): an explicit list shows
    exactly the requested views ("open-local", "gpu"). None keeps the
    show-everything-available default (a deliberate superset of the
    reference's hide-by-default: the data is already computed)."""
    parts = [
        "=== Cluster ===",
        cluster_report(result),
        "=== Placements ===",
        placement_report(result),
    ]
    if extended:
        want_storage = extended_resources is None or "open-local" in extended_resources
        want_gpu = extended_resources is None or "gpu" in extended_resources
        stor = storage_report(result) if want_storage else ""
        if stor:
            parts += ["=== Local Storage ===", stor]
        gpu = gpu_report(result) if want_gpu else ""
        if gpu:
            parts += ["=== GPU Share ===", gpu]
    pre = preempted_report(result)
    if pre:
        parts += ["=== Preempted ===", pre]
    parts += ["=== Unscheduled ===", unscheduled_report(result)]
    return "\n\n".join(parts)
