"""Simulation reports: cluster / node / app views.

Parity: the pterm report tables in `/root/reference/pkg/apply/apply.go:308-687`
(reportClusterInfo, reportNodeInfo, reportApp*): per-node requested vs
allocatable cpu/mem with percentages, pod counts, new-node marking, pod→node
placements grouped by workload, and unscheduled pods with reasons.
"""

from __future__ import annotations

from typing import Dict, List

from ..core.objects import (
    ANNO_WORKLOAD_KIND,
    ANNO_WORKLOAD_NAME,
    LABEL_NEW_NODE,
    Node,
    Pod,
)
from ..utils.quantity import format_bytes, format_milli
from ..utils.tables import render_table
from .simulator import SimulateResult


def _pct(used: float, total: float) -> str:
    if total <= 0:
        return "-"
    return f"{100.0 * used / total:.1f}%"


def cluster_report(result: SimulateResult) -> str:
    headers = [
        "Node", "CPU Alloc", "CPU Req", "CPU%", "Mem Alloc", "Mem Req", "Mem%",
        "Pods", "PodCap", "New",
    ]
    rows = []
    total_cpu = total_cpu_req = 0
    total_mem = total_mem_req = 0
    for st in result.node_status:
        node = st.node
        cpu_alloc = node.allocatable.get("cpu", 0)
        mem_alloc = node.allocatable.get("memory", 0)
        cpu_req = sum(p.requests.get("cpu", 0) for p in st.pods)
        mem_req = sum(p.requests.get("memory", 0) for p in st.pods)
        total_cpu += cpu_alloc
        total_cpu_req += cpu_req
        total_mem += mem_alloc
        total_mem_req += mem_req
        rows.append(
            [
                node.name,
                format_milli(cpu_alloc),
                format_milli(cpu_req),
                _pct(cpu_req, cpu_alloc),
                format_bytes(mem_alloc),
                format_bytes(mem_req),
                _pct(mem_req, mem_alloc),
                len(st.pods),
                node.allocatable.get("pods", 0),
                "yes" if LABEL_NEW_NODE in node.meta.labels else "",
            ]
        )
    rows.append(
        [
            "(total)",
            format_milli(total_cpu),
            format_milli(total_cpu_req),
            _pct(total_cpu_req, total_cpu),
            format_bytes(total_mem),
            format_bytes(total_mem_req),
            _pct(total_mem_req, total_mem),
            sum(len(st.pods) for st in result.node_status),
            "",
            "",
        ]
    )
    return render_table(headers, rows)


def placement_report(result: SimulateResult) -> str:
    headers = ["Node", "Pod", "Workload", "CPU Req", "Mem Req"]
    rows = []
    for st in sorted(result.node_status, key=lambda s: s.node.name):
        for pod in sorted(st.pods, key=lambda p: p.key):
            kind = pod.meta.annotations.get(ANNO_WORKLOAD_KIND, "Pod")
            name = pod.meta.annotations.get(ANNO_WORKLOAD_NAME, "")
            rows.append(
                [
                    st.node.name,
                    pod.key,
                    f"{kind}/{name}" if name else kind,
                    format_milli(pod.requests.get("cpu", 0)),
                    format_bytes(pod.requests.get("memory", 0)),
                ]
            )
    return render_table(headers, rows)


def unscheduled_report(result: SimulateResult) -> str:
    if not result.unscheduled:
        return "All pods scheduled."
    headers = ["Pod", "Reason"]
    rows = [[u.pod.key, u.reason] for u in result.unscheduled]
    return render_table(headers, rows)


def full_report(result: SimulateResult) -> str:
    return "\n\n".join(
        [
            "=== Cluster ===",
            cluster_report(result),
            "=== Placements ===",
            placement_report(result),
            "=== Unscheduled ===",
            unscheduled_report(result),
        ]
    )
