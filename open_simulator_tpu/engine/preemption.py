"""DefaultPreemption PostFilter: evict lower-priority pods to place a pod.

Parity target: the vendored default-preemption plugin,
`/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/framework/plugins/
defaultpreemption/default_preemption.go`:
  - PodEligibleToPreemptOthers (:231): preemptionPolicy != Never
  - nodesWherePreemptionMightHelp (:258): skip nodes whose filter failure is
    UnschedulableAndUnresolvable (taints, node affinity, node name,
    unschedulable flag — removing pods can't fix those)
  - selectVictimsOnNode (:578): remove ALL lower-priority pods, check fit,
    then reprieve PDB-violating victims first and non-violating second, each
    class from the most important pod down (MoreImportantPod = higher
    priority first)
  - filterPodsWithPDBViolation (:736): a victim violates a PDB when evicting
    it would drive the budget's DisruptionsAllowed below zero (budgets are
    decremented per selected victim)
  - pickOneNodeForPreemption (:443): fewest PDB violations → lowest highest
    victim priority → lowest victim-priority sum → fewest victims → first
    (the reference's final earliest-start-time tiebreaks have no analog here:
    the simulation has no pod start times)

Deviation (documented): feasibility during victim selection checks the
resolvable filters host-side — resources (CPU/mem/pods/extended) — on top of
the static unresolvable gate. Topology-spread/inter-pod-affinity/storage/GPU
coupling to victims is not modeled; the upstream plugin itself skips
affinity-to-victim coupling "for performance reasons" (:628-632).

This runs host-side: preemption is rare (only failed pods with priority > 0),
and its victim search is branch-heavy sequential logic that would serialize on
device anyway — the TPU path stays a pure batch scheduler, and preemption
re-syncs device state once per successful eviction round.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.matcher import (
    fits_resources,
    match_label_selector,
    match_node_affinity,
    untolerated_taint,
)
from ..core.objects import LabelSelector, Node, Pod
from ..utils.tracing import span


@dataclass
class PodDisruptionBudget:
    """Decoded policy/v1beta1 PodDisruptionBudget (the reference syncs PDBs
    into the fake cluster, simulator.go:388-394)."""
    name: str
    namespace: str
    selector: Optional[LabelSelector]
    min_available: Optional[str] = None      # int or "NN%"
    max_unavailable: Optional[str] = None
    disruptions_allowed: Optional[int] = None  # from status, when present

    @staticmethod
    def from_dict(d: dict) -> "PodDisruptionBudget":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        da = status.get("disruptionsAllowed")
        return PodDisruptionBudget(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            selector=LabelSelector.from_dict(spec.get("selector")),
            min_available=_opt_str(spec.get("minAvailable")),
            max_unavailable=_opt_str(spec.get("maxUnavailable")),
            disruptions_allowed=int(da) if da is not None else None,
        )

    def matches(self, pod: Pod) -> bool:
        if not pod.meta.labels:
            return False  # "A pod with no labels will not match any PDB"
        if pod.meta.namespace != self.namespace:
            return False
        if self.selector is None:
            return False  # nil/empty selector matches nothing (:755)
        if not self.selector.match_labels and not self.selector.match_expressions:
            return False
        return match_label_selector(self.selector, pod.meta.labels)

    def allowed_disruptions(self, matching_healthy: int) -> int:
        """DisruptionsAllowed: status value when provided; otherwise derived
        from spec the way the disruption controller would for currently-
        healthy count `matching_healthy`."""
        if self.disruptions_allowed is not None:
            return self.disruptions_allowed
        if self.min_available is not None:
            need = _resolve_count(self.min_available, matching_healthy)
            return max(0, matching_healthy - need)
        if self.max_unavailable is not None:
            return max(0, _resolve_count(self.max_unavailable, matching_healthy))
        return 0


def _opt_str(v) -> Optional[str]:
    return None if v is None else str(v)


def _resolve_count(v: str, total: int) -> int:
    if v.endswith("%"):
        import math

        return math.ceil(float(v[:-1]) / 100.0 * total)
    return int(v)


@dataclass
class PreemptionResult:
    node: str
    victims: List[Pod]
    num_pdb_violations: int


def _static_unresolvable_ok(pod: Pod, node: Node) -> bool:
    """Filters whose failure preemption cannot fix (nodesWherePreemptionMight-
    Help skips UnschedulableAndUnresolvable nodes)."""
    if node.unschedulable and not _tolerates_unschedulable(pod):
        return False
    if pod.node_name and pod.node_name != node.name:
        return False
    if untolerated_taint(pod.tolerations, node) is not None:
        return False
    if not match_node_affinity(pod, node):
        return False
    return True


def _tolerates_unschedulable(pod: Pod) -> bool:
    for t in pod.tolerations:
        key_ok = not t.key or t.key == "node.kubernetes.io/unschedulable"
        val_ok = t.operator == "Exists" or not t.value
        eff_ok = not t.effect or t.effect == "NoSchedule"
        if key_ok and val_ok and eff_ok:
            return True
    return False


def _free_after(node: Node, pods: Sequence[Pod]) -> Dict[str, int]:
    free = dict(node.allocatable)
    free["pods"] = free.get("pods", 0)
    for p in pods:
        for res, q in p.requests.items():
            free[res] = free.get(res, 0) - q
        free["pods"] = free.get("pods", 0) - 1
    return free


def _fits(pod: Pod, node: Node, remaining: Sequence[Pod]) -> bool:
    free = _free_after(node, remaining)
    # pod.requests never carries the "pods" slot resource; check it explicitly
    # (the reference's full-filter dry run gets this via NodeResourcesFit).
    if free.get("pods", 0) < 1:
        return False
    return not fits_resources(pod, free)


def _more_important(p: Pod) -> Tuple:
    """Sort key for MoreImportantPod order (higher priority first; the
    start-time tiebreak has no analog — encoding order is stable)."""
    return (-p.priority,)


def _victim_candidates(
    pod: Pod,
    bound: Sequence[Pod],
    pdbs: Sequence[PodDisruptionBudget],
    pdb_allowed: Dict[int, int],
) -> Optional[Tuple[List[Pod], List[Tuple[Pod, bool]]]]:
    """The deterministic prefix of selectVictimsOnNode: (keep, ordered
    reprieve queue of (pod, violates_pdb)). Victims process in MoreImportant
    order, PDB-violating first, budgets decremented per candidate (:736)."""
    potential = [p for p in bound if p.priority < pod.priority]
    if not potential:
        return None
    keep = [p for p in bound if p.priority >= pod.priority]
    potential.sort(key=_more_important)
    allowed = dict(pdb_allowed)
    violating: List[Pod] = []
    non_violating: List[Pod] = []
    for p in potential:
        is_violating = False
        for i, pdb in enumerate(pdbs):
            if pdb.matches(p):
                allowed[i] = allowed.get(i, 0) - 1
                if allowed[i] < 0:
                    is_violating = True
        (violating if is_violating else non_violating).append(p)
    queue = [(p, True) for p in violating] + [(p, False) for p in non_violating]
    return keep, queue


@dataclass
class _Lane:
    """One candidate node's reprieve state in the lane driver."""
    node: Node
    remaining: List[Pod]
    queue: List[Tuple[Pod, bool]]
    victims: List[Pod]
    num_violating: int = 0


def _drive_lanes(pod: Pod, lanes: List[_Lane], fits_many_fn) -> List[PreemptionResult]:
    """The single reprieve implementation (selectVictimsOnNode's loop,
    :595-660) run over any number of lanes in lockstep rounds: round 0 checks
    fit with every potential victim evicted, then each round every active
    lane tries to reprieve its k-th queued victim. Per-lane semantics are
    exactly the sequential algorithm — lanes are independent. Lanes whose
    reprieve run ends with no victims are dropped (the pod's real failure
    was a filter preemption can't fix there)."""
    if not lanes:
        return []
    fit0 = fits_many_fn(pod, [(l.node, l.remaining) for l in lanes])
    lanes = [l for l, ok in zip(lanes, fit0) if ok]
    max_q = max((len(l.queue) for l in lanes), default=0)
    for k in range(max_q):
        active = [l for l in lanes if k < len(l.queue)]
        if not active:
            break
        results = fits_many_fn(
            pod, [(l.node, l.remaining + [l.queue[k][0]]) for l in active]
        )
        for lane, ok in zip(active, results):
            p, is_violating = lane.queue[k]
            if ok:
                lane.remaining.append(p)   # reprieved
            else:
                lane.victims.append(p)
                if is_violating:
                    lane.num_violating += 1
    return [
        PreemptionResult(
            node=l.node.name, victims=l.victims,
            num_pdb_violations=l.num_violating,
        )
        for l in lanes
        if l.victims
    ]


def select_victims_on_node(
    pod: Pod,
    node: Node,
    bound: Sequence[Pod],
    pdbs: Sequence[PodDisruptionBudget],
    pdb_allowed: Dict[int, int],
    fits_fn=None,
) -> Optional[PreemptionResult]:
    """selectVictimsOnNode (:578). `pdb_allowed` maps pdb index -> remaining
    DisruptionsAllowed (shared across the node loop the way the reference
    recomputes per node from status — budgets here are per-candidate, so pass
    a copy).

    `fits_fn(pod, node, remaining) -> bool` overrides the host-side
    resources-only fit model. Implemented as a one-lane run of the shared
    lane driver so there is exactly one reprieve implementation."""
    fits = fits_fn or _fits
    got = _victim_candidates(pod, bound, pdbs, pdb_allowed)
    if got is None:
        return None
    keep, queue = got

    def fits_many(pod2, items):
        return [fits(pod2, n, remaining) for n, remaining in items]

    out = _drive_lanes(
        pod, [_Lane(node=node, remaining=list(keep), queue=queue, victims=[])],
        fits_many,
    )
    return out[0] if out else None


def pick_one_node(candidates: List[PreemptionResult]) -> Optional[PreemptionResult]:
    """pickOneNodeForPreemption (:443) tiebreak cascade."""
    if not candidates:
        return None
    best = min(c.num_pdb_violations for c in candidates)
    pool = [c for c in candidates if c.num_pdb_violations == best]
    if len(pool) > 1:
        hi = min(max(v.priority for v in c.victims) for c in pool)
        pool = [c for c in pool if max(v.priority for v in c.victims) == hi]
    if len(pool) > 1:
        # Offset each victim by MaxInt32+1 (default_preemption.go:497-503) so
        # victim count dominates the sum even with negative priorities.
        def psum(c):
            return sum(v.priority + (1 << 31) for v in c.victims)

        s = min(psum(c) for c in pool)
        pool = [c for c in pool if psum(c) == s]
    if len(pool) > 1:
        n = min(len(c.victims) for c in pool)
        pool = [c for c in pool if len(c.victims) == n]
    return pool[0]


def call_preempt_extenders(
    extenders,
    pod: Pod,
    candidates: List[PreemptionResult],
    bound_by_node: Dict[str, List[Pod]],
    nodes: Sequence[Node] = (),
) -> List[PreemptionResult]:
    """CallExtenders (default_preemption.go:346-394): run the candidate map
    through every preemption-supporting, interested extender in chain order.
    Each extender may veto nodes or trim victims; its output feeds the next.
    An erroring ignorable extender is skipped; a non-ignorable one raises
    ExtenderError (the reference aborts the whole preemption). An empty map
    short-circuits — no preemption can happen regardless of later extenders.

    Candidates that pass through an extender come back with
    num_pdb_violations=0 — the vendored reconversion drops the count
    (extender.go:211-230); see HTTPExtender.process_preemption."""
    from .extenders import ExtenderError
    from ..utils.tracing import log

    relevant = [
        e for e in extenders
        if e.supports_preemption and e.is_interested(pod)
    ]
    if not relevant or not candidates:
        return candidates
    victims_map = {
        c.node: (list(c.victims), c.num_pdb_violations) for c in candidates
    }
    # NodeInfoLister analog (extender.go:214-217): every cluster node is
    # resolvable, with an empty pod list when nothing is bound there — an
    # extender answering with a pod-free node must not be misreported as
    # "unknown node".
    pods_on_node = {n.name: bound_by_node.get(n.name, []) for n in nodes}
    for name, pods in bound_by_node.items():
        pods_on_node.setdefault(name, pods)
    for ext in relevant:
        try:
            victims_map = ext.process_preemption(
                pod, victims_map, pods_on_node
            )
        except ExtenderError as e:
            if ext.is_ignorable:
                log.warning(
                    "skipping extender %s during preemption: %s (ignorable)",
                    ext.base, e,
                )
                continue
            raise
        if not victims_map:
            break
    return [
        PreemptionResult(node=node, victims=victims, num_pdb_violations=nv)
        for node, (victims, nv) in victims_map.items()
    ]


def try_preempt(
    pod: Pod,
    nodes: Sequence[Node],
    bound_by_node: Dict[str, List[Pod]],
    pdbs: Sequence[PodDisruptionBudget],
    fits_fn=None,
    fits_many_fn=None,
    extenders=(),
) -> Optional[PreemptionResult]:
    """Full PostFilter: find the best node + minimal victim set, or None.

    `fits_many_fn(pod, [(node, remaining), ...]) -> [bool]` enables the
    lane-parallel driver: every candidate node advances its reprieve loop in
    lockstep rounds, so one preemptor costs 1 + max(queue length) batched fit
    evaluations instead of sum over nodes of (1 + queue length) single
    probes. Per-lane semantics are identical to select_victims_on_node —
    lanes are independent (budgets are per-candidate copies, :736). This is
    the engine's analog of the reference evaluating selectVictimsOnNode in
    parallel goroutines over candidate nodes (default_preemption.go:560-576).
    """
    if pod.preemption_policy == "Never":
        return None  # PodEligibleToPreemptOthers (:231)
    # budgets from current healthy counts
    all_bound = [p for pods in bound_by_node.values() for p in pods]
    pdb_allowed = {
        i: pdb.allowed_disruptions(sum(1 for p in all_bound if pdb.matches(p)))
        for i, pdb in enumerate(pdbs)
    }
    if fits_many_fn is None:
        fits = fits_fn or _fits

        def fits_many_fn(pod2, items):   # one-probe-per-call adapter
            return [fits(pod2, n, remaining) for n, remaining in items]

    with span("preempt", pod=pod.key) as sp:
        lanes: List[_Lane] = []
        for node in nodes:
            if not _static_unresolvable_ok(pod, node):
                continue
            got = _victim_candidates(
                pod, bound_by_node.get(node.name, []), pdbs, pdb_allowed
            )
            if got is None:
                continue
            keep, queue = got
            lanes.append(_Lane(node=node, remaining=list(keep), queue=queue,
                               victims=[]))
        sp.meta["lanes"] = len(lanes)
        candidates = _drive_lanes(pod, lanes, fits_many_fn)
        # dryRunPreemption → CallExtenders → SelectCandidate (preempt(),
        # default_preemption.go:141-176): extenders see the full candidate map
        # between victim selection and the final pick.
        candidates = call_preempt_extenders(
            extenders, pod, candidates, bound_by_node, nodes
        )
        # An extender may have emptied a node's victim list while keeping the
        # node: such a candidate means "schedulable here without evictions"
        # from the extender's view, but the engine only reached preemption
        # because the pod failed — drop victimless candidates like
        # _drive_lanes does.
        return pick_one_node([c for c in candidates if c.victims])
