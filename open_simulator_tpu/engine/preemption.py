"""DefaultPreemption PostFilter: evict lower-priority pods to place a pod.

Parity target: the vendored default-preemption plugin,
`/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/framework/plugins/
defaultpreemption/default_preemption.go`:
  - PodEligibleToPreemptOthers (:231): preemptionPolicy != Never
  - nodesWherePreemptionMightHelp (:258): skip nodes whose filter failure is
    UnschedulableAndUnresolvable (taints, node affinity, node name,
    unschedulable flag — removing pods can't fix those)
  - selectVictimsOnNode (:578): remove ALL lower-priority pods, check fit,
    then reprieve PDB-violating victims first and non-violating second, each
    class from the most important pod down (MoreImportantPod = higher
    priority first)
  - filterPodsWithPDBViolation (:736): a victim violates a PDB when evicting
    it would drive the budget's DisruptionsAllowed below zero (budgets are
    decremented per selected victim)
  - pickOneNodeForPreemption (:443): fewest PDB violations → lowest highest
    victim priority → lowest victim-priority sum → fewest victims → first
    (the reference's final earliest-start-time tiebreaks have no analog here:
    the simulation has no pod start times)

Deviation (documented): feasibility during victim selection checks the
resolvable filters host-side — resources (CPU/mem/pods/extended) — on top of
the static unresolvable gate. Topology-spread/inter-pod-affinity/storage/GPU
coupling to victims is not modeled; the upstream plugin itself skips
affinity-to-victim coupling "for performance reasons" (:628-632).

This runs host-side: preemption is rare (only failed pods with priority > 0),
and its victim search is branch-heavy sequential logic that would serialize on
device anyway — the TPU path stays a pure batch scheduler, and preemption
re-syncs device state once per successful eviction round.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.matcher import (
    fits_resources,
    match_label_selector,
    match_node_affinity,
    untolerated_taint,
)
from ..core.objects import LabelSelector, Node, Pod


@dataclass
class PodDisruptionBudget:
    """Decoded policy/v1beta1 PodDisruptionBudget (the reference syncs PDBs
    into the fake cluster, simulator.go:388-394)."""
    name: str
    namespace: str
    selector: Optional[LabelSelector]
    min_available: Optional[str] = None      # int or "NN%"
    max_unavailable: Optional[str] = None
    disruptions_allowed: Optional[int] = None  # from status, when present

    @staticmethod
    def from_dict(d: dict) -> "PodDisruptionBudget":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        da = status.get("disruptionsAllowed")
        return PodDisruptionBudget(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            selector=LabelSelector.from_dict(spec.get("selector")),
            min_available=_opt_str(spec.get("minAvailable")),
            max_unavailable=_opt_str(spec.get("maxUnavailable")),
            disruptions_allowed=int(da) if da is not None else None,
        )

    def matches(self, pod: Pod) -> bool:
        if not pod.meta.labels:
            return False  # "A pod with no labels will not match any PDB"
        if pod.meta.namespace != self.namespace:
            return False
        if self.selector is None:
            return False  # nil/empty selector matches nothing (:755)
        if not self.selector.match_labels and not self.selector.match_expressions:
            return False
        return match_label_selector(self.selector, pod.meta.labels)

    def allowed_disruptions(self, matching_healthy: int) -> int:
        """DisruptionsAllowed: status value when provided; otherwise derived
        from spec the way the disruption controller would for currently-
        healthy count `matching_healthy`."""
        if self.disruptions_allowed is not None:
            return self.disruptions_allowed
        if self.min_available is not None:
            need = _resolve_count(self.min_available, matching_healthy)
            return max(0, matching_healthy - need)
        if self.max_unavailable is not None:
            return max(0, _resolve_count(self.max_unavailable, matching_healthy))
        return 0


def _opt_str(v) -> Optional[str]:
    return None if v is None else str(v)


def _resolve_count(v: str, total: int) -> int:
    if v.endswith("%"):
        import math

        return math.ceil(float(v[:-1]) / 100.0 * total)
    return int(v)


@dataclass
class PreemptionResult:
    node: str
    victims: List[Pod]
    num_pdb_violations: int


def _static_unresolvable_ok(pod: Pod, node: Node) -> bool:
    """Filters whose failure preemption cannot fix (nodesWherePreemptionMight-
    Help skips UnschedulableAndUnresolvable nodes)."""
    if node.unschedulable and not _tolerates_unschedulable(pod):
        return False
    if pod.node_name and pod.node_name != node.name:
        return False
    if untolerated_taint(pod.tolerations, node) is not None:
        return False
    if not match_node_affinity(pod, node):
        return False
    return True


def _tolerates_unschedulable(pod: Pod) -> bool:
    for t in pod.tolerations:
        key_ok = not t.key or t.key == "node.kubernetes.io/unschedulable"
        val_ok = t.operator == "Exists" or not t.value
        eff_ok = not t.effect or t.effect == "NoSchedule"
        if key_ok and val_ok and eff_ok:
            return True
    return False


def _free_after(node: Node, pods: Sequence[Pod]) -> Dict[str, int]:
    free = dict(node.allocatable)
    free["pods"] = free.get("pods", 0)
    for p in pods:
        for res, q in p.requests.items():
            free[res] = free.get(res, 0) - q
        free["pods"] = free.get("pods", 0) - 1
    return free


def _fits(pod: Pod, node: Node, remaining: Sequence[Pod]) -> bool:
    free = _free_after(node, remaining)
    # pod.requests never carries the "pods" slot resource; check it explicitly
    # (the reference's full-filter dry run gets this via NodeResourcesFit).
    if free.get("pods", 0) < 1:
        return False
    return not fits_resources(pod, free)


def _more_important(p: Pod) -> Tuple:
    """Sort key for MoreImportantPod order (higher priority first; the
    start-time tiebreak has no analog — encoding order is stable)."""
    return (-p.priority,)


def select_victims_on_node(
    pod: Pod,
    node: Node,
    bound: Sequence[Pod],
    pdbs: Sequence[PodDisruptionBudget],
    pdb_allowed: Dict[int, int],
    fits_fn=None,
) -> Optional[PreemptionResult]:
    """selectVictimsOnNode (:578). `pdb_allowed` maps pdb index -> remaining
    DisruptionsAllowed (shared across the node loop the way the reference
    recomputes per node from status — budgets here are per-candidate, so pass
    a copy).

    `fits_fn(pod, node, remaining) -> bool` overrides the host-side
    resources-only fit model; the engine passes the device filter kernel
    (Simulator._device_fits) so victim selection sees the FULL filter set —
    spread/affinity/storage/GPU/ports — exactly like the reference's dry-run
    of the filter plugins on the post-eviction node (:598-626)."""
    fits = fits_fn or _fits
    potential = [p for p in bound if p.priority < pod.priority]
    if not potential:
        return None
    keep = [p for p in bound if p.priority >= pod.priority]
    if not fits(pod, node, keep):
        return None

    potential.sort(key=_more_important)
    # split by PDB violation, decrementing budgets per selected victim (:736)
    allowed = dict(pdb_allowed)
    violating: List[Pod] = []
    non_violating: List[Pod] = []
    for p in potential:
        is_violating = False
        for i, pdb in enumerate(pdbs):
            if pdb.matches(p):
                allowed[i] = allowed.get(i, 0) - 1
                if allowed[i] < 0:
                    is_violating = True
        (violating if is_violating else non_violating).append(p)

    victims: List[Pod] = []
    num_violating = 0
    remaining = list(keep)

    def reprieve(p: Pod) -> bool:
        remaining.append(p)
        if fits(pod, node, remaining):
            return True
        remaining.pop()
        victims.append(p)
        return False

    for p in violating:
        if not reprieve(p):
            num_violating += 1
    for p in non_violating:
        reprieve(p)
    if not victims:
        # Every candidate was reprieved: the pod fits without evictions under
        # this host-side resource model, so its real failure was a filter
        # preemption can't resolve here — don't nominate this node.
        return None
    return PreemptionResult(node=node.name, victims=victims, num_pdb_violations=num_violating)


def pick_one_node(candidates: List[PreemptionResult]) -> Optional[PreemptionResult]:
    """pickOneNodeForPreemption (:443) tiebreak cascade."""
    if not candidates:
        return None
    best = min(c.num_pdb_violations for c in candidates)
    pool = [c for c in candidates if c.num_pdb_violations == best]
    if len(pool) > 1:
        hi = min(max(v.priority for v in c.victims) for c in pool)
        pool = [c for c in pool if max(v.priority for v in c.victims) == hi]
    if len(pool) > 1:
        # Offset each victim by MaxInt32+1 (default_preemption.go:497-503) so
        # victim count dominates the sum even with negative priorities.
        def psum(c):
            return sum(v.priority + (1 << 31) for v in c.victims)

        s = min(psum(c) for c in pool)
        pool = [c for c in pool if psum(c) == s]
    if len(pool) > 1:
        n = min(len(c.victims) for c in pool)
        pool = [c for c in pool if len(c.victims) == n]
    return pool[0]


def try_preempt(
    pod: Pod,
    nodes: Sequence[Node],
    bound_by_node: Dict[str, List[Pod]],
    pdbs: Sequence[PodDisruptionBudget],
    fits_fn=None,
) -> Optional[PreemptionResult]:
    """Full PostFilter: find the best node + minimal victim set, or None."""
    if pod.preemption_policy == "Never":
        return None  # PodEligibleToPreemptOthers (:231)
    # budgets from current healthy counts
    all_bound = [p for pods in bound_by_node.values() for p in pods]
    pdb_allowed = {
        i: pdb.allowed_disruptions(sum(1 for p in all_bound if pdb.matches(p)))
        for i, pdb in enumerate(pdbs)
    }
    candidates: List[PreemptionResult] = []
    for node in nodes:
        if not _static_unresolvable_ok(pod, node):
            continue
        res = select_victims_on_node(
            pod, node, bound_by_node.get(node.name, []), pdbs, pdb_allowed,
            fits_fn=fits_fn,
        )
        if res is not None:
            candidates.append(res)
    return pick_one_node(candidates)
