"""AOT warmup: compile every jit entry before the watchdog window opens.

Every bench round that wedged (BENCH_r02..r05) lost its deadline inside an
XLA compile — the first real batch paid 76 s of compilation against a
flaky TPU tunnel and the watchdog killed the round. The fix is to make
compilation a *phase*, not a side effect: enumerate every audited jit
entry at its canonical bucketed shapes (the exact capture list
`simon audit` proves over, analysis/jaxpr_audit.AUDIT_TARGETS), drive each
through the AOT chain ``fn.trace(...).lower().compile()``, and let the
persistent compilation cache bank the executables. A later process that
shares ``OSIM_COMPILE_CACHE`` then serves every compile request from the
cache — `simon warmup --check` asserts exactly that (zero *cold* compiles
over the full capacity sweep, see jaxpr_audit.warm_start_check).

The registry is not a second list to keep in sync: `warmup_registry()`
replays jaxpr_audit's capture pass, so the warmup set and the audit set
are identical by construction (one entry per AUDIT_TARGETS attr), and a
jit entry added without audit coverage fails both gates at once. The
same capture list feeds `simon preflight` (analysis/hlo_audit), which
re-lowers every entry abstractly at each ladder rung × mesh shape for
the static HBM/collective budget gate — so the warmup, audit, and
preflight sets cannot drift apart either.

Node-axis shapes come from the bucket ladder (ops.encode.node_bucket):
the sweep rehearsal touches the same ladder rungs a production capacity
search rounds to, so the report's ``ladder_rungs`` names exactly the
node-axis shape family the cache banked — an off-ladder rung in a later
run is a shape the warmup could not have pre-compiled, and the recompile
guard's ``ladder_ok`` flags it.

Donation interacts cleanly: ``Function.trace`` only needs avals, so
entries that donate buffers (ops.delta scatters, the scenario commit
engine) trace fine even though the capture run consumed their originals
(the capture snapshots donated args — jaxpr_audit._snapshot_donated).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, List

__all__ = [
    "EntryWarmup",
    "WarmupReport",
    "warmup_registry",
    "registry_captures",
    "run_warmup",
]


@dataclasses.dataclass
class EntryWarmup:
    """One registry entry driven through trace().lower().compile()."""

    name: str
    seconds: float
    donated: List[int] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seconds": round(self.seconds, 4),
            "donated": list(self.donated),
        }


@dataclasses.dataclass
class WarmupReport:
    """What `simon warmup` did: per-entry AOT compiles plus the sweep
    rehearsal, with the CompileCounter's honest compile accounting.

    ``ok`` demands full registry coverage (every REQUIRED_COVERAGE entry
    captured and compiled) — NOT zero compiles; a cold process is supposed
    to compile here. Zero-compile assertions belong to the warm-start
    check, which runs after this banked the cache."""

    entries: List[EntryWarmup]
    missing: List[str]
    seconds: float
    backend_compiles: int
    persistent_hits: int
    cache_dir: str = ""
    swept: bool = True
    #: node-bucket ladder rungs the sweep rehearsal compiled programs for
    ladder_rungs: List[int] = dataclasses.field(default_factory=list)

    @property
    def cold_compiles(self) -> int:
        return max(0, self.backend_compiles - self.persistent_hits)

    @property
    def ok(self) -> bool:
        return not self.missing

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "entries": [e.to_dict() for e in self.entries],
            "missing": list(self.missing),
            "seconds": round(self.seconds, 4),
            "backend_compiles": self.backend_compiles,
            "persistent_hits": self.persistent_hits,
            "cold_compiles": self.cold_compiles,
            "cache_dir": self.cache_dir,
            "swept": self.swept,
            "ladder_rungs": list(self.ladder_rungs),
        }

    def render_text(self) -> str:
        lines = [
            f"warmup: {'ok' if self.ok else 'FAILED'} — "
            f"{len(self.entries)} entries AOT-compiled in {self.seconds:.2f}s "
            f"({self.backend_compiles} compile request(s), "
            f"{self.persistent_hits} persistent-cache hit(s), "
            f"{self.cold_compiles} cold)"
        ]
        if self.cache_dir:
            lines.append(f"  cache: {self.cache_dir}")
        if not self.swept:
            lines.append("  sweep rehearsal: skipped (--no-sweep)")
        elif self.ladder_rungs:
            lines.append(
                f"  node-bucket rungs banked: {self.ladder_rungs}"
            )
        for e in sorted(self.entries, key=lambda e: -e.seconds):
            don = (
                f"  donates {e.donated}" if e.donated else ""
            )
            lines.append(f"  {e.name:28s} {e.seconds:7.3f}s{don}")
        for name in self.missing:
            lines.append(f"  MISSING: {name} (audited but not captured)")
        return "\n".join(lines)


def warmup_registry() -> List[Any]:
    """The warmup registry: jaxpr_audit's capture list — one _Captured
    (name, jitted fn, canonical concrete args) per audited entry, produced
    by running the host dispatchers over the canonical bucketed state.

    Note the capture run itself executes every entry, so calling this on a
    cold process already populates the persistent cache; run_warmup's AOT
    pass on top is the explicit, per-entry-timed contract."""
    from ..analysis.jaxpr_audit import _capture_calls

    return _capture_calls()


def registry_captures(names: Any = None) -> List[Any]:
    """`warmup_registry()` filtered to ``names`` (audit names like
    ``"ops.fast:schedule_scenarios"``); ``None`` keeps everything.

    Raises KeyError naming the misses so a preflight run asked for an
    entry that no longer exists fails loudly instead of silently
    auditing an empty matrix."""
    caps = warmup_registry()
    if names is None:
        return caps
    wanted = set(names)
    got = [c for c in caps if c.name in wanted]
    missing = wanted - {c.name for c in got}
    if missing:
        raise KeyError(
            f"not in the capture registry: {sorted(missing)} "
            f"(known: {sorted(c.name for c in caps)})"
        )
    return got


def run_warmup(include_sweep: bool = True) -> WarmupReport:
    """Compile everything the engine will need, before anyone is timing.

    1. Configure the persistent compilation cache (OSIM_COMPILE_CACHE) —
       BEFORE the first compile, or the bank stays empty.
    2. Capture the registry (executes each entry once at canonical shapes).
    3. Drive every entry through trace().lower().compile() — the AOT chain
       the compile-lifecycle docs promise; per-entry seconds reported.
    4. With ``include_sweep``, rehearse the full capacity sweep
       (jaxpr_audit._run_sweeps) so auxiliary programs the sweeps build
       outside the audited entries (growth shapes, reductions) are banked
       too — this is what lets `simon warmup --check` demand zero cold
       compiles over the same sweep.
    """
    from ..analysis.jaxpr_audit import REQUIRED_COVERAGE, _run_sweeps
    from ..ops.fast import reset_scenario_programs, scenario_programs
    from ..utils.platform import (
        CompileCounter,
        enable_compilation_cache,
        install_compile_listener,
    )

    cache_dir = enable_compilation_cache()
    install_compile_listener()
    reset_scenario_programs()
    t_start = time.perf_counter()
    entries: List[EntryWarmup] = []
    with CompileCounter() as counter:
        caps = warmup_registry()
        for cap in caps:
            t0 = time.perf_counter()
            cap.fn.trace(*cap.args, **cap.kwargs).lower().compile()
            entries.append(
                EntryWarmup(
                    name=cap.name,
                    seconds=time.perf_counter() - t0,
                    donated=sorted(
                        getattr(cap.fn, "__osim_donate_argnums__", ()) or ()
                    ),
                )
            )
        if include_sweep:
            _run_sweeps()
    missing = sorted(REQUIRED_COVERAGE - {e.name for e in entries})
    return WarmupReport(
        entries=entries,
        missing=missing,
        seconds=time.perf_counter() - t_start,
        backend_compiles=counter.backend_compiles,
        persistent_hits=counter.persistent_hits,
        cache_dir=cache_dir or "",
        swept=include_sweep,
        ladder_rungs=sorted({n for (n, _p) in scenario_programs()}),
    )
