"""Scheduler extenders: out-of-process filter/prioritize over HTTP.

Parity: the vendored HTTPExtender
(`/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/core/extender.go`)
as wired by `pkg/simulator/simulator.go:211-216` (WithExtenders). The engine
calls extenders between the device filter mask and the final score combine,
exactly where `generic_scheduler.go` does:

  - Filter: `findNodesThatPassExtenders` (generic_scheduler.go:345-374) —
    extenders run in config order over the currently-feasible set; a failed
    map entry records the node's failure message; an error skips an
    `ignorable` extender and fails the pod otherwise.
  - Prioritize: `prioritizeNodes` (generic_scheduler.go:521-555) — each
    extender returns host scores in 0..10, multiplied by the extender weight,
    summed, then scaled by MaxNodeScore/MaxExtenderPriority (= 10) and added
    to the framework score.
  - IsInterested (extender.go:440-468): managedResources empty = every pod;
    otherwise the pod must request at least one managed resource.

Wire format: ExtenderArgs{Pod, Nodes|NodeNames} in; ExtenderFilterResult /
HostPriorityList out — the same JSON schema real extenders implement, so an
extender written for the reference works against this engine unchanged.
"""

from __future__ import annotations

import http.client
import json
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.objects import Node, Pod
from ..models.profiles import ExtenderConfig
from ..resilience import faults
from ..resilience.policy import RetryExhaustedError, RetryPolicy, breaker_for
from ..utils import httppool, metrics
from ..utils.tracing import current_traceparent, log, span

# framework.MaxNodeScore / extenderv1.MaxExtenderPriority (100 / 10)
EXTENDER_SCORE_SCALE = 10.0

# Response-body bytes quoted in error messages (real extenders put the actual
# failure reason in the body; unbounded quoting would bloat pod reasons).
ERROR_BODY_SNIPPET_BYTES = 200


class ExtenderError(Exception):
    """A non-ignorable extender failed; the pod being scheduled fails with
    this message (the reference aborts Schedule() with the error)."""


class TransientExtenderError(ExtenderError):
    """An extender failure worth retrying: connection/timeout errors, HTTP
    5xx, or a malformed (possibly truncated) JSON body. Subclasses
    ExtenderError so an exhausted retry degrades exactly like before."""


def _http_error_detail(e: urllib.error.HTTPError) -> str:
    """Status line + a bounded body snippet. urlopen raises HTTPError on any
    non-2xx, so this — not a dead `resp.status != 200` branch — is where
    extender-side failure text (carried in the body) must be captured."""
    try:
        body = e.read(ERROR_BODY_SNIPPET_BYTES + 1)
    except Exception:
        body = b""
    snippet = body[:ERROR_BODY_SNIPPET_BYTES].decode("utf-8", "replace").strip()
    detail = f"HTTP {e.code} {e.reason}"
    return f"{detail}: {snippet}" if snippet else detail


def _pod_json(pod: Pod) -> dict:
    """v1.Pod JSON for the wire. Prefer the original manifest; overlay the
    fields the engine owns (name/namespace/labels/annotations/nodeName) so
    synthesized workload pods (whose raw is the template) are still
    identifiable by the extender."""
    d = dict(pod.raw) if pod.raw else {"apiVersion": "v1", "kind": "Pod"}
    meta = dict(d.get("metadata") or {})
    meta["name"] = pod.meta.name
    meta["namespace"] = pod.meta.namespace or "default"
    # an extender following the k8s protocol names preemption victims by
    # string(pod.UID); emit the engine's pod identity so it round-trips
    meta.setdefault("uid", _pod_uid(pod))
    if pod.meta.labels:
        meta["labels"] = dict(pod.meta.labels)
    if pod.meta.annotations:
        meta["annotations"] = dict(pod.meta.annotations)
    d["metadata"] = meta
    spec = dict(d.get("spec") or {})
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    spec.setdefault("schedulerName", pod.scheduler_name)
    if not spec.get("containers"):
        # minimal container so the pod parses as a v1.Pod on the far side
        spec["containers"] = [
            {
                "name": "app",
                "image": "none",
                "resources": {
                    "requests": {k: str(v) for k, v in pod.requests.items()}
                },
            }
        ]
    d["spec"] = spec
    return d


def _pod_uid(pod: Pod) -> str:
    """MetaPod identity (extender.go:255-260 uses string(pod.UID)). Simulated
    pods usually carry no UID, so fall back to namespace/name — unique here
    because workload expansion uniquifies names with RNG suffixes. _pod_json
    emits this same value as metadata.uid, so a protocol-conformant extender
    that echoes string(pod.UID) round-trips."""
    uid = ((pod.raw or {}).get("metadata") or {}).get("uid")
    return str(uid) if uid else f"{pod.meta.namespace or 'default'}/{pod.meta.name}"


def _node_json(node: Node) -> dict:
    d = dict(node.raw) if node.raw else {"apiVersion": "v1", "kind": "Node"}
    meta = dict(d.get("metadata") or {})
    meta["name"] = node.name
    if node.meta.labels:
        meta["labels"] = dict(node.meta.labels)
    if node.meta.annotations:
        meta["annotations"] = dict(node.meta.annotations)
    d["metadata"] = meta
    return d


class HTTPExtender:
    """One configured extender endpoint (extender.go:93-123)."""

    def __init__(
        self, cfg: ExtenderConfig, policy: Optional[RetryPolicy] = None
    ):
        self.cfg = cfg
        base = cfg.url_prefix.rstrip("/")
        if cfg.enable_https and base.startswith("http://"):
            base = "https://" + base[len("http://"):]
        self.base = base
        self.managed = frozenset(r for r in cfg.managed_resources if r)
        # Retries cover the idempotent filter/prioritize verbs only; the
        # breaker registry is endpoint-keyed and shared process-wide so its
        # state survives the per-simulate() rebuild of HTTPExtender objects.
        self.policy = policy if policy is not None else RetryPolicy.from_env()
        self.breaker = breaker_for(self.base)
        # a pod's wire JSON is identical across its filter and prioritize
        # calls; id() keys are safe because pods outlive the per-simulate()
        # extender object holding this cache
        self._pod_json_cache: Dict[int, dict] = {}

    # -- extender.go:440-468 ------------------------------------------------
    def is_interested(self, pod: Pod) -> bool:
        """managedResources empty = every pod; otherwise the pod must name a
        managed resource under requests OR limits (hasManagedResources scans
        both, extender.go:448-468 — a limits-only extended resource still
        routes the pod through the extender)."""
        if not self.managed:
            return True
        return any(r in self.managed for r in pod.requests) or any(
            r in self.managed for r in pod.limits
        )

    @property
    def is_ignorable(self) -> bool:
        return self.cfg.ignorable

    def _roundtrip(self, url: str, verb: str, data: bytes,
                   timeout: Optional[float], key: str = "") -> dict:
        """One HTTP attempt over the shared keep-alive pool. Transient
        failures (connection/timeout, HTTP 5xx, malformed JSON) raise
        TransientExtenderError; everything else raises plain ExtenderError
        and is never retried. `key` (pod UID) keys fault injection so a plan
        replays byte-identically under the concurrent wave engine."""
        rule = faults.maybe_inject("extender", verb, key=key)
        body: Optional[bytes] = None
        try:
            if rule is not None:
                body = faults.apply_http_fault(rule, url)
            if body is None:
                # http_timeout_s == 0 means no client timeout (Go zero
                # Timeout); a retry policy deadline may tighten it further
                eff = timeout
                if self.cfg.http_timeout_s:
                    eff = (
                        self.cfg.http_timeout_s
                        if eff is None
                        else min(eff, self.cfg.http_timeout_s)
                    )
                # Both transports carry the W3C traceparent of whatever
                # trace this worker thread is inside, so the extender's own
                # telemetry can join the request's trace; the attempt
                # itself is a child span, and the header names THAT span
                # (the response "lands" under it). Outside any trace no
                # header is sent — minting one nobody can correlate is
                # noise — and the empty value tells the pool transport to
                # skip its own injection (the extender-http span would
                # otherwise look like an active trace to it).
                headers = {"Content-Type": "application/json"}
                traced = current_traceparent() is not None
                if not httppool.keepalive_enabled():
                    # transport escape hatch (OSIM_EXTENDER_KEEPALIVE=0):
                    # one fresh connection per request; urlopen raises
                    # HTTPError on >= 400, handled below like fault-plan
                    # errors
                    with span("extender-http", verb=verb, url=url):
                        if traced:
                            headers["traceparent"] = current_traceparent()
                        req = urllib.request.Request(
                            url, data=data, method="POST", headers=headers,
                        )
                        with urllib.request.urlopen(req, timeout=eff) as resp:
                            body = resp.read()
                else:
                    pool, path = httppool.pool_for(url)
                    with span("extender-http", verb=verb, url=url) as hs:
                        headers["traceparent"] = (
                            current_traceparent() if traced else ""
                        )
                        status, reason, raw = pool.request(
                            "POST", path, data, headers, eff,
                        )
                        hs.meta["status"] = status
                    if status >= 400:
                        snippet = (
                            raw[:ERROR_BODY_SNIPPET_BYTES]
                            .decode("utf-8", "replace").strip()
                        )
                        detail = f"HTTP {status} {reason}"
                        if snippet:
                            detail = f"{detail}: {snippet}"
                        cls = (
                            TransientExtenderError
                            if status >= 500
                            else ExtenderError
                        )
                        raise cls(f"extender {url}: {detail}")
                    body = raw
        except urllib.error.HTTPError as e:
            # raised by the fault plan (apply_http_fault keeps the old
            # transport's exception shape) and by the keepalive=0 transport
            detail = _http_error_detail(e)
            cls = TransientExtenderError if e.code >= 500 else ExtenderError
            raise cls(f"extender {url}: {detail}")
        except ExtenderError:
            raise
        except (
            urllib.error.URLError, http.client.HTTPException, OSError,
            TimeoutError,
        ) as e:
            raise TransientExtenderError(f"extender {url}: {e}")
        try:
            return json.loads(body) or {}
        except ValueError as e:
            # truncated/garbled payloads are transport-level and transient
            raise TransientExtenderError(
                f"extender {url}: invalid JSON response: {e}"
            )

    def _send(
        self, verb: str, args: dict, retry: bool = True, key: str = ""
    ) -> dict:
        url = f"{self.base}/{verb}"
        data = json.dumps(args).encode()
        t0 = time.monotonic()
        outcome = "ok"
        try:
            if not self.breaker.allow():
                outcome = "circuit_open"
                metrics.EXTENDER_REQUESTS.inc(
                    verb=verb, outcome="circuit_open"
                )
                raise ExtenderError(
                    f"extender {url}: {self.breaker.describe()}; failing fast"
                )
            try:
                if retry:
                    out = self.policy.execute(
                        lambda t: self._roundtrip(url, verb, data, t, key),
                        retryable=(TransientExtenderError,),
                        target="extender",
                    )
                else:
                    out = self._roundtrip(url, verb, data, None, key)
            except RetryExhaustedError as e:
                self.breaker.record_failure(str(e.last_exc))
                # stays Transient: the capacity planner re-runs trials that
                # failed this way rather than buying nodes for a blip
                raise TransientExtenderError(str(e))
            except ExtenderError as e:
                self.breaker.record_failure(str(e))
                raise
            self.breaker.record_success()
        except ExtenderError:
            if outcome == "ok":
                outcome = "error"
            metrics.EXTENDER_REQUESTS.inc(verb=verb, outcome="error")
            raise
        finally:
            # error and fail-fast outcomes cost wall time too; the old
            # success-only observation hid retry storms from the histogram
            metrics.EXTENDER_DURATION.observe(
                time.monotonic() - t0, verb=verb, outcome=outcome
            )
        metrics.EXTENDER_REQUESTS.inc(verb=verb, outcome="ok")
        return out

    def _wire_args(self, pod: Pod, nodes: Sequence[Node]) -> dict:
        """ExtenderArgs{Pod, Nodes|NodeNames} — shared by filter and
        prioritize so the wire shape can't diverge between verbs."""
        pj = self._pod_json_cache.get(id(pod))
        if pj is None:
            pj = self._pod_json_cache[id(pod)] = _pod_json(pod)
        args: dict = {"Pod": pj}
        if self.cfg.node_cache_capable:
            args["NodeNames"] = [n.name for n in nodes]
            args["Nodes"] = None
        else:
            args["NodeNames"] = None
            args["Nodes"] = {"items": [_node_json(n) for n in nodes]}
        return args

    # -- extender.go:273-341 ------------------------------------------------
    def filter(
        self, pod: Pod, nodes: Sequence[Node]
    ) -> Tuple[List[Node], Dict[str, str]]:
        """Returns (still-feasible nodes, failed node -> message). Raises
        ExtenderError on transport/extender errors (caller applies the
        ignorable policy)."""
        if not self.cfg.filter_verb:
            return list(nodes), {}
        by_name = {n.name: n for n in nodes}
        result = self._send(
            self.cfg.filter_verb, self._wire_args(pod, nodes),
            key=_pod_uid(pod),
        )
        if result.get("Error"):
            raise ExtenderError(
                f"extender {self.base}: {result['Error']}"
            )
        out: List[Node] = []
        if self.cfg.node_cache_capable and result.get("NodeNames") is not None:
            for name in result["NodeNames"]:
                node = by_name.get(name)
                if node is None:
                    raise ExtenderError(
                        f"extender {self.base} claims a filtered node "
                        f"{name!r} which is not in the input node list"
                    )
                out.append(node)
        elif result.get("Nodes") is not None:
            for item in result["Nodes"].get("items") or []:
                name = (item.get("metadata") or {}).get("name", "")
                node = by_name.get(name)
                if node is not None:
                    out.append(node)
        failed = {
            str(k): str(v)
            for k, v in (result.get("FailedNodes") or {}).items()
        }
        return out, failed

    # -- extender.go:158-230 ------------------------------------------------
    @property
    def supports_preemption(self) -> bool:
        """SupportsPreemption (extender.go:160-162): preemptVerb defined."""
        return bool(self.cfg.preempt_verb)

    def process_preemption(
        self,
        pod: Pod,
        victims_map: Dict[str, Tuple[List[Pod], int]],
        pods_on_node: Dict[str, List[Pod]],
    ) -> Dict[str, Tuple[List[Pod], int]]:
        """ProcessPreemption (extender.go:164-205): send the candidate
        node -> victims map, return the extender's trimmed map. The extender
        may veto whole nodes (dropping map keys) or trim/replace victims on a
        node (any pod bound there is addressable, like the reference's
        nodeInfo.Pods lookup).

        `victims_map`: node name -> (victim pods, numPDBViolations).
        `pods_on_node`: node name -> all bound pods (the NodeInfoLister
        analog used to resolve returned MetaPod UIDs back to pods).

        Raises ExtenderError on transport errors or on a response naming an
        unknown node/pod UID (convertPodUIDToPod treats cache inconsistency
        as an error, extender.go:236-253)."""
        if not self.supports_preemption:
            raise ExtenderError(
                f"preempt verb is not defined for extender {self.base} but "
                "run into ProcessPreemption"
            )
        args: dict = {"Pod": _pod_json(pod)}
        if self.cfg.node_cache_capable:
            # MetaVictims: pod identity only (UIDs). The reference's
            # convertToNodeNameToMetaVictims builds Pods and leaves
            # NumPDBViolations at its zero value (extender.go:246-268) —
            # send 0 for byte parity, not the real count.
            args["NodeNameToMetaVictims"] = {
                node: {
                    "Pods": [{"UID": _pod_uid(v)} for v in victims],
                    "NumPDBViolations": 0,
                }
                for node, (victims, _n_viol) in victims_map.items()
            }
        else:
            args["NodeNameToVictims"] = {
                node: {
                    "Pods": [_pod_json(v) for v in victims],
                    "NumPDBViolations": n_viol,
                }
                for node, (victims, n_viol) in victims_map.items()
            }
        # ProcessPreemption is NOT retried: the verb mutates extender-side
        # victim bookkeeping in real deployments, so only the idempotent
        # filter/prioritize verbs ride the retry policy.
        result = self._send(
            self.cfg.preempt_verb, args, retry=False, key=_pod_uid(pod)
        )
        # The extender always returns NodeNameToMetaVictims (extender.go:195)
        out: Dict[str, Tuple[List[Pod], int]] = {}
        for node, meta in (result.get("NodeNameToMetaVictims") or {}).items():
            bound = pods_on_node.get(node)
            if bound is None:
                raise ExtenderError(
                    f"extender {self.base} returned preemption victims on "
                    f"unknown node {node!r}"
                )
            by_uid = {_pod_uid(p): p for p in bound}
            victims: List[Pod] = []
            for mp in (meta or {}).get("Pods") or []:
                uid = str((mp or {}).get("UID", ""))
                v = by_uid.get(uid)
                if v is None:
                    raise ExtenderError(
                        f"extender {self.base} returned victim pod {uid!r} "
                        f"not found on node {node!r} (cache inconsistency)"
                    )
                victims.append(v)
            # Parity quirk: the vendored convertToNodeNameToVictims rebuilds
            # Victims{Pods} WITHOUT copying NumPDBViolations
            # (extender.go:211-230), so candidates that pass through an
            # extender lose their violation count — pickOneNode then
            # tiebreaks on victim priorities alone. Mirrored exactly.
            out[node] = (victims, 0)
        return out

    # -- extender.go:343-381 ------------------------------------------------
    def prioritize(
        self, pod: Pod, nodes: Sequence[Node]
    ) -> Dict[str, float]:
        """host -> score*weight (HostPriorityList entries are 0..10; the
        caller scales the combined sum by EXTENDER_SCORE_SCALE)."""
        if not self.cfg.prioritize_verb:
            return {}
        result = self._send(
            self.cfg.prioritize_verb, self._wire_args(pod, nodes),
            key=_pod_uid(pod),
        )
        out: Dict[str, float] = {}
        entries = result if isinstance(result, list) else []
        for item in entries:
            if isinstance(item, dict):
                out[str(item.get("Host", ""))] = (
                    float(item.get("Score", 0)) * float(self.cfg.weight)
                )
        return out


def build_extenders(
    configs: Optional[Sequence[ExtenderConfig]],
) -> List[HTTPExtender]:
    exts = [HTTPExtender(c) for c in (configs or [])]
    for e in exts:
        if e.cfg.bind_verb:
            log.warning(
                "extender %s: bindVerb is accepted but inert (simon disables "
                "DefaultBinder and binds through its own plugin)", e.base,
            )
    # The reference moves ignorable extenders to the tail of the chain
    # (factory.go:111-113) so a non-ignorable extender's error aborts the pod
    # before any ignorable one runs; failedNodes first-wins attribution
    # follows the same order.
    return [e for e in exts if not e.is_ignorable] + [
        e for e in exts if e.is_ignorable
    ]
