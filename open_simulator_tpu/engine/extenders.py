"""Scheduler extenders: out-of-process filter/prioritize over HTTP.

Parity: the vendored HTTPExtender
(`/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/core/extender.go`)
as wired by `pkg/simulator/simulator.go:211-216` (WithExtenders). The engine
calls extenders between the device filter mask and the final score combine,
exactly where `generic_scheduler.go` does:

  - Filter: `findNodesThatPassExtenders` (generic_scheduler.go:345-374) —
    extenders run in config order over the currently-feasible set; a failed
    map entry records the node's failure message; an error skips an
    `ignorable` extender and fails the pod otherwise.
  - Prioritize: `prioritizeNodes` (generic_scheduler.go:521-555) — each
    extender returns host scores in 0..10, multiplied by the extender weight,
    summed, then scaled by MaxNodeScore/MaxExtenderPriority (= 10) and added
    to the framework score.
  - IsInterested (extender.go:440-468): managedResources empty = every pod;
    otherwise the pod must request at least one managed resource.

Wire format: ExtenderArgs{Pod, Nodes|NodeNames} in; ExtenderFilterResult /
HostPriorityList out — the same JSON schema real extenders implement, so an
extender written for the reference works against this engine unchanged.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.objects import Node, Pod
from ..models.profiles import ExtenderConfig
from ..utils.tracing import log

# framework.MaxNodeScore / extenderv1.MaxExtenderPriority (100 / 10)
EXTENDER_SCORE_SCALE = 10.0


class ExtenderError(Exception):
    """A non-ignorable extender failed; the pod being scheduled fails with
    this message (the reference aborts Schedule() with the error)."""


def _pod_json(pod: Pod) -> dict:
    """v1.Pod JSON for the wire. Prefer the original manifest; overlay the
    fields the engine owns (name/namespace/labels/annotations/nodeName) so
    synthesized workload pods (whose raw is the template) are still
    identifiable by the extender."""
    d = dict(pod.raw) if pod.raw else {"apiVersion": "v1", "kind": "Pod"}
    meta = dict(d.get("metadata") or {})
    meta["name"] = pod.meta.name
    meta["namespace"] = pod.meta.namespace or "default"
    if pod.meta.labels:
        meta["labels"] = dict(pod.meta.labels)
    if pod.meta.annotations:
        meta["annotations"] = dict(pod.meta.annotations)
    d["metadata"] = meta
    spec = dict(d.get("spec") or {})
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    spec.setdefault("schedulerName", pod.scheduler_name)
    if not spec.get("containers"):
        # minimal container so the pod parses as a v1.Pod on the far side
        spec["containers"] = [
            {
                "name": "app",
                "image": "none",
                "resources": {
                    "requests": {k: str(v) for k, v in pod.requests.items()}
                },
            }
        ]
    d["spec"] = spec
    return d


def _node_json(node: Node) -> dict:
    d = dict(node.raw) if node.raw else {"apiVersion": "v1", "kind": "Node"}
    meta = dict(d.get("metadata") or {})
    meta["name"] = node.name
    if node.meta.labels:
        meta["labels"] = dict(node.meta.labels)
    if node.meta.annotations:
        meta["annotations"] = dict(node.meta.annotations)
    d["metadata"] = meta
    return d


class HTTPExtender:
    """One configured extender endpoint (extender.go:93-123)."""

    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg
        base = cfg.url_prefix.rstrip("/")
        if cfg.enable_https and base.startswith("http://"):
            base = "https://" + base[len("http://"):]
        self.base = base
        self.managed = frozenset(r for r in cfg.managed_resources if r)

    # -- extender.go:440-468 ------------------------------------------------
    def is_interested(self, pod: Pod) -> bool:
        """managedResources empty = every pod; otherwise the pod must name a
        managed resource under requests OR limits (hasManagedResources scans
        both, extender.go:448-468 — a limits-only extended resource still
        routes the pod through the extender)."""
        if not self.managed:
            return True
        return any(r in self.managed for r in pod.requests) or any(
            r in self.managed for r in pod.limits
        )

    @property
    def is_ignorable(self) -> bool:
        return self.cfg.ignorable

    def _send(self, verb: str, args: dict) -> dict:
        url = f"{self.base}/{verb}"
        data = json.dumps(args).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.cfg.http_timeout_s
            ) as resp:
                body = resp.read()
                if resp.status != 200:
                    raise ExtenderError(
                        f"extender {url}: HTTP {resp.status}"
                    )
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ExtenderError(f"extender {url}: {e}")
        try:
            return json.loads(body) or {}
        except ValueError as e:
            raise ExtenderError(f"extender {url}: invalid JSON response: {e}")

    def _wire_args(self, pod: Pod, nodes: Sequence[Node]) -> dict:
        """ExtenderArgs{Pod, Nodes|NodeNames} — shared by filter and
        prioritize so the wire shape can't diverge between verbs."""
        args: dict = {"Pod": _pod_json(pod)}
        if self.cfg.node_cache_capable:
            args["NodeNames"] = [n.name for n in nodes]
            args["Nodes"] = None
        else:
            args["NodeNames"] = None
            args["Nodes"] = {"items": [_node_json(n) for n in nodes]}
        return args

    # -- extender.go:273-341 ------------------------------------------------
    def filter(
        self, pod: Pod, nodes: Sequence[Node]
    ) -> Tuple[List[Node], Dict[str, str]]:
        """Returns (still-feasible nodes, failed node -> message). Raises
        ExtenderError on transport/extender errors (caller applies the
        ignorable policy)."""
        if not self.cfg.filter_verb:
            return list(nodes), {}
        by_name = {n.name: n for n in nodes}
        result = self._send(self.cfg.filter_verb, self._wire_args(pod, nodes))
        if result.get("Error"):
            raise ExtenderError(
                f"extender {self.base}: {result['Error']}"
            )
        out: List[Node] = []
        if self.cfg.node_cache_capable and result.get("NodeNames") is not None:
            for name in result["NodeNames"]:
                node = by_name.get(name)
                if node is None:
                    raise ExtenderError(
                        f"extender {self.base} claims a filtered node "
                        f"{name!r} which is not in the input node list"
                    )
                out.append(node)
        elif result.get("Nodes") is not None:
            for item in result["Nodes"].get("items") or []:
                name = (item.get("metadata") or {}).get("name", "")
                node = by_name.get(name)
                if node is not None:
                    out.append(node)
        failed = {
            str(k): str(v)
            for k, v in (result.get("FailedNodes") or {}).items()
        }
        return out, failed

    # -- extender.go:343-381 ------------------------------------------------
    def prioritize(
        self, pod: Pod, nodes: Sequence[Node]
    ) -> Dict[str, float]:
        """host -> score*weight (HostPriorityList entries are 0..10; the
        caller scales the combined sum by EXTENDER_SCORE_SCALE)."""
        if not self.cfg.prioritize_verb:
            return {}
        result = self._send(self.cfg.prioritize_verb, self._wire_args(pod, nodes))
        out: Dict[str, float] = {}
        entries = result if isinstance(result, list) else []
        for item in entries:
            if isinstance(item, dict):
                out[str(item.get("Host", ""))] = (
                    float(item.get("Score", 0)) * float(self.cfg.weight)
                )
        return out


def build_extenders(
    configs: Optional[Sequence[ExtenderConfig]],
) -> List[HTTPExtender]:
    exts = [HTTPExtender(c) for c in (configs or [])]
    for e in exts:
        if e.cfg.preempt_verb or e.cfg.bind_verb:
            log.warning(
                "extender %s: preemptVerb/bindVerb are accepted but inert "
                "(simon disables DefaultBinder; the engine's preemption pass "
                "has no extender hook)", e.base,
            )
    # The reference moves ignorable extenders to the tail of the chain
    # (factory.go:111-113) so a non-ignorable extender's error aborts the pod
    # before any ignorable one runs; failedNodes first-wins attribution
    # follows the same order.
    return [e for e in exts if not e.is_ignorable] + [
        e for e in exts if e.is_ignorable
    ]
