"""The Simon config CR (apiVersion simon/v1alpha1, kind Config).

Parity: `/root/reference/pkg/api/v1alpha1/types.go` and the validation in
`pkg/apply/apply.go:62-74,269-306`. Paths are resolved relative to the config
file's directory when not absolute (the reference resolves relative to CWD;
we accept both, preferring an existing CWD-relative path for compatibility).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List

import yaml


@dataclass
class AppInConfig:
    name: str
    path: str
    chart: bool = False


@dataclass
class SimonConfig:
    name: str = ""
    custom_config: str = ""     # directory of cluster manifests
    kube_config: str = ""       # kubeconfig of a real cluster
    app_list: List[AppInConfig] = field(default_factory=list)
    new_node: str = ""          # directory/file with the candidate node

    @staticmethod
    def load(path: str) -> "SimonConfig":
        with open(path, "r") as fh:
            doc = yaml.safe_load(fh)
        if not isinstance(doc, dict):
            raise ValueError(f"invalid simon config: {path}")
        api_version = doc.get("apiVersion", "")
        kind = doc.get("kind", "")
        if kind != "Config" or not api_version.startswith("simon/"):
            raise ValueError(
                f"invalid simon config {path}: want kind Config, apiVersion simon/v1alpha1, "
                f"got {kind}/{api_version}"
            )
        spec = doc.get("spec") or {}
        cluster = spec.get("cluster") or {}
        base = os.path.dirname(os.path.abspath(path))

        def resolve(p: str) -> str:
            if not p or os.path.isabs(p) or os.path.exists(p):
                return p
            candidate = os.path.join(base, p)
            return candidate if os.path.exists(candidate) else p

        cfg = SimonConfig(
            name=(doc.get("metadata") or {}).get("name", ""),
            custom_config=resolve(cluster.get("customConfig", "") or ""),
            kube_config=resolve(cluster.get("kubeConfig", "") or ""),
            app_list=[
                AppInConfig(
                    name=a.get("name", f"app-{i}"),
                    path=resolve(a.get("path", "")),
                    chart=bool(a.get("chart")),
                )
                for i, a in enumerate(spec.get("appList") or [])
            ],
            new_node=resolve(spec.get("newNode", "") or ""),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        """apply.go:269-306 parity: exactly one cluster source; paths exist."""
        if bool(self.custom_config) == bool(self.kube_config):
            raise ValueError(
                "simon config: exactly one of spec.cluster.customConfig / "
                "spec.cluster.kubeConfig must be set"
            )
        if self.custom_config and not os.path.exists(self.custom_config):
            raise ValueError(f"cluster customConfig path not found: {self.custom_config}")
        for app in self.app_list:
            if not app.path or not os.path.exists(app.path):
                raise ValueError(f"app {app.name}: path not found: {app.path}")
        if self.new_node and not os.path.exists(self.new_node):
            raise ValueError(f"newNode path not found: {self.new_node}")
