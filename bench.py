"""Headline benchmark: the BASELINE.json north-star config.

Capacity-plans a 100k-pod workload onto a 10k-node simulated cluster — the
full pod×node Filter/Score/Select sweep with sequential commit — on one TPU
chip, and reports scheduling throughput.

Baseline: the reference publishes no numbers (BASELINE.md); the driver-defined
target is 100k pods onto 10k nodes in <60s on a v5e-8, i.e. 1667 pods/s.
vs_baseline is throughput relative to that target (>1.0 beats it).

Output: one JSON line, e.g.
  {"metric": "schedule_100k_pods_10k_nodes", "value": 2560.0,
   "unit": "pods/s", "vs_baseline": 1.54, ...}
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

TARGET_PODS_PER_SEC = 100_000 / 60.0  # driver north star


def _probe_backend(platform: str, timeout_s: float) -> tuple[bool, str]:
    """Check in a child process (bounded, killable) that `platform` can
    actually initialize. The TPU tunnel ("axon") is known to hang during
    backend init (round-1 BENCH was rc:1, MULTICHIP hung to rc:124), and a
    hung in-process init cannot be interrupted — hence the subprocess."""
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
    else:
        env.pop("JAX_PLATFORMS", None)
    # the image's site hook overrides the env var after import; config.update
    # is authoritative (utils/platform.ensure_platform)
    select = (
        f"import jax; jax.config.update('jax_platforms', {platform!r}); "
        if platform
        else "import jax; "
    )
    code = (
        select + "d = jax.devices(); "
        "import jax.numpy as jnp; jnp.zeros(8).block_until_ready(); "
        "print(d[0].platform, len(d))"
    )
    # Deterministic backend-hang injection (CI watchdog smoke): stall the
    # non-CPU probe exactly the way the wedged tunnel does, so the deadline
    # path is exercised end to end. The CPU fallback probe is never stalled —
    # the injection models a dead tunnel, not a dead host.
    try:
        hang_s = float(os.environ.get("OSIM_FAULT_BACKEND_HANG_S", "0") or 0)
    except ValueError:
        hang_s = 0.0
    if hang_s > 0 and platform != "cpu":
        code = f"import time; time.sleep({hang_s}); " + code
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            env=env, timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init timed out after {timeout_s:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return False, (tail[-1] if tail else f"rc={r.returncode}")
    return True, r.stdout.strip()


def _watchdog_fired_total() -> int:
    """Total osim_watchdog_fired_total across stages (this process)."""
    from open_simulator_tpu.utils.metrics import WATCHDOG_FIRED

    return int(
        sum(s["value"] for s in WATCHDOG_FIRED.snapshot()["samples"])
    )


def _select_backend(
    attempts: int = 2, timeout_s: float | None = None, journal=None
) -> dict:
    """Pick a working JAX platform before importing jax in this process.

    Tries the environment's preset platform (the TPU tunnel) with bounded
    retries under the OSIM_BACKEND_DEADLINE_S deadline (default 60 s here);
    a probe timeout counts as a fired watchdog. On failure falls back to
    CPU, clearly labeled as TOP-LEVEL fallback/fallback_reason fields in
    the output and journaled when a run journal is active."""
    if timeout_s is None:
        try:
            timeout_s = float(
                os.environ.get("OSIM_BACKEND_DEADLINE_S", "60") or 60
            )
        except ValueError:
            timeout_s = 60.0
    preset = os.environ.get("JAX_PLATFORMS", "")
    info = {"requested_platform": preset or "(default)"}
    last_err = ""
    for attempt in range(attempts):
        ok, msg = _probe_backend(preset, timeout_s)
        if ok:
            info["backend_probe"] = msg
            if journal is not None:
                journal.append("backend", **info)
            return info
        last_err = msg
        if "timed out" in msg:
            from open_simulator_tpu.utils.metrics import WATCHDOG_FIRED

            WATCHDOG_FIRED.inc(stage="backend-acquire")
        if attempt + 1 < attempts:
            if journal is not None:
                journal.append("backend_retry", error=msg)
            time.sleep(2.0 * (attempt + 1))
    os.environ["JAX_PLATFORMS"] = "cpu"
    info["fallback"] = "cpu"
    info["fallback_reason"] = last_err
    if journal is not None:
        journal.append("backend_fallback", **info)
    return info


def build_state(n_nodes: int, n_pods: int):
    from open_simulator_tpu.core.objects import Node, Pod
    from open_simulator_tpu.ops.encode import (
        Encoder,
        encode_nodes,
        encode_pods,
        initial_selector_counts,
    )
    from open_simulator_tpu.ops.state import (
        carry_from_table,
        node_static_from_table,
    )
    from open_simulator_tpu.ops.tile import tile_pod_batch

    rng = np.random.default_rng(0)
    nodes = []
    for i in range(n_nodes):
        taints = (
            [{"key": "dedicated", "value": "batch", "effect": "NoSchedule"}]
            if i % 10 == 0
            else []
        )
        nodes.append(
            Node.from_dict(
                {
                    "metadata": {
                        "name": f"node-{i}",
                        "labels": {
                            "kubernetes.io/hostname": f"node-{i}",
                            "topology.kubernetes.io/zone": f"az-{i % 3}",
                            "node.kubernetes.io/instance-type": ["m5.4x", "m5.8x", "c5.9x"][i % 3],
                        },
                    },
                    "spec": {"taints": taints},
                    "status": {
                        "allocatable": {
                            "cpu": str(16 + 16 * int(rng.integers(0, 3))),
                            "memory": f"{32 + 32 * int(rng.integers(0, 3))}Gi",
                            "pods": "110",
                        }
                    },
                }
            )
        )

    # Workload templates: a service with zone spread, a tolerating batch job,
    # a selector-pinned cache, a plain web tier.
    templates = []
    tmpl_specs = [
        dict(
            cpu="500m", mem="512Mi", labels={"app": "web"},
            spread=True, tol=False, sel=None,
        ),
        dict(
            cpu="2", mem="4Gi", labels={"app": "batch"},
            spread=False, tol=True, sel=None,
        ),
        dict(
            cpu="1", mem="2Gi", labels={"app": "cache"},
            spread=False, tol=False, sel={"node.kubernetes.io/instance-type": "m5.8x"},
        ),
        dict(
            cpu="250m", mem="256Mi", labels={"app": "sidecar"},
            spread=True, tol=False, sel=None,
        ),
    ]
    for t, s in enumerate(tmpl_specs):
        spec = {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": s["cpu"], "memory": s["mem"]}}}
            ]
        }
        if s["spread"]:
            spec["topologySpreadConstraints"] = [
                {
                    "maxSkew": 50,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "ScheduleAnyway",
                    "labelSelector": {"matchLabels": s["labels"]},
                }
            ]
        if s["tol"]:
            spec["tolerations"] = [
                {"key": "dedicated", "operator": "Equal", "value": "batch",
                 "effect": "NoSchedule"}
            ]
        if s["sel"]:
            spec["nodeSelector"] = s["sel"]
        templates.append(
            Pod.from_dict(
                {
                    "metadata": {
                        "name": f"tpl-{t}", "namespace": "bench", "labels": s["labels"],
                    },
                    "spec": spec,
                }
            )
        )

    share = n_pods // len(templates)
    counts = [share] * len(templates)
    counts[0] += n_pods - share * len(templates)

    enc = Encoder()
    enc.register_pods(templates)
    table = encode_nodes(enc, nodes)
    tmpl_batch = encode_pods(enc, templates)
    batch = tile_pod_batch(tmpl_batch, counts)
    ns = node_static_from_table(enc, table)
    carry = carry_from_table(table, initial_selector_counts(enc, table, []))
    return ns, carry, batch


# ---------------------------------------------------------------------------
# The five BASELINE.json configs, driven END-TO-END through the product
# engine (simulate()/plan_capacity — workload expansion, validation, encode,
# compile and decode all included in the reported wall).
# ---------------------------------------------------------------------------

def _mk_node(name, cpu, mem, pods="110", labels=None, capacity_extra=None):
    from open_simulator_tpu.core.objects import Node

    res = {"cpu": cpu, "memory": mem, "pods": pods}
    if capacity_extra:
        res.update(capacity_extra)
    return Node.from_dict(
        {
            "metadata": {
                "name": name,
                "labels": {"kubernetes.io/hostname": name, **(labels or {})},
            },
            "status": {"allocatable": dict(res), "capacity": dict(res)},
        }
    )


def _mk_deploy(name, replicas, cpu, mem, labels=None, spec_extra=None, anno=None):
    spec = {
        "containers": [
            {"name": "c", "image": "img",
             "resources": {"requests": {"cpu": cpu, "memory": mem}}}
        ]
    }
    spec.update(spec_extra or {})
    return {
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": "bench"},
        "spec": {
            "replicas": replicas,
            "template": {
                "metadata": {
                    "labels": {"app": name, **(labels or {})},
                    "annotations": anno or {},
                },
                "spec": spec,
            },
        },
    }


def config_stock():
    """Config 1: the stock quickstart sample (cluster + 5 apps incl. a chart
    + the add-node capacity search), through the full Applier. Uses the
    first-party example/ tree; falls back to the reference's demo_1 only
    when example/ is missing from the checkout."""
    import io

    from open_simulator_tpu.api.config import AppInConfig, SimonConfig
    from open_simulator_tpu.engine.apply import run_apply

    here = os.path.join(os.path.dirname(os.path.abspath(__file__)), "example")
    if os.path.isdir(os.path.join(here, "cluster", "demo")):
        cfg = SimonConfig.load(os.path.join(here, "simon-config.yaml"))
    else:
        ref = "/root/reference/example"
        cfg = SimonConfig(
            custom_config=f"{ref}/cluster/demo_1",
            new_node=f"{ref}/newnode/demo_1",
            app_list=[
                AppInConfig(
                    name="yoda", path=f"{ref}/application/charts/yoda", chart=True
                ),
                AppInConfig(name="simple", path=f"{ref}/application/simple"),
                AppInConfig(name="complicated", path=f"{ref}/application/complicate"),
                AppInConfig(name="open_local", path=f"{ref}/application/open_local"),
                AppInConfig(name="more_pods", path=f"{ref}/application/more_pods"),
            ],
        )
    t0 = time.time()
    outcome = run_apply(cfg, out=io.StringIO())
    wall = time.time() - t0
    added = outcome.plan.nodes_added if outcome.plan else 0
    return {
        "wall_s": round(wall, 2),
        "nodes_added": added,
        "unscheduled": len(outcome.result.unscheduled),
    }


def _simulate_config(nodes, deploys):
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        simulate,
    )

    t0 = time.time()
    result = simulate(
        ClusterResource(nodes=nodes),
        [AppResource(name="bench", objects=deploys)],
    )
    wall = time.time() - t0
    placed = sum(len(st.pods) for st in result.node_status)
    return wall, placed, len(result.unscheduled)


def config_fit(n_pods=1_000, n_nodes=100):
    """Config 2: NodeResourcesFit-only bin-packing, 1k pods x 100 nodes."""
    nodes = [_mk_node(f"n-{i}", "32", "64Gi") for i in range(n_nodes)]
    deploys = [
        _mk_deploy("web", n_pods // 2, "500m", "1Gi"),
        _mk_deploy("api", n_pods - n_pods // 2, "1", "2Gi"),
    ]
    wall, placed, unsched = _simulate_config(nodes, deploys)
    return {
        "wall_s": round(wall, 2),
        "value": round(n_pods / wall, 1),
        "scheduled": placed,
        "unscheduled": unsched,
    }


def config_spread_affinity(n_pods=10_000, n_nodes=1_000):
    """Config 3: PodTopologySpread + InterPodAffinity, 10k pods x 1k nodes
    across 3 zones."""
    nodes = [
        _mk_node(
            f"n-{i}", "32", "64Gi",
            labels={"topology.kubernetes.io/zone": f"az-{i % 3}"},
        )
        for i in range(n_nodes)
    ]
    spread = {
        "topologySpreadConstraints": [
            {
                "maxSkew": 50,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": "front"}},
            }
        ]
    }
    affinity = {
        "affinity": {
            "podAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": 10,
                        "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": "front"}},
                            "topologyKey": "topology.kubernetes.io/zone",
                        },
                    }
                ]
            }
        }
    }
    deploys = [
        _mk_deploy("front", n_pods // 2, "250m", "512Mi", spec_extra=spread),
        _mk_deploy("back", n_pods - n_pods // 2, "500m", "1Gi",
                   spec_extra=affinity),
    ]
    wall, placed, unsched = _simulate_config(nodes, deploys)
    return {
        "wall_s": round(wall, 2),
        "value": round(n_pods / wall, 1),
        "scheduled": placed,
        "unscheduled": unsched,
    }


def config_gpushare(n_pods=5_000, n_nodes=320):
    """Config 4: the gpushare example shape scaled to 5k GPU pods (8x16GiB
    devices per node, mixed 4/8 GiB share requests)."""
    gpu_extra = {
        "alibabacloud.com/gpu-count": "8",
        "alibabacloud.com/gpu-mem": "128Gi",
    }
    nodes = [
        _mk_node(f"g-{i}", "64", "256Gi", capacity_extra=gpu_extra)
        for i in range(n_nodes)
    ]
    deploys = [
        _mk_deploy(
            "train", n_pods // 2, "2", "8Gi",
            anno={"alibabacloud.com/gpu-mem": "8Gi",
                  "alibabacloud.com/gpu-count": "1"},
        ),
        _mk_deploy(
            "infer", n_pods - n_pods // 2, "1", "4Gi",
            anno={"alibabacloud.com/gpu-mem": "4Gi",
                  "alibabacloud.com/gpu-count": "1"},
        ),
    ]
    wall, placed, unsched = _simulate_config(nodes, deploys)
    return {
        "wall_s": round(wall, 2),
        "value": round(n_pods / wall, 1),
        "scheduled": placed,
        "unscheduled": unsched,
    }


def config_plan(n_pods=100_000, n_nodes=10_000):
    """Config 5 — the north star: full capacity plan, 100k pods onto a
    10k-node cluster sized so the workload overflows and the add-node search
    must run. Wall includes workload expansion, validation, encode, all
    probe simulations and every compile."""
    from open_simulator_tpu.engine.capacity import plan_capacity
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
    )

    # Sized so the workload genuinely overflows (~37.5k cpu demand vs ~30k
    # capacity at full scale) and the add-node search must bracket + bisect.
    nodes = [
        _mk_node(
            f"n-{i}", "3", "6Gi",
            labels={"topology.kubernetes.io/zone": f"az-{i % 3}"},
        )
        for i in range(n_nodes)
    ]
    spread = {
        "topologySpreadConstraints": [
            {
                "maxSkew": 50,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "ScheduleAnyway",
                "labelSelector": {"matchLabels": {"app": "web"}},
            }
        ]
    }
    deploys = [
        _mk_deploy("web", n_pods // 2, "500m", "1Gi", spec_extra=spread),
        _mk_deploy("batch", n_pods - n_pods // 2, "250m", "512Mi"),
    ]
    template = _mk_node("new-node", "32", "64Gi")
    cluster = ClusterResource(nodes=nodes)
    apps = [AppResource(name="bench", objects=deploys)]
    t0 = time.time()
    plan = plan_capacity(cluster, apps, template)
    wall = time.time() - t0
    return {
        "wall_s": round(wall, 2),
        "value": round(n_pods / wall, 1),
        "nodes_added": plan.nodes_added if plan else -1,
        "attempts": plan.attempts if plan else 0,
        "under_60s": wall < 60.0,
    }


def config_capacity_sweep(n_base=2, n_replicas=48):
    """Config: serial-vs-batched capacity search on the same fixture, same
    process. Required pod anti-affinity on hostname makes the demand-based
    lower bound useless (estimate ~1 node, true answer ~replicas-base), so
    the serial path must walk the full exponential bracket + bisection
    (>=8 probe simulations) while the batched path (plan_capacity
    sweep_mode=batched, docs/batching.md) closes the same bracket in <=3
    vmapped device calls. `capacity_sweep_speedup` is the recorded
    serial/batched wall-clock ratio."""
    from open_simulator_tpu.engine.capacity import plan_capacity
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
    )

    anti = {
        "affinity": {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"app": "lonely"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
    }

    def fixture():
        nodes = [_mk_node(f"n-{i}", "32", "64Gi") for i in range(n_base)]
        deploys = [_mk_deploy("lonely", n_replicas, "500m", "1Gi",
                              spec_extra=anti)]
        cluster = ClusterResource(nodes=nodes)
        apps = [AppResource(name="bench", objects=deploys)]
        template = _mk_node("new-node", "32", "64Gi")
        return cluster, apps, template

    def one(mode):
        from open_simulator_tpu.core.workloads import reset_name_rng

        reset_name_rng()  # identical pod names => comparable searches
        cluster, apps, template = fixture()
        t0 = time.time()
        plan = plan_capacity(cluster, apps, template, sweep_mode=mode)
        return time.time() - t0, plan

    serial_wall, serial_plan = one("serial")
    batched_wall, batched_plan = one("batched")
    out = {
        "serial_wall_s": round(serial_wall, 2),
        "batched_wall_s": round(batched_wall, 2),
        "capacity_sweep_speedup": round(serial_wall / batched_wall, 2),
        "serial_probes": serial_plan.attempts if serial_plan else -1,
        "batched_calls": batched_plan.batched_calls if batched_plan else -1,
        "nodes_added": batched_plan.nodes_added if batched_plan else -1,
        "wall_s": round(serial_wall + batched_wall, 2),
    }
    if (serial_plan is None) != (batched_plan is None) or (
        serial_plan is not None
        and serial_plan.nodes_added != batched_plan.nodes_added
    ):
        out["error"] = (
            f"serial/batched disagree: "
            f"{serial_plan and serial_plan.nodes_added} vs "
            f"{batched_plan and batched_plan.nodes_added}"
        )
    return out


def config_multi_scenario(n_scenarios=64, n_nodes=64, n_pods=400):
    """Config: one simulate_batch() call sweeping 64 what-if node-count
    scenarios of a 400-pod workload — the scenario axis rides a single
    vmapped program (docs/batching.md), so the sweep costs one compile and
    one (bucketed) device call instead of 64 serial simulations.
    `scenarios_per_second` sits next to `pods_per_second`: the former is
    the sweep's own throughput, the latter counts every lane's pods."""
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        Scenario,
        simulate_batch,
    )

    nodes = [_mk_node(f"n-{i}", "16", "32Gi") for i in range(n_nodes)]
    deploys = [_mk_deploy("web", n_pods, "500m", "1Gi")]
    cluster = ClusterResource(nodes=nodes)
    apps = [AppResource(name="bench", objects=deploys)]
    # node counts cycle over the top half so every lane keeps a distinct
    # prefix of the cluster but all lanes share one padded node tensor
    scenarios = [
        Scenario(
            name=f"s-{i}",
            node_count=n_nodes // 2 + (i % (n_nodes // 2 + 1)),
        )
        for i in range(n_scenarios)
    ]
    t0 = time.time()
    results = simulate_batch(cluster, apps, scenarios)
    wall = time.time() - t0
    placed = sum(len(st.pods) for r in results for st in r.node_status)
    return {
        "wall_s": round(wall, 2),
        "scenarios": n_scenarios,
        "scenarios_per_second": round(n_scenarios / wall, 2),
        "pods_per_second": round(n_scenarios * n_pods / wall, 1),
        "scheduled": placed,
        "unscheduled": sum(len(r.unscheduled) for r in results),
    }


def config_warm_start():
    """Config: the compile-lifecycle headline. Cold leg: `simon warmup`'s
    engine — AOT-compile every audited jit entry at canonical shapes plus
    the capacity-sweep rehearsal, banking the persistent compile cache;
    ALL compile time lives here. Warm leg: the identical full capacity
    sweep re-run against warm caches under CompileCounter, demanding ZERO
    cold compiles — so the warm wall-clock excludes compile time by
    construction (a counted invariant), not by subtraction."""
    from open_simulator_tpu.analysis.jaxpr_audit import _run_sweeps
    from open_simulator_tpu.engine.warmup import run_warmup
    from open_simulator_tpu.ops.fast import reset_scenario_programs
    from open_simulator_tpu.utils.platform import CompileCounter

    t0 = time.time()
    report = run_warmup()
    cold_s = time.time() - t0
    reset_scenario_programs()
    t1 = time.time()
    with CompileCounter() as counter:
        plan, plan_b = _run_sweeps()
    warm_s = time.time() - t1
    out = {
        "wall_s": round(warm_s, 2),
        "cold_wall_s": round(cold_s, 2),
        "warm_wall_s": round(warm_s, 2),
        "warm_speedup": round(cold_s / warm_s, 1) if warm_s > 0 else None,
        "warmup_entries": len(report.entries),
        "warmup_cold_compiles": report.cold_compiles,
        "warm_backend_compiles": counter.backend_compiles,
        "warm_persistent_hits": counter.persistent_hits,
        "warm_cold_compiles": counter.cold_compiles,
        "nodes_added": plan.nodes_added,
        "batched_nodes_added": plan_b.nodes_added,
        "cache_dir": report.cache_dir,
    }
    if not report.ok:
        out["error"] = f"warmup missed audited entries: {report.missing}"
    elif counter.cold_compiles != 0:
        out["error"] = (
            f"warm leg paid {counter.cold_compiles} cold compile(s); "
            "warm start must exclude all compile time"
        )
    elif plan.nodes_added != plan_b.nodes_added:
        out["error"] = (
            f"serial/batched sweep answers diverged: "
            f"{plan.nodes_added} vs {plan_b.nodes_added}"
        )
    return out


def config_sharded_smoke(n_scenarios=8, n_nodes=24, n_pods=120):
    """Config: scenario-axis sharding equivalence. The same what-if sweep
    runs unsharded and sharded across a 2-device mesh (scenario lanes split
    over devices, node tensors replicated — parallel/mesh.shard_scenarios);
    per-lane placements and unscheduled reasons must be byte-identical.
    _run_segment provisions the 2 virtual CPU devices for this segment via
    --xla_force_host_platform_device_count, so it runs in every CI lane."""
    import jax

    from open_simulator_tpu.core.workloads import reset_name_rng
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        Scenario,
        simulate_batch,
    )
    from open_simulator_tpu.parallel.mesh import product_mesh

    ndev = len(jax.devices())
    if ndev < 2:
        return {"error": f"sharded smoke needs >=2 devices, have {ndev}"}

    def _digest(r) -> str:
        doc = {
            "placements": {
                st.node.name: sorted(p.key for p in st.pods)
                for st in r.node_status
            },
            "unscheduled": sorted(
                (u.pod.key, u.reason) for u in r.unscheduled
            ),
        }
        return json.dumps(doc, sort_keys=True)

    nodes = [_mk_node(f"n-{i}", "8", "16Gi") for i in range(n_nodes)]
    cluster = ClusterResource(nodes=nodes)
    apps = [AppResource(
        name="bench", objects=[_mk_deploy("web", n_pods, "500m", "1Gi")]
    )]
    scenarios = [
        Scenario(name=f"s-{i}", node_count=n_nodes // 2 + i)
        for i in range(n_scenarios)
    ]
    reset_name_rng()
    t0 = time.time()
    base = simulate_batch(cluster, apps, scenarios)
    unsharded_s = time.time() - t0
    mesh = product_mesh(2)
    reset_name_rng()
    t1 = time.time()
    sharded = simulate_batch(cluster, apps, scenarios, mesh=mesh)
    sharded_s = time.time() - t1
    mismatches = [
        sc.name
        for sc, a, b in zip(scenarios, base, sharded)
        if _digest(a) != _digest(b)
    ]
    out = {
        "wall_s": round(sharded_s, 2),
        "unsharded_wall_s": round(unsharded_s, 2),
        "sharded_wall_s": round(sharded_s, 2),
        "devices": ndev,
        "scenarios": n_scenarios,
        "lanes_identical": not mismatches,
    }
    if mismatches:
        out["error"] = f"sharded lanes diverged: {mismatches}"
    return out


def config_preempt(n_nodes=60, n_low=400, n_high=100):
    """Config 6: priority-tiered preemption. A low-priority tier fills the
    cluster (400 x 1cpu on 60 x 8cpu = 80 cpu headroom), then a
    high-priority tier (100 x 2cpu, priority 100) arrives: ~40 pods fit in
    the headroom and the rest must evict low-priority victims through the
    lane-parallel batched probe path (engine/preemption.try_preempt with
    fits_many_fn). Measures the cost the reference pays in
    selectVictimsOnNode's per-node filter dry runs
    (default_preemption.go:578-626)."""
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        simulate,
    )

    def one_run():
        nodes = [_mk_node(f"n-{i}", "8", "32Gi") for i in range(n_nodes)]
        low = _mk_deploy("low-tier", n_low, "1", "1Gi")
        high = _mk_deploy(
            "high-tier", n_high, "2", "1Gi", spec_extra={"priority": 100}
        )
        t0 = time.time()
        result = simulate(
            ClusterResource(nodes=nodes),
            [AppResource(name="bench", objects=[low, high])],
        )
        return time.time() - t0, result

    n_pods = n_low + n_high
    # Cold: compiles dominate (every probe lane-bucket shape traces its own
    # vmapped run_filters). Warm: a second identical run in the same process
    # reuses every executable — the steady state a server-mode or capacity-
    # search caller sees, and what the persistent XLA cache gives a fresh
    # process. The reference pays neither (plain Go calls) but its per-probe
    # cost is a full filter dry run per candidate node
    # (default_preemption.go:578-626).
    cold_wall, cold_res = one_run()
    warm_wall, result = one_run()
    placed = sum(len(st.pods) for st in result.node_status)
    assert len(result.preempted) == len(cold_res.preempted)
    return {
        "wall_s": round(warm_wall, 2),
        "value": round(n_pods / warm_wall, 1),
        "cold_wall_s": round(cold_wall, 2),
        "cold_value": round(n_pods / cold_wall, 1),
        "scheduled": placed,
        "unscheduled": len(result.unscheduled),
        "preempted": len(result.preempted),
    }


def config_extender(n_pods=1_000, n_nodes=100):
    """Config 7: the extender tax, wave vs serial. A local pass-through HTTP
    extender (filter + prioritize, interested in every pod) forces all 1k
    pods down the extender path — the cost the reference pays in
    findNodesThatPassExtenders/prioritizeNodes per scheduling cycle
    (core/extender.go:273-381). Two legs against the same in-process mock:
    the wave pipeline (engine/extender_wave.py, default) and a
    `legacy_serial` baseline (OSIM_EXTENDER_WAVE=0 + OSIM_EXTENDER_KEEPALIVE=0
    — the pre-wave engine transport included: per-pod probe→HTTP→commit on a
    fresh urllib connection per request). Placement multisets must match
    exactly (the tentpole's byte-identity contract) and the wave leg's
    schedule-extenders span must beat serial by the `speedup_x >= 3`
    acceptance bar (errors below it — CI enforces)."""
    import socket
    import threading

    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        simulate,
    )
    from open_simulator_tpu.models.profiles import ExtenderConfig
    from open_simulator_tpu.utils import httppool, metrics

    class _LeanExtender:
        """Raw-socket HTTP/1.1 pass-through extender: keep-alive like a real
        (Go net/http) backend, thread per connection, TCP_NODELAY both ways.
        The mock's server-side Python is GIL-bound work the client cannot
        overlap, so it is kept lean (no BaseHTTPRequestHandler, responses
        cached by node set) and each request charges HANDLER_LATENCY_S of
        GIL-free handler time — a generously fast real extender. A
        zero-latency in-process mock measures only serialized client-side
        Python, which no concurrency can compress; the latency is what any
        out-of-process backend actually exhibits and is identical for both
        legs."""

        HANDLER_LATENCY_S = 0.0005

        def __init__(self):
            self.sock = socket.create_server(("127.0.0.1", 0), backlog=128)
            self.port = self.sock.getsockname()[1]
            threading.Thread(target=self._accept, daemon=True).start()

        def _accept(self):
            while True:
                try:
                    conn, _ = self.sock.accept()
                except OSError:
                    return  # closed
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                threading.Thread(
                    target=self._serve, args=(conn,), daemon=True
                ).start()

        _resp_cache: dict = {}

        def _serve(self, conn):
            f = conn.makefile("rb")
            try:
                while True:
                    line = f.readline()
                    if not line:
                        return
                    path = line.split()[1]
                    length, close = 0, False
                    while True:
                        h = f.readline()
                        if h in (b"\r\n", b"\n", b""):
                            break
                        k, _, v = h.partition(b":")
                        k = k.lower()
                        if k == b"content-length":
                            length = int(v)
                        elif k == b"connection" and b"close" in v.lower():
                            close = True  # urllib's fresh-connection mode
                    body = json.loads(f.read(length) or b"{}")
                    names = body.get("NodeNames") or []
                    key = (path.endswith(b"/filter"), tuple(names))
                    data = self._resp_cache.get(key)
                    if data is None:
                        if key[0]:
                            resp = {
                                "NodeNames": names, "FailedNodes": {},
                                "Error": "",
                            }
                        else:
                            resp = [{"Host": n, "Score": 5} for n in names]
                        payload = json.dumps(resp).encode()
                        data = self._resp_cache[key] = (
                            b"HTTP/1.1 200 OK\r\n"
                            b"Content-Type: application/json\r\n"
                            b"Content-Length: %d\r\n\r\n" % len(payload)
                            + payload
                        )
                    time.sleep(self.HANDLER_LATENCY_S)
                    conn.sendall(data)
                    if close:
                        return
            except (OSError, ValueError, IndexError):
                pass
            finally:
                try:
                    f.close()
                except OSError:
                    pass
                conn.close()

        def close(self):
            try:
                self.sock.close()
            except OSError:
                pass

    httpd = _LeanExtender()

    def span_sum():
        _, s, _ = metrics.SPAN_DURATION.child_state(span="schedule-extenders")
        return s

    def leg(wave_env: str, keepalive_env: str):
        """One mode, warm-measured: a cold pass pays the jit compiles, a
        second pass is timed (wall + the schedule-extenders span delta)."""
        prev = {
            k: os.environ.get(k)
            for k in ("OSIM_EXTENDER_WAVE", "OSIM_EXTENDER_KEEPALIVE")
        }
        os.environ["OSIM_EXTENDER_WAVE"] = wave_env
        os.environ["OSIM_EXTENDER_KEEPALIVE"] = keepalive_env
        try:
            cfg = ExtenderConfig(
                url_prefix=f"http://127.0.0.1:{httpd.port}",
                filter_verb="filter",
                prioritize_verb="prioritize",
                node_cache_capable=True,  # NodeNames wire: dispatch cost only
            )
            apps = [
                AppResource(
                    name="bench",
                    objects=[_mk_deploy("ext-load", n_pods, "500m", "256Mi")],
                )
            ]

            def one():
                nodes = [
                    _mk_node(f"n-{i}", "16", "64Gi") for i in range(n_nodes)
                ]
                t0 = time.time()
                res = simulate(
                    ClusterResource(nodes=nodes), apps, extenders=[cfg]
                )
                return time.time() - t0, res
            cold_wall, _ = one()
            s0 = span_sum()
            warm_wall, result = one()
            span_s = span_sum() - s0
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            httppool.reset_pools()  # no warm sockets leak across legs
        placements = sorted(
            (
                p.meta.annotations.get("simon/workload-name", ""),
                st.node.name,
            )
            for st in result.node_status
            for p in st.pods
        )
        return {
            "wall_s": round(warm_wall, 2),
            "cold_wall_s": round(cold_wall, 2),
            "span_s": round(span_s, 3),
            "value": round(n_pods / warm_wall, 1),
            "scheduled": len(placements),
            "unscheduled": len(result.unscheduled),
        }, placements

    try:
        wave, wave_placed = leg("", "1")       # default: wave pipeline on
        serial, serial_placed = leg("0", "0")  # pre-wave engine + transport
    finally:
        httpd.close()
        httppool.reset_pools()
    speedup = (
        round(serial["span_s"] / wave["span_s"], 2) if wave["span_s"] else 0.0
    )
    out = {
        **wave,
        "legacy_serial": serial,
        "speedup_x": speedup,
        "identical_placements": wave_placed == serial_placed,
    }
    if wave_placed != serial_placed:
        out["error"] = (
            "wave placements diverge from legacy serial: byte-identity "
            "contract broken"
        )
    elif speedup < 3.0:
        out["error"] = (
            f"extender wave speedup {speedup}x is below the 3x acceptance "
            f"bar (span {wave['span_s']}s vs serial {serial['span_s']}s)"
        )
    return out


def config_sanitize_overhead(n_pods=1_000, n_nodes=100):
    """Config 8: the OSIM_SANITIZE=1 checkify tax. The same
    NodeResourcesFit sweep as fit_1k_100n, run plain and then sanitized in
    one process — ops/sanitize.py reads the env var per dispatch, so the
    flip needs no re-import. Each mode runs twice and reports its second,
    warm wall (the sanitized mode compiles its own checkify-wrapped
    executables on the first pass); overhead_x is warm-vs-warm."""
    nodes = [_mk_node(f"n-{i}", "32", "64Gi") for i in range(n_nodes)]
    deploys = [
        _mk_deploy("web", n_pods // 2, "500m", "1Gi"),
        _mk_deploy("api", n_pods - n_pods // 2, "1", "2Gi"),
    ]

    def run_mode(flag: str):
        prev = os.environ.get("OSIM_SANITIZE")
        os.environ["OSIM_SANITIZE"] = flag
        try:
            cold, _, _ = _simulate_config(nodes, deploys)
            warm, placed, unsched = _simulate_config(nodes, deploys)
        finally:
            if prev is None:
                os.environ.pop("OSIM_SANITIZE", None)
            else:
                os.environ["OSIM_SANITIZE"] = prev
        return cold, warm, placed, unsched

    p_cold, p_warm, p_placed, p_unsched = run_mode("0")
    s_cold, s_warm, s_placed, s_unsched = run_mode("1")
    out = {
        "wall_s": round(s_warm, 2),
        "value": round(n_pods / s_warm, 1),
        "plain_wall_s": round(p_warm, 2),
        "sanitized_wall_s": round(s_warm, 2),
        "plain_cold_wall_s": round(p_cold, 2),
        "sanitized_cold_wall_s": round(s_cold, 2),
        "overhead_x": round(s_warm / p_warm, 2) if p_warm > 0 else None,
        "scheduled": p_placed,
        "unscheduled": p_unsched,
    }
    if (s_placed, s_unsched) != (p_placed, p_unsched):
        # the sanitizer must be observational — a placement drift is a bug
        out["error"] = (
            f"sanitized run placed {s_placed}/{s_unsched} vs plain "
            f"{p_placed}/{p_unsched}"
        )
    return out


def config_serving_concurrent(
    n_clients=16, n_requests=4, queue_depth=8, coalesce_ms=50.0
):
    """Config 9: the overload-safe serving path (docs/serving.md). M
    concurrent clients burst identical deploy-apps requests at an embedded
    server with a bounded admission queue and a coalescing window; reports
    p50/p99 latency, req/s, shed rate, and the mean coalesced batch size —
    "heavy traffic" as a number. Every response must be definite (200 or a
    shed 429-with-Retry-After); anything else is reported as an error."""
    import threading
    import urllib.error
    import urllib.request

    from open_simulator_tpu.server import server as server_mod
    from open_simulator_tpu.utils import metrics

    def raw_node(name):
        res = {"cpu": "32", "memory": "64Gi", "pods": "110"}
        return {
            "kind": "Node",
            "metadata": {
                "name": name, "labels": {"kubernetes.io/hostname": name},
            },
            "status": {"allocatable": dict(res), "capacity": dict(res)},
        }

    body = json.dumps(
        {
            "cluster": {"objects": [raw_node(f"n-{i}") for i in range(20)]},
            "apps": [
                {
                    "name": "web",
                    "objects": [_mk_deploy("web", 100, "500m", "1Gi")],
                }
            ],
        }
    ).encode()

    srv = server_mod.make_server(
        0, queue_depth=queue_depth, coalesce_ms=coalesce_ms
    )
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{port}/api/deploy-apps"

    def one(timeout=120.0):
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        t0 = time.time()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, time.time() - t0
        except urllib.error.HTTPError as e:
            e.read()
            return e.code, time.time() - t0
        except Exception:
            return -1, time.time() - t0

    # Warm pass: compile the simulate executables before the timed burst so
    # the latency distribution measures serving, not first-compile.
    warm_status, _ = one()
    try:
        if warm_status != 200:
            return {"error": f"warm-up request returned {warm_status}"}
        metrics.REGISTRY.reset()

        outcomes: list = []
        lock = threading.Lock()
        barrier = threading.Barrier(n_clients)

        def client():
            barrier.wait()
            mine = [one() for _ in range(n_requests)]
            with lock:
                outcomes.extend(mine)

        threads = [threading.Thread(target=client) for _ in range(n_clients)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0

        total = len(outcomes)
        ok_lat = sorted(lat for code, lat in outcomes if code == 200)
        shed = sum(1 for code, _ in outcomes if code in (429, 503))
        other = total - len(ok_lat) - shed
        _, co_sum, co_count = metrics.COALESCED_BATCH.child_state(mode="fanout")
        shed_by_reason = {
            s["labels"]["reason"]: int(s["value"])
            for s in metrics.REQUESTS_SHED.snapshot()["samples"]
        }

        def pct(p):
            if not ok_lat:
                return None
            return round(
                1000 * ok_lat[min(len(ok_lat) - 1, int(p * len(ok_lat)))], 1
            )

        out = {
            "wall_s": round(wall, 2),
            "value": round(len(ok_lat) / wall, 1) if wall > 0 else 0.0,
            "unit": "req/s",
            "clients": n_clients,
            "requests": total,
            "ok": len(ok_lat),
            "shed": shed,
            "shed_rate": round(shed / total, 3) if total else 0.0,
            "shed_by_reason": shed_by_reason,
            "p50_latency_ms": pct(0.50),
            "p99_latency_ms": pct(0.99),
            "queue_depth": queue_depth,
            "coalesce_ms": coalesce_ms,
            "coalesced_batch_mean": (
                round(co_sum / co_count, 2) if co_count else 0.0
            ),
        }
        if other:
            # 200 and shed-with-Retry-After are the only acceptable answers
            out["error"] = (
                f"{other} request(s) got a non-200/non-shed response: "
                f"{sorted({c for c, _ in outcomes if c not in (200, 429, 503)})}"
            )
        return out
    finally:
        srv.shutdown()
        srv.server_close()


def config_serving_saturation(
    n_clients=8, n_requests=12, queue_depth=16, n_nodes=10, replicas=12
):
    """Config 11: sustained serving throughput at queue saturation
    (docs/serving.md "continuous batching"). M closed-loop clients — each
    fires its next request the moment the previous answer lands — post
    bodies that differ only in score weights, so every pack is a
    multi-lane batched device call. n_clients defaults to one full
    SCENARIO_BUCKET (8): the pack heuristic dispatches at a full bucket,
    so the steady state is back-to-back full-occupancy device calls. The workload runs twice on the same
    machine: once against the replaced architecture (coalesce-window
    latency floor + cold per-pack dispatch: OSIM_SERVER_LOOP=0 and the
    loop's legacy_floor switch) and once against the continuous-batching
    loop (no floor, warm ScenarioSession packs). Reports sustained req/s
    for both, lane occupancy mean, p50/p99, and the speedup; the
    acceptance bar is speedup_x >= 2, and any non-200 response is an
    error (closed-loop clients never overrun the queue, so zero shed)."""
    import os
    import threading
    import urllib.error
    import urllib.request

    from open_simulator_tpu.server import server as server_mod
    from open_simulator_tpu.utils import metrics

    def raw_node(name):
        res = {"cpu": "32", "memory": "64Gi", "pods": "110"}
        return {
            "kind": "Node",
            "metadata": {
                "name": name, "labels": {"kubernetes.io/hostname": name},
            },
            "status": {"allocatable": dict(res), "capacity": dict(res)},
        }

    base_body = {
        "cluster": {"objects": [raw_node(f"n-{i}") for i in range(n_nodes)]},
        "apps": [
            {
                "name": "web",
                "objects": [_mk_deploy("web", replicas, "500m", "1Gi")],
            }
        ],
    }
    bodies = [
        json.dumps(
            dict(base_body, weights={"least_allocated": 50 + i})
        ).encode()
        for i in range(n_clients)
    ]

    def run_mode(loop_on: bool) -> dict:
        os.environ["OSIM_SERVER_LOOP"] = "1" if loop_on else "0"
        with server_mod._sessions_lock:
            server_mod._sessions.clear()
        srv = server_mod.make_server(
            0, queue_depth=queue_depth, pack_window_ms=50.0
        )
        if not loop_on:
            # faithful baseline: the pre-loop worker waited the window out
            # on EVERY batch, then dispatched cold
            srv.admission._loop.legacy_floor = True
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{port}/api/deploy-apps"

        def one(payload, timeout=120.0):
            req = urllib.request.Request(
                url, data=payload,
                headers={"Content-Type": "application/json"},
            )
            t0 = time.time()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    r.read()
                    return r.status, time.time() - t0
            except urllib.error.HTTPError as e:
                e.read()
                return e.code, time.time() - t0
            except Exception:
                return -1, time.time() - t0

        try:
            # warm pass: compile the batched scenario program (full pack of
            # n_clients lanes) before the timed run
            warm_outcomes: list = []
            warm_lock = threading.Lock()
            warm_barrier = threading.Barrier(n_clients)

            def warm_client(i):
                warm_barrier.wait()
                res = one(bodies[i])
                with warm_lock:
                    warm_outcomes.append(res)

            threads = [
                threading.Thread(target=warm_client, args=(i,))
                for i in range(n_clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            bad = [c for c, _ in warm_outcomes if c != 200]
            if bad:
                return {"error": f"warm-up returned {sorted(set(bad))}"}
            metrics.REGISTRY.reset()

            outcomes: list = []
            lock = threading.Lock()
            barrier = threading.Barrier(n_clients)

            def client(i):
                barrier.wait()
                mine = [one(bodies[i]) for _ in range(n_requests)]
                with lock:
                    outcomes.extend(mine)

            threads = [
                threading.Thread(target=client, args=(i,))
                for i in range(n_clients)
            ]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.time() - t0

            ok_lat = sorted(lat for code, lat in outcomes if code == 200)
            bad_codes = sorted({c for c, _ in outcomes if c != 200})
            _, occ_sum, occ_count = metrics.LANE_OCCUPANCY.child_state()
            _, it_sum, it_count = metrics.LOOP_ITERATION.child_state()

            def pct(p):
                if not ok_lat:
                    return None
                return round(
                    1000
                    * ok_lat[min(len(ok_lat) - 1, int(p * len(ok_lat)))],
                    1,
                )

            mode = {
                "wall_s": round(wall, 2),
                "req_s": (
                    round(len(ok_lat) / wall, 1) if wall > 0 else 0.0
                ),
                "ok": len(ok_lat),
                "requests": len(outcomes),
                "p50_latency_ms": pct(0.50),
                "p99_latency_ms": pct(0.99),
                "lane_occupancy_mean": (
                    round(occ_sum / occ_count, 3) if occ_count else None
                ),
                "loop_iterations": int(it_count),
            }
            if bad_codes:
                mode["error"] = (
                    f"non-200 response(s) at saturation: {bad_codes}"
                )
            return mode
        finally:
            srv.shutdown()
            srv.server_close()

    prior = os.environ.get("OSIM_SERVER_LOOP")
    try:
        baseline = run_mode(loop_on=False)
        loop = run_mode(loop_on=True)
    finally:
        if prior is None:
            os.environ.pop("OSIM_SERVER_LOOP", None)
        else:
            os.environ["OSIM_SERVER_LOOP"] = prior
        with server_mod._sessions_lock:
            server_mod._sessions.clear()

    for mode_name, mode in (("baseline", baseline), ("loop", loop)):
        if mode.get("error"):
            return {"error": f"{mode_name}: {mode['error']}"}
    speedup = (
        round(loop["req_s"] / baseline["req_s"], 2)
        if baseline["req_s"]
        else 0.0
    )
    out = {
        "value": loop["req_s"],
        "unit": "req/s",
        "wall_s": round(baseline["wall_s"] + loop["wall_s"], 2),
        "clients": n_clients,
        "requests_per_client": n_requests,
        "queue_depth": queue_depth,
        "baseline_req_s": baseline["req_s"],
        "speedup_x": speedup,
        "p50_latency_ms": loop["p50_latency_ms"],
        "p99_latency_ms": loop["p99_latency_ms"],
        "lane_occupancy_mean": loop["lane_occupancy_mean"],
        "baseline": baseline,
        "loop": loop,
    }
    if speedup < 2.0:
        out["error"] = (
            f"continuous-batching speedup {speedup}x is below the 2x "
            f"acceptance bar ({loop['req_s']} vs {baseline['req_s']} req/s)"
        )
    return out


def config_resident_delta_10k(n_nodes=10_000, n_deltas=30, touched=8):
    """Config 10: the resident-state delta path (engine/resident.py) at 10k
    nodes. A ResidentCluster cold-encodes once, then absorbs `n_deltas`
    refreshes that each bind `touched` new pods; the per-sync delta wall
    (host row re-encode + jitted scatters) is compared against the full
    `encode_nodes` re-encode the non-resident path would pay per refresh.
    The acceptance bar is speedup_x >= 10; the run ends with one forced
    drift-detector pass, so a digest divergence (or any repair during the
    walk) is reported as an error, not a faster-but-wrong number."""
    import statistics
    import tempfile
    import time

    from open_simulator_tpu.core.objects import Pod
    from open_simulator_tpu.engine.resident import ResidentCluster
    from open_simulator_tpu.ops.encode import encode_nodes

    nodes = [_mk_node(f"r-{i}", "32", "64Gi") for i in range(n_nodes)]

    def bound_pod(serial: int, node_name: str) -> Pod:
        return Pod.from_dict(
            {
                "metadata": {"name": f"b-{serial}", "namespace": "bench"},
                "spec": {
                    "nodeName": node_name,
                    "containers": [
                        {
                            "name": "c",
                            "image": "img",
                            "resources": {
                                "requests": {"cpu": "500m", "memory": "1Gi"}
                            },
                        }
                    ],
                },
            }
        )

    pods = [bound_pod(i, f"r-{i % n_nodes}") for i in range(256)]
    prev = os.environ.get("OSIM_RESIDENT_VERIFY_EVERY")
    os.environ["OSIM_RESIDENT_VERIFY_EVERY"] = "0"  # time pure applies
    try:
        res = ResidentCluster(journal_dir=tempfile.mkdtemp(prefix="osim-bench-"))
        t0 = time.time()
        res.sync(nodes, pods)  # cold start: full encode + device upload
        cold_wall = time.time() - t0

        full_walls = []
        for _ in range(3):
            t0 = time.time()
            encode_nodes(
                res.enc, nodes,
                existing_usage=res._usage, existing_gpu=res._gpu_usage,
                n_pad=res._host.n, min_axes=res._axes,
            )
            full_walls.append(time.time() - t0)

        serial = len(pods)
        delta_walls = []
        for k in range(n_deltas):
            for j in range(touched):
                serial += 1
                pods.append(bound_pod(serial, f"r-{(serial * 131) % n_nodes}"))
            t0 = time.time()
            res.sync(nodes, pods)
            delta_walls.append(time.time() - t0)
        delta_walls = delta_walls[1:]  # first sync pays the scatter-jit trace

        full_ms = 1000 * statistics.median(full_walls)
        delta_ms = 1000 * statistics.median(delta_walls)
        verified = res.verify_now()
    finally:
        if prev is None:
            os.environ.pop("OSIM_RESIDENT_VERIFY_EVERY", None)
        else:
            os.environ["OSIM_RESIDENT_VERIFY_EVERY"] = prev

    speedup = full_ms / delta_ms if delta_ms > 0 else None
    out = {
        "wall_s": round(sum(full_walls) + sum(delta_walls) + cold_wall, 2),
        "value": round(speedup, 1) if speedup else None,
        "unit": "x faster than full re-encode",
        "nodes": n_nodes,
        "deltas": n_deltas,
        "touched_rows_per_delta": touched,
        "cold_encode_ms": round(1000 * cold_wall, 1),
        "full_encode_ms": round(full_ms, 1),
        "delta_apply_ms": round(delta_ms, 2),
        "speedup_x": round(speedup, 1) if speedup else None,
        "verified": bool(verified),
        "repairs": res.repairs,
    }
    if not verified or res.repairs:
        out["error"] = (
            f"drift during bench: verified={verified} repairs={res.repairs}"
        )
    elif speedup is not None and speedup < 10:
        out["error"] = (
            f"delta apply only {speedup:.1f}x faster than full re-encode "
            "(acceptance floor is 10x)"
        )
    return out


def _hetero_template(name="new-node"):
    """A realistic heterogeneous capacity template: zone/instance-type
    labels, a taint, GPUs, open-local storage — the loop encode pays every
    axis it would pay in production, so the stamped-vs-loop ratio is the
    honest one."""
    import json as _json

    from open_simulator_tpu.core.objects import ANNO_NODE_LOCAL_STORAGE

    GiB = 1 << 30
    template = _mk_node(
        name, "32", "64Gi",
        labels={
            "topology.kubernetes.io/zone": "az-1",
            "node.kubernetes.io/instance-type": "ecs.gn7.8xlarge",
            "disk": "ssd",
        },
        capacity_extra={
            "alibabacloud.com/gpu-count": "4",
            "alibabacloud.com/gpu-mem": f"{4 * 16384}Mi",
        },
    )
    template.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = _json.dumps(
        {
            "vgs": [{"name": "vg-open", "capacity": str(400 * GiB),
                     "requested": str(40 * GiB)}],
            "devices": [{"name": "sdb", "device": "/dev/sdb",
                         "capacity": str(200 * GiB), "mediaType": "ssd",
                         "isAllocated": False}],
        }
    )
    return template


def _preflight_verdict(config):
    """The statically machine-checked fits-in-HBM verdict that `simon
    preflight --write-budgets` banked for ``config`` in the checked-in
    budget book, or None. Lets the bench line carry the static
    peak-HBM/collective proof next to the measured throughput without
    recompiling anything here."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "budgets",
        "preflight.json",
    )
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f).get("verdicts", {}).get(config)
    except (OSError, ValueError):
        return None


def _config_plan_scaled(n_pods, n_nodes):
    """Million-scale node axis (docs/performance.md, node-bucket ladder):
    one segment publishing the four acceptance numbers together —

      - stamped-vs-loop encode wall at n_nodes clones, byte-identity
        asserted on every NodeTable array (floor: 10x);
      - full capacity plan pods/s at (n_pods, n_nodes) scale;
      - distinct compiled scenario programs, all on ladder rungs;
      - per-device HBM bytes for the node-sharded vs replicated table
        (>= 2 devices; sharded must be strictly smaller)."""
    import numpy as np

    from open_simulator_tpu.engine.capacity import new_fake_nodes, plan_capacity
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
    )
    from open_simulator_tpu.ops.encode import (
        _STAMP_FIELDS,
        Encoder,
        encode_nodes,
        node_bucket,
    )
    from open_simulator_tpu.ops.fast import (
        reset_scenario_programs,
        scenario_programs,
    )

    out = {}

    # --- template-stamped encode: loop vs stamped at n_nodes clones -------
    clones = new_fake_nodes(_hetero_template(), n_nodes)
    t0 = time.time()
    t_loop = encode_nodes(Encoder(), clones, stamp=False)
    loop_s = time.time() - t0
    stamped_s = float("inf")
    enc_stamp = None
    for _ in range(3):
        enc = Encoder()
        t0 = time.time()
        t_stamp = encode_nodes(enc, clones, stamp=True)
        if time.time() - t0 < stamped_s:
            stamped_s = time.time() - t0
            enc_stamp = enc
    byte_identical = all(
        np.asarray(getattr(t_loop, f)).tobytes()
        == np.asarray(getattr(t_stamp, f)).tobytes()
        for f in _STAMP_FIELDS
    ) and t_loop.names == t_stamp.names
    speedup = loop_s / stamped_s if stamped_s > 0 else None
    out["encode_loop_ms"] = round(1000 * loop_s, 1)
    out["encode_stamped_ms"] = round(1000 * stamped_s, 1)
    out["encode_stamped_speedup"] = round(speedup, 1) if speedup else None
    out["encode_byte_identical"] = bool(byte_identical)
    if not byte_identical:
        out["error"] = "stamped encode is not byte-identical to loop encode"
    elif speedup is not None and speedup < 10:
        out["error"] = (
            f"stamped encode only {speedup:.1f}x faster than loop "
            "(acceptance floor is 10x)"
        )

    # --- per-device HBM: node-sharded vs replicated table -----------------
    import jax

    if len(jax.devices()) >= 2:
        from open_simulator_tpu.ops.state import node_static_from_table
        from open_simulator_tpu.parallel.mesh import (
            hbm_bytes_per_device,
            node_sharding,
            product_mesh_2d,
            replicated,
        )

        mesh = product_mesh_2d(1, len(jax.devices()))
        ns = node_static_from_table(enc_stamp, t_stamp)
        rep = hbm_bytes_per_device(jax.device_put(ns, replicated(mesh, ns)))
        shd = hbm_bytes_per_device(jax.device_put(ns, node_sharding(mesh)))
        out["hbm_bytes_per_device_replicated"] = max(rep.values())
        out["hbm_bytes_per_device_sharded"] = max(shd.values())
        if max(shd.values()) >= max(rep.values()):
            out["error"] = out.get("error") or (
                "node-sharded table not smaller per device than replicated"
            )
        del ns

    # --- full capacity plan at (n_pods, n_nodes) scale --------------------
    # Sized like plan_100k_10k: the workload genuinely overflows (~0.375
    # cpu/pod demand vs 3 cpu/node supply) so the add-node search runs. No
    # spread constraint here — spread chunks the commit scan at every skew
    # boundary (plan_100k_10k covers that at scale); these segments measure
    # raw plan throughput on the node-bucket ladder, where whole
    # deployments batch through the group fast path.
    nodes = [
        _mk_node(
            f"n-{i}", "3", "6Gi",
            labels={"topology.kubernetes.io/zone": f"az-{i % 3}"},
        )
        for i in range(n_nodes)
    ]
    deploys = [
        _mk_deploy("web", n_pods // 2, "500m", "1Gi"),
        _mk_deploy("batch", n_pods - n_pods // 2, "250m", "512Mi"),
    ]
    template = _mk_node("new-node", "32", "64Gi")
    reset_scenario_programs()
    t0 = time.time()
    plan = plan_capacity(
        ClusterResource(nodes=nodes),
        [AppResource(name="bench", objects=deploys)],
        template,
    )
    wall = time.time() - t0
    out["wall_s"] = round(wall + loop_s + 3 * stamped_s, 2)
    out["plan_wall_s"] = round(wall, 2)
    out["value"] = round(n_pods / wall, 1)
    out["unit"] = "pods/s"
    out["nodes_added"] = plan.nodes_added if plan else -1
    out["attempts"] = plan.attempts if plan else 0
    out["batched_calls"] = plan.batched_calls if plan else 0
    # the commit engine the sweep routed through: OSIM_WAVE_COMMIT=1 runs
    # this whole segment on the conflict-parallel wave driver (byte-
    # identical placements; rounds/fallbacks in `simon metrics`), the
    # default is auto (wave only on parallel backends — see ops/wave.py)
    from open_simulator_tpu.ops import wave as wave_mod

    out["commit_engine"] = (
        "wave" if wave_mod.wave_enabled(n_pods) else "serial"
    )

    # --- distinct programs: every one on a ladder rung --------------------
    progs = scenario_programs()
    out["distinct_programs"] = sum(len(p) for p in progs.values())
    out["ladder_rungs_touched"] = sorted({n for (n, _p) in progs})
    off = [n for (n, _p) in progs if node_bucket(n) != n]
    if off:
        out["error"] = out.get("error") or f"off-ladder node paddings: {off}"

    # --- static preflight verdict (budgets/preflight.json) ----------------
    if (n_pods, n_nodes) == (1_000_000, 100_000):
        vd = _preflight_verdict("plan_1m_100k")
        if vd is not None:
            out["preflight_ok"] = bool(vd.get("ok"))
            out["preflight_peak_gib"] = vd.get("peak_gib")
            out["preflight_mesh"] = vd.get("mesh")
            out["preflight_node_table_sharded"] = vd.get(
                "node_table_sharded"
            )
            if not vd.get("ok"):
                out["error"] = out.get("error") or (
                    "preflight verdict failed: plan_1m_100k does not fit "
                    "per-device HBM (or node table replicated) — see "
                    "`simon preflight`"
                )
    return out


def config_prove_smoke(n_universes=512):
    """The `simon prove` engine-vs-oracle checker on a strided sample of
    the small-scope corpus: tracks the exhaustive checker's device
    throughput and pins `universes_checked` into the bench JSON. The CI
    prove job runs the full 151,875-universe corpus against the banked
    contract; this is the bench-side heartbeat with the same engine path
    (stamped-gather packing onto the scenario axis, one device call at
    this sample size)."""
    from open_simulator_tpu.analysis.semantics import run_prove

    out = {"n_universes": n_universes}
    t0 = time.time()
    report = run_prove(smoke=n_universes, chunk=n_universes)
    wall = time.time() - t0
    out["wall_s"] = round(wall, 2)
    out["universes_checked"] = report.universes_checked
    out["device_calls"] = report.device_calls
    out["divergences"] = report.divergence_total
    out["digest"] = report.digest
    out["value"] = round(report.universes_checked / wall, 1)
    out["unit"] = "universes/s"
    if report.divergence_total:
        out["error"] = (
            f"{report.divergence_total} oracle divergence(s); minimized "
            f"counterexample: {report.minimized}"
        )
    return out


def config_interleave_smoke():
    """The `simon interleave` protocol model checker under quick bounds:
    explored-states throughput of the cooperative-scheduler explorer
    over all five protocol scenarios. The report itself is
    wall-clock-free by design (same seed => byte-identical), so the
    timing lives here, bench-side. Any invariant violation on the real
    protocols — or a scenario exhausting its run budget — is an error."""
    from open_simulator_tpu.analysis.interleave import run_interleave

    out = {}
    t0 = time.time()
    report = run_interleave(quick=True)
    wall = time.time() - t0
    states = sum(s.states for s in report.scenarios)
    out["wall_s"] = round(wall, 2)
    out["runs"] = sum(s.runs for s in report.scenarios)
    out["states"] = states
    out["pruned"] = sum(s.pruned for s in report.scenarios)
    out["scenarios"] = {
        s.name: {"runs": s.runs, "states": s.states,
                 "completed": s.completed}
        for s in report.scenarios
    }
    out["digest"] = report.to_dict()["digest"]
    out["value"] = round(states / wall, 1)
    out["unit"] = "states/s"
    if not report.ok:
        bad = [f"{s.name}:{v.invariant}"
               for s in report.scenarios for v in s.violations]
        incomplete = [s.name for s in report.scenarios if not s.completed]
        out["error"] = (
            f"interleave not clean on real protocols: "
            f"violations={bad} budget-exhausted={incomplete}"
        )
    return out


def config_plan_200k_20k():
    """CPU-scaled million-node segment: 200k pods / 20k nodes (CI publishes
    this one; plan_1m_100k is the full-scale variant)."""
    return _config_plan_scaled(200_000, 20_000)


def config_plan_1m_100k():
    """The full million-scale segment: 1M pods / 100k nodes."""
    return _config_plan_scaled(1_000_000, 100_000)


def config_checkpoint_overhead(n_pods=10_000, n_nodes=100, chunk=1024):
    """Config: the chunked-commit checkpoint tax (docs/durability.md). The
    same 10k-pod commit scan dispatched once monolithically and once
    chunked (OSIM_COMMIT_CHUNK) under a live PlanCheckpointer — every
    chunk journaled, a carry+prefix snapshot every 4 chunks, all into a
    throwaway run dir. Each mode runs twice and reports its warm wall
    (the chunked program compiles separately on the first pass);
    overhead_x is warm-vs-warm and must stay within 5%: checkpointing is
    host-side bookkeeping between device dispatches, not extra device
    work. The two final carries must also digest-match bit-for-bit — the
    chunked driver's byte-identity contract, asserted at bench scale."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    from open_simulator_tpu.durable import RunJournal
    from open_simulator_tpu.durable.checkpoint import (
        PlanCheckpointer,
        installed,
    )
    from open_simulator_tpu.ops import fast
    from open_simulator_tpu.ops import state as state_mod
    from open_simulator_tpu.ops.kernels import weights_array
    from open_simulator_tpu.utils import metrics

    ns, carry, batch = build_state(n_nodes, n_pods)
    s_pad = fast.scenario_bucket(1)
    w_s = jnp.asarray(np.stack([np.asarray(weights_array())] * s_pad))
    valid_s = jnp.asarray(np.stack([np.asarray(ns.valid)] * s_pad))

    def run_once():
        import jax

        carry_s = state_mod.stack_carry(carry, s_pad)
        t0 = time.time()
        out = fast.schedule_scenarios_host(
            ns, carry_s, batch, w_s, valid_s, 1
        )
        jax.block_until_ready(out[0])
        wall = time.time() - t0
        return wall, fast.scenario_carry_digest(out[0])

    def _put_env(key, val):
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = val

    def run_mode(chunked: bool):
        prev = os.environ.get("OSIM_COMMIT_CHUNK")
        prev_every = os.environ.get("OSIM_CKPT_EVERY")
        run_dir = None
        try:
            if chunked:
                os.environ["OSIM_COMMIT_CHUNK"] = str(chunk)
                os.environ["OSIM_CKPT_EVERY"] = "4"
                run_dir = tempfile.mkdtemp(prefix="osim-ckpt-bench-")
                journal = RunJournal.open(run_dir)
                try:
                    with installed(PlanCheckpointer(journal)):
                        cold, _ = run_once()
                        warm, digest = run_once()
                finally:
                    journal.close()
            else:
                os.environ.pop("OSIM_COMMIT_CHUNK", None)
                cold, _ = run_once()
                warm, digest = run_once()
        finally:
            _put_env("OSIM_COMMIT_CHUNK", prev)
            _put_env("OSIM_CKPT_EVERY", prev_every)
            if run_dir:
                shutil.rmtree(run_dir, ignore_errors=True)
        return cold, warm, digest

    m_cold, m_warm, m_digest = run_mode(chunked=False)
    bytes0 = metrics.CHECKPOINT_BYTES.value()
    chunks0 = metrics.PLAN_CHUNKS.value()
    c_cold, c_warm, c_digest = run_mode(chunked=True)
    overhead = (c_warm / m_warm) if m_warm > 0 else None
    out = {
        "wall_s": round(c_warm, 2),
        "value": round(n_pods / c_warm, 1) if c_warm > 0 else None,
        "monolithic_wall_s": round(m_warm, 2),
        "chunked_wall_s": round(c_warm, 2),
        "monolithic_cold_wall_s": round(m_cold, 2),
        "chunked_cold_wall_s": round(c_cold, 2),
        "overhead_x": round(overhead, 3) if overhead else None,
        "chunk": chunk,
        "chunks_dispatched": int(metrics.PLAN_CHUNKS.value() - chunks0),
        "checkpoint_bytes": int(metrics.CHECKPOINT_BYTES.value() - bytes0),
        "digest": f"{c_digest:08x}",
    }
    if c_digest != m_digest:
        out["error"] = (
            f"chunked digest {c_digest:08x} != monolithic {m_digest:08x}; "
            "the chunked driver must be byte-identical"
        )
    elif overhead is not None and overhead > 1.05:
        out["error"] = (
            f"checkpoint overhead {overhead:.3f}x exceeds the 1.05x budget"
        )
    return out


def config_wave_commit_10k(
    n_pods=10_000, n_nodes=500, wave_pods=1_280, wave=256, wave_rounds=8
):
    """Config: the conflict-parallel wave commit (ops/wave.py, ROADMAP
    item 1) against the serial scan it replaces.

    Three legs:
      1. serial oracle — the monolithic decide+commit scan over n_pods
         (one schedule_step per pod); its warm wall is the baseline and
         its placements/carry digest are the reference.
      2. commit phase — the serial leg's choices replayed through
         `ops.fast:commit_choices` (the row-wise commit scan): the only
         inherently sequential part of the wave engine. The acceptance
         floor is >= 10x faster than the serial scan on CPU — the
         sequential-depth reduction the wave engine buys — and the final
         carry must digest-match the serial leg bit-for-bit.
      3. wave engine — the full Jacobi round driver (OSIM_WAVE_COMMIT=1)
         over a wave_pods prefix-sized workload, reporting
         rounds-to-converge, conflicts, and bounded-rounds fallbacks
         from the metrics registry, plus its own serial-reference digest
         equality. Total wall is reported, NOT gated: on a single-core
         CPU host a probe round costs about one serial scan of the wave
         (element-throughput-bound), so the data-parallel win needs a
         parallel backend — docs/performance.md works the numbers.
    """
    import jax
    import jax.numpy as jnp

    from open_simulator_tpu.ops import fast
    from open_simulator_tpu.ops import state as state_mod
    from open_simulator_tpu.ops.kernels import weights_array
    from open_simulator_tpu.utils import metrics

    def msum(counter) -> float:
        return sum(
            s["value"] for s in counter.snapshot()["samples"]
        )

    saved = {
        k: os.environ.get(k)
        for k in (
            "OSIM_WAVE_COMMIT", "OSIM_WAVE_SIZE", "OSIM_WAVE_ROUNDS",
            "OSIM_COMMIT_CHUNK",
        )
    }

    def _put_env(key, val):
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = val

    def serial_run(ns, carry, batch, w_s, valid_s, s_pad):
        carry_s = state_mod.stack_carry(carry, s_pad)
        t0 = time.time()
        out = fast.schedule_scenarios_host(
            ns, carry_s, batch, w_s, valid_s, 1
        )
        jax.block_until_ready(out[0])
        return time.time() - t0, out

    def hist_stats(hist):
        snap = hist.snapshot()
        if not snap["samples"]:
            return 0, 0.0
        s = snap["samples"][0]
        return int(s["count"]), float(s["sum"])

    out = {}
    try:
        os.environ.pop("OSIM_COMMIT_CHUNK", None)
        os.environ["OSIM_WAVE_COMMIT"] = "0"

        # --- leg 1: the serial oracle at n_pods --------------------------
        ns, carry, batch = build_state(n_nodes, n_pods)
        s_pad = fast.scenario_bucket(1)
        w_s = jnp.asarray(np.stack([np.asarray(weights_array())] * s_pad))
        valid_s = jnp.asarray(np.stack([np.asarray(ns.valid)] * s_pad))
        serial_run(ns, carry, batch, w_s, valid_s, s_pad)  # compile
        t_serial, ref = serial_run(ns, carry, batch, w_s, valid_s, s_pad)
        ref_digest = fast.scenario_carry_digest(ref[0])
        p_pad = int(batch.p)
        nodes_ref = np.asarray(ref[1])

        # --- leg 2: the commit phase (row-wise replay of the choices) ----
        rows = fast.pod_rows_from_batch(batch)
        choices = jnp.asarray(
            np.broadcast_to(nodes_ref[:1], (s_pad, p_pad)).copy()
        )
        count = jnp.int32(p_pad)

        def commit_run():
            carry_s = state_mod.stack_carry(carry, s_pad)
            t0 = time.time()
            r = fast.commit_choices(ns, carry_s, rows, valid_s, choices, count)
            jax.block_until_ready(r[0])
            return time.time() - t0, r

        commit_run()  # compile
        t_commit, rep = commit_run()
        commit_digest = fast.scenario_carry_digest(rep[0])
        commit_speedup = t_serial / t_commit if t_commit > 0 else None

        # --- leg 3: the wave round driver at wave_pods -------------------
        ns_w, carry_w, batch_w = build_state(n_nodes, wave_pods)
        valid_w = jnp.asarray(np.stack([np.asarray(ns_w.valid)] * s_pad))
        serial_run(ns_w, carry_w, batch_w, w_s, valid_w, s_pad)  # compile
        t_sw, ref_w = serial_run(ns_w, carry_w, batch_w, w_s, valid_w, s_pad)
        ref_w_digest = fast.scenario_carry_digest(ref_w[0])

        os.environ["OSIM_WAVE_COMMIT"] = "1"
        os.environ["OSIM_WAVE_SIZE"] = str(wave)
        os.environ["OSIM_WAVE_ROUNDS"] = str(wave_rounds)
        serial_run(ns_w, carry_w, batch_w, w_s, valid_w, s_pad)  # compile
        rounds_n0, rounds_s0 = hist_stats(metrics.COMMIT_ROUNDS)
        conflicts0 = msum(metrics.WAVE_CONFLICTS)
        fallbacks0 = msum(metrics.WAVE_FALLBACKS)
        t_wave, wout = serial_run(ns_w, carry_w, batch_w, w_s, valid_w, s_pad)
        wave_digest = fast.scenario_carry_digest(wout[0])
        rounds_n1, rounds_s1 = hist_stats(metrics.COMMIT_ROUNDS)
        n_waves = rounds_n1 - rounds_n0
        rounds_total = rounds_s1 - rounds_s0

        out = {
            "wall_s": round(t_serial + t_commit + t_sw + t_wave, 2),
            "value": round(n_pods / t_commit, 1) if t_commit > 0 else None,
            "unit": "pods/s (commit phase)",
            "serial_wall_s": round(t_serial, 2),
            "serial_pods_s": round(n_pods / t_serial, 1),
            "commit_wall_s": round(t_commit, 3),
            "commit_phase_speedup_x": (
                round(commit_speedup, 1) if commit_speedup else None
            ),
            "wave_pods": wave_pods,
            "wave_size": wave,
            "wave_rounds_budget": wave_rounds,
            "wave_wall_s": round(t_wave, 2),
            "wave_serial_wall_s": round(t_sw, 2),
            "wave_total_speedup_x": (
                round(t_sw / t_wave, 2) if t_wave > 0 else None
            ),
            "waves_dispatched": n_waves,
            "rounds_to_converge_mean": (
                round(rounds_total / n_waves, 1) if n_waves else None
            ),
            "wave_conflicts": int(msum(metrics.WAVE_CONFLICTS) - conflicts0),
            "wave_fallbacks": int(msum(metrics.WAVE_FALLBACKS) - fallbacks0),
            "digest": f"{ref_digest:08x}",
        }
        if commit_digest != ref_digest:
            out["error"] = (
                f"commit-phase digest {commit_digest:08x} != serial "
                f"{ref_digest:08x}; the row-wise commit must be "
                "byte-identical"
            )
        elif wave_digest != ref_w_digest:
            out["error"] = (
                f"wave-engine digest {wave_digest:08x} != serial "
                f"{ref_w_digest:08x}; the fixpoint driver must be "
                "byte-identical"
            )
        elif commit_speedup is not None and commit_speedup < 10:
            out["error"] = (
                f"commit-phase speedup {commit_speedup:.1f}x below the "
                "10x acceptance floor"
            )
    finally:
        for k, v in saved.items():
            _put_env(k, v)
    return out


CONFIGS = {
    "stock": config_stock,
    "fit_1k_100n": config_fit,
    "sanitize_overhead_1k": config_sanitize_overhead,
    "spread_aff_10k_1k": config_spread_affinity,
    "gpushare_5k": config_gpushare,
    "plan_100k_10k": config_plan,
    "capacity_sweep_batched": config_capacity_sweep,
    "multi_scenario_64": config_multi_scenario,
    "warm_start_100k": config_warm_start,
    "sharded_2dev_smoke": config_sharded_smoke,
    "preempt_tiered": config_preempt,
    "extender_1k": config_extender,
    "serving_concurrent": config_serving_concurrent,
    "serving_saturation": config_serving_saturation,
    "resident_delta_10k": config_resident_delta_10k,
    "prove_smoke": config_prove_smoke,
    "interleave_smoke": config_interleave_smoke,
    "plan_200k_20k": config_plan_200k_20k,
    "plan_1m_100k": config_plan_1m_100k,
    "checkpoint_overhead": config_checkpoint_overhead,
    "wave_commit_10k": config_wave_commit_10k,
}

# Excluded from `--configs all`: run them by name (CI runs plan_200k_20k
# on its own schedule; plan_1m_100k is the full-scale local run).
SLOW_CONFIGS = {"plan_200k_20k", "plan_1m_100k"}


def _fmt_count(n: int) -> str:
    return f"{n // 1000}k" if n >= 1000 else str(n)


def _run_headline(pods: int, nodes: int) -> dict:
    """The headline kernel benchmark, in-process (called in a child)."""
    import jax

    from open_simulator_tpu.ops.fast import (
        DEFAULT_GROUP_CHUNK,
        schedule_batch_fast,
    )
    from open_simulator_tpu.ops.kernels import weights_array

    def phase(msg: str) -> None:
        # Stderr breadcrumbs: when a tunnel deadline kills this child, the
        # .err file's last line says which phase hung (encode vs compile
        # pass vs timed pass) — see BASELINE.md round-5 wedge forensics.
        print(f"[headline {time.strftime('%H:%M:%S')}] {msg}",
              file=sys.stderr, flush=True)

    from open_simulator_tpu.utils.tracing import span

    t_enc0 = time.time()
    with span("encode", pods=pods, nodes=nodes):
        ns, carry, batch = build_state(nodes, pods)
    t_enc = time.time() - t_enc0
    phase(f"encode done in {t_enc:.1f}s (pods={pods} nodes={nodes})")
    w = weights_array()
    # Cap on per-group device-program length (scan steps per dispatch).
    # Overridable for tunnel experiments: the axon relay wedges on some
    # large programs, and a smaller chunk bounds what each dispatch asks
    # of the remote worker (scripts/tpu_bisect.sh sweeps this).
    try:
        chunk = int(
            os.environ.get("OSIM_HEADLINE_CHUNK", str(DEFAULT_GROUP_CHUNK))
        )
    except ValueError:
        raise SystemExit(
            f"OSIM_HEADLINE_CHUNK must be a positive integer, got "
            f"{os.environ['OSIM_HEADLINE_CHUNK']!r}"
        )
    if chunk <= 0:
        # chunk<=0 would make the fast-path chunking loop spin forever
        raise SystemExit(
            f"OSIM_HEADLINE_CHUNK must be a positive integer, got {chunk}"
        )

    # Warm up with one full untimed pass (same shapes => same executables),
    # then one timed pass. The grouped scheduler's per-group chunking
    # (schedule_batch_grouped max_group_chunk) bounds each device program to a
    # few seconds — a single 100k-step scan trips the TPU worker's watchdog.
    phase("warm pass (compiles) starting")
    t0 = time.time()
    with span("schedule-warm", pods=pods):
        schedule_batch_fast(ns, carry, batch, w, max_group_chunk=chunk)
    compile_s = time.time() - t0
    phase(f"warm pass done in {compile_s:.1f}s; timed pass starting")

    t1 = time.time()
    with span("schedule-timed", pods=pods):
        _, placed, *_ = schedule_batch_fast(
            ns, carry, batch, w, max_group_chunk=chunk
        )
    run = time.time() - t1
    phase(f"timed pass done in {run:.2f}s")
    scheduled = int((placed >= 0).sum())
    pods_per_sec = pods / run

    from open_simulator_tpu.ops.fast import PATH_COUNTS

    out = {
        "paths": {k: v for k, v in PATH_COUNTS.items() if v},
        "metric": f"schedule_{_fmt_count(pods)}_pods_{_fmt_count(nodes)}_nodes",
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / TARGET_PODS_PER_SEC, 3),
        "wall_s": round(run, 2),
        "compile_s": round(compile_s, 2),
        "encode_s": round(t_enc, 2),
        "scheduled": scheduled,
        "pods": pods,
        "nodes": nodes,
        "device": str(jax.devices()[0]),
    }
    if chunk != DEFAULT_GROUP_CHUNK:
        # a non-default dispatch granularity changes what the number means —
        # stamp it so the JSON is never mistaken for a default-chunk figure
        out["group_chunk"] = chunk
    return out


# Per-segment wall-clock deadlines (seconds). Generous vs expected runtimes
# (headline ≈ 30 s run + compiles; each config well under its cap on TPU) but
# bounded: a wedged TPU tunnel hangs device calls indefinitely and an
# in-process hang cannot be interrupted, so every segment runs in a killable
# child process (same reasoning as _probe_backend).
SEGMENT_TIMEOUT_S = {
    "headline": 1200.0,
    "canary": 300.0,
    "headline_mid": 600.0,
    "stock": 900.0,
    "fit_1k_100n": 600.0,
    "sanitize_overhead_1k": 900.0,
    "spread_aff_10k_1k": 900.0,
    "gpushare_5k": 900.0,
    "plan_100k_10k": 1200.0,
    "capacity_sweep_batched": 900.0,
    "multi_scenario_64": 600.0,
    "warm_start_100k": 900.0,
    "sharded_2dev_smoke": 600.0,
    "preempt_tiered": 900.0,
    "extender_1k": 900.0,
    "serving_concurrent": 600.0,
    "serving_saturation": 900.0,
    "resident_delta_10k": 900.0,
    # Three legs (serial oracle scan, replayed row-wise commit phase, wave
    # driver) plus compiles; ~1 min warm on a 1-core CPU host.
    "wave_commit_10k": 900.0,
    # The scaled plan segments run the default batched sweep, which commits
    # per-pod (no group fast path inside schedule_scenarios yet): on a CPU
    # host they are wall-hours, which is why they sit in SLOW_CONFIGS and
    # CI runs plan_200k_20k in its own push-only job.
    "plan_200k_20k": 7200.0,
    "plan_1m_100k": 14400.0,
}


def _segment_main(name: str, pods: int, nodes: int) -> int:
    """Child-process entry: run one segment, print its JSON to stdout."""
    from open_simulator_tpu.utils.platform import (
        enable_compilation_cache,
        ensure_platform,
        install_compile_listener,
    )

    ensure_platform()
    enable_compilation_cache()
    install_compile_listener()
    try:
        if name in ("headline", "canary", "headline_mid"):
            out = _run_headline(pods, nodes)
        else:
            out = CONFIGS[name]()
    except Exception as e:  # noqa: BLE001 - report, don't crash the parent
        out = {"error": f"{type(e).__name__}: {e}"}
    if isinstance(out, dict) and "metrics" not in out:
        # phase histograms / compile-cache behavior / failure reasons for
        # this segment's process (each segment is its own child, so the
        # snapshot is per-segment)
        from open_simulator_tpu.utils.metrics import COMPILE_CACHE, REGISTRY

        out["metrics"] = REGISTRY.snapshot()
        # explicit top-of-doc compile count so BENCH_*.json diffs catch
        # recompile regressions without digging through the metrics tree
        out["compiles"] = int(COMPILE_CACHE.value(event="backend_compile"))
    if isinstance(out, dict):
        # device-time evidence (utils/profiling.py): always present so JSON
        # consumers can key on the fields; null unless OSIM_DEVICE_PROFILE=1
        # opts the segment into the post-run dispatch-gap analysis (the
        # sandwich re-times every audited entry, so it is not free).
        out.setdefault("device_time_ms", None)
        out.setdefault("dispatch_gap_ratio", None)
        if os.environ.get("OSIM_DEVICE_PROFILE", "") == "1":
            try:
                from open_simulator_tpu.utils.profiling import (
                    analyze_dispatch_gaps,
                )

                rep = analyze_dispatch_gaps(repeats=1)
                out["device_time_ms"] = rep.device_time_ms
                out["dispatch_gap_ratio"] = rep.dispatch_gap_ratio
                out["device_profile"] = rep.to_dict()
            except Exception as e:  # noqa: BLE001 - profiling must not fail the segment
                out["device_profile"] = {
                    "error": f"{type(e).__name__}: {e}"
                }
    print(json.dumps(out), flush=True)
    return 0


def _run_segment(name: str, pods: int, nodes: int, platform: str) -> dict:
    """Run one segment in a killable child under its deadline."""
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
    if name in ("sharded_2dev_smoke", "plan_200k_20k", "plan_1m_100k"):
        # these segments need >=2 devices on every CI lane (the sharding
        # smoke proves placement equivalence; the plan segments report
        # per-device HBM for the node-sharded vs replicated table):
        # provision 2 virtual CPU devices — the flag only affects the host
        # platform, so they are deliberately CPU-pinned
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2"
        ).strip()
    deadline = SEGMENT_TIMEOUT_S.get(name, 900.0)
    cmd = [
        sys.executable, "-u", os.path.abspath(__file__),
        "--segment", name, "--pods", str(pods), "--nodes", str(nodes),
    ]
    t0 = time.time()
    try:
        r = subprocess.run(
            cmd, env=env, timeout=deadline, capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {
            "error": f"timeout after {deadline:.0f}s (device hang?)",
            "wall_s": round(time.time() - t0, 2),
        }
    for line in (r.stderr or "").splitlines()[-12:]:
        if "WARNING" not in line and "cpu_aot_loader" not in line:
            print(f"  [{name}] {line[:300]}", file=sys.stderr, flush=True)
    tail = (r.stdout or "").strip().splitlines()
    if r.returncode != 0 or not tail:
        err = (r.stderr or "").strip().splitlines()
        return {
            "error": f"rc={r.returncode}: {err[-1] if err else 'no output'}"
        }
    try:
        return json.loads(tail[-1])
    except json.JSONDecodeError:
        return {"error": f"unparseable output: {tail[-1][:200]}"}


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--pods", type=int, default=100_000)
    parser.add_argument("--nodes", type=int, default=10_000)
    parser.add_argument("--quick", action="store_true", help="tiny smoke sizes")
    parser.add_argument(
        "--configs", default="all",
        help="comma list of end-to-end configs to run alongside the headline "
        f"kernel benchmark ({', '.join(CONFIGS)}), 'all', or 'none'; "
        f"'all' skips the slow configs ({', '.join(sorted(SLOW_CONFIGS))}) — "
        "name them explicitly to run them",
    )
    parser.add_argument(
        "--segment", default="",
        help="(internal) run one segment in-process: headline or a config name",
    )
    parser.add_argument(
        "--run-dir", default="",
        help="journal this bench run into DIR (one JSONL record per "
        "completed segment) so a crashed/wedged run can be resumed",
    )
    parser.add_argument(
        "--resume", nargs="?", const=True, default=False, metavar="RUN_DIR",
        help="resume a journaled bench run: completed segments are replayed "
        "from the journal, not re-measured (RUN_DIR defaults to --run-dir)",
    )
    args = parser.parse_args()
    if args.segment:
        return _segment_main(args.segment, args.pods, args.nodes)
    if args.quick:
        args.pods, args.nodes = 2_000, 200

    run_dir = args.run_dir or (
        args.resume if isinstance(args.resume, str) else ""
    )
    resume = bool(args.resume)
    if resume and not run_dir:
        parser.error("--resume needs a run dir (positional or --run-dir)")

    journal = None
    done_segments: dict = {}
    if run_dir:
        from open_simulator_tpu.durable import RunJournal, completed_segments
        from open_simulator_tpu.utils.metrics import RUN_RESUMED

        journal = RunJournal.open(run_dir)
        if not journal.has("run_start"):
            journal.append(
                "run_start", kind="bench", pods=args.pods, nodes=args.nodes,
                configs=args.configs,
            )
        if resume:
            RUN_RESUMED.inc()
            journal.append("run_resume")
            done_segments = completed_segments(journal.events())
            if done_segments:
                print(
                    f"resuming: {len(done_segments)} journaled segment(s) "
                    f"will be replayed, not re-measured "
                    f"({', '.join(sorted(done_segments))})",
                    file=sys.stderr, flush=True,
                )

    # Validate --configs up front so a typo fails fast even with --quick.
    if args.configs in ("none", "all"):
        wanted = ([] if args.configs == "none"
                  else [c for c in CONFIGS if c not in SLOW_CONFIGS])
    else:
        wanted = [c.strip() for c in args.configs.split(",") if c.strip()]
        unknown = [c for c in wanted if c not in CONFIGS]
        if unknown:
            parser.error(
                f"--configs: unknown config(s) {unknown}; "
                f"choose from {', '.join(CONFIGS)}, all, none"
            )

    # Resume-provenance guard: when the headline is already journaled, its
    # backend provenance must come from the journal too — a fresh probe in
    # the resumed process might fall back to CPU and would then mislabel a
    # genuinely-on-TPU journaled headline as a CPU fallback (or vice versa).
    journaled_backend = None
    if resume and journal is not None and "headline" in done_segments:
        for e in journal.events():
            if e.get("event") in ("backend", "backend_fallback"):
                journaled_backend = {
                    k: v for k, v in e.items()
                    if k not in ("seq", "ts", "event")
                }
    if journaled_backend is not None:
        backend_info = journaled_backend
        if backend_info.get("fallback") == "cpu":
            os.environ["JAX_PLATFORMS"] = "cpu"
    else:
        backend_info = _select_backend(journal=journal)
    platform = os.environ.get("JAX_PLATFORMS", "")

    def run_seg(name: str, pods: int, nodes: int, plat: str) -> dict:
        """One segment through the journal: replayed if already committed,
        measured (and committed on success) otherwise. Failed segments are
        NOT journaled, so a resume re-runs exactly what never succeeded."""
        if name in done_segments:
            print(
                f"bench segment {name}: replayed from journal",
                file=sys.stderr, flush=True,
            )
            return dict(done_segments[name])
        res = _run_segment(name, pods, nodes, plat)
        if journal is not None and "error" not in res:
            journal.append("segment", segment=name, result=res)
        return res

    def _fall_back_to_cpu(stage: str, err: str) -> str:
        """Label the fallback in backend_info and return the new platform."""
        print(
            f"{stage} failed on '{platform or 'default'}' ({err}); "
            "falling back to cpu for all remaining segments",
            file=sys.stderr, flush=True,
        )
        backend_info["fallback"] = "cpu"
        backend_info["fallback_reason"] = f"{stage}: {err}" if stage != "headline" else err
        return "cpu"

    # Every segment runs in its own killable subprocess under a deadline, and
    # results flush to stderr as they land: a TPU-tunnel wedge mid-run (it
    # hangs device calls indefinitely; observed repeatedly in-round) costs one
    # segment, not the whole bench. In --quick mode stay in-process (CI speed).
    if args.quick:
        from open_simulator_tpu.utils.platform import (
            enable_compilation_cache,
            ensure_platform,
            install_compile_listener,
        )

        if "headline" in done_segments:
            print(
                "bench segment headline: replayed from journal",
                file=sys.stderr, flush=True,
            )
            result = dict(done_segments["headline"])
        else:
            ensure_platform()
            enable_compilation_cache()
            install_compile_listener()
            result = _run_headline(args.pods, args.nodes)
            if journal is not None:
                journal.append("segment", segment="headline", result=result)
        # The serial-vs-batched capacity sweep is cheap enough to keep in
        # the quick profile, and the speedup ratio is only meaningful when
        # both paths run in the same process (shared caches, same backend).
        if "capacity_sweep_batched" in done_segments:
            print(
                "bench segment capacity_sweep_batched: replayed from journal",
                file=sys.stderr, flush=True,
            )
            sweep = dict(done_segments["capacity_sweep_batched"])
        else:
            sweep = config_capacity_sweep()
            if journal is not None and "error" not in sweep:
                journal.append(
                    "segment", segment="capacity_sweep_batched", result=sweep
                )
        result["capacity_sweep_batched"] = sweep
        result["capacity_sweep_speedup"] = sweep.get("capacity_sweep_speedup")
        # The compile-lifecycle headline stays in the quick profile: cold
        # wall (warmup pays every compile) vs warm wall (the same sweep,
        # zero cold compiles asserted — warm start excludes all compile
        # time by construction).
        if "warm_start_100k" in done_segments:
            print(
                "bench segment warm_start_100k: replayed from journal",
                file=sys.stderr, flush=True,
            )
            warm = dict(done_segments["warm_start_100k"])
        else:
            warm = config_warm_start()
            if journal is not None and "error" not in warm:
                journal.append(
                    "segment", segment="warm_start_100k", result=warm
                )
        result["warm_start_100k"] = warm
        result["cold_wall_s"] = warm.get("cold_wall_s")
        result["warm_wall_s"] = warm.get("warm_wall_s")
        result.update(backend_info)
        from open_simulator_tpu.utils.metrics import COMPILE_CACHE, REGISTRY

        result["metrics"] = REGISTRY.snapshot()
        result["compiles"] = int(COMPILE_CACHE.value(event="backend_compile"))
        result["watchdog_fired"] = _watchdog_fired_total()
        if journal is not None:
            journal.append("run_end", outcome="ok")
            from open_simulator_tpu.durable import atomic_write

            atomic_write(
                os.path.join(run_dir, "bench.json"),
                json.dumps(result, sort_keys=True) + "\n",
            )
        print(json.dumps(result))
        return 0

    if (
        platform != "cpu"
        and "fallback" not in backend_info
        and not backend_info.get("backend_probe", "").startswith("cpu")
    ):
        # Device canary: a miniature headline under a tight deadline. The
        # round-5 tunnel failure mode is init-succeeds-but-programs-wedge
        # (backend probe passed in 10 s, then the 100k headline hung its
        # full 1200 s deadline); a 5-minute canary converts that 20-minute
        # burn into a fast, labeled CPU fallback — and its pods/s is a real
        # small-scale device number even when the full headline later fails.
        canary = run_seg("canary", 2_000, 200, platform)
        backend_info["canary"] = canary
        if "error" in canary:
            platform = _fall_back_to_cpu("canary", canary["error"])
        elif (
            "TPU" in str(canary.get("device", "")) and args.pods > 20_000
        ):
            # The canary proved the device on small shapes; bank a mid-size
            # device number BEFORE risking the full headline — if the 100k
            # program wedges the tunnel (observed round 5), this is the
            # at-scale TPU evidence that survives in the JSON. Skipped when
            # the requested headline isn't actually bigger than the mid.
            mid = run_seg("headline_mid", 20_000, 2_000, platform)
            backend_info["headline_mid"] = mid
            if "error" in mid:
                # mid-size already wedges: the full headline has no chance
                # and the tunnel likely needs recovery — go straight to CPU
                # for the official metric, keeping the canary as evidence.
                platform = _fall_back_to_cpu("headline_mid", mid["error"])

    result = run_seg("headline", args.pods, args.nodes, platform)
    if "error" in result and platform != "cpu":
        # The TPU died mid-headline: re-measure on CPU so the round still
        # records a real number, clearly labeled.
        platform = _fall_back_to_cpu("headline", result["error"])
        result = run_seg("headline", args.pods, args.nodes, platform)
    result.update(backend_info)
    print(f"headline: {json.dumps(result)}", file=sys.stderr, flush=True)

    # End-to-end BASELINE configs (through simulate()/run_apply/plan_capacity;
    # wall includes expansion, validation, encode, compile and decode).
    # Progress lines go to stderr; the single stdout JSON line stays the
    # driver contract, carrying the per-config results under "configs".
    if wanted:
        # Every config is CPU-feasible since the domain-merge fast path and
        # the capacity-search expansion cache landed (spread_aff 8.7 s, the
        # 100k plan 44 s on CPU) — and each segment's deadline bounds the
        # damage if that ever regresses, so nothing is skipped on a CPU
        # backend anymore.
        on_cpu = (
            platform == "cpu"
            or backend_info.get("fallback") == "cpu"
            or backend_info.get("backend_probe", "").startswith("cpu")
        )
        configs_out = {}
        for name in wanted:
            print(f"bench config {name}...", file=sys.stderr, flush=True)
            configs_out[name] = run_seg(name, args.pods, args.nodes, platform)
            # stamp the platform each config ACTUALLY ran on: after a
            # mid-bench tunnel wedge flips to cpu, individual numbers must
            # not be mistakable for TPU ones when read in isolation
            configs_out[name].setdefault(
                "platform", platform or "(default)"
            )
            print(
                f"bench config {name}: {json.dumps(configs_out[name])}",
                file=sys.stderr, flush=True,
            )
            if "timeout" in str(configs_out[name].get("error", "")) and not on_cpu:
                # One wedge usually means the tunnel is gone — re-probe before
                # burning every remaining segment's deadline on it. This does
                # NOT touch backend_info: the headline (already merged above)
                # was measured before the wedge and stays labeled as such.
                ok, msg = _probe_backend(platform, 60.0)
                if not ok:
                    on_cpu = True
                    result["configs_fallback"] = {
                        "after": name,
                        "reason": f"tunnel wedged mid-bench ({msg})",
                    }
                    platform = "cpu"
        result["configs"] = configs_out

    # Honest top-level provenance: `device` already names what the headline
    # actually ran on (_run_headline stamps it in-child); watchdog_fired
    # makes a deadline-triggered degradation visible in the JSON itself.
    result["watchdog_fired"] = _watchdog_fired_total()
    if journal is not None:
        journal.append("run_end", outcome="ok")
        from open_simulator_tpu.durable import atomic_write

        atomic_write(
            os.path.join(run_dir, "bench.json"),
            json.dumps(result, sort_keys=True) + "\n",
        )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
