"""Headline benchmark: the BASELINE.json north-star config.

Capacity-plans a 100k-pod workload onto a 10k-node simulated cluster — the
full pod×node Filter/Score/Select sweep with sequential commit — on one TPU
chip, and reports scheduling throughput.

Baseline: the reference publishes no numbers (BASELINE.md); the driver-defined
target is 100k pods onto 10k nodes in <60s on a v5e-8, i.e. 1667 pods/s.
vs_baseline is throughput relative to that target (>1.0 beats it).

Output: one JSON line, e.g.
  {"metric": "schedule_100k_pods_10k_nodes", "value": 2560.0,
   "unit": "pods/s", "vs_baseline": 1.54, ...}
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

TARGET_PODS_PER_SEC = 100_000 / 60.0  # driver north star


def _probe_backend(platform: str, timeout_s: float) -> tuple[bool, str]:
    """Check in a child process (bounded, killable) that `platform` can
    actually initialize. The TPU tunnel ("axon") is known to hang during
    backend init (round-1 BENCH was rc:1, MULTICHIP hung to rc:124), and a
    hung in-process init cannot be interrupted — hence the subprocess."""
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
    else:
        env.pop("JAX_PLATFORMS", None)
    code = (
        "import jax; d = jax.devices(); "
        "import jax.numpy as jnp; jnp.zeros(8).block_until_ready(); "
        "print(d[0].platform, len(d))"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            env=env, timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init timed out after {timeout_s:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return False, (tail[-1] if tail else f"rc={r.returncode}")
    return True, r.stdout.strip()


def _select_backend(attempts: int = 2, timeout_s: float = 60.0) -> dict:
    """Pick a working JAX platform before importing jax in this process.

    Tries the environment's preset platform (the TPU tunnel) with bounded
    retries; on failure falls back to CPU, clearly labeled in the output.
    """
    preset = os.environ.get("JAX_PLATFORMS", "")
    info = {"requested_platform": preset or "(default)"}
    last_err = ""
    for attempt in range(attempts):
        ok, msg = _probe_backend(preset, timeout_s)
        if ok:
            info["backend_probe"] = msg
            return info
        last_err = msg
        if attempt + 1 < attempts:
            time.sleep(2.0 * (attempt + 1))
    os.environ["JAX_PLATFORMS"] = "cpu"
    info["fallback"] = "cpu"
    info["fallback_reason"] = last_err
    return info


def build_state(n_nodes: int, n_pods: int):
    from open_simulator_tpu.core.objects import Node, Pod
    from open_simulator_tpu.ops.encode import (
        Encoder,
        encode_nodes,
        encode_pods,
        initial_selector_counts,
    )
    from open_simulator_tpu.ops.state import (
        carry_from_table,
        node_static_from_table,
        pod_rows_from_batch,
    )
    from open_simulator_tpu.ops.tile import tile_pod_batch

    rng = np.random.default_rng(0)
    nodes = []
    for i in range(n_nodes):
        taints = (
            [{"key": "dedicated", "value": "batch", "effect": "NoSchedule"}]
            if i % 10 == 0
            else []
        )
        nodes.append(
            Node.from_dict(
                {
                    "metadata": {
                        "name": f"node-{i}",
                        "labels": {
                            "kubernetes.io/hostname": f"node-{i}",
                            "topology.kubernetes.io/zone": f"az-{i % 3}",
                            "node.kubernetes.io/instance-type": ["m5.4x", "m5.8x", "c5.9x"][i % 3],
                        },
                    },
                    "spec": {"taints": taints},
                    "status": {
                        "allocatable": {
                            "cpu": str(16 + 16 * int(rng.integers(0, 3))),
                            "memory": f"{32 + 32 * int(rng.integers(0, 3))}Gi",
                            "pods": "110",
                        }
                    },
                }
            )
        )

    # Workload templates: a service with zone spread, a tolerating batch job,
    # a selector-pinned cache, a plain web tier.
    templates = []
    tmpl_specs = [
        dict(
            cpu="500m", mem="512Mi", labels={"app": "web"},
            spread=True, tol=False, sel=None,
        ),
        dict(
            cpu="2", mem="4Gi", labels={"app": "batch"},
            spread=False, tol=True, sel=None,
        ),
        dict(
            cpu="1", mem="2Gi", labels={"app": "cache"},
            spread=False, tol=False, sel={"node.kubernetes.io/instance-type": "m5.8x"},
        ),
        dict(
            cpu="250m", mem="256Mi", labels={"app": "sidecar"},
            spread=True, tol=False, sel=None,
        ),
    ]
    for t, s in enumerate(tmpl_specs):
        spec = {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": s["cpu"], "memory": s["mem"]}}}
            ]
        }
        if s["spread"]:
            spec["topologySpreadConstraints"] = [
                {
                    "maxSkew": 50,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "ScheduleAnyway",
                    "labelSelector": {"matchLabels": s["labels"]},
                }
            ]
        if s["tol"]:
            spec["tolerations"] = [
                {"key": "dedicated", "operator": "Equal", "value": "batch",
                 "effect": "NoSchedule"}
            ]
        if s["sel"]:
            spec["nodeSelector"] = s["sel"]
        templates.append(
            Pod.from_dict(
                {
                    "metadata": {
                        "name": f"tpl-{t}", "namespace": "bench", "labels": s["labels"],
                    },
                    "spec": spec,
                }
            )
        )

    share = n_pods // len(templates)
    counts = [share] * len(templates)
    counts[0] += n_pods - share * len(templates)

    enc = Encoder()
    enc.register_pods(templates)
    table = encode_nodes(enc, nodes)
    tmpl_batch = encode_pods(enc, templates)
    batch = tile_pod_batch(tmpl_batch, counts)
    ns = node_static_from_table(enc, table)
    carry = carry_from_table(table, initial_selector_counts(enc, table, []))
    return ns, carry, batch


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--pods", type=int, default=100_000)
    parser.add_argument("--nodes", type=int, default=10_000)
    parser.add_argument("--quick", action="store_true", help="tiny smoke sizes")
    args = parser.parse_args()
    if args.quick:
        args.pods, args.nodes = 2_000, 200

    backend_info = _select_backend()

    import jax

    if backend_info.get("fallback") == "cpu":
        from open_simulator_tpu.utils.platform import ensure_platform

        ensure_platform()
    from open_simulator_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()

    from open_simulator_tpu.ops.fast import schedule_batch_fast
    from open_simulator_tpu.ops.kernels import weights_array

    t_enc0 = time.time()
    ns, carry, batch = build_state(args.nodes, args.pods)
    t_enc = time.time() - t_enc0
    w = weights_array()

    # Warm up with one full untimed pass (same shapes => same executables),
    # then one timed pass. The grouped scheduler's per-group chunking
    # (schedule_batch_grouped max_group_chunk) bounds each device program to a
    # few seconds — a single 100k-step scan trips the TPU worker's watchdog.
    t0 = time.time()
    schedule_batch_fast(ns, carry, batch, w)
    compile_s = time.time() - t0

    t1 = time.time()
    _, placed, *_ = schedule_batch_fast(ns, carry, batch, w)
    run = time.time() - t1
    scheduled = int((placed >= 0).sum())
    pods_per_sec = args.pods / run
    result = {
        "metric": f"schedule_{args.pods//1000}k_pods_{args.nodes//1000}k_nodes",
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / TARGET_PODS_PER_SEC, 3),
        "wall_s": round(run, 2),
        "compile_s": round(compile_s, 2),
        "encode_s": round(t_enc, 2),
        "scheduled": scheduled,
        "pods": args.pods,
        "nodes": args.nodes,
        "device": str(jax.devices()[0]),
    }
    result.update(backend_info)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
