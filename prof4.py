"""Phase timing at 10k nodes + step-floor at N=10016 (scratch)."""
import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import build_state
from open_simulator_tpu.ops import fast
from open_simulator_tpu.ops.grouped import group_runs
from open_simulator_tpu.ops.kernels import weights_array
from open_simulator_tpu.ops.state import pod_rows_from_batch

# --- floor test at N=10016 ---
N, G = 10016, 2048
x0 = jnp.zeros(N, jnp.float32)
sc = jnp.arange(N, dtype=jnp.float32) % 97.0


def timeit(fn, *args):
    jax.block_until_ready(fn(*args))
    t0 = time.time()
    jax.block_until_ready(fn(*args))
    return time.time() - t0


@jax.jit
def floor_body(x0):
    def step(x, i):
        s = x + sc
        lo = jnp.min(jnp.where(s > 0, s, jnp.inf))
        hi = jnp.max(s)
        n = jnp.argmax((s - lo) / jnp.maximum(hi - lo, 1e-9))
        return x - (jnp.arange(N) == n) * 0.5, n
    return jax.lax.scan(step, x0, jnp.arange(G))


t = timeit(floor_body, x0)
print(f"floor (minmax+argmax) N=10016: {1e6*t/G:.1f} us/step")

# --- phase timing of the current fast path at 10k nodes, 20k pods ---
ns, carry0, batch = build_state(10000, 20000)
w = weights_array()
rows_all = pod_rows_from_batch(batch)
NN = ns.valid.shape[0]
valid_np = np.asarray(ns.valid)

for rep in range(2):
    carry = carry0
    t_all0 = time.time()
    t0 = time.time()
    runs = group_runs(batch)
    t_hash = time.time() - t0
    t0 = time.time()
    free_entry = np.asarray(carry.free)
    t_tl = time.time() - t0
    t_traj = t_light = t_np = t_exit = 0.0
    for start, length in runs:
        row = jax.tree.map(lambda a: a[start], rows_all)
        j_need = fast._traj_len(free_entry, valid_np, batch.req[start], length)
        j_steps = fast._bucket_j(j_need)
        t0 = time.time()
        out = fast.build_trajectory(ns, carry, row, w, j_steps)
        jax.block_until_ready(out[0].packed)
        t_traj += time.time() - t0
        traj, s_ok, s_ff, s_sc, na_ok = out
        x = jnp.zeros(NN, jnp.int32)
        cur = traj.packed[:, 0, :]
        chunks = []
        done = 0
        n_steps = 0
        t0 = time.time()
        while done < length:
            n = min(length - done, 16384)
            g = fast._bucket_light(n)
            n_steps += g
            x, cur, nodes, jidxs = fast.light_scan(
                ns, traj, carry, row, s_ok, s_sc, na_ok, w,
                x, cur, jnp.int32(done), g, jnp.int32(length),
            )
            chunks.append((n, nodes, jidxs))
            done += n
        jax.block_until_ready(x)
        t_light += time.time() - t0
        t0 = time.time()
        nodes_d = jnp.concatenate([c[1][: c[0]] for c in chunks])
        jidx_d = jnp.concatenate([c[2][: c[0]] for c in chunks])
        take_d, vg_d, dev_d = fast.gather_takes(traj, nodes_d, jidx_d)
        _ = np.asarray(nodes_d), np.asarray(take_d)
        t_np += time.time() - t0
        t0 = time.time()
        carry = fast.exit_carry(ns, carry, row, traj, x)
        jax.block_until_ready(carry.free)
        t_exit += time.time() - t0
    wall = time.time() - t_all0
    print(
        f"rep{rep}: wall {wall:.2f}s hash {t_hash:.2f} free_xfer {t_tl:.2f} "
        f"traj {t_traj:.2f} light {t_light:.2f} ({1e6*t_light/20000:.0f}us/pod) "
        f"np {t_np:.2f} exit {t_exit:.2f}"
    )
