#!/bin/bash
# Wide oracle-parity fuzz soak, chunked across pytest processes.
#
# Why chunked: after roughly 40-55 randomized fuzz workloads in ONE
# process (content-dependent: what matters is the cumulative count of
# DISTINCT compiled programs), XLA:CPU segfaults inside
# backend_compile_and_load while compiling a fresh program (reproduced with
# seeds 300-379 at the 55th test and seeds 490-529 at the 41st; unaffected
# by a 64 MiB stack; every crashing seed passes alone and smaller chunks of
# the same ranges pass). This is an upstream compiler-process limitation,
# not an engine bug — the engine's own long-lived surface (server mode)
# compiles a bounded shape family per cluster, far below this churn.
#
# Usage: scripts/fuzz_soak.sh [START END [CHUNK]]   (defaults 300 379 20)
set -u
START=${1:-300}; END=${2:-379}; CHUNK=${3:-20}
cd "$(dirname "$0")/.."
fail=0
for ((a = START; a <= END; a += CHUNK)); do
    b=$((a + CHUNK - 1)); ((b > END)) && b=$END
    echo "== seeds $a-$b =="
    OSIM_FUZZ_SEEDS="$a-$b" python -m pytest tests/test_fuzz_parity.py -q || fail=1
done
exit $fail
