#!/usr/bin/env python3
"""CI load smoke for the overload-safe serving path (docs/serving.md).

Bursts 32 concurrent requests at an embedded server with admission queue
depth 4 and asserts the ISSUE-7 overload contract end to end:

  1. every request gets a DEFINITE answer — 200 or 429-with-Retry-After,
     never a 5xx, a hang, or a silent drop;
  2. the shed metrics match the arithmetic exactly:
     osim_requests_shed_total == number of non-200 responses, and
     osim_requests_dropped_total == 0;
  3. a request whose deadline has already expired is shed at dequeue and
     NEVER enters a simulate call (proved with a recording wrapper around
     _simulate_request).

Runs on CPU in-process; exits nonzero with a labeled failure otherwise.
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from open_simulator_tpu.server import server as server_mod  # noqa: E402
from open_simulator_tpu.utils import metrics  # noqa: E402

BURST = 32
DEPTH = 4


def _body(tag):
    res = {"cpu": "32", "memory": "64Gi", "pods": "110"}
    return {
        "tag": tag,
        "cluster": {
            "objects": [
                {
                    "kind": "Node",
                    "metadata": {
                        "name": f"n-{i}",
                        "labels": {"kubernetes.io/hostname": f"n-{i}"},
                    },
                    "status": {
                        "allocatable": dict(res), "capacity": dict(res),
                    },
                }
                for i in range(10)
            ]
        },
        "apps": [
            {
                "name": "web",
                "objects": [
                    {
                        "kind": "Deployment",
                        "metadata": {"name": "web", "namespace": "smoke"},
                        "spec": {
                            "replicas": 20,
                            "template": {
                                "metadata": {"labels": {"app": "web"}},
                                "spec": {
                                    "containers": [
                                        {
                                            "name": "c",
                                            "image": "img",
                                            "resources": {
                                                "requests": {
                                                    "cpu": "500m",
                                                    "memory": "1Gi",
                                                }
                                            },
                                        }
                                    ]
                                },
                            },
                        },
                    }
                ],
            }
        ],
    }


def _post(port, body, headers=None, timeout=60.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/deploy-apps",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"{}")


def fail(msg):
    print(f"load smoke FAILED: {msg}")
    sys.exit(1)


def main():
    srv = server_mod.make_server(0, queue_depth=DEPTH, coalesce_ms=0.0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    # Recording + throttling wrapper: `seen` proves which request bodies
    # actually entered simulate; the delay keeps the worker busy long
    # enough that a 32-burst genuinely overflows a depth-4 queue.
    real_simulate = server_mod._simulate_request
    seen = []
    blocker_started = threading.Event()

    def recording(body):
        seen.append(body.get("tag"))
        if body.get("tag") == "blocker":
            blocker_started.set()
            time.sleep(0.2)
        else:
            time.sleep(0.05)
        return real_simulate(body)

    server_mod._simulate_request = recording

    # Warm-up outside the measured burst (first simulate pays compiles).
    code, _, _ = _post(port, _body("warmup"))
    if code != 200:
        fail(f"warm-up request returned {code}")

    shed0 = sum(
        s["value"] for s in metrics.REQUESTS_SHED.snapshot()["samples"]
    )
    dropped0 = metrics.REQUESTS_DROPPED.value()

    # --- 1+2: the 32-burst at depth 4 -------------------------------------
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(BURST)

    def client(i):
        barrier.wait()
        res = _post(port, _body(f"burst-{i}"))  # distinct bodies: no coalesce
        with lock:
            results.append(res)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(BURST)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120.0)

    if len(results) != BURST:
        fail(f"only {len(results)}/{BURST} requests answered (hang/drop)")
    codes = [code for code, _, _ in results]
    bad = sorted({c for c in codes if c not in (200, 429)})
    if bad:
        fail(f"non-200/429 responses in burst: {bad} (zero 5xx required)")
    n_ok = codes.count(200)
    n_shed = codes.count(429)
    for code, headers, payload in results:
        if code == 429:
            if int(headers.get("Retry-After", "0")) < 1:
                fail(f"429 without a usable Retry-After: {headers}")
            if payload.get("reason") not in ("queue_full", "deadline"):
                fail(f"429 with unexpected reason: {payload}")

    shed_metric = (
        sum(s["value"] for s in metrics.REQUESTS_SHED.snapshot()["samples"])
        - shed0
    )
    if shed_metric != n_shed:
        fail(
            f"osim_requests_shed_total moved by {shed_metric} but "
            f"{n_shed} requests were shed"
        )
    if metrics.REQUESTS_DROPPED.value() != dropped0:
        fail("osim_requests_dropped_total moved: a request was dropped")
    if n_ok + n_shed != BURST:
        fail(f"accounting mismatch: {n_ok} ok + {n_shed} shed != {BURST}")
    print(
        f"burst OK: {n_ok}x200 + {n_shed}x429 = {BURST}, "
        f"shed metric matches, zero 5xx, zero drops"
    )

    # --- 3: expired deadline never enters simulate ------------------------
    seen.clear()
    doomed_result = []

    def doomed_client():
        doomed_result.append(
            _post(
                port, _body("doomed"), headers={"X-Osim-Deadline-Ms": "1"}
            )
        )

    blocker = threading.Thread(
        target=lambda: _post(port, _body("blocker"))
    )
    blocker.start()
    if not blocker_started.wait(30.0):
        fail("blocker request never entered simulate")
    # the worker is now busy for 200 ms; a 1 ms deadline queued behind it
    # must expire while waiting and be shed at dequeue
    doomed = threading.Thread(target=doomed_client)
    doomed.start()
    doomed.join(60.0)
    blocker.join(60.0)
    if not doomed_result:
        fail("deadline request never answered")
    code, _, payload = doomed_result[0]
    if code != 429 or payload.get("reason") != "deadline":
        fail(f"expired deadline got {code} {payload}, wanted 429/deadline")
    if "doomed" in seen:
        fail("expired-deadline request ENTERED simulate")
    print("deadline OK: expired request shed at dequeue, simulate untouched")

    srv.shutdown()
    srv.server_close()
    print(
        json.dumps(
            {
                "burst": BURST,
                "queue_depth": DEPTH,
                "ok": n_ok,
                "shed": n_shed,
                "dropped": 0,
            }
        )
    )
    print("load smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
