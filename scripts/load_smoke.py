#!/usr/bin/env python3
"""CI load smoke for the overload-safe serving path (docs/serving.md).

Bursts 32 concurrent requests at an embedded server with admission queue
depth 4 and asserts the ISSUE-7 overload contract end to end:

  1. every request gets a DEFINITE answer — 200 or 429-with-Retry-After,
     never a 5xx, a hang, or a silent drop;
  2. the shed metrics match the arithmetic exactly:
     osim_requests_shed_total == number of non-200 responses, and
     osim_requests_dropped_total == 0;
  3. a request whose deadline has already expired is shed at dequeue and
     NEVER enters a simulate call (proved with a recording wrapper around
     _simulate_request);
  4. (continuous-batching loop) a closed-loop saturation burst against the
     real simulate path answers every request 200-or-429 with the same
     exact shed arithmetic, and the sustained req/s lands in the CI job
     summary when GITHUB_STEP_SUMMARY is set;
  5. (async jobs) POST /v1/jobs runs a journaled capacity sweep to
     completion, GET /v1/jobs/<id> streams its sweep progress records,
     and a resume re-POST replays the journal to a byte-identical
     outcome.json instead of recomputing;
  6. (extender wave) a 500-pod apply through the wave-pipelined extender
     engine against an example HTTP extender server answers every filter
     and prioritize round trip 200 (zero 5xx served, zero error/circuit
     outcomes recorded), and placements are identical to a rerun under
     the escape hatch `OSIM_EXTENDER_WAVE=0` (the serial per-pod loop;
     `OSIM_EXTENDER_KEEPALIVE=0` further reverts the transport — see
     docs/performance.md).

Runs on CPU in-process; exits nonzero with a labeled failure otherwise.
"""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from open_simulator_tpu.server import server as server_mod  # noqa: E402
from open_simulator_tpu.utils import metrics  # noqa: E402

BURST = 32
DEPTH = 4
SAT_CLIENTS = 6
SAT_ROUNDS = 5


def _body(tag):
    res = {"cpu": "32", "memory": "64Gi", "pods": "110"}
    return {
        "tag": tag,
        "cluster": {
            "objects": [
                {
                    "kind": "Node",
                    "metadata": {
                        "name": f"n-{i}",
                        "labels": {"kubernetes.io/hostname": f"n-{i}"},
                    },
                    "status": {
                        "allocatable": dict(res), "capacity": dict(res),
                    },
                }
                for i in range(10)
            ]
        },
        "apps": [
            {
                "name": "web",
                "objects": [
                    {
                        "kind": "Deployment",
                        "metadata": {"name": "web", "namespace": "smoke"},
                        "spec": {
                            "replicas": 20,
                            "template": {
                                "metadata": {"labels": {"app": "web"}},
                                "spec": {
                                    "containers": [
                                        {
                                            "name": "c",
                                            "image": "img",
                                            "resources": {
                                                "requests": {
                                                    "cpu": "500m",
                                                    "memory": "1Gi",
                                                }
                                            },
                                        }
                                    ]
                                },
                            },
                        },
                    }
                ],
            }
        ],
    }


def _post(port, body, headers=None, timeout=60.0, path="/api/deploy-apps"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"{}")


def _get(port, path, timeout=30.0):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def fail(msg):
    print(f"load smoke FAILED: {msg}")
    sys.exit(1)


def _closed_loop(port, bodies, rounds):
    """Closed-loop clients: each posts its body `rounds` times back to
    back, firing the next request the moment the previous answer lands.
    Returns the flat [(code, headers)] across all clients."""
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(len(bodies))

    def client(body):
        barrier.wait()
        mine = []
        for _ in range(rounds):
            code, headers, _ = _post(port, body, timeout=120.0)
            mine.append((code, headers))
        with lock:
            results.extend(mine)

    threads = [
        threading.Thread(target=client, args=(b,)) for b in bodies
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300.0)
    return results


def _saturation(n_clients, rounds):
    """Section 4: sustained closed-loop load against the real simulate
    path (no recording wrapper, no artificial delays). Bodies differ only
    in score weights, so the scheduler loop packs them as lanes of one
    batched device call; the overload contract (200-or-429, exact shed
    arithmetic, zero drops) must hold at full speed."""
    srv = server_mod.make_server(0, queue_depth=16, pack_window_ms=50.0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = _body("sat")
    base.pop("tag")
    bodies = [
        dict(base, weights={"least_allocated": 50 + i})
        for i in range(n_clients)
    ]
    try:
        warm = _closed_loop(port, bodies, 1)
        warm_bad = sorted({c for c, _ in warm if c != 200})
        if warm_bad:
            fail(f"saturation warm-up returned {warm_bad}")
        shed0 = sum(
            s["value"] for s in metrics.REQUESTS_SHED.snapshot()["samples"]
        )
        dropped0 = metrics.REQUESTS_DROPPED.value()

        t0 = time.time()
        results = _closed_loop(port, bodies, rounds)
        wall = time.time() - t0

        want = n_clients * rounds
        if len(results) != want:
            fail(f"saturation: {len(results)}/{want} answered (hang/drop)")
        codes = [c for c, _ in results]
        bad = sorted({c for c in codes if c not in (200, 429)})
        if bad:
            fail(f"saturation: non-200/429 responses {bad} (zero 5xx)")
        n_ok = codes.count(200)
        n_shed = codes.count(429)
        for code, headers in results:
            if code == 429 and int(headers.get("Retry-After", "0")) < 1:
                fail(f"saturation: 429 without usable Retry-After {headers}")
        shed_metric = (
            sum(s["value"] for s in metrics.REQUESTS_SHED.snapshot()["samples"])
            - shed0
        )
        if shed_metric != n_shed:
            fail(
                f"saturation: shed metric moved {shed_metric} != "
                f"{n_shed} shed responses"
            )
        if metrics.REQUESTS_DROPPED.value() != dropped0:
            fail("saturation: a request was dropped")
        req_s = round(n_ok / wall, 1) if wall > 0 else 0.0
        print(
            f"saturation OK: {n_ok}x200 + {n_shed}x429 = {want} over "
            f"{round(wall, 2)}s -> {req_s} req/s sustained"
        )
        return {
            "clients": n_clients,
            "rounds": rounds,
            "ok": n_ok,
            "shed": n_shed,
            "wall_s": round(wall, 2),
            "req_s": req_s,
        }
    finally:
        srv.shutdown()
        srv.server_close()


def _jobs_smoke():
    """Section 5: an async capacity job over /v1/jobs. The sweep journals
    its phases; GET streams them as progress; a resume re-POST replays the
    journal and must land a byte-identical outcome.json (the snapshot is
    deliberately timestamp-free) instead of recomputing."""
    tmp = tempfile.mkdtemp(prefix="osim-jobs-smoke-")
    prior = os.environ.get("OSIM_RUNS_DIR")
    os.environ["OSIM_RUNS_DIR"] = tmp
    srv = server_mod.make_server(0, queue_depth=DEPTH, pack_window_ms=0.0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    res = {"cpu": "4", "memory": "8Gi", "pods": "110"}
    body = {
        "kind": "capacity",
        "job": "smoke-capacity",
        "cluster": {
            "objects": [
                {
                    "kind": "Node",
                    "metadata": {
                        "name": f"cap-{i}",
                        "labels": {"kubernetes.io/hostname": f"cap-{i}"},
                    },
                    "status": {
                        "allocatable": dict(res), "capacity": dict(res),
                    },
                }
                for i in range(2)
            ]
        },
        # 12 cpu of pods on 8 cpu of nodes: the sweep MUST add capacity,
        # so at least one ladder phase lands in the journal as progress
        "apps": [
            {
                "name": "web",
                "objects": [
                    {
                        "kind": "Deployment",
                        "metadata": {"name": "web", "namespace": "smoke"},
                        "spec": {
                            "replicas": 12,
                            "template": {
                                "metadata": {"labels": {"app": "web"}},
                                "spec": {
                                    "containers": [
                                        {
                                            "name": "c",
                                            "image": "img",
                                            "resources": {
                                                "requests": {
                                                    "cpu": "1",
                                                    "memory": "512Mi",
                                                }
                                            },
                                        }
                                    ]
                                },
                            },
                        },
                    }
                ],
            }
        ],
        "newNode": {
            "kind": "Node",
            "metadata": {
                "name": "cap-new",
                "labels": {"kubernetes.io/hostname": "cap-new"},
            },
            "status": {
                "allocatable": {
                    "cpu": "16", "memory": "32Gi", "pods": "110",
                },
                "capacity": {
                    "cpu": "16", "memory": "32Gi", "pods": "110",
                },
            },
        },
    }

    def poll_to_completion():
        deadline = time.time() + 120.0
        after, status, progress, last = -1, None, [], {}
        while time.time() < deadline:
            code, st = _get(port, f"/v1/jobs/smoke-capacity?after={after}")
            if code != 200:
                fail(f"job status returned {code}: {st}")
            progress.extend(st.get("progress") or [])
            after = st.get("next_after", after)
            status, last = st["status"], st
            if status in ("completed", "failed", "interrupted"):
                break
            time.sleep(0.2)
        return status, progress, last

    try:
        code, _, payload = _post(port, body, path="/v1/jobs")
        if code != 202:
            fail(f"job submit returned {code}: {payload}")
        status, progress, last = poll_to_completion()
        if status != "completed":
            fail(f"job finished as {status!r}: {last}")
        if not progress:
            fail("job streamed NO sweep progress records")
        outcome = last.get("outcome") or {}
        if outcome.get("outcome") != "ok":
            fail(f"capacity job outcome not ok: {outcome}")
        if outcome.get("nodes_added", 0) < 1:
            fail(f"workload was sized to need capacity: {outcome}")
        outcome_path = os.path.join(tmp, "smoke-capacity", "outcome.json")
        with open(outcome_path, "rb") as fh:
            first_bytes = fh.read()

        # resume re-POST: replays the committed journal, no recompute
        code, _, payload = _post(
            port, dict(body, resume=True), path="/v1/jobs"
        )
        if code != 202:
            fail(f"job resume returned {code}: {payload}")
        status, _, last = poll_to_completion()
        if status != "completed":
            fail(f"job resume finished as {status!r}: {last}")
        with open(outcome_path, "rb") as fh:
            if fh.read() != first_bytes:
                fail("resume replay changed outcome.json (recomputed?)")

        code, listing = _get(port, "/v1/jobs")
        names = [j.get("name") for j in listing.get("jobs", [])]
        if code != 200 or "smoke-capacity" not in names:
            fail(f"/v1/jobs listing missing the job: {code} {names}")
        print(
            f"jobs OK: capacity sweep completed with "
            f"{len(progress)} progress records, "
            f"nodes_added={outcome['nodes_added']}, resume byte-identical"
        )
        return {
            "job": "smoke-capacity",
            "sweep_records": len(progress),
            "nodes_added": outcome["nodes_added"],
            "resume": "byte-identical",
        }
    finally:
        srv.shutdown()
        srv.server_close()
        if prior is None:
            os.environ.pop("OSIM_RUNS_DIR", None)
        else:
            os.environ["OSIM_RUNS_DIR"] = prior


def _extender_smoke(n_pods=500, n_nodes=50):
    """Section 6: the wave-pipelined extender engine under load. An example
    scheduler-extender server (pass-through filter + prioritize, the shape
    a real deployment would run out of process) serves a 500-pod apply
    through the default wave pipeline, then the same apply reruns under
    the escape hatch `OSIM_EXTENDER_WAVE=0` (serial per-pod loop). The
    server must have answered every round trip 200 — zero 5xx — the
    engine must have recorded zero error/circuit_open outcomes, and the
    two placement multisets must be identical."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        simulate,
    )
    from open_simulator_tpu.core.objects import Node
    from open_simulator_tpu.models.profiles import ExtenderConfig
    from open_simulator_tpu.utils import httppool

    served = []  # status codes the example server answered with

    class ExampleExtender(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            body = json.loads(
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                or b"{}"
            )
            names = body.get("NodeNames") or []
            if self.path.endswith("/filter"):
                resp = {"NodeNames": names, "FailedNodes": {}, "Error": ""}
            elif self.path.endswith("/prioritize"):
                resp = [{"Host": n, "Score": 5} for n in names]
            else:
                served.append(404)
                self.send_error(404)
                return
            payload = json.dumps(resp).encode()
            served.append(200)
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), ExampleExtender)
    srv.daemon_threads = True
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    cfg = ExtenderConfig(
        url_prefix=f"http://127.0.0.1:{port}",
        filter_verb="filter",
        prioritize_verb="prioritize",
        node_cache_capable=True,
    )
    res = {"cpu": "16", "memory": "64Gi", "pods": "110"}
    deploy = {
        "kind": "Deployment",
        "metadata": {"name": "ext-smoke", "namespace": "smoke"},
        "spec": {
            "replicas": n_pods,
            "template": {
                "metadata": {"labels": {"app": "ext-smoke"}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "img",
                            "resources": {
                                "requests": {"cpu": "500m", "memory": "1Gi"}
                            },
                        }
                    ]
                },
            },
        },
    }

    def err_outcomes():
        return sum(
            s["value"]
            for s in metrics.EXTENDER_REQUESTS.snapshot()["samples"]
            if s["labels"].get("outcome") != "ok"
        )

    def leg(wave_env):
        prior = os.environ.get("OSIM_EXTENDER_WAVE")
        if wave_env is None:
            os.environ.pop("OSIM_EXTENDER_WAVE", None)
        else:
            os.environ["OSIM_EXTENDER_WAVE"] = wave_env
        try:
            nodes = [
                Node.from_dict(
                    {
                        "metadata": {
                            "name": f"ext-n-{i}",
                            "labels": {"kubernetes.io/hostname": f"ext-n-{i}"},
                        },
                        "status": {
                            "allocatable": dict(res), "capacity": dict(res),
                        },
                    }
                )
                for i in range(n_nodes)
            ]
            apps = [AppResource(name="smoke", objects=[dict(deploy)])]
            result = simulate(
                ClusterResource(nodes=nodes), apps, extenders=[cfg]
            )
        finally:
            if prior is None:
                os.environ.pop("OSIM_EXTENDER_WAVE", None)
            else:
                os.environ["OSIM_EXTENDER_WAVE"] = prior
            httppool.reset_pools()  # no warm sockets leak across legs
        placements = sorted(
            (
                p.meta.annotations.get("simon/workload-name", ""),
                st.node.name,
            )
            for st in result.node_status
            for p in st.pods
        )
        return placements, len(result.unscheduled)

    err0 = err_outcomes()
    try:
        wave_placed, wave_unsched = leg(None)  # default: wave pipeline
        serial_placed, _ = leg("0")            # escape hatch: serial loop
    finally:
        srv.shutdown()
        srv.server_close()

    bad = sorted({c for c in served if c != 200})
    if bad:
        fail(f"extender server answered non-200 statuses {bad} (zero 5xx)")
    if err_outcomes() != err0:
        fail("extender engine recorded error/circuit_open outcomes")
    if len(wave_placed) != n_pods or wave_unsched:
        fail(
            f"extender apply placed {len(wave_placed)}/{n_pods} pods "
            f"({wave_unsched} unscheduled)"
        )
    if wave_placed != serial_placed:
        fail(
            "wave placements diverge from OSIM_EXTENDER_WAVE=0 "
            "(escape-hatch byte-identity contract broken)"
        )
    print(
        f"extender OK: {n_pods} pods through the wave pipeline, "
        f"{len(served)} round trips all 200, placements identical to "
        f"OSIM_EXTENDER_WAVE=0"
    )
    return {
        "pods": n_pods,
        "round_trips": len(served),
        "non_200": 0,
        "identical_to_serial": True,
    }


def _publish_summary(n_ok, n_shed, sat, jobs, ext):
    """Append the human-readable result to the CI job summary when GitHub
    provides one (GITHUB_STEP_SUMMARY); silently a no-op locally."""
    path = os.environ.get("GITHUB_STEP_SUMMARY")
    if not path:
        return
    lines = [
        "### Serving load smoke",
        "",
        f"- overload burst: {n_ok}x200 + {n_shed}x429 = {BURST} "
        f"(depth {DEPTH}), zero 5xx, zero drops",
        f"- sustained throughput: **{sat['req_s']} req/s** "
        f"({sat['clients']} closed-loop clients x {sat['rounds']} rounds, "
        f"{sat['ok']}x200 + {sat['shed']}x429)",
        f"- async job `{jobs['job']}`: {jobs['sweep_records']} sweep "
        f"progress records, nodes_added={jobs['nodes_added']}, "
        f"resume replay byte-identical",
        f"- extender wave: {ext['pods']} pods, {ext['round_trips']} "
        f"round trips all 200, placements identical to "
        f"`OSIM_EXTENDER_WAVE=0`",
        "",
    ]
    with open(path, "a") as fh:
        fh.write("\n".join(lines))


def main():
    srv = server_mod.make_server(0, queue_depth=DEPTH, coalesce_ms=0.0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    # Recording + throttling wrapper: `seen` proves which request bodies
    # actually entered simulate; the delay keeps the worker busy long
    # enough that a 32-burst genuinely overflows a depth-4 queue.
    real_simulate = server_mod._simulate_request
    seen = []
    blocker_started = threading.Event()

    def recording(body):
        seen.append(body.get("tag"))
        if body.get("tag") == "blocker":
            blocker_started.set()
            time.sleep(0.2)
        else:
            time.sleep(0.05)
        return real_simulate(body)

    server_mod._simulate_request = recording

    # Warm-up outside the measured burst (first simulate pays compiles).
    code, _, _ = _post(port, _body("warmup"))
    if code != 200:
        fail(f"warm-up request returned {code}")

    shed0 = sum(
        s["value"] for s in metrics.REQUESTS_SHED.snapshot()["samples"]
    )
    dropped0 = metrics.REQUESTS_DROPPED.value()

    # --- 1+2: the 32-burst at depth 4 -------------------------------------
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(BURST)

    def client(i):
        barrier.wait()
        res = _post(port, _body(f"burst-{i}"))  # distinct bodies: no coalesce
        with lock:
            results.append(res)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(BURST)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120.0)

    if len(results) != BURST:
        fail(f"only {len(results)}/{BURST} requests answered (hang/drop)")
    codes = [code for code, _, _ in results]
    bad = sorted({c for c in codes if c not in (200, 429)})
    if bad:
        fail(f"non-200/429 responses in burst: {bad} (zero 5xx required)")
    n_ok = codes.count(200)
    n_shed = codes.count(429)
    for code, headers, payload in results:
        if code == 429:
            if int(headers.get("Retry-After", "0")) < 1:
                fail(f"429 without a usable Retry-After: {headers}")
            if payload.get("reason") not in ("queue_full", "deadline"):
                fail(f"429 with unexpected reason: {payload}")

    shed_metric = (
        sum(s["value"] for s in metrics.REQUESTS_SHED.snapshot()["samples"])
        - shed0
    )
    if shed_metric != n_shed:
        fail(
            f"osim_requests_shed_total moved by {shed_metric} but "
            f"{n_shed} requests were shed"
        )
    if metrics.REQUESTS_DROPPED.value() != dropped0:
        fail("osim_requests_dropped_total moved: a request was dropped")
    if n_ok + n_shed != BURST:
        fail(f"accounting mismatch: {n_ok} ok + {n_shed} shed != {BURST}")
    print(
        f"burst OK: {n_ok}x200 + {n_shed}x429 = {BURST}, "
        f"shed metric matches, zero 5xx, zero drops"
    )

    # --- 3: expired deadline never enters simulate ------------------------
    seen.clear()
    doomed_result = []

    def doomed_client():
        doomed_result.append(
            _post(
                port, _body("doomed"), headers={"X-Osim-Deadline-Ms": "1"}
            )
        )

    blocker = threading.Thread(
        target=lambda: _post(port, _body("blocker"))
    )
    blocker.start()
    if not blocker_started.wait(30.0):
        fail("blocker request never entered simulate")
    # the worker is now busy for 200 ms; a 1 ms deadline queued behind it
    # must expire while waiting and be shed at dequeue
    doomed = threading.Thread(target=doomed_client)
    doomed.start()
    doomed.join(60.0)
    blocker.join(60.0)
    if not doomed_result:
        fail("deadline request never answered")
    code, _, payload = doomed_result[0]
    if code != 429 or payload.get("reason") != "deadline":
        fail(f"expired deadline got {code} {payload}, wanted 429/deadline")
    if "doomed" in seen:
        fail("expired-deadline request ENTERED simulate")
    print("deadline OK: expired request shed at dequeue, simulate untouched")

    srv.shutdown()
    srv.server_close()

    # --- 4: closed-loop saturation against the REAL simulate path ---------
    # No recording wrapper and no artificial delays: this is the sustained
    # req/s the continuous-batching loop actually delivers on this runner,
    # under the same 200-or-429 + exact-shed-arithmetic contract.
    server_mod._simulate_request = real_simulate
    sat = _saturation(SAT_CLIENTS, SAT_ROUNDS)

    # --- 5: async jobs — journaled capacity sweep over /v1/jobs ------------
    jobs = _jobs_smoke()

    # --- 6: extender wave pipeline vs the OSIM_EXTENDER_WAVE=0 hatch -------
    ext = _extender_smoke()

    _publish_summary(n_ok, n_shed, sat, jobs, ext)
    print(
        json.dumps(
            {
                "burst": BURST,
                "queue_depth": DEPTH,
                "ok": n_ok,
                "shed": n_shed,
                "dropped": 0,
                "saturation": sat,
                "jobs": jobs,
                "extender": ext,
            }
        )
    )
    print("load smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
