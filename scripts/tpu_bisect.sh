#!/bin/bash
# Escalating axon-tunnel bisect: localize the wedge, then capture the round.
#
# Round-5 observed failure mode: backend init + a trivial program succeed in
# seconds, then the first real headline program hangs indefinitely; after a
# hang, even init hangs until the server side recovers (minutes to hours).
# This ladder runs ever-larger pieces of the real workload, each in a
# killable child under a deadline, waiting for the tunnel to re-initialize
# after any hang — so one pass tells us the largest thing that works and the
# smallest thing that doesn't, with timestamps, in $OUT.
#
# Usage: scripts/tpu_bisect.sh          (full ladder)
# Results: /tmp/tpu_bisect/NN_<stage>.{out,err}, summary.log
set -u
OUT=/tmp/tpu_bisect
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
SUMMARY="$OUT/summary.log"
. scripts/tpu_lib.sh

run_stage() { # run_stage NN name deadline cmd...
    local nn=$1 name=$2 deadline=$3; shift 3
    note "stage $nn $name (deadline ${deadline}s): $*"
    if timeout "$deadline" "$@" > "$OUT/${nn}_${name}.out" 2> "$OUT/${nn}_${name}.err"; then
        note "stage $nn $name OK: $(grep -v WARNING "$OUT/${nn}_${name}.out" | tail -1 | cut -c1-220)"
        return 0
    fi
    note "stage $nn $name FAILED/HUNG (rc=$?)"
    wait_up || { note "tunnel never recovered; aborting ladder"; exit 1; }
    return 1
}

wait_up || { note "tunnel down at start; aborting"; exit 1; }

run_stage 01 transfer 180 python scripts/axon_probe.py transfer
run_stage 02 scan 240 python scripts/axon_probe.py scan
run_stage 03 sort 300 python scripts/axon_probe.py sort

# Real headline programs at escalating scale. --quick runs in-process on the
# tunnel; larger sizes go through the bench's own killable-segment machinery
# but are invoked here as --segment children directly so each has OUR deadline.
run_stage 04 quick_2k 420 env JAX_PLATFORMS=axon python bench.py --quick --configs none
# --quick goes through _select_backend and silently falls back to CPU when the
# probe fails, still printing pods/s — require an actual TPU device string.
if ! grep -q '"device": "TPU' "$OUT/04_quick_2k.out" 2>/dev/null; then
    # cache interaction check: same tiny headline with the persistent
    # compilation cache disabled
    run_stage 05 quick_2k_nocache 420 env JAX_PLATFORMS=axon OSIM_COMPILE_CACHE= \
        python bench.py --quick --configs none
fi

run_stage 06 mid_10k 600 env JAX_PLATFORMS=axon \
    python bench.py --segment headline --pods 10000 --nodes 1000
run_stage 07 mid_20k 600 env JAX_PLATFORMS=axon \
    python bench.py --segment headline --pods 20000 --nodes 2000
run_stage 08 mid_50k 900 env JAX_PLATFORMS=axon \
    python bench.py --segment headline --pods 50000 --nodes 5000
run_stage 09 full_100k 1200 env JAX_PLATFORMS=axon \
    python bench.py --segment headline --pods 100000 --nodes 10000

# If the full headline only works with smaller device programs, sweep chunk.
PASS_CHUNK=
if ! grep -q pods/s "$OUT/09_full_100k.out" 2>/dev/null; then
    for c in 4096 1024; do
        run_stage "10c$c" "full_100k_chunk$c" 1200 env JAX_PLATFORMS=axon \
            OSIM_HEADLINE_CHUNK=$c \
            python bench.py --segment headline --pods 100000 --nodes 10000
        if grep -q pods/s "$OUT/10c${c}_full_100k_chunk$c.out" 2>/dev/null; then
            PASS_CHUNK=$c
            break
        fi
    done
fi

# Propagate what the ladder just learned: if the default-chunk headline hung
# and only a chunk-sweep size passed, the capture must not re-run the
# known-wedging shape — chain_capture_if_passed pins that chunk.
chain_capture_if_passed "$PASS_CHUNK" \
    "$OUT"/09_full_100k.out "$OUT"/10c*_full_100k_chunk*.out
