# Shared helpers for the TPU tunnel ladder scripts (sourced, not executed).
# Callers must set $OUT (scratch dir) and $SUMMARY (log file) first.

note() { echo "$(date -u +%H:%M:%S) $*" | tee -a "$SUMMARY"; }

# Wait for the tunnel to answer a 90 s matmul probe, retrying every 120 s.
wait_up() { # wait_up [attempts=20]
    local attempts=${1:-20}
    for _ in $(seq 1 "$attempts"); do
        if timeout 90 python scripts/axon_probe.py matmul \
            > "$OUT/probe.out" 2> "$OUT/probe.err"; then
            note "tunnel UP: $(tail -2 "$OUT/probe.out" | head -1)"
            return 0
        fi
        note "tunnel down; retry in 120s"
        sleep 120
    done
    return 1
}

# Does any of the given .out files carry ON-DEVICE evidence? Parses the
# last JSON line's TOP-LEVEL device/fallback fields (the honest-provenance
# contract): a CPU-fallback rung still prints a pods/s figure, and nested
# segment results (canary, headline_mid) carry their own device strings —
# so neither `grep pods/s` nor a whole-file device grep is a device check
# (the exact mislabel class ADVICE.md documents).
seg_on_device() { # seg_on_device file...
    local f
    for f in "$@"; do
        [ -s "$f" ] || continue
        if tail -1 "$f" | python -c '
import json, sys
try:
    d = json.loads(sys.stdin.read())
except Exception:
    sys.exit(1)
ok = (
    str(d.get("device", "")).startswith("TPU")
    and d.get("fallback") != "cpu"
    and "error" not in d
)
sys.exit(0 if ok else 1)
'; then
            return 0
        fi
    done
    return 1
}

# If any of the given .out files carries an on-device pass (top-level
# provenance, see seg_on_device), chain into the full round capture with
# the platform (and optional chunk) pinned.
# Returns 1 when nothing passed so callers can branch to a fallback.
chain_capture_if_passed() { # chain_capture_if_passed chunk file...
    local chunk=$1; shift
    if seg_on_device "$@"; then
        export JAX_PLATFORMS=axon
        [ -n "$chunk" ] && export OSIM_HEADLINE_CHUNK="$chunk"
        note "full headline passed — chaining into the round capture" \
            "(chunk=${OSIM_HEADLINE_CHUNK:-default})"
        # `| tee` swallows the capture's exit status: a CPU-fallback capture
        # exits nonzero (tpu_round_capture.sh provenance guard) and must not
        # read as success to the ladder, so take the pipeline head's status.
        bash scripts/tpu_round_capture.sh 2>&1 | tee -a "$SUMMARY"
        local rc=${PIPESTATUS[0]}
        if [ "$rc" -ne 0 ]; then
            note "round capture FAILED (rc=$rc) — not banked as TPU evidence"
            return "$rc"
        fi
    else
        note "ladder done; full headline did not pass — bracket is in $OUT"
        return 1
    fi
}
