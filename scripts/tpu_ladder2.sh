#!/bin/bash
# Cache-aware escalating headline ladder (round-5, second iteration).
#
# What the first ladder learned (see /tmp/tpu_bisect and BASELINE.md):
#   - probes + 2k canary PASS on-device (1,698.7 pods/s steady, 74 s remote
#     compile); 10k x 1k hung a 600 s deadline with no output.
#   - host<->device transfer through the relay is ~1-8 MB/s and the remote
#     compile path is slow — a "wedge" may simply be a compile/transfer that
#     outlives the deadline.
# Strategy: per-dispatch breadcrumbs (OSIM_PROGRESS=1 + bench phase lines in
# each rung's .err) localize any hang; every failed attempt gets one retry
# after a re-probe, resuming from the persistent compile cache (axon
# executables serialize — verified 03:16-03:21, 269 entries banked by the
# canary); the 100k prize runs FIRST and chains straight into the round
# capture while the tunnel window is still fresh, with the mid rungs filled
# in afterwards as evidence points.
#
# Usage: scripts/tpu_ladder2.sh [--warmup]
# Results: /tmp/tpu_ladder2/, summary.log
set -u
OUT=/tmp/tpu_ladder2
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
SUMMARY="$OUT/summary.log"
. scripts/tpu_lib.sh
export OSIM_PROGRESS=1
WARMUP=0
for arg in "$@"; do
    case "$arg" in
        --warmup) WARMUP=1 ;;
        *) echo "unknown arg: $arg (usage: $0 [--warmup])" >&2; exit 2 ;;
    esac
done

# Run one bench segment (headline rung or named config) in a killable child.
# Success = the child exited 0 AND printed a result JSON without an "error"
# key AND — when the JSON stamps provenance — that provenance is not a CPU
# run wearing the axon label: bench's _segment_main catches exceptions and
# exits 0 with {"error": ...}, and a degraded backend still prints real
# pods/s figures, so neither the exit code nor "did it print a number" can
# detect a half-wedged tunnel or a silent CPU fallback.
run_seg() { # run_seg name deadline segment [pods nodes]
    local name=$1 deadline=$2 seg=$3 pods=${4:-} nodes=${5:-}
    local args=(--segment "$seg")
    [ -n "$pods" ] && args+=(--pods "$pods" --nodes "$nodes")
    note "seg $name (deadline ${deadline}s): ${args[*]}"
    if timeout "$deadline" env JAX_PLATFORMS=axon \
        python bench.py "${args[@]}" \
        > "$OUT/${name}.out" 2> "$OUT/${name}.err" \
        && grep -q '"wall_s"' "$OUT/${name}.out" \
        && ! grep -q '"error"' "$OUT/${name}.out" \
        && ! grep -q '"fallback": "cpu"' "$OUT/${name}.out" \
        && ! grep -q '"device": "[^"]*CPU' "$OUT/${name}.out"; then
        note "seg $name OK: $(tail -1 "$OUT/${name}.out" | cut -c1-200)"
        return 0
    fi
    note "seg $name FAILED/HUNG; last breadcrumb: $(grep -v WARNING "$OUT/${name}.err" | tail -1 | cut -c1-160)"
    return 1
}

# Try a headline rung, and on failure wait for the tunnel and retry once
# (the retry resumes from the persistent compile cache).
rung_with_retry() { # name deadline1 deadline2 pods nodes
    local name=$1 d1=$2 d2=$3 pods=$4 nodes=$5
    run_seg "$name" "$d1" headline "$pods" "$nodes" && return 0
    wait_up 45 || { note "tunnel never recovered; stopping ladder"; exit 1; }
    run_seg "${name}_retry" "$d2" headline "$pods" "$nodes" && return 0
    # a failed retry usually leaves the tunnel wedged (the documented axon
    # failure mode) — re-probe now so the NEXT attempt's deadline is never
    # burned against a dead tunnel
    wait_up 45 || { note "tunnel never recovered; stopping ladder"; exit 1; }
    return 1
}

wait_up 45 || { note "tunnel down at start"; exit 1; }

if [ "$WARMUP" = 1 ]; then
    # AOT-bank every audited jit entry + the sweep rehearsal into the
    # persistent compile cache BEFORE any rung's deadline is running —
    # compile time then never competes with a capture window. Best-effort:
    # a failed warmup means the rungs pay their own compiles, as before.
    note "warmup: simon warmup (AOT-compiling audited entries)"
    if timeout 1200 env JAX_PLATFORMS=axon \
        python -m open_simulator_tpu.cli.main warmup \
        > "$OUT/warmup.out" 2> "$OUT/warmup.err"; then
        note "warmup OK: $(grep '^warmup:' "$OUT/warmup.out" | cut -c1-200)"
    else
        note "warmup FAILED (rungs will compile cold): $(tail -1 "$OUT/warmup.err" | cut -c1-160)"
        wait_up 45 || { note "tunnel never recovered after warmup"; exit 1; }
    fi
fi

# Cache-resume sanity check: the 2k family compiled (74 s) earlier this
# round. If this re-run's compile_s is seconds, axon executables persist
# across processes and the retry strategy is load-bearing. A wedge here
# takes the tunnel down for whatever follows — re-probe before moving on
# so the 100k rung's long first attempt isn't burned against a dead tunnel.
run_seg cache_check_2k 420 headline 2000 200 \
    || wait_up 45 \
    || { note "tunnel never recovered after cache check"; exit 1; }
grep -o '"compile_s": [0-9.]*' "$OUT/cache_check_2k.out" 2>/dev/null | tee -a "$SUMMARY" || true

# Prize first: headline families at different scales share no compiled
# programs (node buckets differ — 2k→N=256, 10k→N=1024, 100k→N=12288), so
# small rungs only spend window time without shrinking the 100k compile
# bill. Windows have been short (15-50 min); go for the 100k number while
# the tunnel is freshest. CPU compile for the whole 100k family is 37 s
# (~12 programs); at the observed ~5x remote-compile multiplier that's
# ~3 min — 2400 s is ample headroom for transfer stalls on top.
rung_with_retry r100k 2400 1200 100000 10000 || true

# Chain into the full round capture IMMEDIATELY after a 100k pass — the
# capture re-runs the (now cached) headline plus all configs, and must not
# wait behind the mid rungs lest the window close first.
if chain_capture_if_passed "" "$OUT/r100k.out" "$OUT/r100k_retry.out"; then
    captured=1
else
    captured=0
fi

# Mid rungs as evidence points. r10k keeps its long first deadline: its
# cold family previously hung a 600 s deadline, and nothing the 100k rung
# compiled warms it (disjoint node buckets).
rung_with_retry r10k 1800 900 10000 1000 || true
rung_with_retry r20k 1200 900 20000 2000 || true
rung_with_retry r50k 1800 1200 50000 5000 || true
rung_with_retry r04k 600 600 4000 400 || true

if [ "$captured" = 0 ]; then
    # The full capture never ran this window — bank per-config device
    # numbers instead, so the round still gets on-device evidence for the
    # other six BASELINE configs (each compiles its own program family into
    # the persistent cache, shrinking any later capture's compile bill).
    note "banking per-config device numbers"
    for cfg in fit_1k_100n gpushare_5k stock preempt_tiered extender_1k \
               spread_aff_10k_1k; do
        run_seg "cfg_${cfg}" 900 "$cfg" && continue
        # Mirror rung_with_retry: once the tunnel answers a probe again,
        # one retry resumes from the persistent compile cache (the first
        # attempt's compiles are already banked, so the retry's deadline
        # buys mostly execution, not compilation).
        wait_up 45 || { note "tunnel never recovered"; exit 1; }
        run_seg "cfg_${cfg}_retry" 900 "$cfg" || true
    done
fi
