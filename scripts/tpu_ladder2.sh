#!/bin/bash
# Cache-aware escalating headline ladder (round-5, second iteration).
#
# What the first ladder learned (see /tmp/tpu_bisect and BASELINE.md):
#   - probes + 2k canary PASS on-device (1,698.7 pods/s steady, 74 s remote
#     compile); 10k x 1k hung a 600 s deadline with no output.
#   - host<->device transfer through the relay is ~1-8 MB/s and the remote
#     compile path is slow — a "wedge" may simply be a compile/transfer that
#     outlives the deadline.
# This ladder therefore (a) prints per-dispatch breadcrumbs (OSIM_PROGRESS=1
# + bench phase lines land in each rung's .err), (b) gives first attempts
# LONG deadlines, and (c) retries each failed rung once after a re-probe —
# if the persistent compile cache holds axon executables, the retry resumes
# where the kill landed instead of starting over.
#
# Usage: scripts/tpu_ladder2.sh    Results: /tmp/tpu_ladder2/, summary.log
set -u
OUT=/tmp/tpu_ladder2
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
SUMMARY="$OUT/summary.log"
. scripts/tpu_lib.sh
export OSIM_PROGRESS=1

run_rung() { # run_rung name deadline pods nodes [extra_env...]
    local name=$1 deadline=$2 pods=$3 nodes=$4; shift 4
    note "rung $name (deadline ${deadline}s) pods=$pods nodes=$nodes $*"
    if timeout "$deadline" env JAX_PLATFORMS=axon "$@" \
        python bench.py --segment headline --pods "$pods" --nodes "$nodes" \
        > "$OUT/${name}.out" 2> "$OUT/${name}.err"; then
        note "rung $name OK: $(tail -1 "$OUT/${name}.out" | cut -c1-200)"
        return 0
    fi
    note "rung $name FAILED/HUNG; last breadcrumb: $(grep -v WARNING "$OUT/${name}.err" | tail -1 | cut -c1-160)"
    return 1
}

# Try a rung, and on failure wait for the tunnel and retry once (the retry
# resumes from the persistent compile cache if axon executables serialize).
rung_with_retry() { # name deadline1 deadline2 pods nodes
    local name=$1 d1=$2 d2=$3 pods=$4 nodes=$5
    run_rung "$name" "$d1" "$pods" "$nodes" && return 0
    wait_up 45 || { note "tunnel never recovered; stopping ladder"; exit 1; }
    run_rung "${name}_retry" "$d2" "$pods" "$nodes" && return 0
    # a failed retry usually leaves the tunnel wedged (the documented axon
    # failure mode) — re-probe now so the NEXT rung's long first deadline
    # is never burned against a dead tunnel
    wait_up 45 || { note "tunnel never recovered; stopping ladder"; exit 1; }
    return 1
}

wait_up 45 || { note "tunnel down at start"; exit 1; }

# Cache-resume sanity check: the 2k family compiled (74 s) earlier this
# round. If this re-run's compile_s is seconds, axon executables persist
# across processes and the retry strategy below is load-bearing. A wedge
# here takes the tunnel down for whatever follows — re-probe before moving
# on so r04k's long first attempt isn't burned against a dead tunnel.
run_rung cache_check_2k 420 2000 200 \
    || wait_up 45 \
    || { note "tunnel never recovered after cache check"; exit 1; }
grep -o '"compile_s": [0-9.]*' "$OUT/cache_check_2k.out" 2>/dev/null | tee -a "$SUMMARY" || true

rung_with_retry r04k 900 600 4000 400 || true
rung_with_retry r10k 1800 900 10000 1000 || true
rung_with_retry r20k 1800 900 20000 2000 || true
rung_with_retry r50k 2400 1200 50000 5000 || true
rung_with_retry r100k 2400 1200 100000 10000

chain_capture_if_passed "" "$OUT/r100k.out" "$OUT/r100k_retry.out"
