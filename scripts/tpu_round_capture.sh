#!/bin/bash
# One-command TPU capture for the round's blocked item (VERDICT #1):
# run this the moment the axon tunnel initializes (e.g. when the probe loop
# has written /tmp/tpu_ready.json). It records, in order:
#   1. the full bench (headline + all 7 configs) on the TPU backend
#   2. the OSIM_PALLAS=1 oracle-parity suite (compiled mode, real TPU)
#   3. a Pallas-vs-XLA timing A/B on the domain path
# Results land in /tmp/tpu_capture/ — paste the numbers into BASELINE.md and
# record the Pallas keep/delete decision there.
set -u
OUT=/tmp/tpu_capture
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== 1/3 bench (TPU) =="
# JAX_PLATFORMS=axon requests the tunnel, but bench's own backend probe
# still falls back to CPU when the tunnel flaps (bench.py _select_backend) —
# so verify the recorded provenance and refuse to mislabel a CPU run as
# the round's TPU capture. bench stamps device/fallback as TOP-LEVEL
# fields; parse those, not a whole-file grep (per-segment payloads and the
# metrics snapshot can contain device strings for the wrong backend).
# --run-dir journals every segment, so a wedged capture resumes with
#   scripts/tpu_round_capture.sh --resume
RESUME_ARGS=()
[ "${1:-}" = "--resume" ] && RESUME_ARGS=(--resume)
JAX_PLATFORMS=axon timeout 7200 python bench.py \
    --run-dir "$OUT/run" "${RESUME_ARGS[@]}" \
    2>"$OUT/bench.err" | tail -1 > "$OUT/bench_tpu.json"
tail -c 400 "$OUT/bench_tpu.json"; echo
if ! python - "$OUT/bench_tpu.json" <<'EOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except (OSError, ValueError):
    sys.exit(1)
ok = str(d.get("device", "")).startswith("TPU") and d.get("fallback") != "cpu"
sys.exit(0 if ok else 1)
EOF
then
    mv "$OUT/bench_tpu.json" "$OUT/bench_cpu_fallback.json"
    echo "stage 1 fell back to CPU — saved as bench_cpu_fallback.json, NOT a TPU capture"
    # Nonzero so callers (chain_capture_if_passed) never bank this as the
    # round's TPU evidence; stages 2/3 are meaningless off-device anyway.
    exit 1
fi

echo "== 2/3 Pallas parity (compiled, real TPU) =="
# OSIM_TEST_PLATFORM=axon: conftest.py otherwise pins tests to CPU, which
# would make this stage silently validate nothing on-device.
OSIM_TEST_PLATFORM=axon OSIM_PALLAS=1 timeout 1800 \
    python -m pytest tests/test_fast.py -q -k domain \
    > "$OUT/pallas_parity.txt" 2>&1
tail -2 "$OUT/pallas_parity.txt"

echo "== 3/3 Pallas timing A/B =="
JAX_PLATFORMS=axon timeout 1800 python - <<'EOF' > "$OUT/pallas_timing.txt" 2>&1
import os, time
import numpy as np

def run(pallas: bool):
    os.environ["OSIM_PALLAS"] = "1" if pallas else "0"
    # fresh process state matters for the env flag; this in-process A/B is
    # valid only if ops.fast reads the flag per call — check and fall back
    import importlib
    import open_simulator_tpu.ops.fast as fast
    importlib.reload(fast)
    import bench
    t0 = time.time()
    out = bench._run_headline(20_000, 2_000)
    return out

a = run(False)
print("XLA   :", a)
b = run(True)
print("PALLAS:", b)
print("decision hint: keep Pallas iff bit-identical (suite above) AND "
      "PALLAS wall_s < XLA wall_s")
EOF
tail -4 "$OUT/pallas_timing.txt"
echo "== capture complete: $OUT =="
