"""Staged axon-tunnel probes: find where a wedge starts.

The round-5 failure mode is init-succeeds-but-programs-wedge: the tunnel
initializes and runs a trivial program in seconds, then the first real
headline program hangs indefinitely (and afterwards even backend init
hangs until the server side recovers). Each stage here is small, prints
a JSON line when it completes, and is meant to run under `timeout` in a
killable child so a hang costs its deadline, not the session:

    timeout 90  python scripts/axon_probe.py matmul
    timeout 180 python scripts/axon_probe.py transfer
    timeout 240 python scripts/axon_probe.py scan
    timeout 300 python scripts/axon_probe.py sort

Run the stages in order; the first one that times out localizes the
wedge (RPC transfer vs compiled-program dispatch vs the specific op
family the scheduler leans on). scripts/tpu_bisect.sh drives the full
ladder including bench headlines at escalating sizes.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "axon"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

t0 = time.time()
devs = jax.devices()
print(
    json.dumps(
        {
            "stage": "init",
            "s": round(time.time() - t0, 1),
            "devices": [str(d) for d in devs],
        }
    ),
    flush=True,
)

stage = sys.argv[1] if len(sys.argv) > 1 else "matmul"


def timed(name, fn):
    t = time.time()
    out = fn()
    if out is not None:
        jax.block_until_ready(out)
    print(
        json.dumps({"stage": name, "s": round(time.time() - t, 2)}),
        flush=True,
    )


if stage == "matmul":
    x = jnp.ones((1024, 1024), jnp.bfloat16)
    f = jax.jit(lambda a: a @ a)
    timed("matmul_compile+run", lambda: f(x))
    timed("matmul_warm_x10", lambda: [f(x) for _ in range(10)][-1])
elif stage == "transfer":
    import numpy as np

    for mb in (1, 8, 64):
        n = mb * 1024 * 1024 // 4
        a = np.ones(n, np.float32)
        t = time.time()
        da = jax.device_put(a)
        da.block_until_ready()
        up = time.time() - t
        t = time.time()
        np.asarray(da)
        down = time.time() - t
        print(
            json.dumps(
                {
                    "stage": f"transfer_{mb}MB",
                    "up_s": round(up, 2),
                    "down_s": round(down, 2),
                }
            ),
            flush=True,
        )
elif stage == "scan":
    # The scheduler's program shape: a long lax.scan whose carry updates
    # via indexed adds (dynamic_update_slice family).
    def body(c, x):
        return c.at[x % 1000].add(1.0), x

    f = jax.jit(lambda c, xs: jax.lax.scan(body, c, xs))
    c0 = jnp.zeros(1000, jnp.float32)
    xs = jnp.arange(16384, dtype=jnp.int32)
    timed("scan16k_compile+run", lambda: f(c0, xs)[0])
    timed("scan16k_warm", lambda: f(c0, xs)[0])
elif stage == "sort":
    # The sort fast path's program shape: key-sort over the node axis.
    k = jax.random.key(0)
    x = jax.random.uniform(k, (100_000,))
    f = jax.jit(lambda a: jnp.sort(a))
    timed("sort100k_compile+run", lambda: f(x))
    timed("sort100k_warm", lambda: f(x))
else:
    print(json.dumps({"error": f"unknown stage {stage!r}"}), flush=True)
    sys.exit(2)
print(json.dumps({"stage": "done", "ok": True}), flush=True)
