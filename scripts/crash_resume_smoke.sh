#!/bin/bash
# CI crash-resume smoke (docs/durability.md): SIGKILL a journaled capacity
# sweep at an exact trial boundary, resume it, and require the resumed run's
# outcome.json to be BYTE-IDENTICAL to an uninterrupted run's. Proves the
# whole durable chain end to end: fsync'd journal commits survive SIGKILL,
# the resume replays trials instead of re-running them, and placements are
# reproduced exactly (placement_digest), not just counted.
#
# Usage: scripts/crash_resume_smoke.sh [scratch_dir]
set -eu
cd "$(dirname "$0")/.."
SCRATCH=${1:-$(mktemp -d)}
mkdir -p "$SCRATCH"
export JAX_PLATFORMS=cpu

# 1. Reference: one uninterrupted journaled apply.
python -m open_simulator_tpu.cli.main apply -f example/simon-config.yaml \
    --run-dir "$SCRATCH/ref" --output-file "$SCRATCH/ref.txt"
[ -f "$SCRATCH/ref/outcome.json" ] || { echo "no reference outcome"; exit 1; }

# 2. Crash run: the fault plan SIGKILLs the process the moment the 2nd
#    trial verdict would commit to the journal (kind=kill fires BEFORE the
#    record is written, so that trial is NOT journaled and must re-run).
cat > "$SCRATCH/faults.yaml" <<'EOF'
rules:
  - target: journal
    op: trial
    kind: kill
    after: 1
EOF
rc=0
OSIM_FAULT_PLAN="$SCRATCH/faults.yaml" \
    python -m open_simulator_tpu.cli.main apply -f example/simon-config.yaml \
    --run-dir "$SCRATCH/crash" --output-file "$SCRATCH/crash.txt" \
    > /dev/null 2>&1 || rc=$?
if [ "$rc" -ne 137 ] && [ "$rc" -ne 1 ]; then
    echo "expected the run to be SIGKILLed (rc 137), got rc=$rc"; exit 1
fi
[ -f "$SCRATCH/crash/outcome.json" ] && { echo "crashed run wrote an outcome?"; exit 1; }

# 3. Resume. Journaled trials replay; only the killed trial re-runs.
python -m open_simulator_tpu.cli.main runs resume "$SCRATCH/crash"

# 4. Byte-identity: outcome.json is timestamp-free by design so this diff
#    is exact — same plan, same attempts/retries, same placement digest.
cmp "$SCRATCH/ref/outcome.json" "$SCRATCH/crash/outcome.json" || {
    echo "resumed outcome differs from the uninterrupted run:"
    diff "$SCRATCH/ref/outcome.json" "$SCRATCH/crash/outcome.json" || true
    exit 1
}

# 5. The journal must show the surviving trials were replayed, not re-run:
#    only the SIGKILLed trial runs live after run_resume. (A `final` record
#    appears only when the winning verdict itself came from the journal —
#    here the killed trial is the winner, so it re-runs live instead.)
python - "$SCRATCH/crash" "$SCRATCH/ref" <<'EOF'
import sys
from open_simulator_tpu.durable import replay
events = [e["event"] for e in replay(sys.argv[1])]
ref_trials = [e["event"] for e in replay(sys.argv[2])].count("trial")
i = events.index("run_resume")
pre = events[:i].count("trial")
post = events[i:].count("trial")
assert pre >= 1, f"no trial survived the crash: {events}"
assert post == 1, f"resume re-ran {post} trials (expected 1): {events}"
assert pre + post == ref_trials, (
    f"trial count drifted: {pre} journaled + {post} re-run != "
    f"{ref_trials} in the reference run: {events}"
)
assert "run_end" in events[i:], f"resume never completed: {events}"
print(f"crash-resume smoke OK: {pre} journaled trial(s) replayed, "
      f"{post} re-run, outcome byte-identical")
EOF

# ---------------------------------------------------------------------------
# Batched leg (docs/batching.md): same contract for the batched capacity
# sweep, whose journal unit is a `sweep` record carrying ALL lane verdicts
# of one vmapped device call. SIGKILL between sweep commits, resume, and
# require zero re-run scenarios for the surviving records plus a
# byte-identical outcome.json. (example/ configs carry DaemonSets, which
# force the serial fallback — the DS-free tests/fixtures/sweep config is
# the batch-eligible one.)
# ---------------------------------------------------------------------------
SWEEP_CFG=tests/fixtures/sweep/simon-config.yaml

# 6. Reference: one uninterrupted journaled batched sweep.
python -m open_simulator_tpu.cli.main sweep -f "$SWEEP_CFG" --capacity \
    --run-dir "$SCRATCH/sweepref" > /dev/null
[ -f "$SCRATCH/sweepref/outcome.json" ] || { echo "no sweep reference outcome"; exit 1; }

# 7. Crash run: SIGKILL the moment the 2nd `sweep` record would commit —
#    the first batched call is journaled, the rest never happened.
cat > "$SCRATCH/sweep-faults.yaml" <<'EOF'
rules:
  - target: journal
    op: sweep
    kind: kill
    after: 1
EOF
rc=0
OSIM_FAULT_PLAN="$SCRATCH/sweep-faults.yaml" \
    python -m open_simulator_tpu.cli.main sweep -f "$SWEEP_CFG" --capacity \
    --run-dir "$SCRATCH/sweepcrash" > /dev/null 2>&1 || rc=$?
if [ "$rc" -ne 137 ] && [ "$rc" -ne 1 ]; then
    echo "expected the sweep to be SIGKILLed (rc 137), got rc=$rc"; exit 1
fi
[ -f "$SCRATCH/sweepcrash/outcome.json" ] && { echo "crashed sweep wrote an outcome?"; exit 1; }

# 8. Resume through the same entry point as apply runs (`runs resume`
#    dispatches on the journaled kind).
python -m open_simulator_tpu.cli.main runs resume "$SCRATCH/sweepcrash" > /dev/null

# 9. Byte-identity again — attempts, batched_calls, and placement digest
#    all live in the timestamp-free snapshot.
cmp "$SCRATCH/sweepref/outcome.json" "$SCRATCH/sweepcrash/outcome.json" || {
    echo "resumed sweep outcome differs from the uninterrupted run:"
    diff "$SCRATCH/sweepref/outcome.json" "$SCRATCH/sweepcrash/outcome.json" || true
    exit 1
}

# 10. The surviving sweep record replayed (zero re-run scenarios for it);
#     only the killed-and-after batched calls ran live after run_resume.
python - "$SCRATCH/sweepcrash" "$SCRATCH/sweepref" <<'EOF'
import sys
from open_simulator_tpu.durable import replay
events = [e["event"] for e in replay(sys.argv[1])]
ref_sweeps = [e["event"] for e in replay(sys.argv[2])].count("sweep")
i = events.index("run_resume")
pre = events[:i].count("sweep")
post = events[i:].count("sweep")
assert pre >= 1, f"no sweep record survived the crash: {events}"
assert pre + post == ref_sweeps, (
    f"sweep count drifted: {pre} journaled + {post} re-run != "
    f"{ref_sweeps} in the reference run: {events}"
)
assert "final" in events[i:], f"resume never materialized the plan: {events}"
assert "run_end" in events[i:], f"resume never completed: {events}"
print(f"crash-resume smoke OK (batched): {pre} sweep record(s) replayed "
      f"with zero re-run scenarios, {post} re-run, outcome byte-identical")
EOF

# ---------------------------------------------------------------------------
# Mid-chunk leg (docs/durability.md): the kill now lands INSIDE a batched
# device call. With OSIM_COMMIT_CHUNK the commit scan is a host loop of
# chunk dispatches, each journaled (`plan_chunk`) and periodically
# snapshotted — so a SIGKILL between chunks loses at most one chunk, not
# the whole plan. Resume restores the newest verified snapshot, replays
# the journal tail with per-chunk digest cross-checks, and the final
# outcome must STILL byte-match the unchunked reference from step 6 —
# proving chunked == monolithic and crash == clean in one cmp.
# ---------------------------------------------------------------------------
export OSIM_COMMIT_CHUNK=8 OSIM_CKPT_EVERY=2

# 11. Crash run: a device-plane chunk_kill SIGKILLs the sweep at commit
#     chunk 3 of the first chunked plan — after chunks 0-2 journaled and
#     the chunks 0-1 snapshot hit the disk.
cat > "$SCRATCH/chunk-faults.yaml" <<'EOF'
rules:
  - target: device
    op: "commit-chunk:3"
    kind: chunk_kill
    times: 1
EOF
rc=0
OSIM_FAULT_PLAN="$SCRATCH/chunk-faults.yaml" \
    python -m open_simulator_tpu.cli.main sweep -f "$SWEEP_CFG" --capacity \
    --run-dir "$SCRATCH/chunkcrash" > /dev/null 2>&1 || rc=$?
if [ "$rc" -ne 137 ]; then
    echo "expected a mid-chunk SIGKILL (rc 137), got rc=$rc"; exit 1
fi
[ -f "$SCRATCH/chunkcrash/outcome.json" ] && { echo "mid-chunk-killed sweep wrote an outcome?"; exit 1; }

# 12. The journal must already hold per-chunk records and ckpt/ a snapshot:
#     the whole point is that the death happened mid-plan, not between plans.
python - "$SCRATCH/chunkcrash" <<'EOF'
import glob, os, sys
from open_simulator_tpu.durable import replay
chunks = [e for e in replay(sys.argv[1]) if e["event"] == "plan_chunk"]
assert chunks, "no plan_chunk records: the chunked driver never engaged"
snaps = glob.glob(os.path.join(sys.argv[1], "ckpt", "plan-*.npz"))
assert snaps, "no carry snapshot on disk at kill time"
EOF

# 13. Resume (same chunk env: plan keys embed the chunk size).
python -m open_simulator_tpu.cli.main runs resume "$SCRATCH/chunkcrash" > /dev/null

# 14. Byte-identity against the UNCHUNKED reference of step 6.
cmp "$SCRATCH/sweepref/outcome.json" "$SCRATCH/chunkcrash/outcome.json" || {
    echo "mid-chunk resumed outcome differs from the monolithic run:"
    diff "$SCRATCH/sweepref/outcome.json" "$SCRATCH/chunkcrash/outcome.json" || true
    exit 1
}

# 15. The resume actually skipped the snapshotted chunks (a chunk-restore
#     flight-recorder artifact names the restore point) and re-journaled
#     only the tail — no duplicate plan_chunk records.
python - "$SCRATCH/chunkcrash" <<'EOF'
import collections, glob, json, os, sys
from open_simulator_tpu.durable import replay
run = sys.argv[1]
arts = glob.glob(os.path.join(run, "flightrec-chunk-restore-*.json"))
assert arts, "resume left no chunk-restore flight-recorder artifact"
notes = [e for a in arts for e in json.load(open(a))["events"]
         if e.get("kind") == "plan-restore"]
assert notes, "no plan-restore note in the artifact"
int(notes[-1]["digest"], 16)
seen = collections.Counter(
    (e["plan"], e["chunk"]) for e in replay(run) if e["event"] == "plan_chunk"
)
dupes = {k: n for k, n in seen.items() if n > 1}
assert not dupes, f"duplicate plan_chunk records after resume: {dupes}"
print(f"crash-resume smoke OK (mid-chunk): restored at chunk "
      f"{notes[-1]['chunk'] + 1} (digest {notes[-1]['digest']}), "
      f"{len(seen)} chunk records, outcome byte-identical to monolithic")
EOF
unset OSIM_COMMIT_CHUNK OSIM_CKPT_EVERY
