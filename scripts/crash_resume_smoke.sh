#!/bin/bash
# CI crash-resume smoke (docs/durability.md): SIGKILL a journaled capacity
# sweep at an exact trial boundary, resume it, and require the resumed run's
# outcome.json to be BYTE-IDENTICAL to an uninterrupted run's. Proves the
# whole durable chain end to end: fsync'd journal commits survive SIGKILL,
# the resume replays trials instead of re-running them, and placements are
# reproduced exactly (placement_digest), not just counted.
#
# Usage: scripts/crash_resume_smoke.sh [scratch_dir]
set -eu
cd "$(dirname "$0")/.."
SCRATCH=${1:-$(mktemp -d)}
mkdir -p "$SCRATCH"
export JAX_PLATFORMS=cpu

# 1. Reference: one uninterrupted journaled apply.
python -m open_simulator_tpu.cli.main apply -f example/simon-config.yaml \
    --run-dir "$SCRATCH/ref" --output-file "$SCRATCH/ref.txt"
[ -f "$SCRATCH/ref/outcome.json" ] || { echo "no reference outcome"; exit 1; }

# 2. Crash run: the fault plan SIGKILLs the process the moment the 2nd
#    trial verdict would commit to the journal (kind=kill fires BEFORE the
#    record is written, so that trial is NOT journaled and must re-run).
cat > "$SCRATCH/faults.yaml" <<'EOF'
rules:
  - target: journal
    op: trial
    kind: kill
    after: 1
EOF
rc=0
OSIM_FAULT_PLAN="$SCRATCH/faults.yaml" \
    python -m open_simulator_tpu.cli.main apply -f example/simon-config.yaml \
    --run-dir "$SCRATCH/crash" --output-file "$SCRATCH/crash.txt" \
    > /dev/null 2>&1 || rc=$?
if [ "$rc" -ne 137 ] && [ "$rc" -ne 1 ]; then
    echo "expected the run to be SIGKILLed (rc 137), got rc=$rc"; exit 1
fi
[ -f "$SCRATCH/crash/outcome.json" ] && { echo "crashed run wrote an outcome?"; exit 1; }

# 3. Resume. Journaled trials replay; only the killed trial re-runs.
python -m open_simulator_tpu.cli.main runs resume "$SCRATCH/crash"

# 4. Byte-identity: outcome.json is timestamp-free by design so this diff
#    is exact — same plan, same attempts/retries, same placement digest.
cmp "$SCRATCH/ref/outcome.json" "$SCRATCH/crash/outcome.json" || {
    echo "resumed outcome differs from the uninterrupted run:"
    diff "$SCRATCH/ref/outcome.json" "$SCRATCH/crash/outcome.json" || true
    exit 1
}

# 5. The journal must show the surviving trials were replayed, not re-run:
#    only the SIGKILLed trial runs live after run_resume. (A `final` record
#    appears only when the winning verdict itself came from the journal —
#    here the killed trial is the winner, so it re-runs live instead.)
python - "$SCRATCH/crash" "$SCRATCH/ref" <<'EOF'
import sys
from open_simulator_tpu.durable import replay
events = [e["event"] for e in replay(sys.argv[1])]
ref_trials = [e["event"] for e in replay(sys.argv[2])].count("trial")
i = events.index("run_resume")
pre = events[:i].count("trial")
post = events[i:].count("trial")
assert pre >= 1, f"no trial survived the crash: {events}"
assert post == 1, f"resume re-ran {post} trials (expected 1): {events}"
assert pre + post == ref_trials, (
    f"trial count drifted: {pre} journaled + {post} re-run != "
    f"{ref_trials} in the reference run: {events}"
)
assert "run_end" in events[i:], f"resume never completed: {events}"
print(f"crash-resume smoke OK: {pre} journaled trial(s) replayed, "
      f"{post} re-run, outcome byte-identical")
EOF
