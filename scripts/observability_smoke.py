#!/usr/bin/env python
"""CI observability smoke (docs/observability.md).

Runs a small apply plus the dispatch-gap analyzer under OSIM_TRACE_FILE
inside ONE root span, then proves the exported Chrome trace is a single
connected tree:

  * every event carries the same trace_id (one request = one trace);
  * exactly one root event (no parent_id) — the smoke's own root span;
  * every parent_id resolves to a span_id present in the file (no
    orphans);
  * both host spans (the apply/simulate phases) and device spans
    (`device:<entry>` from the dispatch-gap analyzer) are present.

Publishes the per-entry device-time table to the GitHub job summary when
GITHUB_STEP_SUMMARY is set. Exits nonzero on any violation.
"""

import io
import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

CONFIG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "simon-config.yaml",
)


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="osim-obs-smoke-")
    trace_path = os.path.join(out_dir, "trace.json")
    os.environ["OSIM_TRACE_FILE"] = trace_path
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from open_simulator_tpu.api.config import SimonConfig
    from open_simulator_tpu.engine.apply import run_apply
    from open_simulator_tpu.utils.platform import ensure_platform
    from open_simulator_tpu.utils.profiling import analyze_dispatch_gaps
    from open_simulator_tpu.utils.tracing import span

    ensure_platform()
    cfg = SimonConfig.load(CONFIG)
    with span("observability-smoke"):
        run_apply(cfg, out=io.StringIO())
        report = analyze_dispatch_gaps(repeats=1)

    with open(trace_path) as fh:
        events = json.load(fh)["traceEvents"]
    assert events, "trace export produced no events"

    trace_ids = {e["args"]["trace_id"] for e in events}
    assert len(trace_ids) == 1, (
        f"expected one connected trace, got {len(trace_ids)}: "
        f"{sorted(trace_ids)}"
    )
    roots = [e for e in events if "parent_id" not in e["args"]]
    assert len(roots) == 1, (
        f"expected exactly one root span, got "
        f"{[r['name'] for r in roots]}"
    )
    assert roots[0]["name"] == "observability-smoke", roots[0]["name"]
    span_ids = {e["args"]["span_id"] for e in events}
    orphans = [
        e["name"] for e in events
        if e["args"].get("parent_id") not in span_ids | {None}
    ]
    assert not orphans, f"orphaned spans (unresolvable parent_id): {orphans}"

    device = sorted(
        e["name"] for e in events if e["name"].startswith("device:")
    )
    host = sorted(
        {e["name"] for e in events if not e["name"].startswith("device:")}
    )
    assert device, "no device:<entry> spans in the trace"
    assert len(host) > 1, f"expected host phase spans beyond the root: {host}"
    assert report.entries, "dispatch-gap analyzer timed no entries"

    lines = [
        "### observability smoke",
        "",
        f"- one connected trace: `{trace_ids.pop()}` "
        f"({len(events)} spans, {len(device)} device, root "
        f"`{roots[0]['name']}`)",
        f"- aggregate dispatch-gap ratio: {report.dispatch_gap_ratio}",
        "",
        "| entry | device ms | dispatch ms | gap |",
        "|---|---|---|---|",
    ]
    for e in sorted(report.entries, key=lambda e: -e.device_ms):
        lines.append(
            f"| {e.name} | {e.device_ms:.3f} | {e.dispatch_ms:.3f} "
            f"| {e.gap_ratio:.3f} |"
        )
    summary = "\n".join(lines)
    print(summary)
    step_summary = os.environ.get("GITHUB_STEP_SUMMARY")
    if step_summary:
        with open(step_summary, "a") as fh:
            fh.write(summary + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
