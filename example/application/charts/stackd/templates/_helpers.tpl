{{- define "stackd.fullname" -}}
{{- printf "%s-%s" .Release.Name .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "stackd.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
{{- end -}}
