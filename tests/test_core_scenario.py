"""Faithful port of the reference's full integration scenario.

Parity target: `/root/reference/pkg/simulator/core_test.go:32-361` (the
"simple" TestSimulate case) and its `checkResult` oracle (`:364-591`):

  cluster = master-1 (tainted, local storage) + master-2 + master-3 +
            worker-1 (local storage), 4 static pods pre-bound to master-1,
            a metrics-server Deployment with node-affinity (master Exists) +
            required pod-anti-affinity on a zone topology key, and 3
            DaemonSets (kube-proxy-master / kube-proxy-worker / coredns)
            with taints/selectors/affinity;
  app "simple" = Deployment busybox-deploy (4×1500m/1Gi, tolerates the
            master taint), DaemonSet busybox-ds (worker-only via
            DoesNotExist affinity), Job pi, bare Pod single-pod (master
            nodeSelector + toleration), StatefulSet busybox-sts (4 replicas,
            preferred pod-anti-affinity), ReplicaSet calico-kube-controllers
            (2 replicas, request-less, tolerates everything);
  oracle = failedPodsNum == 0, per-workload pod-count conservation
            (DaemonSet expectations recomputed per node via the daemon
            controller predicates), and individual-pod count conservation.

The workload templates here intentionally carry NO labels — the reference's
pkg/test factories don't set any (statefulset.go:15-45 etc.), which makes the
busybox-sts preferred anti-affinity vacuously inert exactly as it is in the
reference run.
"""

import json

from open_simulator_tpu.core.objects import Node, Pod
from open_simulator_tpu.core.workloads import daemonset_pods, expected_pod_counts
from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    simulate,
)

MASTER_LABELS = {
    "beta.kubernetes.io/arch": "amd64",
    "beta.kubernetes.io/os": "linux",
    "kubernetes.io/arch": "amd64",
    "kubernetes.io/os": "linux",
    "node-role.kubernetes.io/master": "",
}
WORKER_LABELS = {
    "beta.kubernetes.io/arch": "amd64",
    "beta.kubernetes.io/os": "linux",
    "kubernetes.io/arch": "amd64",
    "kubernetes.io/os": "linux",
    "node-role.kubernetes.io/worker": "",
}

# utils.NodeStorage JSON exactly as WithNodeLocalStorage encodes it
# (core_test.go:60-80; SharedResource/ExclusiveResource with 100Gi pools)
LOCAL_STORAGE = json.dumps(
    {
        "vgs": [
            {"name": "yoda-pool0", "capacity": 107374182400},
            {"name": "yoda-pool1", "capacity": 107374182400},
        ],
        "devices": [
            {
                "name": "/dev/vdd",
                "device": "/dev/vdd",
                "capacity": 107374182400,
                "isAllocated": False,
                "mediaType": "hdd",
            }
        ],
    }
)


def _node(name, labels, tainted=False, storage=False):
    """MakeFakeNode parity (node.go:15-40): 8 cpu / 16Gi / 110 pods."""
    meta = {
        "name": name,
        "labels": {"kubernetes.io/hostname": name, **labels},
        "annotations": (
            {"simon/node-local-storage": LOCAL_STORAGE} if storage else {}
        ),
    }
    spec = {}
    if tainted:
        spec["taints"] = [
            {"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}
        ]
    res = {"cpu": "8", "memory": "16Gi", "pods": "110"}
    return Node.from_dict(
        {
            "metadata": meta,
            "spec": spec,
            "status": {"allocatable": dict(res), "capacity": dict(res)},
        }
    )


def _static_pod(name, cpu):
    """MakeFakePod + WithPodNodeName (pod.go:13-47): pre-bound to master-1,
    empty resource strings mean no request at all."""
    res = {}
    if cpu:
        res["cpu"] = cpu
    return Pod.from_dict(
        {
            "metadata": {"name": name, "namespace": "kube-system"},
            "spec": {
                "nodeName": "master-1",
                "containers": [
                    {
                        "name": "container",
                        "image": "nginx",
                        "resources": {"requests": res},
                    }
                ],
            },
        }
    )


def _tmpl_spec(cpu, memory, tolerations=None, node_selector=None, affinity=None):
    """Reference pkg/test template: single container, NO labels."""
    res = {}
    if cpu:
        res["cpu"] = cpu
    if memory:
        res["memory"] = memory
    spec = {
        "containers": [
            {"name": "container", "image": "nginx", "resources": {"requests": res}}
        ]
    }
    if tolerations:
        spec["tolerations"] = tolerations
    if node_selector:
        spec["nodeSelector"] = node_selector
    if affinity:
        spec["affinity"] = affinity
    return spec


def _workload(kind, name, ns, spec_extra, tmpl):
    return {
        "kind": kind,
        "metadata": {"name": name, "namespace": ns},
        "spec": {**spec_extra, "template": {"metadata": {}, "spec": tmpl}},
    }


MASTER_EXISTS_AFFINITY = {
    "nodeAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": [
                {
                    "matchExpressions": [
                        {
                            "key": "node-role.kubernetes.io/master",
                            "operator": "Exists",
                        }
                    ]
                }
            ]
        }
    }
}


def _build_cluster():
    nodes = [
        _node("master-1", MASTER_LABELS, tainted=True, storage=True),
        _node("master-2", MASTER_LABELS),
        _node("master-3", MASTER_LABELS),
        _node("worker-1", WORKER_LABELS, storage=True),
    ]
    static_pods = [
        _static_pod("etcd-master-1", ""),
        _static_pod("kube-apiserver-master-1", "250m"),
        _static_pod("kube-controller-manager-master-1", "200m"),
        _static_pod("kube-scheduler-master-1", "100m"),
    ]
    metrics_server = _workload(
        "Deployment", "metrics-server", "kube-system",
        {"replicas": 1},
        _tmpl_spec(
            "1", "500Mi",
            affinity={
                **MASTER_EXISTS_AFFINITY,
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {
                                "matchLabels": {"k8s-app": "metrics-server"}
                            },
                            "topologyKey": "failure-domain.beta.kubernetes.io/zone",
                        }
                    ]
                },
            },
        ),
    )
    daemonsets = [
        _workload(
            "DaemonSet", "kube-proxy-master", "kube-system", {},
            _tmpl_spec(
                "", "",
                tolerations=[{"operator": "Exists"}],
                node_selector={"node-role.kubernetes.io/master": ""},
            ),
        ),
        _workload(
            "DaemonSet", "kube-proxy-worker", "kube-system", {},
            _tmpl_spec(
                "", "",
                tolerations=[{"operator": "Exists"}],
                node_selector={"node-role.kubernetes.io/worker": ""},
            ),
        ),
        _workload(
            "DaemonSet", "coredns", "kube-system", {},
            _tmpl_spec(
                "100m", "70Mi",
                tolerations=[
                    {
                        "effect": "NoSchedule",
                        "key": "node-role.kubernetes.io/master",
                    }
                ],
                node_selector={"beta.kubernetes.io/os": "linux"},
                affinity=MASTER_EXISTS_AFFINITY,
            ),
        ),
    ]
    cluster = ClusterResource(
        nodes=nodes,
        pods=static_pods,
        daemonsets=daemonsets,
        others={},
    )
    # non-DaemonSet cluster workloads ride in the first app position the way
    # RunCluster schedules them with the cluster's own pending pods
    return cluster, metrics_server


def _build_app():
    master_toleration = [
        {
            "effect": "NoSchedule",
            "key": "node-role.kubernetes.io/master",
            "operator": "Exists",
        }
    ]
    objects = [
        _workload(
            "Deployment", "busybox-deploy", "simple", {"replicas": 4},
            _tmpl_spec("1500m", "1Gi", tolerations=master_toleration),
        ),
        _workload(
            "DaemonSet", "busybox-ds", "simple", {},
            _tmpl_spec(
                "500m", "512Mi",
                node_selector={"beta.kubernetes.io/os": "linux"},
                affinity={
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {
                                            "key": "node-role.kubernetes.io/master",
                                            "operator": "DoesNotExist",
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                },
            ),
        ),
        _workload(
            "Job", "pi", "default", {"completions": 1, "parallelism": 1},
            _tmpl_spec("100m", "100Mi"),
        ),
        {
            "kind": "Pod",
            "metadata": {"name": "single-pod", "namespace": "simple"},
            "spec": {
                **_tmpl_spec(
                    "100m", "100Mi",
                    tolerations=[
                        {
                            "effect": "NoSchedule",
                            "key": "node-role.kubernetes.io/master",
                            "operator": "Exists",
                        }
                    ],
                    node_selector={"node-role.kubernetes.io/master": ""},
                ),
            },
        },
        _workload(
            "StatefulSet", "busybox-sts", "simple", {"replicas": 4},
            _tmpl_spec(
                "1", "512Mi",
                tolerations=master_toleration,
                affinity={
                    "podAntiAffinity": {
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "weight": 100,
                                "podAffinityTerm": {
                                    "labelSelector": {
                                        "matchExpressions": [
                                            {
                                                "key": "app",
                                                "operator": "In",
                                                "values": ["busybox-sts"],
                                            }
                                        ]
                                    },
                                    "topologyKey": "kubernetes.io/hostname",
                                },
                            }
                        ]
                    }
                },
            ),
        ),
        _workload(
            "ReplicaSet", "calico-kube-controllers", "kube-system",
            {"replicas": 2},
            _tmpl_spec(
                "", "",
                tolerations=[
                    {"effect": "NoSchedule", "operator": "Exists"},
                    {"key": "CriticalAddonsOnly", "operator": "Exists"},
                    {"effect": "NoExecute", "operator": "Exists"},
                ],
            ),
        ),
    ]
    return AppResource(name="simple", objects=objects)


def _check_result(cluster, all_workloads, result, failed_pods_num=0):
    """checkResult parity (core_test.go:364-591): exact per-workload counts
    + individual-pod conservation, DaemonSet expectations recomputed from the
    daemon-controller predicates per node."""
    assert len(result.unscheduled) == failed_pods_num, [
        (u.pod.key, u.reason) for u in result.unscheduled
    ]

    all_pods = [p for st in result.node_status for p in st.pods]
    all_pods += [u.pod for u in result.unscheduled]

    expected = expected_pod_counts(all_workloads, cluster.nodes)
    # individual pods (static + bare app pods) are keyed as Pod/<ns>/<name>
    expected_individual = sum(
        n for key, n in expected.items() if key.startswith("Pod/")
    )
    expected_workloads = {
        key: n for key, n in expected.items() if not key.startswith("Pod/")
    }

    got_workloads = {key: 0 for key in expected_workloads}
    got_individual = 0
    for pod in all_pods:
        kind = pod.meta.annotations.get("simon/workload-kind", "")
        name = pod.meta.annotations.get("simon/workload-name", "")
        ns = pod.meta.annotations.get("simon/workload-namespace", "")
        if not kind:
            got_individual += 1
            continue
        key = f"{kind}/{ns or 'default'}/{name}"
        # checkResult's owner-kind indirection (core_test.go:519-546):
        # Deployment pods are ReplicaSet-owned — attribute to the Deployment
        # when no ReplicaSet of that name exists; likewise CronJob pods are
        # Job-owned.
        if key not in got_workloads and kind == "ReplicaSet":
            key = f"Deployment/{ns or 'default'}/{name}"
        if key not in got_workloads and kind == "Job":
            key = f"CronJob/{ns or 'default'}/{name}"
        assert key in got_workloads, f"pod {pod.key} from unexpected {key}"
        got_workloads[key] += 1

    assert got_workloads == expected_workloads
    assert got_individual == expected_individual


def test_core_scenario_simple():
    cluster, metrics_server = _build_cluster()
    app = _build_app()
    # metrics-server is a cluster Deployment in the reference fixture; our
    # ClusterResource carries non-DaemonSet workloads through an app entry
    # scheduled first (RunCluster order: cluster pods+DaemonSets, then apps)
    cluster_app = AppResource(name="cluster-workloads", objects=[metrics_server])
    result = simulate(cluster, [cluster_app, app])

    all_workloads = (
        [metrics_server]
        + list(cluster.daemonsets)
        + app.objects
        + [
            {"kind": "Pod", "metadata": {"name": p.meta.name,
                                         "namespace": p.meta.namespace}}
            for p in cluster.pods
        ]
    )
    _check_result(cluster, all_workloads, result, failed_pods_num=0)

    placed = {
        p.meta.name if not p.meta.annotations.get("simon/workload-name")
        else p.meta.annotations["simon/workload-name"]: st.node.name
        for st in result.node_status
        for p in st.pods
    }
    by_node = {
        st.node.name: [p for p in st.pods] for st in result.node_status
    }

    # static pods stayed pre-bound on master-1
    master1 = {p.meta.name for p in by_node["master-1"]}
    assert {"etcd-master-1", "kube-apiserver-master-1",
            "kube-controller-manager-master-1",
            "kube-scheduler-master-1"} <= master1

    def nodes_of(workload):
        return {
            st.node.name
            for st in result.node_status
            for p in st.pods
            if p.meta.annotations.get("simon/workload-name") == workload
        }

    # DaemonSet placement follows the daemon-controller predicates exactly
    assert nodes_of("kube-proxy-master") == {"master-1", "master-2", "master-3"}
    assert nodes_of("kube-proxy-worker") == {"worker-1"}
    assert nodes_of("coredns") == {"master-1", "master-2", "master-3"}
    assert nodes_of("busybox-ds") == {"worker-1"}

    # metrics-server: node-affinity restricts to masters, and without a
    # toleration the master-1 taint excludes it -> master-2 or master-3
    assert nodes_of("metrics-server") <= {"master-2", "master-3"}

    # single-pod: master nodeSelector + toleration -> any master
    single_nodes = {
        st.node.name
        for st in result.node_status
        for p in st.pods
        if p.meta.name == "single-pod"
    }
    assert single_nodes <= {"master-1", "master-2", "master-3"}
    assert len(single_nodes) == 1

    # the DaemonSet eligibility oracle agrees with the per-node expansion
    for ds in cluster.daemonsets + [app.objects[1]]:
        expected_nodes = {
            p.node_name or n.name
            for n in cluster.nodes
            for p in daemonset_pods(ds, [n])
        }
        name = ds["metadata"]["name"]
        assert nodes_of(name) == expected_nodes, name


def test_core_scenario_overload_fails_exact_count():
    """The same cluster with the app scaled past capacity reports exactly the
    overflow as unscheduled (failedPodsNum-style assertion with a non-zero
    expectation)."""
    cluster, metrics_server = _build_cluster()
    # 4 nodes × 8 cpu; busybox-deploy at 1500m per replica: the cluster fits
    # only so many after the cluster workloads — ask for far more
    objects = [
        _workload(
            "Deployment", "busybox-deploy", "simple", {"replicas": 30},
            _tmpl_spec(
                "1500m", "1Gi",
                tolerations=[
                    {
                        "effect": "NoSchedule",
                        "key": "node-role.kubernetes.io/master",
                        "operator": "Exists",
                    }
                ],
            ),
        ),
    ]
    app = AppResource(name="overload", objects=objects)
    cluster_app = AppResource(name="cluster-workloads", objects=[metrics_server])
    result = simulate(cluster, [cluster_app, app])
    # capacity arithmetic: per node 8000m minus cluster pods' requests;
    # every unscheduled pod must be a busybox-deploy replica and the
    # conservation oracle still balances
    assert result.unscheduled
    assert all(
        u.pod.meta.annotations.get("simon/workload-name") == "busybox-deploy"
        for u in result.unscheduled
    )
    all_workloads = (
        [metrics_server] + list(cluster.daemonsets) + objects
        + [
            {"kind": "Pod", "metadata": {"name": p.meta.name,
                                         "namespace": p.meta.namespace}}
            for p in cluster.pods
        ]
    )
    _check_result(
        cluster, all_workloads, result,
        failed_pods_num=len(result.unscheduled),
    )
    placed = sum(
        1
        for st in result.node_status
        for p in st.pods
        if p.meta.annotations.get("simon/workload-name") == "busybox-deploy"
    )
    assert placed + len(result.unscheduled) == 30
    # every unscheduled reason names the actual blockers
    for u in result.unscheduled:
        assert u.reason.startswith("0/4 nodes are available")
        assert "Insufficient" in u.reason