"""The `simon prove` reference oracle and small-scope checker.

Three layers, per the prover's trust chain:

* constants cross-check — oracle.py deliberately REDECLARES every shared
  contract constant (filter indices, weight fold order, f32 slack) instead
  of importing ops/kernels.py; these tests are the tripwire that catches a
  drift on either side before the prover silently compares two different
  contracts.
* hand-pinned universes — the oracle's verdicts on feasibility edges,
  score ties, priority presentation order, and unschedulable reason codes
  are asserted as literal values written straight from the kube contract,
  so the oracle cannot regress into merely agreeing with the engine.
* engine agreement + seeded mutation (slow, compile-heavy) — the same
  pinned universes run through the real vmapped engine via
  `check_universes`, and a perturbed commit rule must produce divergences
  and a minimized counterexample (the acceptance teeth of `simon prove`).
"""

import numpy as np
import pytest

from open_simulator_tpu.analysis import oracle
from open_simulator_tpu.analysis.semantics import SmallScope, Universe


@pytest.fixture(scope="module")
def scope():
    return SmallScope()


def _oracle(scope, nodes, pods):
    u = Universe(nodes, pods)
    return oracle.schedule(scope.oracle_table(u), scope.oracle_batch(u))


# ---------------------------------------------------------------------------
# shared contract constants: redeclared in oracle.py, cross-checked here
# ---------------------------------------------------------------------------

def test_filter_indices_match_kernels():
    from open_simulator_tpu.ops import kernels as k

    names = (
        "F_UNSCHEDULABLE", "F_NODE_NAME", "F_TAINT", "F_NODE_AFFINITY",
        "F_NODE_PORTS", "F_RESOURCES", "F_SPREAD", "F_POD_AFFINITY",
        "F_STORAGE", "F_GPU", "F_EXTRA", "NUM_FILTERS",
    )
    for name in names:
        assert getattr(oracle, name) == getattr(k, name), name


def test_weights_and_fold_order_match_kernels():
    from open_simulator_tpu.ops import kernels as k

    assert oracle.DEFAULT_WEIGHTS == k.DEFAULT_WEIGHTS
    assert oracle.WEIGHT_ORDER == k.WEIGHT_ORDER


def test_eps_and_encode_vocab_match():
    from open_simulator_tpu.ops import encode, kernels as k

    assert oracle.EPS == np.float32(k._EPS)
    assert oracle.GPU_COUNT_IDX == encode.GPU_COUNT_IDX
    assert (
        oracle.OP_PAD, oracle.OP_IN, oracle.OP_NOT_IN, oracle.OP_EXISTS,
        oracle.OP_NOT_EXISTS, oracle.OP_GT, oracle.OP_LT,
    ) == (
        encode.OP_PAD, encode.OP_IN, encode.OP_NOT_IN, encode.OP_EXISTS,
        encode.OP_NOT_EXISTS, encode.OP_GT, encode.OP_LT,
    )


# ---------------------------------------------------------------------------
# hand-pinned universes: literal verdicts from the kube contract
# ---------------------------------------------------------------------------

def test_feasibility_edge_exact_fit(scope):
    # node B is 2 cpu / 4 Gi; pod p is 1 cpu / 2 Gi: exactly two fit (the
    # f32 comparison slack must not admit a third), the rest report the
    # resources filter as the first failure.
    r = _oracle(scope, "B---", "ppppp")
    assert r.nodes[:5].tolist() == [0, 0, -1, -1, -1]
    for row in (2, 3, 4):
        assert r.reasons[row, oracle.F_RESOURCES] == 1
        assert r.reasons[row].sum() == 1


def test_score_tie_breaks_to_lowest_node_index(scope):
    # two identical A nodes: every plugin scores them equally for the first
    # pod, and the contract's tie-break is argmax-lowest-index — node 0.
    r = _oracle(scope, "AA--", "ppppp")
    assert r.nodes[0] == 0
    # subsequent pods alternate as least-allocated rebalances
    assert r.nodes[:5].tolist() == [0, 1, 0, 1, 0]


def test_priority_presentation_order(scope):
    # q (prio 10) is presented before the slot-earlier p's (prio 0): it
    # claims its 2 cpu first, so only two p's fit behind it. The scan
    # engine models priority by presentation order, not eviction.
    rows = scope.pod_rows(Universe("A---", "ppppq"))
    r = _oracle(scope, "A---", "ppppq")
    # q is catalog row 1: despite sitting in the last slot it is presented
    # first (descending priority, stable slot index — the contract clause)
    assert rows[0] == 1 and rows[1:5] == [0, 0, 0, 0]
    assert r.nodes[:5].tolist() == [0, 0, 0, -1, -1]
    assert r.reasons[3, oracle.F_RESOURCES] == 1
    assert r.reasons[4, oracle.F_RESOURCES] == 1


def test_unschedulable_reason_codes(scope):
    # cordoned node -> unschedulable filter
    r = _oracle(scope, "D---", "ppppp")
    assert (r.nodes[:5] == -1).all()
    assert (r.reasons[:5, oracle.F_UNSCHEDULABLE] == 1).all()
    # tier=a nodeSelector vs tier=b node -> node-affinity filter
    r = _oracle(scope, "B---", "qqqqq")
    assert (r.nodes[:5] == -1).all()
    assert (r.reasons[:5, oracle.F_NODE_AFFINITY] == 1).all()
    # GPU-share pod vs GPU-less node -> gpu filter
    r = _oracle(scope, "A---", "rrrrr")
    assert (r.nodes[:5] == -1).all()
    assert (r.reasons[:5, oracle.F_GPU] == 1).all()


def test_gpu_share_commit_and_exhaustion(scope):
    # C carries 2 devices x 8 Gi; r takes a 4 Gi share: four shares total,
    # the fifth r fails the gpu filter with every share consumed.
    r = _oracle(scope, "C---", "rrrrr")
    assert r.nodes[:5].tolist() == [0, 0, 0, 0, -1]
    assert r.gpu_take[:4].sum(axis=1).tolist() == [1, 1, 1, 1]
    assert r.reasons[4, oracle.F_GPU] == 1
    assert float(r.carry.gpu_free.sum()) == 0.0


def test_pad_rows_are_inert(scope):
    # P is padded to 8: pad rows place nowhere and report nothing
    r = _oracle(scope, "B---", "ppppp")
    assert (r.nodes[5:] == -1).all()
    assert r.reasons[5:].sum() == 0


# ---------------------------------------------------------------------------
# CLI plumbing: exit codes and json shape, no device work
# ---------------------------------------------------------------------------

def _fake_report(diverging: bool):
    from open_simulator_tpu.analysis.semantics import Divergence, ProveReport

    rep = ProveReport(universes_checked=7, device_calls=1, digest="sha256:x")
    if diverging:
        rep.divergence_total = 1
        rep.divergences = [Divergence("AA--/ppppp", "nodes", "1", "0")]
        rep.minimized = "--AA/ppppp"
    return rep


def test_cli_prove_exit_codes(monkeypatch, capsys):
    import json

    from open_simulator_tpu.analysis import semantics
    from open_simulator_tpu.cli import main as cli

    monkeypatch.setattr(
        semantics, "run_prove", lambda **kw: _fake_report(False)
    )
    assert cli.main(["prove", "--format=json", "--smoke", "7"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True and doc["universes_checked"] == 7

    monkeypatch.setattr(
        semantics, "run_prove", lambda **kw: _fake_report(True)
    )
    assert cli.main(["prove", "--format=json", "--smoke", "7"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False
    assert doc["minimized_counterexample"] == "--AA/ppppp"


# ---------------------------------------------------------------------------
# engine agreement + seeded mutations (compile-heavy)
# ---------------------------------------------------------------------------

PINNED = [
    Universe("B---", "ppppp"),
    Universe("AA--", "ppppp"),
    Universe("D---", "ppppp"),
    Universe("B---", "qqqqq"),
    Universe("A---", "rrrrr"),
    Universe("A---", "ppppq"),
    Universe("C---", "rrrrr"),
]


@pytest.mark.slow
def test_pinned_universes_match_live_engine(scope):
    from open_simulator_tpu.analysis.semantics import check_universes

    report = check_universes(scope, PINNED)
    assert report.ok, report.render_text()
    assert report.universes_checked == len(PINNED)
    assert report.device_calls == 1
    assert report.digest.startswith("sha256:")


@pytest.mark.slow
def test_mutated_tiebreak_is_caught_and_minimized(scope):
    from open_simulator_tpu.analysis.semantics import (
        check_universes,
        minimize,
    )

    # highest-index tie-break flips the AA tie; non-tied universes still
    # agree, so the divergence is attributable to the seeded rule change
    report = check_universes(scope, PINNED, mutate="tiebreak")
    assert report.divergence_total > 0
    bad = report.divergences[0].universe.split("/")
    small = minimize(scope, Universe(*bad), "tiebreak")
    # the minimized counterexample still diverges and is no larger
    assert len(small.nodes.replace("-", "")) <= len(
        bad[0].replace("-", "")
    )


@pytest.mark.slow
def test_mutated_nocommit_is_caught(scope):
    from open_simulator_tpu.analysis.semantics import check_universes

    # dropping the carry thread makes every pod see the pristine cluster:
    # the feasibility-edge universe must diverge on placements or carry
    report = check_universes(scope, PINNED, mutate="nocommit")
    assert report.divergence_total > 0
    assert not report.ok
