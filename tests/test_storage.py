"""Open-Local storage plugin: LVM VG binpack + exclusive-device allocation.

Parity targets:
  - Filter/Score/Bind: /root/reference/pkg/simulator/plugin/open-local.go
  - ProcessLVMPVCPredicate / Binpack / ProcessDevicePVC / ScoreLVM / ScoreDevice:
    vendor/github.com/alibaba/open-local/pkg/scheduler/algorithm/algo/common.go
  - annotation codecs: pkg/utils/utils.go:510-625
"""

import json

import numpy as np
import pytest

from open_simulator_tpu.core.objects import (
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_POD_LOCAL_STORAGE,
    Node,
    NodeLocalStorage,
    Pod,
)
from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    simulate,
)
from open_simulator_tpu.ops.encode import (
    Encoder,
    encode_nodes,
    encode_pods,
    initial_selector_counts,
)
from open_simulator_tpu.ops.kernels import (
    F_STORAGE,
    schedule_batch,
    weights_array,
)
from open_simulator_tpu.ops.state import (
    carry_from_table,
    node_static_from_table,
    pod_rows_from_batch,
)

GiB = 1 << 30


def storage_node(name, vgs=(), devices=(), cpu="8", mem="16Gi"):
    d = {
        "metadata": {"name": name},
        "status": {"allocatable": {"cpu": cpu, "memory": mem, "pods": "110"}},
    }
    node = Node.from_dict(d)
    if vgs or devices:
        node.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = json.dumps(
            {
                "vgs": [
                    {"name": n, "capacity": str(c), "requested": str(r)}
                    for n, c, r in vgs
                ],
                "devices": [
                    {
                        "name": n,
                        "device": n,
                        "capacity": str(c),
                        "mediaType": m,
                        "isAllocated": a,
                    }
                    for n, c, m, a in devices
                ],
            }
        )
    return node


def storage_pod(name, volumes):
    return Pod.from_dict(
        {
            "metadata": {
                "name": name,
                "namespace": "stor",
                "annotations": {
                    ANNO_POD_LOCAL_STORAGE: json.dumps({"volumes": volumes})
                },
            },
            "spec": {
                "containers": [
                    {
                        "name": "c",
                        "image": "img",
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "128Mi"}
                        },
                    }
                ]
            },
        }
    )


def lvm_vol(size, sc="open-local-lvm", vg=""):
    v = {"size": str(size), "kind": "LVM", "scName": sc}
    if vg:
        v["vgName"] = vg
    return v


def dev_vol(size, media="ssd"):
    kind = media.upper()
    return {
        "size": str(size),
        "kind": kind,
        "scName": f"open-local-device-{media}",
    }


def run_batch(nodes, pods):
    enc = Encoder()
    enc.register_pods(pods)
    table = encode_nodes(enc, nodes)
    batch = encode_pods(enc, pods)
    ns = node_static_from_table(enc, table)
    carry = carry_from_table(table, initial_selector_counts(enc, table, []))
    rows = pod_rows_from_batch(batch)
    fc, placed, reasons, *_ = schedule_batch(ns, carry, rows, weights_array())
    names = [table.names[i] if i >= 0 else None for i in np.asarray(placed)[: len(pods)]]
    return names, np.asarray(reasons), fc, table


# ---------------------------------------------------------------------------
# annotation codecs
# ---------------------------------------------------------------------------

def test_node_storage_codec():
    node = storage_node(
        "n",
        vgs=[("pool0", 100 * GiB, 5 * GiB)],
        devices=[("/dev/vdd", 50 * GiB, "hdd", "false")],
    )
    st = node.local_storage()
    assert st is not None
    assert st.vgs[0].name == "pool0"
    assert st.vgs[0].capacity == 100 * GiB
    assert st.vgs[0].requested == 5 * GiB
    assert st.devices[0].name == "/dev/vdd"
    assert st.devices[0].media_type == "hdd"
    assert not st.devices[0].is_allocated
    assert Node.from_dict({"metadata": {"name": "x"}}).local_storage() is None


def test_pod_volume_split():
    pod = storage_pod(
        "p",
        [
            lvm_vol(5 * GiB),
            dev_vol(10 * GiB, "ssd"),
            dev_vol(20 * GiB, "hdd"),
            {"size": "1", "kind": "Bogus", "scName": "open-local-lvm"},
        ],
    )
    lvm, dev = pod.local_volumes()
    assert [v.size for v in lvm] == [5 * GiB]
    assert sorted(v.size for v in dev) == [10 * GiB, 20 * GiB]
    assert {v.media_type for v in dev} == {"ssd", "hdd"}


# ---------------------------------------------------------------------------
# LVM binpack semantics
# ---------------------------------------------------------------------------

def test_lvm_binpack_prefers_smallest_fitting_vg():
    nodes = [
        storage_node("big", vgs=[("pool0", 100 * GiB, 0)]),
        storage_node("small", vgs=[("pool0", 10 * GiB, 0)]),
    ]
    names, _, _, _ = run_batch(nodes, [storage_pod("p", [lvm_vol(5 * GiB)])])
    # ScoreLVM(Binpack) rewards the higher used/capacity fraction -> "small"
    assert names == ["small"]


def test_lvm_binpack_across_vgs_on_one_node():
    # Two VGs: request fits only the bigger one once the smaller fills up.
    nodes = [
        storage_node("n", vgs=[("pool0", 8 * GiB, 0), ("pool1", 40 * GiB, 0)])
    ]
    pods = [
        storage_pod("a", [lvm_vol(6 * GiB)]),   # -> pool0 (smallest fit)
        storage_pod("b", [lvm_vol(6 * GiB)]),   # pool0 has 2GiB left -> pool1
        storage_pod("c", [lvm_vol(40 * GiB)]),  # pool1 has 34GiB left -> fail
    ]
    names, reasons, fc, _ = run_batch(nodes, pods)
    assert names[:2] == ["n", "n"]
    assert names[2] is None
    assert reasons[2][F_STORAGE] == 1
    vg_free = np.asarray(fc.vg_free)[0]
    assert vg_free[0] == pytest.approx(2 * 1024, abs=1)      # pool0: 2GiB left
    assert vg_free[1] == pytest.approx(34 * 1024, abs=1)     # pool1: 34GiB left


def test_lvm_explicit_vg_name():
    nodes = [
        storage_node("n", vgs=[("alpha", 50 * GiB, 0), ("beta", 50 * GiB, 0)])
    ]
    names, _, fc, _ = run_batch(
        nodes, [storage_pod("p", [lvm_vol(10 * GiB, vg="beta")])]
    )
    assert names == ["n"]
    vg_free = np.asarray(fc.vg_free)[0]
    assert vg_free[0] == pytest.approx(50 * 1024, abs=1)   # alpha untouched
    assert vg_free[1] == pytest.approx(40 * 1024, abs=1)   # beta charged


def test_lvm_explicit_vg_allocated_before_binpack():
    # Reference order: pvcsWithVG first (common.go:59-75). A binpack volume
    # listed earlier in the annotation must NOT steal the explicit volume's VG.
    nodes = [
        storage_node("n", vgs=[("vg1", 100 * GiB, 0), ("vg2", 120 * GiB, 0)])
    ]
    pods = [
        storage_pod("p", [lvm_vol(90 * GiB), lvm_vol(90 * GiB, vg="vg1")])
    ]
    names, _, fc, _ = run_batch(nodes, pods)
    assert names == ["n"]
    vg_free = np.asarray(fc.vg_free)[0]
    assert vg_free[0] == pytest.approx(10 * 1024, abs=1)   # vg1: explicit
    assert vg_free[1] == pytest.approx(30 * 1024, abs=1)   # vg2: binpack


def test_lvm_missing_vg_fails():
    nodes = [storage_node("n", vgs=[("alpha", 50 * GiB, 0)])]
    names, reasons, _, _ = run_batch(
        nodes, [storage_pod("p", [lvm_vol(1 * GiB, vg="nope")])]
    )
    assert names == [None]
    assert reasons[0][F_STORAGE] == 1


def test_initial_requested_is_respected():
    # 10GiB VG with 8GiB already requested can't take 5GiB.
    nodes = [storage_node("n", vgs=[("pool0", 10 * GiB, 8 * GiB)])]
    names, _, _, _ = run_batch(nodes, [storage_pod("p", [lvm_vol(5 * GiB)])])
    assert names == [None]


def test_no_storage_node_rejects_storage_pod():
    nodes = [storage_node("plain")]  # no annotation
    names, reasons, _, _ = run_batch(
        nodes, [storage_pod("p", [lvm_vol(1 * GiB)])]
    )
    assert names == [None]
    assert reasons[0][F_STORAGE] == 1


def test_storage_free_pod_ignores_storage():
    nodes = [storage_node("plain")]
    pod = Pod.from_dict(
        {
            "metadata": {"name": "p", "namespace": "stor"},
            "spec": {
                "containers": [
                    {"name": "c", "image": "img", "resources": {"requests": {"cpu": "1"}}}
                ]
            },
        }
    )
    names, _, _, _ = run_batch(nodes, [pod])
    assert names == ["plain"]


# ---------------------------------------------------------------------------
# exclusive devices
# ---------------------------------------------------------------------------

def test_device_exclusive_allocation():
    nodes = [
        storage_node(
            "n",
            devices=[("/dev/vdd", 100 * GiB, "ssd", "false")],
        )
    ]
    pods = [
        storage_pod("a", [dev_vol(10 * GiB, "ssd")]),
        storage_pod("b", [dev_vol(10 * GiB, "ssd")]),  # device taken -> fail
    ]
    names, reasons, fc, _ = run_batch(nodes, pods)
    assert names == ["n", None]
    assert reasons[1][F_STORAGE] == 1
    assert np.asarray(fc.dev_free)[0, 0] == 0.0


def test_device_media_type_must_match():
    nodes = [
        storage_node("n", devices=[("/dev/vdd", 100 * GiB, "hdd", "false")])
    ]
    names, _, _, _ = run_batch(nodes, [storage_pod("p", [dev_vol(GiB, "ssd")])])
    assert names == [None]


def test_device_tightest_fit():
    # Smallest device with enough capacity wins (ascending walk parity).
    nodes = [
        storage_node(
            "n",
            devices=[
                ("/dev/big", 100 * GiB, "ssd", "false"),
                ("/dev/small", 20 * GiB, "ssd", "false"),
            ],
        )
    ]
    names, _, fc, _ = run_batch(
        nodes, [storage_pod("p", [dev_vol(10 * GiB, "ssd")])]
    )
    assert names == ["n"]
    dev_free = np.asarray(fc.dev_free)[0]
    assert dev_free[0] == 1.0   # big stays free
    assert dev_free[1] == 0.0   # small allocated


def test_device_pre_allocated_is_skipped():
    nodes = [
        storage_node("n", devices=[("/dev/vdd", 100 * GiB, "ssd", "true")])
    ]
    names, _, _, _ = run_batch(
        nodes, [storage_pod("p", [dev_vol(GiB, "ssd")])]
    )
    assert names == [None]


def test_multi_volume_pod():
    nodes = [
        storage_node(
            "n",
            vgs=[("pool0", 50 * GiB, 0)],
            devices=[
                ("/dev/vdd", 30 * GiB, "ssd", "false"),
                ("/dev/vde", 30 * GiB, "hdd", "false"),
            ],
        )
    ]
    pods = [
        storage_pod(
            "p",
            [lvm_vol(10 * GiB), dev_vol(5 * GiB, "ssd"), dev_vol(5 * GiB, "hdd")],
        )
    ]
    names, _, fc, _ = run_batch(nodes, pods)
    assert names == ["n"]
    assert np.asarray(fc.vg_free)[0, 0] == pytest.approx(40 * 1024, abs=1)
    assert np.asarray(fc.dev_free)[0].tolist() == [0.0, 0.0]


# ---------------------------------------------------------------------------
# end-to-end via simulate() with STS volumeClaimTemplates
# ---------------------------------------------------------------------------

def test_statefulset_volume_claims_end_to_end():
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": "db", "namespace": "stor"},
        "spec": {
            "replicas": 2,
            "template": {
                "metadata": {"labels": {"app": "db"}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "img",
                            "resources": {
                                "requests": {"cpu": "100m", "memory": "128Mi"}
                            },
                        }
                    ]
                },
            },
            "volumeClaimTemplates": [
                {
                    "metadata": {"name": "data"},
                    "spec": {
                        "storageClassName": "open-local-lvm",
                        "resources": {"requests": {"storage": "8Gi"}},
                    },
                }
            ],
        },
    }
    cluster = ClusterResource(
        nodes=[
            storage_node("w1", vgs=[("pool0", 10 * GiB, 0)]),
            storage_node("w2", vgs=[("pool0", 10 * GiB, 0)]),
        ]
    )
    result = simulate(cluster, [AppResource(name="db", objects=[sts])])
    # each 8GiB claim fills most of one 10GiB VG; two replicas need two nodes
    assert not result.unscheduled
    placed_nodes = {
        st.node.name for st in result.node_status if st.pods
    }
    assert placed_nodes == {"w1", "w2"}
    # result.storage reflects the committed requests
    for name in ("w1", "w2"):
        vg = result.storage[name].vgs[0]
        assert vg.requested == pytest.approx(8 * GiB, rel=1e-6)


def test_capacity_exhaustion_reports_storage_reason():
    sts_vol = [lvm_vol(8 * GiB)]
    cluster = ClusterResource(nodes=[storage_node("w1", vgs=[("pool0", 10 * GiB, 0)])])
    pods = [storage_pod("a", sts_vol), storage_pod("b", sts_vol)]
    cluster.pods.extend(pods)
    result = simulate(cluster, [])
    assert len(result.unscheduled) == 1
    assert "local storage" in result.unscheduled[0].reason
