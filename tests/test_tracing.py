"""Observability subsystem: spans, LogLevel, slow-trace, /debug/timings."""

import json
import logging
import threading
import urllib.request

from open_simulator_tpu.utils import tracing


def test_span_nesting_and_history():
    with tracing.span("root", kind="test") as root:
        with tracing.span("child"):
            pass
        with tracing.span("child2"):
            pass
    assert [c.name for c in root.children] == ["child", "child2"]
    latest = tracing.recent_timings()[-1]
    assert latest["name"] == "root"
    assert latest["meta"] == {"kind": "test"}
    assert [c["name"] for c in latest["children"]] == ["child", "child2"]


def test_span_to_dict_records_start_timestamp():
    import time

    before = time.time()
    with tracing.span("stamped"):
        pass
    latest = tracing.recent_timings()[-1]
    assert before - 1 <= latest["start"] <= time.time()
    # entries are orderable by wall clock
    with tracing.span("stamped2"):
        pass
    t2 = tracing.recent_timings()[-1]
    assert t2["start"] >= latest["start"]


def test_span_history_env_override(monkeypatch):
    monkeypatch.setenv("OSIM_SPAN_HISTORY", "3")
    for i in range(5):
        with tracing.span(f"h{i}"):
            pass
    names = [r["name"] for r in tracing.recent_timings()]
    assert len(names) == 3
    assert names == ["h2", "h3", "h4"]
    # malformed values fall back to the default instead of raising
    monkeypatch.setenv("OSIM_SPAN_HISTORY", "lots")
    with tracing.span("h5"):
        pass
    assert tracing.recent_timings()[-1]["name"] == "h5"


def test_slow_trace_logs_warning(monkeypatch, caplog):
    monkeypatch.setattr(tracing, "SLOW_TRACE_S", 0.0)
    with caplog.at_level(logging.WARNING, logger="osim"):
        with tracing.span("slowroot"):
            pass
    assert any("slow trace" in r.message for r in caplog.records)
    assert any("slowroot" in r.getMessage() for r in caplog.records)


def test_init_logging_loglevel_env(monkeypatch):
    monkeypatch.setenv("LogLevel", "debug")
    tracing.init_logging()
    assert tracing.log.level == logging.DEBUG
    monkeypatch.setenv("LogLevel", "bogus")
    tracing.init_logging()
    assert tracing.log.level == logging.INFO


def test_simulate_emits_spans():
    from open_simulator_tpu.core.objects import Node
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        simulate,
    )

    nodes = [
        Node.from_dict(
            {
                "metadata": {"name": "n0", "labels": {"kubernetes.io/hostname": "n0"}},
                "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}},
            }
        )
    ]
    deploy = {
        "kind": "Deployment",
        "metadata": {"name": "d", "namespace": "x"},
        "spec": {
            "replicas": 2,
            "template": {
                "metadata": {"labels": {"app": "d"}},
                "spec": {
                    "containers": [
                        {"name": "c", "image": "i",
                         "resources": {"requests": {"cpu": "1"}}}
                    ]
                },
            },
        },
    }
    simulate(ClusterResource(nodes=nodes), [AppResource(name="a", objects=[deploy])])
    roots = tracing.recent_timings()
    sim = [r for r in roots if r["name"] == "simulate"][-1]
    child_names = [c["name"] for c in sim["children"]]
    assert "expand-workloads" in child_names
    assert "encode-cluster" in child_names
    assert "decode-result" in child_names


def test_server_debug_timings_endpoint():
    from open_simulator_tpu.server.server import make_server

    httpd = make_server(0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with tracing.span("server-visible"):
            pass
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/timings", timeout=5
        ) as resp:
            payload = json.loads(resp.read())
        assert any(r["name"] == "server-visible" for r in payload["timings"])
    finally:
        httpd.shutdown()
        httpd.server_close()
