"""AST lint engine + rules: per-rule true positive and near-miss fixtures.

Each rule gets (at least) one fixture snippet that MUST be flagged and one
superficially similar snippet that MUST NOT be (the near-miss false
positive). Fixture packages are written to tmp_path and only parsed —
never imported — so snippets are free to reference jax without tracing
anything.
"""

import json
import textwrap

from open_simulator_tpu.analysis import iter_rules, run_lint
from open_simulator_tpu.analysis.lint import build_context


def _lint(tmp_path, source, extra_modules=None, only_rules=None):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    for name, src in (extra_modules or {}).items():
        (pkg / f"{name}.py").write_text(textwrap.dedent(src))
    return run_lint(
        package_root=str(pkg), report_root=str(tmp_path), only_rules=only_rules
    )


def _rules_hit(report):
    return {(f.rule, f.line) for f in report.active}


def _rule_ids(report):
    return {f.rule for f in report.active}


# ---------------------------------------------------------------------------
# tracer-coercion


def test_tracer_coercion_true_positive(tmp_path):
    r = _lint(
        tmp_path,
        """
        import jax

        @jax.jit
        def kern(x):
            v = float(x)
            w = x.item()
            return v + w
        """,
    )
    assert sum(f.rule == "tracer-coercion" for f in r.active) == 2


def test_tracer_coercion_near_miss_static_and_host(tmp_path):
    """float() of a shape (static) and float() in host-only code are fine."""
    r = _lint(
        tmp_path,
        """
        import jax

        @jax.jit
        def kern(x):
            return x * float(x.shape[0])

        def host(x):
            return float(x)
        """,
    )
    assert "tracer-coercion" not in _rule_ids(r)


def test_tracer_coercion_np_asarray(tmp_path):
    r = _lint(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def kern(x):
            return np.asarray(x)

        @jax.jit
        def kern_ok(x):
            return x + np.zeros(4)[0]
        """,
    )
    hits = [f for f in r.active if f.rule == "tracer-coercion"]
    assert len(hits) == 1 and "asarray" in hits[0].message


# ---------------------------------------------------------------------------
# impure-read


def test_impure_read_true_positive(tmp_path):
    r = _lint(
        tmp_path,
        """
        import os
        import time
        import random
        import jax

        @jax.jit
        def kern(x):
            t = time.time()
            e = os.environ.get("K")
            z = random.random()
            return x + t + z
        """,
    )
    assert sum(f.rule == "impure-read" for f in r.active) == 3


def test_impure_read_near_miss_host_only(tmp_path):
    """The same reads outside jit-reachable code are host configuration."""
    r = _lint(
        tmp_path,
        """
        import os
        import time
        import jax

        def configure():
            return float(os.environ.get("K", "1")) + time.time()

        @jax.jit
        def kern(x):
            return x * 2
        """,
    )
    assert "impure-read" not in _rule_ids(r)


# ---------------------------------------------------------------------------
# unhashable-static-default


def test_unhashable_static_default_true_positive(tmp_path):
    r = _lint(
        tmp_path,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def kern(x, opts=[]):
            return x
        """,
    )
    assert "unhashable-static-default" in _rule_ids(r)


def test_unhashable_static_default_near_miss(tmp_path):
    """Tuple defaults on static args and list defaults on TRACED args are
    both fine (only static args become cache keys)."""
    r = _lint(
        tmp_path,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def kern(x, opts=(), scales=None):
            return x
        """,
    )
    assert "unhashable-static-default" not in _rule_ids(r)


def test_unhashable_static_default_jit_alias_form(tmp_path):
    """`name = jax.jit(fn, static_argnames=...)` marks fn as an entry too."""
    r = _lint(
        tmp_path,
        """
        import jax

        def kern(x, opts=[]):
            return x

        kern_jit = jax.jit(kern, static_argnames=("opts",))
        """,
    )
    assert "unhashable-static-default" in _rule_ids(r)


# ---------------------------------------------------------------------------
# import-time-jnp


def test_import_time_jnp_true_positive(tmp_path):
    r = _lint(
        tmp_path,
        """
        import jax.numpy as jnp

        TABLE = jnp.arange(16)
        """,
    )
    assert "import-time-jnp" in _rule_ids(r)


def test_import_time_jnp_near_miss(tmp_path):
    """jnp inside functions and module-level *numpy* constants are fine."""
    r = _lint(
        tmp_path,
        """
        import jax.numpy as jnp
        import numpy as np

        TABLE = np.arange(16)

        def build():
            return jnp.arange(16)
        """,
    )
    assert "import-time-jnp" not in _rule_ids(r)


# ---------------------------------------------------------------------------
# f64-literal (scoped to ops/ modules)


def test_f64_literal_true_positive(tmp_path):
    pkg = tmp_path / "pkg"
    ops = pkg / "ops"
    ops.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (ops / "__init__.py").write_text("")
    (ops / "k.py").write_text(
        textwrap.dedent(
            """
            import numpy as np

            def f(x):
                return np.zeros(4, np.float64), x.astype(float)
            """
        )
    )
    r = run_lint(package_root=str(pkg), report_root=str(tmp_path))
    assert sum(f.rule == "f64-literal" for f in r.active) == 2


def test_f64_literal_near_miss_outside_ops(tmp_path):
    """float64 outside ops/ (report layer etc.) is out of scope; float32
    inside ops/ is the blessed dtype."""
    pkg = tmp_path / "pkg"
    ops = pkg / "ops"
    ops.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (ops / "__init__.py").write_text("")
    (pkg / "report.py").write_text("import numpy as np\nX = np.float64(0)\n")
    (ops / "k.py").write_text("import numpy as np\nY = np.zeros(4, np.float32)\n")
    r = run_lint(package_root=str(pkg), report_root=str(tmp_path))
    assert "f64-literal" not in _rule_ids(r)


# ---------------------------------------------------------------------------
# unbucketed-jit-shape


_SHAPE_PKG = {
    "encode": """
        def round_up(n, minimum=8):
            m = minimum
            while m < n:
                m *= 2
            return m
        """,
}


def test_unbucketed_shape_true_positive(tmp_path):
    r = _lint(
        tmp_path,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("out_size",))
        def sized(x, out_size):
            return x[:out_size]

        def host(xs):
            n = len(xs)
            return sized(xs, n)
        """,
        extra_modules=_SHAPE_PKG,
    )
    assert "unbucketed-jit-shape" in _rule_ids(r)


def test_unbucketed_shape_near_miss_bucketed(tmp_path):
    """Sizes that provably flow through round_up (directly, via a local, or
    via min/max composition) are the blessed pattern."""
    r = _lint(
        tmp_path,
        """
        import functools
        import jax

        from .encode import round_up

        @functools.partial(jax.jit, static_argnames=("out_size",))
        def sized(x, out_size):
            return x[:out_size]

        def host(xs):
            g = round_up(len(xs))
            return sized(xs, g), sized(xs, min(round_up(4), 64))
        """,
        extra_modules=_SHAPE_PKG,
    )
    assert "unbucketed-jit-shape" not in _rule_ids(r)


def test_unbucketed_shape_wrapper_propagation(tmp_path):
    """A thin wrapper forwarding its own param into a jit shape arg moves
    the obligation to the wrapper's call sites (the _group_call pattern)."""
    r = _lint(
        tmp_path,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("group_size",))
        def kern(x, group_size):
            return x[:group_size]

        def wrapper(x, group_size):
            return kern(x, group_size=group_size)

        def host_bad(xs):
            return wrapper(xs, len(xs))
        """,
        extra_modules=_SHAPE_PKG,
    )
    hits = [f for f in r.active if f.rule == "unbucketed-jit-shape"]
    assert len(hits) == 1  # the wrapper call site, not the wrapper body


# ---------------------------------------------------------------------------
# device-sync-in-loop


def test_device_sync_in_loop_true_positive(tmp_path):
    r = _lint(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def kern(x):
            return x * 2

        def drive(xs):
            acc = []
            for x in xs:
                y = kern(x)
                acc.append(np.asarray(y))
                y.block_until_ready()
            return acc
        """,
    )
    hits = [f for f in r.active if f.rule == "device-sync-in-loop"]
    assert len(hits) == 2
    assert any("np.asarray" in f.message for f in hits)
    assert any("block_until_ready" in f.message for f in hits)


def test_device_sync_near_miss_host_numpy_and_epilogue(tmp_path):
    """Coercing genuine numpy state in the loop is host arithmetic, and a
    one-shot sync after the loop is the blessed shape — neither flags."""
    r = _lint(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def kern(x):
            return x * 2

        def drive(xs, hosts):
            outs = []
            for x, h in zip(xs, hosts):
                outs.append(kern(x))
                total = float(np.sum(h))  # host state, not a jit result
            return np.asarray(outs[-1]), total
        """,
    )
    assert "device-sync-in-loop" not in _rule_ids(r)


def test_device_sync_near_miss_consolidated_device_get(tmp_path):
    """One jax.device_get over the batch is the idiom the rule pushes
    toward; a host-returning wrapper that fetches internally is likewise
    not jit-ish, so loops around it are free to coerce its results."""
    r = _lint(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def kern(x):
            return x * 2

        def kern_host(x):
            return jax.device_get(kern(x))

        def drive(xs):
            outs = [kern(x) for x in xs]
            fetched = []
            for x in xs:
                y = kern_host(x)
                fetched.append(float(np.sum(y)))
            return jax.device_get(outs), fetched
        """,
    )
    assert "device-sync-in-loop" not in _rule_ids(r)


def test_device_sync_suppression_escape(tmp_path):
    """A deliberate per-iteration sync takes the standard comment escape."""
    r = _lint(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def kern(x):
            return x * 2

        def drive(xs):
            for x in xs:
                y = kern(x)
                # the mask gates the next dispatch; the sync is the point
                m = np.asarray(y)  # osim: lint-ok[device-sync-in-loop]
                if not m.any():
                    break
        """,
    )
    assert "device-sync-in-loop" not in _rule_ids(r)
    assert sum(f.suppressed for f in r.findings) == 1


# ---------------------------------------------------------------------------
# engine machinery


def test_reachability_through_helpers_and_scan(tmp_path):
    """Violations in helpers are attributed to the jit root that reaches
    them, including scan-body functions passed to jax.lax.scan."""
    r = _lint(
        tmp_path,
        """
        import time
        import jax

        def step(c, x):
            return c + time.time(), x

        def helper(x):
            return float(x)

        @jax.jit
        def kern(xs):
            out, _ = jax.lax.scan(step, 0.0, xs)
            return helper(out)

        def unreached(x):
            return float(x)
        """,
    )
    assert ("impure-read" in _rule_ids(r)) and ("tracer-coercion" in _rule_ids(r))
    roots = {f.jit_root for f in r.active if f.jit_root}
    assert roots == {"pkg.mod:kern"}
    flagged_lines = {f.line for f in r.active}
    assert not any(
        f.line > 17 for f in r.active
    ), f"unreached host fn must not be flagged: {flagged_lines}"


def test_suppression_comment(tmp_path):
    r = _lint(
        tmp_path,
        """
        import time
        import jax

        @jax.jit
        def kern(x):
            # trace-time constant is intentional here (test fixture)
            t = time.time()  # osim: lint-ok[impure-read]
            return x + t
        """,
    )
    assert not r.active
    assert sum(f.suppressed for f in r.findings) == 1


def test_suppression_is_rule_specific(tmp_path):
    """A lint-ok for one rule must not swallow a different rule's finding
    on the same line."""
    r = _lint(
        tmp_path,
        """
        import time
        import jax

        @jax.jit
        def kern(x):
            t = float(time.time())  # osim: lint-ok[impure-read]
            return x + t
        """,
    )
    assert _rule_ids(r) == {"tracer-coercion"}


def test_json_output_schema(tmp_path):
    r = _lint(
        tmp_path,
        """
        import jax

        @jax.jit
        def kern(x):
            return float(x)
        """,
    )
    doc = json.loads(r.to_json())
    assert doc["version"] == 1
    assert doc["files_scanned"] >= 2
    assert doc["rules"] == sorted(rid for rid, _ in iter_rules())
    (finding,) = doc["findings"]
    assert finding["rule"] == "tracer-coercion"
    assert finding["path"].endswith("mod.py")
    assert finding["line"] > 0 and "message" in finding


def test_rule_filter(tmp_path):
    r = _lint(
        tmp_path,
        """
        import time
        import jax

        @jax.jit
        def kern(x):
            return float(x) + time.time()
        """,
        only_rules=["impure-read"],
    )
    assert _rule_ids(r) == {"impure-read"}


def test_repo_package_is_lint_clean():
    """The acceptance gate: `simon lint` exits 0 on the repository, and
    every surviving suppression is justified (non-empty neighbour comment)."""
    report = run_lint()
    assert not report.active, report.render_text()
    ctx = build_context()
    for mod in ctx.modules.values():
        for line_no in mod.suppressions:
            window = mod.lines[max(0, line_no - 3): line_no]
            assert any(
                "#" in line for line in window
            ), f"{mod.path}:{line_no}: suppression lacks a justification comment"


def test_repo_jit_roots_discovered():
    """The engine must keep seeing the real kernels — an import refactor
    that silently drops reachability would make every purity rule vacuous."""
    ctx = build_context()
    roots = set(ctx.reachable.values())
    for expected in (
        "open_simulator_tpu.ops.fast:build_trajectory",
        "open_simulator_tpu.ops.fast:sort_select",
        "open_simulator_tpu.ops.fast:light_scan",
        "open_simulator_tpu.ops.fast:domain_select",
        "open_simulator_tpu.ops.grouped:schedule_group",
        "open_simulator_tpu.ops.kernels:schedule_batch",
        "open_simulator_tpu.ops.kernels:probe_step",
        "open_simulator_tpu.ops.kernels:commit_step",
    ):
        assert expected in roots, f"missing jit root {expected}"


# ---------------------------------------------------------------------------
# lock-in-hot-path


HOT_PREAMBLE = """
    import threading
"""


def test_lock_in_hot_path_true_positive(tmp_path):
    """A per-call Lock on a thread target (and in everything it calls)
    synchronizes nothing and must be flagged."""
    r = _lint(
        tmp_path,
        HOT_PREAMBLE + """
    def helper():
        guard = threading.RLock()
        return guard

    def worker():
        lock = threading.Lock()
        with lock:
            helper()

    threading.Thread(target=worker).start()
    """,
        only_rules=["lock-in-hot-path"],
    )
    assert sum(f.rule == "lock-in-hot-path" for f in r.active) == 2


def test_lock_in_hot_path_instance_and_module_lifetime_ok(tmp_path):
    """Module-level locks and instance publishes (self._lock = Lock(),
    Condition(Lock()) wrappers included) are the sanctioned shapes."""
    r = _lint(
        tmp_path,
        HOT_PREAMBLE + """
    _lock = threading.Lock()

    class Pool:
        def worker(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(threading.Lock())
            with _lock:
                pass

    threading.Thread(target=Pool().worker).start()
    """,
        only_rules=["lock-in-hot-path"],
    )
    assert not r.active, [f.render() for f in r.active]


def test_lock_in_hot_path_cold_code_not_flagged(tmp_path):
    """A local lock in code no thread root reaches is out of scope (the
    module-hosts expansion the race pass uses does NOT apply here)."""
    r = _lint(
        tmp_path,
        HOT_PREAMBLE + """
    def setup_once():
        lock = threading.Lock()
        return lock

    def worker():
        pass

    threading.Thread(target=worker).start()
    """,
        only_rules=["lock-in-hot-path"],
    )
    assert not r.active, [f.render() for f in r.active]


def test_lock_in_hot_path_suppression(tmp_path):
    r = _lint(
        tmp_path,
        HOT_PREAMBLE + """
    def worker():
        lock = threading.Lock()  # osim: lint-ok[lock-in-hot-path]
        with lock:
            pass

    threading.Thread(target=worker).start()
    """,
        only_rules=["lock-in-hot-path"],
    )
    assert not r.active
    assert sum(f.suppressed for f in r.findings) == 1


def test_repo_clean_against_lock_in_hot_path():
    from open_simulator_tpu.analysis import run_lint

    r = run_lint(only_rules=["lock-in-hot-path"])
    assert not r.active, [f.render() for f in r.active]
