"""Multi-device sharding tests on the virtual 8-device CPU mesh: the sharded
engine must produce bit-identical placements to the single-device engine."""

import numpy as np

import jax

from open_simulator_tpu.ops.kernels import schedule_batch, weights_array
from open_simulator_tpu.ops.tile import tile_pod_batch
from open_simulator_tpu.parallel.mesh import (
    make_mesh,
    shard_state,
    sharded_schedule_batch,
)


def synthetic(n_nodes, n_pods):
    from __graft_entry__ import _synthetic_state

    return _synthetic_state(n_nodes=n_nodes, n_pods=n_pods)


def test_eight_device_mesh_available():
    assert len(jax.devices()) == 8


def test_sharded_matches_single_device():
    ns, carry, rows = synthetic(64, 96)
    w = weights_array()
    carry_ref, nodes_ref, reasons_ref, *_ = schedule_batch(ns, carry, rows, w)

    mesh = make_mesh()
    ns_sh, carry_sh = shard_state(mesh, ns, carry)
    fn = sharded_schedule_batch(mesh)
    carry_out, nodes_sh, reasons_sh, *_ = fn(ns_sh, carry_sh, rows, w)

    np.testing.assert_array_equal(np.asarray(nodes_ref), np.asarray(nodes_sh))
    np.testing.assert_array_equal(np.asarray(reasons_ref), np.asarray(reasons_sh))
    # carry shards gather back to the same free matrix
    np.testing.assert_allclose(
        np.asarray(carry_ref.free), np.asarray(carry_out.free), rtol=0, atol=1e-4
    )


def test_dryrun_multichip_entrypoint():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_product_engine_sharded_matches_single_device():
    """simulate(mesh=...) — the PRODUCT path (grouped scheduler under GSPMD) —
    must place every pod exactly where the single-device run does, on the
    same fixture the e2e suite uses."""
    import os

    from open_simulator_tpu.api.config import SimonConfig
    from open_simulator_tpu.engine.apply import build_apps, build_cluster
    from open_simulator_tpu.engine.simulator import simulate
    from open_simulator_tpu.parallel.mesh import product_mesh

    from open_simulator_tpu.core.workloads import reset_name_rng

    cfg = SimonConfig.load(
        os.path.join(os.path.dirname(__file__), "fixtures", "simon-config.yaml")
    )
    # identical generated pod names across the two independent builds
    reset_name_rng()
    ref = simulate(build_cluster(cfg), build_apps(cfg))
    reset_name_rng()
    sharded = simulate(build_cluster(cfg), build_apps(cfg), mesh=product_mesh(8))

    def placements(res):
        return sorted(
            (p.key, st.node.name) for st in res.node_status for p in st.pods
        )

    assert placements(sharded) == placements(ref)
    assert [u.pod.key for u in sharded.unscheduled] == [
        u.pod.key for u in ref.unscheduled
    ]
    assert [u.reason for u in sharded.unscheduled] == [
        u.reason for u in ref.unscheduled
    ]


def test_tile_pod_batch_matches_full_encoding():
    """Tiling template rows must schedule identically to encoding every pod."""
    from open_simulator_tpu.core.objects import Node, Pod
    from open_simulator_tpu.ops.encode import (
        Encoder,
        encode_nodes,
        encode_pods,
        initial_selector_counts,
    )
    from open_simulator_tpu.ops.state import (
        carry_from_table,
        node_static_from_table,
        pod_rows_from_batch,
    )

    nodes = [
        Node.from_dict(
            {
                "metadata": {"name": f"n{i}", "labels": {"kubernetes.io/hostname": f"n{i}"}},
                "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
            }
        )
        for i in range(4)
    ]

    def pod(name):
        return Pod.from_dict(
            {
                "metadata": {"name": name, "namespace": "d", "labels": {"app": "a"}},
                "spec": {
                    "containers": [
                        {"name": "c", "image": "img", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}
                    ]
                },
            }
        )

    w = weights_array()

    # full encoding
    full_pods = [pod(f"p{i}") for i in range(10)]
    enc1 = Encoder()
    enc1.register_pods(full_pods)
    t1 = encode_nodes(enc1, nodes)
    b1 = encode_pods(enc1, full_pods)
    out1 = schedule_batch(
        node_static_from_table(enc1, t1),
        carry_from_table(t1, initial_selector_counts(enc1, t1, [])),
        pod_rows_from_batch(b1),
        w,
    )

    # template + tile
    enc2 = Encoder()
    tmpl = [pod("tpl")]
    enc2.register_pods(tmpl)
    t2 = encode_nodes(enc2, nodes)
    b2 = tile_pod_batch(encode_pods(enc2, tmpl), [10])
    out2 = schedule_batch(
        node_static_from_table(enc2, t2),
        carry_from_table(t2, initial_selector_counts(enc2, t2, [])),
        pod_rows_from_batch(b2),
        w,
    )
    np.testing.assert_array_equal(
        np.asarray(out1[1])[:10], np.asarray(out2[1])[:10]
    )
    assert b2.keys[:3] == ["d/tpl-0", "d/tpl-1", "d/tpl-2"]


def test_fast_path_under_mesh_matches_single_device():
    """The trajectory fast path (ops/fast.py) under GSPMD node-axis sharding:
    big groups route through build_trajectory + light_scan with sharded
    ns/carry — placements, reasons and the exit carry must equal the
    unsharded run exactly (the `simon apply --devices N` path at scale)."""
    from bench import build_state
    from open_simulator_tpu.ops.fast import schedule_batch_fast

    ns, carry, batch = build_state(64, 512)
    w = weights_array()
    carry_ref, nodes_ref, reasons_ref, *_ = schedule_batch_fast(
        ns, carry, batch, w, force_fast=True
    )

    mesh = make_mesh()
    ns_sh, carry_sh = shard_state(mesh, ns, carry)
    carry_out, nodes_sh, reasons_sh, *_ = schedule_batch_fast(
        ns_sh, carry_sh, batch, w, force_fast=True
    )
    np.testing.assert_array_equal(nodes_ref, nodes_sh)
    np.testing.assert_array_equal(reasons_ref, reasons_sh)
    for name in carry_ref._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(carry_ref, name)),
            np.asarray(getattr(carry_out, name)),
            err_msg=f"carry field {name}",
        )


def test_extender_path_under_mesh(stub_factory):
    """The per-pod extender path (probe_step/commit_step) compiles and runs
    under GSPMD node-axis sharding, matching the single-device run with the
    same pass-through extender."""
    from open_simulator_tpu.core.objects import Node
    from open_simulator_tpu.core.workloads import reset_name_rng
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        simulate,
    )
    from open_simulator_tpu.models.profiles import ExtenderConfig
    from open_simulator_tpu.parallel.mesh import product_mesh

    stub = stub_factory({})   # pass-through: keep all, score 0
    ext = [
        ExtenderConfig(
            url_prefix=stub.url,
            filter_verb="filter", prioritize_verb="prioritize",
        )
    ]

    def nodes():
        return [
            Node.from_dict(
                {
                    "metadata": {
                        "name": f"m{i}",
                        "labels": {"kubernetes.io/hostname": f"m{i}"},
                    },
                    "status": {
                        "allocatable": {
                            "cpu": "8", "memory": "16Gi", "pods": "110"
                        }
                    },
                }
            )
            for i in range(16)
        ]

    app = AppResource(
        name="m",
        objects=[
            {
                "kind": "Deployment",
                "metadata": {"name": "w", "namespace": "m"},
                "spec": {
                    "replicas": 6,
                    "template": {
                        "metadata": {"labels": {"app": "w"}},
                        "spec": {
                            "containers": [
                                {"name": "c", "image": "i",
                                 "resources": {"requests": {"cpu": "2"}}}
                            ]
                        },
                    },
                },
            }
        ],
    )
    reset_name_rng()
    single = simulate(ClusterResource(nodes=nodes()), [app], extenders=ext)
    reset_name_rng()
    sharded = simulate(
        ClusterResource(nodes=nodes()), [app], extenders=ext,
        mesh=product_mesh(8),
    )

    def key(r):
        return sorted(
            (p.key, st.node.name)
            for st in r.node_status
            for p in st.pods
        )

    assert key(single) == key(sharded)
    assert not single.unscheduled and not sharded.unscheduled
