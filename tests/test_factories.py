"""The functional-option fixture factories must produce objects the whole
engine accepts (parity: the reference's pkg/test builders are used by its own
runtime tests)."""

from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    simulate,
)

from factories import (
    make_cronjob,
    make_daemonset,
    make_deployment,
    make_job,
    make_node,
    make_pod,
    make_statefulset,
    spread_constraint,
    taint,
    toleration,
)


def test_factories_drive_full_simulation():
    nodes = [
        make_node(
            f"n-{i}", cpu="16", memory="32Gi",
            with_labels={"topology.kubernetes.io/zone": f"z{i % 2}"},
            with_taints=[taint("dedicated", "batch")] if i == 0 else None,
        )
        for i in range(4)
    ]
    pending = make_pod("seed", cpu="1", with_labels={"app": "seed"})
    apps = [
        AppResource(
            name="a",
            objects=[
                make_deployment(
                    "web", replicas=4, cpu="500m",
                    with_spread=[
                        spread_constraint(
                            "topology.kubernetes.io/zone",
                            max_skew=2,
                            when_unsatisfiable="ScheduleAnyway",
                            match_labels={"app": "web"},
                        )
                    ],
                ),
                make_statefulset("db", replicas=2, cpu="1"),
                make_job("once", completions=2, parallelism=2),
                make_daemonset(
                    "agent",
                    with_tolerations=[
                        toleration("dedicated", operator="Exists")
                    ],
                ),
                make_cronjob("tick"),
            ],
        )
    ]
    res = simulate(ClusterResource(nodes=nodes, pods=[pending]), apps)
    assert not res.unscheduled
    placed = sum(len(st.pods) for st in res.node_status)
    # web 4 + db 2 + job 2 + daemonset on every node 4 + cronjob 1 + seed 1
    assert placed == 14
    agent_nodes = {
        st.node.name
        for st in res.node_status
        for p in st.pods
        if p.meta.name.startswith("agent")
    }
    assert len(agent_nodes) == 4  # daemonset tolerated the taint everywhere
