"""Selector handling at realistic cardinality (VERDICT r2 weak #5).

100k pods across 500 distinct workloads: encoding and seeding the selector
counts must stay in single-digit seconds (the naive pods x selectors Python
product would take minutes), and the carry must stay small."""

import time

import numpy as np

from open_simulator_tpu.core.objects import Node, Pod
from open_simulator_tpu.ops.encode import (
    Encoder,
    encode_nodes,
    encode_pods,
    initial_selector_counts,
    match_vector,
)


def _workload_pods(w: int, replicas: int):
    """One workload's replica clones (shared spec objects, like
    core/workloads._clone_pod produces)."""
    proto = Pod.from_dict(
        {
            "metadata": {
                "name": f"w{w}-0",
                "namespace": f"ns-{w % 20}",
                "labels": {"app": f"app-{w}", "tier": f"t{w % 3}"},
            },
            "spec": {
                "containers": [
                    {"name": "c", "resources": {"requests": {"cpu": "100m"}}}
                ],
                "topologySpreadConstraints": [
                    {
                        "maxSkew": 5,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "ScheduleAnyway",
                        "labelSelector": {"matchLabels": {"app": f"app-{w}"}},
                    }
                ],
            },
        }
    )
    out = [proto]
    import copy

    for i in range(1, replicas):
        clone = copy.copy(proto)
        clone.meta = copy.copy(proto.meta)
        clone.meta.name = f"w{w}-{i}"
        out.append(clone)
    return out


def test_100k_pods_500_workloads_encode_fast():
    n_workloads, replicas = 500, 200   # 100k pods
    pods = []
    for w in range(n_workloads):
        pods.extend(_workload_pods(w, replicas))
    assert len(pods) == 100_000

    nodes = [
        Node.from_dict(
            {
                "metadata": {
                    "name": f"n-{i}",
                    "labels": {
                        "kubernetes.io/hostname": f"n-{i}",
                        "topology.kubernetes.io/zone": f"z-{i % 3}",
                    },
                },
                "status": {
                    "allocatable": {"cpu": "64", "memory": "128Gi", "pods": "110"}
                },
            }
        )
        for i in range(200)
    ]

    enc = Encoder()
    t0 = time.time()
    enc.register_pods(pods)
    table = encode_nodes(enc, nodes)
    batch = encode_pods(enc, pods)
    encode_s = time.time() - t0
    assert len(enc.selectors) >= n_workloads
    assert encode_s < 9.0, f"encode took {encode_s:.1f}s"

    # seeding counts from 100k BOUND pods (the capacity-probe path) must
    # amortize matching by workload signature, not pay pods x selectors
    bound = [(p, f"n-{i % 200}") for i, p in enumerate(pods)]
    t0 = time.time()
    counts = initial_selector_counts(enc, table, bound)
    seed_s = time.time() - t0
    assert seed_s < 9.0, f"selector seeding took {seed_s:.1f}s"
    # every workload's selector sees exactly its own 200 replicas
    row_sums = counts.sum(axis=1)
    assert (row_sums[: n_workloads] >= replicas).all()

    # carry budget: sel_counts is the dominant [S,N] table
    assert counts.nbytes < 50 * (1 << 20), f"sel_counts is {counts.nbytes >> 20} MiB"

    # memoization correctness: cached vector == fresh per-selector matching
    # (the vector is padded to the bucketed S axis; pad entries match nothing)
    probe = pods[12345]
    vec = match_vector(enc, probe)
    fresh = np.array([e.matches(probe) for e in enc.selectors])
    np.testing.assert_array_equal(vec[: len(enc.selectors)], fresh)
    assert not vec[len(enc.selectors):].any()
