"""Built-in Kubernetes REST client + cluster snapshotting.

Parity: CreateClusterResourceFromClient (pkg/simulator/simulator.go:503-601):
nodes; non-DaemonSet-owned, non-terminating Running pods then Pending pods;
PDBs/Services/StorageClasses/PVCs/ConfigMaps/DaemonSets. Exercised against a
stub API server (no live cluster in this environment).
"""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest
import yaml

from open_simulator_tpu.utils.kubeclient import (
    KubeClient,
    KubeClientError,
    KubeConfig,
    create_cluster_resource_from_kubeconfig,
    load_kubeconfig,
    snapshot_cluster,
)


def _node(name):
    return {
        "metadata": {"name": name},
        "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
    }


def _pod(name, phase="Running", node="n1", owner_kind=None, deleting=False):
    meta = {"name": name, "namespace": "default"}
    if owner_kind:
        meta["ownerReferences"] = [
            {"kind": owner_kind, "name": "own", "controller": True}
        ]
    if deleting:
        meta["deletionTimestamp"] = "2026-01-01T00:00:00Z"
    return {
        "metadata": meta,
        "spec": {
            "nodeName": node if phase == "Running" else "",
            "containers": [{"name": "c", "image": "img", "resources": {"requests": {"cpu": "1"}}}],
        },
        "status": {"phase": phase},
    }


APIS = {
    "/api/v1/nodes": {"items": [_node("n1"), _node("n2")]},
    "/api/v1/pods": {
        "items": [
            _pod("run-1"),
            _pod("pend-1", phase="Pending"),
            _pod("ds-pod", owner_kind="DaemonSet"),
            _pod("dying", deleting=True),
            _pod("done", phase="Succeeded"),
        ]
    },
    "/apis/policy/v1beta1/poddisruptionbudgets": {
        "items": [
            {
                "metadata": {"name": "pdb", "namespace": "default"},
                "spec": {"minAvailable": 1, "selector": {"matchLabels": {"a": "b"}}},
            }
        ]
    },
    "/api/v1/services": {"items": []},
    "/apis/storage.k8s.io/v1/storageclasses": {
        "items": [{"metadata": {"name": "open-local-lvm"}}]
    },
    "/api/v1/persistentvolumeclaims": {"items": []},
    "/api/v1/configmaps": {"items": []},
    "/apis/apps/v1/daemonsets": {
        "items": [
            {
                "metadata": {"name": "agent", "namespace": "kube-system"},
                "spec": {
                    "template": {
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "image": "img",
                                    "resources": {"requests": {"cpu": "100m"}},
                                }
                            ]
                        }
                    }
                },
            }
        ]
    },
    "/apis/apps/v1/statefulsets": {
        "items": [{"metadata": {"name": "db", "namespace": "default"}}]
    },
    "/apis/apps/v1/replicasets": {
        "items": [
            {
                "metadata": {
                    "name": "web-abc123",
                    "namespace": "default",
                    "ownerReferences": [
                        {"kind": "Deployment", "name": "web"}
                    ],
                },
            }
        ]
    },
}


class _StubAPI(BaseHTTPRequestHandler):
    auth_seen = []

    def do_GET(self):  # noqa: N802
        path = self.path.split("?")[0]
        type(self).auth_seen.append(self.headers.get("Authorization"))
        doc = APIS.get(path)
        if doc is None:
            self.send_response(404)
            self.end_headers()
            return
        data = json.dumps(doc).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        pass


@pytest.fixture()
def stub_api():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StubAPI)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def _write_kubeconfig(tmp_path, server, token="sekrit"):
    doc = {
        "apiVersion": "v1",
        "kind": "Config",
        "current-context": "ctx",
        "contexts": [{"name": "ctx", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {"server": server}}],
        "users": [{"name": "u", "user": {"token": token}}],
    }
    p = tmp_path / "kubeconfig"
    p.write_text(yaml.safe_dump(doc))
    return str(p)


def test_load_kubeconfig(tmp_path):
    path = _write_kubeconfig(tmp_path, "https://example:6443")
    cfg = load_kubeconfig(path)
    assert cfg.server == "https://example:6443"
    assert cfg.token == "sekrit"


def test_load_kubeconfig_inline_ca(tmp_path):
    doc = {
        "current-context": "ctx",
        "contexts": [{"name": "ctx", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [
            {
                "name": "c",
                "cluster": {
                    "server": "https://example",
                    "certificate-authority-data": base64.b64encode(b"CERT").decode(),
                },
            }
        ],
        "users": [{"name": "u", "user": {"token": "t"}}],
    }
    p = tmp_path / "kc"
    p.write_text(yaml.safe_dump(doc))
    cfg = load_kubeconfig(str(p))
    assert cfg.ca_file and open(cfg.ca_file, "rb").read() == b"CERT"


def test_load_kubeconfig_errors(tmp_path):
    with pytest.raises(KubeClientError):
        load_kubeconfig(str(tmp_path / "missing"))
    p = tmp_path / "empty"
    p.write_text("{}")
    with pytest.raises(KubeClientError):
        load_kubeconfig(str(p))
    # exec plugins unsupported, clearly
    doc = {
        "current-context": "ctx",
        "contexts": [{"name": "ctx", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {"server": "https://x"}}],
        "users": [{"name": "u", "user": {"exec": {"command": "aws"}}}],
    }
    p2 = tmp_path / "exec"
    p2.write_text(yaml.safe_dump(doc))
    with pytest.raises(KubeClientError, match="exec"):
        load_kubeconfig(str(p2))


def test_snapshot_cluster(stub_api):
    client = KubeClient(KubeConfig(server=stub_api, token="tok"))
    cluster = snapshot_cluster(client)
    assert [n.name for n in cluster.nodes] == ["n1", "n2"]
    # DaemonSet-owned, terminating and Succeeded pods are dropped;
    # Running comes before Pending
    assert [p.meta.name for p in cluster.pods] == ["run-1", "pend-1"]
    assert len(cluster.daemonsets) == 1
    assert "PodDisruptionBudget" in cluster.others
    assert "StorageClass" in cluster.others
    # the reference also syncs STS/RS listers (server.go:114-116) — the
    # Deployment->ReplicaSet indirection of scale-apps needs them
    assert [
        r["metadata"]["name"] for r in cluster.others.get("ReplicaSet", [])
    ] == ["web-abc123"]
    assert [
        s["metadata"]["name"] for s in cluster.others.get("StatefulSet", [])
    ] == ["db"]
    # bearer token was sent
    assert "Bearer tok" in _StubAPI.auth_seen


def test_snapshot_via_kubeconfig_end_to_end(stub_api, tmp_path):
    path = _write_kubeconfig(tmp_path, stub_api)
    cluster = create_cluster_resource_from_kubeconfig(path)
    assert len(cluster.nodes) == 2

    # and it simulates: the pending pod reschedules, the DS re-expands
    from open_simulator_tpu.engine.simulator import simulate

    result = simulate(cluster, [])
    assert not result.unscheduled
    placed = {p.meta.name for st in result.node_status for p in st.pods}
    assert "pend-1" in placed
    # daemonset re-expanded onto both nodes
    ds_pods = [p for p in placed if p.startswith("agent-")]
    assert len(ds_pods) == 2


def test_http_error_surfaces(stub_api):
    client = KubeClient(KubeConfig(server=stub_api))
    with pytest.raises(KubeClientError, match="404"):
        client.get("/api/v1/nope")


def test_master_overrides_kubeconfig_server(tmp_path):
    # --master parity (cmd/server/options.go): the URL beats the kubeconfig
    from open_simulator_tpu.utils.kubeclient import KubeClient

    path = _write_kubeconfig(tmp_path, "https://example:6443")
    client = KubeClient.from_kubeconfig(path, master="https://override:8443/")
    assert client.cfg.server == "https://override:8443"
    # token still comes from the kubeconfig
    assert client.cfg.token == "sekrit"
    assert KubeClient.from_kubeconfig(path).cfg.server == "https://example:6443"


def test_master_alone_snapshots(stub_api):
    # BuildConfigFromFlags parity: a bare master URL with no kubeconfig is a
    # valid (anonymous) client
    from open_simulator_tpu.utils.kubeclient import (
        KubeClientError,
        create_cluster_resource_from_kubeconfig,
    )

    cluster = create_cluster_resource_from_kubeconfig("", master=stub_api)
    assert cluster.nodes
    with pytest.raises(KubeClientError, match="neither kubeconfig nor master"):
        create_cluster_resource_from_kubeconfig("")
