"""Grouped kernel must be placement-identical to the naive scan."""

import numpy as np

from open_simulator_tpu.ops.grouped import group_runs, schedule_batch_grouped
from open_simulator_tpu.ops.kernels import schedule_batch, weights_array
from open_simulator_tpu.ops.state import pod_rows_from_batch


def _state(n_nodes, n_pods, seed=3):
    from __graft_entry__ import _synthetic_state

    return _synthetic_state(n_nodes=n_nodes, n_pods=n_pods, seed=seed)


def test_group_runs_detects_templates():
    from open_simulator_tpu.core.objects import Node, Pod
    from open_simulator_tpu.ops.encode import Encoder, encode_nodes, encode_pods
    from open_simulator_tpu.ops.tile import tile_pod_batch

    def pod(name, cpu):
        return Pod.from_dict(
            {
                "metadata": {"name": name, "namespace": "d"},
                "spec": {
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": cpu}}}
                    ]
                },
            }
        )

    enc = Encoder()
    tmpls = [pod("a", "1"), pod("b", "2")]
    enc.register_pods(tmpls)
    encode_nodes(enc, [Node.from_dict({"metadata": {"name": "n"}, "status": {"allocatable": {"cpu": "64", "pods": "110"}}})])
    batch = tile_pod_batch(encode_pods(enc, tmpls), [5, 3])
    assert group_runs(batch) == [(0, 5), (5, 3)]


def test_grouped_matches_naive_on_synthetic_mix():
    # _synthetic_state alternates tolerations every 5 pods and spread selectors
    # every pod, so runs are short — a worst case for grouping, best for parity.
    ns, carry, rows = _state(32, 48)
    w = weights_array()
    _, nodes_ref, reasons_ref, *_ = schedule_batch(ns, carry, rows, w)

    # rebuild the PodBatch (numpy) for the grouped API
    import jax

    from open_simulator_tpu.ops import encode as enc_mod

    # _synthetic_state returns device rows; reconstruct a batch-like object
    # by re-encoding. Simpler: drive grouped path on the same arrays.
    class FakeBatch:
        pass

    # Use the real constructor path instead:
    from __graft_entry__ import _synthetic_state as build

    # grouped path needs the numpy batch; rebuild state with the same seed
    from open_simulator_tpu.core.objects import Node, Pod  # noqa

    # Recreate via the bench builder for a template-tiled case below instead.
    del FakeBatch

    # For this test, wrap rows back into numpy arrays with batch semantics:
    batch = _rows_to_batch(rows)
    carry2, nodes_grp, reasons_grp, *_ = schedule_batch_grouped(ns, carry, batch, w)
    total = int(batch.valid.sum())  # padding rows: naive computes throwaway
    np.testing.assert_array_equal(np.asarray(nodes_ref)[:total], nodes_grp[:total])
    np.testing.assert_array_equal(np.asarray(reasons_ref)[:total], reasons_grp[:total])


def _rows_to_batch(rows):
    """PodRow pytree (stacked arrays) -> PodBatch for the grouped API."""
    from open_simulator_tpu.ops.encode import PodBatch

    d = {k: np.asarray(getattr(rows, k)) for k in rows._fields}
    return PodBatch(keys=[f"p/{i}" for i in range(d["req"].shape[0])], **d)


def test_grouped_matches_naive_on_tiled_templates():
    from bench import build_state

    ns, carry, batch = build_state(64, 256)
    w = weights_array()
    rows = pod_rows_from_batch(batch)
    _, nodes_ref, reasons_ref, *_ = schedule_batch(ns, carry, rows, w)
    _, nodes_grp, reasons_grp, *_ = schedule_batch_grouped(ns, carry, batch, w)
    total = int(batch.valid.sum())
    np.testing.assert_array_equal(np.asarray(nodes_ref)[:total], nodes_grp[:total])
    np.testing.assert_array_equal(np.asarray(reasons_ref)[:total], reasons_grp[:total])
