"""End-to-end tests: config → cluster/apps → simulate → capacity → report/CLI/server.

Modeled on the reference's integration test strategy
(`pkg/simulator/core_test.go`): a multi-node cluster with taints + a cluster
DaemonSet, an app covering several workload kinds, and a workload-conservation
oracle over the results.
"""

import io
import json
import os
import threading
import urllib.request

import pytest

from open_simulator_tpu.api.config import SimonConfig
from open_simulator_tpu.core.workloads import expected_pod_counts
from open_simulator_tpu.engine.apply import build_apps, build_cluster, load_new_node, run_apply
from open_simulator_tpu.engine.capacity import plan_capacity
from open_simulator_tpu.engine.simulator import simulate

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
CONFIG = os.path.join(FIXTURES, "simon-config.yaml")


@pytest.fixture(scope="module")
def cfg():
    return SimonConfig.load(CONFIG)


def test_config_load(cfg):
    assert cfg.custom_config.endswith("cluster")
    assert cfg.app_list[0].name == "shop"
    assert cfg.new_node.endswith("newnode")


def test_simulate_conservation_and_placement(cfg):
    cluster = build_cluster(cfg)
    apps = build_apps(cfg)
    result = simulate(cluster, apps)

    # DaemonSet tolerates everything -> one agent pod per node
    agent_nodes = {
        st.node.name
        for st in result.node_status
        for p in st.pods
        if p.meta.annotations.get("simon/workload-name") == "node-agent"
    }
    assert agent_nodes == {"cp-1", "w-1", "w-2"}

    # workload conservation: scheduled + unscheduled == expected
    expected = expected_pod_counts(
        [o for a in apps for o in a.objects] + cluster.daemonsets, cluster.nodes
    )
    placed = sum(len(st.pods) for st in result.node_status)
    assert placed + len(result.unscheduled) == sum(expected.values())

    # anti-affinity cache pods on distinct nodes
    cache_nodes = [
        st.node.name
        for st in result.node_status
        for p in st.pods
        if p.meta.annotations.get("simon/workload-name") == "cache"
    ]
    assert len(cache_nodes) == len(set(cache_nodes)) == 2

    # control-plane taint respected: only the (tolerating) agent runs there
    cp_pods = result.pods_on("cp-1")
    assert all(
        p.meta.annotations.get("simon/workload-name") == "node-agent" for p in cp_pods
    )

    # 4 web replicas want 2cpu each; workers have 8cpu each minus agents/cache
    assert not result.unscheduled


def test_capacity_plan_when_overloaded(cfg):
    cluster = build_cluster(cfg)
    apps = build_apps(cfg)
    # quadruple the web deployment so it cannot fit
    for app in apps:
        for obj in app.objects:
            if obj.get("kind") == "Deployment":
                obj["spec"]["replicas"] = 20
    result = simulate(cluster, apps)
    assert result.unscheduled

    new_node = load_new_node(cfg)
    plan = plan_capacity(cluster, apps, new_node)
    assert plan is not None
    assert plan.nodes_added >= 1
    assert not plan.result.unscheduled
    # minimality: one fewer node must not suffice
    if plan.nodes_added > 1:
        from open_simulator_tpu.engine.capacity import _probe

        worse = _probe(cluster, apps, new_node, plan.nodes_added - 1, None)
        assert worse.unscheduled


def test_expand_cache_matches_fresh_runs(cfg):
    """plan_capacity shares one expand_cache across probes; the returned
    plan's placements must match a cache-free simulation at the winning node
    count exactly — bindings, DaemonSet synthesis, and the replayed result's
    pod node_names all intact."""
    cluster = build_cluster(cfg)
    apps = build_apps(cfg)
    for app in apps:
        for obj in app.objects:
            if obj.get("kind") == "Deployment":
                obj["spec"]["replicas"] = 20

    new_node = load_new_node(cfg)
    plan = plan_capacity(cluster, apps, new_node)
    assert plan is not None and not plan.result.unscheduled

    from open_simulator_tpu.engine.capacity import _probe

    fresh = _probe(cluster, apps, new_node, plan.nodes_added, None)

    def bindings(result):
        # workload pod names carry random suffixes (reference parity), so
        # compare placements as per-(node, workload) counts
        out = {}
        for st in result.node_status:
            for p in st.pods:
                wl = p.meta.annotations.get("simon/workload-name", p.meta.name)
                key = (st.node.name, wl)
                out[key] = out.get(key, 0) + 1
        return out

    assert bindings(plan.result) == bindings(fresh)
    # every placed pod object carries its binding (the cache replay must not
    # leave stale/reset node_names in the returned result)
    for st in plan.result.node_status:
        for p in st.pods:
            assert p.node_name == st.node.name
            assert p.phase == "Running"


def test_expand_cache_duplicate_app_names(cfg):
    """Two apps sharing a name must not alias cache entries (keyed by
    position, not name): each keeps its own workloads across probes."""
    from open_simulator_tpu.core.objects import Node
    from open_simulator_tpu.engine.simulator import AppResource, ClusterResource

    def deploy(name, replicas, cpu):
        return {
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": "d"},
            "spec": {
                "replicas": replicas,
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": {
                        "containers": [
                            {"name": "c", "image": "i",
                             "resources": {"requests": {"cpu": cpu, "memory": "1Gi"}}}
                        ]
                    },
                },
            },
        }

    node = Node.from_dict(
        {
            "metadata": {"name": "tpl", "labels": {"kubernetes.io/hostname": "tpl"}},
            "status": {
                "allocatable": {"cpu": "4", "memory": "16Gi", "pods": "110"},
                "capacity": {"cpu": "4", "memory": "16Gi", "pods": "110"},
            },
        }
    )
    apps = [
        AppResource(name="web", objects=[deploy("a", 6, "1")]),
        AppResource(name="web", objects=[deploy("b", 10, "500m")]),
    ]
    plan = plan_capacity(ClusterResource(nodes=[]), apps, node)
    assert plan is not None and not plan.result.unscheduled
    by_wl = {}
    for st in plan.result.node_status:
        for p in st.pods:
            wl = p.meta.annotations.get("simon/workload-name")
            by_wl[wl] = by_wl.get(wl, 0) + 1
    assert by_wl == {"a": 6, "b": 10}


def test_run_apply_report(cfg):
    out = io.StringIO()
    outcome = run_apply(cfg, out=out)
    text = out.getvalue()
    assert "=== Cluster ===" in text
    assert "cp-1" in text and "w-1" in text and "w-2" in text
    assert "All pods scheduled." in text
    assert not outcome.result.unscheduled


def test_cli_apply(tmp_path, capsys):
    from open_simulator_tpu.cli.main import main

    report = tmp_path / "report.txt"
    rc = main(["apply", "-f", CONFIG, "--output-file", str(report)])
    assert rc == 0
    assert "=== Unscheduled ===" in report.read_text()

    rc = main(["version"])
    assert rc == 0
    assert "simon-tpu version" in capsys.readouterr().out

    rc = main(["apply", "-f", str(tmp_path / "missing.yaml")])
    assert rc == 1


def test_interactive_loop(cfg):
    from open_simulator_tpu.engine.apply import _interactive_loop

    cluster = build_cluster(cfg)
    apps = build_apps(cfg)
    for app in apps:
        for obj in app.objects:
            if obj.get("kind") == "Deployment":
                obj["spec"]["replicas"] = 12
    result = simulate(cluster, apps)
    assert result.unscheduled
    new_node = load_new_node(cfg)
    out = io.StringIO()
    answers = iter(["r"] + ["a"] * 10 + ["q"])
    final = _interactive_loop(
        cluster, apps, new_node, result, out, lambda _: next(answers)
    )
    text = out.getvalue()
    assert "failed to schedule" in text
    assert f"{result.unscheduled[0].pod.key}:" in text  # [r]easons path
    assert not final.unscheduled  # enough added nodes resolves it


def test_server_roundtrip(cfg):
    from open_simulator_tpu.server.server import make_server

    srv = make_server(0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            assert json.load(r)["status"] == "ok"

        cluster_objs = []
        import yaml

        from open_simulator_tpu.utils.yamlio import walk_files

        for f in walk_files(os.path.join(FIXTURES, "cluster"), (".yaml", ".yml")):
            cluster_objs.extend(d for d in yaml.safe_load_all(open(f)) if d)
        app_objs = []
        for f in walk_files(os.path.join(FIXTURES, "app"), (".yaml", ".yml")):
            app_objs.extend(d for d in yaml.safe_load_all(open(f)) if d)

        body = json.dumps(
            {
                "cluster": {"objects": cluster_objs},
                "apps": [{"name": "shop", "objects": app_objs}],
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            payload = json.load(r)
        assert payload["unscheduled"] == []
        assert len(payload["placements"]) >= 11  # 3 agents + 4 web + 2 cache + 2 job

        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps", data=b"{not-json",
        )
        try:
            urllib.request.urlopen(bad)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.shutdown()
        srv.server_close()


def test_unrenderable_chart_degrades_per_app(cfg, tmp_path, monkeypatch):
    """A chart beyond the template subset fails THAT app only — the rest of
    the run proceeds (round-4 fix; previously aborted the whole apply).
    The helm-binary fallback is disabled so the test is deterministic on
    machines that do have helm installed."""
    import open_simulator_tpu.engine.apply as apply_mod

    monkeypatch.setattr(apply_mod.shutil, "which", lambda name: None)
    from open_simulator_tpu.api.config import AppInConfig, SimonConfig

    bad = tmp_path / "badchart"
    (bad / "templates").mkdir(parents=True)
    (bad / "Chart.yaml").write_text("name: badchart\nversion: 0.1.0\n")
    (bad / "templates" / "cm.yaml").write_text(
        "kind: ConfigMap\nmetadata:\n  name: {{ uuidv4 }}\n"
    )
    badyaml = tmp_path / "badyaml"
    badyaml.mkdir()
    (badyaml / "broken.yaml").write_text("metadata: [unclosed\n")
    broken_cfg = SimonConfig(
        custom_config=cfg.custom_config,
        app_list=list(cfg.app_list)
        + [
            AppInConfig(name="bad", path=str(bad), chart=True),
            AppInConfig(name="badyaml", path=str(badyaml)),
        ],
        new_node=cfg.new_node,
    )
    out = io.StringIO()
    outcome = run_apply(broken_cfg, auto_plan=False, out=out)
    # chart-render failures AND manifest-dir YAML failures both degrade
    assert [fa.name for fa in outcome.failed_apps] == ["bad", "badyaml"]
    assert "uuidv4" in outcome.failed_apps[0].error
    assert "FAILED APP bad" in outcome.report
    assert "FAILED APP badyaml" in outcome.report
    # the good apps still simulated
    assert sum(len(st.pods) for st in outcome.result.node_status) > 0
    # library behavior without an accumulator still raises
    from open_simulator_tpu.engine.apply import ApplyError, build_apps

    with pytest.raises(ApplyError):
        build_apps(broken_cfg)


def test_server_pprof_endpoints():
    from open_simulator_tpu.server.server import make_server

    srv = make_server(0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/profile?seconds=0.3"
        ) as r:
            prof = json.load(r)
        assert prof["polls"] > 0
        assert isinstance(prof["stacks"], list)

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/heap"
        ) as r:
            heap1 = json.load(r)
        assert heap1["note"]  # first call: tracing just started
        # allocate something measurable, snapshot again
        blob = ["x" * 1024 for _ in range(1000)]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/heap"
        ) as r:
            heap2 = json.load(r)
        assert not heap2["note"]
        assert heap2["traced_current_bytes"] > 0
        assert heap2["top"]
        del blob
    finally:
        srv.shutdown()
        srv.server_close()
        # tracing slows every allocation in this process; turn it back off
        # so the rest of the suite isn't taxed (a real server keeps it on by
        # design, like a pprof-enabled runtime)
        import tracemalloc

        from open_simulator_tpu.server import server as server_mod

        tracemalloc.stop()
        server_mod._tracemalloc_on = False


def test_server_scale_apps_roundtrip():
    """POST /api/scale-apps: removeWorkloads drops the named workload's
    bound pods from the snapshot before re-simulating at the new count
    (removePodsOfApp parity, server.go:404-444)."""
    from open_simulator_tpu.server.server import make_server

    nodes = [
        {
            "kind": "Node",
            "metadata": {
                "name": f"s{i}",
                "labels": {"kubernetes.io/hostname": f"s{i}"},
            },
            "status": {
                "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}
            },
        }
        for i in range(2)
    ]
    # two bound replicas of Deployment web (4 cpu each: the nodes are FULL)
    bound = [
        {
            "kind": "Pod",
            "metadata": {
                "name": f"web-{i}",
                "namespace": "d",
                "labels": {"app": "web"},
                "ownerReferences": [
                    {"kind": "ReplicaSet", "name": "web-abc123"}
                ],
                "annotations": {
                    "simon/workload-kind": "Deployment",
                    "simon/workload-name": "web",
                    "simon/workload-namespace": "d",
                },
            },
            "spec": {
                "nodeName": f"s{i}",
                "containers": [
                    {
                        "name": "c",
                        "image": "i",
                        "resources": {"requests": {"cpu": "7"}},
                    }
                ],
            },
        }
        for i in range(2)
    ]
    from tests.factories import make_deployment

    scaled = make_deployment("web", replicas=3, namespace="d", cpu="4")
    srv = make_server(0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        body = json.dumps(
            {
                "cluster": {"objects": nodes + bound},
                "apps": [{"name": "web", "objects": [scaled]}],
                "removeWorkloads": [
                    {"kind": "Deployment", "name": "web", "namespace": "d"}
                ],
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/scale-apps",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            out = json.load(r)
        # old 7-cpu replicas removed -> three new 4-cpu replicas fit
        # (impossible if the old pods still occupied the nodes)
        assert out["unscheduled"] == []
        assert len(out["placements"]) == 3
        # the two REMOVED bound pods (exact keys) must be gone; new replica
        # names carry random suffixes, so only exact matches are safe
        assert "d/web-0" not in out["placements"]
        assert "d/web-1" not in out["placements"]

        # WITHOUT removeWorkloads the old pods stay and nothing fits
        body2 = json.dumps(
            {
                "cluster": {"objects": nodes + bound},
                "apps": [{"name": "web", "objects": [scaled]}],
            }
        ).encode()
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps",
            data=body2,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req2) as r:
            out2 = json.load(r)
        assert len(out2["unscheduled"]) == 3

        # REAL-cluster shape (no simon annotations): pods owned by a
        # ReplicaSet, the RS owned by the Deployment — removeWorkloads must
        # resolve the indirection via the snapshot's RS objects
        # (removePodsOfApp, server.go:408-419)
        raw_bound = []
        for i in range(2):
            p = json.loads(json.dumps(bound[i]))
            del p["metadata"]["annotations"]
            # web-0 carries a leading non-controller ref: OwnedByWorkload
            # scans ALL ownerReferences, not just the first
            p["metadata"]["ownerReferences"] = (
                [{"kind": "Workflow", "name": "nightly"}] if i == 0 else []
            ) + [{"kind": "ReplicaSet", "name": "web-abc123"}]
            raw_bound.append(p)
        rs = {
            "kind": "ReplicaSet",
            "apiVersion": "apps/v1",
            "metadata": {
                "name": "web-abc123",
                "namespace": "d",
                "ownerReferences": [{"kind": "Deployment", "name": "web"}],
            },
        }
        body3 = json.dumps(
            {
                "cluster": {"objects": nodes + raw_bound + [rs]},
                "apps": [{"name": "web", "objects": [scaled]}],
                "removeWorkloads": [
                    {"kind": "Deployment", "name": "web", "namespace": "d"}
                ],
            }
        ).encode()
        req3 = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/scale-apps",
            data=body3,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req3) as r:
            out3 = json.load(r)
        assert out3["unscheduled"] == []
        assert len(out3["placements"]) == 3
        assert "d/web-0" not in out3["placements"]
        assert "d/web-1" not in out3["placements"]
    finally:
        srv.shutdown()
        srv.server_close()


def test_server_goroutine_dump():
    """/debug/pprof/goroutine: instantaneous all-thread stack dump (the
    goroutine-dump analog of server.go:152's pprof surface — the tool the
    reference's leak postmortem leaned on)."""
    from open_simulator_tpu.server.server import make_server

    srv = make_server(0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/goroutine"
        ) as r:
            dump = json.load(r)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/"
        ) as r:
            idx = json.load(r)
        assert set(idx["profiles"]) >= {"goroutine", "heap", "profile", "cmdline"}
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/cmdline"
        ) as r:
            assert isinstance(json.load(r)["cmdline"], list)
        assert dump["count"] >= 2  # at least main + the serving thread
        assert dump["count"] == len(dump["threads"])
        all_frames = [
            frame for th in dump["threads"] for frame in th["stack"]
        ]
        # the serving thread's own handler must be visible in its stack
        assert any("do_GET" in f for f in all_frames)
        assert all(
            isinstance(th["name"], str) and th["id"] for th in dump["threads"]
        )
    finally:
        srv.shutdown()
        srv.server_close()


def test_server_resync_period_matches_reference():
    """30 s is the reference's SharedInformerFactory resync period
    (server.go:106) — the snapshot-cache TTL must track it."""
    from open_simulator_tpu.server import server as server_mod

    assert server_mod.RESYNC_SECONDS == 30.0


def test_server_snapshot_cache(monkeypatch):
    """Kubeconfig/master-backed serving reuses one cluster snapshot across
    requests within the resync TTL (informer-cache parity, server.go:98-136)
    and refetches after it expires."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from open_simulator_tpu.server import server as server_mod

    list_calls = []

    class _API(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            path = self.path.split("?")[0]
            list_calls.append(path)
            if path == "/api/v1/nodes":
                doc = {
                    "items": [
                        {
                            "metadata": {
                                "name": f"s{i}",
                                "labels": {"kubernetes.io/hostname": f"s{i}"},
                            },
                            "status": {
                                "allocatable": {
                                    "cpu": "8",
                                    "memory": "16Gi",
                                    "pods": "110",
                                }
                            },
                        }
                        for i in range(2)
                    ]
                }
            else:
                doc = {"items": []}
            data = json.dumps(doc).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *args):
            pass

    api = ThreadingHTTPServer(("127.0.0.1", 0), _API)
    threading.Thread(target=api.serve_forever, daemon=True).start()
    monkeypatch.setattr(
        server_mod, "_master", f"http://127.0.0.1:{api.server_address[1]}"
    )
    monkeypatch.setattr(server_mod, "_kubeconfig", None)
    monkeypatch.setattr(server_mod, "_snapshot", None)
    monkeypatch.setattr(server_mod, "_snapshot_at", 0.0)
    monkeypatch.setattr(server_mod, "_snapshot_fetches", 0)
    monkeypatch.setattr(server_mod, "_resync_s", 3600.0)
    try:
        app = {
            "name": "a",
            "objects": [
                {
                    "kind": "Deployment",
                    "metadata": {"name": "d", "namespace": "x"},
                    "spec": {
                        "replicas": 1,
                        "template": {
                            "metadata": {"labels": {"app": "d"}},
                            "spec": {
                                "containers": [
                                    {
                                        "name": "c",
                                        "image": "i",
                                        "resources": {
                                            "requests": {"cpu": "1"}
                                        },
                                    }
                                ]
                            },
                        },
                    },
                }
            ],
        }
        out1 = server_mod._simulate_request({"apps": [app]})
        assert len(out1["placements"]) == 1
        n_lists_after_first = len(list_calls)
        out2 = server_mod._simulate_request({"apps": [app]})
        assert len(out2["placements"]) == 1
        # second request served from the cached snapshot: no new list calls
        assert len(list_calls) == n_lists_after_first
        assert server_mod._snapshot_fetches == 1
        # expire the TTL -> the next request refetches (30 s resync parity)
        monkeypatch.setattr(server_mod, "_snapshot_at", -10_000.0)
        server_mod._simulate_request({"apps": [app]})
        assert server_mod._snapshot_fetches == 2
        assert len(list_calls) > n_lists_after_first
        # the cached snapshot itself must stay pristine: a request that
        # appends newNodes / filters pods works on a fresh wrapper
        before = len(server_mod._snapshot.nodes)
        server_mod._simulate_request(
            {
                "apps": [app],
                "newNodes": [
                    {
                        "kind": "Node",
                        "metadata": {
                            "name": "extra",
                            "labels": {"kubernetes.io/hostname": "extra"},
                        },
                        "status": {
                            "allocatable": {
                                "cpu": "8",
                                "memory": "16Gi",
                                "pods": "110",
                            }
                        },
                    }
                ],
            }
        )
        assert len(server_mod._snapshot.nodes) == before
    finally:
        api.shutdown()
        api.server_close()


def test_server_rss_soak(cfg):
    """100 sequential deploy-apps requests must not grow RSS unboundedly —
    the rebuild's regression guard for the reference's production memory
    leak (docs/design/内存泄漏.md: goroutine/informer leak grew RSS per
    request until OOM). Warm up 10 requests (jit caches fill), then assert
    the remaining 90 add < 120 MB."""
    from open_simulator_tpu.server.server import make_server

    def rss_mb() -> float:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
        return 0.0

    srv = make_server(0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()

    import yaml

    from open_simulator_tpu.utils.yamlio import walk_files

    cluster_objs = []
    for f in walk_files(os.path.join(FIXTURES, "cluster"), (".yaml", ".yml")):
        cluster_objs.extend(d for d in yaml.safe_load_all(open(f)) if d)
    app_objs = []
    for f in walk_files(os.path.join(FIXTURES, "app"), (".yaml", ".yml")):
        app_objs.extend(d for d in yaml.safe_load_all(open(f)) if d)
    body = json.dumps(
        {
            "cluster": {"objects": cluster_objs},
            "apps": [{"name": "soak", "objects": app_objs}],
        }
    ).encode()

    curve = []
    try:
        for i in range(100):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/deploy-apps",
                data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                payload = json.load(r)
            assert payload["unscheduled"] == []
            if i in (0, 9, 24, 49, 74, 99):
                curve.append((i + 1, round(rss_mb(), 1)))
        warm = dict(curve)[10]
        final = dict(curve)[100]
        growth = final - warm
        # bounded: steady-state requests must not accumulate memory. The
        # bound is generous (fragmentation, allocator slack) — a real leak
        # like the reference's grows without bound and blows through it.
        assert growth < 120.0, f"RSS grew {growth:.1f} MB over 90 warm requests: {curve}"
        print(f"RSS soak curve (requests, MB): {curve}")
    finally:
        srv.shutdown()
        srv.server_close()


def test_first_party_example_tree():
    """The shipped example/ quickstart must work from a bare checkout (no
    /root/reference needed): config loads, the stackd chart renders, all
    five apps simulate, and the only shortfall is the one the capacity
    search exists to fix (README flow, reference example/ parity)."""
    import yaml as _yaml

    from open_simulator_tpu.api.config import SimonConfig
    from open_simulator_tpu.engine.apply import run_apply

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg_path = os.path.join(root, "example", "simon-config.yaml")
    cfg = SimonConfig.load(cfg_path)
    assert [a.name for a in cfg.app_list] == [
        "stackd", "simple", "complicate", "open_local", "more_pods",
    ]
    assert cfg.app_list[0].chart
    out = io.StringIO()
    outcome = run_apply(cfg, auto_plan=False, out=out)
    assert not outcome.failed_apps
    placed = {
        p.meta.annotations.get("simon/workload-name", p.meta.name)
        for st in outcome.result.node_status
        for p in st.pods
    }
    # the chart's controller + agent made it through render -> placement
    assert any("stackd" in name for name in placed)
    # open-local replicas took VG + device storage on the workers
    report = out.getvalue()
    assert "ordervault" in report
    assert "Local Storage" in report
    # the demo cluster is sized to need the capacity search for more_pods
    assert 0 < len(outcome.result.unscheduled) <= 4
    # the gpushare variant runs end-to-end too (README advertises it)
    gpu_cfg = SimonConfig.load(
        os.path.join(root, "example", "simon-gpushare-config.yaml")
    )
    gpu_outcome = run_apply(gpu_cfg, auto_plan=False, out=io.StringIO())
    assert not gpu_outcome.failed_apps
    assert not gpu_outcome.result.unscheduled
    assert "GPU Share" in gpu_outcome.report
    # every plain-YAML manifest and local-storage JSON parses (chart
    # templates are exercised by the render above, not parsed here)
    from open_simulator_tpu.utils.yamlio import walk_files

    n_files = 0
    for f in walk_files(
        os.path.join(root, "example"), (".yaml", ".yml", ".json")
    ):
        n_files += 1
        with open(f) as fh:
            if f.endswith(".json"):
                json.load(fh)
            elif "templates" not in f:
                list(_yaml.safe_load_all(fh))
    assert n_files > 30


def test_report_colorization(cfg, monkeypatch):
    from open_simulator_tpu.utils.tables import colorize_report

    plain = "=== Cluster ===\n| n | 8.1% | 55.0% | 95.0% |"
    colored = colorize_report(plain)
    assert "\x1b[1m=== Cluster ===\x1b[0m" in colored
    assert "\x1b[32m8.1%\x1b[0m" in colored     # green < 50
    assert "\x1b[33m55.0%\x1b[0m" in colored    # yellow < 80
    assert "\x1b[31m95.0%\x1b[0m" in colored    # red >= 80

    # the real isatty gate: a tty-like stdout gets colors...
    import io
    import sys

    class _TtyOut(io.StringIO):
        def isatty(self):
            return True

    tty = _TtyOut()
    monkeypatch.setattr(sys, "stdout", tty)
    outcome = run_apply(cfg, auto_plan=False)   # out=None -> sys.stdout
    assert "\x1b[1m=== Cluster ===\x1b[0m" in tty.getvalue()
    # ...while the returned report and non-tty output stay plain
    assert "\x1b[" not in outcome.report
    monkeypatch.undo()
    out = io.StringIO()
    outcome2 = run_apply(cfg, auto_plan=False, out=out)
    assert "\x1b[" not in out.getvalue()
