"""Replica expansion via prototype cloning: per-replica independence and the
volumeClaimTemplates annotation override (utils.go:139-171, 246-292)."""

import json

from open_simulator_tpu.core.objects import ANNO_POD_LOCAL_STORAGE
from open_simulator_tpu.core.workloads import pods_from_workload


def test_sts_storage_annotation_overrides_template_value():
    sts = {
        "kind": "StatefulSet",
        "metadata": {"name": "db", "namespace": "d"},
        "spec": {
            "replicas": 2,
            "template": {
                "metadata": {
                    # stale hand-written value: volumeClaimTemplates win
                    "annotations": {ANNO_POD_LOCAL_STORAGE: '{"volumes": []}'}
                },
                "spec": {
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": "1"}}}
                    ]
                },
            },
            "volumeClaimTemplates": [
                {
                    "spec": {
                        "storageClassName": "open-local-lvm",
                        "resources": {"requests": {"storage": "8Gi"}},
                    }
                }
            ],
        },
    }
    pods = pods_from_workload(sts)
    assert len(pods) == 2
    for p in pods:
        vols = json.loads(p.meta.annotations[ANNO_POD_LOCAL_STORAGE])["volumes"]
        assert vols and vols[0]["scName"] == "open-local-lvm"


def test_clone_independence():
    dep = {
        "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "d"},
        "spec": {
            "replicas": 3,
            "template": {
                "metadata": {"labels": {"app": "web"}},
                "spec": {
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": "1"}}}
                    ]
                },
            },
        },
    }
    pods = pods_from_workload(dep)
    assert len({p.meta.name for p in pods}) == 3
    pods[0].meta.annotations["k"] = "v"
    pods[0].meta.labels["l"] = "v"
    pods[0].requests["cpu"] = 999
    pods[0].node_name = "n1"
    assert "k" not in pods[1].meta.annotations
    assert "l" not in pods[1].meta.labels
    assert pods[1].requests["cpu"] == 1000
    assert pods[1].node_name == ""
    # raw metadata names follow the clone
    assert pods[1].raw["metadata"]["name"] == pods[1].meta.name


def test_zero_replicas():
    dep = {
        "kind": "Deployment",
        "metadata": {"name": "w", "namespace": "d"},
        "spec": {"replicas": 0, "template": {"spec": {"containers": []}}},
    }
    assert pods_from_workload(dep) == []
