"""Preflight auditor: budget book round-trip/diff, collective census,
replication + transfer-guard fixtures, and the ladder×mesh matrix on the
conftest's 8 forced host devices.

The capture pass (warmup_registry) executes every entry once (~30 s), so
it is module-scoped and shared; matrix tests filter it down to a couple
of entries rather than re-lowering all 18.
"""

import dataclasses
import json
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from open_simulator_tpu.analysis import hlo_audit as H
from open_simulator_tpu.analysis.budget import (
    BudgetBook,
    ProgramBudget,
    estimate_bytes_by_device,
    program_key,
)
from open_simulator_tpu.parallel import mesh as pmesh

# ---------------------------------------------------------------------------
# shared captures (one ~30 s capture run for the whole module)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def caps():
    from open_simulator_tpu.engine.warmup import registry_captures

    return registry_captures()


@pytest.fixture(scope="module")
def tables():
    return H._axis_tables()


def _only(caps, *names):
    wanted = set(names)
    return [c for c in caps if c.name in wanted]


# ---------------------------------------------------------------------------
# budget book: round-trip + diff semantics (no jax compile involved)
# ---------------------------------------------------------------------------


def _budget(**over):
    base = dict(
        peak_bytes=1_000_000, argument_bytes=600_000, output_bytes=300_000,
        temp_bytes=100_000, alias_bytes=0,
        collectives={"all-reduce": 2}, collective_bytes=4096,
    )
    base.update(over)
    return ProgramBudget(**base)


def test_budget_book_round_trip(tmp_path):
    key = program_key("ops.fast:schedule_scenarios", 128, "2x2")
    book = BudgetBook(
        programs={key: _budget()},
        verdicts={"plan_1m_100k": {"ok": True, "peak_gib": 1.7}},
    )
    path = str(tmp_path / "budgets" / "preflight.json")
    book.save(path)
    loaded = BudgetBook.load(path)
    assert loaded.to_dict() == book.to_dict()
    # the on-disk form is plain sorted json (reviewable in a PR diff)
    doc = json.loads((tmp_path / "budgets" / "preflight.json").read_text())
    assert key in doc["programs"]
    assert doc["verdicts"]["plan_1m_100k"]["ok"] is True


def test_budget_diff_violation_kinds():
    key = program_key("e", 64, "1")
    book = BudgetBook(programs={key: _budget()}, slack_bytes=0, tolerance=0.05)

    # within tolerance + shrinking: clean
    assert book.diff({key: _budget(peak_bytes=1_040_000)}) == []
    assert book.diff({key: _budget(peak_bytes=10, argument_bytes=10,
                                   output_bytes=10, temp_bytes=10)}) == []

    # memory: any byte field over budget*(1+tol)+slack
    v = book.diff({key: _budget(peak_bytes=1_100_000)})
    assert [x.kind for x in v] == ["memory"]
    assert v[0].field == "peak_bytes"

    # new-collective: count above budget (absent kind counts as 0)
    v = book.diff({key: _budget(collectives={"all-reduce": 2, "all-gather": 1})})
    assert [(x.kind, x.field) for x in v] == [("new-collective", "all-gather")]

    # collective-bytes: same counts, fatter operands
    v = book.diff({key: _budget(collective_bytes=1 << 20)})
    assert [x.kind for x in v] == [("collective-bytes")]

    # unbudgeted: measured program with no book entry
    v = book.diff({program_key("e", 128, "1"): _budget()})
    assert [x.kind for x in v] == ["unbudgeted"]

    # book entries absent from measured are NOT violations (partial runs)
    assert book.diff({}) == []


# ---------------------------------------------------------------------------
# collective census + replication detector (pure text parsing)
# ---------------------------------------------------------------------------

_HLO_FIXTURE = """\
ENTRY %main (p0: f32[128,8]) -> f32[128,8] {
  %ag = f32[128,8]{1,0} all-gather(f32[64,8]{1,0} %p0), dimensions={0}
  %ar.1 = f32[128]{0} all-reduce(f32[128]{0} %x), to_apply=%sum
  %rs = f32[64,8]{1,0} reduce-scatter(f32[128,8]{1,0} %ag), dimensions={0}
  %ag2-start = (f32[4,2]) all-gather-start(f32[2,2]{1,0} %y), dimensions={0}
}
"""


def test_collective_census_counts_kinds_and_bytes():
    kinds, total, ops = H.collective_census(_HLO_FIXTURE)
    assert kinds == {"all-gather": 2, "all-reduce": 1, "reduce-scatter": 1}
    assert [k for k, _s in ops] == [
        "all-gather", "all-reduce", "reduce-scatter", "all-gather",
    ]
    # 128*8*4 + 128*4 + 64*8*4 + 4*2*4
    assert total == 4096 + 512 + 2048 + 32


def test_node_table_gathers_flags_full_rung_dims():
    _k, _t, ops = H.collective_census(_HLO_FIXTURE)
    assert H.node_table_gathers(ops, 128) == ["f32[128,8]{1,0}"]
    # reductions and lane-scalar gathers never carry the rung dim
    assert H.node_table_gathers(ops, 999) == []


def test_parse_mesh():
    assert H.parse_mesh("1") == (1, 1)
    assert H.parse_mesh("2x1") == (2, 1)
    assert H.parse_mesh("1x4") == (1, 4)
    with pytest.raises(ValueError):
        H.parse_mesh("weird")


# ---------------------------------------------------------------------------
# seeded fixtures: replication flagged, clean program passes
# ---------------------------------------------------------------------------


def _fixture_cap(name, fn, *args):
    return types.SimpleNamespace(name=name, fn=jax.jit(fn), args=args, kwargs={})


def test_seeded_replication_fixture_is_flagged(tables):
    """A program that de-shards its node-sharded input back to every
    device must trip the replication detector at a rescaled rung."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = pmesh.product_mesh_2d(1, 2)
    rep = NamedSharding(mesh, P())

    def replicate(x):
        return jax.lax.with_sharding_constraint(x + 1.0, rep)

    cap = _fixture_cap(
        "fixture:replicate", replicate, np.zeros((64, 4), np.float32)
    )
    pa = H.audit_program(cap, 128, "1x2", tables=tables)
    assert not pa.error, pa.error
    assert pa.collectives.get("all-gather", 0) >= 1
    assert pa.node_gathers, pa.to_dict()
    assert not pa.ok


def test_clean_sharded_fixture_passes(tables):
    """The same shape kept node-sharded compiles collective-free."""
    cap = _fixture_cap(
        "fixture:scale", lambda x: x * 2.0, np.zeros((64, 4), np.float32)
    )
    pa = H.audit_program(cap, 128, "1x2", tables=tables)
    assert not pa.error, pa.error
    assert pa.collectives == {}
    assert pa.node_gathers == []
    assert pa.estimate_ok, pa.to_dict()
    assert pa.ok


def test_replication_detector_mute_at_canonical_rung(tables):
    """At rung == N_CANON every fixed 64-wide dim matches the node dim,
    so the detector deliberately reports nothing there."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = pmesh.product_mesh_2d(1, 2)
    rep = NamedSharding(mesh, P())

    def replicate(x):
        return jax.lax.with_sharding_constraint(x + 1.0, rep)

    cap = _fixture_cap(
        "fixture:replicate64", replicate, np.zeros((64, 4), np.float32)
    )
    pa = H.audit_program(cap, H.N_CANON, "1x2", tables=tables)
    assert pa.node_gathers == []


# ---------------------------------------------------------------------------
# estimator vs materialized placement (hbm_bytes_per_device's twin)
# ---------------------------------------------------------------------------


def test_estimator_matches_materialized_placement():
    """The static estimate of an unmaterialized sharded aval must equal
    hbm_bytes_per_device of the same tree actually placed on a 2-device
    mesh — the pre-materialization twin contract of satellite fix 3."""
    mesh = pmesh.product_mesh_2d(1, 2)
    x = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
    placed = jax.device_put(x, pmesh.node_sharding(mesh).alloc)
    real = pmesh.hbm_bytes_per_device(placed)
    aval = jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=placed.sharding)
    est = estimate_bytes_by_device(aval)
    assert est == real
    # and hbm_bytes_per_device itself accepts the unplaced aval
    assert pmesh.hbm_bytes_per_device(aval) == real


def test_estimator_mismatch_fails_the_audit(tables, monkeypatch):
    """If the shape arithmetic under-counts, estimate_ok must go false —
    the cross-check is a real gate, not advisory."""
    cap = _fixture_cap(
        "fixture:big", lambda x: x + 1.0, np.zeros((256, 256), np.float32)
    )
    monkeypatch.setattr(
        H.budget_mod, "estimate_max_bytes_per_device",
        lambda *a, **k: 0,
    )
    pa = H.audit_program(cap, 64, "1", tables=tables)
    assert not pa.estimate_ok
    assert not pa.ok


# ---------------------------------------------------------------------------
# transfer guard
# ---------------------------------------------------------------------------


def test_transfer_guard_fixture_violation():
    """An entry that rebuilds a host operand and feeds it to the device
    every call pays a per-call h2d transfer — exactly what the guard must
    catch once the warm call has landed the one-time constants. (On the
    CPU backend only host->device transfers are guarded; d2h is
    zero-copy, which is why the fixture leaks in this direction.)"""
    add = jax.jit(jnp.add)

    def leaky(x):
        bias = np.arange(8, dtype=np.float32)  # host-built, every call
        return add(x, bias)

    chk = H.guarded_steady_state_check(
        leaky, (np.ones((8,), np.float32),), {}
    )
    assert not chk.ok
    assert "transfer" in chk.error.lower() or "disallow" in chk.error.lower()


def test_transfer_guard_clean_jit_entry_passes():
    fn = jax.jit(lambda x: x * 2.0)
    chk = H.guarded_steady_state_check(fn, (np.ones((8,), np.float32),), {})
    assert chk.ok, chk.error


# ---------------------------------------------------------------------------
# the matrix + verdict on real captured entries (8 forced devices)
# ---------------------------------------------------------------------------


def test_matrix_on_ladder_and_meshes(caps):
    subset = _only(
        caps, "ops.fast:schedule_scenarios", "ops.kernels:schedule_batch"
    )
    assert len(subset) == 2
    report = H.run_preflight(
        rungs=(64, 128), meshes=("1", "2x1", "2x2"), caps=subset,
        transfers=False, verdict=False,
    )
    assert report.meshes_skipped == []
    assert len(report.programs) == 12
    assert all(p.ok for p in report.programs), report.render_text()
    # lane parallelism: schedule_scenarios must stay collective-free on
    # meshes that do not shard the node axis
    for p in report.programs:
        if p.entry == "ops.fast:schedule_scenarios" and p.mesh in ("1", "2x1"):
            assert p.collectives == {}, p.to_dict()
    # the rescaled rung really reshaped the programs
    assert {p.rung for p in report.programs} == {64, 128}


def test_scenario_only_entry_skips_node_sharded_meshes(caps):
    subset = _only(caps, "ops.fast:light_scan")
    report = H.run_preflight(
        rungs=(64,), meshes=("1", "2x2"), caps=subset,
        transfers=False, verdict=False,
    )
    assert [p.mesh for p in report.programs] == ["1"]
    assert report.programs_skipped == [
        program_key("ops.fast:light_scan", 64, "2x2")
    ]
    assert report.ok, report.render_text()


def test_fixed_shape_entry_is_never_rung_resized():
    # the prover engine stacks EVERY leaf on the scenario axis at the
    # small-scope pads; rung-rescaling such a capture corrupts the vmap
    # axis. FIXED_SHAPE entries keep their captured shapes, unsharded.
    assert "ops.fast:schedule_universes" in H.FIXED_SHAPE
    cap = types.SimpleNamespace(
        name="ops.fast:schedule_universes",
        fn=None,
        args=(np.ones((8, 64, 4), np.float32), np.ones((8, 4), np.int32)),
        kwargs={"n_valid": 5},
    )
    args, kwargs = H.abstract_args(cap, rung=128, mesh=None, resize=False)
    assert [a.shape for a in args] == [(8, 64, 4), (8, 4)]
    assert all(a.sharding is None for a in args)
    assert kwargs == {"n_valid": 5}


def test_budget_write_and_diff_flow(caps, tmp_path):
    subset = _only(caps, "ops.kernels:probe_step")
    report = H.run_preflight(
        rungs=(64,), meshes=("1",), caps=subset,
        transfers=False, verdict=False,
    )
    assert report.ok

    path = str(tmp_path / "preflight.json")
    report.to_book().save(path)
    book = BudgetBook.load(path)

    # re-diffing the same measurements against the fresh book is clean
    assert book.diff(report.measured()) == []

    # a regression (node table suddenly 10x bigger) trips `memory`
    key = report.programs[0].key
    fat = dataclasses.replace(
        report.measured()[key],
        peak_bytes=report.measured()[key].peak_bytes * 10 + (64 << 20),
    )
    v = book.diff({key: fat})
    assert [x.kind for x in v] == ["memory"]

    # a brand-new (entry, rung, mesh) must be consciously admitted
    v = book.diff({program_key("ops.kernels:probe_step", 256, "1"):
                   report.measured()[key]})
    assert [x.kind for x in v] == ["unbudgeted"]


def test_plan_verdict_fits(caps, tables):
    v = H.plan_verdict(caps, hbm_gib=32.0, tables=tables)
    assert v["config"] == "plan_1m_100k"
    assert v["mesh"] == "1x4"
    assert v["rung"] == 102400
    assert not v.get("error"), v
    assert v["fits"] is True
    assert v["node_table_sharded"] is True
    assert v["peak_gib"] < 32.0
    assert v["ok"] is True


def test_plan_verdict_without_entry_reports_error():
    v = H.plan_verdict([], hbm_gib=32.0)
    assert v["ok"] is False
    assert "schedule_scenarios" in v["error"]
