"""Native host-runtime parity: the compiled quantity parser and row hasher
(open_simulator_tpu/native/osim_native.cpp) must agree with the exact Python
implementations on every value they accept.

The reference's host layer is compiled Go; this module is the TPU build's
equivalent compiled layer (SURVEY §2.4). All tests skip when no compiler is
available — the Python fallbacks carry full behavior.
"""

import math
import random

import numpy as np
import pytest

from open_simulator_tpu import native
from open_simulator_tpu.utils.quantity import parse_quantity

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no compiler)"
)


def exact_quad(s):
    q = parse_quantity(s)
    m, b = q * 1000, q
    return (
        int(math.ceil(m)),
        int(math.floor(m)),
        int(math.ceil(b)),
        int(math.floor(b)),
    )


CORPUS = [
    "0", "1", "250m", "1500m", "2", "512Mi", "4Gi", "1Ki", "3Ti", "2Pi",
    "107374182400", "1.5Ti", "100k", "2M", "3G", "4T", "5P",
    "0.1", "  3  ", "+2.5Gi", "-1500m", "-2", "1e3", "2E-2", "1e0", "5e6",
    "3n", "7u", ".5", "5.", "0.000001", "999999999", "12.345Mi", "1.000000001",
]

INVALID = ["", "bogus", "1.2.3", "Ki", "--1", "1..", "e3", "1ee3", "1 Gi", "1KiB"]


def test_scalar_parity_with_exact_python():
    for s in CORPUS:
        got = native.parse_quantity_one(s)
        if got is None:
            continue  # punting to the exact path is always legal
        assert got == exact_quad(s), s


def test_invalid_values_rejected():
    for s in INVALID:
        assert native.parse_quantity_one(s) is None


def test_large_negative_exponent_punts_not_wraps():
    # 10^40 would wrap u128; the parser must punt (None) so the exact
    # Fraction path answers, never return a silently-wrapped value.
    s = "3" + "0" * 35 + "e-40"
    got = native.parse_quantity_one(s)
    assert got is None or got == exact_quad(s)
    from open_simulator_tpu.utils.quantity import parse_quad

    parse_quad.cache_clear()
    assert parse_quad(s) == exact_quad(s) == (1, 0, 1, 0)


def test_randomized_parity():
    rng = random.Random(0)
    suffixes = ["", "m", "k", "M", "G", "Ki", "Mi", "Gi", "Ti", "n", "u"]
    for _ in range(2000):
        num = rng.choice(
            [
                str(rng.randint(0, 10**12)),
                f"{rng.randint(0, 10**6)}.{rng.randint(0, 999999)}",
                f".{rng.randint(1, 999)}",
            ]
        )
        s = ("-" if rng.random() < 0.2 else "") + num + rng.choice(suffixes)
        got = native.parse_quantity_one(s)
        if got is not None:
            assert got == exact_quad(s), s


def test_acceptance_matches_python_grammar():
    # Whatever Python accepts, native must either match or punt — and
    # whatever Python REJECTS, native must reject too.
    for s in CORPUS + INVALID:
        try:
            parse_quantity(s)
            py_ok = True
        except ValueError:
            py_ok = False
        got = native.parse_quantity_one(s)
        if not py_ok:
            assert got is None, s


def test_hash_rows_identity_and_difference():
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 255, (1000, 137), dtype=np.uint8)
    h = native.hash_rows(rows)
    assert h.shape == (1000, 2)
    # identical rows hash identically
    rows2 = rows.copy()
    rows2[5] = rows2[4]
    h2 = native.hash_rows(rows2)
    assert (h2[4] == h2[5]).all()
    # single-byte flips change the hash
    rows3 = rows.copy()
    rows3[7, 100] ^= 1
    h3 = native.hash_rows(rows3)
    assert (h3[7] != h[7]).any()
    # no collisions across 1000 random distinct rows
    assert len(np.unique(h.view([("a", np.uint64), ("b", np.uint64)]))) == 1000


def test_group_runs_use_native_hashing():
    # end-to-end: grouped scheduling still detects identical-pod runs
    from open_simulator_tpu.core.objects import Pod
    from open_simulator_tpu.ops.encode import Encoder, encode_pods
    from open_simulator_tpu.ops.grouped import group_runs

    def pod(name, cpu):
        return Pod.from_dict(
            {
                "metadata": {"name": name, "namespace": "d"},
                "spec": {
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": cpu}}}
                    ]
                },
            }
        )

    pods = [pod(f"a{i}", "1") for i in range(5)] + [pod(f"b{i}", "2") for i in range(3)]
    enc = Encoder()
    enc.register_pods(pods)
    batch = encode_pods(enc, pods)
    assert group_runs(batch) == [(0, 5), (5, 3)]
