{{/*
Expand the name of the chart.
*/}}
{{- define "scaffold.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" }}
{{- end }}

{{/*
Create a default fully qualified app name, truncated at 63 chars.
If release name contains chart name it will be used as a full name.
*/}}
{{- define "scaffold.fullname" -}}
{{- if .Values.fullnameOverride }}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" }}
{{- else }}
{{- $name := default .Chart.Name .Values.nameOverride }}
{{- if contains $name .Release.Name }}
{{- .Release.Name | trunc 63 | trimSuffix "-" }}
{{- else }}
{{- printf "%s-%s" .Release.Name $name | trunc 63 | trimSuffix "-" }}
{{- end }}
{{- end }}
{{- end }}

{{/*
Create chart name and version as used by the chart label.
*/}}
{{- define "scaffold.chart" -}}
{{- printf "%s-%s" .Chart.Name .Chart.Version | replace "+" "_" | trunc 63 | trimSuffix "-" }}
{{- end }}

{{/*
Common labels
*/}}
{{- define "scaffold.labels" -}}
helm.sh/chart: {{ include "scaffold.chart" . }}
{{ include "scaffold.selectorLabels" . }}
{{- if .Chart.AppVersion }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
{{- end }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{/*
Selector labels
*/}}
{{- define "scaffold.selectorLabels" -}}
app.kubernetes.io/name: {{ include "scaffold.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end }}

{{/*
Create the name of the service account to use
*/}}
{{- define "scaffold.serviceAccountName" -}}
{{- if .Values.serviceAccount.create }}
{{- default (include "scaffold.fullname" .) .Values.serviceAccount.name }}
{{- else }}
{{- default "default" .Values.serviceAccount.name }}
{{- end }}
{{- end }}
