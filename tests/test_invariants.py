"""Engine-level invariant fuzz over the full simulate() API.

The kernel-level fuzz (test_fuzz_parity.py) proves the fast paths equal the
sequential oracle; this layer checks what no kernel oracle can — that the
END-TO-END engine (workload expansion, ordering, device state bookkeeping,
preemption eviction/rollback accounting) never produces a physically
invalid result. Checked with the pure-Python predicates in core/matcher.py
(the reference's validation logic re-derived), against randomized clusters
mixing priorities, PDBs, taints, selectors and anti-affinity:

  1. conservation: placed + unscheduled == expected per workload
  2. no overcommit: per-node cpu/mem/pod-count within allocatable
  3. placement legality: every placed pod tolerates its node's NoSchedule
     taints and matches its own nodeSelector
  4. eviction accounting: preempted pods are unbound (and never double-
     counted in node usage), every preemptor is placed or honestly failed
"""

import random

from open_simulator_tpu.core.matcher import (
    match_node_affinity,
    untolerated_taint,
)
from open_simulator_tpu.core.workloads import expected_pod_counts
from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    simulate,
)
from tests.factories import (
    make_daemonset,
    make_deployment,
    make_job,
    make_node,
    make_statefulset,
    taint,
    toleration,
)


def _rand_cluster(rng):
    nodes = []
    for i in range(rng.randint(2, 8)):
        labels = {}
        if rng.random() < 0.5:
            labels["pool"] = rng.choice(["a", "b"])
        nodes.append(
            make_node(
                f"n{i}",
                cpu=str(rng.choice([2, 4, 8])),
                memory=f"{rng.choice([4, 8, 16])}Gi",
                pods=str(rng.choice([5, 110])),
                with_labels=labels,
                with_taints=(
                    [taint("ded", "x")] if rng.random() < 0.3 else None
                ),
            )
        )
    return nodes


def _rand_workloads(rng, n):
    objs = []
    for w in range(n):
        opts = dict(
            cpu=rng.choice(["250m", "500m", "1", "2"]),
            memory=rng.choice(["256Mi", "1Gi"]),
            namespace="inv",
        )
        if rng.random() < 0.4:
            opts["with_tolerations"] = [toleration("ded", operator="Exists")]
        if rng.random() < 0.3:
            opts["with_node_selector"] = {"pool": rng.choice(["a", "b"])}
        if rng.random() < 0.3:
            opts["with_priority"] = rng.choice([0, 10, 100])
        if rng.random() < 0.2:
            opts["with_affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {
                                "matchLabels": {"app": f"w{w}"}
                            },
                            "topologyKey": "kubernetes.io/hostname",
                        }
                    ]
                }
            }
        kind = rng.choice(["Deployment", "StatefulSet", "Job"])
        if kind == "Deployment":
            objs.append(
                make_deployment(f"w{w}", replicas=rng.randint(1, 6), **opts)
            )
        elif kind == "StatefulSet":
            objs.append(
                make_statefulset(f"w{w}", replicas=rng.randint(1, 6), **opts)
            )
        else:
            objs.append(
                make_job(
                    f"w{w}", completions=rng.randint(1, 6), parallelism=2,
                    **opts,
                )
            )
    pdbs = []
    if rng.random() < 0.4:
        pdbs.append(
            {
                "kind": "PodDisruptionBudget",
                "metadata": {"name": "pdb", "namespace": "inv"},
                "spec": {
                    "minAvailable": rng.randint(0, 2),
                    "selector": {"matchLabels": {"app": "w0"}},
                },
            }
        )
    return objs, pdbs


def _check_invariants(cluster, objs, result):
    # 1. conservation — preempted victims are DELETED from the cluster
    # (the reference's PrepareCandidate deletes them), so they account for
    # the gap between expected and placed+unscheduled
    expected = expected_pod_counts(objs + cluster.daemonsets, cluster.nodes)
    placed = sum(len(st.pods) for st in result.node_status)
    assert placed + len(result.unscheduled) + len(result.preempted) == sum(
        expected.values()
    ), (placed, len(result.unscheduled), len(result.preempted), expected)

    node_by_name = {n.name: n for n in cluster.nodes}
    placed_keys = set()
    for st in result.node_status:
        node = st.node
        cpu = mem = 0
        for p in st.pods:
            assert p.node_name == node.name and p.phase == "Running"
            assert p.key not in placed_keys, f"double-bound {p.key}"
            placed_keys.add(p.key)
            cpu += p.requests.get("cpu", 0)
            mem += p.requests.get("memory", 0)
            # 3. placement legality
            taint = untolerated_taint(p.tolerations, node)
            assert taint is None or taint.effect != "NoSchedule", (
                f"{p.key} on {node.name} despite taint {taint}"
            )
            for k, v in p.node_selector.items():
                assert node.meta.labels.get(k) == v, (
                    f"{p.key}: selector {k}={v} vs {node.meta.labels}"
                )
            assert match_node_affinity(p, node), f"{p.key} affinity"
        # 2. no overcommit
        assert cpu <= node.allocatable.get("cpu", 0), (node.name, "cpu")
        assert mem <= node.allocatable.get("memory", 0), (node.name, "mem")
        assert len(st.pods) <= node.allocatable.get("pods", 1 << 30)

    # 4. eviction accounting
    for pre in result.preempted:
        assert pre.pod.key not in placed_keys, (
            f"preempted {pre.pod.key} still bound"
        )
        assert pre.pod.node_name == "" and pre.pod.phase == "Pending"
    unsched_keys = {u.pod.key for u in result.unscheduled}
    assert not (unsched_keys & placed_keys)


def test_engine_invariants_randomized():
    """OSIM_INV_TRIALS widens the sweep for soaks (default 12 for CI); the
    seed is fixed so any failure reproduces by trial count alone."""
    import os

    trials = int(os.environ.get("OSIM_INV_TRIALS", "12"))
    rng = random.Random(20260730)
    for trial in range(trials):
        nodes = _rand_cluster(rng)
        objs, pdbs = _rand_workloads(rng, rng.randint(1, 4))
        cluster = ClusterResource(
            nodes=nodes, others={"PodDisruptionBudget": pdbs}
        )
        result = simulate(cluster, [AppResource(name="inv", objects=objs)])
        _check_invariants(cluster, objs, result)


def test_engine_invariants_with_extender(stub_factory):
    """The per-pod extender path (probe→HTTP→commit, plus extender-aware
    preemption) must uphold the same physical invariants as the fused batch
    scan — a pass-through extender routes EVERY pod through it."""
    from open_simulator_tpu.models.profiles import ExtenderConfig

    stub = stub_factory({})   # keep all nodes, score 0
    cfg = ExtenderConfig(
        url_prefix=stub.url, filter_verb="filter",
        prioritize_verb="prioritize", preempt_verb="preempt",
    )
    rng = random.Random(51)
    for trial in range(4):
        nodes = _rand_cluster(rng)
        objs, pdbs = _rand_workloads(rng, rng.randint(1, 3))
        cluster = ClusterResource(
            nodes=nodes, others={"PodDisruptionBudget": pdbs}
        )
        result = simulate(
            cluster, [AppResource(name="inv", objects=objs)],
            extenders=[cfg],
        )
        _check_invariants(cluster, objs, result)
    assert stub.calls   # the extender really was in the path


def test_engine_invariants_with_cluster_daemonset():
    rng = random.Random(77)
    for trial in range(4):
        nodes = _rand_cluster(rng)
        objs, _ = _rand_workloads(rng, 2)
        ds = make_daemonset(
            "agent", namespace="kube-system", cpu="100m", memory="64Mi",
            with_tolerations=[{"operator": "Exists"}],
        )
        cluster = ClusterResource(nodes=nodes, daemonsets=[ds])
        result = simulate(cluster, [AppResource(name="inv", objects=objs)])
        _check_invariants(cluster, objs, result)
