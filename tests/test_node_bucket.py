"""Node-bucket ladder, template-stamped encode, and the 2-D
(scenarios, nodes) mesh (docs/performance.md, "Node-bucket ladder").

Three contracts:
  - shapes: `round_up(floor, step)` and `node_bucket` pin the exact ladder
    the jit program family compiles against — any drift is a silent
    recompile storm, so the rungs are regression-pinned here;
  - bytes: the template-stamping fast path in `encode_nodes` must be
    byte-identical to the per-node loop encode over arbitrary node
    populations (GPU, local-storage, taints, usage maps included);
  - digests: padding to a bigger rung, and sharding the sweep over a 2-D
    (scenarios, nodes) mesh, must not change a single placement or reason.
"""

import random
import time

import jax
import numpy as np
import pytest

from open_simulator_tpu.core.objects import Node
from open_simulator_tpu.core.workloads import reset_name_rng
from open_simulator_tpu.engine.simulator import Scenario, simulate, simulate_batch
from open_simulator_tpu.ops.encode import (
    NODE_BUCKET_FLOOR,
    NODE_BUCKET_STEP,
    Encoder,
    _STAMP_FIELDS,
    encode_nodes,
    ladder_rungs,
    node_bucket,
    round_up,
)
from tests.factories import make_node
from tests.test_batch_engine import digest, overflow_fixture

# ---------------------------------------------------------------------------
# shape regression: the ladder itself
# ---------------------------------------------------------------------------


def test_round_up_floor_and_step_are_distinct_knobs():
    assert round_up(1) == 8          # default floor
    assert round_up(9) == 16         # pow2 region
    assert round_up(4096) == 4096
    assert round_up(4097) == 8192    # first linear rung
    assert round_up(1, floor=64) == 64
    # step bounds the pow2 region: past it, multiples of step
    assert round_up(100, floor=64, step=32) == 128
    assert round_up(33, floor=8, step=32) == 64
    assert round_up(65, floor=8, step=32) == 96


def test_node_bucket_pins_the_ladder():
    assert node_bucket(0) == 64
    assert node_bucket(1) == 64
    assert node_bucket(64) == 64
    assert node_bucket(65) == 128
    assert node_bucket(4096) == 4096
    assert node_bucket(4097) == 8192
    assert node_bucket(8193) == 12288
    assert node_bucket(100_000) == 102_400
    # rename-compat: node_bucket is exactly the old round_up(n, 64)
    for n in (0, 1, 63, 64, 65, 1000, 4095, 4096, 4097, 9000, 123_456):
        assert node_bucket(n) == round_up(n, floor=64)


def test_ladder_rungs_enumerates_the_program_family():
    assert ladder_rungs(64) == [64]
    assert ladder_rungs(4097) == [64, 128, 256, 512, 1024, 2048, 4096, 8192]
    rungs = ladder_rungs(20_000)
    assert rungs[-1] == node_bucket(20_000) == 20_480
    # every rung is a fixed point of node_bucket (the ladder_ok contract)
    for r in rungs:
        assert node_bucket(r) == r
    assert NODE_BUCKET_FLOOR == 64 and NODE_BUCKET_STEP == 4096


# ---------------------------------------------------------------------------
# bucket-boundary digest equivalence
# ---------------------------------------------------------------------------


def test_padding_to_a_bigger_rung_changes_nothing():
    """The same cluster simulated at its natural rung (64) and one rung up
    (128) must produce byte-identical placements, reasons, preemptions —
    padded rows are inert, so the rung is purely a compilation shape."""
    cluster, apps = overflow_fixture()
    reset_name_rng()
    ref = simulate(cluster, apps)
    for n_pad in (128, 256):
        reset_name_rng()
        cluster2, apps2 = overflow_fixture()
        assert digest(simulate(cluster2, apps2, n_pad=n_pad)) == digest(ref)


# ---------------------------------------------------------------------------
# template-stamped encode == loop encode, byte for byte
# ---------------------------------------------------------------------------


def gpu_node(name, count=2, per_dev_mib=16_384):
    return Node.from_dict(
        {
            "metadata": {
                "name": name,
                "labels": {"kubernetes.io/hostname": name},
            },
            "status": {
                "allocatable": {
                    "cpu": "32",
                    "memory": "128Gi",
                    "pods": "110",
                    "alibabacloud.com/gpu-count": str(count),
                    "alibabacloud.com/gpu-mem": f"{count * per_dev_mib}Mi",
                }
            },
        }
    )


def storage_node(name, vgs=(), devices=()):
    import json as _json

    from open_simulator_tpu.core.objects import ANNO_NODE_LOCAL_STORAGE

    node = Node.from_dict(
        {
            "metadata": {"name": name},
            "status": {
                "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}
            },
        }
    )
    GiB = 1 << 30
    node.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = _json.dumps(
        {
            "vgs": [
                {"name": n, "capacity": str(c * GiB), "requested": str(r * GiB)}
                for n, c, r in vgs
            ],
            "devices": [
                {
                    "name": n,
                    "device": f"/dev/{n}",
                    "capacity": str(c * GiB),
                    "mediaType": m,
                    "isAllocated": a,
                }
                for n, c, m, a in devices
            ],
        }
    )
    return node


def unsched_node(name):
    return Node.from_dict(
        {
            "metadata": {"name": name},
            "spec": {"unschedulable": True},
            "status": {
                "allocatable": {"cpu": "4", "memory": "8Gi", "pods": "110"}
            },
        }
    )


def mixed_population(seed, n_specs=6, max_clones=7):
    """A randomized node population with clone runs of every axis the row
    encode touches: plain, labeled, tainted, unschedulable, GPU, and
    local-storage (VG + device) nodes, interleaved."""
    rng = random.Random(seed)
    makers = [
        lambda nm: make_node(nm, cpu="4", memory="8Gi"),
        lambda nm: make_node(
            nm, cpu="8", memory="16Gi",
            with_labels={"zone": f"az-{rng.randint(0, 1)}", "disk": "ssd"},
        ),
        lambda nm: make_node(
            nm, cpu="16", memory="32Gi",
            with_taints=[
                {"key": "dedicated", "value": "batch", "effect": "NoSchedule"}
            ],
        ),
        lambda nm: unsched_node(nm),
        lambda nm: gpu_node(nm, count=rng.choice((1, 4)), per_dev_mib=8192),
        lambda nm: storage_node(
            nm,
            vgs=(("vg-open", 200, 20),),
            devices=(("sdb", 100, "hdd", False), ("sdc", 50, "ssd", False)),
        ),
    ]
    nodes = []
    for s in range(n_specs):
        mk = makers[s % len(makers)]
        for c in range(rng.randint(2, max_clones)):
            nodes.append(mk(f"spec{s}-n{c}"))
    rng.shuffle(nodes)
    return nodes


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_stamped_encode_is_byte_identical_to_loop_encode(seed):
    nodes = mixed_population(seed)
    # usage maps key off node NAME — give a few nodes bound-pod usage and
    # GPU usage so differing usage splits otherwise-identical specs
    usage = {nodes[0].name: {"cpu": 2000, "memory": 1 << 30}}
    gpu = {
        nd.name: np.array([1024.0], np.float32)
        for nd in nodes
        if nd.gpu_count() == 1
    }

    enc_loop, enc_stamp = Encoder(), Encoder()
    t_loop = encode_nodes(
        enc_loop, nodes, existing_usage=usage, existing_gpu=gpu, stamp=False
    )
    t_stamp = encode_nodes(
        enc_stamp, nodes, existing_usage=usage, existing_gpu=gpu, stamp=True
    )

    for f in _STAMP_FIELDS:
        a = np.asarray(getattr(t_loop, f))
        b = np.asarray(getattr(t_stamp, f))
        # tobytes: NaN-aware (label_num pads with NaN)
        assert a.tobytes() == b.tobytes(), f"field {f} diverged"
    assert t_loop.names == t_stamp.names
    # clone names intern at their loop position: the vocabularies agree
    assert len(enc_loop.names) == len(enc_stamp.names)
    assert len(enc_loop.pairs) == len(enc_stamp.pairs)


def test_fake_node_clones_stamp_byte_identical():
    """The identity-token fast path (new_fake_nodes clones skip the content
    signature) must stay byte-identical to the loop encode — including a
    clone that drifts out of its group via a bound-usage entry."""
    from open_simulator_tpu.engine.capacity import new_fake_nodes

    base = [make_node(f"base-{i}", cpu="8", memory="16Gi") for i in range(3)]
    t1 = make_node("t1", cpu="32", memory="64Gi", with_labels={"zone": "a"})
    t2 = gpu_node("t2", count=2)
    nodes = base + new_fake_nodes(t1, 50) + new_fake_nodes(t2, 80, start=50)
    usage = {"simon-00003": {"cpu": 1000, "memory": 1 << 30}}

    enc_loop, enc_stamp = Encoder(), Encoder()
    t_loop = encode_nodes(enc_loop, nodes, existing_usage=usage, stamp=False)
    t_stamp = encode_nodes(enc_stamp, nodes, existing_usage=usage, stamp=True)
    for f in _STAMP_FIELDS:
        assert (
            np.asarray(getattr(t_loop, f)).tobytes()
            == np.asarray(getattr(t_stamp, f)).tobytes()
        ), f"field {f} diverged"
    assert t_loop.names == t_stamp.names
    assert len(enc_loop.names) == len(enc_stamp.names)
    assert len(enc_loop.pairs) == len(enc_stamp.pairs)


def test_stamped_rows_metric_counts_clones():
    from open_simulator_tpu.utils import metrics

    nodes = [make_node(f"m-{i}", cpu="4", memory="8Gi") for i in range(10)]
    before = metrics.ENCODE_STAMPED_ROWS.value()
    encode_nodes(Encoder(), nodes, stamp=True)
    assert metrics.ENCODE_STAMPED_ROWS.value() == before + 9  # 1 template


@pytest.mark.slow
def test_stamped_encode_speedup_at_20k_nodes():
    """Acceptance: >= 10x over the loop encode at 20k clones of one spec —
    the capacity-plan shape (new_fake_nodes clones of a realistic
    heterogeneous template: zone/instance-type labels, a taint, GPUs, and
    open-local storage, so the per-row loop encode pays every axis it would
    pay in production)."""
    import json as _json

    from open_simulator_tpu.core.objects import ANNO_NODE_LOCAL_STORAGE
    from open_simulator_tpu.engine.capacity import new_fake_nodes

    GiB = 1 << 30
    template = make_node(
        "tmpl", cpu="32", memory="64Gi",
        with_labels={
            "topology.kubernetes.io/zone": "az-1",
            "node.kubernetes.io/instance-type": "ecs.gn7.8xlarge",
            "disk": "ssd",
            "pool": "batch",
        },
        with_taints=[
            {"key": "dedicated", "value": "batch", "effect": "NoSchedule"}
        ],
        with_capacity={
            "alibabacloud.com/gpu-count": "4",
            "alibabacloud.com/gpu-mem": f"{4 * 16384}Mi",
        },
    )
    template.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = _json.dumps(
        {
            "vgs": [{"name": "vg-open", "capacity": str(400 * GiB),
                     "requested": str(40 * GiB)}],
            "devices": [{"name": "sdb", "device": "/dev/sdb",
                         "capacity": str(200 * GiB), "mediaType": "ssd",
                         "isAllocated": False}],
        }
    )
    nodes = new_fake_nodes(template, 20_000)
    t0 = time.perf_counter()
    t_loop = encode_nodes(Encoder(), nodes, stamp=False)
    loop_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(3):
        enc = Encoder()
        t0 = time.perf_counter()
        t_stamp = encode_nodes(enc, nodes, stamp=True)
        best = min(best, time.perf_counter() - t0)
    assert loop_s / best >= 10.0, f"stamped {best:.3f}s vs loop {loop_s:.3f}s"
    for f in _STAMP_FIELDS:
        assert (
            np.asarray(getattr(t_loop, f)).tobytes()
            == np.asarray(getattr(t_stamp, f)).tobytes()
        ), f"field {f} diverged at 20k nodes"


# ---------------------------------------------------------------------------
# 2-D (scenarios, nodes) mesh: digest-identical, less HBM per device
# ---------------------------------------------------------------------------


def _mesh_or_skip(s_devs, n_devs):
    from open_simulator_tpu.parallel.mesh import product_mesh_2d

    if len(jax.devices()) < s_devs * n_devs:
        pytest.skip(f"needs {s_devs * n_devs} devices")
    return product_mesh_2d(s_devs, n_devs)


@pytest.mark.parametrize("s_devs,n_devs", [(2, 1), (1, 2), (2, 2), (2, 4)])
def test_2d_mesh_sweep_is_digest_identical(s_devs, n_devs):
    mesh = _mesh_or_skip(s_devs, n_devs)
    cluster, apps = overflow_fixture()
    scenarios = [
        Scenario(name="tiny", node_count=2),
        Scenario(name="half", node_count=3),
        Scenario(name="most", node_count=5),
        Scenario(name="all"),
    ]
    reset_name_rng()
    ref = simulate_batch(cluster, apps, scenarios)
    reset_name_rng()
    cluster2, apps2 = overflow_fixture()
    sharded = simulate_batch(cluster2, apps2, scenarios, mesh=mesh)
    assert [digest(r) for r in sharded] == [digest(r) for r in ref]


def test_2d_mesh_shards_node_tables_across_hbm():
    """Sharding the node axis must actually cut per-device bytes vs the
    replicated layout (the reason the 2-D mesh exists)."""
    from open_simulator_tpu.parallel.mesh import (
        hbm_bytes_per_device,
        node_sharding,
        product_mesh_2d,
        replicated,
    )
    from open_simulator_tpu.ops.state import node_static_from_table
    from open_simulator_tpu.utils import metrics

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = product_mesh_2d(2, 4)
    enc = Encoder()
    nodes = [make_node(f"h-{i:04d}", cpu="8", memory="16Gi")
             for i in range(512)]
    ns = node_static_from_table(enc, encode_nodes(enc, nodes))

    rep = hbm_bytes_per_device(jax.device_put(ns, replicated(mesh, ns)))
    shd = hbm_bytes_per_device(jax.device_put(ns, node_sharding(mesh)))
    assert max(shd.values()) < max(rep.values())
    # the gauge snapshots the last call's layout
    for dev, nbytes in shd.items():
        assert metrics.HBM_BYTES_PER_DEVICE.value(device=dev) == nbytes


# ---------------------------------------------------------------------------
# capacity search stays on the ladder
# ---------------------------------------------------------------------------


def test_batched_capacity_sweep_compiles_only_ladder_rungs():
    """Every scenario program key a batched capacity sweep touches must sit
    on a ladder rung (node_bucket fixed point) with at most
    SCENARIO_PROGRAMS_PER_BUCKET paddings per key — the <= 1 program per
    rung guarantee that makes `simon warmup` able to pre-bank the sweep."""
    from open_simulator_tpu.engine.capacity import plan_capacity
    from open_simulator_tpu.engine.simulator import AppResource, ClusterResource
    from open_simulator_tpu.ops.fast import (
        reset_scenario_programs,
        scenario_programs,
    )
    from tests.factories import make_deployment
    from tests.test_batch_engine import HOSTNAME_ANTI

    cluster = ClusterResource(
        nodes=[make_node(f"base-{i}", cpu="32", memory="64Gi")
               for i in range(2)]
    )
    apps = [
        AppResource(
            name="app",
            objects=[
                make_deployment(
                    "lonely", replicas=40, cpu="500m", memory="1Gi",
                    with_affinity=HOSTNAME_ANTI,
                )
            ],
        )
    ]
    template = make_node("clone", cpu="32", memory="64Gi")
    reset_scenario_programs()
    reset_name_rng()
    plan = plan_capacity(cluster, apps, template, sweep_mode="batched")
    assert plan is not None and plan.batched_calls > 0
    progs = scenario_programs()
    assert progs, "batched sweep must record scenario programs"
    for (n, _p), pads in progs.items():
        assert node_bucket(n) == n, f"off-ladder node pad {n}"
        assert len(pads) <= 2, f"paddings exploded for N={n}: {pads}"
