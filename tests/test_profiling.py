"""Device-time profiling (utils/profiling.py): dispatch-gap analyzer +
jax.profiler capture wrapper, plus the bench/CLI surfaces that expose them."""

import jax.numpy as jnp
import pytest

from open_simulator_tpu.utils import metrics, profiling, tracing
from open_simulator_tpu.utils.profiling import (
    DispatchGapReport,
    EntryTiming,
    analyze_dispatch_gaps,
    capture_device_trace,
)


class _Cap:
    """Minimal stand-in for a jaxpr_audit capture: .name/.fn/.args/.kwargs."""

    def __init__(self, name, fn, args=(), kwargs=None):
        self.name = name
        self.fn = fn
        self.args = args
        self.kwargs = kwargs or {}


def _caps():
    return [
        _Cap("t:add", lambda a, b: a + b, (jnp.ones(64), jnp.ones(64))),
        _Cap("t:sum", lambda a: jnp.sum(a * a), (jnp.arange(128.0),)),
    ]


def test_analyze_dispatch_gaps_times_every_entry():
    rep = analyze_dispatch_gaps(captures=_caps(), repeats=2)
    assert [e.name for e in rep.entries] == ["t:add", "t:sum"]
    for e in rep.entries:
        assert e.total_ms > 0
        assert e.dispatch_ms >= 0 and e.device_ms >= 0
        assert 0.0 <= e.gap_ratio <= 1.0
        assert e.repeats == 2
        # the sandwich decomposes the total exactly
        assert e.dispatch_ms + e.device_ms == pytest.approx(
            e.total_ms, rel=1e-6
        )
    # the report property rounds to 4 decimals
    assert rep.device_time_ms == pytest.approx(
        sum(e.device_ms for e in rep.entries), abs=1e-4
    )


def test_analyze_publishes_metrics_and_device_spans():
    analyze_dispatch_gaps(captures=_caps(), repeats=1)
    assert metrics.DEVICE_TIME.value(entry="t:add") >= 0.0
    assert 0.0 <= metrics.DISPATCH_GAP.value(entry="t:sum") <= 1.0
    root = [
        r for r in tracing.recent_timings()
        if r["name"] == "dispatch-gap-analysis"
    ][-1]
    dev = {c["name"]: c for c in root["children"]}
    assert "device:t:add" in dev and "device:t:sum" in dev
    meta = dev["device:t:sum"]["meta"]
    assert {"entry", "device_ms", "dispatch_ms", "gap_ratio"} <= set(meta)


def test_aggregate_gap_is_time_weighted_not_mean_of_ratios():
    """A tiny all-dispatch entry must not outvote a big all-device one:
    the aggregate is sum(dispatch)/sum(total), not mean(gap_ratio)."""
    rep = DispatchGapReport(
        entries=[
            EntryTiming("tiny", dispatch_ms=1.0, device_ms=0.0,
                        total_ms=1.0, gap_ratio=1.0, repeats=1),
            EntryTiming("big", dispatch_ms=0.0, device_ms=99.0,
                        total_ms=99.0, gap_ratio=0.0, repeats=1),
        ],
        seconds=0.1,
    )
    assert rep.dispatch_gap_ratio == 0.01  # not (1.0 + 0.0) / 2
    assert rep.device_time_ms == 99.0
    d = rep.to_dict()
    assert d["dispatch_gap_ratio"] == 0.01
    assert [e["name"] for e in d["entries"]] == ["tiny", "big"]
    assert "aggregate gap ratio 0.010" in rep.render_text()


def test_fresh_args_recopies_donated_argnums():
    """A donating entry consumes its inputs; the analyzer must hand it a
    fresh copy per call so the registry's canonical args stay live."""

    def fn(a, b):
        return a + b

    fn.__osim_donate_argnums__ = (0,)
    a, b = jnp.ones(8), jnp.ones(8)
    cap = _Cap("t:donate", fn, (a, b))
    fresh = profiling._fresh_args(cap)
    assert fresh[0] is not a        # donated: re-copied
    assert fresh[1] is b            # non-donated: passed through
    assert (fresh[0] == a).all()
    # no donation marker -> the stored tuple is reused as-is
    cap2 = _Cap("t:plain", lambda x: x, (a,))
    assert profiling._fresh_args(cap2) is cap2.args


def test_capture_device_trace_writes_into_out_dir(tmp_path):
    out = tmp_path / "devtrace"
    rep = capture_device_trace(
        str(out), fn=lambda: jnp.sum(jnp.ones(32)).block_until_ready()
    )
    assert rep["ok"] is True, rep
    assert rep["trace_dir"] == str(out)
    assert rep["seconds"] >= 0
    assert out.is_dir()


def test_capture_device_trace_failure_degrades_not_raises(tmp_path):
    def boom():
        raise RuntimeError("profiled workload exploded")

    rep = capture_device_trace(str(tmp_path / "t2"), fn=boom)
    assert rep["ok"] is False
    assert "profiled workload exploded" in rep["error"]


def test_bench_segment_device_fields_default_null(monkeypatch, capsys):
    import json

    import bench

    monkeypatch.delenv("OSIM_DEVICE_PROFILE", raising=False)
    monkeypatch.setitem(
        bench.CONFIGS, "null_probe", lambda: {"elapsed_s": 0.0}
    )
    rc = bench._segment_main("null_probe", 0, 0)
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["device_time_ms"] is None
    assert out["dispatch_gap_ratio"] is None
    assert "device_profile" not in out


def test_bench_segment_device_fields_filled_under_env(monkeypatch, capsys):
    import json

    import bench
    from open_simulator_tpu.utils import profiling as prof_mod

    monkeypatch.setenv("OSIM_DEVICE_PROFILE", "1")
    monkeypatch.setattr(
        prof_mod, "registry_captures", lambda names=None: _caps(),
        raising=False,
    )
    # route the registry lookup through the injected captures
    orig = prof_mod.analyze_dispatch_gaps
    monkeypatch.setattr(
        prof_mod,
        "analyze_dispatch_gaps",
        lambda names=None, repeats=2, captures=None: orig(
            captures=_caps(), repeats=repeats
        ),
    )
    monkeypatch.setitem(
        bench.CONFIGS, "null_probe", lambda: {"elapsed_s": 0.0}
    )
    rc = bench._segment_main("null_probe", 0, 0)
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["device_time_ms"] is not None and out["device_time_ms"] >= 0
    assert 0.0 <= out["dispatch_gap_ratio"] <= 1.0
    assert [e["name"] for e in out["device_profile"]["entries"]] == [
        "t:add", "t:sum",
    ]


def test_cli_profile_gaps_json(monkeypatch, capsys):
    import json

    from open_simulator_tpu.cli import main as cli
    from open_simulator_tpu.utils import profiling as prof_mod

    orig = prof_mod.analyze_dispatch_gaps
    monkeypatch.setattr(
        prof_mod,
        "analyze_dispatch_gaps",
        lambda names=None, repeats=2, captures=None: orig(
            captures=_caps(), repeats=repeats
        ),
    )
    rc = cli.main(["profile", "--format", "json"])
    assert rc == 0
    out = capsys.readouterr().out
    doc = json.loads(out[out.index("{"):])
    assert "trace" not in doc  # no command given -> analyzer only
    entries = doc["dispatch_gaps"]["entries"]
    assert [e["name"] for e in entries] == ["t:add", "t:sum"]
    assert all(e["repeats"] == 3 for e in entries)
