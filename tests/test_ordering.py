"""Pod-ordering queues (core/ordering.py).

Parity: pkg/algo/{greed,affinity,toleration}.go — GreedQueue's dominant-share
descending order with node-pinned pods first, AffinityQueue (nodeSelector
first), TolerationQueue (tolerations first), and ScheduleApp's composition.
"""

from open_simulator_tpu.core.objects import Node, Pod
from open_simulator_tpu.core.ordering import (
    affinity_sort,
    cluster_totals,
    greed_sort,
    order_pods,
    pod_dominant_share,
    share,
    toleration_sort,
)


def mknode(name, cpu="10", mem="100Gi"):
    return Node.from_dict(
        {
            "metadata": {"name": name},
            "status": {"allocatable": {"cpu": cpu, "memory": mem, "pods": "110"}},
        }
    )


def mkpod(name, cpu=None, mem=None, selector=None, tolerations=None, node=""):
    req = {}
    if cpu:
        req["cpu"] = cpu
    if mem:
        req["memory"] = mem
    spec = {"containers": [{"name": "c", "image": "img", "resources": {"requests": req}}]}
    if selector:
        spec["nodeSelector"] = selector
    if tolerations:
        spec["tolerations"] = tolerations
    if node:
        spec["nodeName"] = node
    return Pod.from_dict({"metadata": {"name": name, "namespace": "d"}, "spec": spec})


def names(pods):
    return [p.meta.name for p in pods]


def test_share():
    assert share(0, 0) == 0.0
    assert share(5, 0) == 1.0
    assert share(5, 10) == 0.5


def test_dominant_share_is_max_over_cpu_mem():
    nodes = [mknode("n", cpu="10", mem="100Gi")]
    totals = cluster_totals(nodes)
    # 2/10 cpu vs 10/100 mem -> cpu dominates at 0.2
    p = mkpod("p", cpu="2", mem="10Gi")
    assert pod_dominant_share(p, totals) == 0.2
    assert pod_dominant_share(mkpod("empty"), totals) == 0.0


def test_greed_sort_descending_share_pinned_first():
    nodes = [mknode("n")]
    big = mkpod("big", cpu="5")
    small = mkpod("small", cpu="1")
    mid = mkpod("mid", cpu="3")
    pinned = mkpod("pinned", cpu="1", node="n")
    assert names(greed_sort([small, big, pinned, mid], nodes)) == [
        "pinned", "big", "mid", "small",
    ]


def test_affinity_and_toleration_sorts():
    sel = mkpod("sel", cpu="1", selector={"zone": "a"})
    plain = mkpod("plain", cpu="1")
    tol = mkpod("tol", cpu="1", tolerations=[{"key": "k", "operator": "Exists"}])
    assert names(affinity_sort([plain, sel])) == ["sel", "plain"]
    assert names(toleration_sort([plain, tol])) == ["tol", "plain"]


def test_order_pods_composition():
    nodes = [mknode("n")]
    a = mkpod("big-tol", cpu="5", tolerations=[{"key": "k", "operator": "Exists"}])
    b = mkpod("small-tol", cpu="1", tolerations=[{"key": "k", "operator": "Exists"}])
    c = mkpod("big-plain", cpu="4")
    d = mkpod("small-plain", cpu="2")
    # default: toleration class first, stable within class
    assert names(order_pods([c, a, d, b], nodes)) == [
        "big-tol", "small-tol", "big-plain", "small-plain",
    ]
    # greed: share ordering within each toleration class
    assert names(order_pods([b, d, a, c], nodes, use_greed=True)) == [
        "big-tol", "small-tol", "big-plain", "small-plain",
    ]


def test_use_greed_end_to_end():
    from open_simulator_tpu.engine.simulator import (
        AppResource,
        ClusterResource,
        simulate,
    )

    cluster = ClusterResource(nodes=[mknode("w", cpu="8", mem="16Gi")])
    dep = {
        "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "d"},
        "spec": {
            "replicas": 3,
            "template": {
                "spec": {
                    "containers": [
                        {"name": "c", "image": "img", "resources": {"requests": {"cpu": "1"}}}
                    ]
                }
            },
        },
    }
    result = simulate(cluster, [AppResource("a", [dep])], use_greed=True)
    assert not result.unscheduled
