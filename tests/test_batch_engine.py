"""Batched scenario engine equivalence (docs/batching.md).

Every lane of ``simulate_batch()`` must be *byte-identical* to a serial
``simulate()`` of that lane's scenario — same placements, same unscheduled
pods with the same reason strings — whether the batched vmapped path runs
or the engine falls back to per-scenario serial simulation (preemption).
Pod names draw from the process-global seeded RNG (core/workloads._rng),
so every expansion that must be comparable calls ``reset_name_rng()``
first; without it the *names* differ even when placements agree.

Also covers the batched capacity search's call budget: where the serial
bisection issues >= 8 probe simulations, the batched sweep must close the
same bracket in <= 3 vmapped device calls, reaching the same answer, and
keep every scenario program key within its padding budget.
"""

import json

import pytest

from open_simulator_tpu.core.workloads import reset_name_rng
from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    Scenario,
    simulate,
    simulate_batch,
)
from tests.factories import make_deployment, make_node

HOSTNAME_ANTI = {
    "podAntiAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [
            {
                "labelSelector": {"matchLabels": {"app": "lonely"}},
                "topologyKey": "kubernetes.io/hostname",
            }
        ]
    }
}


def digest(result) -> str:
    """Canonical byte-serialization of a SimulateResult: node -> sorted pod
    keys, plus every unscheduled (pod key, reason) pair. Any placement or
    reason drift between the batched and serial paths changes this string."""
    doc = {
        "placements": {
            st.node.name: sorted(p.key for p in st.pods)
            for st in result.node_status
        },
        "unscheduled": sorted(
            (u.pod.key, u.reason) for u in result.unscheduled
        ),
        "preempted": sorted(
            (p.pod.key, p.node, p.by) for p in result.preempted
        ),
    }
    return json.dumps(doc, sort_keys=True)


def serial_oracle(cluster, apps, sc: Scenario, n_nodes: int):
    """Serial simulate() of exactly the subcluster scenario `sc` describes."""
    keep = sc.keep_mask(n_nodes)
    nodes = (
        cluster.nodes
        if keep is None
        else [n for n, k in zip(cluster.nodes, keep) if k]
    )
    sub = ClusterResource(
        nodes=nodes,
        pods=cluster.pods,
        daemonsets=cluster.daemonsets,
        others=cluster.others,
    )
    reset_name_rng()
    return simulate(sub, apps, weights=sc.weights)


def overflow_fixture(n_nodes=6):
    """More pods than the small node prefixes hold: lanes with few nodes
    leave pods unscheduled (exercising reason strings), large lanes fit."""
    cluster = ClusterResource(
        nodes=[make_node(f"node-{i}", cpu="8", memory="16Gi")
               for i in range(n_nodes)]
    )
    apps = [
        AppResource(
            name="app",
            objects=[
                make_deployment("web", replicas=20, cpu="1", memory="1Gi"),
                make_deployment("db", replicas=6, cpu="2", memory="2Gi"),
            ],
        )
    ]
    return cluster, apps


def assert_lanes_match_serial(cluster, apps, scenarios):
    n_nodes = len(cluster.nodes)
    reset_name_rng()
    batched = simulate_batch(cluster, apps, scenarios)
    assert len(batched) == len(scenarios)
    for sc, got in zip(scenarios, batched):
        want = serial_oracle(cluster, apps, sc, n_nodes)
        assert digest(got) == digest(want), f"lane {sc.name} diverged"
    return batched


def test_node_count_lanes_match_serial_including_reasons():
    cluster, apps = overflow_fixture()
    scenarios = [
        Scenario(name=f"+{k}", node_count=k) for k in range(1, 7)
    ]
    results = assert_lanes_match_serial(cluster, apps, scenarios)
    # the grid is only meaningful if it spans both outcomes
    assert results[0].unscheduled, "smallest lane should overflow"
    assert not results[-1].unscheduled, "largest lane should fit"
    assert "nodes are available" in results[0].unscheduled[0].reason


def test_node_valid_mask_lanes_match_serial():
    cluster, apps = overflow_fixture()
    scenarios = [
        Scenario(name="evens", node_valid=[i % 2 == 0 for i in range(6)]),
        Scenario(name="no-mid", node_valid=[True, True, False, False, True, True]),
        Scenario(name="all", node_valid=[True] * 6),
    ]
    assert_lanes_match_serial(cluster, apps, scenarios)


def test_per_scenario_weights_match_serial_and_differ():
    cluster, apps = overflow_fixture()
    spread = {"least_allocated": 100}
    # uniform per-node scores (no affinity terms in play) => every node
    # ties => argmax packs the lowest index: a first-fit counter-policy
    pack = {"node_affinity": 1}
    scenarios = [
        Scenario(name="default"),
        Scenario(name="spread", weights=spread),
        Scenario(name="pack", weights=pack),
    ]
    results = assert_lanes_match_serial(cluster, apps, scenarios)
    # distinct policies must actually produce distinct placements somewhere,
    # otherwise the weight axis silently stopped reaching the kernel
    digests = {digest(r) for r in results}
    assert len(digests) >= 2


def test_preemption_scenarios_fall_back_but_still_match_serial():
    cluster = ClusterResource(
        nodes=[make_node(f"node-{i}", cpu="4", memory="8Gi")
               for i in range(4)]
    )
    apps = [
        AppResource(
            name="tiers",
            objects=[
                make_deployment("low", replicas=14, cpu="1", memory="512Mi"),
                make_deployment(
                    "high", replicas=4, cpu="2", memory="1Gi",
                    with_priority=100,
                ),
            ],
        )
    ]
    scenarios = [Scenario(name=f"+{k}", node_count=k) for k in (2, 3, 4)]
    results = assert_lanes_match_serial(cluster, apps, scenarios)
    # priority>0 pods force the per-scenario serial fallback; the point of
    # the fixture is that preemption really fires and still matches
    assert any(r.preempted for r in results)


def test_mixed_axes_single_batch():
    cluster, apps = overflow_fixture()
    scenarios = [
        Scenario(name="small", node_count=2),
        Scenario(name="masked", node_valid=[False, True] * 3,
                 weights={"least_allocated": 100}),
        Scenario(name="full"),
    ]
    assert_lanes_match_serial(cluster, apps, scenarios)


def test_batched_capacity_sweep_call_budget():
    """Acceptance: serial bisection >= 8 probes, batched sweep <= 3 device
    calls, identical nodes_added — on a fixture whose demand/supply estimate
    is useless (hostname anti-affinity: ~1 node estimated, ~replicas
    needed)."""
    from open_simulator_tpu.engine.capacity import plan_capacity
    from open_simulator_tpu.ops.fast import (
        reset_scenario_programs,
        scenario_programs,
    )

    def fixture():
        cluster = ClusterResource(
            nodes=[make_node(f"base-{i}", cpu="32", memory="64Gi")
                   for i in range(2)]
        )
        apps = [
            AppResource(
                name="app",
                objects=[
                    make_deployment(
                        "lonely", replicas=40, cpu="500m", memory="1Gi",
                        with_affinity=HOSTNAME_ANTI,
                    )
                ],
            )
        ]
        return cluster, apps, make_node("clone", cpu="32", memory="64Gi")

    reset_name_rng()
    cluster, apps, template = fixture()
    serial = plan_capacity(cluster, apps, template, sweep_mode="serial")
    assert serial is not None
    assert serial.attempts >= 8, "fixture must force a long serial search"
    assert serial.batched_calls == 0

    reset_scenario_programs()
    reset_name_rng()
    cluster, apps, template = fixture()
    batched = plan_capacity(cluster, apps, template, sweep_mode="batched")
    assert batched is not None
    assert batched.nodes_added == serial.nodes_added
    assert 0 < batched.batched_calls <= 3
    # lane shaping: at most {ladder pad, refine pad} per program key
    for key, pads in scenario_programs().items():
        assert len(pads) <= 2, f"scenario paddings exploded for {key}: {pads}"


def test_scenario_validation():
    with pytest.raises(ValueError):
        Scenario(name="both", node_count=1, node_valid=[True]).keep_mask(1)
    with pytest.raises(ValueError):
        Scenario(name="oob", node_count=9).keep_mask(4)
    assert Scenario(name="all").keep_mask(3) is None
