"""Bench harness contract tests: canary segment routing + chunk knob.

The driver runs bench.py unattended at round end; these pin the pieces a
wedged TPU tunnel leans on — the canary segment must route to the headline
runner (so its deadline entry is honored) and a bad OSIM_HEADLINE_CHUNK
must fail fast with a clear message instead of hanging the chunking loop.
"""

import json
import os
import subprocess
import sys

import bench


def test_canary_segment_routes_to_headline(monkeypatch, capsys):
    # _segment_main enables the persistent compilation cache; keep this
    # test from flipping that global on for the rest of the suite
    monkeypatch.setenv("OSIM_COMPILE_CACHE", "")
    seen = {}

    def fake_headline(pods, nodes):
        seen["sizes"] = (pods, nodes)
        return {"ok": True}

    monkeypatch.setattr(bench, "_run_headline", fake_headline)
    rc = bench._segment_main("canary", 2_000, 200)
    assert rc == 0
    assert seen["sizes"] == (2_000, 200)
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out) == {"ok": True}


def test_canary_has_tighter_deadline_than_headline():
    assert bench.SEGMENT_TIMEOUT_S["canary"] < bench.SEGMENT_TIMEOUT_S["headline"]


def test_bad_chunk_fails_fast_not_hangs():
    """chunk<=0 would spin the fast-path chunk loop forever; it must exit
    immediately with the knob's name in the message (both malformed and
    non-positive values)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for bad in ("0", "4k"):
        env["OSIM_HEADLINE_CHUNK"] = bad
        r = subprocess.run(
            [sys.executable, bench.__file__, "--quick", "--configs", "none"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode != 0
        assert "OSIM_HEADLINE_CHUNK" in (r.stderr + r.stdout)
