"""Bench harness contract tests: canary segment routing + chunk knob.

The driver runs bench.py unattended at round end; these pin the pieces a
wedged TPU tunnel leans on — the canary segment must route to the headline
runner (so its deadline entry is honored) and a bad OSIM_HEADLINE_CHUNK
must fail fast with a clear message instead of hanging the chunking loop.
"""

import json
import os
import subprocess
import sys

import bench


def test_canary_segment_routes_to_headline(monkeypatch, capsys):
    # _segment_main enables the persistent compilation cache; keep this
    # test from flipping that global on for the rest of the suite
    monkeypatch.setenv("OSIM_COMPILE_CACHE", "")
    seen = {}

    def fake_headline(pods, nodes):
        seen["sizes"] = (pods, nodes)
        return {"ok": True}

    monkeypatch.setattr(bench, "_run_headline", fake_headline)
    rc = bench._segment_main("canary", 2_000, 200)
    assert rc == 0
    assert seen["sizes"] == (2_000, 200)
    out = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(out)
    assert parsed["ok"] is True
    # every segment's JSON carries its process's metrics snapshot
    assert isinstance(parsed["metrics"], dict)


def test_canary_has_tighter_deadline_than_headline():
    assert bench.SEGMENT_TIMEOUT_S["canary"] < bench.SEGMENT_TIMEOUT_S["headline"]


def _drive_main(monkeypatch, capsys, segment_results, argv=None):
    """Run bench.main() with canned per-segment results; return (calls, out).

    segment_results: {segment_name: dict} — what _run_segment returns.
    Each recorded call is (name, pods, nodes, platform).
    """
    calls = []

    def fake_run_segment(name, pods, nodes, platform):
        calls.append((name, pods, nodes, platform))
        return dict(segment_results[name])

    monkeypatch.setattr(bench, "_run_segment", fake_run_segment)
    monkeypatch.setattr(
        bench, "_select_backend",
        lambda *a, **k: {"requested_platform": "axon", "backend_probe": "tpu 1"},
    )
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(
        sys, "argv", argv or ["bench.py", "--configs", "none"]
    )
    rc = bench.main()
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    return calls, out


def test_mid_headline_banks_tpu_number_before_full(monkeypatch, capsys):
    """A TPU-passing canary inserts the 20k mid headline before the 100k."""
    calls, out = _drive_main(
        monkeypatch, capsys,
        {
            "canary": {"value": 1.0, "device": "TPU v5 lite0"},
            "headline_mid": {"value": 2.0, "device": "TPU v5 lite0"},
            "headline": {"value": 3.0, "device": "TPU v5 lite0"},
        },
    )
    assert [c[0] for c in calls] == ["canary", "headline_mid", "headline"]
    assert calls[1][1:] == (20_000, 2_000, "axon")
    assert calls[2][3] == "axon"  # full headline stayed on the device
    assert out["headline_mid"]["value"] == 2.0
    assert "fallback" not in out


def test_mid_headline_wedge_flips_full_to_cpu(monkeypatch, capsys):
    """If the mid headline wedges, the full headline runs on CPU and the
    canary evidence survives in the output."""
    calls, out = _drive_main(
        monkeypatch, capsys,
        {
            "canary": {"value": 1.0, "device": "TPU v5 lite0"},
            "headline_mid": {"error": "timeout after 600s (device hang?)"},
            "headline": {"value": 3.0, "device": "TFRT_CPU_0"},
        },
    )
    assert [c[0] for c in calls] == ["canary", "headline_mid", "headline"]
    assert calls[2][3] == "cpu"
    assert out["fallback"] == "cpu"
    assert "headline_mid" in out["fallback_reason"]
    assert out["canary"]["device"] == "TPU v5 lite0"


def test_mid_skipped_when_headline_not_bigger(monkeypatch, capsys):
    """--pods at or below the mid size must not run an oversized mid stage
    (whose failure would wrongly force CPU for a feasible small headline)."""
    calls, out = _drive_main(
        monkeypatch, capsys,
        {
            "canary": {"value": 1.0, "device": "TPU v5 lite0"},
            "headline": {"value": 3.0, "device": "TPU v5 lite0"},
        },
        argv=["bench.py", "--configs", "none", "--pods", "5000",
              "--nodes", "500"],
    )
    assert [c[0] for c in calls] == ["canary", "headline"]
    assert "fallback" not in out


def test_canary_wedge_skips_mid_and_flips_to_cpu(monkeypatch, capsys):
    calls, out = _drive_main(
        monkeypatch, capsys,
        {
            "canary": {"error": "timeout after 300s (device hang?)"},
            "headline": {"value": 3.0, "device": "TFRT_CPU_0"},
        },
    )
    assert [c[0] for c in calls] == ["canary", "headline"]
    assert calls[1][3] == "cpu"
    assert out["fallback"] == "cpu"
    assert "canary" in out["fallback_reason"]


def test_bad_chunk_fails_fast_not_hangs():
    """chunk<=0 would spin the fast-path chunk loop forever; it must exit
    immediately with the knob's name in the message (both malformed and
    non-positive values)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for bad in ("0", "4k"):
        env["OSIM_HEADLINE_CHUNK"] = bad
        r = subprocess.run(
            [sys.executable, bench.__file__, "--quick", "--configs", "none"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode != 0
        assert "OSIM_HEADLINE_CHUNK" in (r.stderr + r.stdout)
