"""Apiserver-grade validation tests (parity: pkg/utils/utils.go:495-508 via
vendored pkg/apis/core/validation) and the MaxVG capacity gate
(apply.go:689-775)."""

import pytest

from open_simulator_tpu.core.objects import Node, Pod
from open_simulator_tpu.core.validation import (
    ValidationError,
    check_nodes,
    check_pods,
    validate_node,
    validate_pod,
)
from open_simulator_tpu.engine.capacity import satisfy_resource_setting
from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    SimulateResult,
    simulate,
)


def mkpod(name="p", ns="default", containers=None, **spec_extra):
    spec = {
        "containers": containers
        if containers is not None
        else [{"name": "c", "image": "img",
               "resources": {"requests": {"cpu": "1"}}}],
    }
    spec.update(spec_extra)
    return Pod.from_dict(
        {"metadata": {"name": name, "namespace": ns}, "spec": spec}
    )


def test_valid_pod_passes():
    assert validate_pod(mkpod()) == []


def test_bad_name_rejected():
    errs = validate_pod(mkpod(name="Bad_Name!"))
    assert any("metadata.name" in e and "RFC 1123" in e for e in errs)


def test_missing_name_rejected():
    errs = validate_pod(mkpod(name=""))
    assert any("metadata.name: Required value" in e for e in errs)


def test_bad_namespace_rejected():
    errs = validate_pod(mkpod(ns="Not.A.Label"))
    assert any("metadata.namespace" in e for e in errs)


def test_no_containers_rejected():
    errs = validate_pod(mkpod(containers=[]))
    assert any("spec.containers: Required value" in e for e in errs)


def test_missing_image_rejected():
    errs = validate_pod(mkpod(containers=[{"name": "c"}]))
    assert any("spec.containers[0].image: Required value" in e for e in errs)


def test_duplicate_container_names_rejected():
    errs = validate_pod(
        mkpod(containers=[{"name": "c", "image": "i"}, {"name": "c", "image": "i"}])
    )
    assert any("Duplicate value" in e for e in errs)


def test_bad_restart_policy_rejected():
    errs = validate_pod(mkpod(restartPolicy="WhenIFeelLikeIt"))
    assert any("spec.restartPolicy: Unsupported value" in e for e in errs)


def test_request_above_limit_rejected():
    errs = validate_pod(
        mkpod(
            containers=[
                {
                    "name": "c",
                    "image": "i",
                    "resources": {
                        "requests": {"cpu": "2"},
                        "limits": {"cpu": "1"},
                    },
                }
            ]
        )
    )
    assert any("must be less than or equal to cpu limit" in e for e in errs)


def test_bad_label_key_rejected():
    p = mkpod()
    p.meta.labels["-bad-"] = "x"
    errs = validate_pod(p)
    assert any("metadata.labels" in e for e in errs)


def test_node_validation():
    good = Node.from_dict(
        {"metadata": {"name": "n-1"},
         "status": {"allocatable": {"cpu": "4"}}}
    )
    assert validate_node(good) == []
    bad = Node.from_dict({"metadata": {"name": "N_1!"}})
    assert any("metadata.name" in e for e in validate_node(bad))
    check_nodes([good])
    with pytest.raises(ValidationError):
        check_nodes([bad])


def test_simulate_rejects_invalid_cluster_pod():
    node = Node.from_dict(
        {"metadata": {"name": "n0"},
         "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}}}
    )
    bad = mkpod(containers=[{"name": "c"}])  # no image
    with pytest.raises(ValidationError, match="image: Required value"):
        simulate(ClusterResource(nodes=[node], pods=[bad]), [])


def test_simulate_rejects_invalid_app_pod():
    node = Node.from_dict(
        {"metadata": {"name": "n0"},
         "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}}}
    )
    app = AppResource(
        name="bad",
        objects=[
            {
                "kind": "Deployment",
                "metadata": {"name": "Bad_Caps", "namespace": "default"},
                "spec": {
                    "replicas": 1,
                    "template": {
                        "spec": {"containers": [{"name": "c", "image": "i"}]}
                    },
                },
            }
        ],
    )
    with pytest.raises(ValidationError, match="app bad"):
        simulate(ClusterResource(nodes=[node]), [app])


# ---------------------------------------------------------------------------
# MaxVG gate
# ---------------------------------------------------------------------------

def _vg_result(requested_pct: float) -> SimulateResult:
    from open_simulator_tpu.core.objects import LocalVG, NodeLocalStorage

    res = SimulateResult()
    cap = 100 * (1 << 30)
    res.storage["n0"] = NodeLocalStorage(
        vgs=[LocalVG(name="pool", capacity=cap,
                     requested=int(cap * requested_pct / 100.0))],
        devices=[],
    )
    return res


def test_max_vg_gate(monkeypatch):
    monkeypatch.setenv("MaxVG", "50")
    assert satisfy_resource_setting(_vg_result(40.0))
    assert satisfy_resource_setting(_vg_result(50.0))  # int(50) <= 50
    assert not satisfy_resource_setting(_vg_result(61.0))
    monkeypatch.delenv("MaxVG")
    assert satisfy_resource_setting(_vg_result(99.0))
