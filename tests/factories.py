"""Functional-option fixture factories (parity: the reference's `pkg/test`
builders — MakeFakeNode `node.go:15-40`, MakeFakePod `pod.go:13-47`, and the
per-workload-kind MakeFake* with With* options).

Usage:
    node = make_node("n1", cpu="8", with_labels={"zone": "z1"},
                     with_taints=[taint("dedicated", "batch")])
    pod = make_pod("p1", cpu="500m", with_node_selector={"zone": "z1"})
    deploy = make_deployment("web", replicas=3, cpu="1",
                             with_tolerations=[toleration("dedicated")])
"""

from __future__ import annotations

from typing import Dict, List, Optional

from open_simulator_tpu.core.objects import Node, Pod


def taint(key: str, value: str = "", effect: str = "NoSchedule") -> dict:
    return {"key": key, "value": value, "effect": effect}


def toleration(
    key: str, value: str = "", operator: str = "", effect: str = ""
) -> dict:
    t: dict = {"key": key}
    if operator:
        t["operator"] = operator
    if value:
        t["value"] = value
    if effect:
        t["effect"] = effect
    return t


def spread_constraint(
    topology_key: str,
    max_skew: int = 1,
    when_unsatisfiable: str = "DoNotSchedule",
    match_labels: Optional[Dict[str, str]] = None,
) -> dict:
    return {
        "maxSkew": max_skew,
        "topologyKey": topology_key,
        "whenUnsatisfiable": when_unsatisfiable,
        "labelSelector": {"matchLabels": match_labels or {}},
    }


def make_node(
    name: str,
    cpu: str = "4",
    memory: str = "8Gi",
    pods: str = "110",
    with_labels: Optional[Dict[str, str]] = None,
    with_taints: Optional[List[dict]] = None,
    with_annotations: Optional[Dict[str, str]] = None,
    with_capacity: Optional[Dict[str, str]] = None,
) -> Node:
    """MakeFakeNode parity: 110-pod capacity default, hostname label set."""
    res = {"cpu": cpu, "memory": memory, "pods": pods, **(with_capacity or {})}
    return Node.from_dict(
        {
            "metadata": {
                "name": name,
                "labels": {
                    "kubernetes.io/hostname": name, **(with_labels or {})
                },
                "annotations": with_annotations or {},
            },
            "spec": {"taints": with_taints or []},
            "status": {"allocatable": dict(res), "capacity": dict(res)},
        }
    )


def _pod_spec(
    cpu: str,
    memory: str,
    with_node_selector=None,
    with_tolerations=None,
    with_affinity=None,
    with_spread=None,
    with_host_ports=None,
    with_priority=None,
    with_scheduler=None,
    with_node_name=None,
) -> dict:
    container: dict = {
        "name": "c",
        "image": "img",
        "resources": {"requests": {"cpu": cpu, "memory": memory}},
    }
    if with_host_ports:
        container["ports"] = [
            {"containerPort": p, "hostPort": p} for p in with_host_ports
        ]
    spec: dict = {"containers": [container]}
    if with_node_selector:
        spec["nodeSelector"] = dict(with_node_selector)
    if with_tolerations:
        spec["tolerations"] = list(with_tolerations)
    if with_affinity:
        spec["affinity"] = with_affinity
    if with_spread:
        spec["topologySpreadConstraints"] = list(with_spread)
    if with_priority is not None:
        spec["priority"] = with_priority
    if with_scheduler:
        spec["schedulerName"] = with_scheduler
    if with_node_name:
        spec["nodeName"] = with_node_name
    return spec


def make_pod(
    name: str,
    namespace: str = "default",
    cpu: str = "100m",
    memory: str = "128Mi",
    with_labels: Optional[Dict[str, str]] = None,
    with_annotations: Optional[Dict[str, str]] = None,
    **spec_options,
) -> Pod:
    """MakeFakePod parity; spec options mirror the With* functional options."""
    return Pod.from_dict(
        {
            "metadata": {
                "name": name,
                "namespace": namespace,
                "labels": with_labels or {},
                "annotations": with_annotations or {},
            },
            "spec": _pod_spec(cpu, memory, **spec_options),
        }
    )


def _workload(
    kind: str,
    name: str,
    namespace: str,
    replicas: int,
    cpu: str,
    memory: str,
    with_labels: Optional[Dict[str, str]] = None,
    **spec_options,
) -> dict:
    labels = {"app": name, **(with_labels or {})}
    return {
        "kind": kind,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": labels},
                "spec": _pod_spec(cpu, memory, **spec_options),
            },
        },
    }


def make_deployment(name, replicas=1, namespace="default", cpu="100m",
                    memory="128Mi", **opts) -> dict:
    return _workload("Deployment", name, namespace, replicas, cpu, memory, **opts)


def make_replicaset(name, replicas=1, namespace="default", cpu="100m",
                    memory="128Mi", **opts) -> dict:
    return _workload("ReplicaSet", name, namespace, replicas, cpu, memory, **opts)


def make_statefulset(name, replicas=1, namespace="default", cpu="100m",
                     memory="128Mi", **opts) -> dict:
    return _workload("StatefulSet", name, namespace, replicas, cpu, memory, **opts)


def make_daemonset(name, namespace="default", cpu="100m", memory="128Mi",
                   **opts) -> dict:
    d = _workload("DaemonSet", name, namespace, 0, cpu, memory, **opts)
    del d["spec"]["replicas"]
    return d


def make_job(name, completions=1, parallelism=1, namespace="default",
             cpu="100m", memory="128Mi", **opts) -> dict:
    d = _workload("Job", name, namespace, 0, cpu, memory, **opts)
    del d["spec"]["replicas"]
    d["spec"]["completions"] = completions
    d["spec"]["parallelism"] = parallelism
    return d


def make_cronjob(name, namespace="default", cpu="100m", memory="128Mi",
                 **opts) -> dict:
    inner = make_job(name, namespace=namespace, cpu=cpu, memory=memory, **opts)
    return {
        "kind": "CronJob",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "schedule": "* * * * *",
            "jobTemplate": {"spec": inner["spec"]},
        },
    }
