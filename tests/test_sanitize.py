"""OSIM_SANITIZE=1 checkify mode: off-path passthrough, violation
raising + metric, entry coverage, and plain-vs-sanitized result parity."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from open_simulator_tpu.ops.sanitize import (
    SANITIZE_ENV,
    SanitizerViolation,
    sanitizable,
    sanitize_enabled,
    sanitized_entries,
)
from open_simulator_tpu.utils import metrics


@sanitizable("test:log_entry")
@jax.jit
def _log_entry(x):
    return jnp.log(x)


@sanitizable("test:static_entry", static_argnames=("n",))
@functools.partial(jax.jit, static_argnames=("n",))
def _pad_entry(x, n):
    return jnp.pad(x, (0, n))


def test_env_parsing(monkeypatch):
    for off in ("", "0", "false", "no", " NO "):
        monkeypatch.setenv(SANITIZE_ENV, off)
        assert not sanitize_enabled()
    for on in ("1", "true", "yes", "on"):
        monkeypatch.setenv(SANITIZE_ENV, on)
        assert sanitize_enabled()
    monkeypatch.delenv(SANITIZE_ENV)
    assert not sanitize_enabled()


def test_disabled_passthrough_keeps_nan_silent(monkeypatch):
    monkeypatch.delenv(SANITIZE_ENV, raising=False)
    out = _log_entry(jnp.float32(-1.0))
    assert np.isnan(out)  # plain jit semantics, no raise


def test_violation_raises_and_increments_metric(monkeypatch):
    monkeypatch.setenv(SANITIZE_ENV, "1")
    before = metrics.SANITIZER_VIOLATIONS.value(entry="test:log_entry")
    with pytest.raises(SanitizerViolation) as ei:
        _log_entry(jnp.float32(-1.0))
    assert ei.value.entry == "test:log_entry"
    assert "nan" in ei.value.check_message.lower()
    after = metrics.SANITIZER_VIOLATIONS.value(entry="test:log_entry")
    assert after == before + 1


def test_clean_call_returns_plain_value(monkeypatch):
    monkeypatch.setenv(SANITIZE_ENV, "1")
    out = _log_entry(jnp.float32(1.0))
    assert float(out) == 0.0


def test_positional_static_args_survive_sanitizing(monkeypatch):
    """Regression: the checkified re-jit must bind static_argnames for
    positionally-passed args (grouped.py calls _group_jit positionally)."""
    monkeypatch.setenv(SANITIZE_ENV, "1")
    out = _pad_entry(jnp.ones(3, jnp.float32), 2)
    assert out.shape == (5,)


def test_nested_trace_falls_through(monkeypatch):
    """Inside someone else's jit trace the outer entry owns the checkify
    scope — the wrapper must not try to re-jit concrete-side."""
    monkeypatch.setenv(SANITIZE_ENV, "1")

    @jax.jit
    def outer(x):
        return _log_entry(x)

    assert np.isnan(outer(jnp.float32(-1.0)))  # no raise


def test_all_audited_entries_are_sanitizable():
    from open_simulator_tpu.analysis.jaxpr_audit import (
        AUDIT_TARGETS,
        REQUIRED_COVERAGE,
    )
    from open_simulator_tpu.ops import delta, fast, grouped, kernels

    entries = sanitized_entries(delta, fast, grouped, kernels)
    assert set(REQUIRED_COVERAGE) <= set(entries)
    expected = sum(len(attrs) for attrs in AUDIT_TARGETS.values())
    assert len([e for e in entries if not e.startswith("test:")]) == expected


def test_trace_delegation_for_jaxpr_audit():
    """The jaxpr auditor calls .trace() on captured entries; the wrapper
    must delegate to the underlying jit Function."""
    traced = _log_entry.trace(jnp.zeros(4, jnp.float32))
    assert len(traced.jaxpr.jaxpr.invars) == 1


def test_simulation_parity_plain_vs_sanitized(monkeypatch):
    """A real end-to-end sweep places identically with the sanitizer armed
    (observational mode): same scheduled/unscheduled counts."""
    from bench import _mk_deploy, _mk_node, _simulate_config

    nodes = [_mk_node(f"n-{i}", "16", "32Gi") for i in range(8)]
    deploys = [_mk_deploy("web", 24, "500m", "1Gi")]
    monkeypatch.delenv(SANITIZE_ENV, raising=False)
    _, plain_placed, plain_unsched = _simulate_config(nodes, deploys)
    monkeypatch.setenv(SANITIZE_ENV, "1")
    _, san_placed, san_unsched = _simulate_config(nodes, deploys)
    assert (san_placed, san_unsched) == (plain_placed, plain_unsched)
    assert plain_placed == 24
