"""Interleave model checker: clean real protocols, seeded-bug catches,
determinism, DPOR cross-check, schedule replay, CLI and SARIF wiring.

The checker runs the REAL admission/loop/session/journal/breaker code
under cooperative shim primitives, so these tests double as concurrency
regression tests for those modules: a future protocol bug that widens a
critical section or drops a notify shows up here as a violation with a
minimized schedule.
"""

import json

import pytest

from open_simulator_tpu.analysis import interleave
from open_simulator_tpu.analysis import sarif as sarif_mod
from tests.fixture_bad_protocols import BAD_PROTOCOLS


def _report_bytes(report):
    return json.dumps(report.to_dict(), indent=2, sort_keys=True).encode()


# ---------------------------------------------------------------------------
# the real protocols are clean (exhaustive within quick bounds)
# ---------------------------------------------------------------------------

def test_real_protocols_clean_under_quick_bounds():
    report = interleave.run_interleave(quick=True)
    assert report.ok
    assert sorted(s.name for s in report.scenarios) == sorted(
        interleave.SCENARIOS
    )
    for s in report.scenarios:
        assert s.completed, f"{s.name} exhausted its run budget"
        assert not s.violations
        assert s.runs >= 1 and s.states > s.runs


def test_fixture_catalog_matches_shipped_mutations():
    """fixture_bad_protocols.py and interleave.MUTATIONS must not drift."""
    assert {b.mutation for b in BAD_PROTOCOLS} == set(interleave.MUTATIONS)
    for b in BAD_PROTOCOLS:
        assert interleave.MUTATIONS[b.mutation][0] == b.scenario


# ---------------------------------------------------------------------------
# seeded bugs: every mutation caught, minimized, replayable
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "bad", BAD_PROTOCOLS, ids=[b.mutation for b in BAD_PROTOCOLS]
)
def test_seeded_bug_caught_minimized_and_replayable(bad):
    report = interleave.run_interleave(mutate=bad.mutation)
    assert not report.ok
    assert len(report.scenarios) == 1
    sc = report.scenarios[0]
    assert sc.name == bad.scenario
    assert sc.violations, f"{bad.mutation} was not caught"
    v = sc.violations[0]
    assert v.invariant in bad.invariants, (
        f"{bad.mutation} caught as {v.invariant!r}, expected one of "
        f"{sorted(bad.invariants)}: {v.message}"
    )
    # the minimized schedule is replayable: the same interventions under
    # --replay reproduce a violation of the same bug
    sched = interleave._schedule_dict(v, report.seed, report.mutate)
    assert sched["scenario"] == bad.scenario
    assert sched["mutate"] == bad.mutation
    assert all(
        isinstance(step, int) and isinstance(actor, int)
        for step, actor in sched["interventions"]
    )
    replay_report = interleave.run_interleave(replay=sched)
    assert not replay_report.ok
    replay_v = replay_report.scenarios[0].violations
    assert replay_v and replay_v[0].invariant in bad.invariants
    assert replay_report.replayed == {
        "scenario": bad.scenario,
        "interventions": [list(p) for p in v.interventions],
    }


def test_minimization_drops_redundant_interventions():
    """ddmin keeps only interventions the violation still needs; for the
    seeded torn checkpoint that is exactly one crash choice."""
    report = interleave.run_interleave(mutate="torn-checkpoint")
    (sc,) = report.scenarios
    v = sc.violations[0]
    assert len(v.interventions) <= 2
    assert any(actor == interleave.CRASH for _, actor in v.interventions)


# ---------------------------------------------------------------------------
# determinism: same seed => byte-identical report
# ---------------------------------------------------------------------------

def test_same_seed_byte_identical_report():
    a = interleave.run_interleave(["breaker", "journal"], seed=7, quick=True)
    b = interleave.run_interleave(["breaker", "journal"], seed=7, quick=True)
    assert _report_bytes(a) == _report_bytes(b)
    assert a.to_dict()["digest"] == b.to_dict()["digest"]


def test_same_seed_byte_identical_violation_schedule():
    a = interleave.run_interleave(mutate="double-probe", seed=3)
    b = interleave.run_interleave(mutate="double-probe", seed=3)
    assert _report_bytes(a) == _report_bytes(b)
    va = a.scenarios[0].violations[0]
    vb = b.scenarios[0].violations[0]
    assert va.interventions == vb.interventions
    assert va.trace == vb.trace


# ---------------------------------------------------------------------------
# DPOR: the reduction prunes runs but never verdicts
# ---------------------------------------------------------------------------

def test_dpor_cross_check_same_verdict_fewer_runs():
    with_dpor = interleave.run_interleave(["breaker"], quick=True)
    without = interleave.run_interleave(
        ["breaker"], quick=True, use_dpor=False
    )
    assert with_dpor.ok and without.ok
    assert with_dpor.scenarios[0].completed and without.scenarios[0].completed
    assert with_dpor.scenarios[0].runs <= without.scenarios[0].runs


def test_dpor_still_catches_seeded_bug_when_disabled():
    report = interleave.run_interleave(mutate="double-probe", use_dpor=False)
    assert not report.ok


# ---------------------------------------------------------------------------
# input validation
# ---------------------------------------------------------------------------

def test_unknown_scenario_and_mutation_raise():
    with pytest.raises(ValueError, match="unknown scenario"):
        interleave.run_interleave(["no-such-scenario"])
    with pytest.raises(ValueError, match="unknown mutation"):
        interleave.run_interleave(mutate="no-such-mutation")
    with pytest.raises(ValueError, match="unknown scenario"):
        interleave.run_interleave(
            replay={"scenario": "nope", "interventions": []}
        )


# ---------------------------------------------------------------------------
# CLI: exit codes, schedule-out, replay round trip
# ---------------------------------------------------------------------------

def test_cli_interleave_mutate_schedule_out_and_replay(tmp_path, capsys):
    from open_simulator_tpu.cli.main import main

    sched_path = tmp_path / "sched.json"
    rc = main([
        "interleave", "--mutate", "double-probe",
        "--schedule-out", str(sched_path), "--format", "json",
    ])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert not report["ok"]
    sched = json.loads(sched_path.read_text())
    assert sched["scenario"] == "breaker"
    assert sched["mutate"] == "double-probe"

    rc = main(["interleave", "--replay", str(sched_path), "--format", "json"])
    assert rc == 1
    replayed = json.loads(capsys.readouterr().out)
    assert not replayed["ok"]
    assert replayed["replayed"]["scenario"] == "breaker"


def test_cli_interleave_clean_scenario_exits_zero(capsys):
    from open_simulator_tpu.cli.main import main

    rc = main(["interleave", "breaker", "--quick", "--format", "json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] and report["bounds"]["preemptions"] == 1


# ---------------------------------------------------------------------------
# SARIF conversion (`simon check --format=sarif`)
# ---------------------------------------------------------------------------

def test_sarif_run_from_violation_report():
    report = interleave.run_interleave(mutate="double-probe")
    run = sarif_mod.interleave_run(report)
    assert run["tool"]["driver"]["name"] == "simon-interleave"
    assert run["results"], "violations must become SARIF results"
    res = run["results"][0]
    assert res["level"] == "error"
    assert res["ruleId"] in {r["id"] for r in run["tool"]["driver"]["rules"]}
    loc = res["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
    assert loc == sarif_mod.SCENARIO_SUBJECTS["breaker"]
    # the annotation carries the replayable schedule
    assert res["properties"]["interventions"]
    assert res["properties"]["scenario"] == "breaker"


def test_sarif_document_shape_and_cli_check(tmp_path, capsys):
    from open_simulator_tpu.cli.main import main

    out = tmp_path / "check.sarif"
    rc = main([
        "check", "--no-lint", "--no-races", "--no-invariants",
        "--no-preflight", "--quick", "--output", str(out),
    ])
    capsys.readouterr()
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["$schema"] == sarif_mod.SARIF_SCHEMA
    assert len(doc["runs"]) == 1
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "simon-interleave"
    assert run["results"] == []
