"""Built-in Helm chart rendering (utils/chart.py).

Parity target: pkg/chart/chart.go (ProcessChart: load, installable check,
Release context, render, NOTES.txt strip, InstallOrder sort) plus the
Go-template subset the engine implements.
"""

import os
import textwrap

import pytest
import yaml

from open_simulator_tpu.utils.chart import (
    ChartError,
    load_chart,
    process_chart,
    render_template,
)


# ---------------------------------------------------------------------------
# template engine
# ---------------------------------------------------------------------------

CTX = {
    "Values": {
        "name": "web",
        "replicas": 3,
        "enabled": True,
        "tag": "",
        "items": ["a", "b"],
        "nested": {"image": "nginx", "port": 8080},
    },
    "Release": {"Name": "rel", "Namespace": "default"},
    "Chart": {"name": "c", "version": "1.0"},
}


def test_lookup_and_literals():
    assert render_template("{{ .Values.name }}", CTX) == "web"
    assert render_template("{{ .Values.nested.image }}", CTX) == "nginx"
    assert render_template("{{ $.Release.Name }}", CTX) == "rel"
    assert render_template('{{ "lit" }}', CTX) == "lit"
    assert render_template("{{ 42 }}", CTX) == "42"
    assert render_template("{{ .Values.missing }}", CTX) == ""


def test_trim_markers():
    src = "a\n  {{- .Values.name }}\nb"
    assert render_template(src, CTX) == "aweb\nb"
    # '-}}' eats ALL following whitespace (Go text/template semantics)
    src = "a {{ .Values.name -}}\n  b"
    assert render_template(src, CTX) == "a webb"


def test_if_else_end():
    src = "{{ if .Values.enabled }}on{{ else }}off{{ end }}"
    assert render_template(src, CTX) == "on"
    src = "{{ if .Values.tag }}t{{ else }}empty{{ end }}"
    assert render_template(src, CTX) == "empty"
    src = "{{ if .Values.tag }}a{{ else if .Values.enabled }}b{{ else }}c{{ end }}"
    assert render_template(src, CTX) == "b"


def test_nested_if():
    src = (
        "{{ if .Values.enabled }}{{ if .Values.tag }}x{{ else }}y{{ end }}"
        "{{ else }}z{{ end }}"
    )
    assert render_template(src, CTX) == "y"


def test_range_and_with():
    assert render_template("{{ range .Values.items }}[{{ . }}]{{ end }}", CTX) == "[a][b]"
    assert (
        render_template(
            "{{ with .Values.nested }}{{ .image }}:{{ .port }}{{ end }}", CTX
        )
        == "nginx:8080"
    )
    assert render_template("{{ range .Values.missing }}x{{ else }}none{{ end }}", CTX) == "none"


def test_pipeline_functions():
    assert render_template('{{ .Values.tag | default "latest" }}', CTX) == "latest"
    assert render_template('{{ .Values.name | default "x" }}', CTX) == "web"
    assert render_template("{{ .Values.name | upper }}", CTX) == "WEB"
    assert render_template("{{ .Values.name | quote }}", CTX) == '"web"'
    assert render_template("{{ int .Values.replicas }}", CTX) == "3"
    assert render_template('{{ eq .Values.name "web" }}', CTX) == "true"
    assert render_template("{{ not .Values.enabled }}", CTX) == "false"


def test_unsupported_constructs_raise():
    with pytest.raises(ChartError):
        render_template('{{ include "chart.labels" . }}', CTX)
    with pytest.raises(ChartError):
        render_template("{{ template \"x\" }}", CTX)
    with pytest.raises(ChartError):
        render_template("{{ unknownfn .Values.name }}", CTX)


def test_malformed_blocks_raise_chart_error():
    with pytest.raises(ChartError):
        render_template("{{ if .Values.enabled }}no end", CTX)
    with pytest.raises(ChartError):
        render_template("{{ range .Values.items }}x", CTX)
    with pytest.raises(ChartError):
        render_template("text {{ end }} more", CTX)
    with pytest.raises(ChartError):
        render_template("{{ else }}", CTX)


def test_non_ascii_string_literals():
    assert render_template('{{ "café" }}', CTX) == "café"
    assert render_template('{{ "a\\nb" }}', CTX) == "a\nb"
    assert render_template('{{ `raw\\n` }}', CTX) == "raw\\n"


# ---------------------------------------------------------------------------
# chart loading + ProcessChart
# ---------------------------------------------------------------------------

def _write_chart(root, name="demo", values=None, templates=None, meta_extra=""):
    cdir = os.path.join(root, name)
    os.makedirs(os.path.join(cdir, "templates"), exist_ok=True)
    with open(os.path.join(cdir, "Chart.yaml"), "w") as fh:
        fh.write(f"apiVersion: v2\nname: {name}\nversion: 0.1.0\n{meta_extra}")
    with open(os.path.join(cdir, "values.yaml"), "w") as fh:
        yaml.safe_dump(values or {}, fh)
    for rel, src in (templates or {}).items():
        with open(os.path.join(cdir, "templates", rel), "w") as fh:
            fh.write(src)
    return cdir


def test_process_chart_renders_and_sorts(tmp_path):
    cdir = _write_chart(
        tmp_path,
        values={"replicas": 2, "image": "nginx"},
        templates={
            "deploy.yaml": textwrap.dedent(
                """\
                apiVersion: apps/v1
                kind: Deployment
                metadata:
                  name: {{ .Release.Name }}-web
                spec:
                  replicas: {{ .Values.replicas }}
                  template:
                    spec:
                      containers:
                      - name: c
                        image: {{ .Values.image }}
                """
            ),
            "ns.yaml": "apiVersion: v1\nkind: Namespace\nmetadata:\n  name: n\n",
            "NOTES.txt": "thanks for installing {{ .Release.Name }}",
        },
    )
    docs = process_chart(cdir, release_name="myapp")
    kinds = [d["kind"] for d in docs]
    # Namespace sorts before Deployment; NOTES.txt stripped
    assert kinds == ["Namespace", "Deployment"]
    dep = docs[1]
    # Release.Name is the APP name (chart.go overwrites Metadata.Name)
    assert dep["metadata"]["name"] == "myapp-web"
    assert dep["spec"]["replicas"] == 2
    # default: chart's own name
    assert process_chart(cdir)[1]["metadata"]["name"] == "demo-web"


def test_library_charts_rejected(tmp_path):
    cdir = _write_chart(tmp_path, name="lib", meta_extra="type: library\n")
    with pytest.raises(ChartError):
        process_chart(cdir)


def test_subchart_values_scoping(tmp_path):
    parent = _write_chart(
        tmp_path,
        name="parent",
        values={"sub": {"msg": "from-parent"}},
        templates={
            "cm.yaml": "kind: ConfigMap\nmetadata:\n  name: parent-cm\n",
        },
    )
    subdir = os.path.join(parent, "charts")
    os.makedirs(subdir)
    _write_chart(
        subdir,
        name="sub",
        values={"msg": "own-default", "keep": "kept"},
        templates={
            "cm.yaml": (
                "kind: ConfigMap\nmetadata:\n  name: sub-cm\ndata:\n"
                "  msg: {{ .Values.msg }}\n  keep: {{ .Values.keep }}\n"
            ),
        },
    )
    objs = process_chart(parent)
    sub_cm = next(o for o in objs if o["metadata"]["name"] == "sub-cm")
    assert sub_cm["data"]["msg"] == "from-parent"   # parent override wins
    assert sub_cm["data"]["keep"] == "kept"         # own defaults survive


def test_tgz_chart(tmp_path):
    import tarfile

    cdir = _write_chart(
        tmp_path,
        templates={"cm.yaml": "kind: ConfigMap\nmetadata:\n  name: x\n"},
    )
    tgz = os.path.join(tmp_path, "demo.tgz")
    with tarfile.open(tgz, "w:gz") as tf:
        tf.add(cdir, arcname="demo")
    import glob
    import tempfile

    pattern = os.path.join(tempfile.gettempdir(), "osim-chart-*")
    before = set(glob.glob(pattern))
    docs = process_chart(tgz)
    assert docs[0]["kind"] == "ConfigMap"
    # extraction temp dirs are cleaned up
    assert set(glob.glob(pattern)) == before


def test_tgz_symlink_escape_rejected(tmp_path):
    import io
    import tarfile

    tgz = os.path.join(tmp_path, "evil.tgz")
    with tarfile.open(tgz, "w:gz") as tf:
        link = tarfile.TarInfo("demo/sub")
        link.type = tarfile.SYMTYPE
        link.linkname = str(tmp_path / "victim")
        tf.addfile(link)
        data = b"kind: ConfigMap\n"
        f = tarfile.TarInfo("demo/sub/x.yaml")
        f.size = len(data)
        tf.addfile(f, io.BytesIO(data))
    with pytest.raises(ChartError):
        process_chart(tgz)
    assert not (tmp_path / "victim").exists()


# ---------------------------------------------------------------------------
# the reference's real chart
# ---------------------------------------------------------------------------

def test_renders_reference_yoda_chart():
    path = "/root/reference/example/application/charts/yoda"
    if not os.path.isdir(path):
        pytest.skip("reference chart unavailable")
    objs = process_chart(path, release_name="yoda")
    kinds = [o["kind"] for o in objs]
    assert kinds.count("StorageClass") == 5
    assert "DaemonSet" in kinds and "CronJob" in kinds
    # install order: every StorageClass before every Deployment
    assert max(i for i, k in enumerate(kinds) if k == "StorageClass") < min(
        i for i, k in enumerate(kinds) if k == "Deployment"
    )
    joined = yaml.safe_dump_all(objs)
    assert "{{" not in joined
    sc_names = {o["metadata"]["name"] for o in objs if o["kind"] == "StorageClass"}
    assert "yoda-lvm-default" in sc_names


# ---------------------------------------------------------------------------
# round 4: full template language — variables, define/include/template/block,
# sprig helpers — driving a `helm create`-style scaffold with _helpers.tpl
# (parity: vendor/helm.sh/helm/v3/pkg/engine as used by pkg/chart/chart.go)
# ---------------------------------------------------------------------------

def test_variables():
    assert render_template('{{ $x := "v" }}{{ $x }}', CTX) == "v"
    assert render_template('{{ $x := 1 }}{{ $x = 2 }}{{ $x }}', CTX) == "2"
    # variable declared before a block is visible inside it
    src = '{{ $n := .Values.name }}{{ if true }}{{ $n }}{{ end }}'
    assert render_template(src, CTX) == "web"
    # assignment to an undeclared variable is an error
    with pytest.raises(ChartError):
        render_template("{{ $nope = 1 }}", CTX)


def test_range_with_variables():
    src = "{{ range $i, $v := .Values.items }}{{ $i }}={{ $v }};{{ end }}"
    assert render_template(src, CTX) == "0=a;1=b;"
    # one variable binds the element; $ stays the root inside the body
    src = "{{ range $v := .Values.items }}{{ $v }}{{ $.Release.Name }} {{ end }}"
    assert render_template(src, CTX) == "arel brel "
    # dict ranges visit keys in sorted order (Go template semantics)
    ctx = dict(CTX, Values={"m": {"b": 2, "a": 1, "c": 3}})
    src = "{{ range $k, $v := .Values.m }}{{ $k }}{{ $v }}{{ end }}"
    assert render_template(src, ctx) == "a1b2c3"


def test_define_include_template_block():
    src = (
        '{{ define "t1" }}[{{ . }}]{{ end }}'
        '{{ include "t1" "x" }}{{ template "t1" "y" }}'
    )
    assert render_template(src, CTX) == "[x][y]"
    # include pipes into other functions
    src = '{{ define "up" }}{{ . }}{{ end }}{{ include "up" "ab" | upper }}'
    assert render_template(src, CTX) == "AB"
    # block defines and renders in place
    src = '{{ block "b" .Values.name }}hello {{ . }}{{ end }}'
    assert render_template(src, CTX) == "hello web"
    # $ inside a template is the dot it was invoked with
    src = '{{ define "d" }}{{ $.nested.port }}{{ end }}{{ include "d" .Values }}'
    assert render_template(src, CTX) == "8080"
    with pytest.raises(ChartError):
        render_template('{{ include "missing" . }}', CTX)
    # unbounded recursion is cut off, not a stack overflow
    with pytest.raises(ChartError):
        render_template('{{ define "r" }}{{ include "r" . }}{{ end }}{{ include "r" . }}', CTX)


def test_sprig_string_functions():
    assert render_template('{{ printf "%s-%d" "a" 3 }}', CTX) == "a-3"
    assert render_template('{{ printf "%q" "x" }}', CTX) == '"x"'
    assert render_template('{{ contains "el" "hello" }}', CTX) == "true"
    assert render_template('{{ "hello" | contains "xyz" }}', CTX) == "false"
    assert render_template('{{ "abcdef" | trunc 3 }}', CTX) == "abc"
    assert render_template('{{ "a-b-" | trimSuffix "-" }}', CTX) == "a-b"
    assert render_template('{{ "v1+2" | replace "+" "_" }}', CTX) == "v1_2"
    assert render_template('{{ hasPrefix "he" "hello" }}', CTX) == "true"
    assert render_template('{{ "a,b" | splitList "," | join ";" }}', CTX) == "a;b"
    assert render_template('{{ "ab" | repeat 3 }}', CTX) == "ababab"
    assert render_template('{{ b64enc "hi" }}', CTX) == "aGk="
    assert render_template('{{ b64dec "aGk=" }}', CTX) == "hi"
    assert render_template('{{ sha256sum "" }}', CTX).startswith("e3b0c442")


def test_sprig_logic_and_collections():
    assert render_template('{{ ternary "y" "n" true }}', CTX) == "y"
    assert render_template('{{ false | ternary "y" "n" }}', CTX) == "n"
    assert render_template('{{ required "msg" "v" }}', CTX) == "v"
    with pytest.raises(ChartError, match="need it"):
        render_template('{{ required "need it" .Values.missing }}', CTX)
    assert render_template('{{ hasKey .Values "name" }}', CTX) == "true"
    assert render_template('{{ hasKey .Values "zzz" }}', CTX) == "false"
    assert render_template('{{ toJson .Values.items }}', CTX) == '["a","b"]'
    assert render_template('{{ index .Values.items 1 }}', CTX) == "b"
    assert render_template('{{ index .Values "nested" "port" }}', CTX) == "8080"
    assert render_template('{{ list 1 2 3 | last }}', CTX) == "3"
    assert render_template('{{ dict "a" 1 "b" 2 | keys | join "," }}', CTX) == "a,b"
    assert render_template('{{ add 1 2 3 }}{{ sub 5 2 }}{{ mul 2 3 }}', CTX) == "636"
    assert render_template('{{ coalesce nil "" "x" }}', CTX) == "x"
    assert render_template('{{ kindIs "map" .Values.nested }}', CTX) == "true"
    assert render_template('{{ until 3 | join "" }}', CTX) == "012"


def test_parenthesized_pipelines_and_tpl():
    src = '{{ default (printf "%s!" .Values.name) .Values.tag }}'
    assert render_template(src, CTX) == "web!"
    src = '{{ if (and .Values.enabled (not .Values.tag)) }}y{{ end }}'
    assert render_template(src, CTX) == "y"
    src = '{{ tpl "{{ .Values.name }}" . }}'
    assert render_template(src, CTX) == "web"


def test_capabilities_method_call():
    ctx = dict(CTX)
    from open_simulator_tpu.utils.chart import _CAPABILITIES
    ctx["Capabilities"] = _CAPABILITIES
    assert render_template('{{ .Capabilities.APIVersions.Has "apps/v1" }}', ctx) == "true"
    assert render_template('{{ .Capabilities.APIVersions.Has "nope/v9" }}', ctx) == "false"
    assert render_template("{{ .Capabilities.KubeVersion.Major }}", ctx) == "1"


def test_nondeterministic_functions_rejected():
    for fn in ("randAlphaNum 8", "uuidv4", "now"):
        with pytest.raises(ChartError, match="nondeterministic|unsupported"):
            render_template("{{ %s }}" % fn, CTX)


def test_scaffold_chart_matches_golden():
    """The helm-create-style scaffold (with _helpers.tpl driving every name
    and label through define/include) renders byte-identically to the
    checked-in golden, which was verified by hand against the reference's
    Helm-engine semantics (pkg/chart/chart.go: the app name overwrites the
    chart name, then engine.Render)."""
    import json

    here = os.path.dirname(__file__)
    objs = process_chart(
        os.path.join(here, "fixtures", "scaffold-chart"), release_name="myapp"
    )
    with open(os.path.join(here, "fixtures", "scaffold-chart.golden.json")) as fh:
        golden = json.load(fh)
    assert objs == golden
    # spot-check the semantics the helpers encode
    by_kind = {o["kind"]: o for o in objs}
    # chart.go:23 parity: the app name overwrites .Chart.Name before
    # rendering, so fullname == release name ("myapp", not "myapp-scaffold")
    assert by_kind["Deployment"]["metadata"]["name"] == "myapp"
    labels = by_kind["Deployment"]["metadata"]["labels"]
    assert labels["helm.sh/chart"] == "myapp-0.1.0"
    assert labels["app.kubernetes.io/version"] == "1.16.0"
    # image tag defaults to appVersion through a pipeline default
    cont = by_kind["Deployment"]["spec"]["template"]["spec"]["containers"][0]
    assert cont["image"] == "nginx:1.16.0"
    # NOTES.txt stripped; install order SA < Secret < CM < Service < Deploy
    kinds = [o["kind"] for o in objs]
    assert kinds == ["ServiceAccount", "Secret", "ConfigMap", "Service", "Deployment"]


def test_scaffold_release_name_containment():
    # with the chart renamed to the app (chart.go:23), fullname is always
    # the release name; the container keeps .Chart.Name == app name too
    here = os.path.dirname(__file__)
    objs = process_chart(
        os.path.join(here, "fixtures", "scaffold-chart"),
        release_name="scaffold-prod",
    )
    names = {o["metadata"]["name"] for o in objs if o["kind"] == "Service"}
    assert names == {"scaffold-prod"}
    dep = next(o for o in objs if o["kind"] == "Deployment")
    assert dep["spec"]["template"]["spec"]["containers"][0]["name"] == "scaffold-prod"


def test_comment_with_apostrophe():
    # an unpaired quote inside a comment is not an open string (Go lexer
    # treats {{/* ... */}} as an unparsed unit)
    assert render_template("a{{/* don't use */}}b", CTX) == "ab"
    assert render_template("a{{- /* it's gone */ -}} b", CTX) == "ab"


def test_if_with_variable_declaration():
    src = "{{ if $x := .Values.name }}{{ $x }}!{{ end }}"
    assert render_template(src, CTX) == "web!"
    src = "{{ if $x := .Values.tag }}{{ $x }}{{ else }}none{{ end }}"
    assert render_template(src, CTX) == "none"


def test_helper_misuse_raises_chart_error():
    # helper misuse degrades to ChartError (per-app failure), never a raw
    # Python traceback that would abort the whole apply
    for src in (
        '{{ printf "%x" "abc" }}',
        "{{ div 1 0 }}",
        "{{ upper }}",
        '{{ "abcdef" | trunc "x" }}',
    ):
        with pytest.raises(ChartError):
            render_template(src, CTX)


def test_scalar_field_access_is_an_error():
    # Go templates error on field access through a scalar; an open getattr
    # would leak Python internals into manifests
    with pytest.raises(ChartError, match="cannot access field"):
        render_template("{{ .Values.name.upper }}", CTX)
    with pytest.raises(ChartError, match="cannot access field"):
        render_template("{{ .Values.name.__class__ }}", CTX)
    # navigation through a missing key still renders empty (kube charts
    # lean on this)
    assert render_template("{{ .Values.missing.deeper }}", CTX) == ""


def test_div_mod_truncate_toward_zero():
    # Go int64 semantics: -7/2 = -3, -7%2 = -1 (Python floors: -4 / 1)
    assert render_template("{{ div -7 2 }}", CTX) == "-3"
    assert render_template("{{ mod -7 2 }}", CTX) == "-1"
    assert render_template("{{ div 7 2 }}", CTX) == "3"
    assert render_template("{{ mod 7 -2 }}", CTX) == "1"


def test_merge_mutates_destination():
    # sprig merge is in-place: dest keys win, sources fill gaps, and the
    # merge is visible through the destination afterwards
    ctx = {"Values": {"a": {"x": 1, "n": {"k": "keep"}}, "b": {"y": 2, "n": {"k": "lose", "m": 3}}}}
    src = '{{ $_ := merge .Values.a .Values.b }}{{ .Values.a.y }}/{{ .Values.a.x }}/{{ .Values.a.n.k }}/{{ .Values.a.n.m }}'
    assert render_template(src, ctx) == "2/1/keep/3"


def test_files_access(tmp_path):
    """.Files parity (helm engine files.go): Get / Glob / Lines / AsConfig /
    AsSecrets over the chart's non-template files."""
    cdir = _write_chart(
        tmp_path,
        templates={
            "cm.yaml": textwrap.dedent(
                """\
                apiVersion: v1
                kind: ConfigMap
                metadata:
                  name: files-cm
                data:
                  one: {{ .Files.Get "config/one.conf" | quote }}
                  lines: {{ .Files.Lines "config/two.conf" | len }}
                  {{- range $path, $content := .Files.Glob "config/*.conf" }}
                  glob-{{ base $path }}: {{ $content | quote }}
                  {{- end }}
                """
            ),
            "cm2.yaml": textwrap.dedent(
                """\
                apiVersion: v1
                kind: ConfigMap
                metadata:
                  name: asconfig-cm
                data:
                  {{- (.Files.Glob "config/*").AsConfig | nindent 2 }}
                """
            ),
            "secret.yaml": textwrap.dedent(
                """\
                apiVersion: v1
                kind: Secret
                metadata:
                  name: files-secret
                data:
                  {{- (.Files.Glob "config/one.conf").AsSecrets | nindent 2 }}
                """
            ),
        },
    )
    os.makedirs(os.path.join(cdir, "config"))
    with open(os.path.join(cdir, "config", "one.conf"), "w") as fh:
        fh.write("a=1")            # single line: YAML-safe through `quote`
    with open(os.path.join(cdir, "config", "two.conf"), "w") as fh:
        fh.write("x=9\ny=8")       # multi line: carried via AsConfig/Lines

    objs = process_chart(cdir)
    cm = next(o for o in objs if o["metadata"]["name"] == "files-cm")
    assert cm["data"]["one"] == "a=1"
    assert cm["data"]["lines"] == 2
    assert cm["data"]["glob-one.conf"] == "a=1"
    cm2 = next(o for o in objs if o["metadata"]["name"] == "asconfig-cm")
    assert cm2["data"] == {"one.conf": "a=1", "two.conf": "x=9\ny=8"}
    sec = next(o for o in objs if o["metadata"]["name"] == "files-secret")
    import base64 as b64

    assert b64.b64decode(sec["data"]["one.conf"]).decode() == "a=1"
    # Chart.yaml / values.yaml / templates are not Files
    from open_simulator_tpu.utils.chart import load_chart

    chart = load_chart(cdir)
    assert set(chart.files) == {"config/one.conf", "config/two.conf"}


def test_files_glob_segment_semantics_and_helmignore(tmp_path):
    cdir = _write_chart(
        tmp_path,
        templates={"cm.yaml": "kind: ConfigMap\nmetadata:\n  name: x\n"},
    )
    os.makedirs(os.path.join(cdir, "config", "sub"))
    for rel, content in [
        ("config/one.conf", "1"),
        ("config/sub/deep.conf", "2"),
        ("README.md", "docs"),
        ("notes.txt", "n"),
    ]:
        with open(os.path.join(cdir, rel), "w") as fh:
            fh.write(content)
    with open(os.path.join(cdir, ".helmignore"), "w") as fh:
        fh.write("# comment\n*.md\n")

    from open_simulator_tpu.utils.chart import load_chart

    chart = load_chart(cdir)
    # .helmignore filters *.md; .helmignore itself is never a File
    assert set(chart.files) == {
        "config/one.conf", "config/sub/deep.conf", "notes.txt"
    }
    files_ctx = {"Files": None}
    from open_simulator_tpu.utils.chart import _Files

    f = _Files(chart.files)
    # '*' does not cross '/' (gobwas glob with separator); '**' does
    assert set(f.Glob("config/*.conf")._files) == {"config/one.conf"}
    assert set(f.Glob("config/**.conf")._files) == {
        "config/one.conf", "config/sub/deep.conf"
    }


def test_go_path_functions():
    assert render_template('{{ base "a/b.txt" }}', CTX) == "b.txt"
    assert render_template('{{ base "a/" }}', CTX) == "a"
    assert render_template('{{ base "" }}', CTX) == "."
    assert render_template('{{ dir "a/b.txt" }}', CTX) == "a"
    assert render_template('{{ dir "a" }}', CTX) == "."
    assert render_template('{{ ext ".bashrc" }}', CTX) == ".bashrc"
    assert render_template('{{ ext "a/b.txt" }}', CTX) == ".txt"
    assert render_template('{{ ext "a/b" }}', CTX) == ""


def test_method_pipe_and_field_access_guards():
    from open_simulator_tpu.utils.chart import _Files

    ctx = dict(CTX)
    ctx["Files"] = _Files({"f.txt": b"hi"})
    # piping into a method passes the piped value as the argument
    assert render_template('{{ "f.txt" | .Files.Get }}', ctx) == "hi"
    # a value argument to a non-function is still an error (Go semantics),
    # not silent field navigation
    with pytest.raises(ChartError):
        render_template("{{ .Values.nested .image }}", ctx)
