"""Built-in Helm chart rendering (utils/chart.py).

Parity target: pkg/chart/chart.go (ProcessChart: load, installable check,
Release context, render, NOTES.txt strip, InstallOrder sort) plus the
Go-template subset the engine implements.
"""

import os
import textwrap

import pytest
import yaml

from open_simulator_tpu.utils.chart import (
    ChartError,
    load_chart,
    process_chart,
    render_template,
)


# ---------------------------------------------------------------------------
# template engine
# ---------------------------------------------------------------------------

CTX = {
    "Values": {
        "name": "web",
        "replicas": 3,
        "enabled": True,
        "tag": "",
        "items": ["a", "b"],
        "nested": {"image": "nginx", "port": 8080},
    },
    "Release": {"Name": "rel", "Namespace": "default"},
    "Chart": {"name": "c", "version": "1.0"},
}


def test_lookup_and_literals():
    assert render_template("{{ .Values.name }}", CTX) == "web"
    assert render_template("{{ .Values.nested.image }}", CTX) == "nginx"
    assert render_template("{{ $.Release.Name }}", CTX) == "rel"
    assert render_template('{{ "lit" }}', CTX) == "lit"
    assert render_template("{{ 42 }}", CTX) == "42"
    assert render_template("{{ .Values.missing }}", CTX) == ""


def test_trim_markers():
    src = "a\n  {{- .Values.name }}\nb"
    assert render_template(src, CTX) == "aweb\nb"
    # '-}}' eats ALL following whitespace (Go text/template semantics)
    src = "a {{ .Values.name -}}\n  b"
    assert render_template(src, CTX) == "a webb"


def test_if_else_end():
    src = "{{ if .Values.enabled }}on{{ else }}off{{ end }}"
    assert render_template(src, CTX) == "on"
    src = "{{ if .Values.tag }}t{{ else }}empty{{ end }}"
    assert render_template(src, CTX) == "empty"
    src = "{{ if .Values.tag }}a{{ else if .Values.enabled }}b{{ else }}c{{ end }}"
    assert render_template(src, CTX) == "b"


def test_nested_if():
    src = (
        "{{ if .Values.enabled }}{{ if .Values.tag }}x{{ else }}y{{ end }}"
        "{{ else }}z{{ end }}"
    )
    assert render_template(src, CTX) == "y"


def test_range_and_with():
    assert render_template("{{ range .Values.items }}[{{ . }}]{{ end }}", CTX) == "[a][b]"
    assert (
        render_template(
            "{{ with .Values.nested }}{{ .image }}:{{ .port }}{{ end }}", CTX
        )
        == "nginx:8080"
    )
    assert render_template("{{ range .Values.missing }}x{{ else }}none{{ end }}", CTX) == "none"


def test_pipeline_functions():
    assert render_template('{{ .Values.tag | default "latest" }}', CTX) == "latest"
    assert render_template('{{ .Values.name | default "x" }}', CTX) == "web"
    assert render_template("{{ .Values.name | upper }}", CTX) == "WEB"
    assert render_template("{{ .Values.name | quote }}", CTX) == '"web"'
    assert render_template("{{ int .Values.replicas }}", CTX) == "3"
    assert render_template('{{ eq .Values.name "web" }}', CTX) == "true"
    assert render_template("{{ not .Values.enabled }}", CTX) == "false"


def test_unsupported_constructs_raise():
    with pytest.raises(ChartError):
        render_template('{{ include "chart.labels" . }}', CTX)
    with pytest.raises(ChartError):
        render_template("{{ template \"x\" }}", CTX)
    with pytest.raises(ChartError):
        render_template("{{ unknownfn .Values.name }}", CTX)


def test_malformed_blocks_raise_chart_error():
    with pytest.raises(ChartError):
        render_template("{{ if .Values.enabled }}no end", CTX)
    with pytest.raises(ChartError):
        render_template("{{ range .Values.items }}x", CTX)
    with pytest.raises(ChartError):
        render_template("text {{ end }} more", CTX)
    with pytest.raises(ChartError):
        render_template("{{ else }}", CTX)


def test_non_ascii_string_literals():
    assert render_template('{{ "café" }}', CTX) == "café"
    assert render_template('{{ "a\\nb" }}', CTX) == "a\nb"
    assert render_template('{{ `raw\\n` }}', CTX) == "raw\\n"


# ---------------------------------------------------------------------------
# chart loading + ProcessChart
# ---------------------------------------------------------------------------

def _write_chart(root, name="demo", values=None, templates=None, meta_extra=""):
    cdir = os.path.join(root, name)
    os.makedirs(os.path.join(cdir, "templates"), exist_ok=True)
    with open(os.path.join(cdir, "Chart.yaml"), "w") as fh:
        fh.write(f"apiVersion: v2\nname: {name}\nversion: 0.1.0\n{meta_extra}")
    with open(os.path.join(cdir, "values.yaml"), "w") as fh:
        yaml.safe_dump(values or {}, fh)
    for rel, src in (templates or {}).items():
        with open(os.path.join(cdir, "templates", rel), "w") as fh:
            fh.write(src)
    return cdir


def test_process_chart_renders_and_sorts(tmp_path):
    cdir = _write_chart(
        tmp_path,
        values={"replicas": 2, "image": "nginx"},
        templates={
            "deploy.yaml": textwrap.dedent(
                """\
                apiVersion: apps/v1
                kind: Deployment
                metadata:
                  name: {{ .Release.Name }}-web
                spec:
                  replicas: {{ .Values.replicas }}
                  template:
                    spec:
                      containers:
                      - name: c
                        image: {{ .Values.image }}
                """
            ),
            "ns.yaml": "apiVersion: v1\nkind: Namespace\nmetadata:\n  name: n\n",
            "NOTES.txt": "thanks for installing {{ .Release.Name }}",
        },
    )
    docs = process_chart(cdir, release_name="myapp")
    kinds = [d["kind"] for d in docs]
    # Namespace sorts before Deployment; NOTES.txt stripped
    assert kinds == ["Namespace", "Deployment"]
    dep = docs[1]
    # Release.Name is the APP name (chart.go overwrites Metadata.Name)
    assert dep["metadata"]["name"] == "myapp-web"
    assert dep["spec"]["replicas"] == 2
    # default: chart's own name
    assert process_chart(cdir)[1]["metadata"]["name"] == "demo-web"


def test_library_charts_rejected(tmp_path):
    cdir = _write_chart(tmp_path, name="lib", meta_extra="type: library\n")
    with pytest.raises(ChartError):
        process_chart(cdir)


def test_subchart_values_scoping(tmp_path):
    parent = _write_chart(
        tmp_path,
        name="parent",
        values={"sub": {"msg": "from-parent"}},
        templates={
            "cm.yaml": "kind: ConfigMap\nmetadata:\n  name: parent-cm\n",
        },
    )
    subdir = os.path.join(parent, "charts")
    os.makedirs(subdir)
    _write_chart(
        subdir,
        name="sub",
        values={"msg": "own-default", "keep": "kept"},
        templates={
            "cm.yaml": (
                "kind: ConfigMap\nmetadata:\n  name: sub-cm\ndata:\n"
                "  msg: {{ .Values.msg }}\n  keep: {{ .Values.keep }}\n"
            ),
        },
    )
    objs = process_chart(parent)
    sub_cm = next(o for o in objs if o["metadata"]["name"] == "sub-cm")
    assert sub_cm["data"]["msg"] == "from-parent"   # parent override wins
    assert sub_cm["data"]["keep"] == "kept"         # own defaults survive


def test_tgz_chart(tmp_path):
    import tarfile

    cdir = _write_chart(
        tmp_path,
        templates={"cm.yaml": "kind: ConfigMap\nmetadata:\n  name: x\n"},
    )
    tgz = os.path.join(tmp_path, "demo.tgz")
    with tarfile.open(tgz, "w:gz") as tf:
        tf.add(cdir, arcname="demo")
    import glob
    import tempfile

    pattern = os.path.join(tempfile.gettempdir(), "osim-chart-*")
    before = set(glob.glob(pattern))
    docs = process_chart(tgz)
    assert docs[0]["kind"] == "ConfigMap"
    # extraction temp dirs are cleaned up
    assert set(glob.glob(pattern)) == before


def test_tgz_symlink_escape_rejected(tmp_path):
    import io
    import tarfile

    tgz = os.path.join(tmp_path, "evil.tgz")
    with tarfile.open(tgz, "w:gz") as tf:
        link = tarfile.TarInfo("demo/sub")
        link.type = tarfile.SYMTYPE
        link.linkname = str(tmp_path / "victim")
        tf.addfile(link)
        data = b"kind: ConfigMap\n"
        f = tarfile.TarInfo("demo/sub/x.yaml")
        f.size = len(data)
        tf.addfile(f, io.BytesIO(data))
    with pytest.raises(ChartError):
        process_chart(tgz)
    assert not (tmp_path / "victim").exists()


# ---------------------------------------------------------------------------
# the reference's real chart
# ---------------------------------------------------------------------------

def test_renders_reference_yoda_chart():
    path = "/root/reference/example/application/charts/yoda"
    if not os.path.isdir(path):
        pytest.skip("reference chart unavailable")
    objs = process_chart(path, release_name="yoda")
    kinds = [o["kind"] for o in objs]
    assert kinds.count("StorageClass") == 5
    assert "DaemonSet" in kinds and "CronJob" in kinds
    # install order: every StorageClass before every Deployment
    assert max(i for i, k in enumerate(kinds) if k == "StorageClass") < min(
        i for i, k in enumerate(kinds) if k == "Deployment"
    )
    joined = yaml.safe_dump_all(objs)
    assert "{{" not in joined
    sc_names = {o["metadata"]["name"] for o in objs if o["kind"] == "StorageClass"}
    assert "yoda-lvm-default" in sc_names
