"""Chunked commit checkpoints (durable/checkpoint.py + ops/fast.py).

The contract under test, end to end on a small plan (N=8, 24 pods,
3 live scenarios):

  - chunked dispatch (OSIM_COMMIT_CHUNK) is byte-identical to the
    monolithic scan — carry and every output, across seeds and for
    non-divisor chunk sizes;
  - a plan killed mid-chunk resumes byte-identically from its journal +
    newest verified snapshot, including onto a SMALLER mesh (4-dev ->
    2-dev -> single-device elastic resume);
  - a torn or content-corrupted snapshot is detected by its embedded
    digest and skipped in favor of the previous one (or a from-scratch
    replay), never trusted;
  - a re-executed chunk whose digest contradicts the journaled
    `plan_chunk` record refuses to continue (CheckpointError);
  - `device_lost` faults roll back to the last good carry and replay in
    place (degraded, not failed), with a flight-recorder artifact naming
    the last good chunk and carry digest.

Everything here runs on the conftest's 8 virtual CPU devices. The chunk
size is 4 everywhere (one compiled program per (N, C) pair, shared
across tests); the true-SIGKILL subprocess test is `slow`.
"""

import glob
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from open_simulator_tpu.durable import RunJournal, replay
from open_simulator_tpu.durable.checkpoint import (
    OUTPUT_NAMES,
    CheckpointError,
    PlanCheckpointer,
    checkpoint_every,
    installed,
)
from open_simulator_tpu.ops import fast
from open_simulator_tpu.ops import state as state_mod
from open_simulator_tpu.ops.kernels import Carry, weights_array
from open_simulator_tpu.parallel import mesh as pmesh
from open_simulator_tpu.resilience import faults
from open_simulator_tpu.utils import metrics

S_REAL = 3
CHUNK = 4  # 24 pods bucket to 32 -> 8 chunks; one shared program per test


@pytest.fixture(scope="module")
def plan_state():
    from bench import build_state

    ns, carry, batch = build_state(8, 24)
    s_pad = fast.scenario_bucket(S_REAL)
    weights = np.stack([np.asarray(weights_array())] * s_pad)
    return ns, carry, batch, weights, s_pad


def _valid_lanes(ns, s_pad, seed):
    """[s_pad, N] validity: lane 0 = the real cluster, lanes 1..S_REAL-1
    knock out a seeded fraction of nodes, pad lanes copy lane 0."""
    base = np.asarray(ns.valid)
    v = np.stack([base.copy() for _ in range(s_pad)])
    rng = np.random.RandomState(seed)
    for lane in range(1, S_REAL):
        v[lane] = base & ~(rng.rand(base.shape[0]) < 0.25)
    return v


def _to_host(out):
    return (fast.carry_to_host(out[0]),) + tuple(
        np.asarray(a) for a in out[1:]
    )


def _dispatch(plan_state, valid, ndev=0):
    """One schedule_scenarios_host call on a fresh stacked carry,
    optionally sharded over the first `ndev` devices."""
    ns, carry, batch, weights, s_pad = plan_state
    carry_s = state_mod.stack_carry(carry, s_pad)
    w_s = jnp.asarray(weights)
    v_s = jnp.asarray(valid)
    if ndev:
        m = pmesh.scenario_mesh(pmesh.make_mesh(jax.devices()[:ndev]))
        ns, carry_s, v_s, w_s = pmesh.shard_scenarios(m, ns, carry_s, v_s, w_s)
    return _to_host(
        fast.schedule_scenarios_host(ns, carry_s, batch, w_s, v_s, S_REAL)
    )


def _assert_identical(got, want):
    for f in Carry._fields:
        np.testing.assert_array_equal(
            got[0][f], want[0][f], err_msg=f"carry.{f}"
        )
    for k, name in enumerate(OUTPUT_NAMES):
        np.testing.assert_array_equal(got[1 + k], want[1 + k], err_msg=name)


def _mono_ref(plan_state, valid, monkeypatch):
    monkeypatch.delenv("OSIM_COMMIT_CHUNK", raising=False)
    return _dispatch(plan_state, valid)


def _device_lost_plan(chunk, times):
    faults.install_plan(
        faults.FaultPlan(
            rules=[
                faults.FaultRule(
                    target="device",
                    kind="device_lost",
                    op=f"commit-chunk:{chunk}",
                    times=times,
                )
            ]
        )
    )


def _crash_run(plan_state, valid, run_dir, ndev=0, kill_chunk=4):
    """Run chunked under a checkpointer and a 3-strike device_lost rule:
    two in-place recoveries, then the third strike aborts the plan with
    chunks 0..kill_chunk-1 journaled and a snapshot on disk."""
    journal = RunJournal.open(run_dir)
    cp = PlanCheckpointer(journal, every=2)
    _device_lost_plan(kill_chunk, times=3)
    try:
        with installed(cp):
            with pytest.raises(faults.DeviceLostError):
                _dispatch(plan_state, valid, ndev=ndev)
    finally:
        faults.uninstall_plan()
        journal.close()


def _resume_run(plan_state, valid, run_dir, ndev=0):
    journal = RunJournal.open(run_dir)
    cp = PlanCheckpointer(journal, resume=True, every=2)
    try:
        with installed(cp):
            return _dispatch(plan_state, valid, ndev=ndev)
    finally:
        journal.close()


def _snapshot_paths(run_dir):
    return sorted(glob.glob(os.path.join(run_dir, "ckpt", "plan-*.npz")))


# ---------------------------------------------------------------------------
# Byte-identity: chunked == monolithic
# ---------------------------------------------------------------------------

def test_chunked_matches_monolithic_across_seeds(plan_state, monkeypatch):
    ns, _, _, _, s_pad = plan_state
    for seed in (0, 1, 2):
        valid = _valid_lanes(ns, s_pad, seed)
        ref = _mono_ref(plan_state, valid, monkeypatch)
        monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))
        got = _dispatch(plan_state, valid)
        _assert_identical(got, ref)
        assert fast.scenario_carry_digest_host(
            got[0]
        ) == fast.scenario_carry_digest_host(ref[0])


def test_chunked_matches_monolithic_non_divisor_chunk(plan_state, monkeypatch):
    # C=5 does not divide the padded pod count: the final chunk runs with
    # trailing pad rows whose carry writes the count gate must mask exactly
    ns, _, _, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 0)
    ref = _mono_ref(plan_state, valid, monkeypatch)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", "5")
    _assert_identical(_dispatch(plan_state, valid), ref)


def test_chunk_at_least_plan_size_stays_monolithic(plan_state, monkeypatch):
    ns, _, batch, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 0)
    ref = _mono_ref(plan_state, valid, monkeypatch)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(int(batch.p)))
    before = metrics.PLAN_CHUNKS.value()
    _assert_identical(_dispatch(plan_state, valid), ref)
    assert metrics.PLAN_CHUNKS.value() == before  # single-scan path taken


def test_carry_digest_device_host_twins_agree(plan_state):
    _, carry, _, _, s_pad = plan_state
    carry_s = state_mod.stack_carry(carry, s_pad)
    dev = fast.scenario_carry_digest(carry_s)
    host = fast.scenario_carry_digest_host(fast.carry_to_host(carry_s))
    assert dev == host


# ---------------------------------------------------------------------------
# Device-loss rollback (no checkpointer: the in-memory last_good path)
# ---------------------------------------------------------------------------

def test_device_lost_recovers_in_place(plan_state, monkeypatch, tmp_path):
    ns, _, _, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 0)
    ref = _mono_ref(plan_state, valid, monkeypatch)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))
    monkeypatch.setenv("OSIM_FLIGHT_DIR", str(tmp_path))
    yes0 = metrics.DEVICE_LOST.value(handled="yes")
    _device_lost_plan(chunk=2, times=1)
    try:
        got = _dispatch(plan_state, valid)
    finally:
        faults.uninstall_plan()
    _assert_identical(got, ref)
    assert metrics.DEVICE_LOST.value(handled="yes") == yes0 + 1
    # the flight-recorder artifact names the last good chunk + carry digest
    arts = sorted(glob.glob(str(tmp_path / "flightrec-device-lost-*.json")))
    assert arts
    with open(arts[-1]) as fh:
        events = json.load(fh)["events"]
    lost = [e for e in events if e.get("kind") == "device-lost"]
    assert lost and lost[-1]["chunk"] == 2
    assert "restored_to" in lost[-1]
    int(lost[-1]["digest"], 16)  # well-formed carry digest


def test_device_lost_strikes_out_after_three(plan_state, monkeypatch):
    ns, _, _, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 0)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))
    no0 = metrics.DEVICE_LOST.value(handled="no")
    yes0 = metrics.DEVICE_LOST.value(handled="yes")
    _device_lost_plan(chunk=1, times=3)
    try:
        with pytest.raises(faults.DeviceLostError):
            _dispatch(plan_state, valid)
    finally:
        faults.uninstall_plan()
    assert metrics.DEVICE_LOST.value(handled="yes") == yes0 + 2
    assert metrics.DEVICE_LOST.value(handled="no") == no0 + 1


# ---------------------------------------------------------------------------
# Crash -> resume byte-identity (journal + snapshot)
# ---------------------------------------------------------------------------

def test_crash_then_resume_byte_identical(plan_state, monkeypatch, tmp_path):
    ns, _, batch, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 1)
    ref = _mono_ref(plan_state, valid, monkeypatch)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))
    run_dir = str(tmp_path / "run")

    _crash_run(plan_state, valid, run_dir, kill_chunk=4)
    events = replay(run_dir)
    chunks = [e for e in events if e["event"] == "plan_chunk"]
    assert [e["chunk"] for e in chunks] == [0, 1, 2, 3]
    assert _snapshot_paths(run_dir)  # at least one on-disk snapshot

    skipped0 = metrics.RESUME_CHUNKS_SKIPPED.value()
    got = _resume_run(plan_state, valid, run_dir)
    _assert_identical(got, ref)
    # the newest snapshot covers chunks 0..3 (every=2): all four skipped
    assert metrics.RESUME_CHUNKS_SKIPPED.value() == skipped0 + 4

    events = replay(run_dir)
    chunks = [e for e in events if e["event"] == "plan_chunk"]
    n_chunks = -(-int(batch.p) // CHUNK)
    # no duplicate records: the resumed run journals only the tail chunks
    assert [e["chunk"] for e in chunks] == list(range(n_chunks))
    done = [e for e in events if e["event"] == "plan_done"]
    assert len(done) == 1 and done[0]["chunks"] == n_chunks


def test_elastic_resume_on_smaller_mesh(plan_state, monkeypatch, tmp_path):
    """A plan snapshotted on a 4-device mesh resumes byte-identically on
    2 devices, and a 2-device snapshot resumes on a single device."""
    ns, _, _, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 2)
    ref = _mono_ref(plan_state, valid, monkeypatch)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))

    run_dir = str(tmp_path / "run-4dev")
    _crash_run(plan_state, valid, run_dir, ndev=4, kill_chunk=4)
    _assert_identical(_resume_run(plan_state, valid, run_dir, ndev=2), ref)

    run_dir = str(tmp_path / "run-2dev")
    _crash_run(plan_state, valid, run_dir, ndev=2, kill_chunk=4)
    _assert_identical(_resume_run(plan_state, valid, run_dir, ndev=0), ref)


# ---------------------------------------------------------------------------
# Snapshot corruption: torn files and digest mismatches are never trusted
# ---------------------------------------------------------------------------

def test_torn_snapshot_falls_back_to_previous(plan_state, monkeypatch, tmp_path):
    ns, _, _, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 1)
    ref = _mono_ref(plan_state, valid, monkeypatch)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))
    run_dir = str(tmp_path / "run")
    _crash_run(plan_state, valid, run_dir, kill_chunk=4)

    snaps = _snapshot_paths(run_dir)
    assert len(snaps) == 2  # every=2 -> snapshots after chunks 1 and 3
    with open(snaps[-1], "rb+") as fh:  # tear the newest one in half
        fh.truncate(os.path.getsize(snaps[-1]) // 2)

    skipped0 = metrics.RESUME_CHUNKS_SKIPPED.value()
    got = _resume_run(plan_state, valid, run_dir)
    _assert_identical(got, ref)
    # fell back to the chunks 0..1 snapshot: only two chunks skipped
    assert metrics.RESUME_CHUNKS_SKIPPED.value() == skipped0 + 2


def test_corrupt_snapshot_digest_detected(plan_state, monkeypatch, tmp_path):
    """A snapshot with silently flipped carry bytes is a valid .npz whose
    embedded digest no longer matches its leaves: resume must skip it."""
    ns, _, _, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 1)
    ref = _mono_ref(plan_state, valid, monkeypatch)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))
    run_dir = str(tmp_path / "run")
    _crash_run(plan_state, valid, run_dir, kill_chunk=4)

    for path in _snapshot_paths(run_dir):  # corrupt BOTH snapshots
        with np.load(path) as z:
            arrays = {k: z[k].copy() for k in z.files}
        leaf = f"carry_{Carry._fields[0]}"
        flat = arrays[leaf].reshape(-1)
        flat[0] = flat[0] + 1
        with open(path, "wb") as fh:
            np.savez(fh, **arrays)

    skipped0 = metrics.RESUME_CHUNKS_SKIPPED.value()
    got = _resume_run(plan_state, valid, run_dir)
    _assert_identical(got, ref)
    # no trustworthy snapshot: full from-scratch replay, nothing skipped,
    # with every re-executed chunk digest-checked against the journal
    assert metrics.RESUME_CHUNKS_SKIPPED.value() == skipped0
    _, _, batch, _, _ = plan_state
    chunks = [
        e["chunk"] for e in replay(run_dir) if e["event"] == "plan_chunk"
    ]
    # tail re-journaled once, no dupes
    assert chunks == list(range(-(-int(batch.p) // CHUNK)))


def test_resume_refuses_divergent_replay(plan_state, monkeypatch, tmp_path):
    """A journaled plan_chunk digest that contradicts the re-executed
    chunk is journal corruption or non-determinism: hard refusal."""
    ns, _, batch, _, s_pad = plan_state
    valid = _valid_lanes(ns, s_pad, 0)
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", str(CHUNK))
    run_dir = str(tmp_path / "run")
    journal = RunJournal.open(run_dir)
    key = f"0:{int(ns.valid.shape[0])}x{int(batch.p)}x{s_pad}c{CHUNK}"
    journal.append("plan_chunk", plan=key, chunk=0, pods=CHUNK,
                   digest="deadbeef")
    journal.close()

    journal = RunJournal.open(run_dir)
    cp = PlanCheckpointer(journal, resume=True, every=2)
    try:
        with installed(cp):
            with pytest.raises(CheckpointError, match="not .*byte-identical|refusing"):
                _dispatch(plan_state, valid)
    finally:
        journal.close()


# ---------------------------------------------------------------------------
# Knob parsing
# ---------------------------------------------------------------------------

def test_knob_parsing(monkeypatch):
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", "garbage")
    assert fast.commit_chunk_size() == 0
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", "-3")
    assert fast.commit_chunk_size() == 0
    monkeypatch.setenv("OSIM_COMMIT_CHUNK", "256")
    assert fast.commit_chunk_size() == 256
    monkeypatch.setenv("OSIM_CKPT_EVERY", "0")
    assert checkpoint_every() == 1
    monkeypatch.setenv("OSIM_CKPT_EVERY", "nope")
    assert checkpoint_every() == 4
    monkeypatch.delenv("OSIM_CKPT_EVERY")
    assert checkpoint_every() == 4


# ---------------------------------------------------------------------------
# True SIGKILL: a real sweep subprocess killed mid-chunk, resumed by the
# CLI into byte-identical placements (the crash_resume_smoke.sh scenario)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sigkill_mid_chunk_then_cli_resume(tmp_path):
    import random

    cfg = os.path.join(
        os.path.dirname(__file__), "fixtures", "sweep", "simon-config.yaml"
    )
    kill_chunk = random.Random(0xC0FFEE).randrange(1, 4)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        OSIM_COMMIT_CHUNK="8",
        OSIM_CKPT_EVERY="2",
    )
    env.pop("OSIM_FAULT_PLAN", None)

    def sweep(run_dir, fault_plan=None):
        e = dict(env)
        if fault_plan:
            e["OSIM_FAULT_PLAN"] = fault_plan
        return subprocess.run(
            [sys.executable, "-m", "open_simulator_tpu.cli.main", "sweep",
             "--capacity", "-f", cfg, "--run-dir", run_dir],
            env=e, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ).returncode

    ref_dir = str(tmp_path / "ref")
    assert sweep(ref_dir) == 0

    run_dir = str(tmp_path / "run")
    plan = (
        "rules:\n"
        "  - target: device\n"
        f"    op: \"commit-chunk:{kill_chunk}\"\n"
        "    kind: chunk_kill\n"
        "    times: 1\n"
    )
    rc = sweep(run_dir, fault_plan=plan)
    assert rc in (137, -9), f"expected SIGKILL, got rc={rc}"
    assert any(
        e["event"] == "plan_chunk" for e in replay(run_dir)
    ), "child died before journaling any chunk"

    rc = subprocess.run(
        [sys.executable, "-m", "open_simulator_tpu.cli.main", "runs",
         "resume", run_dir],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    ).returncode
    assert rc == 0

    with open(os.path.join(ref_dir, "outcome.json")) as fh:
        want = json.load(fh)["placement_digest"]
    with open(os.path.join(run_dir, "outcome.json")) as fh:
        got = json.load(fh)["placement_digest"]
    assert got == want
