"""Resident cluster state (engine/resident.py, ops/delta.py, server wiring).

The load-bearing property is *byte identity*: after every sync, the resident
planes — host mirror AND the device copies — must equal a fresh
`encode_nodes` of the same (nodes, bound pods) through the same encoder, at
the resident bucket shapes. The randomized sequence tests drive 200+ delta
syncs through every mutation class (pod bind/unbind, relabel, cordon, node
add/remove, no-op) and assert that identity after each step.

The chaos tests prove the robustness envelope: an injected torn delta or
digest mismatch produces a journaled anti-entropy repair with exact counter
accounting and a state that is byte-identical afterwards — never a wrong
answer, never an exception out of sync(). Fencing tests prove the admission
queue re-keys tickets whose generation moved before dequeue (including the
stale_generation chaos sentinel) and that epochs never collide across
resident instances (the re-serve bug class).
"""

import dataclasses

import numpy as np
import pytest

from open_simulator_tpu.core.objects import Node, Pod
from open_simulator_tpu.engine import resident as resident_mod
from open_simulator_tpu.engine.resident import ResidentCluster
from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    simulate,
)
from open_simulator_tpu.ops import delta as delta_ops
from open_simulator_tpu.ops.encode import NodeTable, encode_nodes
from open_simulator_tpu.resilience import faults
from open_simulator_tpu.server.admission import AdmissionQueue, coalesce_key
from open_simulator_tpu.utils import metrics

import jax.numpy as jnp


# ---------------------------------------------------------------------------
# fixtures / helpers
# ---------------------------------------------------------------------------


def mknode(name, cpu="8", mem="16Gi", labels=None, unschedulable=False):
    return Node.from_dict(
        {
            "metadata": {"name": name, "labels": dict(labels or {})},
            "spec": {"unschedulable": unschedulable},
            "status": {
                "allocatable": {"cpu": cpu, "memory": mem, "pods": "110"}
            },
        }
    )


def mkpod(name, node, cpu="1", mem="1Gi"):
    return Pod.from_dict(
        {
            "metadata": {"name": name, "namespace": "rt"},
            "spec": {
                "nodeName": node,
                "containers": [
                    {
                        "name": "c",
                        "image": "img",
                        "resources": {"requests": {"cpu": cpu, "memory": mem}},
                    }
                ],
            },
        }
    )


def assert_byte_identical(res: ResidentCluster):
    """The correctness contract: resident planes == fresh encode of the
    adopted (nodes, bound) through the SAME encoder at resident shapes,
    compared as raw bytes (NaN payloads and signed zeros included)."""
    fresh = encode_nodes(
        res.enc,
        res._nodes,
        existing_usage=res._usage,
        existing_gpu=res._gpu_usage,
        n_pad=res._host.n,
        min_axes=res._axes,
    )
    for f in dataclasses.fields(NodeTable):
        if f.name == "names":
            continue
        a, b = getattr(res._host, f.name), getattr(fresh, f.name)
        assert a.shape == b.shape and a.dtype == b.dtype, f.name
        assert a.tobytes() == b.tobytes(), f"host plane {f.name} diverged"
    assert res._host.names == fresh.names
    for name in resident_mod.DEVICE_PLANES:
        dv = np.asarray(res._dev[name])
        assert dv.tobytes() == getattr(fresh, name).tobytes(), (
            f"device plane {name} diverged from fresh encode"
        )


def repair_count(reason: str) -> float:
    return metrics.RESIDENT_DRIFT_REPAIRS.value(reason=reason)


def plan(op: str, kind: str, times: int = 1) -> faults.FaultPlan:
    return faults.FaultPlan.from_dict(
        {
            "rules": [
                {"target": "resident", "op": op, "kind": kind, "times": times}
            ]
        }
    )


# ---------------------------------------------------------------------------
# delta kernels (ops/delta.py)
# ---------------------------------------------------------------------------


def test_digest_fold_host_matches_device_bit_patterns():
    rng = np.random.default_rng(0)
    f = rng.standard_normal((13, 7)).astype(np.float32)
    # the digest must see raw bit patterns: NaN, -0.0, +/-inf included
    f[0, 0] = np.nan
    f[1, 1] = -0.0
    f[2, 2] = np.inf
    f[3, 3] = -np.inf
    assert int(delta_ops.digest_fold(jnp.asarray(f))) == (
        delta_ops.digest_fold_host(f)
    )
    i = rng.integers(-5, 5, (9, 4)).astype(np.int32)
    assert int(delta_ops.digest_fold(jnp.asarray(i))) == (
        delta_ops.digest_fold_host(i)
    )
    b = rng.random((17,)) < 0.5
    assert int(delta_ops.digest_fold(jnp.asarray(b))) == (
        delta_ops.digest_fold_host(b)
    )


def test_digest_distinguishes_permutation_and_zero_fill():
    a = np.arange(8, dtype=np.float32)
    perm = a[::-1].copy()
    assert delta_ops.digest_fold_host(a) != delta_ops.digest_fold_host(perm)
    assert delta_ops.digest_fold_host(a) != delta_ops.digest_fold_host(
        np.zeros_like(a)
    )


def test_apply_rows_drops_pad_slots():
    arr = jnp.asarray(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = delta_ops.pad_indices([1], 4)  # pad slots hold n=4 -> dropped
    assert idx.shape[0] == 8 and set(idx[1:]) == {4}
    rows = np.zeros((8, 3), np.float32)
    rows[0] = 99.0
    out = np.asarray(delta_ops.apply_rows(arr, jnp.asarray(idx), jnp.asarray(rows)))
    assert (out[1] == 99.0).all()
    # rows 0/2/3 untouched — a clamped pad slot would have smashed row 3
    assert out[0].tolist() == [0, 1, 2] and out[3].tolist() == [9, 10, 11]


# ---------------------------------------------------------------------------
# randomized delta sequences: byte identity after every step
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(12))
def test_random_delta_sequences_byte_identical(seed, tmp_path):
    """12 seeds x 20 steps = 240 random delta syncs, byte-compared against a
    fresh encode after every one. Mutations cover usage deltas, node-row
    deltas, node adds (in-bucket), removals (structural fallback), and
    no-ops; epochs must be monotonic and only move when state moved."""
    rng = np.random.default_rng(seed)
    nodes = [
        mknode(f"n{seed}-{i}", labels={"zone": f"az-{i % 3}"}) for i in range(8)
    ]
    pods = []
    serial = 0
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, pods)
    assert_byte_identical(res)
    repairs_before = res.repairs
    last_epoch = res.epoch
    for step in range(20):
        action = rng.choice(
            ["bind", "unbind", "relabel", "cordon", "add_node",
             "remove_node", "noop"],
            p=[0.35, 0.15, 0.15, 0.1, 0.1, 0.05, 0.1],
        )
        if action == "bind":
            serial += 1
            target = nodes[rng.integers(len(nodes))].name
            pods.append(
                mkpod(f"p{seed}-{serial}", target,
                      cpu=str(1 + int(rng.integers(3))))
            )
        elif action == "unbind" and pods:
            pods.pop(int(rng.integers(len(pods))))
        elif action == "relabel":
            i = int(rng.integers(len(nodes)))
            raw = {k: v for k, v in nodes[i].raw.items()}
            meta = dict(raw.get("metadata") or {})
            labels = dict(meta.get("labels") or {})
            labels["step"] = f"s{step}"
            meta["labels"] = labels
            raw["metadata"] = meta
            nodes[i] = Node.from_dict(raw)
        elif action == "cordon":
            i = int(rng.integers(len(nodes)))
            raw = {k: v for k, v in nodes[i].raw.items()}
            spec = dict(raw.get("spec") or {})
            spec["unschedulable"] = not spec.get("unschedulable", False)
            raw["spec"] = spec
            nodes[i] = Node.from_dict(raw)
        elif action == "add_node":
            serial += 1
            nodes.append(mknode(f"n{seed}-new{serial}"))
        elif action == "remove_node" and len(nodes) > 2:
            i = int(rng.integers(len(nodes)))
            gone = nodes.pop(i)
            pods = [p for p in pods if p.node_name != gone.name]
        epoch = res.sync(nodes, pods)
        assert epoch >= last_epoch
        last_epoch = epoch
        assert_byte_identical(res)
        assert res.covers_reason(
            nodes, [(p, p.node_name) for p in pods]
        ) is None
    # the whole walk was delta-expressible or structurally re-encoded —
    # never a drift repair
    assert res.repairs == repairs_before
    assert res.verify_now() is True


def test_noop_sync_holds_epoch_and_mutation_bumps_it(tmp_path):
    nodes = [mknode("a"), mknode("b")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    e1 = res.sync(nodes, [])
    assert e1 == res.sync(nodes, [])  # no-op: same epoch, key stability
    e2 = res.sync(nodes, [mkpod("p1", "a")])
    assert e2 > e1
    assert_byte_identical(res)


# ---------------------------------------------------------------------------
# chaos: every injected fault becomes a journaled repair, never a wrong
# answer — with exact counter accounting
# ---------------------------------------------------------------------------


def test_torn_delta_repairs_and_journals(tmp_path):
    nodes = [mknode("a"), mknode("b")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [])
    before = repair_count("torn_delta")
    with faults.injected(plan("apply", "torn_delta")):
        res.sync(nodes, [mkpod("p1", "a")])
    assert res.repairs == 1
    assert repair_count("torn_delta") == before + 1
    assert_byte_identical(res)  # the partial device apply was healed
    events = res._journal.events("resident_repair")
    assert len(events) == 1
    assert events[0]["reason"] == "torn_delta"
    assert events[0]["epoch"] == res.epoch
    # the stream keeps working after the repair
    res.sync(nodes, [mkpod("p1", "a"), mkpod("p2", "b")])
    assert_byte_identical(res)


def test_digest_mismatch_detected_and_repaired(tmp_path):
    nodes = [mknode("a"), mknode("b"), mknode("c")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [mkpod("p1", "a")])
    before = repair_count("digest_mismatch")
    mismatches = metrics.RESIDENT_VERIFICATIONS.value(outcome="mismatch")
    with faults.injected(plan("verify", "digest_mismatch")):
        assert res.verify_now() is False
    assert res.repairs == 1
    assert repair_count("digest_mismatch") == before + 1
    assert metrics.RESIDENT_VERIFICATIONS.value(outcome="mismatch") == (
        mismatches + 1
    )
    assert res._journal.has("resident_repair")
    assert_byte_identical(res)
    assert res.verify_now() is True  # fault exhausted: detector is clean


def test_periodic_verify_fires_on_cadence(tmp_path, monkeypatch):
    monkeypatch.setenv("OSIM_RESIDENT_VERIFY_EVERY", "2")
    nodes = [mknode("a"), mknode("b")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [])
    ok_before = metrics.RESIDENT_VERIFICATIONS.value(outcome="ok")
    res.sync(nodes, [mkpod("p1", "a")])
    res.sync(nodes, [mkpod("p1", "a"), mkpod("p2", "b")])  # 2nd delta
    assert metrics.RESIDENT_VERIFICATIONS.value(outcome="ok") == ok_before + 1


def test_delta_budget_exhaustion_repairs(tmp_path, monkeypatch):
    monkeypatch.setenv("OSIM_RESIDENT_DELTA_BUDGET", "2")
    nodes = [mknode("a"), mknode("b")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [])
    before = repair_count("delta_budget")
    res.sync(nodes, [mkpod("p1", "a")])
    assert repair_count("delta_budget") == before  # 1 delta: under budget
    res.sync(nodes, [mkpod("p1", "a"), mkpod("p2", "b")])
    assert repair_count("delta_budget") == before + 1
    assert res.repairs == 1
    assert_byte_identical(res)
    # the re-encode reset the budget: the next delta is cheap again
    res.sync(nodes, [mkpod("p2", "b")])
    assert repair_count("delta_budget") == before + 1


def test_mid_run_disable_is_a_counted_repair(tmp_path, monkeypatch):
    nodes = [mknode("a"), mknode("b")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [])
    assert res.covers_reason(nodes, []) is None
    before = repair_count("disabled")
    monkeypatch.setenv("OSIM_RESIDENT", "0")
    res.sync(nodes, [mkpod("p1", "a")])
    assert repair_count("disabled") == before + 1
    assert res.covers_reason(nodes, [(mkpod("p1", "a"), "a")]) == "disabled"
    # flipping back re-enables the delta path without another repair
    monkeypatch.setenv("OSIM_RESIDENT", "1")
    res.sync(nodes, [mkpod("p1", "a")])
    assert repair_count("disabled") == before + 1
    assert res.covers_reason(nodes, [(mkpod("p1", "a"), "a")]) is None
    assert_byte_identical(res)


def test_structural_changes_are_fallbacks_not_repairs(tmp_path):
    nodes = [mknode("a"), mknode("b"), mknode("c")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [])
    removed_before = metrics.RESIDENT_FALLBACKS.value(reason="node_removed")
    res.sync(nodes[:2], [])  # node c vanished
    assert metrics.RESIDENT_FALLBACKS.value(reason="node_removed") == (
        removed_before + 1
    )
    assert res.repairs == 0  # structural != drift
    assert_byte_identical(res)
    # reorder is its own reason
    order_before = metrics.RESIDENT_FALLBACKS.value(reason="node_order")
    res.sync([nodes[1], nodes[0]], [])
    assert metrics.RESIDENT_FALLBACKS.value(reason="node_order") == (
        order_before + 1
    )
    assert_byte_identical(res)


# ---------------------------------------------------------------------------
# generation fencing
# ---------------------------------------------------------------------------


def test_epochs_never_collide_across_instances(tmp_path):
    """The re-serve bug class: a new ResidentCluster (new serve()) must not
    mint epochs an old instance already used — coalesce keys survive."""
    r1 = ResidentCluster(journal_dir=str(tmp_path / "a"))
    r1.sync([mknode("a")], [])
    r2 = ResidentCluster(journal_dir=str(tmp_path / "b"))
    r2.sync([mknode("a")], [])
    assert r2.epoch > r1.epoch


def test_fence_rekeys_ticket_when_epoch_moves(tmp_path):
    nodes = [mknode("a"), mknode("b")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [])
    q = AdmissionQueue(
        lambda bodies: [{"ok": True} for _ in bodies],
        depth=8, coalesce_ms=0, default_deadline_ms=0,
        fence=res.fence_epoch,
    )
    current_before = metrics.ADMISSION_FENCE.value(outcome="current")
    rekeyed_before = metrics.ADMISSION_FENCE.value(outcome="rekeyed")
    t1 = q.submit({"a": 1}, key=f"k:gen{res.epoch}", fence_epoch=res.epoch)
    res.sync(nodes, [mkpod("p1", "a")])  # epoch moves before dequeue
    t2 = q.submit({"a": 1}, key=f"k:gen{res.epoch}", fence_epoch=res.epoch)
    t3 = q.submit({"b": 2}, key="unfenced")  # no fence_epoch: untouched
    q.run_pending()
    assert t1.code == t2.code == t3.code == 200
    assert t1.key == f"k:gen{res.epoch - 1}@fence{res.epoch}" or t1.key.endswith(
        f"@fence{res.epoch}"
    )
    assert t2.key == f"k:gen{res.epoch}"  # admitted at the current epoch
    assert t3.key == "unfenced"
    assert metrics.ADMISSION_FENCE.value(outcome="rekeyed") == rekeyed_before + 1
    assert metrics.ADMISSION_FENCE.value(outcome="current") == current_before + 1


def test_stale_generation_chaos_forces_rekey(tmp_path):
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync([mknode("a")], [])
    q = AdmissionQueue(
        lambda bodies: [{"ok": True} for _ in bodies],
        depth=8, coalesce_ms=0, default_deadline_ms=0,
        fence=res.fence_epoch,
    )
    t = q.submit({"a": 1}, key=f"k:gen{res.epoch}", fence_epoch=res.epoch)
    with faults.injected(plan("fence", "stale_generation")):
        q.run_pending()
    assert t.code == 200  # degraded to a private key, never a wrong merge
    assert t.key.endswith("@fence-1")


def test_coalesce_key_stale_dimension():
    body = {"apps": []}
    fresh = coalesce_key("/api/deploy-apps", body, generation=7)
    stale = coalesce_key("/api/deploy-apps", body, generation=7, stale=True)
    assert fresh != stale and stale.endswith(":stale")
    # staleness is only meaningful for generation-keyed (live) requests
    assert coalesce_key("/p", body) == coalesce_key("/p", body, stale=True)


# ---------------------------------------------------------------------------
# simulator equivalence + server wiring
# ---------------------------------------------------------------------------


def _deployment(name, replicas, cpu="1"):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": "rt"},
        "spec": {
            "replicas": replicas,
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "img",
                            "resources": {
                                "requests": {"cpu": cpu, "memory": "1Gi"}
                            },
                        }
                    ]
                }
            },
        },
    }


def _placement_nodes(result):
    return sorted(
        st.node.name for st in result.node_status for _ in st.pods
    )


def test_simulate_with_resident_matches_plain(tmp_path):
    nodes = [mknode(f"s{i}", labels={"zone": f"az-{i % 2}"}) for i in range(6)]
    pods = [mkpod("pre1", "s0"), mkpod("pre2", "s1", cpu="2")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, pods)
    apps = [AppResource(name="a", objects=[_deployment("d", 5)])]

    def cluster():
        return ClusterResource(nodes=list(nodes), pods=list(pods))

    fallbacks_before = metrics.RESIDENT_FALLBACKS.snapshot()
    plain = simulate(cluster(), apps)
    fast = simulate(cluster(), apps, resident=res)
    assert _placement_nodes(plain) == _placement_nodes(fast)
    # the fast path was actually taken: no fallback reason was recorded
    assert metrics.RESIDENT_FALLBACKS.snapshot() == fallbacks_before
    # and it holds across a delta: bind one more pod, both paths agree again
    pods.append(mkpod("pre3", "s2"))
    res.sync(nodes, pods)
    plain2 = simulate(cluster(), apps)
    fast2 = simulate(cluster(), apps, resident=res)
    assert _placement_nodes(plain2) == _placement_nodes(fast2)
    assert_byte_identical(res)


def test_simulate_falls_back_when_not_covering(tmp_path):
    nodes = [mknode("f0"), mknode("f1")]
    res = ResidentCluster(journal_dir=str(tmp_path))
    res.sync(nodes, [])
    before = metrics.RESIDENT_FALLBACKS.value(reason="not_covering")
    other = ClusterResource(nodes=[mknode("f0"), mknode("other")], pods=[])
    out = simulate(other, [AppResource(name="a", objects=[_deployment("d", 1)])],
                   resident=res)
    assert metrics.RESIDENT_FALLBACKS.value(reason="not_covering") == before + 1
    assert len(_placement_nodes(out)) == 1  # answer is still correct


def test_server_refresh_creates_and_fences_resident(monkeypatch, tmp_path):
    from unittest import mock

    import open_simulator_tpu.utils.kubeclient as kc
    from open_simulator_tpu.server import server as srv

    snap = ClusterResource(nodes=[mknode("l0"), mknode("l1")], pods=[])
    monkeypatch.setattr(srv, "_kubeconfig", "fake")
    monkeypatch.setattr(srv, "_master", "")
    monkeypatch.setattr(srv, "_snapshot", None)
    monkeypatch.setattr(srv, "_snapshot_at", 0.0)
    monkeypatch.setattr(srv, "_resident", None)
    monkeypatch.setattr(srv, "_snapshot_stale", False)
    with mock.patch.object(
        kc, "create_cluster_resource_from_kubeconfig", return_value=snap
    ):
        srv._live_snapshot()
    assert srv._resident is not None
    gen, stale = srv._snapshot_generation()
    assert gen == srv._resident.epoch and stale is False
    key, fence = srv._coalesce_key_for("/api/deploy-apps", {"apps": []})
    assert f":gen{gen}" in key and fence == gen
    # a body that carries its own cluster is neither keyed nor fenced
    key2, fence2 = srv._coalesce_key_for(
        "/api/deploy-apps", {"cluster": {"objects": [{"kind": "Node"}]}}
    )
    assert "gen" not in key2 and fence2 is None
    # failed refresh: stale flag flips, key grows the :stale dimension
    monkeypatch.setattr(srv, "_snapshot_at", -1e9)
    with mock.patch.object(
        kc,
        "create_cluster_resource_from_kubeconfig",
        side_effect=kc.KubeClientError("boom"),
    ):
        srv._live_snapshot()
    key3, _ = srv._coalesce_key_for("/api/deploy-apps", {"apps": []})
    assert key3.endswith(":stale")
    # recovery clears it
    with mock.patch.object(
        kc, "create_cluster_resource_from_kubeconfig", return_value=snap
    ):
        srv._live_snapshot()
    assert srv._snapshot_generation()[1] is False
