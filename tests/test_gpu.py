"""Open-Gpu-Share parity tests.

Allocation semantics under test mirror GpuNodeInfo.AllocateGpuId
(`/root/reference/pkg/type/open-gpu-share/cache/gpunodeinfo.go:232-290`):
single-GPU pods take the tightest-fitting device; multi-GPU pods run a
two-pointer greedy that may pack several shares onto one device. The e2e test
feeds the reference's own gpushare example manifests through the engine.
"""

import os

import numpy as np
import pytest

from open_simulator_tpu.core.objects import (
    ANNO_GPU_INDEX,
    Node,
    Pod,
)
from open_simulator_tpu.engine.simulator import (
    AppResource,
    ClusterResource,
    simulate,
)
from open_simulator_tpu.ops.encode import (
    Encoder,
    aggregate_gpu_usage,
    encode_nodes,
    encode_pods,
    host_allocate_gpu,
    initial_selector_counts,
)
from open_simulator_tpu.ops.kernels import F_GPU, schedule_batch, weights_array
from open_simulator_tpu.ops.state import (
    carry_from_table,
    node_static_from_table,
    pod_rows_from_batch,
)

REF_EXAMPLE = "/root/reference/example"


def gpu_node(name, count, per_dev_mib, cpu="32", mem="128Gi"):
    total = count * per_dev_mib
    return Node.from_dict(
        {
            "metadata": {"name": name},
            "status": {
                "allocatable": {
                    "cpu": cpu,
                    "memory": mem,
                    "pods": "110",
                    "alibabacloud.com/gpu-count": str(count),
                    "alibabacloud.com/gpu-mem": f"{total}Mi",
                },
                "capacity": {
                    "cpu": cpu,
                    "memory": mem,
                    "pods": "110",
                    "alibabacloud.com/gpu-count": str(count),
                    "alibabacloud.com/gpu-mem": f"{total}Mi",
                },
            },
        }
    )


def gpu_pod(name, mem_mib, count=1, cpu="1"):
    return Pod.from_dict(
        {
            "metadata": {
                "name": name,
                "namespace": "default",
                "annotations": {
                    "alibabacloud.com/gpu-mem": f"{mem_mib}Mi",
                    "alibabacloud.com/gpu-count": str(count),
                },
            },
            "spec": {
                "containers": [
                    {"name": "c", "resources": {"requests": {"cpu": cpu}}}
                ]
            },
        }
    )


def run_gpu(nodes, pods, placed=()):
    enc = Encoder()
    enc.register_pods(pods)
    table = encode_nodes(
        enc,
        nodes,
        existing_gpu=aggregate_gpu_usage(nodes, list(placed)),
    )
    batch = encode_pods(enc, pods)
    ns = node_static_from_table(enc, table)
    carry = carry_from_table(table, initial_selector_counts(enc, table, list(placed)))
    rows = pod_rows_from_batch(batch)
    final, placed_idx, reasons, take, *_ = schedule_batch(ns, carry, rows, weights_array())
    names = [
        table.names[int(i)] if int(i) >= 0 else None
        for i in np.asarray(placed_idx)[: len(pods)]
    ]
    return names, np.asarray(reasons)[: len(pods)], np.asarray(take)[: len(pods)], final


def ids_from_take(take_row):
    return [d for d in range(len(take_row)) for _ in range(int(take_row[d]))]


def test_single_gpu_tightest_fit():
    # devices free: [16384, 8192(partially used), 24576] after seeding a pod
    node = gpu_node("g0", 3, 16384)
    seed = gpu_pod("seed", 8192)
    seed.node_name = "g0"
    seed.meta.annotations[ANNO_GPU_INDEX] = "1"
    pod = gpu_pod("p", 4096)
    names, _, take, _ = run_gpu([node], [pod], placed=[(seed, "g0")])
    assert names == ["g0"]
    # tightest fit: device 1 has 8192 free (least that still fits 4096)
    assert ids_from_take(take[0]) == [1]


def test_multi_gpu_two_pointer_packs_one_device():
    # 2 devices x 20 GiB; request 3 shares of 8 GiB -> greedy packs dev0 twice
    node = gpu_node("g0", 2, 20480)
    pod = gpu_pod("p", 8192, count=3)
    names, _, take, _ = run_gpu([node], [pod])
    assert names == ["g0"]
    assert ids_from_take(take[0]) == [0, 0, 1]


def test_gpu_infeasible_when_no_device_fits():
    # total free 20 GiB but no single device holds 12 GiB
    node = gpu_node("g0", 2, 10240)
    pod = gpu_pod("p", 12288)
    names, reasons, _, _ = run_gpu([node], [pod])
    assert names == [None]
    assert reasons[0][F_GPU] == 1


def test_gpu_pod_rejected_on_non_gpu_node():
    plain = Node.from_dict(
        {
            "metadata": {"name": "cpu0"},
            "status": {"allocatable": {"cpu": "32", "memory": "64Gi", "pods": "110"}},
        }
    )
    pod = gpu_pod("p", 1024)
    names, reasons, _, _ = run_gpu([plain], [pod])
    assert names == [None]
    assert reasons[0][F_GPU] == 1


def test_sequential_packing_until_full():
    # one node, 2 devices x 10 GiB; five 4-GiB pods: fits 2+2, fifth fails
    node = gpu_node("g0", 2, 10240)
    pods = [gpu_pod(f"p{i}", 4096) for i in range(5)]
    names, reasons, take, _ = run_gpu([node], pods)
    assert names[:4] == ["g0"] * 4
    assert names[4] is None
    assert reasons[4][F_GPU] == 1
    per_dev = np.zeros(take.shape[1])
    for row in take[:4]:
        per_dev += row
    assert sorted(per_dev[per_dev > 0].tolist()) == [2.0, 2.0]


def test_whole_gpu_resource_uses_dynamic_count():
    # 2 devices; a shared pod consumes ALL of one device, so only 1 device
    # stays allocatable (GpuAllocatable subtracts fully-used devices,
    # gpunodeinfo.go:355-362): a whole-GPU pod requesting 2 must fail even
    # though the static allocatable says 2. A partially-used device would NOT
    # reduce the count.
    node = gpu_node("g0", 2, 16384)
    shared = gpu_pod("shared", 16384)
    whole = Pod.from_dict(
        {
            "metadata": {"name": "whole", "namespace": "default"},
            "spec": {
                "containers": [
                    {
                        "name": "c",
                        "resources": {
                            "requests": {"cpu": "1", "alibabacloud.com/gpu-count": "2"}
                        },
                    }
                ]
            },
        }
    )
    names, reasons, _, _ = run_gpu([node], [shared, whole])
    assert names[0] == "g0"
    assert names[1] is None

    # without the shared pod, the whole-GPU pod fits
    names2, _, _, _ = run_gpu([gpu_node("g0", 2, 16384)], [whole])
    assert names2 == ["g0"]

    # a PARTIALLY-used device still counts as allocatable (reference quirk)
    partial = gpu_pod("partial", 1024)
    names3, _, _, _ = run_gpu([gpu_node("g0", 2, 16384)], [partial, whole])
    assert names3 == ["g0", "g0"]


def test_host_allocator_matches_kernel():
    rng = np.random.default_rng(7)
    for _ in range(50):
        g = int(rng.integers(1, 6))
        per_dev = float(rng.integers(4, 40) * 1024)
        node = gpu_node("g0", g, int(per_dev))
        mem = int(rng.integers(1, 20) * 512)
        num = int(rng.integers(1, 5))
        pod = gpu_pod("p", mem, count=num)
        names, _, take, _ = run_gpu([node], [pod])
        free = np.full(g, np.float32(per_dev), np.float32)
        host_ids = host_allocate_gpu(free, np.float32(mem), num)
        if host_ids is None:
            assert names == [None]
        else:
            assert names == ["g0"]
            assert ids_from_take(take[0]) == host_ids


@pytest.mark.skipif(
    not os.path.isdir(os.path.join(REF_EXAMPLE, "cluster/gpushare")),
    reason="reference examples unavailable",
)
def test_reference_gpushare_example_end_to_end():
    from open_simulator_tpu.utils.yamlio import objects_from_directory

    cluster = ClusterResource.from_objects(
        objects_from_directory(os.path.join(REF_EXAMPLE, "cluster/gpushare"))
    )
    app = AppResource(
        name="gpushare",
        objects=objects_from_directory(
            os.path.join(REF_EXAMPLE, "application/gpushare")
        ),
    )
    result = simulate(cluster, [app])
    placed = [p for st in result.node_status for p in st.pods]
    gpu_pods = [p for p in placed if p.gpu_mem_request() > 0]
    # every scheduled GPU pod carries a device assignment
    for p in gpu_pods:
        assert p.meta.annotations.get(ANNO_GPU_INDEX), p.key
    assert gpu_pods, "no GPU pods scheduled from the reference example"
