"""Device-kernel tests: feasibility masks must agree with the pure-Python
oracle (core.matcher) on randomized clusters, and the scan must reproduce the
sequential-commit behaviors of the reference scheduler."""

import numpy as np
import pytest

from open_simulator_tpu.core.matcher import (
    fits_resources,
    match_node_affinity,
    untolerated_taint,
)
from open_simulator_tpu.core.objects import Node, Pod
from open_simulator_tpu.ops.encode import (
    Encoder,
    encode_nodes,
    encode_pods,
    initial_selector_counts,
)
from open_simulator_tpu.ops.kernels import (
    F_POD_AFFINITY,
    F_RESOURCES,
    F_TAINT,
    NUM_FILTERS,
    run_filters,
    schedule_batch,
    weights_array,
)
from open_simulator_tpu.ops.state import (
    carry_from_table,
    node_static_from_table,
    pod_rows_from_batch,
)

import jax


def mknode(name, cpu="8", mem="16Gi", labels=None, taints=None, unschedulable=False):
    return Node.from_dict(
        {
            "metadata": {"name": name, "labels": labels or {}},
            "spec": {"taints": taints or [], "unschedulable": unschedulable},
            "status": {"allocatable": {"cpu": cpu, "memory": mem, "pods": "110"}},
        }
    )


def mkpod(name, cpu="1", mem="1Gi", ns="default", **spec_extra):
    spec = {
        "containers": [
            {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": mem}}}
        ]
    }
    spec.update(spec_extra)
    return Pod.from_dict({"metadata": {"name": name, "namespace": ns}, "spec": spec})


def encode_all(nodes, pods, placed=()):
    enc = Encoder()
    enc.register_pods(pods)
    table = encode_nodes(enc, nodes)
    batch = encode_pods(enc, pods)
    ns = node_static_from_table(enc, table)
    carry = carry_from_table(table, initial_selector_counts(enc, table, list(placed)))
    rows = pod_rows_from_batch(batch)
    return enc, table, batch, ns, carry, rows


def run(nodes, pods, placed=()):
    enc, table, batch, ns, carry, rows = encode_all(nodes, pods, placed)
    carry2, placed_idx, reasons, *_ = schedule_batch(ns, carry, rows, weights_array())
    names = [table.names[i] if i >= 0 else None for i in np.asarray(placed_idx)[: len(pods)]]
    return names, np.asarray(reasons), np.asarray(carry2.free), table


# ---------------------------------------------------------------------------
# Oracle agreement on randomized inputs
# ---------------------------------------------------------------------------

def test_filters_match_python_oracle_randomized():
    rng = np.random.default_rng(7)
    keys = ["zone", "disk", "arch", "role"]
    values = ["a", "b", "c"]
    effects = ["NoSchedule", "PreferNoSchedule", "NoExecute"]
    for trial in range(6):
        nodes = []
        for i in range(8):
            labels = {
                k: str(rng.choice(values)) for k in keys if rng.random() < 0.6
            }
            taints = [
                {
                    "key": str(rng.choice(keys)),
                    "value": str(rng.choice(values)),
                    "effect": str(rng.choice(effects)),
                }
                for _ in range(rng.integers(0, 3))
            ]
            nodes.append(
                mknode(
                    f"n{i}",
                    cpu=str(rng.integers(1, 9)),
                    mem=f"{rng.integers(1, 17)}Gi",
                    labels=labels,
                    taints=taints,
                    unschedulable=bool(rng.random() < 0.1),
                )
            )
        pods = []
        for j in range(6):
            spec = {}
            if rng.random() < 0.5:
                spec["nodeSelector"] = {str(rng.choice(keys)): str(rng.choice(values))}
            if rng.random() < 0.5:
                spec["tolerations"] = [
                    {
                        "key": str(rng.choice(keys)),
                        "operator": str(rng.choice(["Equal", "Exists"])),
                        "value": str(rng.choice(values)),
                        "effect": str(rng.choice(effects + [""])),
                    }
                ]
            if rng.random() < 0.4:
                spec["affinity"] = {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {
                                            "key": str(rng.choice(keys)),
                                            "operator": str(
                                                rng.choice(
                                                    ["In", "NotIn", "Exists", "DoesNotExist"]
                                                )
                                            ),
                                            "values": [str(rng.choice(values))],
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                }
            pods.append(
                mkpod(f"p{j}", cpu=str(rng.integers(1, 5)), mem=f"{rng.integers(1, 9)}Gi", **spec)
            )

        enc, table, batch, ns, carry, rows = encode_all(nodes, pods)
        for j, pod in enumerate(pods):
            row = jax.tree.map(lambda a: a[j], rows)
            mask, first_fail = run_filters(ns, carry, row)
            mask = np.asarray(mask)
            for i, node in enumerate(nodes):
                free = {
                    r: node.allocatable.get(r, 0) for r in node.allocatable
                }
                expect = (
                    not node.unschedulable
                    and untolerated_taint(pod.tolerations, node) is None
                    and match_node_affinity(pod, node)
                    and not fits_resources(pod, free)
                )
                assert mask[i] == expect, (
                    f"trial {trial} pod {j} node {i}: kernel={mask[i]} oracle={expect}\n"
                    f"pod={pod}\nnode={node}"
                )


# ---------------------------------------------------------------------------
# Sequential-commit behaviors
# ---------------------------------------------------------------------------

def test_resource_exhaustion_and_reasons():
    nodes = [mknode("a", cpu="2", mem="4Gi"), mknode("b", cpu="2", mem="4Gi")]
    pods = [mkpod(f"p{i}", cpu="1500m", mem="1Gi") for i in range(4)]
    names, reasons, free, _ = run(nodes, pods)
    assert names[0] is not None and names[1] is not None
    assert set(names[:2]) == {"a", "b"}  # spreading via least-allocated
    assert names[2] is None and names[3] is None
    assert reasons[2][F_RESOURCES] == 2


def test_node_name_pinning():
    nodes = [mknode("a"), mknode("b")]
    pods = [mkpod("p0", nodeName="b")]
    names, _, _, _ = run(nodes, pods)
    assert names == ["b"]


def test_taints_and_tolerations():
    taint = [{"key": "dedicated", "value": "gpu", "effect": "NoSchedule"}]
    nodes = [mknode("tainted", taints=taint), mknode("open", cpu="1", mem="1Gi")]
    # intolerant pod that only fits the tainted node -> unschedulable there
    big = mkpod("big", cpu="4", mem="4Gi")
    names, reasons, _, _ = run(nodes, [big])
    assert names == [None]
    assert reasons[0][F_TAINT] == 1
    # tolerant pod lands on the tainted node
    tol = mkpod(
        "tol", cpu="4", mem="4Gi",
        tolerations=[{"key": "dedicated", "operator": "Equal", "value": "gpu", "effect": "NoSchedule"}],
    )
    names, _, _, _ = run(nodes, [tol])
    assert names == ["tainted"]


def test_node_selector_and_affinity():
    nodes = [
        mknode("ssd", labels={"disk": "ssd"}),
        mknode("hdd", labels={"disk": "hdd"}),
    ]
    pods = [
        mkpod("sel", nodeSelector={"disk": "ssd"}),
        mkpod(
            "aff",
            affinity={
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {
                                "matchExpressions": [
                                    {"key": "disk", "operator": "In", "values": ["hdd"]}
                                ]
                            }
                        ]
                    }
                }
            },
        ),
    ]
    names, _, _, _ = run(nodes, pods)
    assert names == ["ssd", "hdd"]


def test_preferred_affinity_steers():
    nodes = [mknode("a", labels={"zone": "a"}), mknode("b", labels={"zone": "b"})]
    pod = mkpod(
        "p",
        affinity={
            "nodeAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": 100,
                        "preference": {
                            "matchExpressions": [
                                {"key": "zone", "operator": "In", "values": ["b"]}
                            ]
                        },
                    }
                ]
            }
        },
    )
    names, _, _, _ = run(nodes, [pod])
    assert names == ["b"]


def test_anti_affinity_spreads_replicas():
    nodes = [mknode(f"n{i}") for i in range(3)]
    anti = {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
    }
    pods = []
    for i in range(4):
        p = Pod.from_dict(
            {
                "metadata": {"name": f"w{i}", "namespace": "d", "labels": {"app": "web"}},
                "spec": {
                    "containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}],
                    "affinity": anti,
                },
            }
        )
        pods.append(p)
    names, reasons, _, _ = run(nodes, pods)
    # 3 replicas land on 3 distinct nodes; the 4th has nowhere left
    assert sorted(n for n in names[:3]) == ["n0", "n1", "n2"]
    assert names[3] is None
    assert reasons[3][F_POD_AFFINITY] == 3


def test_required_pod_affinity_collocates():
    nodes = [
        mknode("za1", labels={"zone": "a"}),
        mknode("zb1", labels={"zone": "b"}),
    ]
    base = Pod.from_dict(
        {
            "metadata": {"name": "db", "namespace": "d", "labels": {"app": "db"}},
            "spec": {
                "containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}],
                "nodeSelector": {"zone": "b"},
            },
        }
    )
    follower = Pod.from_dict(
        {
            "metadata": {"name": "web", "namespace": "d", "labels": {"app": "web"}},
            "spec": {
                "containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}],
                "affinity": {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"app": "db"}},
                                "topologyKey": "zone",
                            }
                        ]
                    }
                },
            },
        }
    )
    names, _, _, _ = run(nodes, [base, follower])
    assert names == ["zb1", "zb1"]


def test_self_affinity_first_pod_bootstraps():
    nodes = [mknode("a", labels={"zone": "a"})]
    pod = Pod.from_dict(
        {
            "metadata": {"name": "g0", "namespace": "d", "labels": {"app": "g"}},
            "spec": {
                "containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}],
                "affinity": {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"app": "g"}},
                                "topologyKey": "zone",
                            }
                        ]
                    }
                },
            },
        }
    )
    names, _, _, _ = run(nodes, [pod])
    assert names == ["a"]


def test_topology_spread_hard():
    nodes = [
        mknode("a1", labels={"zone": "a"}),
        mknode("a2", labels={"zone": "a"}),
        mknode("b1", labels={"zone": "b"}),
    ]
    pods = []
    for i in range(4):
        pods.append(
            Pod.from_dict(
                {
                    "metadata": {"name": f"s{i}", "namespace": "d", "labels": {"app": "s"}},
                    "spec": {
                        "containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}],
                        "topologySpreadConstraints": [
                            {
                                "maxSkew": 1,
                                "topologyKey": "zone",
                                "whenUnsatisfiable": "DoNotSchedule",
                                "labelSelector": {"matchLabels": {"app": "s"}},
                            }
                        ],
                    },
                }
            )
        )
    names, _, _, _ = run(nodes, pods)
    zones = {"a1": "a", "a2": "a", "b1": "b"}
    placed_zones = [zones[n] for n in names]
    # after 4 pods the skew |a - b| must stay <= 1 at every prefix
    for k in range(1, 5):
        prefix = placed_zones[:k]
        assert abs(prefix.count("a") - prefix.count("b")) <= 1


def test_unschedulable_node():
    nodes = [mknode("u", unschedulable=True), mknode("ok")]
    names, _, _, _ = run(nodes, [mkpod("p")])
    assert names == ["ok"]


def test_existing_pods_consume_free():
    nodes = [mknode("a", cpu="4", mem="8Gi")]
    existing = mkpod("old", cpu="3", mem="1Gi")
    enc = Encoder()
    pods = [mkpod("new", cpu="2", mem="1Gi")]
    enc.register_pods(pods)
    usage = {"a": existing.requests}
    from open_simulator_tpu.ops.encode import encode_nodes as en

    table = en(enc, nodes, existing_usage=usage)
    batch = encode_pods(enc, pods)
    ns = node_static_from_table(enc, table)
    carry = carry_from_table(table, initial_selector_counts(enc, table, [(existing, "a")]))
    rows = pod_rows_from_batch(batch)
    _, placed, reasons, *_ = schedule_batch(ns, carry, rows, weights_array())
    assert np.asarray(placed)[0] == -1  # only 1 cpu free, pod wants 2
    assert np.asarray(reasons)[0][F_RESOURCES] == 1


def test_combine_scores_prefix_split_is_exact():
    """The micro body's foundation: combine_scores' left fold must split
    bitwise as fold(order[:-1]) + w_last * s_last (kernels.combine_scores
    docstring; topology_spread is last by the SP_IDX assert in ops/fast.py)."""
    import numpy as np

    from open_simulator_tpu.ops.kernels import WEIGHT_ORDER, combine_scores

    rng = np.random.default_rng(7)
    N = 4097
    by_name = {
        k: (rng.standard_normal(N) * rng.integers(1, 1000)).astype(np.float32)
        for k in WEIGHT_ORDER
    }
    w = rng.standard_normal(len(WEIGHT_ORDER)).astype(np.float32)

    full = np.asarray(combine_scores(by_name, w))
    prefix = np.asarray(combine_scores(by_name, w, order=WEIGHT_ORDER[:-1]))
    split = prefix + w[-1] * by_name[WEIGHT_ORDER[-1]]
    np.testing.assert_array_equal(
        full.view(np.uint32), np.asarray(split).view(np.uint32)
    )
