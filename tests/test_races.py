"""Race detector: true-positive fixtures, near-miss negatives, and the
package-level regression gate.

Fixture packages are written to tmp_path and only parsed — never imported
or executed — so snippets are free to spawn fake threads and handlers.
The package-level tests pin the two server.py fixes this detector
motivated: the _tracemalloc_on check-then-act now runs under
_tracemalloc_lock, and the snapshot-cache refresh carries
@guarded_by("_snapshot_lock") (formerly the POST _busy try-lock, removed
when admission control landed).
"""

import json
import textwrap
import threading

from open_simulator_tpu.analysis.races import run_races
from open_simulator_tpu.analysis.lint import build_context


def _races(tmp_path, source, extra_modules=None):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    for name, src in (extra_modules or {}).items():
        (pkg / f"{name}.py").write_text(textwrap.dedent(src))
    return run_races(package_root=str(pkg), report_root=str(tmp_path))


HANDLER_PREAMBLE = """
    import threading
    from http.server import BaseHTTPRequestHandler

    _lock = threading.Lock()
    _cache = {}
    _hits = 0
"""


# ---------------------------------------------------------------------------
# true positives
# ---------------------------------------------------------------------------

def test_unguarded_container_mutation_in_handler_flagged(tmp_path):
    rep = _races(
        tmp_path,
        HANDLER_PREAMBLE + """
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            _cache[self.path] = 1
    """,
    )
    assert [f.access for f in rep.active] == ["mutate"]
    assert rep.active[0].state == "pkg.mod._cache"
    assert not rep.ok


def test_unguarded_scalar_rmw_flagged(tmp_path):
    rep = _races(
        tmp_path,
        HANDLER_PREAMBLE + """
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            global _hits
            _hits += 1
    """,
    )
    assert [f.access for f in rep.active] == ["rmw"]
    assert rep.active[0].state == "pkg.mod._hits"


def test_check_then_act_flagged(tmp_path):
    """A read and a separate rebind in one function is the TOCTOU shape
    (the _tracemalloc_on bug) even without an AugAssign."""
    rep = _races(
        tmp_path,
        HANDLER_PREAMBLE + """
    _started = False

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            global _started
            if not _started:
                _started = True
    """,
    )
    assert [f.access for f in rep.active] == ["check-then-act"]
    assert rep.active[0].state == "pkg.mod._started"


def test_thread_target_and_helper_reachability(tmp_path):
    """Mutations in a helper function called from a Thread target are
    reachable and flagged."""
    rep = _races(
        tmp_path,
        """
    import threading

    _jobs = []

    def _push(x):
        _jobs.append(x)

    def worker():
        _push(1)

    def start():
        threading.Thread(target=worker).start()
    """,
    )
    assert [(f.access, f.state) for f in rep.active] == [
        ("mutate", "pkg.mod._jobs")
    ]
    assert "thread target" in rep.active[0].thread_root


def test_signal_handler_is_a_root(tmp_path):
    rep = _races(
        tmp_path,
        """
    import signal

    _seen = []

    def on_term(signum, frame):
        _seen.append(signum)

    def install():
        signal.signal(signal.SIGTERM, on_term)
    """,
    )
    assert [f.state for f in rep.active] == ["pkg.mod._seen"]
    assert "signal handler" in rep.active[0].thread_root


def test_cross_module_shared_state(tmp_path):
    """A handler mutating another module's shared dict through a module
    import is resolved to the owning module."""
    rep = _races(
        tmp_path,
        """
    from http.server import BaseHTTPRequestHandler
    from . import store

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            store.table["k"] = 1
    """,
        extra_modules={"store": "table = {}\n"},
    )
    assert [f.state for f in rep.active] == ["pkg.store.table"]


def test_self_method_thread_target_is_rooted(tmp_path):
    """Thread(target=self._method) inside a class resolves the sibling
    method as a root (the AdmissionQueue._worker_main shape)."""
    rep = _races(
        tmp_path,
        """
    import threading

    _pending = []

    class Q:
        def start(self):
            threading.Thread(target=self._worker_main).start()

        def _worker_main(self):
            _pending.append(1)
    """,
    )
    assert [f.state for f in rep.active] == ["pkg.mod._pending"]
    assert "Q._worker_main" in rep.active[0].thread_root


def test_nested_function_thread_target_is_rooted(tmp_path):
    """A def nested inside the spawning function (the guarded_call._worker
    shape) is resolved via its enclosing scope."""
    rep = _races(
        tmp_path,
        """
    import threading

    _done = []

    def guarded_call(fn):
        def _worker():
            _done.append(fn())

        threading.Thread(target=_worker).start()
    """,
    )
    assert [f.state for f in rep.active] == ["pkg.mod._done"]
    assert "guarded_call._worker" in rep.active[0].thread_root


def test_executor_submit_target_is_rooted(tmp_path):
    rep = _races(
        tmp_path,
        """
    from concurrent.futures import ThreadPoolExecutor

    _results = []

    def job(x):
        _results.append(x)

    def run(pool: ThreadPoolExecutor):
        pool.submit(job, 1)
    """,
    )
    assert [f.state for f in rep.active] == ["pkg.mod._results"]
    assert "executor task" in rep.active[0].thread_root


def test_cross_class_attribute_call_is_audited(tmp_path):
    """self.<attr>.<method>() hops into the attribute's class when the
    method name is unique package-wide (the SchedulerLoop.run_forever ->
    session.take_pack shape). The callee lives in another module, so only
    the hop — not the root-module blanket audit — can reach it."""
    rep = _races(
        tmp_path,
        """
    import threading
    from .sess import Session

    class Loop:
        def __init__(self):
            self.session = Session()

        def run_forever(self):
            self.session.take_pack_unique()

        def start(self):
            threading.Thread(target=self.run_forever).start()
    """,
        extra_modules={
            "sess": """
    _packs = []

    class Session:
        def take_pack_unique(self):
            _packs.append(1)
    """,
        },
    )
    assert [f.state for f in rep.active] == ["pkg.sess._packs"]


def test_ambiguous_method_name_not_resolved(tmp_path):
    """Two classes defining the same method name => the self.<attr>.m()
    hop stays unresolved (no guessing), so the other-module mutation is
    unreachable."""
    rep = _races(
        tmp_path,
        """
    import threading
    from .sess import A

    class Loop:
        def __init__(self):
            self.session = A()

        def run_forever(self):
            self.session.step()

        def start(self):
            threading.Thread(target=self.run_forever).start()
    """,
        extra_modules={
            "sess": """
    _packs = []

    class A:
        def step(self):
            _packs.append(1)

    class B:
        def step(self):
            pass
    """,
        },
    )
    assert rep.ok, rep.render_text()


# ---------------------------------------------------------------------------
# near-miss negatives
# ---------------------------------------------------------------------------

def test_with_lock_dominated_mutation_ok(tmp_path):
    rep = _races(
        tmp_path,
        HANDLER_PREAMBLE + """
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            with _lock:
                _cache[self.path] = 1
    """,
    )
    assert rep.ok, rep.render_text()


def test_guarded_by_annotation_trusted(tmp_path):
    """@guarded_by asserts the caller holds the lock (e.g. a non-with
    acquire like server.py's do_POST) — the body is treated as dominated."""
    rep = _races(
        tmp_path,
        HANDLER_PREAMBLE + """
    from pkg.conc import guarded_by

    @guarded_by("_lock")
    def refresh(k):
        global _hits
        _hits = _hits + 1
        _cache[k] = _hits

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            with _lock:
                refresh(self.path)
    """,
        extra_modules={
            "conc": "def guarded_by(name):\n    return lambda fn: fn\n"
        },
    )
    assert rep.ok, rep.render_text()


def test_plain_publish_not_flagged(tmp_path):
    """A single rebind with no read in the same function is an atomic
    publish under the GIL — the serve()-resets-the-snapshot shape."""
    rep = _races(
        tmp_path,
        HANDLER_PREAMBLE + """
    _snapshot = None

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            global _snapshot
            _snapshot = None
    """,
    )
    assert rep.ok, rep.render_text()


def test_pure_reads_not_flagged(tmp_path):
    rep = _races(
        tmp_path,
        HANDLER_PREAMBLE + """
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            x = _cache.get(self.path)
            y = _hits
            return (x, y)
    """,
    )
    assert rep.ok, rep.render_text()


def test_unreachable_mutation_not_flagged(tmp_path):
    """No thread roots in the package => nothing is audited."""
    rep = _races(
        tmp_path,
        """
    _cache = {}

    def mutate():
        _cache["k"] = 1
    """,
    )
    assert rep.ok
    assert rep.audited_functions == 0


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_audit_ok_suppression_and_staleness(tmp_path):
    src = HANDLER_PREAMBLE + """
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            _cache[self.path] = 1  # osim: audit-ok[race]
            x = 1  # osim: audit-ok[race]
    """
    rep = _races(tmp_path, src)
    assert rep.active == []
    assert [f.suppressed for f in rep.findings] == [True]
    # the second comment suppresses nothing -> stale, and ok stays False
    assert [(u.line, u.rule) for u in rep.unused_suppressions] == [
        (rep.findings[0].line + 1, "race")
    ]
    assert not rep.ok


def test_report_json_is_deterministic(tmp_path):
    src = HANDLER_PREAMBLE + """
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            global _hits
            _hits += 1
            _cache[self.path] = 1
    """
    a = json.dumps(_races(tmp_path, src).to_dict(), sort_keys=True)
    b = json.dumps(_races(tmp_path, src).to_dict(), sort_keys=True)
    assert a == b
    doc = json.loads(a)
    assert [f["access"] for f in doc["findings"]] == ["rmw", "mutate"]


# ---------------------------------------------------------------------------
# package-level regression gate + the fixed server.py bugs
# ---------------------------------------------------------------------------

def test_installed_package_has_no_unguarded_races():
    rep = run_races()
    assert rep.ok, rep.render_text()
    # the audit actually looked at the threaded surface
    assert rep.audited_functions > 0
    assert any("do_POST" in r or "do_GET" in r for r in rep.thread_roots)


def test_package_thread_roots_cover_workers_and_watchdog():
    """The enclosing-scope pass must root the admission worker (a
    Thread(target=self._worker_main) sibling) and the watchdog's nested
    _worker def — the two shapes the module-scope pass used to miss."""
    rep = run_races()
    roots = "\n".join(rep.thread_roots)
    assert "AdmissionQueue._worker_main" in roots, roots
    assert "guarded_call._worker" in roots, roots


def test_known_good_guarded_modules_not_flagged():
    """policy.py's _breakers and tracing's history are with-lock guarded;
    they must appear as shared state yet produce no findings."""
    rep = run_races()
    assert any("policy._breakers" in s for s in rep.shared_objects)
    assert not [f for f in rep.findings if "policy" in f.state]


def test_heap_profile_check_then_act_is_serialized():
    """Regression for the _tracemalloc_on race: concurrent heap profiles
    must agree that exactly one of them started tracing."""
    from open_simulator_tpu.server import server

    server._tracemalloc_on = False
    results = []
    barrier = threading.Barrier(4)

    def go():
        barrier.wait()
        results.append(server._heap_profile()["note"] != "")

    threads = [threading.Thread(target=go) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 1, results


def test_live_snapshot_declares_its_lock():
    from open_simulator_tpu.server import server
    from open_simulator_tpu.utils.concurrency import GUARDED_BY_ATTR

    assert (
        getattr(server._refresh_snapshot_locked, GUARDED_BY_ATTR)
        == "_snapshot_lock"
    )


def test_build_context_reuse_matches_fresh_run():
    """run_races accepts a prebuilt context (the audit driver path)."""
    ctx = build_context()
    a = run_races(ctx=ctx).to_dict()
    b = run_races().to_dict()
    assert a == b


# ---------------------------------------------------------------------------
# lock-order deadlock pass
# ---------------------------------------------------------------------------

DEADLOCK_PREAMBLE = """
    import threading
    import queue

    lock_a = threading.Lock()
    lock_b = threading.Lock()
    _q = queue.Queue()
"""


def test_lock_order_cycle_flagged(tmp_path):
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    def worker():
        with lock_a:
            with lock_b:
                pass

    def other():
        with lock_b:
            with lock_a:
                pass

    threading.Thread(target=worker).start()
    threading.Thread(target=other).start()
    """,
    )
    cyc = [f for f in rep.active if f.rule == "deadlock"]
    assert len(cyc) == 1 and "lock-order cycle" in cyc[0].message
    assert sorted(rep.lock_edges) == [
        "pkg.mod:lock_a -> pkg.mod:lock_b",
        "pkg.mod:lock_b -> pkg.mod:lock_a",
    ]
    assert not rep.ok


def test_lock_order_cycle_through_callee_flagged(tmp_path):
    """The interprocedural half: B is acquired via a helper call made
    while A is held."""
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    def worker():
        with lock_a:
            helper()

    def helper():
        with lock_b:
            pass

    def other():
        with lock_b:
            with lock_a:
                pass

    threading.Thread(target=worker).start()
    threading.Thread(target=other).start()
    """,
    )
    assert any("lock-order cycle" in f.message for f in rep.active)


def test_consistent_lock_order_not_flagged(tmp_path):
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    def worker():
        with lock_a:
            with lock_b:
                pass

    def other():
        with lock_a:
            with lock_b:
                pass

    threading.Thread(target=worker).start()
    threading.Thread(target=other).start()
    """,
    )
    assert rep.ok, rep.render_text()
    assert rep.lock_edges == ["pkg.mod:lock_a -> pkg.mod:lock_b"]


def test_self_deadlock_on_plain_lock_flagged(tmp_path):
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    def worker():
        with lock_a:
            with lock_a:
                pass

    threading.Thread(target=worker).start()
    """,
    )
    assert any("self-deadlock" in f.message for f in rep.active)


def test_rlock_reentry_not_flagged(tmp_path):
    rep = _races(
        tmp_path,
        """
    import threading

    rl = threading.RLock()

    def worker():
        with rl:
            with rl:
                pass

    threading.Thread(target=worker).start()
    """,
    )
    assert rep.ok, rep.render_text()


def test_blocking_get_and_join_under_lock_flagged(tmp_path):
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    class Pool:
        def __init__(self):
            self._mu = threading.Lock()
            self._t = threading.Thread(target=self._run)

        def _run(self):
            with self._mu:
                item = _q.get()
            with self._mu:
                self._t.join()
    """,
    )
    verbs = sorted(
        f.message.split("`")[1] for f in rep.active if f.access == "blocking"
    )
    assert verbs == [".get()", ".join()"]
    # instance lock resolved to its class-qualified identity
    assert all("Pool._mu" in f.state for f in rep.active)


def test_bounded_waits_and_dict_get_not_flagged(tmp_path):
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    _d = {}

    def worker():
        with lock_a:
            item = _q.get(timeout=1.0)
            v = _d.get("k")
            "x".join(["a"])

    threading.Thread(target=worker).start()
    """,
    )
    assert rep.ok, rep.render_text()


def test_deadlock_suppression_and_staleness(tmp_path):
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    def worker():
        with lock_a:
            item = _q.get()  # osim: audit-ok[deadlock]
            x = 1  # osim: audit-ok[deadlock]

    threading.Thread(target=worker).start()
    """,
    )
    assert not rep.active
    assert [f.rule for f in rep.findings if f.suppressed] == ["deadlock"]
    assert len(rep.unused_suppressions) == 1
    assert not rep.ok  # the stale suppression keeps the audit red


def test_race_suppression_does_not_silence_deadlock(tmp_path):
    rep = _races(
        tmp_path,
        DEADLOCK_PREAMBLE + """
    def worker():
        with lock_a:
            item = _q.get()  # osim: audit-ok[race]

    threading.Thread(target=worker).start()
    """,
    )
    assert any(f.rule == "deadlock" for f in rep.active)
    # and the race escape is stale: it matched no race finding
    assert len(rep.unused_suppressions) == 1


# ---------------------------------------------------------------------------
# PR 18 thread-root surfaces: watchdog-guarded callables + subprocess
# wrappers
# ---------------------------------------------------------------------------

def test_guarded_call_target_is_a_thread_root(tmp_path):
    """guarded_call(stage, fn, deadline) runs fn on a watchdog worker
    thread; an unguarded mutation inside fn must be flagged."""
    rep = _races(
        tmp_path,
        """
    import threading

    _lock = threading.Lock()
    _progress = {}

    def _sweep():
        _progress["chunk"] = 1

    def drive(guarded_call):
        guarded_call("sweep", _sweep, 30.0)
    """,
    )
    assert any("watchdog-guarded call" in r for r in rep.thread_roots)
    assert [f.access for f in rep.active] == ["mutate"]
    assert rep.active[0].state == "pkg.mod._progress"


def test_subprocess_wrapper_is_a_thread_root(tmp_path):
    """A function that launches a child process keeps running concurrently
    with it; its own shared-state writes are audited like a thread's."""
    rep = _races(
        tmp_path,
        """
    import subprocess as _sp
    import sys

    _runs = {}

    def kill_and_resume(cfg):
        _runs[cfg] = "started"
        _sp.run([sys.executable, "-m", "child", cfg])
    """,
    )
    assert any("subprocess wrapper" in r for r in rep.thread_roots)
    assert any(f.state == "pkg.mod._runs" for f in rep.active)


def test_subprocess_helper_alias_not_misrooted(tmp_path):
    """A same-named method on a non-subprocess object must not root its
    caller (the alias has to resolve to the subprocess module)."""
    rep = _races(
        tmp_path,
        """
    class Runner:
        def run(self, argv):
            return argv

    _state = {}

    def drive(cfg):
        _state[cfg] = 1
        Runner().run([cfg])
    """,
    )
    assert not any("subprocess wrapper" in r for r in rep.thread_roots)
    assert rep.ok, rep.render_text()


def test_package_roots_cover_chaos_capacity_and_checkpoint_drivers():
    """The real repo's PR 18 surfaces: the chaos --capacity subprocess
    wrapper and the watchdog-guarded capacity-sweep callable."""
    rep = run_races()
    roots = "\n".join(rep.thread_roots)
    assert "subprocess wrapper" in roots, roots
    assert "_run_chaos_capacity" in roots, roots
    assert "watchdog-guarded call" in roots, roots
